"""Polygon (non-point) store-scale proof — round-4 VERDICT #4: the
lean XZ2 tier holds ≥200M polygons in ONE TpuDataStore on the chip and
serves INTERSECTS/BBOX ECQL, the attribute tier, deletes and id
lookups, oracle-verified at checkpoints.

The reference's XZ indexes are first-class at cluster scale
(XZ2SFC.scala:54-77, XZ2IndexKeySpace.scala:44); round 4 capped
non-point schemas at the full-fat ~150M/chip tier.  The stream is
OBJECT-FREE: axis-aligned footprint rectangles arrive as envelope
arrays and pack vectorized (`packed_from_boxes`) — 200M Python
geometry objects would dominate the build.

Records to STORE_SCALE_POLY_r05.json (monotonic).  ``POLY_SCALE_N``
overrides the row count.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

KINDS = np.array(["road", "building", "park", "water", "rare"],
                 dtype=object)
KIND_P = [0.4, 0.4, 0.1, 0.0999, 0.0001]


def _improves(record_path: str, rows: int) -> bool:
    try:
        with open(record_path) as f:
            return rows >= int(json.load(f).get("rows", 0))
    except Exception:
        return True


def _slice_data(i: int, m: int):
    """Slice ``i`` of an OSM-buildings-shaped stream: small axis-aligned
    rectangles clustered around city hotspots."""
    rng = np.random.default_rng(70_000 + i)
    hot = rng.integers(0, 4, m)
    cx = np.array([-74.0, 2.3, 116.4, 28.0])[hot]
    cy = np.array([40.7, 48.8, 39.9, -26.2])[hot]
    x = np.clip(cx + rng.normal(0, 15.0, m), -179.8, 179.8)
    y = np.clip(cy + rng.normal(0, 10.0, m), -84.8, 84.8)
    w = rng.uniform(0.0005, 0.01, m)
    h = rng.uniform(0.0005, 0.01, m)
    bbox = np.stack([x - w, y - h, x + w, y + h], axis=1)
    kind = KINDS[rng.choice(len(KINDS), m, p=KIND_P)]
    return bbox, kind


def run(n: int = 200_000_000, slice_rows: int = 4_194_304,
        progress=print, record: bool = True) -> dict:
    import jax

    try:
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass

    import geomesa_tpu  # noqa: F401
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry.packed import packed_from_boxes

    ds = TpuDataStore()
    ds.create_schema(
        "osm", "kind:String:index=true,*geom:Polygon;"
               "geomesa.index.profile=lean")
    st = ds._store("osm")
    assert st.lean and st.lean_kind == "xz2"

    qbox = (-75.0, 40.0, -73.0, 42.0)      # NYC hotspot window
    q_ecql = (f"INTERSECTS(geom, POLYGON(({qbox[0]} {qbox[1]}, "
              f"{qbox[2]} {qbox[1]}, {qbox[2]} {qbox[3]}, "
              f"{qbox[0]} {qbox[3]}, {qbox[0]} {qbox[1]})))")

    # prewarm the xz2/attr scan programs on a tiny same-shaped store
    warm = TpuDataStore()
    warm.create_schema("w", "kind:String:index=true,*geom:Polygon;"
                            "geomesa.index.profile=lean")
    wb, wk = _slice_data(0, 4096)
    warm.write("w", {"kind": wk, "geom": packed_from_boxes(wb)})
    warm.query_result("w", q_ecql)
    warm.query_result("w", "kind = 'rare'")
    del warm
    progress("  poly-scale: programs prewarmed")

    record_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "STORE_SCALE_POLY_r05.json")

    def verify(label: str) -> dict:
        bb = st.batch.geoms.bbox
        kd = st.batch.column("kind")
        got = ds.query_result("osm", q_ecql)
        tq = time.perf_counter()
        got = ds.query_result("osm", q_ecql)
        q_warm = time.perf_counter() - tq
        # axis-aligned rectangles: INTERSECTS == bbox overlap (exact)
        want = np.flatnonzero((bb[:, 0] <= qbox[2])
                              & (bb[:, 2] >= qbox[0])
                              & (bb[:, 1] <= qbox[3])
                              & (bb[:, 3] >= qbox[1]))
        assert np.array_equal(np.sort(got.positions), want), (
            f"{label}: {len(got.positions)} vs {len(want)}")
        a_got = ds.query_result("osm", "kind = 'rare'")
        assert a_got.strategy.index == "attr:kind"
        tq = time.perf_counter()
        a_got = ds.query_result("osm", "kind = 'rare'")
        a_warm = time.perf_counter() - tq
        a_want = np.flatnonzero(kd == "rare")
        assert np.array_equal(np.sort(a_got.positions), a_want), (
            f"{label} attr: {len(a_got.positions)} vs {len(a_want)}")
        progress(f"  poly-scale: {label} verified — intersects "
                 f"{len(want)} hits {q_warm*1e3:.0f}ms, attr "
                 f"{len(a_want)} hits {a_warm*1e3:.0f}ms "
                 "(oracle-exact)")
        return {"query_warm_ms": [round(q_warm * 1e3, 1)],
                "query_hits": [int(len(want))],
                "attr_query_warm_ms": [round(a_warm * 1e3, 1)],
                "attr_query_hits": [int(len(a_want))],
                "oracle_exact": True, "attr_oracle_exact": True}

    t0 = time.perf_counter()
    done = 0
    i = 1
    out: dict = {}
    while done < n:
        m = min(slice_rows, n - done)
        bbox, kind = _slice_data(i, m)
        ds.write("osm", {"kind": kind, "geom": packed_from_boxes(bbox)})
        st.index("xz2").block()
        done += m
        i += 1
        if i % 12 == 0 or done >= n:
            build_s = time.perf_counter() - t0
            idx = st.index("xz2")
            stats = jax.local_devices()[0].memory_stats() or {}
            out = {
                "rows": int(len(st.batch)),
                "generations": len(idx.generations),
                "tiers": idx.tier_counts(),
                "device_bytes": int(idx.device_bytes()),
                "hbm_bytes_in_use": int(stats.get(
                    "bytes_in_use", idx.device_bytes())),
                "build_s": round(build_s, 1),
                "ingest_rows_per_sec": int(len(st.batch) / build_s),
                **verify(f"{done / 1e6:.0f}M"),
            }
            if record and _improves(record_path, out["rows"]):
                with open(record_path + ".tmp", "w") as f:
                    json.dump(out, f, indent=1)
                os.replace(record_path + ".tmp", record_path)
    # deletes + id lookup at full capacity
    bb = st.batch.geoms.bbox
    hit0 = int(np.flatnonzero((bb[:, 0] <= qbox[2])
                              & (bb[:, 2] >= qbox[0])
                              & (bb[:, 1] <= qbox[3])
                              & (bb[:, 3] >= qbox[1]))[0])
    assert ds.delete("osm", [str(hit0)]) == 1
    got = ds.query_result("osm", q_ecql)
    assert hit0 not in set(got.positions.tolist())
    one = ds.query_result("osm", f"IN ('{hit0 + 1}')")
    assert list(one.positions) == [hit0 + 1]
    out["delete_and_id_ok"] = True
    if record and _improves(record_path, out["rows"]):
        with open(record_path + ".tmp", "w") as f:
            json.dump(out, f, indent=1)
        os.replace(record_path + ".tmp", record_path)
    progress(f"  poly-scale: COMPLETE at {len(st.batch) / 1e6:.0f}M "
             "polygons through the store facade")
    return out


if __name__ == "__main__":
    n = int(os.environ.get("POLY_SCALE_N", 200_000_000))
    out = run(n)
    print(json.dumps({"metric": "poly_scale_proof", **out}))
