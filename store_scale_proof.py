"""Store-level scale proof (round-4 VERDICT #1): TpuDataStore itself —
not a standalone index artifact — holds ≥100M rows under the lean
profile and serves ECQL (spatial AND attribute residuals), stats,
density, arrow export and kNN with oracle-verified results on the real
chip.

The reference's defining property is FULL query semantics at scale
through one DataStore (docs/user/introduction.rst:24,
GeoMesaDataStore.scala:48); this drives that property end-to-end:
chunked writes stream through `TpuDataStore.write` (stats observed on
write, keys appended to the tiered LeanZ3Index), then every query runs
through the planner facade.

Run directly (``STORE_SCALE_N`` overrides the row count) or through
``bench.py``'s scale stanza.  Results record to STORE_SCALE_r04.json
(monotonic: a smaller rerun never replaces a larger verified record).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

MS_2021 = 1609459200000  # 2021-01-01
DAY = 86_400_000
#: round-5 adds a needle value (~1e-4) so attribute-INDEXED access has
#: a selective target at 1B (round-4 VERDICT #1)
NAMES = np.array(["alpha", "beta", "gamma", "delta", "rare"],
                 dtype=object)
NAME_P = [0.55, 0.3, 0.0999, 0.05, 0.0001]


def _improves(record_path: str, rows: int) -> bool:
    try:
        with open(record_path) as f:
            return rows >= int(json.load(f).get("rows", 0))
    except Exception:
        return True


def _write_record(record_path: str, out: dict) -> None:
    """Atomic record update that PRESERVES evidence keys the new dict
    doesn't carry yet (a mid-build checkpoint must not delete the prior
    record's kNN measurements — they re-record at completion)."""
    merged = dict(out)
    try:
        with open(record_path) as f:
            prior = json.load(f)
    except Exception:       # missing OR corrupt — overwrite either way
        prior = {}
    carried = [k for k in prior if k not in merged]
    for k in carried:
        merged[k] = prior[k]
    if any(k.startswith("knn") for k in carried):
        # provenance: carried kNN numbers were measured at the PRIOR
        # record's row count, not this checkpoint's
        merged["knn_measured_at_rows"] = prior.get(
            "knn_measured_at_rows", prior.get("rows"))
    with open(record_path + ".tmp", "w") as f:
        json.dump(merged, f, indent=1)
    os.replace(record_path + ".tmp", record_path)


def _slice_data(i: int, m: int):
    """Slice ``i`` of a GDELT-shaped stream with an attribute column:
    population hotspots, six months of timestamps, skewed names."""
    rng = np.random.default_rng(40_000 + i)
    hot = rng.integers(0, 4, m)
    cx = np.array([-74.0, 2.3, 116.4, 28.0])[hot]
    cy = np.array([40.7, 48.8, 39.9, -26.2])[hot]
    x = np.clip(cx + rng.normal(0, 20.0, m), -179.9, 179.9)
    y = np.clip(cy + rng.normal(0, 12.0, m), -89.9, 89.9)
    t = rng.integers(MS_2021, MS_2021 + 180 * DAY, m)
    name = NAMES[rng.choice(len(NAMES), m, p=NAME_P)]
    score = rng.uniform(0, 100, m)
    return x, y, t, name, score


def run(n: int = 100_000_000, slice_rows: int = 8_388_608,
        progress=print, record: bool = True) -> dict:
    import jax

    try:  # persistent compile cache (see bench._enable_compile_cache)
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass

    import geomesa_tpu  # noqa: F401  (x64)
    from geomesa_tpu.datastore import TpuDataStore

    ds = TpuDataStore()
    ds.create_schema(
        "gdelt", "name:String:index=true,score:Double:index=true,dtg:Date,"
                 "*geom:Point;geomesa.index.profile=lean")
    st = ds._store("gdelt")
    assert st.lean

    nyc = (-75.0, 40.0, -73.0, 42.0)
    paris = (1.0, 47.5, 3.5, 50.0)
    w_nyc = (MS_2021 + 30 * DAY, MS_2021 + 44 * DAY)
    w_paris = (MS_2021 + 90 * DAY, MS_2021 + 97 * DAY)
    ecqls = [
        # pure spatio-temporal
        (f"BBOX(geom,{nyc[0]},{nyc[1]},{nyc[2]},{nyc[3]}) AND dtg "
         "DURING 2021-01-31T00:00:00Z/2021-02-14T00:00:00Z",
         lambda x, y, t, nm, sc: ((x >= nyc[0]) & (x <= nyc[2])
                                  & (y >= nyc[1]) & (y <= nyc[3])
                                  & (t >= w_nyc[0]) & (t <= w_nyc[1]))),
        # attribute residual on gid-decoded candidates
        (f"BBOX(geom,{paris[0]},{paris[1]},{paris[2]},{paris[3]}) AND "
         "dtg DURING 2021-04-01T00:00:00Z/2021-04-08T00:00:00Z AND "
         "name = 'beta' AND score > 50",
         lambda x, y, t, nm, sc: ((x >= paris[0]) & (x <= paris[2])
                                  & (y >= paris[1]) & (y <= paris[3])
                                  & (t >= w_paris[0]) & (t <= w_paris[1])
                                  & (nm == "beta") & (sc > 50))),
    ]

    # prewarm the lean query programs on a tiny same-shaped store while
    # the device is near-empty (remote compiles under GiBs of resident
    # buffers have wedged the runtime; docs/scale.md)
    warm = TpuDataStore()
    warm.create_schema(
        "w", "name:String:index=true,score:Double:index=true,dtg:Date,"
             "*geom:Point;geomesa.index.profile=lean")
    wx, wy, wt, wn, wsc = _slice_data(0, 4096)
    warm.write("w", {"name": wn, "score": wsc, "dtg": wt,
                     "geom": (wx, wy)})
    for ecql, _ in ecqls:
        warm.query_result("w", ecql)
    warm.query_windows("w", [([nyc], *w_nyc), ([paris], *w_paris)])
    # round-5 surfaces: attr index scans, density push-down, Count()
    warm.query_result("w", "name = 'rare'")
    warm.query_result("w", "name = 'rare' AND dtg DURING "
                           "2021-02-01T00:00:00Z/2021-04-01T00:00:00Z")
    from geomesa_tpu.process.density import density_process
    from geomesa_tpu.process.stats_process import stats_process
    world_env = (-180.0, -90.0, 180.0, 90.0)
    density_process(warm, "w", "INCLUDE", world_env, 256, 128)
    stats_process(warm, "w", "INCLUDE", "Count()")
    del warm
    progress("  store-scale: programs prewarmed")

    # raw-index rate measured in the SAME run (round-4 VERDICT #7's
    # denominator): a throwaway LeanZ3Index + LeanAttrIndex pair takes
    # the same slices the facade will, discarded before the real build
    from geomesa_tpu.index.attr_lean import LeanAttrIndex
    from geomesa_tpu.index.z3_lean import LeanZ3Index
    raw_z3 = LeanZ3Index(period="week")
    raw_at = LeanAttrIndex("name", "string")
    rx, ry, rt, rn, _ = _slice_data(0, slice_rows)
    raw_z3.append(rx, ry, rt)   # warm the append programs
    raw_at.append(rn, rt)
    raw_times = []
    for w in range(1, 4):
        rx, ry, rt, rn, _ = _slice_data(10_000 + w, slice_rows)
        tq = time.perf_counter()
        raw_z3.append(rx, ry, rt)
        raw_z3.block()
        raw_at.append(rn, rt)
        raw_at.block()
        raw_times.append(time.perf_counter() - tq)
    raw_rate = int(slice_rows / sorted(raw_times)[1])
    del raw_z3, raw_at
    progress(f"  store-scale: raw index rate {raw_rate} rows/s "
             "(z3 + attr, same slices)")

    record_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "STORE_SCALE_r05.json")

    def verify(label: str) -> dict:
        x, yv = st.batch.geom_xy()
        t = st.batch.column("dtg")
        nm = st.batch.column("name")
        sc = st.batch.column("score")
        q_warm, q_hits = [], []
        for ecql, oracle in ecqls:
            got = ds.query_result("gdelt", ecql)
            tq = time.perf_counter()
            got = ds.query_result("gdelt", ecql)   # steady-state
            q_warm.append(time.perf_counter() - tq)
            want = np.flatnonzero(oracle(x, yv, t, nm, sc))
            assert np.array_equal(np.sort(got.positions), want), (
                f"{label}: {len(got.positions)} vs {len(want)}")
            q_hits.append(int(len(want)))
        # round-5: attribute-INDEXED access at scale (VERDICT #1) —
        # attr-only, attr + wide bbox (the round-4 full-host-scan
        # degradations), and attr + time window (the date tier)
        a_warm, a_hits = [], []
        attr_ecqls = [
            ("name = 'rare'",
             lambda: nm == "rare"),
            ("name = 'rare' AND BBOX(geom,-180,-90,180,90)",
             lambda: nm == "rare"),
            ("name = 'rare' AND dtg DURING "
             "2021-02-01T00:00:00Z/2021-04-01T00:00:00Z",
             lambda: ((nm == "rare")
                      & (t >= MS_2021 + 31 * DAY)
                      & (t <= MS_2021 + 90 * DAY))),
        ]
        for ecql, oracle in attr_ecqls:
            got = ds.query_result("gdelt", ecql)
            assert got.strategy.index == "attr:name", got.strategy
            tq = time.perf_counter()
            got = ds.query_result("gdelt", ecql)
            a_warm.append(time.perf_counter() - tq)
            want = np.flatnonzero(oracle())
            assert np.array_equal(np.sort(got.positions), want), (
                f"{label} attr: {len(got.positions)} vs {len(want)}")
            a_hits.append(int(len(want)))
        progress(f"  store-scale: {label} attr-indexed verified — "
                 f"hits {a_hits}, warm "
                 f"{[round(v * 1e3) for v in a_warm]}ms")
        # stats through the facade vs exact aggregation
        cnt = ds.get_count("gdelt")
        assert cnt == len(st.batch), (cnt, len(st.batch))
        mm = ds.stat("gdelt", "score_minmax")
        assert abs(mm.bounds[0] - sc.min()) < 1e-9
        assert abs(mm.bounds[1] - sc.max()) < 1e-9
        topk = ds.stat("gdelt", "name_topk").topk(1)[0][0]
        assert topk == "alpha", topk
        # arrow export of a selective window
        tbl = ds.query_arrow("gdelt", ecqls[1][0],
                             dictionary_fields=("name",))
        assert tbl.num_rows == q_hits[1]
        progress(f"  store-scale: {label} verified — hits {q_hits}, "
                 f"warm {[round(v * 1e3) for v in q_warm]}ms "
                 "(oracle-exact, ECQL+stats+arrow)")
        return {"query_warm_ms": [round(v * 1e3, 1) for v in q_warm],
                "query_hits": q_hits, "oracle_exact": True,
                "attr_query_warm_ms": [round(v * 1e3, 1)
                                       for v in a_warm],
                "attr_query_hits": a_hits, "attr_oracle_exact": True}

    t0 = time.perf_counter()
    done = 0
    i = 1   # slice 0 seeds the prewarm store
    out: dict = {}
    while done < n:
        m = min(slice_rows, n - done)
        x, y, t, name, score = _slice_data(i, m)
        ds.write("gdelt", {"name": name, "score": score, "dtg": t,
                           "geom": (x, y)})
        st.index("z3").block()   # serialize slices (tunnel wedge)
        done += m
        i += 1
        if i % 6 == 0 or done >= n:
            build_s = time.perf_counter() - t0
            idx = st.index("z3")
            stats = jax.local_devices()[0].memory_stats() or {}
            rate = int(len(st.batch) / build_s)
            out = {
                "rows": int(len(st.batch)),
                "generations": len(idx.generations),
                "tiers": idx.tier_counts(),
                "attr_tiers": st.attribute_index("name").tier_counts(),
                "device_bytes": int(idx.device_bytes()),
                "hbm_bytes_in_use": int(stats.get(
                    "bytes_in_use", idx.device_bytes())),
                "build_s": round(build_s, 1),
                "ingest_rows_per_sec": rate,
                "raw_index_rows_per_sec": raw_rate,
                "facade_fraction_of_raw": round(rate / raw_rate, 3),
                **verify(f"{done / 1e6:.0f}M"),
            }
            if record and _improves(record_path, out["rows"]):
                _write_record(record_path, out)
    # kNN process against the full store (round-4 VERDICT #5).  Cold
    # includes the first-time compiles of the generation-count-shaped
    # scan programs (cached on disk afterwards); warm is the steady
    # state an interactive workload sees.
    from geomesa_tpu.process import knn_process
    t0 = time.perf_counter()
    kpos, kdist = knn_process(ds, "gdelt", -74.0, 40.7, 25)
    knn_cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    kpos, kdist = knn_process(ds, "gdelt", -74.0, 40.7, 25)
    knn_s = time.perf_counter() - t0
    from geomesa_tpu.process.knn import haversine_m
    x, yv = st.batch.geom_xy()
    # chunked brute-force oracle: a whole-array haversine over 1B rows
    # allocates several 8 GB temporaries on top of the ~40 GB column
    # store and OOM-killed the 1B run (dmesg: 130 GB RSS) — per-chunk
    # partition keeps the working set at one chunk
    k = 25
    best = np.empty(0)
    step = 1 << 26
    for lo in range(0, len(x), step):
        d = haversine_m(-74.0, 40.7, x[lo:lo + step], yv[lo:lo + step])
        top = np.partition(d, min(k - 1, len(d) - 1))[:k]
        best = np.sort(np.concatenate([best, top]))[:k]
    assert np.allclose(np.sort(kdist), best, rtol=1e-12)
    out["knn25_cold_ms"] = round(knn_cold_s * 1e3, 1)
    out["knn25_warm_ms"] = round(knn_s * 1e3, 1)
    out["knn_oracle_exact"] = True
    out["knn_measured_at_rows"] = int(len(st.batch))
    progress(f"  store-scale: kNN k=25 over {len(st.batch) / 1e6:.0f}M "
             f"rows cold {knn_cold_s * 1e3:.0f}ms / warm "
             f"{knn_s * 1e3:.0f}ms, exact vs brute force")
    # round-5: whole-extent heatmap + Count() push-down at full scale
    # (VERDICT #2) — grids/sketches accumulate next to the keys; only
    # the grid crosses; verified against a CHUNKED numpy oracle
    from geomesa_tpu.process.density import density_process
    from geomesa_tpu.process.stats_process import stats_process
    world_env = (-180.0, -90.0, 180.0, 90.0)
    grid = density_process(ds, "gdelt", "INCLUDE", world_env, 256, 128)
    tq = time.perf_counter()
    grid = density_process(ds, "gdelt", "INCLUDE", world_env, 256, 128)
    dens_s = time.perf_counter() - tq
    xall, yall = st.batch.geom_xy()
    want_grid = np.zeros((128, 256))
    step = 1 << 26
    for lo in range(0, len(xall), step):
        gx = np.clip(((xall[lo:lo + step] + 180.0) / 360.0 * 256)
                     .astype(np.int64), 0, 255)
        gy = np.clip(((yall[lo:lo + step] + 90.0) / 180.0 * 128)
                     .astype(np.int64), 0, 127)
        np.add.at(want_grid, (gy, gx), 1.0)
    assert grid.sum() == len(st.batch), (grid.sum(), len(st.batch))
    dens_exact = bool(np.array_equal(grid, want_grid))
    out["density_1b_ms"] = round(dens_s * 1e3, 1)
    out["density_oracle_exact"] = dens_exact
    if not dens_exact:
        diff = np.abs(grid - want_grid)
        out["density_cells_differing"] = int((diff > 0).sum())
        out["density_max_cell_diff"] = float(diff.max())
    tq = time.perf_counter()
    cstat = stats_process(ds, "gdelt", "INCLUDE", "Count()")
    count_s = time.perf_counter() - tq
    assert cstat.count == len(st.batch), (cstat.count, len(st.batch))
    out["count_pushdown_ms"] = round(count_s * 1e3, 1)
    progress(f"  store-scale: whole-extent heatmap {dens_s*1e3:.0f}ms "
             f"(per-cell exact={dens_exact}), Count() push-down "
             f"{count_s*1e3:.0f}ms — both over "
             f"{len(st.batch)/1e6:.0f}M rows, no hit materialized")
    # ISSUE 3: full stat-sketch push-down at scale — Count/MinMax/
    # Histogram over a bbox+time window fold per sealed run next to
    # the attr keys; the warm repeat serves sealed runs from the
    # sketch-partial cache (the 1B cold/warm stat latency the bench's
    # stats_pushdown stanza points at)
    try:
        from geomesa_tpu.metrics import (
            LEAN_STATS_MATERIALIZED, registry as _reg,
        )
        sspec = "Count();MinMax(score);Histogram(score,20,0,100)"
        sq = ("BBOX(geom,-180,-90,180,90) AND dtg DURING "
              "2021-01-31T00:00:00Z/2021-02-14T00:00:00Z")
        m0 = _reg.counter(LEAN_STATS_MATERIALIZED).count
        tq = time.perf_counter()
        s_cold = stats_process(ds, "gdelt", sq, sspec)
        out["stats_pushdown_cold_ms"] = round(
            (time.perf_counter() - tq) * 1e3, 1)
        stats_process(ds, "gdelt", sq, sspec)   # live-only compile
        tq = time.perf_counter()
        s_warm = stats_process(ds, "gdelt", sq, sspec)
        out["stats_pushdown_warm_ms"] = round(
            (time.perf_counter() - tq) * 1e3, 1)
        out["stats_pushdown_speedup"] = round(
            out["stats_pushdown_cold_ms"]
            / max(out["stats_pushdown_warm_ms"], 1e-3), 1)
        out["stats_materialized_fallbacks"] = int(
            _reg.counter(LEAN_STATS_MATERIALIZED).count - m0)
        assert s_cold.to_json() == s_warm.to_json()
        progress("  store-scale: stat-sketch push-down cold "
                 f"{out['stats_pushdown_cold_ms']:.0f}ms / warm "
                 f"{out['stats_pushdown_warm_ms']:.0f}ms, "
                 f"{out['stats_materialized_fallbacks']} "
                 "materialized fallbacks")
    except Exception as e:  # the proof must not die over the stanza
        out["stats_pushdown_error"] = repr(e)
    if record and _improves(record_path, out["rows"]):
        _write_record(record_path, out)
    progress(f"  store-scale: COMPLETE at {len(st.batch) / 1e6:.0f}M "
             f"rows through the store facade")
    return out


if __name__ == "__main__":
    n = int(os.environ.get("STORE_SCALE_N", 100_000_000))
    out = run(n)
    print(json.dumps({"metric": "store_scale_proof", **out}))
