"""Benchmark: BASELINE configs 1 (Z3), 2 (Z2 OR), 3 (XZ2), 5 (kNN/tube)
+ Pallas health, all recurring so regressions anywhere are visible in
BENCH_r*.json (VERDICT r1 items 4/6).

Measured on one chip, GDELT/OSM/AIS-shaped synthetic data:

* **config 1 ingest**: vectorized Z3 SFC encode + device key sort,
  keys/sec/chip (the reference's write-path hot loop,
  Z3IndexKeySpace.toIndexKey — it claims >10k records/sec/node;
  docs/user/introduction.rst:26), plus chunked append-per-slice
  sustained ingest (the 1B-path streaming shape, docs/scale.md).
* **config 1 scan**: bbox+week query (plan + device seeks + fused
  candidate filter) single and 32-window batched.
* **config 2**: Z2 multi-bbox OR query (FilterSplitter disjunctions).
* **config 3**: XZ2 polygon intersects over 200k polygons.
* **config 5**: kNN and tube-select over 500k AIS-shaped points through
  the store facade (batched expanding rings / per-segment windows).
* **pallas**: density grid Pallas-vs-XLA timings + kernel health
  (fallback counters) so a Mosaic regression is loud.

Prints ONE JSON line with the primary metric (ingest keys/sec/chip);
vs_baseline is the ratio to the reference's 10k records/sec/node claim.
"""

import json
import os
import time

import numpy as np


def _enable_compile_cache():
    """Persist XLA/Mosaic compiles to disk: over the remote-tunnel TPU a
    fresh program costs 20-40s to compile, and the bench has ~15 distinct
    programs — the cache makes recurring driver runs compile-free."""
    import jax
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # older jax: run without the cache

N = 16_000_000
SCAN_N = 4_000_000
MS_2018 = 1514764800000



def _median_time(fn, iters=5):
    """Median per-iteration wall time — robust to tunnel stalls that
    would skew a mean."""
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    times.sort()
    mid = len(times) // 2
    if len(times) % 2:
        return times[mid]
    return (times[mid - 1] + times[mid]) / 2


def _mem_probe() -> dict:
    """Memory footprint at stanza completion (ISSUE 9): the process
    peak host RSS (a cumulative high-water mark — stanzas run in a
    fixed order, so same-stanza comparisons across rounds are
    apples-to-apples) and total live device-resident bytes.  Both feed
    the regression gate's storage direction (lower is better), so a
    memory regression fails as loudly as a perf one."""
    out: dict = {}
    try:
        import resource
        out["peak_rss_mb"] = round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0,
            1)   # linux ru_maxrss is KiB
    except Exception:
        pass
    try:
        import jax
        out["device_resident_bytes"] = int(sum(
            int(getattr(a, "nbytes", 0)) for a in jax.live_arrays()))
    except Exception:
        pass
    return out


def main():
    _enable_compile_cache()
    import jax
    import jax.numpy as jnp

    import geomesa_tpu  # noqa: F401  (enables x64)
    from geomesa_tpu.curve import TimePeriod, to_binned_time, z3_sfc
    from geomesa_tpu.index import Z3PointIndex

    rng = np.random.default_rng(42)
    # GDELT-shaped: world-wide events over two weeks
    x = rng.uniform(-180.0, 180.0, N)
    y = rng.uniform(-56.0, 72.0, N)
    t = rng.integers(MS_2018, MS_2018 + 14 * 86_400_000, N)

    sfc = z3_sfc(TimePeriod.WEEK)
    bins, offs = to_binned_time(t, TimePeriod.WEEK)

    xd = jax.device_put(jnp.asarray(x))
    yd = jax.device_put(jnp.asarray(y))
    od = jax.device_put(jnp.asarray(offs.astype(np.float64)))
    bd = jax.device_put(jnp.asarray(bins.astype(np.int32)))

    @jax.jit
    def ingest(xs, ys, os_, bs):
        z = sfc.index(xs, ys, os_)
        # variadic 2-key sort with the permutation as payload: ~7x faster
        # than lexsort+gather on TPU
        return jax.lax.sort(
            (bs, z, jnp.arange(z.shape[0], dtype=jnp.int32)),
            dimension=0, num_keys=2)

    # warmup/compile; completion is forced via a tiny device→host read
    # because block_until_ready can return before remote execution
    # finishes on tunneled platforms
    _ = np.asarray(ingest(xd, yd, od, bd)[0][:1])

    ingest_dt = _median_time(
        lambda: np.asarray(ingest(xd, yd, od, bd)[0][:1]))
    ingest_rate = N / ingest_dt

    # scan: selective bbox + 5-day window
    index = Z3PointIndex.build(x[:SCAN_N], y[:SCAN_N], t[:SCAN_N],
                               period=TimePeriod.WEEK)
    box = (-80.0, 30.0, -60.0, 50.0)
    tlo, thi = MS_2018 + 2 * 86_400_000, MS_2018 + 7 * 86_400_000
    hits = index.query([box], tlo, thi)  # warm (compiles both phases)
    q_dt = _median_time(lambda: index.query([box], tlo, thi), iters=10)
    scan_rate = len(hits) / q_dt
    # index-resident points covered per second of query wall time (the
    # reference's "tens of millions of points in seconds" claim scale)
    scanned_rate = SCAN_N / q_dt

    # batched windows: 32 independent bbox+time queries in ONE dispatch
    # (the tube-select / kNN scan pattern; amortizes dispatch latency)
    qrng = np.random.default_rng(7)
    windows = []
    for _ in range(32):
        cx = float(qrng.uniform(-150, 150))
        cy = float(qrng.uniform(-40, 60))
        lo = MS_2018 + int(qrng.integers(0, 9)) * 86_400_000
        windows.append(([(cx - 3, cy - 3, cx + 3, cy + 3)],
                        lo, lo + 3 * 86_400_000))
    batched = index.query_many(windows)  # warm
    batched_dt = _median_time(lambda: index.query_many(windows))
    batched_hits = int(sum(len(b) for b in batched))

    # density histogram (auto: sorted-segment at this N; Pallas MXU
    # one-hot for small batches)
    from geomesa_tpu.ops.density import density_grid_auto
    import jax.numpy as jnp
    dmask = jnp.ones(N, dtype=bool)
    dw = jnp.ones(N, dtype=jnp.float32)
    grid = density_grid_auto(xd, yd, dw, dmask,
                             (-180.0, -90.0, 180.0, 90.0), 256, 128)
    _ = np.asarray(grid)  # warm

    def one_density():
        g = density_grid_auto(xd, yd, dw, dmask,
                              (-180.0, -90.0, 180.0, 90.0), 256, 128)
        _ = np.asarray(g[:1, :1])

    density_dt = _median_time(one_density)

    # -- chunked sustained ingest (the 1B-path streaming shape): seed
    # with the already-compiled 4M build shape, then append host slices
    # into sentinel padding — the host→device stream a 1B build uses
    # (docs/scale.md HBM budget).  First append warms the (capacity,
    # slice) compile bucket; the measured appends reuse it.
    CH = 2_000_000
    from geomesa_tpu.ops.search import gather_capacity
    chunk_idx = Z3PointIndex.build(x[:SCAN_N], y[:SCAN_N], t[:SCAN_N],
                                   period=TimePeriod.WEEK)
    a0 = SCAN_N
    # pre-size capacity for the whole stream so no growth (and no fresh
    # compile bucket) lands inside the measured region — a production 1B
    # build sizes its slices the same way (docs/scale.md)
    chunk_idx._grow_capacity(gather_capacity(a0 + 6 * CH))
    chunk_idx.append(x[a0:a0 + CH], y[a0:a0 + CH], t[a0:a0 + CH])  # warm
    # median of >=3 measured appends: single-shot captures conflated
    # tunnel stalls with real regressions (round-4 VERDICT #3)
    append_times = []
    for s in range(1, 5):
        lo, hi = a0 + s * CH, a0 + (s + 1) * CH
        t0 = time.perf_counter()
        chunk_idx.append(x[lo:hi], y[lo:hi], t[lo:hi])
        _ = np.asarray(chunk_idx.z[:1])  # force completion
        append_times.append(time.perf_counter() - t0)
    append_times.sort()
    chunked_dt = append_times[len(append_times) // 2]
    chunked_rate = CH / chunked_dt

    # -- config 2: Z2 multi-bbox OR (OSM traces / FilterSplitter ORs)
    from geomesa_tpu.index.z2 import Z2PointIndex
    z2 = Z2PointIndex.build(x[:SCAN_N], y[:SCAN_N])
    boxes2 = [(-80.0, 30.0, -70.0, 40.0), (0.0, 40.0, 10.0, 50.0),
              (110.0, -40.0, 125.0, -25.0)]
    z2_hits = z2.query(boxes2)  # warm
    z2_dt = _median_time(lambda: z2.query(boxes2), iters=10)
    # world heatmap straight from the sorted column (z-prefix boundary
    # seeks, one dispatch; device time ~1-2ms — tunnel RTT dominates)
    _ = z2.density_world(256, 128)  # warm
    dw_dt = _median_time(lambda: z2.density_world(256, 128), iters=5)

    # -- config 3: XZ2 polygon intersects (OSM buildings)
    from geomesa_tpu.geometry.types import Polygon
    from geomesa_tpu.index.xz2 import XZ2Index
    prng = np.random.default_rng(11)
    NP_ = 100_000
    pcx = prng.uniform(-170, 170, NP_)
    pcy = prng.uniform(-80, 80, NP_)
    pw = prng.uniform(0.001, 0.05, NP_)
    t0 = time.perf_counter()
    polys = [Polygon([(a - d, b - d), (a + d, b - d),
                      (a + d, b + d), (a - d, b + d)])
             for a, b, d in zip(pcx, pcy, pw)]
    xz2 = XZ2Index.build(polys, g=12)
    xz2_build_s = time.perf_counter() - t0
    qpoly = Polygon([(-80.0, 30.0), (-60.0, 30.0), (-60.0, 50.0),
                     (-80.0, 50.0)])
    xz2_hits = xz2.query(qpoly, exact=False)  # warm
    xz2_dt = _median_time(lambda: xz2.query(qpoly, exact=False), iters=10)

    # -- config 5: kNN + tube-select through the store facade (AIS)
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.process.knn import knn_process
    from geomesa_tpu.process.tube import tube_select
    arng = np.random.default_rng(13)
    # same row count as the scan index so the store's z3/z2 builds reuse
    # the compiled 4M shapes (TPU compiles dominate bench wall time)
    NA = SCAN_N
    ds = TpuDataStore()
    ds.create_schema("ais", "dtg:Date,*geom:Point")
    ds.write("ais", {
        "dtg": arng.integers(MS_2018, MS_2018 + 7 * 86_400_000, NA),
        "geom": (arng.uniform(-75.0, -70.0, NA),
                 arng.uniform(38.0, 42.0, NA)),
    })
    knn_process(ds, "ais", -73.0, 40.0, 25)  # warm
    knn_dt = _median_time(
        lambda: knn_process(ds, "ais", -73.0, 40.0, 25), iters=3)
    tk = np.linspace(0, 1, 41)
    track = np.column_stack([-75.0 + 4.0 * tk, 38.5 + 3.0 * tk])
    track_t = (MS_2018 + (tk * 5 * 86_400_000)).astype(np.int64)
    tube_select(ds, "ais", track, track_t, 5_000.0, 3_600_000)  # warm
    tube_dt = _median_time(
        lambda: tube_select(ds, "ais", track, track_t, 5_000.0,
                            3_600_000), iters=3)

    # -- pallas: compiled-kernel timings vs XLA + health (loud Mosaic
    # regressions; VERDICT r1 weak #1/#2)
    from geomesa_tpu.ops.pallas_kernels import on_tpu, pallas_health
    pallas = dict(pallas_health())
    raw_ms: dict = {}   # unrounded medians — the tuning decision
    # must not quantize at 0.1ms (sub-ms kernels would all tie)

    def _rec(key, seconds):
        raw_ms[key] = seconds * 1e3
        pallas[key] = round(seconds * 1e3, 1)
    if on_tpu():
        from geomesa_tpu.ops.density import density_grid
        from geomesa_tpu.ops.pallas_kernels import density_grid_pallas
        NSMALL = 1_000_000
        xs, ys = xd[:NSMALL], yd[:NSMALL]
        ws = jnp.ones(NSMALL, jnp.float32)
        ms = jnp.ones(NSMALL, bool)
        env = (-180.0, -90.0, 180.0, 90.0)
        try:
            _ = np.asarray(density_grid_pallas(xs, ys, ws, ms, env,
                                               256, 128)[:1, :1])
            _rec("density_pallas_1m_ms", _median_time(
                lambda: np.asarray(density_grid_pallas(
                    xs, ys, ws, ms, env, 256, 128)[:1, :1])))
        except Exception as e:  # Mosaic failure must be visible
            pallas["density_pallas_error"] = repr(e)
        _ = np.asarray(density_grid(xs, ys, ws, ms, env, 256, 128)[:1, :1])
        _rec("density_xla_1m_ms", _median_time(
            lambda: np.asarray(density_grid(
                xs, ys, ws, ms, env, 256, 128)[:1, :1])))

        # z2 int-space mask: fused Pallas decode+box kernel vs the XLA
        # deinterleave + (N × R) broadcast (round-3 next #8 kernel #1)
        from geomesa_tpu.curve.zorder import deinterleave2
        from geomesa_tpu.ops.pallas_kernels import z2_mask_pallas
        from geomesa_tpu.curve.sfc import z2_sfc
        z2v = z2_sfc().index(xs, ys)
        ixy8 = np.stack([np.array([i << 27, i << 26, (i + 8) << 27,
                                   (i + 8) << 26], dtype=np.int32)
                         for i in range(8)])

        @jax.jit
        def _z2_mask_xla(zz, bx):
            ix, iy = deinterleave2(zz.astype(jnp.uint64))
            ix = ix.astype(jnp.int64)
            iy = iy.astype(jnp.int64)
            return ((ix[:, None] >= bx[None, :, 0])
                    & (iy[:, None] >= bx[None, :, 1])
                    & (ix[:, None] <= bx[None, :, 2])
                    & (iy[:, None] <= bx[None, :, 3])).any(axis=1)

        try:
            _ = np.asarray(z2_mask_pallas(z2v, ixy8)[:1])
            _rec("z2_mask_pallas_1m_ms", _median_time(
                lambda: np.asarray(z2_mask_pallas(z2v, ixy8)[:1])))
        except Exception as e:
            pallas["z2_mask_pallas_error"] = repr(e)
        _ = np.asarray(_z2_mask_xla(z2v, jnp.asarray(ixy8))[:1])
        _rec("z2_mask_xla_1m_ms", _median_time(
            lambda: np.asarray(_z2_mask_xla(
                z2v, jnp.asarray(ixy8))[:1])))

        # z3 int-space mask: fused Pallas decode+box+time kernel vs the
        # XLA deinterleave3 path — measured so the z3_scan gate's claim
        # is uniform with the others (round-4 VERDICT #6)
        from geomesa_tpu.curve.zorder import deinterleave3
        from geomesa_tpu.ops.pallas_kernels import z3_mask_pallas
        z3v = sfc.index(xs, ys, od[:NSMALL])
        tlo3 = jnp.zeros(NSMALL, jnp.int32)
        thi3 = jnp.full(NSMALL, (1 << 21) - 1, jnp.int32)
        ixy3 = np.stack([np.array([i << 17, i << 16, (i + 8) << 17,
                                   (i + 8) << 16], dtype=np.int32)
                         for i in range(8)])

        @jax.jit
        def _z3_mask_xla(zz, bx, lo, hi):
            ix, iy, it = deinterleave3(zz.astype(jnp.uint64))
            ix = ix.astype(jnp.int32)
            iy = iy.astype(jnp.int32)
            it = it.astype(jnp.int32)
            hit = ((ix[:, None] >= bx[None, :, 0])
                   & (iy[:, None] >= bx[None, :, 1])
                   & (ix[:, None] <= bx[None, :, 2])
                   & (iy[:, None] <= bx[None, :, 3])).any(axis=1)
            return hit & (it >= lo) & (it <= hi)

        try:
            _ = np.asarray(z3_mask_pallas(z3v, ixy3, tlo3, thi3)[:1])
            _rec("z3_mask_pallas_1m_ms", _median_time(
                lambda: np.asarray(z3_mask_pallas(
                    z3v, ixy3, tlo3, thi3)[:1])))
        except Exception as e:
            pallas["z3_mask_pallas_error"] = repr(e)
        _ = np.asarray(_z3_mask_xla(z3v, jnp.asarray(ixy3), tlo3,
                                    thi3)[:1])
        _rec("z3_mask_xla_1m_ms", _median_time(
            lambda: np.asarray(_z3_mask_xla(
                z3v, jnp.asarray(ixy3), tlo3, thi3)[:1])))

        # 1-D histogram: MXU one-hot kernel vs XLA scatter-add (kernel #2)
        from geomesa_tpu.ops.pallas_kernels import hist1d_pallas
        hb = jnp.clip(((xs + 180.0) / 360.0 * 256).astype(jnp.int32),
                      0, 255)

        @jax.jit
        def _hist_xla(b, m):
            return jnp.zeros((256,), jnp.int64).at[b].add(
                jnp.where(m, 1, 0).astype(jnp.int64))

        try:
            _ = np.asarray(hist1d_pallas(hb, ws, ms, 256)[:1])
            _rec("hist1d_pallas_1m_ms", _median_time(
                lambda: np.asarray(hist1d_pallas(hb, ws, ms,
                                                 256)[:1])))
            # the kernel just ran successfully — record it on the gate
            # (its integrations would otherwise report 'untried' here)
            from geomesa_tpu.ops.pallas_kernels import GATES
            GATES["hist1d"].ok = True
        except Exception as e:
            pallas["hist1d_pallas_error"] = repr(e)
        _ = np.asarray(_hist_xla(hb, ms)[:1])
        _rec("hist1d_xla_1m_ms", _median_time(
            lambda: np.asarray(_hist_xla(hb, ms)[:1])))

        # measured wins govern the gates from here on: every shipped
        # kernel is >=1.0x on THIS chip or disabled by measurement
        # (.pallas_tuning.json, loaded by every later process —
        # round-4 VERDICT #6)
        from geomesa_tpu.ops.pallas_kernels import record_tuning

        def _win(p_key, x_key):
            # RAW medians, not the 0.1ms-rounded report values: the
            # disable decision must not quantize (sub-ms kernels would
            # all tie at 1.0)
            p, q = raw_ms.get(p_key), raw_ms.get(x_key)
            if p is None or q is None or p <= 0:
                return None
            return round(q / p, 3)

        wins = {
            "density": _win("density_pallas_1m_ms", "density_xla_1m_ms"),
            "z2_scan": _win("z2_mask_pallas_1m_ms", "z2_mask_xla_1m_ms"),
            "z3_scan": _win("z3_mask_pallas_1m_ms", "z3_mask_xla_1m_ms"),
            "hist1d": _win("hist1d_pallas_1m_ms", "hist1d_xla_1m_ms"),
        }
        record_tuning({k: v for k, v in wins.items() if v is not None})
        pallas["measured_wins"] = wins
        # refresh health after the compiled runs above
        pallas.update(pallas_health())
    pallas["active"] = bool(pallas.get("z3_scan_ok") is not False
                            and pallas.get("z2_scan_ok") is not False
                            and pallas.get("hist1d_ok") is not False
                            and pallas["on_tpu"])

    scale = _guarded_stanza(_scale_stanza)
    compaction = _guarded_stanza(_compaction_stanza)
    stats_pd = _guarded_stanza(_stats_pushdown_stanza)
    xz3_scale = _guarded_stanza(_xz3_scale_stanza)
    obs_stanza = _guarded_stanza(_obs_stanza)
    heat_stanza = _guarded_stanza(_heat_stanza)
    arrow_stanza = _guarded_stanza(_arrow_stanza)
    lint_stanza = _guarded_stanza(_lint_stanza)
    resilience_stanza = _guarded_stanza(_resilience_stanza)
    serving_stanza = _guarded_stanza(_serving_stanza)
    pyramid_stanza = _guarded_stanza(_pyramid_stanza)
    planning_stanza = _guarded_stanza(_planning_stanza)
    slo_stanza = _guarded_stanza(_slo_stanza)
    full = {
        "metric": "z3_ingest_keys_per_sec_per_chip",
        "value": round(ingest_rate),
        "unit": "keys/sec",
        "vs_baseline": round(ingest_rate / 10_000.0, 2),
        "extra": {
            "n_points": N,
            "bbox_time_scan_features_per_sec": round(scan_rate),
            "scan_points_covered_per_sec": round(scanned_rate),
            "scan_hits": int(len(hits)),
            "batched_windows_per_sec": round(32 / batched_dt, 1),
            "batched_window_hits": batched_hits,
            "density_256x128_ms": round(density_dt * 1e3, 1),
            "chunked_append_keys_per_sec": round(chunked_rate),
            "chunked_total_rows": int(chunk_idx._n_rows
                                      if hasattr(chunk_idx, "_n_rows")
                                      else 8 * CH),
            "z2_or3_ms": round(z2_dt * 1e3, 1),
            "z2_or3_hits": int(len(z2_hits)),
            "density_world_zprefix_ms": round(dw_dt * 1e3, 1),
            "xz2_build_s": round(xz2_build_s, 2),
            "xz2_query_ms": round(xz2_dt * 1e3, 2),
            "xz2_candidates": int(len(xz2_hits)),
            "knn25_4m_ms": round(knn_dt * 1e3, 1),
            "tube40_4m_ms": round(tube_dt * 1e3, 1),
            "pallas": pallas,
            "scale": scale,
            "compaction": compaction,
            "stats_pushdown": stats_pd,
            "xz3_scale": xz3_scale,
            "obs": obs_stanza,
            "heat": heat_stanza,
            "arrow": arrow_stanza,
            "lint": lint_stanza,
            "resilience": resilience_stanza,
            "serving": serving_stanza,
            "pyramid": pyramid_stanza,
            "planning": planning_stanza,
            "slo": slo_stanza,
            "device": str(jax.devices()[0]),
        },
    }
    # Full detail survives in a FILE; the driver only retains the last
    # ~2,000 chars of stdout, which the round-4 full blob outgrew
    # (BENCH_r04 parsed: null — round-4 VERDICT weak #1).  The LAST
    # stdout line is therefore a compact summary, bounded well under the
    # tail window, carrying the primary metric plus per-config medians,
    # pallas wins, and scale POINTERS (record file + headline rows/rates
    # only — never the nested records themselves).
    compact = _compact_summary(full)
    # regression gate (round-5 VERDICT: silent median dips): compare
    # the compact record — the schema every BENCH_r*.json captures —
    # against the newest prior round, log loudly, and RECORD the list
    regressions = _regression_gate(compact)
    # arrow acceptance-gate failures (byte-exactness / 50x) count as
    # regressions too — the stanza records them without killing the
    # run, and here they become part of the failure signal
    for f in (arrow_stanza or {}).get("gate_failures", ()):
        regressions.append({"metric": "arrow.gate", "prior": None,
                            "current": None, "ratio": None,
                            "detail": f})
    # resilience acceptance-gate failures (deadline-overshoot pin,
    # shed behavior) fail the run the same way (ISSUE 16)
    for f in (resilience_stanza or {}).get("gate_failures", ()):
        regressions.append({"metric": "resilience.gate", "prior": None,
                            "current": None, "ratio": None,
                            "detail": f})
    # serving acceptance-gate failures (fused >= 3x serial, zero warm
    # recompiles, real fan-in) fail the run the same way (ISSUE 17)
    for f in (serving_stanza or {}).get("gate_failures", ()):
        regressions.append({"metric": "serving.gate", "prior": None,
                            "current": None, "ratio": None,
                            "detail": f})
    # pyramid acceptance-gate failures (>= 20x warm speedup, <50ms
    # warm tile p99, zero recompiles, bit-exactness) likewise
    # (ISSUE 18)
    for f in (pyramid_stanza or {}).get("gate_failures", ()):
        regressions.append({"metric": "pyramid.gate", "prior": None,
                            "current": None, "ratio": None,
                            "detail": f})
    # planning acceptance-gate failures (sketch-fed mispredict p95,
    # exactly-once bit-exact replans, zero warm recompiles) likewise
    # (ISSUE 19)
    for f in (planning_stanza or {}).get("gate_failures", ()):
        regressions.append({"metric": "planning.gate", "prior": None,
                            "current": None, "ratio": None,
                            "detail": f})
    # SLO-plane acceptance-gate failures (>= 90% attributed wall,
    # <= 5% hook overhead, zero warm recompiles, resolvable exemplar)
    # likewise (ISSUE 20)
    for f in (slo_stanza or {}).get("gate_failures", ()):
        regressions.append({"metric": "slo.gate", "prior": None,
                            "current": None, "ratio": None,
                            "detail": f})
    full["regressions"] = regressions
    compact["extra"]["regressions"] = len(regressions)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_FULL.json"), "w") as f:
        json.dump(full, f, indent=1)
    print(json.dumps(compact, separators=(",", ":")))


def _compact_summary(full: dict) -> dict:
    """The driver-facing last line: same top-level schema as the full
    record, `extra` reduced to scalars + scale pointers.  Must stay
    under ~1,800 chars serialized; past that it hard-trims to a 3-field
    core (pinned by tests/test_review_fixes.py) so a future field can
    never re-break the driver capture."""
    ex = full["extra"]
    scale = ex.get("scale", {})

    def _scale_ptr(key: str) -> dict:
        rec = scale.get(key)
        if not isinstance(rec, dict):
            return {"absent": True}
        out = {}
        for k in ("rows", "ingest_rows_per_sec", "generations", "tiers",
                  "oracle_exact", "knn_measured_at_rows", "knn25_warm_ms",
                  "query_warm_ms", "density_1b_ms", "attr_query_warm_ms",
                  "density_oracle_exact", "attr_oracle_exact",
                  "stats_pushdown_cold_ms", "stats_pushdown_warm_ms",
                  "stats_pushdown_speedup",
                  "stats_materialized_fallbacks"):
            if k in rec:
                v = rec[k]
                if isinstance(v, list):
                    v = v[:3]
                out[k] = v
        return out

    compact = {
        "metric": full["metric"],
        "value": full["value"],
        "unit": full["unit"],
        "vs_baseline": full["vs_baseline"],
        "extra": {
            "bbox_scan_feats_per_sec": ex["bbox_time_scan_features_per_sec"],
            "batched_windows_per_sec": ex["batched_windows_per_sec"],
            "chunked_append_keys_per_sec": ex["chunked_append_keys_per_sec"],
            "density_256x128_ms": ex["density_256x128_ms"],
            "z2_or3_ms": ex["z2_or3_ms"],
            "xz2_query_ms": ex["xz2_query_ms"],
            "knn25_4m_ms": ex["knn25_4m_ms"],
            "tube40_4m_ms": ex["tube40_4m_ms"],
            "pallas_wins": (ex.get("pallas") or {}).get("measured_wins"),
            "pallas_active": (ex.get("pallas") or {}).get("active"),
            "compaction": {
                k: (ex.get("compaction") or {}).get(k)
                for k in ("generations_before", "generations_after",
                          "warm_speedup", "density_warm_ms",
                          "recompiles")
                if k in (ex.get("compaction") or {})},
            "stats_pushdown": {
                k: (ex.get("stats_pushdown") or {}).get(k)
                for k in ("cold_ms", "warm_ms", "warm_speedup",
                          "materialized_fallbacks", "recompiles")
                if k in (ex.get("stats_pushdown") or {})},
            "xz3_scale": {
                k: (ex.get("xz3_scale") or {}).get(k)
                for k in ("ingest_rows_per_sec", "query_warm_ms",
                          "oracle_exact", "recompiles")
                if k in (ex.get("xz3_scale") or {})},
            "obs": {
                k: (ex.get("obs") or {}).get(k)
                for k in ("overhead_pct", "warm_recompiles",
                          "trace_spans")
                if k in (ex.get("obs") or {})},
            "heat": {
                k: (ex.get("heat") or {}).get(k)
                for k in ("ingest_overhead_pct", "query_overhead_pct",
                          "tracked_entries")
                if k in (ex.get("heat") or {})},
            "arrow": {
                k: (ex.get("arrow") or {}).get(k)
                for k in ("arrow_feats_per_sec",
                          "materialize_feats_per_sec", "lift_vs_r05",
                          "byte_exact", "warm_recompiles")
                if k in (ex.get("arrow") or {})},
            "resilience": {
                k: (ex.get("resilience") or {}).get(k)
                for k in ("overshoot_p99", "shed_ms",
                          "timeout_gate_ok", "warm_recompiles")
                if k in (ex.get("resilience") or {})},
            "serving": {
                k: (ex.get("serving") or {}).get(k)
                for k in ("serving_qps", "serial_qps", "fused_speedup",
                          "fanin", "warm_recompiles")
                if k in (ex.get("serving") or {})},
            "pyramid": {
                k: (ex.get("pyramid") or {}).get(k)
                for k in ("pyramid_speedup", "tile_warm_p99_ms",
                          "bit_exact", "fault_exact",
                          "warm_recompiles")
                if k in (ex.get("pyramid") or {})},
            "planning": {
                k: (ex.get("planning") or {}).get(k)
                for k in ("sketch_p95_ratio_dist",
                          "heuristic_p95_ratio_dist",
                          "replan_count", "warm_recompiles")
                if k in (ex.get("planning") or {})},
            "slo": {
                k: (ex.get("slo") or {}).get(k)
                for k in ("residual_pct", "overhead_pct",
                          "exemplar_resolves", "warm_recompiles")
                if k in (ex.get("slo") or {})},
            "scale_1b": _scale_ptr("recorded_1b"),
            "store_1b": _scale_ptr("store_recorded"),
            "store_live": _scale_ptr("store_live"),
            # storage direction (ISSUE 9): peak RSS is a process
            # high-water mark so the final probe covers every stanza,
            # but device residency is a point sample — take the MAX
            # across the per-stanza probes so a stanza that ballooned
            # HBM and freed it before the end still gates; the FULL
            # record keeps the per-stanza values for attribution
            "mem": _mem_highwater(ex),
            "full_record": "BENCH_FULL.json",
            "device": ex["device"],
        },
    }
    blob = json.dumps(compact, separators=(",", ":"))
    if len(blob) > 1800:  # hard-trim rather than re-break the capture
        compact["extra"] = {
            "chunked_append_keys_per_sec": ex["chunked_append_keys_per_sec"],
            "pallas_wins": (ex.get("pallas") or {}).get("measured_wins"),
            "full_record": "BENCH_FULL.json",
        }
    return compact


def _scale_stanza() -> dict:
    """Scale-proof evidence (round-3 next #7): the RECORDED 500M
    single-chip run (SCALE_r03.json, produced by scale_proof.py — too
    long to rerun inside every bench) plus a LIVE smaller streaming
    build each round so the lean generational path has a recurring
    regression number.  ``SCALE_LIVE_N=0`` skips the live run."""
    out: dict = {}
    here = os.path.dirname(os.path.abspath(__file__))
    for key, fns in (
            ("recorded_500m", ["SCALE_r03.json"]),
            ("store_recorded", ["STORE_SCALE_r05.json",
                                "STORE_SCALE_r04.json"]),
            ("recorded_1b", ["SCALE_1B_r05.json",
                             "SCALE_1B_r04.json"])):
        for fn in fns:   # newest PARSEABLE round's record wins
            rec = os.path.join(here, fn)
            if os.path.exists(rec):
                try:
                    with open(rec) as f:
                        out[key] = json.load(f)
                except Exception as e:
                    # a truncated/corrupt newer record must not mask an
                    # older round's good one — keep looking; the error
                    # survives only if every candidate fails
                    out[f"{key}_error"] = repr(e)
                    continue
                out.pop(f"{key}_error", None)
                break
    n_live = int(os.environ.get("SCALE_LIVE_N", 32_000_000))
    if n_live:
        try:
            import scale_proof
            out["live"] = scale_proof.run(n_live, progress=lambda *_: None,
                                          record=False)
        except Exception as e:  # never kill the bench over the stanza
            out["live_error"] = repr(e)
    n_store = int(os.environ.get("STORE_SCALE_LIVE_N", 8_000_000))
    if n_store:
        try:
            import store_scale_proof
            out["store_live"] = store_scale_proof.run(
                n_store, slice_rows=1 << 22,
                progress=lambda *_: None, record=False)
        except Exception as e:
            out["store_live_error"] = repr(e)
    out.update(_mem_probe())
    return out


def _compaction_stanza() -> dict:
    """LSM lifecycle regression numbers: stream a many-generation lean
    build, measure cold density, compact, measure post-compaction
    density, then the WARM repeat (sealed-generation partial cache) —
    the generation-count and warm-speedup trends every future
    BENCH_*.json tracks.  ``COMPACT_BENCH_N=0`` skips."""
    import time

    import numpy as np

    from geomesa_tpu.index.z3_lean import LeanZ3Index

    n = int(os.environ.get("COMPACT_BENCH_N", 4_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    try:
        from geomesa_tpu.obs import compile_count
        _c0 = compile_count()
        rng = np.random.default_rng(11)
        slots = 1 << 17
        ms0 = 1_514_764_800_000
        idx = LeanZ3Index(period="week", generation_slots=slots,
                          payload_on_device=False)
        t0 = time.perf_counter()
        step = slots  # one generation per slice — the LSM flush shape
        for lo in range(0, n, step):
            m = min(step, n - lo)
            idx.append(rng.uniform(-180, 180, m),
                       rng.uniform(-90, 90, m),
                       rng.integers(ms0, ms0 + 14 * 86_400_000, m))
        idx.block()
        out["rows"] = n
        out["ingest_s"] = round(time.perf_counter() - t0, 2)
        out["generations_before"] = len(idx.generations)
        box = [(-60.0, -30.0, 60.0, 30.0)]
        lo_t, hi_t = ms0 + 86_400_000, ms0 + 9 * 86_400_000
        t0 = time.perf_counter()
        cold = idx.density(box, lo_t, hi_t, (-180, -90, 180, 90),
                           256, 128)
        out["density_cold_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1)
        t0 = time.perf_counter()
        stats = idx.compact()
        out["compact_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        out["merged_groups"] = stats["merged_groups"]
        out["generations_after"] = stats["generations"]
        # compaction invalidated the merged runs' partials — this call
        # re-seeds the cache over the compacted shape...
        t0 = time.perf_counter()
        seeded = idx.density(box, lo_t, hi_t, (-180, -90, 180, 90),
                             256, 128)
        out["density_compacted_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1)
        # ...and the warm repeat re-scans only the live generation
        # (first warm call compiles the live-only shapes; time the
        # steady state)
        warm = idx.density(box, lo_t, hi_t, (-180, -90, 180, 90),
                           256, 128)
        t0 = time.perf_counter()
        warm = idx.density(box, lo_t, hi_t, (-180, -90, 180, 90),
                           256, 128)
        out["density_warm_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1)
        out["warm_speedup"] = round(
            out["density_compacted_ms"]
            / max(out["density_warm_ms"], 1e-3), 1)
        out["grids_equal"] = bool(
            np.array_equal(cold, seeded) and np.array_equal(cold, warm))
        out["recompiles"] = int(compile_count() - _c0)
    except Exception as e:  # never kill the bench over the stanza
        out["error"] = repr(e)
    out.update(_mem_probe())
    return out


def _obs_stanza() -> dict:
    """Observability overhead + retrace budget (ISSUE 5): the batched-
    window query stanza measured with the default always-on sampler vs
    tracing disabled — the tracing tax must stay in low single-digit
    percent — plus the warm-repeat recompile count (must be 0: a warm
    lean query that recompiles is the silent TPU perf cliff the
    recompile tracker exists to catch).  ``OBS_BENCH_N=0`` skips."""
    import time

    import numpy as np

    n = int(os.environ.get("OBS_BENCH_N", 2_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    try:
        from geomesa_tpu.config import clear_property, set_property
        from geomesa_tpu.index.z3_lean import LeanZ3Index
        from geomesa_tpu.obs import compile_count, recompile, tracer
        # a warm_recompiles of 0 is only meaningful when the listener
        # covers every compile (the counting_jit fallback is opt-in)
        out["recompile_listener"] = bool(recompile.installed())

        rng = np.random.default_rng(17)
        ms0 = 1_514_764_800_000
        slots = 1 << 18
        idx = LeanZ3Index(period="week", generation_slots=slots,
                          payload_on_device=False)
        for lo in range(0, n, slots):
            m = min(slots, n - lo)
            idx.append(rng.uniform(-180, 180, m),
                       rng.uniform(-90, 90, m),
                       rng.integers(ms0, ms0 + 14 * 86_400_000, m))
        idx.block()
        windows = []
        for i in range(8):
            cx, cy = -150.0 + 40.0 * (i % 8), -30.0 + 8.0 * i
            lo_t = ms0 + (i % 9) * 86_400_000
            windows.append(([(cx - 3, cy - 3, cx + 3, cy + 3)],
                            lo_t, lo_t + 3 * 86_400_000))
        idx.query_many(windows)          # warm/compile
        # warm-repeat recompile budget: repeated identical lean queries
        # must hit every executable cache
        c0 = compile_count()
        for _ in range(3):
            idx.query_many(windows)
        out["warm_recompiles"] = int(compile_count() - c0)
        traced_dt = _median_time(lambda: idx.query_many(windows),
                                 iters=7)
        # one query under an explicit root so the recorded trace shows
        # the full span tree (decompose / device / host under "query")
        from geomesa_tpu.obs import span as obs_span
        with obs_span("query", bench=True):
            idx.query_many(windows)
        ring = tracer.ring
        if ring is not None:
            last = ring.traces()[-1] if len(ring) else None
            out["trace_spans"] = len(last.spans) if last else 0
        set_property("geomesa.obs.enabled", False)
        try:
            idx.query_many(windows)      # settle
            untraced_dt = _median_time(lambda: idx.query_many(windows),
                                       iters=7)
        finally:
            clear_property("geomesa.obs.enabled")
        out["query_traced_ms"] = round(traced_dt * 1e3, 2)
        out["query_untraced_ms"] = round(untraced_dt * 1e3, 2)
        out["overhead_pct"] = round(
            (traced_dt / max(untraced_dt, 1e-9) - 1.0) * 100.0, 2)
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    out.update(_mem_probe())
    return out


def _heat_stanza() -> dict:
    """Heat-tracking + write-span overhead (ISSUE 12): the warm lean
    STORE ingest path (datastore writes — the full write-span tree:
    encode / index append / seal / spill / observe) and the warm query
    path, each measured with the workload instrumentation at defaults
    (heat tracking + tracing on) vs fully off.  The acceptance budget
    is ≤ 5% on both; the regression gate treats the ``*_overhead_pct``
    leaves as lower-is-better.  ``HEAT_BENCH_N=0`` skips."""
    import time

    import numpy as np

    n = int(os.environ.get("HEAT_BENCH_N", 2_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    try:
        from geomesa_tpu.config import clear_property, set_property
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.obs import heat_tracker

        ms0 = 1_514_764_800_000
        day = 86_400_000
        slots = 1 << 18
        spec = ("dtg:Date,*geom:Point;geomesa.index.profile=lean,"
                f"geomesa.lean.generation.slots={slots},"
                "geomesa.lean.compaction.factor=0")
        q = [(-60.0, -30.0, 60.0, 30.0)]
        windows = [(q, ms0 + i * day, ms0 + (i + 3) * day)
                   for i in range(8)]

        def build_and_query(name: str, rows: int):
            rng = np.random.default_rng(23)
            ds = TpuDataStore(user="heat-bench")
            ds.create_schema(name, spec)
            t0 = time.perf_counter()
            for lo in range(0, rows, slots):
                m = min(slots, rows - lo)
                ds.write(name, {
                    "dtg": rng.integers(ms0, ms0 + 14 * day, m),
                    "geom": (rng.uniform(-180, 180, m),
                             rng.uniform(-90, 90, m))})
            idx = ds._store(name)._indexes["z3"]
            idx.block()
            ingest_s = time.perf_counter() - t0
            idx.query_many(windows)         # warm/compile
            q_ms = _median_time(lambda: idx.query_many(windows),
                                iters=7) * 1e3
            return ingest_s, q_ms, len(idx.generations)

        # untimed warm-up: compile the append/scan programs once, so
        # the on-vs-off comparison measures the instrumentation tax,
        # not which run happened to pay the compiles
        build_and_query("hb_warm", min(n, 2 * slots))
        on_s, on_q_ms, gens = build_and_query("hb_on", n)
        set_property("geomesa.obs.heat.enabled", False)
        set_property("geomesa.obs.enabled", False)
        try:
            off_s, off_q_ms, _ = build_and_query("hb_off", n)
        finally:
            clear_property("geomesa.obs.heat.enabled")
            clear_property("geomesa.obs.enabled")
        out["rows"] = n
        out["generations"] = gens
        out["tracked_entries"] = len(heat_tracker)
        out["ingest_on_s"] = round(on_s, 3)
        out["ingest_off_s"] = round(off_s, 3)
        out["ingest_overhead_pct"] = round(
            (on_s / max(off_s, 1e-9) - 1.0) * 100.0, 2)
        out["query_on_ms"] = round(on_q_ms, 2)
        out["query_off_ms"] = round(off_q_ms, 2)
        out["query_overhead_pct"] = round(
            (on_q_ms / max(off_q_ms, 1e-9) - 1.0) * 100.0, 2)
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    out.update(_mem_probe())
    return out


#: BENCH_r05's recorded bbox_scan_feats_per_sec — the row-wise
#: materialization wall the Arrow-native result path (ISSUE 14) is
#: gated against: the warm streamed query must clear >= 50x this
_R05_MATERIALIZE_FEATS_PER_SEC = 88_763.0


def _arrow_stanza() -> dict:
    """Arrow-native materialization gate (ISSUE 14).

    BENCH_r05's 88,763 feats/sec (``bbox_scan_feats_per_sec``) was
    MATERIALIZE-bound — per-row feature ids and Python objects, not
    the scan, set the rate.  The stanza measures a warm wide-bbox
    query streamed through ``store.query_arrow`` and splits its wall
    time against the same query run positions-only, so the
    materialization throughput (rows through gather+encode per second)
    is measured apples-to-apples against the r05 wall:

    * ``arrow_feats_per_sec`` — end-to-end (scan + stream) serving
      rate, the recurring trend line in the regression gate
      (higher-better);
    * ``materialize_feats_per_sec`` — hits over (stream − scan) time;
      the gate asserts >= 50x the r05 baseline, i.e. result
      construction is no longer the bottleneck (the scan is again —
      exactly what ROADMAP item 2 asked for);
    * plus a BYTE-EXACT check of the streamed IPC blob against the
      row-wise ``query_result().batch`` encoded chunk-by-chunk with
      the same schema and shared delta dictionaries (a selective
      bbox+time query with a dictionary-encoded attribute), and a
      zero-recompile warm-repeat budget (the device payload gather
      pads into compile buckets).

    ``ARROW_BENCH_N=0`` skips."""
    import io

    import numpy as np

    n = int(os.environ.get("ARROW_BENCH_N", 2_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    try:
        import pyarrow as pa

        from geomesa_tpu.arrow.schema import encode_record_batch
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.obs import compile_count

        ms0 = 1_514_764_800_000
        day = 86_400_000
        slots = 1 << 18
        rng = np.random.default_rng(29)
        spec = ("name:String,score:Double,dtg:Date,*geom:Point;"
                "geomesa.index.profile=lean,"
                f"geomesa.lean.generation.slots={slots},"
                "geomesa.lean.compaction.factor=0")
        ds = TpuDataStore(user="arrow-bench")
        ds.create_schema("ab", spec)
        for lo in range(0, n, slots):
            m = min(slots, n - lo)
            ds.write("ab", {
                "name": np.array(["ais", "gdelt", "osm"], dtype=object)[
                    rng.integers(0, 3, m)],
                "score": rng.uniform(0, 100, m),
                "dtg": rng.integers(ms0, ms0 + 14 * day, m),
                "geom": (rng.uniform(-180, 180, m),
                         rng.uniform(-90, 90, m))})
        ds._store("ab")._indexes["z3"].block()
        chunk = 262_144
        wide = "BBOX(geom,-175,-85,175,85)"

        def drain():
            return sum(rb.num_rows
                       for rb in ds.query_arrow("ab", wide,
                                                chunk_rows=chunk,
                                                dictionary_fields=()))

        def scan_only():
            ds._query_result_ex("ab", wide, materialize=False)

        def _min_time(fn, iters=5):
            # best-of-N, not median: the materialize rate is a
            # DIFFERENCE of two timings, and box contention inflates
            # both sides asymmetrically — min is the standard
            # de-noised microbenchmark estimator for each half
            best = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                fn()
                best = min(best, time.perf_counter() - t0)
            return best

        hits = drain()                       # warm/compile both halves
        scan_only()
        out["rows"] = n
        out["hits"] = int(hits)
        c0 = compile_count()
        arrow_dt = _min_time(drain, iters=5)
        scan_dt = _min_time(scan_only, iters=5)
        out["warm_recompiles"] = int(compile_count() - c0)
        out["arrow_feats_per_sec"] = round(hits / arrow_dt)
        out["scan_ms"] = round(scan_dt * 1e3, 1)
        out["stream_ms"] = round(arrow_dt * 1e3, 1)
        mat_dt = max(arrow_dt - scan_dt, 1e-9)
        out["materialize_feats_per_sec"] = round(hits / mat_dt)
        out["lift_vs_r05"] = round(
            out["materialize_feats_per_sec"]
            / _R05_MATERIALIZE_FEATS_PER_SEC, 1)
        out["target_50x"] = bool(out["lift_vs_r05"] >= 50.0)
        out["scan_bound_again"] = bool(scan_dt > mat_dt)

        # row-wise reference rate: the old materializing path
        # (positions → LeanBatch.take per chunk → per-row feature ids)
        def rowwise():
            res = ds.query_result("ab", wide)
            st = ds._store("ab")
            total = 0
            for s in range(0, len(res.positions), chunk):
                total += len(st.batch.take(res.positions[s:s + chunk]))
            return total

        rowwise()                            # warm
        row_dt = _median_time(rowwise, iters=3)
        out["rowwise_feats_per_sec"] = round(hits / row_dt)
        out["speedup_vs_rowwise_e2e"] = round(
            row_dt / max(arrow_dt, 1e-9), 2)

        # byte-exact parity on a selective bbox+time query WITH a
        # delta-dictionary attribute: streamed IPC blob vs the
        # row-wise batch encoded chunk-by-chunk, same schema + shared
        # DictionaryState accumulations
        sel = ("BBOX(geom,-60,-30,60,30) AND dtg DURING "
               "2018-01-02T00:00:00Z/2018-01-09T00:00:00Z")
        stream = ds.query_arrow("ab", sel, chunk_rows=65_536,
                                dictionary_fields=("name",))
        schema = stream.schema
        got = stream.to_ipc_bytes()
        res = ds.query_result("ab", sel)
        st = ds._store("ab")
        sink = io.BytesIO()
        writer = pa.ipc.new_stream(
            sink, schema,
            options=pa.ipc.IpcWriteOptions(emit_dictionary_deltas=True))
        dicts: dict = {}
        for s in range(0, len(res.positions), 65_536):
            fb = st.batch.take(res.positions[s:s + 65_536])
            writer.write_batch(encode_record_batch(fb, schema, dicts))
        writer.close()
        out["parity_hits"] = int(len(res.positions))
        out["byte_exact"] = bool(got == sink.getvalue())
        out["ipc_bytes"] = len(got)
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    # the acceptance gate runs OUTSIDE the try (review: an assert
    # swallowed by the stanza's blanket except could never fail a run)
    # and fails the bench the way this bench fails things — a loud
    # line plus a recorded entry main() folds into `regressions`
    failures = []
    if not out.get("byte_exact", False):
        failures.append("arrow stream is not byte-exact vs the "
                        "row-wise encoding")
    if not out.get("target_50x", False):
        failures.append(
            f"materialize_feats_per_sec "
            f"{out.get('materialize_feats_per_sec')} < 50x the r05 "
            f"baseline {_R05_MATERIALIZE_FEATS_PER_SEC}")
    if failures:
        out["gate_failures"] = failures
        for f in failures:
            print(f"BENCH ARROW GATE FAILED: {f}", flush=True)
    out.update(_mem_probe())
    return out


def _guarded_stanza(fn) -> dict:
    """Every stanza RECORDS its failure rather than killing the bench:
    the stanzas' inner try/excepts cover their measured sections, but
    an exception before them (import, setup, env parsing) previously
    propagated and took the whole record with it (ISSUE 16
    satellite)."""
    try:
        out = fn()
    except Exception as e:  # noqa: BLE001 — the record IS the signal
        return {"error": repr(e)}
    if not isinstance(out, dict):
        return {"error": f"stanza returned {type(out).__name__}"}
    return out


def _resilience_stanza() -> dict:
    """Deadline + admission acceptance gate (ISSUE 16): a warm lean
    query given a timeout below its runtime must terminate within
    1.25x the deadline (the cooperative-cancellation pin documented in
    docs/resilience.md — yield points between generation scans bound
    the overshoot to one dispatch), and an over-budget request must
    shed as Backpressure after about the configured queue wait, never
    hang.  ``RESILIENCE_BENCH_N=0`` skips."""
    import numpy as np

    n = int(os.environ.get("RESILIENCE_BENCH_N", 2_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    try:
        from geomesa_tpu import config as gm_config
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.obs import compile_count
        from geomesa_tpu.resilience import Backpressure, admission_gate

        ms0 = 1_514_764_800_000
        day = 86_400_000
        slots = 1 << 16
        rng = np.random.default_rng(31)
        spec = ("dtg:Date,*geom:Point;"
                "geomesa.index.profile=lean,"
                f"geomesa.lean.generation.slots={slots},"
                "geomesa.lean.compaction.factor=0")
        ds = TpuDataStore(user="resilience-bench")
        ds.create_schema("rb", spec)
        for lo in range(0, n, slots):
            m = min(slots, n - lo)
            ds.write("rb", {
                "dtg": rng.integers(ms0, ms0 + 14 * day, m),
                "geom": (rng.uniform(-180, 180, m),
                         rng.uniform(-90, 90, m))})
        idx = ds._store("rb")._indexes["z3"]
        idx.block()
        # per-generation dispatch granularity: production fuses the
        # same-size generations into one batched program (good for
        # throughput, but then the whole scan is a single
        # uninterruptible dispatch and the cooperative pin is
        # unmeasurable); the gate measures the cancellation machinery,
        # so force one dispatch per generation (~31 yield points)
        idx.BATCH_SCAN_BUDGET = 1
        # a SELECTIVE query keeps the scan the long pole: the host
        # recheck over already-gathered candidates must finish for
        # exactness (docs/resilience.md), so a low-selectivity query's
        # overshoot is dominated by that unskippable post-work, not by
        # the dispatch granularity the pin is about
        sel = "BBOX(geom,-170,-80,-150,-60)"
        ds.query_result("rb", sel)          # warm the scan
        warm_ms = _median_time(
            lambda: ds.query_result("rb", sel), iters=3) * 1e3
        out["query_warm_ms"] = round(warm_ms, 2)
        # deadline at half the warm runtime: the query WILL expire
        # mid-scan, and every iteration must still return (partial)
        # within the overshoot pin
        deadline_ms = max(1.0, warm_ms / 2.0)
        out["deadline_ms"] = round(deadline_ms, 2)
        overshoots = []
        c0 = compile_count()
        for _ in range(20):
            t0 = time.perf_counter()
            res = ds.query_result("rb", sel, timeout_ms=deadline_ms,
                                  partial_results=True)
            dt_ms = (time.perf_counter() - t0) * 1e3
            if res.timed_out:
                overshoots.append(dt_ms / deadline_ms)
        out["warm_recompiles"] = int(compile_count() - c0)
        out["timed_out_runs"] = len(overshoots)
        if overshoots:
            overshoots.sort()
            out["overshoot_p99"] = round(
                overshoots[min(len(overshoots) - 1,
                               int(0.99 * len(overshoots)))], 3)
        # shed latency: with the single admission slot held and a
        # short queue wait, the next query must come back Backpressure
        # in roughly queue_ms — a shed that takes seconds is a hang
        # with extra steps
        gm_config.set_property(
            "geomesa.resilience.admission.max.concurrent", 1)
        gm_config.set_property(
            "geomesa.resilience.admission.queue.ms", 20.0)
        try:
            tok = admission_gate.acquire("rb")
            t0 = time.perf_counter()
            try:
                ds.query_result("rb", sel)
                out["shed_error"] = "no Backpressure under overload"
            except Backpressure:
                out["shed_ms"] = round(
                    (time.perf_counter() - t0) * 1e3, 2)
            finally:
                tok.release()
        finally:
            gm_config.clear_property(
                "geomesa.resilience.admission.max.concurrent")
            gm_config.clear_property(
                "geomesa.resilience.admission.queue.ms")
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    # the acceptance gate runs OUTSIDE the try (arrow-stanza
    # precedent: an assert swallowed by the stanza's blanket except
    # could never fail a run)
    failures = []
    if "error" not in out and not out.get("skipped"):
        p99 = out.get("overshoot_p99")
        ok = (p99 is not None and p99 <= 1.25
              and out.get("timed_out_runs", 0) > 0)
        out["timeout_gate_ok"] = bool(ok)
        if not ok:
            failures.append(
                f"deadline overshoot p99 {p99} exceeds the 1.25x pin "
                f"(timed_out_runs={out.get('timed_out_runs')})")
        if "shed_ms" not in out:
            failures.append(out.get("shed_error",
                                    "admission shed did not happen"))
        elif out["shed_ms"] > 1000.0:
            failures.append(
                f"shed latency {out['shed_ms']}ms — the queue wait is "
                "not bounded")
    if failures:
        out["gate_failures"] = failures
        for f in failures:
            print(f"BENCH RESILIENCE GATE FAILED: {f}", flush=True)
    out.update(_mem_probe())
    return out


def _serving_stanza() -> dict:
    """Fused serving plane acceptance gate (ISSUE 17): 64 concurrent
    clients of warm bbox/window queries submitted through the fusion
    scheduler must beat a serial solo baseline of the same workload by
    >= 3x throughput, with ZERO warm recompiles — the power-of-two
    capacity bucketing pins the compiled-shape set (docs/serving.md).
    ``SERVING_BENCH_N=0`` skips."""
    import numpy as np

    n = int(os.environ.get("SERVING_BENCH_N", 2_000_000))
    if not n:
        return {"skipped": True}
    clients = int(os.environ.get("SERVING_BENCH_CLIENTS", 64))
    rounds = int(os.environ.get("SERVING_BENCH_ROUNDS", 4))
    out: dict = {}
    try:
        import threading
        from geomesa_tpu import config as gm_config
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.metrics import (SERVING_FUSED_BATCHES,
                                         SERVING_FUSED_REQUESTS, registry)
        from geomesa_tpu.obs import compile_count

        ms0 = 1_514_764_800_000
        day = 86_400_000
        slots = 1 << 16
        rng = np.random.default_rng(47)
        ds = TpuDataStore(user="serving-bench")
        ds.create_schema("sb", (
            "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
            f"geomesa.lean.generation.slots={slots},"
            "geomesa.lean.compaction.factor=0"))
        for lo in range(0, n, slots):
            m = min(slots, n - lo)
            ds.write("sb", {
                "dtg": rng.integers(ms0, ms0 + 14 * day, m),
                "geom": (rng.uniform(-180, 180, m),
                         rng.uniform(-90, 90, m))})
        ds._store("sb")._indexes["z3"].block()
        # the concurrent-dashboard workload: selective bbox+window
        # filters, distinct per client, all ONE compatibility key
        queries, windows = [], []
        for i in range(16):
            x = -170.0 + i * 1.5
            d = 1 + (i % 5)          # 2018-01-02 .. 2018-01-06 starts
            queries.append(
                f"BBOX(geom,{x},-60,{x + 3},-57) AND dtg DURING "
                f"2018-01-{d:02d}T00:00:00Z/2018-01-{d + 3:02d}"
                "T00:00:00Z")
            windows.append((((x, -60.0, x + 3.0, -57.0),),
                            ms0 + (d - 1) * day, ms0 + (d + 2) * day))
        # a wide coalesce window + full-size batches for the measured
        # phase: on a loaded CI box 2ms of linger can miss riders that
        # a real server's steady-state arrival stream would catch
        gm_config.set_property("geomesa.serving.fuse.window.ms", 10.0)
        gm_config.set_property("geomesa.serving.fuse.max.batch", clients)
        try:
            # warm EVERY pow2 capacity bucket the fused path can hit,
            # then the solo path, then one unrecorded concurrent round
            k = 1
            while k <= clients:
                ds._fused_windows_dispatch(
                    "sb", [windows[j % len(windows)] for j in range(k)])
                k <<= 1
            for q in queries:
                ds.query_result("sb", q)
            errors: list = []
            barrier = threading.Barrier(clients + 1)

            def client(i: int) -> None:
                try:
                    barrier.wait(timeout=60)
                    for r in range(rounds):
                        ds.query_fused(
                            "sb", queries[(i + r) % len(queries)],
                            tenant=f"t{i % 8}")
                except Exception as e:  # surfaced via the gate below
                    errors.append(repr(e))

            def fused_round() -> float:
                barrier.reset()
                threads = [threading.Thread(target=client, args=(i,),
                                            daemon=True)
                           for i in range(clients)]
                for t in threads:
                    t.start()
                barrier.wait(timeout=60)   # releases all clients at once
                t0 = time.perf_counter()
                for t in threads:
                    t.join()
                return time.perf_counter() - t0

            fused_round()                  # unrecorded warm round
            # serial solo baseline: the SAME total query count, one at
            # a time down the unfused path
            total = clients * rounds
            t0 = time.perf_counter()
            for j in range(total):
                ds.query_result("sb", queries[j % len(queries)])
            serial_dt = time.perf_counter() - t0
            c0 = compile_count()
            req0 = registry.counter(SERVING_FUSED_REQUESTS).count
            bat0 = registry.counter(SERVING_FUSED_BATCHES).count
            fused_dt = fused_round()
            out["warm_recompiles"] = int(compile_count() - c0)
            reqs = registry.counter(SERVING_FUSED_REQUESTS).count - req0
            bats = registry.counter(SERVING_FUSED_BATCHES).count - bat0
            out["serial_qps"] = round(total / serial_dt, 1)
            out["serving_qps"] = round(total / fused_dt, 1)
            out["fused_speedup"] = round(serial_dt / fused_dt, 2)
            out["fanin"] = round(reqs / bats, 2) if bats else 0.0
            out["fused_requests"] = int(reqs)
            out["fused_batches"] = int(bats)
            out["clients"] = clients
            if errors:
                out["client_errors"] = errors[:4]
        finally:
            gm_config.clear_property("geomesa.serving.fuse.window.ms")
            gm_config.clear_property("geomesa.serving.fuse.max.batch")
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    # the acceptance gate runs OUTSIDE the try (resilience/arrow
    # precedent: an assert swallowed by the stanza's blanket except
    # could never fail a run)
    failures = []
    if "error" not in out and not out.get("skipped"):
        if out.get("client_errors"):
            failures.append(
                f"fused clients errored: {out['client_errors']}")
        speedup = out.get("fused_speedup")
        if speedup is None or speedup < 3.0:
            failures.append(
                f"fused throughput {out.get('serving_qps')} qps is not "
                f">= 3x the serial baseline {out.get('serial_qps')} qps "
                f"(speedup {speedup})")
        if out.get("warm_recompiles", 1) != 0:
            failures.append(
                f"warm fused path recompiled "
                f"{out.get('warm_recompiles')} time(s) — the capacity "
                "bucketing is leaking shapes")
        if out.get("fanin", 0) < 2.0:
            failures.append(
                f"fan-in {out.get('fanin')} — requests are not "
                "coalescing into shared batches")
    if failures:
        out["gate_failures"] = failures
        for f in failures:
            print(f"BENCH SERVING GATE FAILED: {f}", flush=True)
    out.update(_mem_probe())
    return out


def _pyramid_stanza() -> dict:
    """Density-pyramid acceptance gate (ISSUE 18): a warm whole-extent
    heatmap served off the sealed generations' cached pyramids must
    beat the cold direct sweep by >= 20x, warm single-tile p99 must
    stay under 50 ms with ZERO warm recompiles, and an interrupted
    build (``pyramid.build`` fault point) must leave results exact
    through the sweep fallback.  Bit-exactness of the pyramid-served
    grid vs the direct scan is asserted OUTSIDE the stanza's blanket
    except (the arrow-stanza precedent).  ``PYRAMID_BENCH_N=0``
    skips."""
    import numpy as np

    n = int(os.environ.get("PYRAMID_BENCH_N", 2_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    grids: dict = {}
    try:
        from geomesa_tpu import config as gm_config
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.metrics import PYRAMID_SERVE_HITS, registry
        from geomesa_tpu.obs import compile_count
        from geomesa_tpu.resilience import FaultInjected

        ms0 = 1_514_764_800_000
        day = 86_400_000
        slots = 1 << 16
        base = 512
        world = (-180.0, -90.0, 180.0, 90.0)
        rng = np.random.default_rng(53)
        ds = TpuDataStore(user="pyramid-bench")
        ds.create_schema("pyr", (
            "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
            f"geomesa.lean.generation.slots={slots},"
            "geomesa.lean.compaction.factor=0"))
        for lo in range(0, n, slots):
            m = min(slots, n - lo)
            ds.write("pyr", {
                "dtg": rng.integers(ms0, ms0 + 14 * day, m),
                "geom": (rng.uniform(-180, 180, m),
                         rng.uniform(-90, 90, m))})
        idx = ds._store("pyr")._indexes["z3"]
        idx.block()
        out["generations"] = len(idx.generations)

        def whole_extent():
            return idx.density([world], None, None, world, base, base)

        def cold():
            # the density-partial AND pyramid caches both short-circuit
            # repeat sweeps — drop them so every iteration pays the
            # full direct scan the cold path costs
            idx._density_cache.clear()
            idx._pyramid_cache.clear()
            return whole_extent()

        grids["direct"] = np.asarray(cold())
        cold_ms = _median_time(cold, iters=3) * 1e3
        out["cold_direct_ms"] = round(cold_ms, 2)
        idx._pyramid_cache.clear()
        t0 = time.perf_counter()
        out["builds"] = int(idx.build_pyramids(base=base))
        out["build_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        idx._density_cache.clear()
        h0 = registry.counter(PYRAMID_SERVE_HITS).count
        grids["pyramid"] = np.asarray(whole_extent())
        out["serve_hits"] = int(
            registry.counter(PYRAMID_SERVE_HITS).count - h0)
        warm_ms = _median_time(whole_extent, iters=5) * 1e3
        out["warm_pyramid_ms"] = round(warm_ms, 3)
        out["pyramid_speedup"] = round(cold_ms / max(warm_ms, 1e-3), 1)

        # warm single-tile latency at the finest pyramid-served zoom
        tiles = [(1, tx, ty) for tx in (0, 1) for ty in (0, 1)]
        for z, tx, ty in tiles:
            ds.density_tile("pyr", z, tx, ty)         # warm-up
        c0 = compile_count()
        lat = []
        for i in range(40):
            z, tx, ty = tiles[i % len(tiles)]
            t0 = time.perf_counter()
            ds.density_tile("pyr", z, tx, ty)
            lat.append((time.perf_counter() - t0) * 1e3)
        out["warm_recompiles"] = int(compile_count() - c0)
        lat.sort()
        out["tile_warm_p99_ms"] = round(
            lat[min(len(lat) - 1, int(0.99 * len(lat)))], 2)

        # interrupted build: exact through the fallback, then resumes
        idx._pyramid_cache.clear()
        gm_config.set_property("geomesa.resilience.fault.points",
                               "pyramid.build:2")
        try:
            try:
                idx.build_pyramids(base=base)
                out["fault_error"] = "fault point did not fire"
            except FaultInjected:
                idx._density_cache.clear()
                grids["interrupted"] = np.asarray(whole_extent())
        finally:
            gm_config.clear_property("geomesa.resilience.fault.points")
        out["resumed_builds"] = int(idx.build_pyramids(base=base))
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    # acceptance gates OUTSIDE the try: a swallowed assert could never
    # fail a run
    failures = []
    if "error" not in out and not out.get("skipped"):
        out["bit_exact"] = bool(
            "pyramid" in grids
            and np.array_equal(grids["direct"], grids["pyramid"]))
        if not out["bit_exact"]:
            failures.append("pyramid-served grid != direct scan grid")
        out["fault_exact"] = bool(
            "interrupted" in grids
            and np.array_equal(grids["direct"], grids["interrupted"]))
        if not out["fault_exact"]:
            failures.append(
                out.get("fault_error",
                        "interrupted-build grid != direct scan grid"))
        if out.get("serve_hits", 0) <= 0:
            failures.append("warm heatmap never touched a pyramid")
        if out.get("pyramid_speedup", 0.0) < 20.0:
            failures.append(
                f"pyramid_speedup {out.get('pyramid_speedup')} < 20x "
                f"(cold {out.get('cold_direct_ms')}ms, warm "
                f"{out.get('warm_pyramid_ms')}ms)")
        if out.get("tile_warm_p99_ms", float("inf")) >= 50.0:
            failures.append(
                f"tile_warm_p99_ms {out.get('tile_warm_p99_ms')} "
                "breaches the 50ms interactive pin")
        if out.get("warm_recompiles", 1) != 0:
            failures.append(
                f"{out.get('warm_recompiles')} recompiles while "
                "serving warm tiles")
    if failures:
        out["gate_failures"] = failures
        for f in failures:
            print(f"BENCH PYRAMID GATE FAILED: {f}", flush=True)
    out.update(_mem_probe())
    return out


def _planning_stanza() -> dict:
    """Sketch-driven planning acceptance gate (ISSUE 19): on a SKEWED
    multi-generation lean store, sketch-fed estimates must pull the
    per-query ``plan.estimate.ratio`` distance-from-1 p95 at or below
    the heuristic baseline's (docs/planning.md); a skew-constructed
    mispredict must replan exactly once with bit-exact results; a
    well-predicted query must never replan; warm queries stay
    recompile-free through the adaptive machinery.
    ``PLANNING_BENCH_N=0`` skips."""
    import numpy as np

    n = int(os.environ.get("PLANNING_BENCH_N", 2_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    try:
        from geomesa_tpu import config as gm_config
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.metrics import PLAN_REPLANNED, registry
        from geomesa_tpu.obs import compile_count

        ms0 = 1_514_764_800_000
        day = 86_400_000
        slots = 1 << 16
        rng = np.random.default_rng(41)
        ds = TpuDataStore(user="planning-bench")
        ds.create_schema(
            "pb", "name:String:index=true,dtg:Date,*geom:Point;"
                  "geomesa.index.profile=lean,"
                  f"geomesa.lean.generation.slots={slots},"
                  "geomesa.lean.compaction.factor=0")
        for lo in range(0, n, slots):
            m = min(slots, n - lo)
            dense = int(m * 0.85)     # skew: hot cluster + sparse tail
            ds.write("pb", {
                "name": np.where(rng.uniform(size=m) < 0.9, "hot",
                                 "cold").astype(object),
                "dtg": rng.integers(ms0, ms0 + 14 * day, m),
                "geom": (np.concatenate(
                             [rng.uniform(-74.05, -74.0, dense),
                              rng.uniform(-80, -70, m - dense)]),
                         np.concatenate(
                             [rng.uniform(40.0, 40.05, dense),
                              rng.uniform(35, 45, m - dense)]))})
        ds._store("pb")._indexes["z3"].block()
        # the ratio workload: the hot cluster (heuristics underestimate
        # badly), a same-size cold box (over), a wide box, and a
        # time-restricted cluster slice
        queries = [
            "BBOX(geom,-74.06,39.99,-73.99,40.06)",
            "BBOX(geom,-77.06,42.99,-76.99,43.06)",
            "BBOX(geom,-79,36,-71,44)",
            ("BBOX(geom,-74.06,39.99,-73.99,40.06) AND dtg DURING "
             "2018-01-01T00:00:00Z/2018-01-04T00:00:00Z"),
        ]

        def _ratio_dists() -> list:
            dists = []
            for q in queries:
                r = ds.explain_analyze("pb", q).summary.get(
                    "estimate_ratio")
                if r and r > 0:
                    dists.append(max(float(r), 1.0 / float(r)))
            return sorted(dists)

        def _p(dists: list, q: float) -> float:
            return round(dists[min(len(dists) - 1,
                                   int(q * len(dists)))], 3)

        # A/B the estimate ladder with replanning OFF so the ratios
        # measure pure estimate quality, not the correction; pin the
        # size gate open so a reduced PLANNING_BENCH_N can't silently
        # turn the sketch arm into a second heuristic arm
        gm_config.set_property("geomesa.planning.estimator.min.rows", 0)
        gm_config.set_property("geomesa.planning.replan.threshold", 0.0)
        gm_config.set_property("geomesa.planning.estimator.enabled",
                               False)
        try:
            d = _ratio_dists()
            out["heuristic_p50_ratio_dist"] = _p(d, 0.5)
            out["heuristic_p95_ratio_dist"] = _p(d, 0.95)
            gm_config.set_property("geomesa.planning.estimator.enabled",
                                   True)
            d = _ratio_dists()
            out["sketch_p50_ratio_dist"] = _p(d, 0.5)
            out["sketch_p95_ratio_dist"] = _p(d, 0.95)
        finally:
            gm_config.clear_property("geomesa.planning.replan.threshold")
            gm_config.clear_property(
                "geomesa.planning.estimator.enabled")

        # warm latency + recompile discipline with the adaptive
        # machinery at its DEFAULTS (replan armed, estimator on; the
        # 2M store clears the size gate, so min.rows stays pinned at 0
        # only for reduced-N runs)
        hot = queries[0]
        for q in queries:
            ds.query_result("pb", q)        # warm every shape
        c0 = compile_count()
        times = sorted(_median_time(
            lambda: ds.query_result("pb", hot), iters=3)
            for _ in range(5))
        out["query_warm_p99_ms"] = round(times[-1] * 1e3, 2)
        out["warm_recompiles"] = int(compile_count() - c0)

        # mispredict drill: heuristics under the skew MUST replan
        # exactly once, bit-exact against the non-adaptive path; the
        # sketch-fed plan of the same query must never replan
        oracle = np.sort(ds.query_result("pb", hot).positions)
        gm_config.set_property("geomesa.planning.estimator.enabled",
                               False)
        gm_config.set_property("geomesa.planning.replan.threshold", 2.0)
        gm_config.set_property("geomesa.planning.replan.min.rows", 64)
        try:
            before = registry.counter(PLAN_REPLANNED).count
            adaptive = np.sort(ds.query_result("pb", hot).positions)
            out["replan_count"] = int(
                registry.counter(PLAN_REPLANNED).count - before)
            out["replan_exact"] = bool(np.array_equal(adaptive, oracle))
            gm_config.set_property("geomesa.planning.estimator.enabled",
                                   True)
            before = registry.counter(PLAN_REPLANNED).count
            ds.query_result("pb", hot)
            out["well_predicted_replans"] = int(
                registry.counter(PLAN_REPLANNED).count - before)
        finally:
            gm_config.clear_property(
                "geomesa.planning.estimator.enabled")
            gm_config.clear_property(
                "geomesa.planning.estimator.min.rows")
            gm_config.clear_property("geomesa.planning.replan.threshold")
            gm_config.clear_property("geomesa.planning.replan.min.rows")
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    # acceptance gates OUTSIDE the try (arrow-stanza precedent)
    failures = []
    if "error" not in out and not out.get("skipped"):
        sp, hp = (out.get("sketch_p95_ratio_dist"),
                  out.get("heuristic_p95_ratio_dist"))
        if sp is None or hp is None or sp > hp * 1.05:
            failures.append(
                f"sketch-fed ratio-dist p95 {sp} not <= heuristic "
                f"baseline {hp}")
        if out.get("replan_count") != 1:
            failures.append(
                f"skew mispredict replanned {out.get('replan_count')} "
                "times, expected exactly 1")
        if not out.get("replan_exact"):
            failures.append("replanned results diverged from the "
                            "non-adaptive oracle")
        if out.get("well_predicted_replans", 1) != 0:
            failures.append(
                f"well-predicted query replanned "
                f"{out.get('well_predicted_replans')} times")
        if out.get("warm_recompiles", 1) != 0:
            failures.append(
                f"{out.get('warm_recompiles')} recompiles across warm "
                "adaptive queries")
    if failures:
        out["gate_failures"] = failures
        for f in failures:
            print(f"BENCH PLANNING GATE FAILED: {f}", flush=True)
    out.update(_mem_probe())
    return out


def _lint_stanza() -> dict:
    """gm-lint no-op guard (ISSUE 13 satellite): the static-analysis
    gate must pass on the benched tree AND stay importable with NO jax
    in the interpreter (cold CI shards run it without the accelerator
    stack) — verified in a subprocess so neither property can perturb
    the bench process, and cheap enough (~3 s, pure AST) to run every
    round."""
    import subprocess
    import sys
    out: dict = {}
    code = ("import sys\n"
            "from geomesa_tpu.analysis.__main__ import main\n"
            "rc = main(['--fail-on-new'])\n"
            "assert 'jax' not in sys.modules, 'analyzer imported jax'\n"
            "print('JAXFREE_OK')\n"
            "sys.exit(rc)\n")
    try:
        t0 = time.perf_counter()
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=120)
        out["clean"] = proc.returncode == 0
        # positive sentinel: a crash BEFORE the assert must not read
        # as the property having been verified
        out["jax_free"] = "JAXFREE_OK" in proc.stdout
        out["wall_s"] = round(time.perf_counter() - t0, 2)
        if proc.returncode != 0:
            out["tail"] = (proc.stdout + proc.stderr)[-500:]
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    return out


def _mem_highwater(extra: dict) -> dict:
    """The gated memory leaves: a fresh end-of-run probe, with
    ``device_resident_bytes`` raised to the max across every stanza's
    recorded probe (compact-summary comment)."""
    mem = _mem_probe()
    stanza_dev = [v.get("device_resident_bytes")
                  for v in extra.values() if isinstance(v, dict)]
    candidates = [int(x) for x in stanza_dev if x] + \
        [int(mem.get("device_resident_bytes", 0))]
    if any(candidates):
        mem["device_resident_bytes"] = max(candidates)
    return mem


#: relative tolerance band for the regression gate — tunnel-noise-scale
#: wiggle is not a regression; beyond 20% in the BAD direction is
REGRESSION_TOLERANCE = 0.20

#: metric-name direction conventions: timings regress UP, rates/speedups
#: regress DOWN; the STORAGE direction (ISSUE 9) treats the per-stanza
#: memory leaves (`peak_rss_mb` host high-water mark,
#: `device_resident_bytes` live HBM) as lower-better too, so a memory
#: regression fails as loudly as a perf one; the OVERHEAD direction
#: (ISSUE 12) does the same for the `*_overhead_pct` instrumentation-
#: tax leaves (heat tracking + write spans must stay cheap); anything
#: else (hit counts, row totals, booleans) is not a direction and is
#: never flagged
#: the PLANNING direction (ISSUE 19): mispredict distance
#: (max(ratio, 1/ratio), 1.0 = perfect estimate) regresses UP
_LOWER_BETTER_SUFFIXES = ("_ms", "_s", "_rss_mb", "_resident_bytes",
                          "_overhead_pct", "_ratio_dist")
#: the SERVING direction (ISSUE 17) adds the fused-plane leaves: qps
#: and batch fan-in regress DOWN like any other rate
_HIGHER_BETTER_MARKS = ("per_sec", "speedup", "wins", "value",
                        "_qps", "fanin")


def _flat_scalars(rec, prefix: str = "", depth: int = 0) -> dict:
    """Dotted-key numeric leaves of a (possibly nested) record —
    booleans excluded, two levels deep (the compact-summary shape)."""
    out: dict = {}
    if not isinstance(rec, dict):
        return out
    for k, v in rec.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict) and depth < 2:
            out.update(_flat_scalars(v, f"{key}.", depth + 1))
    return out


def compare_bench_records(current: dict, prior: dict,
                          tolerance: float = REGRESSION_TOLERANCE
                          ) -> list:
    """Regression gate (round-5 VERDICT: two silent median dips with
    no tracking): every directional scalar metric shared by the
    current record and the most recent prior one is compared; a move
    beyond ``tolerance`` in the bad direction yields an entry
    ``{"metric", "prior", "current", "ratio"}`` (ratio > 1 = that many
    times worse).  Pure on its inputs so tests can drive it with
    synthetic records."""
    cur = _flat_scalars(current)
    old = _flat_scalars(prior)
    regs = []
    for name, prev in old.items():
        now = cur.get(name)
        if now is None:
            continue
        leaf = name.rsplit(".", 1)[-1]
        if leaf == "recompiles" or leaf.endswith("_recompiles"):
            # retrace budget: a stanza that compiled NOTHING last round
            # and compiles now is exactly the silent recompile cliff
            # (ISSUE 5) — prev == 0 flags at a finite sentinel ratio so
            # the record stays JSON-serializable
            if now <= prev:
                continue
            ratio = now / prev if prev > 0 else 999.0
        elif prev <= 0:
            continue
        elif leaf.endswith(_LOWER_BETTER_SUFFIXES):
            ratio = now / prev
        elif any(m in name for m in _HIGHER_BETTER_MARKS):
            # matched against the FULL dotted name: pallas win leaves
            # are kernel names under "pallas_wins." — leaf-only
            # matching would silently skip exactly those regressions
            ratio = prev / now if now > 0 else 999.0
        else:
            continue
        if ratio > 1.0 + tolerance:
            regs.append({"metric": name, "prior": prev, "current": now,
                         "ratio": round(ratio, 3)})
    regs.sort(key=lambda r: -r["ratio"])
    return regs


def _latest_prior_record() -> tuple[dict | None, str | None]:
    """The newest prior round's parsed compact record
    (``BENCH_r*.json`` is the driver's capture: ``{"n", "tail",
    "parsed"}``) — the regression gate's baseline."""
    import glob
    import re
    here = os.path.dirname(os.path.abspath(__file__))
    best, best_n = None, -1
    for fn in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", fn)
        if not m:
            continue
        n = int(m.group(1))
        if n > best_n:
            best, best_n = fn, n
    if best is None:
        return None, None
    try:
        with open(best) as f:
            rec = json.load(f)
        parsed = rec.get("parsed")
        return (parsed if isinstance(parsed, dict) else None,
                os.path.basename(best))
    except Exception:
        return None, os.path.basename(best)


def _regression_gate(compact: dict) -> list:
    """Compare this run's compact record against the most recent
    BENCH_r*.json and LOG LOUDLY — silent dips are the failure mode
    this exists to kill."""
    prior, src = _latest_prior_record()
    if prior is None:
        return []
    regs = compare_bench_records(compact, prior)
    for r in regs:
        print(f"BENCH REGRESSION vs {src}: {r['metric']} "
              f"{r['prior']} -> {r['current']} "
              f"({r['ratio']}x worse)", flush=True)
    return regs


def _xz3_scale_stanza() -> dict:
    """Lean XZ3 (non-point WITH time) scale record — round-5 VERDICT:
    'lean XZ3 has no scale record'.  Streams envelope+timestamp slices
    through the generational (bin, code) runs, then measures a warm
    INTERSECTS-with-time query whose residual-filtered result is
    asserted ORACLE-EXACT (candidates must cover the oracle; the
    residual makes them exact — the planner's normal split).
    ``XZ3_SCALE_N=0`` skips."""
    import time

    import numpy as np

    n = int(os.environ.get("XZ3_SCALE_N", 2_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    try:
        from geomesa_tpu.obs import compile_count
        _c0 = compile_count()
        from geomesa_tpu.geometry.types import Polygon
        from geomesa_tpu.index.xz2_lean import LeanXZ3Index

        rng = np.random.default_rng(23)
        cx = rng.uniform(-170, 170, n)
        cy = rng.uniform(-75, 75, n)
        hw = rng.uniform(0.001, 0.05, n)
        bbox = np.column_stack([cx - hw, cy - hw, cx + hw, cy + hw])
        t = rng.integers(MS_2018, MS_2018 + 28 * 86_400_000, n)
        idx = LeanXZ3Index(period="week",
                           generation_slots=1 << 20)
        step = 1 << 20
        t0 = time.perf_counter()
        for lo in range(0, n, step):
            sl = slice(lo, lo + step)
            idx.append_bboxes(bbox[sl], t[sl])
        idx.block()
        out["rows"] = n
        out["ingest_s"] = round(time.perf_counter() - t0, 2)
        out["ingest_rows_per_sec"] = round(n / max(
            time.perf_counter() - t0, 1e-9))
        out["generations"] = len(idx.generations)
        out["tiers"] = idx.tier_counts()
        qx0, qy0, qx1, qy1 = -80.0, 30.0, -60.0, 50.0
        t_lo = MS_2018 + 7 * 86_400_000
        t_hi = MS_2018 + 14 * 86_400_000
        poly = Polygon([(qx0, qy0), (qx1, qy0), (qx1, qy1),
                        (qx0, qy1)])
        cand = idx.query(poly, t_lo, t_hi)   # warm/compile
        t0 = time.perf_counter()
        cand = idx.query(poly, t_lo, t_hi)
        out["query_warm_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 1)
        # residual exactness: envelope-intersects ∧ time window (axis-
        # aligned rects, so envelope-intersect IS intersects)
        hit = ((bbox[:, 0] <= qx1) & (bbox[:, 2] >= qx0)
               & (bbox[:, 1] <= qy1) & (bbox[:, 3] >= qy0)
               & (t >= t_lo) & (t <= t_hi))
        oracle = np.flatnonzero(hit)
        cand = np.asarray(cand, np.int64)
        got = np.unique(cand[hit[cand]])
        covered = bool(np.isin(oracle, cand).all())
        out["candidates"] = int(len(cand))
        out["hits"] = int(len(oracle))
        out["oracle_exact"] = bool(covered
                                   and np.array_equal(got, oracle))
        out["recompiles"] = int(compile_count() - _c0)
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    out.update(_mem_probe())
    return out


def _stats_pushdown_stanza() -> dict:
    """Stat-sketch push-down regression numbers (ISSUE 3): a
    many-generation lean store answers ``Count();MinMax;Histogram``
    over a bbox+time window from per-run sketches — cold folds every
    run, the warm repeat serves sealed runs from the sketch-partial
    cache and folds only the live one; zero candidate materialization
    asserted via the ``lean.sketch.materialized_fallbacks`` counter.
    The recorded 1B twin lives in STORE_SCALE records
    (store_scale_proof.run's stats_pushdown_* fields).
    ``STATS_BENCH_N=0`` skips."""
    import time

    import numpy as np

    n = int(os.environ.get("STATS_BENCH_N", 4_000_000))
    if not n:
        return {"skipped": True}
    out: dict = {}
    try:
        from geomesa_tpu.obs import compile_count
        _c0 = compile_count()
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.metrics import (
            LEAN_STATS_MATERIALIZED, registry,
        )

        rng = np.random.default_rng(29)
        slots = 1 << 17
        ds = TpuDataStore()
        ds.create_schema(
            "sbench", "score:Double:index=true,dtg:Date,*geom:Point;"
                      "geomesa.index.profile=lean,"
                      f"geomesa.lean.generation.slots={slots},"
                      "geomesa.lean.compaction.factor=0")
        t0 = time.perf_counter()
        for lo in range(0, n, slots):
            m = min(slots, n - lo)
            ds.write("sbench", {
                "score": rng.normal(50.0, 20.0, m),
                "dtg": rng.integers(MS_2018,
                                    MS_2018 + 14 * 86_400_000, m),
                "geom": (rng.uniform(-180, 180, m),
                         rng.uniform(-90, 90, m)),
            })
        out["rows"] = n
        out["ingest_s"] = round(time.perf_counter() - t0, 2)
        st = ds._store("sbench")
        out["attr_runs"] = len(st._lean_attr_index("score").generations)
        spec = "Count();MinMax(score);Histogram(score,20,0,100)"
        q = ("BBOX(geom,-180,-90,180,90) AND dtg DURING "
             "2018-01-02T00:00:00Z/2018-01-10T00:00:00Z")
        m0 = registry.counter(LEAN_STATS_MATERIALIZED).count
        t0 = time.perf_counter()
        cold = ds.stats("sbench", q, spec)
        out["cold_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        ds.stats("sbench", q, spec)   # compiles the live-only shape
        t0 = time.perf_counter()
        warm = ds.stats("sbench", q, spec)
        out["warm_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
        out["warm_speedup"] = round(
            out["cold_ms"] / max(out["warm_ms"], 1e-3), 1)
        out["materialized_fallbacks"] = int(
            registry.counter(LEAN_STATS_MATERIALIZED).count - m0)
        out["results_equal"] = bool(
            cold.to_json() == warm.to_json())
        out["recompiles"] = int(compile_count() - _c0)
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    out.update(_mem_probe())
    return out


def _slo_stanza() -> dict:
    """SLO-plane acceptance gate (ISSUE 20): on the warm fused
    64-client workload >= 90% of each root query's wall must land in
    named ledger stages (mean residual < 10%), the per-tenant
    quantiles and burn gauges must appear in the Prometheus
    exposition with at least one parseable exemplar whose trace_id
    resolves in the tracer, and the finish-hook attribution must cost
    <= 5% wall overhead vs ``geomesa.slo.enabled=false`` with ZERO
    warm recompiles.  ``SLO_BENCH_N=0`` skips."""
    import numpy as np

    n = int(os.environ.get("SLO_BENCH_N", 1_000_000))
    if not n:
        return {"skipped": True}
    clients = int(os.environ.get("SLO_BENCH_CLIENTS", 64))
    rounds = int(os.environ.get("SLO_BENCH_ROUNDS", 3))
    out: dict = {}
    try:
        import re as _re
        import threading
        from geomesa_tpu import config as gm_config
        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.metrics import registry
        from geomesa_tpu.obs import (compile_count, prometheus_text,
                                     slo_plane, tracer)

        ms0 = 1_514_764_800_000
        day = 86_400_000
        slots = 1 << 16
        rng = np.random.default_rng(53)
        ds = TpuDataStore(user="slo-bench")
        ds.create_schema("slob", (
            "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
            f"geomesa.lean.generation.slots={slots},"
            "geomesa.lean.compaction.factor=0"))
        for lo in range(0, n, slots):
            m = min(slots, n - lo)
            ds.write("slob", {
                "dtg": rng.integers(ms0, ms0 + 14 * day, m),
                "geom": (rng.uniform(-180, 180, m),
                         rng.uniform(-90, 90, m))})
        ds._store("slob")._indexes["z3"].block()
        # the serving stanza's dashboard workload: selective
        # bbox+window filters, one compatibility key, 8 tenants
        queries, windows = [], []
        for i in range(16):
            x = -170.0 + i * 1.5
            d = 1 + (i % 5)
            queries.append(
                f"BBOX(geom,{x},-60,{x + 3},-57) AND dtg DURING "
                f"2018-01-{d:02d}T00:00:00Z/2018-01-{d + 3:02d}"
                "T00:00:00Z")
            windows.append((((x, -60.0, x + 3.0, -57.0),),
                            ms0 + (d - 1) * day, ms0 + (d + 2) * day))
        gm_config.set_property("geomesa.serving.fuse.window.ms", 10.0)
        gm_config.set_property("geomesa.serving.fuse.max.batch", clients)
        try:
            # warm every pow2 capacity bucket so the measured rounds
            # see a pinned compiled-shape set (serving-stanza recipe)
            k = 1
            while k <= clients:
                ds._fused_windows_dispatch(
                    "slob", [windows[j % len(windows)] for j in range(k)])
                k <<= 1
            errors: list = []
            barrier = threading.Barrier(clients + 1)

            def client(i: int) -> None:
                try:
                    barrier.wait(timeout=60)
                    for r in range(rounds):
                        ds.query_fused(
                            "slob", queries[(i + r) % len(queries)],
                            tenant=f"t{i % 8}")
                except Exception as e:  # surfaced via the gate below
                    errors.append(repr(e))

            def fused_round() -> float:
                barrier.reset()
                threads = [threading.Thread(target=client, args=(i,),
                                            daemon=True)
                           for i in range(clients)]
                for t in threads:
                    t.start()
                barrier.wait(timeout=60)   # releases all clients at once
                t0 = time.perf_counter()
                for t in threads:
                    t.join()
                return time.perf_counter() - t0

            fused_round()                  # unrecorded warm round
            # A/B overhead: the SAME warm workload with the plane off
            # then on, best-of-2 per mode so one scheduler hiccup
            # cannot fake (or mask) an overhead
            gm_config.set_property("geomesa.slo.enabled", False)
            off_dt = min(fused_round() for _ in range(2))
            gm_config.set_property("geomesa.slo.enabled", True)
            slo_plane.reset()              # only warm traces attribute
            c0 = compile_count()
            on_dt = min(fused_round() for _ in range(2))
            out["warm_recompiles"] = int(compile_count() - c0)
            out["slo_off_s"] = round(off_dt, 3)
            out["slo_on_s"] = round(on_dt, 3)
            out["overhead_pct"] = round(
                (on_dt - off_dt) / off_dt * 100.0, 2)
            if errors:
                out["client_errors"] = errors[:4]
            # attributed coverage of the warm fused root query wall
            report = slo_plane.report()
            qcls = report.get("classes", {}).get("query", {})
            out["residual_pct"] = qcls.get("residual_pct")
            out["burn_5m"] = qcls.get("burn_5m")
            # the exposition must carry >= 1 exemplar whose trace_id
            # the tracer can still resolve (the /traces/<id> join)
            expo = slo_plane.exposition()
            m = _re.search(r' # \{trace_id="([0-9a-f]+)"\}', expo)
            out["exemplar_found"] = bool(m)
            out["exemplar_resolves"] = bool(
                m and tracer.find(m.group(1)) is not None)
            # per-tenant p99 + burn gauges on the scrape surface
            slo_plane.publish()
            body = prometheus_text(registry.snapshot())
            out["tenant_p99_exposed"] = (
                "geomesa_slo_tenant_" in body and 'quantile="0.99"' in body)
            out["burn_gauges_exposed"] = (
                "geomesa_slo_query_burn_5m" in body
                and "geomesa_slo_query_burn_1h" in body)
            out["clients"] = clients
        finally:
            gm_config.clear_property("geomesa.serving.fuse.window.ms")
            gm_config.clear_property("geomesa.serving.fuse.max.batch")
            gm_config.clear_property("geomesa.slo.enabled")
    except Exception as e:  # never kill the bench over a stanza
        out["error"] = repr(e)
    # acceptance gates run OUTSIDE the try (resilience/arrow
    # precedent: an assert swallowed by the stanza's blanket except
    # could never fail a run)
    failures = []
    if "error" not in out and not out.get("skipped"):
        if out.get("client_errors"):
            failures.append(f"fused clients errored: {out['client_errors']}")
        residual = out.get("residual_pct")
        if residual is None or residual >= 10.0:
            failures.append(
                f"unattributed residual {residual}% of warm fused root "
                "wall — the stage ledger must cover >= 90%")
        if out.get("overhead_pct", 100.0) > 5.0:
            failures.append(
                f"SLO attribution costs {out.get('overhead_pct')}% wall "
                "vs slo.enabled=false (budget 5%)")
        if out.get("warm_recompiles", 1) != 0:
            failures.append(
                f"warm fused path recompiled {out.get('warm_recompiles')} "
                "time(s) with the SLO plane on")
        if not out.get("exemplar_resolves"):
            failures.append(
                "no exposition exemplar resolves in the tracer "
                f"(found={out.get('exemplar_found')}) — the "
                "/metrics.prom → /traces/<id> join is broken")
        if not out.get("tenant_p99_exposed"):
            failures.append("slo.tenant.* p99 missing from exposition")
        if not out.get("burn_gauges_exposed"):
            failures.append("slo.query.burn.{5m,1h} gauges missing "
                            "from exposition")
    if failures:
        out["gate_failures"] = failures
        for f in failures:
            print(f"BENCH SLO GATE FAILED: {f}", flush=True)
    out.update(_mem_probe())
    return out


if __name__ == "__main__":
    main()
