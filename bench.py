"""Benchmark: Z3 ingest key generation + bbox+time scan (BASELINE config 1).

Measures the framework's hot paths on one chip, GDELT-shaped synthetic
data:

* **ingest**: vectorized Z3 SFC encode + device key sort, keys/sec/chip
  (the reference's write-path hot loop, Z3IndexKeySpace.toIndexKey —
  per-feature JVM code it claims >10k records/sec/node for;
  docs/user/introduction.rst:26).
* **scan**: bbox+week query over the built index — plan (host range
  decomposition) + device seeks + fused candidate filter — reported as
  features-matched/sec.

Prints ONE JSON line with the primary metric (ingest keys/sec/chip);
vs_baseline is the ratio to the reference's 10k records/sec/node claim.
"""

import json
import time

import numpy as np

N = 16_000_000
SCAN_N = 4_000_000
MS_2018 = 1514764800000



def _median_time(fn, iters=5):
    """Median per-iteration wall time — robust to tunnel stalls that
    would skew a mean."""
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    times.sort()
    mid = len(times) // 2
    if len(times) % 2:
        return times[mid]
    return (times[mid - 1] + times[mid]) / 2


def main():
    import jax
    import jax.numpy as jnp

    import geomesa_tpu  # noqa: F401  (enables x64)
    from geomesa_tpu.curve import TimePeriod, to_binned_time, z3_sfc
    from geomesa_tpu.index import Z3PointIndex

    rng = np.random.default_rng(42)
    # GDELT-shaped: world-wide events over two weeks
    x = rng.uniform(-180.0, 180.0, N)
    y = rng.uniform(-56.0, 72.0, N)
    t = rng.integers(MS_2018, MS_2018 + 14 * 86_400_000, N)

    sfc = z3_sfc(TimePeriod.WEEK)
    bins, offs = to_binned_time(t, TimePeriod.WEEK)

    xd = jax.device_put(jnp.asarray(x))
    yd = jax.device_put(jnp.asarray(y))
    od = jax.device_put(jnp.asarray(offs.astype(np.float64)))
    bd = jax.device_put(jnp.asarray(bins.astype(np.int32)))

    @jax.jit
    def ingest(xs, ys, os_, bs):
        z = sfc.index(xs, ys, os_)
        # variadic 2-key sort with the permutation as payload: ~7x faster
        # than lexsort+gather on TPU
        return jax.lax.sort(
            (bs, z, jnp.arange(z.shape[0], dtype=jnp.int32)),
            dimension=0, num_keys=2)

    # warmup/compile; completion is forced via a tiny device→host read
    # because block_until_ready can return before remote execution
    # finishes on tunneled platforms
    _ = np.asarray(ingest(xd, yd, od, bd)[0][:1])

    ingest_dt = _median_time(
        lambda: np.asarray(ingest(xd, yd, od, bd)[0][:1]))
    ingest_rate = N / ingest_dt

    # scan: selective bbox + 5-day window
    index = Z3PointIndex.build(x[:SCAN_N], y[:SCAN_N], t[:SCAN_N],
                               period=TimePeriod.WEEK)
    box = (-80.0, 30.0, -60.0, 50.0)
    tlo, thi = MS_2018 + 2 * 86_400_000, MS_2018 + 7 * 86_400_000
    hits = index.query([box], tlo, thi)  # warm (compiles both phases)
    q_dt = _median_time(lambda: index.query([box], tlo, thi), iters=10)
    scan_rate = len(hits) / q_dt
    # index-resident points covered per second of query wall time (the
    # reference's "tens of millions of points in seconds" claim scale)
    scanned_rate = SCAN_N / q_dt

    # batched windows: 32 independent bbox+time queries in ONE dispatch
    # (the tube-select / kNN scan pattern; amortizes dispatch latency)
    qrng = np.random.default_rng(7)
    windows = []
    for _ in range(32):
        cx = float(qrng.uniform(-150, 150))
        cy = float(qrng.uniform(-40, 60))
        lo = MS_2018 + int(qrng.integers(0, 9)) * 86_400_000
        windows.append(([(cx - 3, cy - 3, cx + 3, cy + 3)],
                        lo, lo + 3 * 86_400_000))
    batched = index.query_many(windows)  # warm
    batched_dt = _median_time(lambda: index.query_many(windows))
    batched_hits = int(sum(len(b) for b in batched))

    # density histogram (auto: sorted-segment at this N; Pallas MXU
    # one-hot for small batches)
    from geomesa_tpu.ops.density import density_grid_auto
    import jax.numpy as jnp
    dmask = jnp.ones(N, dtype=bool)
    dw = jnp.ones(N, dtype=jnp.float32)
    grid = density_grid_auto(xd, yd, dw, dmask,
                             (-180.0, -90.0, 180.0, 90.0), 256, 128)
    _ = np.asarray(grid)  # warm

    def one_density():
        g = density_grid_auto(xd, yd, dw, dmask,
                              (-180.0, -90.0, 180.0, 90.0), 256, 128)
        _ = np.asarray(g[:1, :1])

    density_dt = _median_time(one_density)

    print(json.dumps({
        "metric": "z3_ingest_keys_per_sec_per_chip",
        "value": round(ingest_rate),
        "unit": "keys/sec",
        "vs_baseline": round(ingest_rate / 10_000.0, 2),
        "extra": {
            "n_points": N,
            "bbox_time_scan_features_per_sec": round(scan_rate),
            "scan_points_covered_per_sec": round(scanned_rate),
            "scan_hits": int(len(hits)),
            "batched_windows_per_sec": round(32 / batched_dt, 1),
            "batched_window_hits": batched_hits,
            "density_256x128_ms": round(density_dt * 1e3, 1),
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
