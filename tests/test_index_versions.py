"""Versioned index layouts (VERDICT r1 item 7): v1 (legacy
semi-normalized curve) layouts stay fully queryable, the catalog records
per-index versions, and migration rebuilds at current layouts — the
reference's Z3IndexV1../AttributeIndexV2..V7 + BackCompatibilityTest
machinery (index/index/z3/legacy/)."""

import json
import os

import numpy as np
import pytest

from geomesa_tpu.datastore import CURRENT_INDEX_VERSIONS, TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql

MS = 1514764800000
DAY = 86_400_000
N = 20_003

SPEC_LEGACY = ("name:String:index=true,dtg:Date,*geom:Point;"
               "geomesa.index.versions='z3:1,z2:1'")
Z3_ECQL = ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
           "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
Z2_ECQL = "BBOX(geom, -74.2, 40.8, -73.9, 41.1)"


def _data(rng):
    return {
        "name": rng.choice(["a", "b", "c"], N),
        "dtg": rng.integers(MS, MS + 21 * DAY, N),
        "geom": (rng.uniform(-75.0, -73.0, N), rng.uniform(40.0, 42.0, N)),
    }


def _oracle(ds, name, ecql):
    return np.flatnonzero(
        evaluate_filter(parse_ecql(ecql), ds._store(name).batch))


def test_legacy_curves_differ_from_current():
    """Sanity: v1 keys really are a different layout (else the version
    machinery tests nothing)."""
    from geomesa_tpu.curve.legacy import legacy_z3_sfc
    from geomesa_tpu.curve.sfc import z3_sfc
    x = np.array([-74.3, 10.0])
    y = np.array([40.7, -45.0])
    t = np.array([3.6e5, 1.0e6])
    a = np.asarray(z3_sfc("week").index(x, y, t, xp=np))
    b = np.asarray(legacy_z3_sfc("week").index(x, y, t, xp=np))
    assert not np.array_equal(a, b)


def test_v1_layout_serves_queries_exactly():
    """A schema pinned to v1 layouts plans ranges in the LEGACY curve
    space and still returns oracle-equal hits."""
    ds = TpuDataStore()
    ds.create_schema("ev", SPEC_LEGACY)
    ds.write("ev", _data(np.random.default_rng(3)))
    st = ds._store("ev")
    assert st.index_versions["z3"] == 1 and st.index_versions["z2"] == 1
    for ecql in (Z3_ECQL, Z2_ECQL):
        got = ds.query_result("ev", ecql)
        np.testing.assert_array_equal(np.sort(got.positions),
                                      _oracle(ds, "ev", ecql))
    assert st.z3_index().version == 1
    assert st.z2_index().version == 1


def test_v1_layout_mesh_store():
    """Versioned layouts apply to the sharded indexes too."""
    from geomesa_tpu.parallel import device_mesh
    ds = TpuDataStore(mesh=device_mesh())
    ds.create_schema("ev", SPEC_LEGACY)
    ds.write("ev", _data(np.random.default_rng(5)))
    got = ds.query_result("ev", Z3_ECQL)
    np.testing.assert_array_equal(np.sort(got.positions),
                                  _oracle(ds, "ev", Z3_ECQL))
    assert ds._store("ev").z3_index().version == 1


def test_catalog_records_and_reloads_versions(tmp_path):
    cat = str(tmp_path / "cat")
    ds = TpuDataStore(cat)
    ds.create_schema("ev", SPEC_LEGACY)
    ds.write("ev", _data(np.random.default_rng(7)))
    ds.flush("ev")
    with open(os.path.join(cat, "ev.schema.json")) as f:
        meta = json.load(f)
    assert meta["index_versions"]["z3"] == 1
    # reopen: the recorded layout version must win
    ds2 = TpuDataStore(cat)
    st = ds2._store("ev")
    assert st.index_versions["z3"] == 1
    got = ds2.query_result("ev", Z3_ECQL)
    np.testing.assert_array_equal(np.sort(got.positions),
                                  _oracle(ds2, "ev", Z3_ECQL))


def test_pre_versioning_catalog_defaults_to_current(tmp_path):
    """A v1-era catalog entry (no index_versions key) reads as current
    layouts — that is what the round-1 code wrote."""
    cat = str(tmp_path / "cat")
    ds = TpuDataStore(cat)
    ds.create_schema("ev", "name:String,dtg:Date,*geom:Point")
    ds.write("ev", _data(np.random.default_rng(9)))
    ds.flush("ev")
    # strip the versions key, simulating the old writer
    path = os.path.join(cat, "ev.schema.json")
    with open(path) as f:
        meta = json.load(f)
    del meta["index_versions"]
    with open(path, "w") as f:
        json.dump(meta, f)
    with open(os.path.join(cat, "catalog.version"), "w") as f:
        f.write("1")
    ds2 = TpuDataStore(cat)
    assert ds2._store("ev").index_versions == CURRENT_INDEX_VERSIONS
    got = ds2.query_result("ev", Z3_ECQL)
    np.testing.assert_array_equal(np.sort(got.positions),
                                  _oracle(ds2, "ev", Z3_ECQL))


def test_migrate_schema_rebuilds_current(tmp_path):
    cat = str(tmp_path / "cat")
    ds = TpuDataStore(cat)
    ds.create_schema("ev", SPEC_LEGACY)
    ds.write("ev", _data(np.random.default_rng(11)))
    before = ds.query_result("ev", Z3_ECQL).positions
    assert ds._store("ev").z3_index().version == 1
    old = ds.migrate_schema("ev")
    assert old["z3"] == 1
    st = ds._store("ev")
    assert st.index_versions == CURRENT_INDEX_VERSIONS
    # indexes rebuilt at the new layout; hits unchanged
    assert st.z3_index().version == CURRENT_INDEX_VERSIONS["z3"]
    after = ds.query_result("ev", Z3_ECQL).positions
    np.testing.assert_array_equal(np.sort(before), np.sort(after))
    with open(os.path.join(cat, "ev.schema.json")) as f:
        assert json.load(f)["index_versions"]["z3"] \
            == CURRENT_INDEX_VERSIONS["z3"]


def test_update_schema_current_triggers_migration():
    from geomesa_tpu.features.feature_type import parse_spec
    ds = TpuDataStore()
    ds.create_schema("ev", SPEC_LEGACY)
    ds.write("ev", _data(np.random.default_rng(13)))
    assert ds._store("ev").index_versions["z3"] == 1
    new_sft = parse_spec(
        "ev", "name:String:index=true,dtg:Date,*geom:Point;"
              "geomesa.index.versions=current")
    ds.update_schema("ev", new_sft)
    assert ds._store("ev").index_versions == CURRENT_INDEX_VERSIONS
    got = ds.query_result("ev", Z3_ECQL)
    np.testing.assert_array_equal(np.sort(got.positions),
                                  _oracle(ds, "ev", Z3_ECQL))
