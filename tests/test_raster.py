"""Raster store tests: pyramid levels, bbox query, device mosaic."""

import numpy as np
import pytest

from geomesa_tpu.raster import RasterStore, RasterTile


def checker(v, shape=(16, 16)):
    """Constant tile of value v."""
    return np.full(shape, float(v), dtype=np.float32)


@pytest.fixture
def store():
    rs = RasterStore()
    # 2x2 grid of 16x16 tiles over [0,2]x[0,2]; value = tile index
    k = 0
    for ty in range(2):
        for tx in range(2):
            rs.put(checker(k), (tx, ty, tx + 1, ty + 1))
            k += 1
    # one coarse tile covering everything (32x smaller resolution)
    rs.put(checker(99, (8, 8)), (0, 0, 2, 2))
    return rs


def test_levels_and_counts(store):
    res = store.available_resolutions
    assert len(res) == 2
    assert res[0] == pytest.approx(1 / 16)
    assert res[1] == pytest.approx(2 / 8)
    assert store.count() == 5
    assert store.count(res[0]) == 4


def test_get_tiles_bbox(store):
    tiles = store.get_tiles((0.2, 0.2, 0.8, 0.8))
    assert len(tiles) == 1 and tiles[0].data[0, 0] == 0
    tiles = store.get_tiles((0.5, 0.5, 1.5, 1.5))
    assert len(tiles) == 4
    # coarse level explicitly
    tiles = store.get_tiles((0.2, 0.2, 0.8, 0.8), resolution=0.25)
    assert len(tiles) == 1 and tiles[0].data[0, 0] == 99


def test_resolution_selection(store):
    fine, coarse = store.available_resolutions
    # a request coarser than both picks the coarsest fine-enough level
    assert store._pick_resolution(1.0) == coarse
    assert store._pick_resolution(0.1) == fine
    # finer than available -> finest existing
    assert store._pick_resolution(0.001) == fine
    assert store._pick_resolution(None) == fine


def test_mosaic_values(store):
    grid = store.mosaic((0, 0, 2, 2), 32, 32)
    assert grid.shape == (32, 32)
    # row 0 is north (y near 2): tiles 2 (left) and 3 (right)
    assert grid[0, 0] == 2 and grid[0, -1] == 3
    assert grid[-1, 0] == 0 and grid[-1, -1] == 1
    # no nodata inside full coverage
    assert not np.isnan(grid).any()


def test_mosaic_nodata_and_partial():
    rs = RasterStore()
    rs.put(checker(7), (0, 0, 1, 1))
    grid = rs.mosaic((0, 0, 2, 2), 16, 16)
    south_west = grid[8:, :8]
    assert (south_west == 7).all()
    assert np.isnan(grid[:8, 8:]).all()  # north-east uncovered


def test_mosaic_resamples_resolution(store):
    # ask at the coarse level: everything is the coarse tile's value
    grid = store.mosaic((0, 0, 2, 2), 8, 8, resolution=0.25)
    assert (grid == 99).all()


def test_empty_store():
    rs = RasterStore()
    assert rs.get_tiles((0, 0, 1, 1)) == []
    grid = rs.mosaic((0, 0, 1, 1), 4, 4)
    assert np.isnan(grid).all()


def test_mismatched_tile_shape_rejected():
    rs = RasterStore()
    rs.put(checker(1), (0, 0, 1, 1))
    with pytest.raises(ValueError):
        # same resolution but different shape cannot stack
        rs.put(checker(1, (16, 32)), (2, 0, 4, 1))


def test_tile_resolution():
    t = RasterTile(np.zeros((10, 20), dtype=np.float32), (0, 0, 2, 1))
    assert t.resolution == pytest.approx(0.1)


def test_count_accepts_tile_resolution():
    """count() must accept a tile's own .resolution (rounding-keyed)."""
    rs = RasterStore()
    t = RasterTile(np.zeros((16, 16), dtype=np.float32), (0, 0, 1.0 / 3, 1))
    rs.put(t.data, t.bbox)
    assert rs.count(t.resolution) == 1


def test_raster_bounds_and_grid_range():
    from geomesa_tpu.raster import RasterStore
    rs = RasterStore()
    rs.put(np.ones((16, 16)), (0.0, 0.0, 1.0, 1.0))
    rs.put(np.ones((16, 16)), (1.0, 0.0, 2.0, 1.0))
    assert rs.bounds() == (0.0, 0.0, 2.0, 1.0)
    cols, rows = rs.grid_range()
    assert (cols, rows) == (32, 16)


def test_raster_pyramid_and_mosaic_consistency():
    from geomesa_tpu.raster import RasterStore
    rng = np.random.default_rng(5)
    rs = RasterStore()
    for i in range(2):
        rs.put(rng.uniform(0, 10, (32, 32)).astype(np.float32),
               (i * 1.0, 0.0, (i + 1) * 1.0, 1.0))
    resolutions = rs.build_pyramid(levels=2)
    assert len(resolutions) == 3
    assert resolutions[1] == resolutions[0] * 2
    # coarser level serves a coarse request; tile count preserved
    assert rs.count(resolutions[1]) == 2
    coarse = rs.mosaic((0.0, 0.0, 2.0, 1.0), 16, 8,
                       resolution=resolutions[2])
    fine = rs.mosaic((0.0, 0.0, 2.0, 1.0), 16, 8)
    # pooled pyramid approximates the fine mosaic at coarse output sizes
    assert np.nanmean(np.abs(coarse - fine)) < 3.0
    assert not np.isnan(coarse).any()


def test_raster_save_load_roundtrip(tmp_path):
    from geomesa_tpu.raster import RasterStore
    rng = np.random.default_rng(7)
    rs = RasterStore("elev")
    for i in range(3):
        rs.put(rng.uniform(0, 100, (8, 8)).astype(np.float32),
               (i * 1.0, 0.0, (i + 1) * 1.0, 1.0))
    rs.build_pyramid(levels=1)
    path = str(tmp_path / "raster.npz")
    rs.save(path)
    rs2 = RasterStore.load(path)
    assert rs2.name == "elev"
    assert rs2.available_resolutions == rs.available_resolutions
    assert rs2.count() == rs.count()
    a = rs.mosaic((0.0, 0.0, 3.0, 1.0), 24, 8)
    b = rs2.mosaic((0.0, 0.0, 3.0, 1.0), 24, 8)
    np.testing.assert_allclose(a, b)
