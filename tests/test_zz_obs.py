"""Observability layer (ISSUE 5): tracing through the query path,
quantile metrics, Prometheus/trace web surface, recompile budget,
unified audit, reporters.

The lean-store trace test is the acceptance shape: one traced query
yields ONE trace whose spans cover plan / decompose / scan-device /
scan-host / post-filter with device-ms and cache attributes.
"""

import io
import json
import re
import time

import numpy as np
import pytest

from geomesa_tpu import obs
from geomesa_tpu.audit import InMemoryAuditWriter
from geomesa_tpu.config import clear_property, set_property
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.metrics import (
    DelimitedFileReporter, LoggingReporter, MetricRegistry,
    PeriodicReporter, merge_snapshots, registry,
)

MS = 1514764800000
DAY = 86_400_000

LEAN_Q = ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
          "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")


def _mk_lean_store(audit=None, n=40_000):
    rng = np.random.default_rng(23)
    ds = TpuDataStore(audit_writer=audit, user="obs-test")
    # the tight HBM budget forces real tiering (live full-tier run +
    # demoted host spills), so a traced query exercises device AND
    # host scan phases — the acceptance trace shape
    ds.create_schema(
        "evt", "score:Double,dtg:Date,*geom:Point;"
               "geomesa.index.profile=lean,"
               "geomesa.lean.generation.slots=16384,"
               "geomesa.lean.compaction.factor=0,"
               "geomesa.lean.hbm.budget=700000")
    for s in range(0, n, 16_000):    # several sealed generations
        m = min(16_000, n - s)
        ds.write("evt", {
            "score": rng.uniform(0, 100, m),
            "dtg": rng.integers(MS, MS + 14 * DAY, m),
            "geom": (rng.uniform(-75, -73, m), rng.uniform(40, 42, m))})
    return ds


@pytest.fixture(scope="module")
def lean_ds():
    audit = InMemoryAuditWriter()
    ds = _mk_lean_store(audit=audit)
    ds._obs_audit = audit
    return ds


def _call(app, method, path):
    cap = {}

    def sr(status, headers):
        cap["status"] = int(status.split()[0])
        cap["headers"] = dict(headers)

    qs = ""
    if "?" in path:
        path, qs = path.split("?", 1)
    body = b"".join(app({
        "REQUEST_METHOD": method, "PATH_INFO": path, "QUERY_STRING": qs,
        "CONTENT_LENGTH": "0", "wsgi.input": io.BytesIO(b"")}, sr))
    return cap["status"], cap["headers"], body.decode()


# -- tracing through the query path ---------------------------------------

def test_traced_lean_query_single_trace_covers_phases(lean_ds):
    lean_ds.query("evt", LEAN_Q)           # warm/compile
    audit = lean_ds._obs_audit
    got = lean_ds.query_result("evt", LEAN_Q)
    assert len(got.positions) > 0
    ev = audit.events[-1]
    assert ev.trace_id, "audit event must carry the trace id"
    tr = obs.tracer.find(ev.trace_id)
    assert tr is not None
    # ONE trace: every span shares the trace id
    assert {s.trace_id for s in tr.spans} == {ev.trace_id}
    names = {s.name for s in tr.spans}
    assert {"query", "query.plan", "query.decompose",
            "query.scan.device", "query.scan.host",
            "query.post_filter"} <= names
    root = tr.root_span
    assert root.name == "query"
    assert root.attributes["schema"] == "evt"
    assert root.attributes["hits"] == len(got.positions)
    # device attribution rolled up onto the root
    assert root.attributes.get("device_ms", 0) > 0
    dev = [s for s in tr.spans if s.name == "query.scan.device"]
    assert dev and all(s.attributes["device_ms"] >= 0 for s in dev)
    assert any("runs" in s.attributes for s in dev)
    # children nest under the root's tree (parent ids resolve in-trace)
    ids = {s.span_id for s in tr.spans}
    assert all(s.parent_id in ids for s in tr.spans
               if s.parent_id is not None)


def test_density_trace_carries_cache_attribution():
    # keys-tier generations (payload_on_device=False): the tier whose
    # sealed density partials cache — full-tier runs re-scan by design
    from geomesa_tpu.index.z3_lean import LeanZ3Index
    rng = np.random.default_rng(29)
    idx = LeanZ3Index(period="week", generation_slots=8192,
                      payload_on_device=False)
    for _ in range(3):
        m = 8192
        idx.append(rng.uniform(-75, -73, m), rng.uniform(40, 42, m),
                   rng.integers(MS, MS + 14 * DAY, m))
    idx.block()
    box = [(-74.5, 40.5, -73.5, 41.5)]
    args = (box, MS + 2 * DAY, MS + 9 * DAY, (-180, -90, 180, 90), 64, 64)
    cold = idx.density(*args)               # cold: seeds the cache
    warm = idx.density(*args)
    np.testing.assert_array_equal(cold, warm)
    ring = obs.tracer.ring
    traces = [t for t in ring.traces() if t.name == "lean.density"]
    cold_tr, warm_tr = traces[-2], traces[-1]
    assert cold_tr.root_span.attributes.get(
        "lean.density.cache.misses", 0) > 0
    assert warm_tr.root_span.attributes.get(
        "lean.density.cache.hits", 0) > 0


def test_windows_fast_path_audits_like_planner_path():
    """Satellite: the batched-windows fast path routes through _audit —
    same registry keys, same event shape, trace_id included."""
    audit = InMemoryAuditWriter()
    rng = np.random.default_rng(5)
    ds = TpuDataStore(audit_writer=audit, user="w")
    ds.create_schema("pts", "dtg:Date,*geom:Point")
    n = 5_000
    ds.write("pts", {
        "dtg": rng.integers(MS, MS + 7 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n))})
    windows = [([(-74.5, 40.5, -73.5, 41.5)], MS, MS + 3 * DAY),
               ([(-75.0, 40.0, -74.0, 41.0)], MS + DAY, MS + 5 * DAY)]
    c0 = registry.counter("query.pts.count").count
    t0 = registry.timer("query.pts.plan_ms").count
    s0 = registry.timer("query.pts.scan_ms").count
    hits = ds.query_windows("pts", windows)
    assert registry.counter("query.pts.count").count == c0 + 1
    # planning never ran (the fast path plans inside the index): the
    # plan_ms timer must get NO phantom-zero sample
    assert registry.timer("query.pts.plan_ms").count == t0
    assert registry.timer("query.pts.scan_ms").count == s0 + 1
    ev = audit.events[-1]
    assert ev.filter == "batched windows[2]"
    assert ev.hits == int(sum(len(h) for h in hits))
    assert ev.trace_id and obs.tracer.find(ev.trace_id) is not None
    # identical record shape as the planner path
    planner_ev = None
    ds.query("pts", "BBOX(geom,-74.5,40.5,-73.5,41.5)")
    planner_ev = audit.events[-1]
    assert set(json.loads(ev.to_json())) == set(
        json.loads(planner_ev.to_json()))


def test_slow_query_log_threshold_honored(lean_ds):
    # drain: earlier tests' traces (writes trace too, ISSUE 12) may
    # have filled the bounded log, where append no longer grows len
    obs.tracer.slow_log.clear()
    set_property("geomesa.obs.slow.ms", 1e9)
    try:
        n0 = len(lean_ds and obs.tracer.slow_log)
        lean_ds.query("evt", LEAN_Q)
        assert len(obs.tracer.slow_log) == n0
        set_property("geomesa.obs.slow.ms", 0.0001)
        lean_ds.query("evt", LEAN_Q)
        assert len(obs.tracer.slow_log) == n0 + 1
        slow = obs.tracer.slow_log.traces()[-1]
        assert slow.name == "query" and len(slow.spans) > 1
    finally:
        clear_property("geomesa.obs.slow.ms")


def test_ratio_declined_slow_query_still_logged(lean_ds):
    """A slow query the ratio sampler head-declined must still be kept
    in the slow log (records, but routes only there)."""
    obs.tracer.slow_log.clear()   # see threshold test: bounded log
    set_property("geomesa.obs.sampler", "ratio")
    set_property("geomesa.obs.sample.ratio", 0.0)
    set_property("geomesa.obs.slow.ms", 0.0001)
    try:
        n0 = len(obs.tracer.slow_log)
        r0 = len(obs.tracer.ring)
        lean_ds.query("evt", LEAN_Q)
        assert len(obs.tracer.slow_log) == n0 + 1
        assert len(obs.tracer.ring) == r0        # never exported
        slow = obs.tracer.slow_log.traces()[-1]
        assert slow.name == "query" and len(slow.spans) > 1
    finally:
        clear_property("geomesa.obs.sampler")
        clear_property("geomesa.obs.sample.ratio")
        clear_property("geomesa.obs.slow.ms")


def test_sampler_knobs_live(lean_ds):
    ring = obs.tracer.ring
    ring.clear()   # a full ring (256 traces suite-wide) caps len
    set_property("geomesa.obs.sampler", "never")
    try:
        n0 = len(ring)
        lean_ds.query("evt", LEAN_Q)
        assert len(ring) == n0
        set_property("geomesa.obs.sampler", "ratio")
        set_property("geomesa.obs.sample.ratio", 0.0)
        lean_ds.query("evt", LEAN_Q)
        assert len(ring) == n0
        set_property("geomesa.obs.sampler", "always")
        lean_ds.query("evt", LEAN_Q)
        assert len(ring) == n0 + 1
    finally:
        clear_property("geomesa.obs.sampler")
        clear_property("geomesa.obs.sample.ratio")


def test_obs_disabled_yields_noop_spans(lean_ds):
    set_property("geomesa.obs.enabled", False)
    try:
        n0 = len(obs.tracer.ring)
        with obs.span("query") as sp:
            assert not sp.recording
        lean_ds.query("evt", LEAN_Q)
        assert len(obs.tracer.ring) == n0
        assert obs.current_trace_id() == ""
    finally:
        clear_property("geomesa.obs.enabled")


def test_compaction_traced_and_timed():
    from geomesa_tpu.index.z3_lean import LeanZ3Index
    rng = np.random.default_rng(31)
    idx = LeanZ3Index(period="week", generation_slots=4096,
                      payload_on_device=False)
    for _ in range(5):
        m = 4096
        idx.append(rng.uniform(-180, 180, m), rng.uniform(-90, 90, m),
                   rng.integers(MS, MS + 14 * DAY, m))
    idx.block()
    t0 = registry.timer("lean.compaction.ms").count
    stats = idx.compact(factor=2)
    assert stats["merged_groups"] >= 1
    assert registry.timer("lean.compaction.ms").count > t0
    traces = [t for t in obs.tracer.ring.traces()
              if t.name == "lean.compaction"]
    assert traces and traces[-1].root_span.attributes[
        "merged_groups"] == stats["merged_groups"]


# -- recompile tracking ----------------------------------------------------

def test_recompile_budget_zero_across_warm_lean_queries(lean_ds):
    from geomesa_tpu.obs import recompile
    if not recompile.installed():           # listener-less jax build:
        pytest.skip("jax.monitoring listener unavailable")  # no vacuous 0
    lean_ds.query("evt", LEAN_Q)            # warm every compile bucket
    lean_ds.query("evt", LEAN_Q)
    c0 = obs.compile_count()
    for _ in range(3):
        lean_ds.query("evt", LEAN_Q)
    assert obs.compile_count() - c0 == 0, \
        "warm repeated lean queries must not retrace"


def test_recompile_listener_counts_fresh_compiles():
    import jax
    import jax.numpy as jnp
    c0 = obs.compile_count()
    f = jax.jit(lambda x: x * 3 + 1)
    f(jnp.arange(7))                        # fresh shape -> compile
    assert obs.compile_count() > c0
    c1 = obs.compile_count()
    f(jnp.arange(7))                        # warm -> no compile
    assert obs.compile_count() == c1


def test_counting_jit_fallback_counter():
    import jax.numpy as jnp
    from geomesa_tpu.metrics import JAX_COMPILE_FALLBACK
    f = obs.counting_jit(lambda x: x - 2)
    c0 = registry.counter(JAX_COMPILE_FALLBACK).count
    f(jnp.arange(5))
    assert registry.counter(JAX_COMPILE_FALLBACK).count == c0 + 1
    f(jnp.arange(5))                        # cache hit: no growth
    assert registry.counter(JAX_COMPILE_FALLBACK).count == c0 + 1
    f(jnp.arange(9))                        # new shape
    assert registry.counter(JAX_COMPILE_FALLBACK).count == c0 + 2


# -- quantile metrics ------------------------------------------------------

def test_histogram_quantiles_within_bucket_error():
    reg = MetricRegistry()
    h = reg.histogram("h")
    for v in range(1, 1001):
        h.update(float(v))
    assert abs(h.quantile(0.5) - 500) / 500 < 0.16
    assert abs(h.quantile(0.95) - 950) / 950 < 0.16
    assert abs(h.quantile(0.99) - 990) / 990 < 0.16
    snap = reg.snapshot()["h"]
    assert snap["p50"] == h.quantile(0.5)
    assert snap["min"] == 1.0 and snap["max"] == 1000.0


def test_empty_histogram_snapshot_is_finite():
    reg = MetricRegistry()
    reg.timer("t")                          # never updated
    snap = reg.snapshot()["t"]
    for v in snap.values():
        assert np.isfinite(v)
    assert snap["p50"] == 0.0 and snap["p99"] == 0.0


def test_merge_snapshots_sums_and_requantiles():
    a, b = MetricRegistry(), MetricRegistry()
    a.counter("c").inc(3)
    b.counter("c").inc(4)
    for v in range(1, 501):
        a.histogram("h").update(float(v))
    for v in range(501, 1001):
        b.histogram("h").update(float(v))
    merged = merge_snapshots([a.snapshot(buckets=True),
                              b.snapshot(buckets=True)])
    assert merged["c"] == {"count": 7}
    assert merged["h"]["count"] == 1000
    assert merged["h"]["min"] == 1.0 and merged["h"]["max"] == 1000.0
    assert abs(merged["h"]["p50"] - 500) / 500 < 0.16
    # single-process identity path returns the same shape
    lone = merge_snapshots([a.snapshot(buckets=True)])
    assert lone["h"]["count"] == 500 and "buckets" not in lone["h"]


def test_allreduce_metrics_snapshot_single_process():
    from geomesa_tpu.parallel.stats import allreduce_metrics_snapshot
    reg = MetricRegistry()
    reg.counter("x").inc(2)
    reg.timer("t").update(5.0)
    snap = allreduce_metrics_snapshot(reg)
    assert snap["x"]["count"] == 2
    assert snap["t"]["count"] == 1 and "p95" in snap["t"]


# -- reporters -------------------------------------------------------------

def test_reporters_emit_interval_deltas(tmp_path, caplog):
    reg = MetricRegistry()
    reg.counter("c").inc(3)
    path = tmp_path / "m.csv"
    rep = DelimitedFileReporter(reg, str(path))
    rep.report()
    reg.counter("c").inc(2)
    rep.report()
    rows = [ln for ln in path.read_text().splitlines() if ",c," in ln]
    assert "delta=3" in rows[0] and "count=3" in rows[0]
    assert "delta=2" in rows[1] and "count=5" in rows[1]

    import logging
    lrep = LoggingReporter(reg)
    with caplog.at_level(logging.INFO, logger="geomesa_tpu.metrics"):
        lrep.report()
        reg.counter("c").inc(1)
        lrep.report()
    msgs = [r.getMessage() for r in caplog.records if r.args
            and r.args[0] == "c"]
    assert "'delta': 5" in msgs[0] and "'delta': 1" in msgs[1]


def test_periodic_reporter_runs_and_stops(tmp_path):
    reg = MetricRegistry()
    reg.counter("c").inc(1)
    rep = DelimitedFileReporter(reg, str(tmp_path / "p.csv"))
    per = PeriodicReporter(rep, interval_s=0.02).start()
    time.sleep(0.1)
    per.stop()
    assert per._thread is None
    lines = (tmp_path / "p.csv").read_text().splitlines()
    assert len(lines) >= 2                   # ticked + final flush
    n = len(lines)
    time.sleep(0.06)                         # no further ticks after stop
    assert len((tmp_path / "p.csv").read_text().splitlines()) == n


# -- web surface -----------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{(quantile=\"[0-9.]+\"|le=\"(\+Inf|[0-9.e+-]+)\")\})? -?[0-9]"
    r"[0-9.e+-]*"
    # OpenMetrics exemplar suffix (the SLO latency histograms): the
    # trace_id joining a bucket to /traces/<id>
    r"( # \{trace_id=\"[0-9a-f]+\"\} -?[0-9][0-9.e+-]*)?$")


def test_prometheus_exposition_parses(lean_ds):
    import math

    from geomesa_tpu.web import WebApp
    registry.timer("obs.test.empty_ms")      # empty histogram in the dump
    lean_ds.query("evt", LEAN_Q)
    app = WebApp(lean_ds)
    status, headers, body = _call(app, "GET", "/metrics.prom")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    for line in body.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|summary|gauge|histogram)$",
                            line), line
        else:
            assert _PROM_LINE.match(line), line
            # sample VALUES are always finite — scrapers reject
            # inf/nan (a substring scan would false-positive on the
            # "nan" inside slo.teNANt.* metric names)
            assert math.isfinite(float(line.split()[-1])), line
    assert 'geomesa_query_evt_scan_ms{quantile="0.5"}' in body
    assert 'geomesa_query_evt_scan_ms{quantile="0.99"}' in body
    assert "geomesa_query_evt_count_total" in body
    assert "geomesa_obs_test_empty_ms_count 0" in body


def test_traces_endpoints_roundtrip(lean_ds):
    from geomesa_tpu.web import WebApp
    got = lean_ds.query_result("evt", LEAN_Q)
    audit = lean_ds._obs_audit
    tid = audit.events[-1].trace_id
    app = WebApp(lean_ds)
    status, _, body = _call(app, "GET", "/traces")
    assert status == 200
    summaries = json.loads(body)
    assert any(s["trace_id"] == tid for s in summaries)
    status, _, body = _call(app, "GET", f"/traces/{tid}")
    assert status == 200
    full = json.loads(body)
    assert full["trace_id"] == tid
    names = {s["name"] for s in full["spans"]}
    assert {"query", "query.plan", "query.decompose",
            "query.post_filter"} <= names
    root = [s for s in full["spans"] if s["parent_id"] is None][0]
    assert root["attributes"]["hits"] == len(got.positions)
    status, _, _ = _call(app, "GET", "/traces/deadbeef")
    assert status == 404
    # slow listing stays a list
    status, _, body = _call(app, "GET", "/traces?slow=1")
    assert status == 200 and isinstance(json.loads(body), list)


def test_jsonl_exporter_roundtrip(tmp_path):
    exp = obs.JsonlExporter(str(tmp_path / "traces.jsonl"))
    t = obs.Tracer(sampler=obs.AlwaysSampler(), exporters=[exp])
    with t.span("query", schema="x"):
        with t.span("query.plan"):
            pass
    exp.close()
    lines = (tmp_path / "traces.jsonl").read_text().splitlines()
    rec = json.loads(lines[-1])
    assert rec["name"] == "query"
    assert [s["name"] for s in rec["spans"]] == ["query.plan", "query"]
