"""Aggregation push-down on the lean profile (round-4 VERDICT #2):
density grids and Count() accumulated NEXT TO THE KEYS — full-tier
generations mask exactly on device payload, keys-tier generations
decode cell-granular coordinates from the z key, host-tier runs
contribute numpy partials, merged as monoid sums (psum over the mesh).
Only grids cross the wire; a whole-extent heatmap never materializes a
hit.

Reference parity: DensityScan.scala:31-59, StatsScan.scala,
AggregatingScan.scala:80-102.
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.index.z3_lean import LeanZ3Index
from geomesa_tpu.process.density import density_process
from geomesa_tpu.process.stats_process import stats_process

MS = 1514764800000
DAY = 86_400_000
WORLD = (-180.0, -90.0, 180.0, 90.0)


@pytest.fixture(scope="module")
def pts():
    rng = np.random.default_rng(3)
    n = 60_000
    return (rng.uniform(-75, -73, n), rng.uniform(40, 42, n),
            rng.integers(MS, MS + 14 * DAY, n))


def _brute_grid(x, y, m, env, w, h):
    g = np.zeros((h, w))
    gx = np.clip(((x[m] - env[0]) / (env[2] - env[0]) * w).astype(int),
                 0, w - 1)
    gy = np.clip(((y[m] - env[1]) / (env[3] - env[1]) * h).astype(int),
                 0, h - 1)
    np.add.at(g, (gy, gx), 1.0)
    return g


@pytest.mark.parametrize("payload,budget", [
    (True, None),                       # all full
    (False, None),                      # all keys
    (True, 3 * (1 << 14) * 16),         # mixed full/keys/host
])
def test_index_density_whole_extent_exact_all_tiers(pts, payload,
                                                    budget):
    x, y, t = pts
    idx = LeanZ3Index(period="week", generation_slots=1 << 14,
                      payload_on_device=payload,
                      hbm_budget_bytes=budget)
    idx.append(x, y, t)
    grid = idx.density([WORLD], None, None, WORLD, 256, 128)
    np.testing.assert_array_equal(
        grid, _brute_grid(x, y, np.ones(len(x), bool), WORLD, 256, 128))
    assert idx.range_count([WORLD], None, None) == len(x)


def test_index_density_full_tier_boxed_value_exact(pts):
    """Full-tier masks are value-exact on raw payload: boxed+timed
    counts and MASS are exact for any envelope; per-cell equality holds
    on z-cell-ALIGNED grids (binning goes through the z-cell midpoint
    for cross-platform determinism)."""
    x, y, t = pts
    idx = LeanZ3Index(period="week", generation_slots=1 << 14,
                      payload_on_device=True)
    idx.append(x, y, t)
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    m = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
         & (t >= lo) & (t <= hi))
    # aligned (world, pow2) grid: per-cell EXACT
    np.testing.assert_array_equal(
        idx.density([box], lo, hi, WORLD, 256, 128),
        _brute_grid(x, y, m, WORLD, 256, 128))
    # misaligned envelope: the exact mask keeps the MASS exact; cell
    # assignment quantizes at z-cell straddles (<= 1.7e-4 deg)
    env = (-75.0, 40.0, -73.0, 42.0)
    g = idx.density([box], lo, hi, env, 64, 64)
    assert g.sum() == int(m.sum())
    assert np.abs(g - _brute_grid(x, y, m, env, 64, 64)).max() <= 8
    assert idx.range_count([box], lo, hi) == int(m.sum())


def test_index_density_keys_tier_cell_inclusive(pts):
    """Cell-granular masks over-cover only within one z cell of the
    box/time edges; the mass stays within boundary tolerance."""
    x, y, t = pts
    idx = LeanZ3Index(period="week", generation_slots=1 << 14,
                      payload_on_device=False)
    idx.append(x, y, t)
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    m = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
         & (t >= lo) & (t <= hi))
    g = idx.density([box], lo, hi, (-75.0, 40.0, -73.0, 42.0), 64, 64)
    got, want = g.sum(), int(m.sum())
    assert want <= got <= want + 80   # inclusive superset, edge-bounded


def test_store_density_process_pushdown_no_materialization(pts):
    x, y, t = pts
    ds = TpuDataStore()
    ds.create_schema("evt", "dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    ds.write("evt", {"dtg": t, "geom": (x, y)})
    st = ds._store("evt")
    idx = st.index("z3")
    before = idx.dispatch_count
    grid = density_process(ds, "evt", "INCLUDE", WORLD, 256, 128)
    # the whole-extent sweep costs ONE dispatch per generation bucket
    # (no probe, no expand) and no hits cross the wire
    assert idx.dispatch_count - before == 1
    np.testing.assert_array_equal(
        grid, _brute_grid(x, y, np.ones(len(x), bool), WORLD, 256, 128))


def test_store_count_pushdown_and_fallbacks(pts):
    x, y, t = pts
    ds = TpuDataStore()
    ds.create_schema("evt", "dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    ds.write("evt", {"dtg": t, "geom": (x, y)})
    n = len(x)
    # whole-extent Count() — pushed down
    assert stats_process(ds, "evt", "INCLUDE", "Count()").count == n
    # boxed Count on all-full tiers — value-exact via payload masks
    box_ecql = "BBOX(geom,-74.5,40.5,-73.5,41.5)"
    m = (x >= -74.5) & (x <= -73.5) & (y >= 40.5) & (y <= 41.5)
    assert stats_process(ds, "evt", box_ecql,
                         "Count()").count == int(m.sum())
    # a tombstone forces the exact materializing fallback
    ds.delete("evt", ["5"])
    assert stats_process(ds, "evt", "INCLUDE", "Count()").count == n - 1
    grid = density_process(ds, "evt", "INCLUDE", WORLD, 64, 64)
    assert grid.sum() == n - 1


def test_store_count_keys_tier_boxed_falls_back(pts):
    """A boxed count over non-full tiers is only cell-inclusive — the
    push-down must decline and the exact query path answer."""
    x, y, t = pts
    ds = TpuDataStore()
    ds.create_schema("evt", "dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    ds.write("evt", {"dtg": t, "geom": (x, y)})
    st = ds._store("evt")
    idx = st.index("z3")
    for gen in idx.generations:
        gen.drop_payload()
    idx._sentinels.pop("full", None)
    box_ecql = "BBOX(geom,-74.5,40.5,-73.5,41.5)"
    m = (x >= -74.5) & (x <= -73.5) & (y >= 40.5) & (y <= 41.5)
    assert stats_process(ds, "evt", box_ecql,
                         "Count()").count == int(m.sum())


def test_sharded_lean_density_and_count(pts):
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.lean import ShardedLeanZ3Index

    x, y, t = pts
    n = len(x)
    want = _brute_grid(x, y, np.ones(n, bool), WORLD, 256, 128)
    dsm = TpuDataStore(mesh=device_mesh())
    dsm.create_schema("evt", "dtg:Date,*geom:Point;"
                             "geomesa.index.profile=lean")
    dsm.write("evt", {"dtg": t, "geom": (x, y)})
    np.testing.assert_array_equal(
        density_process(dsm, "evt", "INCLUDE", WORLD, 256, 128), want)
    assert stats_process(dsm, "evt", "INCLUDE", "Count()").count == n
    # budget-spilled sharded index: host partials merge into the grid
    slots = 1 << 10
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=slots,
                             hbm_budget_bytes=slots * 20 * 3)
    for lo in range(0, n, 12_000):
        idx.append(x[lo:lo + 12_000], y[lo:lo + 12_000],
                   t[lo:lo + 12_000])
    assert idx.tier_counts()["host"] >= 1
    np.testing.assert_array_equal(
        idx.density([WORLD], None, None, WORLD, 256, 128), want)
    assert idx.range_count([WORLD], None, None) == n
