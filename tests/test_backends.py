"""Back-end analogs: filesystem store (partition schemes + pruning),
streaming store (broker/cache/events), lambda merged store, merged views,
geohash + bucket index utils."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.filters.ecql import parse_ecql
from geomesa_tpu.fs import (
    AttributeScheme, CompositeScheme, DateTimeScheme, FileSystemDataStore,
    Z2Scheme, scheme_from_config,
)
from geomesa_tpu.lambda_store import LambdaDataStore
from geomesa_tpu.stream import GeoMessage, InProcessBroker, StreamDataStore
from geomesa_tpu.utils import (
    BucketIndex, geohash_decode, geohash_encode, geohash_neighbors,
)
from geomesa_tpu.views import MergedDataStoreView

MS_2018 = 1514764800000
DAY = 86_400_000
SPEC = "name:String,dtg:Date,*geom:Point"


def _mk_cols(n, rng, t0=MS_2018, days=10, xr=(-75, -74), yr=(40, 41)):
    return {
        "name": np.array([f"n{i % 7}" for i in range(n)], dtype=object),
        "dtg": rng.integers(t0, t0 + days * DAY, n),
        "geom": (rng.uniform(*xr, n), rng.uniform(*yr, n)),
    }


# -- geohash ----------------------------------------------------------------

def test_geohash_known_values():
    # canonical example: (-5.6, 42.6) → "ezs42" at precision 5
    assert geohash_encode([-5.6], [42.6], 5)[0] == "ezs42"
    lon, lat, elon, elat = geohash_decode(["ezs42"])
    assert abs(lon[0] - -5.6) < 0.05 and abs(lat[0] - 42.6) < 0.05


def test_geohash_roundtrip_and_neighbors():
    rng = np.random.default_rng(0)
    lon = rng.uniform(-180, 180, 200)
    lat = rng.uniform(-90, 90, 200)
    h = geohash_encode(lon, lat, 9)
    dlon, dlat, elon, elat = geohash_decode(h)
    assert np.all(np.abs(dlon - lon) <= elon * 2.01)
    assert np.all(np.abs(dlat - lat) <= elat * 2.01)
    nbrs = geohash_neighbors("ezs42")
    assert len(nbrs) == 8 and len(set(nbrs)) == 8
    assert all(len(n) == 5 for n in nbrs)


# -- bucket index -----------------------------------------------------------

def test_bucket_index_insert_query_remove():
    idx = BucketIndex()
    rng = np.random.default_rng(1)
    pts = {f"f{i}": (rng.uniform(-180, 180), rng.uniform(-90, 90))
           for i in range(1000)}
    for fid, (x, y) in pts.items():
        idx.insert(fid, x, y)
    assert len(idx) == 1000
    got = set(idx.query(-50, -30, 50, 30))
    want = {f for f, (x, y) in pts.items()
            if -50 <= x <= 50 and -30 <= y <= 30}
    assert got == want
    # update moves the feature
    idx.insert("f0", 0.0, 0.0)
    assert "f0" in idx.query(-1, -1, 1, 1)
    assert idx.remove("f0") and not idx.remove("f0")
    assert len(idx) == 999


# -- partition schemes ------------------------------------------------------

def test_datetime_scheme_partitions_and_pruning():
    ds = TpuDataStore()
    sft = ds.create_schema("t", SPEC)
    rng = np.random.default_rng(2)
    batch = FeatureBatch.from_dict(sft, _mk_cols(100, rng))
    sch = DateTimeScheme("daily")
    parts = sch.partitions_for_batch(sft, batch)
    assert all(p.startswith("2018/01/") for p in parts)
    pruned = sch.partitions_for_filter(
        sft, parse_ecql(
            "dtg DURING 2018-01-02T00:00:00Z/2018-01-03T00:00:00Z"))
    assert "2018/01/02" in pruned and "2018/01/03" in pruned
    assert "2018/01/09" not in pruned
    # unbounded → no pruning
    assert sch.partitions_for_filter(sft, parse_ecql("INCLUDE")) is None


def test_z2_scheme_covers_queries():
    ds = TpuDataStore()
    sft = ds.create_schema("t", SPEC)
    rng = np.random.default_rng(3)
    batch = FeatureBatch.from_dict(sft, _mk_cols(200, rng))
    sch = Z2Scheme(bits=4)
    parts = sch.partitions_for_batch(sft, batch)
    pruned = sch.partitions_for_filter(
        sft, parse_ecql("BBOX(geom,-75,40,-74,41)"))
    assert pruned is not None
    assert set(parts) <= set(pruned)  # every feature partition is covered


def test_attribute_and_composite_schemes():
    ds = TpuDataStore()
    sft = ds.create_schema("t", SPEC)
    rng = np.random.default_rng(4)
    batch = FeatureBatch.from_dict(sft, _mk_cols(50, rng))
    sch = AttributeScheme("name")
    parts = sch.partitions_for_batch(sft, batch)
    assert parts[0] == f"name={batch.columns['name'][0]}"
    assert sch.partitions_for_filter(sft, parse_ecql("name = 'n1'")) == [
        "name=n1"]
    assert sorted(sch.partitions_for_filter(
        sft, parse_ecql("name IN ('n1','n2')"))) == ["name=n1", "name=n2"]

    comp = CompositeScheme([DateTimeScheme("daily"), AttributeScheme("name")])
    cparts = comp.partitions_for_batch(sft, batch)
    assert cparts[0].count("/") == 3  # yyyy/mm/dd/name=v
    pruned = comp.partitions_for_filter(sft, parse_ecql("name = 'n1'"))
    assert pruned and all(p.endswith("name=n1") and p.startswith("*")
                          for p in pruned)
    # config round trip
    again = scheme_from_config(comp.to_config())
    assert isinstance(again, CompositeScheme)


# -- filesystem datastore ---------------------------------------------------

def test_fs_datastore_write_query_pruning(tmp_path):
    fs = FileSystemDataStore(str(tmp_path))
    fs.create_schema("ev", SPEC, {"scheme": "datetime",
                                  "datetime-step": "daily"})
    rng = np.random.default_rng(5)
    cols = _mk_cols(500, rng)
    fs.write("ev", cols)
    assert fs.count("ev") == 500
    assert len(fs.partitions("ev")) >= 9

    q = ("BBOX(geom,-74.8,40.2,-74.2,40.8) AND "
         "dtg DURING 2018-01-02T00:00:00Z/2018-01-05T00:00:00Z")
    out = fs.query("ev", q)
    x, y = cols["geom"]
    t = cols["dtg"]
    want = np.count_nonzero(
        (x >= -74.8) & (x <= -74.2) & (y >= 40.2) & (y <= 40.8)
        & (t >= MS_2018 + DAY) & (t <= MS_2018 + 4 * DAY))
    assert len(out) == want

    # rediscovery from disk
    fs2 = FileSystemDataStore(str(tmp_path))
    assert fs2.type_names == ["ev"]
    assert len(fs2.query("ev", q)) == want


def test_fs_compaction(tmp_path):
    fs = FileSystemDataStore(str(tmp_path))
    fs.create_schema("ev", SPEC)
    rng = np.random.default_rng(6)
    for _ in range(4):
        fs.write("ev", _mk_cols(50, rng, days=1))
    part = fs.partitions("ev")[0]
    meta = fs._storage("ev")._load_meta()
    assert len(meta["partitions"][part]) == 4
    fs.compact("ev")
    meta = fs._storage("ev")._load_meta()
    assert all(len(files) == 1 for files in meta["partitions"].values())
    assert fs.count("ev") == 200


def test_fs_datastore_orc_encoding(tmp_path):
    """ORC storage format round-trip incl. pruning, compaction and
    rediscovery (geomesa-fs orc analog)."""
    fs = FileSystemDataStore(str(tmp_path))
    fs.create_schema("ev", SPEC, {"scheme": "datetime",
                                  "datetime-step": "daily"},
                     encoding="orc")
    rng = np.random.default_rng(9)
    cols = _mk_cols(400, rng)
    fs.write("ev", cols)
    for _ in range(2):
        fs.write("ev", _mk_cols(50, rng, days=1))
    assert fs.count("ev") == 500
    import glob
    import os
    root = os.path.join(str(tmp_path), "ev")
    assert glob.glob(os.path.join(root, "**", "*.orc"), recursive=True)
    assert not glob.glob(os.path.join(root, "**", "*.parquet"),
                         recursive=True)

    q = ("BBOX(geom,-74.8,40.2,-74.2,40.8) AND "
         "dtg DURING 2018-01-02T00:00:00Z/2018-01-05T00:00:00Z")
    x, y = cols["geom"]
    t = cols["dtg"]
    want = np.count_nonzero(
        (x >= -74.8) & (x <= -74.2) & (y >= 40.2) & (y <= 40.8)
        & (t >= MS_2018 + DAY) & (t <= MS_2018 + 4 * DAY))
    # extra writes were on day 1 only, strictly before the query window,
    # so they cannot add hits — the oracle count is exact
    out = fs.query("ev", q)
    fs.compact("ev")
    out2 = fs.query("ev", q)
    assert len(out) == len(out2)
    assert len(out) == want

    fs2 = FileSystemDataStore(str(tmp_path))
    assert fs2._storage("ev").encoding == "orc"
    assert len(fs2.query("ev", q)) == len(out)


# -- streaming --------------------------------------------------------------

def test_broker_ordering_and_offsets():
    b = InProcessBroker(num_partitions=2)
    for i in range(10):
        b.send("t", "key", f"v{i}".encode())   # same key → same partition
    recs = b.poll("g", "t")
    assert [r[1] for r in recs] == [f"v{i}".encode() for i in range(10)]
    b.commit("g", "t", {recs[-1][0][0]: recs[-1][0][1] + 1})
    assert b.poll("g", "t") == []              # committed
    assert b.poll("g2", "t") != []             # other group unaffected


def test_stream_store_end_to_end():
    st = StreamDataStore()
    st.create_schema("live", SPEC)
    events = []
    st.add_listener("live", events.append)

    st.write("live", "a", {"name": "x", "dtg": MS_2018,
                           "geom": (-74.5, 40.5)})
    st.write("live", "b", {"name": "y", "dtg": MS_2018,
                           "geom": (-60.0, 10.0)})
    assert len(st.query("live")) == 0          # not consumed yet
    assert st.consume("live") == 2
    assert len(events) == 2 and events[0].kind == "change"

    out = st.query("live", "BBOX(geom,-75,40,-74,41)")
    assert list(out.ids) == ["a"]
    # update in place
    st.write("live", "a", {"name": "x2", "dtg": MS_2018,
                           "geom": (-74.4, 40.4)})
    st.consume("live")
    assert len(st.cache("live")) == 2
    assert st.query("live", "name = 'x2'").ids[0] == "a"
    # delete + clear
    st.delete("live", "a")
    st.consume("live")
    assert len(st.cache("live")) == 1
    st.clear("live")
    st.consume("live")
    assert len(st.cache("live")) == 0


def test_geomessage_codec():
    m = GeoMessage.change("f1", {"a": 1, "geom": (1.0, 2.0)})
    m2 = GeoMessage.from_bytes(m.to_bytes())
    assert m2.kind == "change" and m2.feature_id == "f1"
    with pytest.raises(ValueError):
        GeoMessage("bogus")
    with pytest.raises(ValueError):
        GeoMessage("change")


# -- lambda store -----------------------------------------------------------

def test_lambda_merged_and_persistence():
    clock = [1000.0]
    persistent = TpuDataStore()
    lam = LambdaDataStore(persistent, expiry_ms=5000,
                          clock=lambda: clock[0])
    lam.create_schema("t", SPEC)
    lam.write("t", "a", {"name": "x", "dtg": MS_2018, "geom": (-74.5, 40.5)})
    clock[0] += 1.0
    lam.write("t", "b", {"name": "y", "dtg": MS_2018, "geom": (-74.6, 40.6)})

    out = lam.query("t", "BBOX(geom,-75,40,-74,41)")
    assert sorted(str(i) for i in out.ids) == ["a", "b"]
    assert persistent.get_count("t") == 0      # still transient

    clock[0] += 4.5                             # expire "a" only (5.5s old)
    n = lam.persist("t")
    assert n == 1
    assert persistent.get_count("t") == 1
    out = lam.query("t", "BBOX(geom,-75,40,-74,41)")
    assert sorted(str(i) for i in out.ids) == ["a", "b"]  # still merged

    # transient wins on id collision: update "a" transiently
    lam.write("t", "a", {"name": "x-new", "dtg": MS_2018,
                         "geom": (-74.5, 40.5)})
    out = lam.query("t", "BBOX(geom,-75,40,-74,41)")
    names = {str(i): n for i, n in zip(out.ids, out.columns["name"])}
    assert names["a"] == "x-new" and len(out) == 2


def test_lambda_flush_into_lean_store():
    """The persistence flusher composes with the LEAN persistent layer
    (round-4 VERDICT #10): flushes append with store-minted row ids,
    re-persisted fids tombstone their old row (LSM upsert), and the
    merged read shadows by the persisted-row mapping."""
    clock = [1000.0]
    persistent = TpuDataStore()
    persistent.create_schema("t", SPEC + ";geomesa.index.profile=lean")
    lam = LambdaDataStore(persistent, expiry_ms=1000,
                          clock=lambda: clock[0])
    lam.stream.create_schema("t", SPEC)
    lam.write("t", "a", {"name": "v1", "dtg": MS_2018,
                         "geom": (-74.5, 40.5)})
    lam.write("t", "b", {"name": "w1", "dtg": MS_2018,
                         "geom": (-74.6, 40.6)})
    clock[0] += 2.0
    assert lam.persist("t") == 2
    assert persistent.get_count("t") == 2       # lean rows, implicit ids
    out = lam.query("t", "BBOX(geom,-75,40,-74,41)")
    assert len(out) == 2
    # upsert: re-write fid 'a' transiently, flush again — the old lean
    # row tombstones, count stays 2, value updates
    lam.write("t", "a", {"name": "v2", "dtg": MS_2018,
                         "geom": (-74.5, 40.5)})
    # transient wins in the merged read before the flush
    out = lam.query("t", "BBOX(geom,-75,40,-74,41)")
    names = sorted(str(n) for n in out.columns["name"])
    assert len(out) == 2 and names == ["v2", "w1"]
    clock[0] += 2.0
    assert lam.persist("t") == 1
    out = lam.query("t", "BBOX(geom,-75,40,-74,41)")
    names = sorted(str(n) for n in out.columns["name"])
    assert len(out) == 2 and names == ["v2", "w1"]
    assert persistent.get_count("t") == 2       # tombstoned, not dup
    # a stream fid that LOOKS like a lean row id shadows nothing
    lam.write("t", "0", {"name": "decoy", "dtg": MS_2018,
                         "geom": (-74.7, 40.7)})
    out = lam.query("t", "BBOX(geom,-75,40,-74,41)")
    assert len(out) == 3


# -- merged views -----------------------------------------------------------

def test_merged_view_union_and_scope():
    rng = np.random.default_rng(7)
    a = TpuDataStore()
    a.create_schema("t", SPEC)
    a.write("t", _mk_cols(40, rng), ids=np.array(
        [f"a{i}" for i in range(40)], dtype=object))
    b = TpuDataStore()
    b.create_schema("t", SPEC)
    b.write("t", _mk_cols(60, rng), ids=np.array(
        [f"b{i}" for i in range(60)], dtype=object))

    view = MergedDataStoreView([a, b])
    out = view.query("t", "BBOX(geom,-76,39,-73,42)")
    assert len(out) == 100
    assert view.count("t", "name = 'n1'") == (
        a.get_count("t", "name = 'n1'") + b.get_count("t", "name = 'n1'"))

    scoped = MergedDataStoreView([a, b],
                                 [parse_ecql("name = 'n1'"), None])
    out = scoped.query("t", "BBOX(geom,-76,39,-73,42)")
    assert len(out) == a.get_count("t", "name = 'n1'") + 60


# -- review regressions ------------------------------------------------------

def test_lambda_repersist_upserts_no_duplicates():
    clock = [1000.0]
    persistent = TpuDataStore()
    lam = LambdaDataStore(persistent, expiry_ms=1000, clock=lambda: clock[0])
    lam.create_schema("t", SPEC)
    lam.write("t", "a", {"name": "v1", "dtg": MS_2018, "geom": (-74.5, 40.5)})
    clock[0] += 2.0
    assert lam.persist("t") == 1
    lam.write("t", "a", {"name": "v2", "dtg": MS_2018, "geom": (-74.5, 40.5)})
    clock[0] += 2.0
    assert lam.persist("t") == 1
    out = lam.query("t", "BBOX(geom,-75,40,-74,41)")
    assert len(out) == 1 and out.columns["name"][0] == "v2"


def test_fs_empty_write_and_empty_result(tmp_path):
    fs = FileSystemDataStore(str(tmp_path))
    fs.create_schema("ev", SPEC)
    fs.write("ev", {"name": np.empty(0, dtype=object),
                    "dtg": np.empty(0, dtype=np.int64),
                    "geom": (np.empty(0), np.empty(0))})
    out = fs.query("ev", "name = 'nothing'")
    assert len(out) == 0
    out.geom_xy()                      # typed empty batch works
    assert out.columns["dtg"].dtype == np.int64
    rng = np.random.default_rng(1)
    fs.write("ev", _mk_cols(10, rng))
    assert len(out.concat(fs.query("ev"))) == 10


def test_datastore_delete_by_id():
    ds = TpuDataStore()
    ds.create_schema("t", SPEC)
    rng = np.random.default_rng(9)
    ds.write("t", _mk_cols(30, rng),
             ids=np.array([f"f{i}" for i in range(30)], dtype=object))
    assert ds.delete("t", ["f1", "f2", "nope"]) == 2
    assert ds.get_count("t") == 28
    out = ds.query("t", "BBOX(geom,-76,39,-73,42)")
    assert "f1" not in set(out.ids) and len(out) == 28


def test_geohash_neighbors_antimeridian():
    from geomesa_tpu.utils import geohash_encode, geohash_neighbors
    h = str(geohash_encode([179.99], [0.0], 5)[0])
    nbrs = geohash_neighbors(h)
    assert len(nbrs) == 8
    from geomesa_tpu.utils import geohash_decode
    lons = geohash_decode(nbrs)[0]
    assert (lons < -179).any()          # wrapped across the antimeridian


def test_polling_stream_source(tmp_path):
    """Polling source tails growing files through a converter into a sink
    (geomesa-stream analog)."""
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.io.converters import converter_from_config
    from geomesa_tpu.stream import PollingStreamSource

    sft = parse_spec("pol", "v:Int,*geom:Point")
    conv = converter_from_config(sft, {
        "type": "csv",
        "fields": [
            {"name": "v", "transform": "toInt($0)"},
            {"name": "geom", "transform": "point($1,$2)"},
        ]})
    got = []
    src = PollingStreamSource(str(tmp_path / "*.log"), conv, got.append)
    f = tmp_path / "a.log"
    f.write_text("1,0.0,0.0\n2,1.0,1.0\n")
    assert src.poll_once() == 2
    # partial line is held back until completed
    with open(f, "a") as fh:
        fh.write("3,2.0")
    assert src.poll_once() == 0
    with open(f, "a") as fh:
        fh.write(",2.0\n")
    assert src.poll_once() == 1
    assert sum(len(b) for b in got) == 3
    assert src.poll_once() == 0


def test_polling_retries_after_sink_failure(tmp_path):
    """A failing sink must not advance the offset (no silent data loss)."""
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.io.converters import converter_from_config
    from geomesa_tpu.stream import PollingStreamSource

    sft = parse_spec("rf", "v:Int,*geom:Point")
    conv = converter_from_config(sft, {
        "type": "csv",
        "fields": [{"name": "v", "transform": "toInt($0)"},
                   {"name": "geom", "transform": "point($1,$2)"}]})
    calls = {"n": 0}
    got = []

    def flaky(batch):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("sink down")
        got.append(batch)

    src = PollingStreamSource(str(tmp_path / "*.log"), conv, flaky)
    (tmp_path / "a.log").write_text("1,0,0\n2,0,0\n")
    import pytest as _pytest
    with _pytest.raises(RuntimeError):
        src.poll_once()
    assert src.poll_once() == 2  # retried, nothing lost
    assert sum(len(b) for b in got) == 2


def test_schema_registry_avro_messages():
    """Confluent-variant streaming: Avro-framed change messages resolved
    through the schema registry (magic byte + schema id + avro binary)."""
    import struct
    from geomesa_tpu.stream import (
        AvroMessageCodec, SchemaRegistry, StreamDataStore,
    )

    reg = SchemaRegistry()
    store = StreamDataStore(registry=reg)
    store.create_schema("ships", "mmsi:String,speed:Double,dtg:Date,"
                                 "*geom:Point")
    store.write("ships", "s1", {"mmsi": "123", "speed": 12.5,
                                "dtg": 1514764800000, "geom": (5.0, 55.0)})
    # wire format really is Confluent-framed avro
    codec = AvroMessageCodec(reg)
    raw = codec.encode("ships", "s2", {"mmsi": "456", "speed": 2.0,
                                       "dtg": 0, "geom": (1.0, 2.0)})
    assert raw[0] == 0x00
    (sid,) = struct.unpack_from(">I", raw, 1)
    assert reg.get(sid).name == "ships"
    sft, fid, attrs = codec.decode(raw)
    assert fid == "s2" and attrs["mmsi"] == "456"
    assert abs(attrs["speed"] - 2.0) < 1e-12

    store.consume("ships")
    got = store.query("ships", "speed > 10")
    assert len(got) == 1 and got.column("mmsi")[0] == "123"
    # registry idempotency + versioning
    assert reg.register("ships", store.get_schema("ships")) == sid
    v2 = reg.register("ships", "mmsi:String,speed:Double,heading:Int,"
                               "dtg:Date,*geom:Point")
    assert v2 != sid and reg.latest("ships")[0] == v2


def test_stream_poison_message_skipped():
    """An undecodable message must not wedge the consumer group."""
    from geomesa_tpu.stream import SchemaRegistry, StreamDataStore

    reg = SchemaRegistry()
    s = StreamDataStore(registry=reg)
    s.create_schema("p", "v:Int,*geom:Point")
    s.write("p", "a", {"v": 1, "geom": (0.0, 0.0)})
    # poison: confluent-framed with an unknown schema id
    s.broker.send("p", "bad", b"\x00\xff\xff\xff\xff...garbage")
    s.write("p", "b", {"v": 2, "geom": [1.0, 1.0]})  # list coords work too
    assert s.consume("p") == 2      # both good records applied
    assert s.consume("p") == 0      # offsets advanced past the poison
    assert len(s.query("p")) == 2


def test_stream_listener_error_redelivers():
    """Apply/listener failures are NOT poison: the offset stays uncommitted
    and the message is redelivered (at-least-once)."""
    from geomesa_tpu.stream import StreamDataStore

    s = StreamDataStore()
    s.create_schema("l", "v:Int,*geom:Point")
    calls = {"n": 0}

    def flaky(msg):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("listener down")

    s.add_listener("l", flaky)
    s.write("l", "a", {"v": 1, "geom": (0.0, 0.0)})
    import pytest as _pytest
    with _pytest.raises(RuntimeError):
        s.consume("l")
    assert s.consume("l") == 1     # redelivered and applied
    assert calls["n"] == 2


def test_stream_apply_failure_dead_letters_after_retries():
    """A decodable message that deterministically fails to apply is
    retried MAX_APPLY_ATTEMPTS times, then dead-lettered."""
    from geomesa_tpu.stream import StreamDataStore

    s = StreamDataStore()
    s.create_schema("dl", "v:Int,*geom:Point")
    s.add_listener("dl", lambda msg: (_ for _ in ()).throw(
        RuntimeError("always fails")))
    s.write("dl", "a", {"v": 1, "geom": (0.0, 0.0)})
    import pytest as _pytest
    for _ in range(s.MAX_APPLY_ATTEMPTS - 1):
        with _pytest.raises(RuntimeError):
            s.consume("dl")
    assert s.consume("dl") == 0       # dead-lettered, offset advanced
    assert s.consume("dl") == 0       # gone for good


def test_z3_feature_ids_locality():
    """Z3-prefixed UUIDs: nearby features in space+time sort near each
    other (Z3FeatureIdGenerator analog); uuids stay v4-shaped unique."""
    import numpy as np
    from geomesa_tpu.utils.feature_id import random_feature_id, z3_feature_ids

    MS = 1514764800000
    rng = np.random.default_rng(0)
    # two tight clusters far apart, same week
    n = 200
    x = np.concatenate([rng.uniform(-75, -74.9, n), rng.uniform(100, 100.1, n)])
    y = np.concatenate([rng.uniform(40, 40.1, n), rng.uniform(-30, -29.9, n)])
    t = np.full(2 * n, MS + 1000)
    ids = z3_feature_ids(x, y, t)
    assert len(set(ids)) == 2 * n
    for u in ids[:5]:
        assert len(u) == 36 and u[14] == "4"  # uuid4 version nibble
    order = np.argsort(ids)
    # sorting by id must keep each cluster contiguous
    cluster = (order >= n).astype(int)
    assert (np.diff(cluster) != 0).sum() == 1
    assert len(random_feature_id()) == 36


def test_z3_feature_ids_exact_key_order():
    """Id string sort order equals (bin, z-prefix) key order exactly —
    the fixed UUID version nibble must not perturb ordering."""
    import numpy as np
    from geomesa_tpu.curve import TimePeriod, to_binned_time, z3_sfc
    from geomesa_tpu.utils.feature_id import z3_feature_ids

    MS = 1514764800000
    rng = np.random.default_rng(1)
    n = 2000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + 14 * 86_400_000, n)
    ids = z3_feature_ids(x, y, t)
    sfc = z3_sfc(TimePeriod.WEEK)
    bins, offs = to_binned_time(t, TimePeriod.WEEK)
    z = np.asarray(sfc.index(x, y, offs.astype(np.float64), xp=np),
                   dtype=np.uint64)
    zkey = (bins.astype(np.uint64) << np.uint64(44)) | (z >> np.uint64(19))
    np.testing.assert_array_equal(zkey[np.argsort(ids, kind="stable")],
                                  np.sort(zkey))


def test_fsds_to_device_store(tmp_path):
    """FSDS partitions lift into a mesh-backed TpuDataStore for device
    queries (the FSDS-through-compute-engine pattern)."""
    import numpy as np
    from geomesa_tpu.fs import FileSystemDataStore, to_device_store
    from geomesa_tpu.parallel import device_mesh

    MS = 1514764800000
    rng = np.random.default_rng(5)
    fs = FileSystemDataStore(str(tmp_path / "fsroot"))
    fs.create_schema("evt", "name:String,dtg:Date,*geom:Point")
    n = 3_000
    for k in range(2):  # two writes → multiple partition files
        fs.write("evt", {
            "name": rng.choice(["a", "b"], n).astype(object),
            "dtg": rng.integers(MS, MS + 10 * 86_400_000, n),
            "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
        })
    ds = to_device_store(fs, "evt", mesh=device_mesh())
    assert ds.get_count("evt") == 2 * n
    ecql = ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
            "2018-01-02T00:00:00Z/2018-01-08T00:00:00Z")
    got = ds.query_result("evt", ecql)
    assert got.strategy.index == "z3"
    # oracle over the FSDS's own (host) query path
    want = fs.query("evt", ecql)
    assert len(got.positions) == len(want)
