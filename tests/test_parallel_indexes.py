"""Sharded Z2/XZ2/XZ3/attribute indexes on the 8-device CPU mesh vs the
single-chip indexes and brute-force oracles (VERDICT round-1 item 2:
sharded execution for every index, not just Z3)."""

import numpy as np
import pytest

from geomesa_tpu.geometry.types import LineString, Point, Polygon
from geomesa_tpu.parallel import (
    ShardedAttributeIndex, ShardedXZ2Index, ShardedXZ3Index, ShardedZ2Index,
    device_mesh,
)

MS = 1514764800000
DAY = 86_400_000


@pytest.fixture(scope="module")
def mesh():
    return device_mesh()


@pytest.fixture(scope="module")
def points():
    rng = np.random.default_rng(11)
    n = 50_007  # not divisible by 8
    x = rng.uniform(-75.0, -73.0, n)
    y = rng.uniform(40.0, 42.0, n)
    return x, y


# -- Z2 ------------------------------------------------------------------
def test_sharded_z2_query_exact(mesh, points):
    x, y = points
    idx = ShardedZ2Index.build(x, y, mesh=mesh)
    assert idx.total() == len(x)
    boxes = [(-74.5, 40.5, -74.0, 41.0), (-73.8, 41.2, -73.2, 41.9)]
    hits = idx.query(boxes)
    brute = np.flatnonzero(np.any(
        [(x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
         for b in boxes], axis=0))
    np.testing.assert_array_equal(hits, brute)
    # overflow-retry path
    np.testing.assert_array_equal(idx.query(boxes, capacity=8), brute)


def test_sharded_z2_query_many(mesh, points):
    x, y = points
    idx = ShardedZ2Index.build(x, y, mesh=mesh)
    sets = [
        [(-74.5, 40.5, -74.0, 41.0)],
        [(-74.9, 40.1, -74.6, 40.4), (-73.5, 41.5, -73.1, 41.9)],
        [(-74.2, 40.8, -74.1, 40.9)],
    ]
    batched = idx.query_many(sets)
    for got, boxes in zip(batched, sets):
        brute = np.flatnonzero(np.any(
            [(x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
             for b in boxes], axis=0))
        np.testing.assert_array_equal(got, brute)


def test_sharded_z2_append(mesh, points):
    x, y = points
    n0 = 30_001
    idx = ShardedZ2Index.build(x[:n0], y[:n0], mesh=mesh)
    idx.append(x[n0:], y[n0:])
    assert idx.total() == len(x)
    box = (-74.5, 40.5, -74.0, 41.0)
    brute = np.flatnonzero(
        (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3]))
    np.testing.assert_array_equal(idx.query([box]), brute)


# -- XZ2 / XZ3 -----------------------------------------------------------
def _rand_geom(rng):
    kind = rng.integers(0, 3)
    cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
    if kind == 0:
        return Point(cx, cy)
    if kind == 1:
        return LineString(np.column_stack(
            [cx + rng.uniform(-2, 2, 4), cy + rng.uniform(-2, 2, 4)]))
    w, h = rng.uniform(0.01, 3), rng.uniform(0.01, 3)
    return Polygon([(cx - w, cy - h), (cx + w, cy - h),
                    (cx + w, cy + h), (cx - w, cy + h)])


@pytest.fixture(scope="module")
def geom_data():
    rng = np.random.default_rng(13)
    n = 1201
    geoms = [_rand_geom(rng) for _ in range(n)]
    t = rng.integers(MS, MS + 30 * DAY, n)
    return geoms, t


def _query_poly(cx, cy, w, h):
    return Polygon([(cx - w, cy - h), (cx + w, cy - h),
                    (cx + w, cy + h), (cx - w, cy + h)])


def test_sharded_xz2_matches_host(mesh, geom_data):
    from geomesa_tpu.index.xz2 import XZ2Index
    geoms, _ = geom_data
    host = XZ2Index.build(geoms, g=12)
    shard = ShardedXZ2Index.build(geoms, g=12, mesh=mesh)
    rng = np.random.default_rng(17)
    for _ in range(5):
        q = _query_poly(rng.uniform(-160, 160), rng.uniform(-70, 70),
                        rng.uniform(0.5, 25), rng.uniform(0.5, 25))
        np.testing.assert_array_equal(
            shard.query(q), host.query(q),
            err_msg="sharded XZ2 != host XZ2")
        # candidate superset property (exact=False)
        qe = q.envelope
        inter = np.flatnonzero([
            g.envelope.xmin <= qe.xmax and g.envelope.xmax >= qe.xmin
            and g.envelope.ymin <= qe.ymax and g.envelope.ymax >= qe.ymin
            for g in geoms])
        assert set(inter) <= set(int(i) for i in shard.query(q, exact=False))


def test_sharded_xz3_matches_host(mesh, geom_data):
    from geomesa_tpu.index.xz3 import XZ3Index
    geoms, t = geom_data
    host = XZ3Index.build(geoms, t, period="week", g=10)
    shard = ShardedXZ3Index.build(geoms, t, period="week", g=10, mesh=mesh)
    rng = np.random.default_rng(19)
    for _ in range(5):
        q = _query_poly(rng.uniform(-160, 160), rng.uniform(-70, 70),
                        rng.uniform(0.5, 25), rng.uniform(0.5, 25))
        tlo = int(rng.integers(MS, MS + 20 * DAY))
        thi = tlo + int(rng.integers(1, 10 * DAY))
        np.testing.assert_array_equal(
            shard.query(q, tlo, thi), host.query(q, tlo, thi),
            err_msg="sharded XZ3 != host XZ3")


# -- attribute -----------------------------------------------------------
@pytest.fixture(scope="module")
def attr_data():
    rng = np.random.default_rng(23)
    n = 20_011
    names = np.array(["alpha", "beta", "gamma", "delta", "epsilon"],
                     dtype=object)[rng.integers(0, 5, n)]
    vals = rng.integers(0, 1000, n).astype(np.int64)
    dtg = rng.integers(MS, MS + 30 * DAY, n)
    return names, vals, dtg


def test_sharded_attr_equals_and_in(mesh, attr_data):
    names, _, dtg = attr_data
    idx = ShardedAttributeIndex.build("name", names, secondary=dtg, mesh=mesh)
    got = idx.query_equals("beta")
    np.testing.assert_array_equal(got, np.flatnonzero(names == "beta"))
    got = idx.query_in(["alpha", "gamma", "nope"])
    np.testing.assert_array_equal(
        got, np.flatnonzero((names == "alpha") | (names == "gamma")))
    assert len(idx.query_equals("zzz")) == 0


def test_sharded_attr_equals_date_window(mesh, attr_data):
    names, _, dtg = attr_data
    idx = ShardedAttributeIndex.build("name", names, secondary=dtg, mesh=mesh)
    lo, hi = MS + 5 * DAY, MS + 12 * DAY
    got = idx.query_equals("delta", sec_window=(lo, hi))
    np.testing.assert_array_equal(
        got, np.flatnonzero((names == "delta") & (dtg >= lo) & (dtg <= hi)))
    # open bounds
    got = idx.query_equals("delta", sec_window=(None, hi))
    np.testing.assert_array_equal(
        got, np.flatnonzero((names == "delta") & (dtg <= hi)))


def test_sharded_attr_numeric_range(mesh, attr_data):
    _, vals, _ = attr_data
    idx = ShardedAttributeIndex.build("v", vals, mesh=mesh)
    got = idx.query_range(100, 200)
    np.testing.assert_array_equal(
        got, np.flatnonzero((vals >= 100) & (vals <= 200)))
    got = idx.query_range(100, 200, lo_inclusive=False, hi_inclusive=False)
    np.testing.assert_array_equal(
        got, np.flatnonzero((vals > 100) & (vals < 200)))
    got = idx.query_range(None, 50)
    np.testing.assert_array_equal(got, np.flatnonzero(vals <= 50))


def test_sharded_attr_prefix(mesh, attr_data):
    names, _, _ = attr_data
    idx = ShardedAttributeIndex.build("name", names, mesh=mesh)
    got = idx.query_prefix("de")
    np.testing.assert_array_equal(got, np.flatnonzero(names == "delta"))
    got = idx.query_prefix("x")
    assert len(got) == 0
    with pytest.raises(TypeError):
        ShardedAttributeIndex.build("v", np.arange(10), mesh=mesh) \
            .query_prefix("1")
