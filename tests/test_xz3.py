"""XZ3 curve vs a pure-python octree-descent oracle (reference: XZ3SFC.scala)."""

import math

import numpy as np
import pytest

from geomesa_tpu.curve import TimePeriod, max_offset
from geomesa_tpu.curve.xz3 import XZ3SFC, xz3_sfc

G = 12
WEEK = float(max_offset(TimePeriod.WEEK))


def py_index(sfc: XZ3SFC, xmin, ymin, zmin, xmax, ymax, zmax):
    g = sfc.g
    xs, ys, zs = sfc.x_hi - sfc.x_lo, sfc.y_hi - sfc.y_lo, sfc.z_hi - sfc.z_lo
    nxmin, nymin, nzmin = (xmin - sfc.x_lo) / xs, (ymin - sfc.y_lo) / ys, (zmin - sfc.z_lo) / zs
    nxmax, nymax, nzmax = (xmax - sfc.x_lo) / xs, (ymax - sfc.y_lo) / ys, (zmax - sfc.z_lo) / zs
    max_dim = max(nxmax - nxmin, nymax - nymin, nzmax - nzmin)
    l1 = g if max_dim <= 0 else int(math.floor(math.log(max_dim) / math.log(0.5)))
    if l1 >= g:
        length = g
    else:
        w2 = 0.5 ** (l1 + 1)
        fits = lambda mn, mx: mx <= math.floor(mn / w2) * w2 + 2 * w2
        length = (
            l1 + 1
            if fits(nxmin, nxmax) and fits(nymin, nymax) and fits(nzmin, nzmax)
            else l1
        )
    x, y, z = nxmin, nymin, nzmin
    b = [0.0, 0.0, 0.0, 1.0, 1.0, 1.0]
    cs = 0
    for i in range(length):
        xc, yc, zc = (b[0] + b[3]) / 2, (b[1] + b[4]) / 2, (b[2] + b[5]) / 2
        q = (0 if x < xc else 1) + (0 if y < yc else 2) + (0 if z < zc else 4)
        cs += 1 + q * (8 ** (g - i) - 1) // 7
        if x < xc: b[3] = xc
        else: b[0] = xc
        if y < yc: b[4] = yc
        else: b[1] = yc
        if z < zc: b[5] = zc
        else: b[2] = zc
    return cs


@pytest.fixture(scope="module")
def sfc():
    return xz3_sfc(TimePeriod.WEEK, G)


def test_index_matches_oracle(sfc, rng):
    for _ in range(200):
        x0, x1 = np.sort(rng.uniform(-180, 180, 2))
        y0, y1 = np.sort(rng.uniform(-90, 90, 2))
        z0, z1 = np.sort(rng.uniform(0, WEEK, 2))
        got = int(sfc.index(x0, y0, z0, x1, y1, z1, xp=np))
        assert got == py_index(sfc, x0, y0, z0, x1, y1, z1)


def test_point_geometries(sfc, rng):
    for _ in range(100):
        x = rng.uniform(-180, 180)
        y = rng.uniform(-90, 90)
        z = rng.uniform(0, WEEK)
        assert int(sfc.index(x, y, z, x, y, z, xp=np)) == py_index(sfc, x, y, z, x, y, z)


def test_ranges_cover_all_intersecting_objects(sfc, rng):
    n = 1500
    cx = rng.uniform(-170, 170, n)
    cy = rng.uniform(-80, 80, n)
    ct = rng.uniform(0, WEEK, n)
    w = rng.exponential(1.0, n).clip(0, 20)
    h = rng.exponential(1.0, n).clip(0, 20)
    d = rng.exponential(3600.0, n).clip(0, WEEK / 10)
    xmin, xmax = (cx - w / 2).clip(-180, 180), (cx + w / 2).clip(-180, 180)
    ymin, ymax = (cy - h / 2).clip(-90, 90), (cy + h / 2).clip(-90, 90)
    zmin, zmax = (ct - d / 2).clip(0, WEEK), (ct + d / 2).clip(0, WEEK)
    codes = sfc.index(xmin, ymin, zmin, xmax, ymax, zmax, xp=np)
    for window in [
        (-10.0, -10.0, 0.0, 10.0, 10.0, WEEK / 4),
        (30.0, 20.0, WEEK / 2, 60.0, 50.0, WEEK),
    ]:
        ranges = sfc.ranges([window])
        intersects = (
            (xmax >= window[0]) & (xmin <= window[3])
            & (ymax >= window[1]) & (ymin <= window[4])
            & (zmax >= window[2]) & (zmin <= window[5])
        )
        in_ranges = np.zeros(n, dtype=bool)
        for lo, hi in ranges:
            in_ranges |= (codes >= lo) & (codes <= hi)
        assert not np.any(intersects & ~in_ranges)


def test_budget(sfc):
    window = (-40.0, -20.0, 0.0, 40.0, 20.0, WEEK)
    exact = sfc.ranges([window], max_ranges=10**8)
    tight = sfc.ranges([window], max_ranges=25)
    assert len(tight) < len(exact)
