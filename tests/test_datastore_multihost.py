"""TpuDataStore multihost mode on the single-process virtual mesh.

With one process, multihost degenerates (gids == rows, allgathers are
identity) but every multihost code path runs: build_multihost for all
index types, gid decode/encode residual filtering, merged stats, global
sort/limit, multihost append through the store.  The REAL two-process
system test lives in test_multihost_real.py; this file keeps the logic
under the fast CI loop."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.features import FeatureBatch
from geomesa_tpu.filters import evaluate_filter, parse_ecql
from geomesa_tpu.parallel import device_mesh
from geomesa_tpu.planning.planner import Query

MS = 1514764800000
N = 20_000


@pytest.fixture(scope="module")
def mh_store():
    rng = np.random.default_rng(77)
    ds = TpuDataStore(mesh=device_mesh(), multihost=True)
    ds.create_schema(
        "mh", "name:String:index=true,score:Double,dtg:Date,*geom:Point")
    ds.write("mh", {
        "name": rng.choice(["alpha", "beta", "gamma"], N).astype(object),
        "score": rng.uniform(0, 100, N),
        "dtg": rng.integers(MS, MS + 14 * 86_400_000, N),
        "geom": (rng.uniform(-75, -73, N), rng.uniform(40, 42, N)),
    })
    return ds


QUERIES = [
    "BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
    "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z",
    "BBOX(geom, -74.2, 40.8, -73.9, 41.1)",
    "name = 'alpha'",
    "name = 'beta' AND score > 90",
    "score < 1.5",
    "IN ('5', '17', '4999')",
]


@pytest.mark.parametrize("ecql", QUERIES)
def test_multihost_mode_oracle_equal(mh_store, ecql):
    st = mh_store._store("mh")
    got = mh_store.query_result("mh", ecql)
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(got.positions), want)


def test_multihost_mode_sort_limit(mh_store):
    got = mh_store.query_result(
        "mh", Query.of("name = 'gamma'", sort_by="score", sort_desc=True,
                       max_features=10))
    st = mh_store._store("mh")
    scores = got.batch.column("score")
    assert len(scores) == 10
    want = np.flatnonzero(evaluate_filter(parse_ecql("name = 'gamma'"),
                                          st.batch))
    top = np.sort(st.batch.column("score")[want])[::-1][:10]
    np.testing.assert_allclose(np.sort(scores)[::-1], top)


def test_multihost_mode_stats_and_bounds(mh_store):
    assert mh_store.get_count("mh") == N
    env = mh_store.get_bounds("mh")
    assert env.xmin >= -75 and env.xmax <= -73
    topk = mh_store.stat("mh", "name_topk")
    assert topk is not None and len(topk.topk(3)) == 3


def test_multihost_mode_write_appends_incrementally(mh_store):
    """A second write goes through the multihost z3 append (collective)
    and stays oracle-exact."""
    rng = np.random.default_rng(78)
    st = mh_store._store("mh")
    _ = mh_store.query("mh", QUERIES[0])  # builds z3
    z3_before = st._indexes.get("z3")
    assert z3_before is not None and z3_before._multihost
    m = 2_000
    mh_store.write("mh", {
        "name": np.array(["delta"] * m, object),
        "score": rng.uniform(0, 100, m),
        "dtg": rng.integers(MS, MS + 14 * 86_400_000, m),
        "geom": (rng.uniform(-75, -73, m), rng.uniform(40, 42, m)),
    })
    assert st._indexes.get("z3") is z3_before  # appended, not rebuilt
    got = mh_store.query_result("mh", QUERIES[0])
    want = np.flatnonzero(evaluate_filter(parse_ecql(QUERIES[0]), st.batch))
    np.testing.assert_array_equal(np.sort(got.positions), want)
    assert mh_store.get_count("mh") == N + m


def test_multihost_mode_delete(mh_store):
    ids = list(mh_store._store("mh").batch.ids[:5])
    removed = mh_store.delete("mh", ids)
    assert removed == 5
    got = mh_store.query_result("mh", "INCLUDE")
    assert len(got.positions) == mh_store.get_count("mh")


def test_multihost_polygon_schema():
    """XZ2 strategy through the multihost store (exact re-check runs on
    gid-decoded local candidates)."""
    from geomesa_tpu.geometry import Polygon
    rng = np.random.default_rng(9)
    ds = TpuDataStore(mesh=device_mesh(), multihost=True)
    ds.create_schema("poly", "v:Int,*geom:Polygon")
    n = 400
    cx = rng.uniform(-10, 10, n)
    cy = rng.uniform(-10, 10, n)
    r = rng.uniform(0.1, 0.5, n)
    geoms = [Polygon([[x - d, y - d], [x + d, y - d], [x + d, y + d],
                      [x - d, y + d], [x - d, y - d]])
             for x, y, d in zip(cx, cy, r)]
    ds.write("poly", {"v": np.arange(n), "geom": geoms})
    ecql = "INTERSECTS(geom, POLYGON((-2 -2, 4 -1, 3 5, -1 3, -2 -2)))"
    got = ds.query_result("poly", ecql)
    st = ds._store("poly")
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(got.positions), want)
    assert got.strategy.index == "xz2"


def test_multihost_requires_mesh():
    with pytest.raises(ValueError, match="requires a mesh"):
        TpuDataStore(multihost=True)


def test_multihost_mode_processes(mh_store):
    """kNN / tube-select / proximity run through the multihost store
    (positions are gids; exact passes decode to local rows)."""
    from geomesa_tpu.geometry import Point
    from geomesa_tpu.process import knn_process, proximity_process
    from geomesa_tpu.process.tube import tube_select

    st = mh_store._store("mh")
    x0, y0 = -74.0, 41.0
    pos, dist = knn_process(mh_store, "mh", x0, y0, 10)
    assert len(pos) == 10 and np.all(np.diff(dist) >= 0)
    # oracle: brute-force nearest over the (single-process) batch
    from geomesa_tpu.process.knn import haversine_m
    bx, by = st.batch.geom_xy()
    want = np.argsort(haversine_m(x0, y0, bx, by), kind="stable")[:10]
    np.testing.assert_array_equal(np.sort(pos), np.sort(want))

    prox = proximity_process(mh_store, "mh", [Point(x0, y0)], 20_000.0)
    want_p = np.flatnonzero(haversine_m(x0, y0, bx, by) <= 20_000.0)
    np.testing.assert_array_equal(prox, want_p)

    track = np.array([[-74.5, 40.5], [-74.0, 41.0], [-73.5, 41.5]])
    dtg = st.batch.column("dtg")
    times = np.array([dtg.min(), (dtg.min() + dtg.max()) // 2, dtg.max()])
    tube = tube_select(mh_store, "mh", track, times, buffer_m=30_000,
                       time_buffer_ms=10 * 86_400_000)
    assert len(tube) > 0
