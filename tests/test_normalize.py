"""Normalization semantics vs the reference's BitNormalizedDimension
(geomesa-z3/.../curve/NormalizedDimension.scala:60-71): floor-based binning,
>=max clamps to max_index, denormalize returns bin centers."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from geomesa_tpu.curve import normalized_lat, normalized_lon, normalized_time


def oracle_normalize(x, lo, hi, precision):
    if x >= hi:
        return (1 << precision) - 1
    return math.floor((x - lo) * ((1 << precision) / (hi - lo)))


@pytest.mark.parametrize("precision", [8, 21, 31])
def test_scalar_matches_oracle(precision, rng):
    dim = normalized_lon(precision)
    for x in list(rng.uniform(-180, 180, 200)) + [-180.0, 180.0, 179.999999999, 0.0]:
        assert dim.normalize_scalar(x) == oracle_normalize(x, -180, 180, precision)


def test_max_clamps():
    lat = normalized_lat(21)
    assert lat.normalize_scalar(90.0) == lat.max_index
    assert lat.normalize_scalar(91.0) == lat.max_index
    assert lat.normalize_scalar(-90.0) == 0


def test_vectorized_matches_scalar(rng):
    lon = normalized_lon(21)
    xs = np.concatenate([rng.uniform(-180, 180, 500), [-180.0, 180.0, 179.9999999]])
    vec_np = lon.normalize(xs, xp=np)
    vec_jnp = np.asarray(lon.normalize(jnp.asarray(xs)))
    scal = np.array([lon.normalize_scalar(float(x)) for x in xs])
    np.testing.assert_array_equal(vec_np, scal)
    np.testing.assert_array_equal(vec_jnp, scal)


def test_denormalize_centers():
    lon = normalized_lon(21)
    for i in [0, 1, 12345, lon.max_index - 1]:
        lo_edge = -180.0 + i * 360.0 / (1 << 21)
        assert abs(lon.denormalize_scalar(i) - (lo_edge + 0.5 * 360.0 / (1 << 21))) < 1e-9
    # max bin denormalizes to the center of the *last* bin even when asked
    # beyond it (reference: denormalize of x >= maxIndex)
    assert lon.denormalize_scalar(lon.max_index) == lon.denormalize_scalar(lon.max_index + 5)


def test_roundtrip_within_bin(rng):
    t = normalized_time(21, 604800.0)
    xs = rng.uniform(0, 604800.0, 1000)
    idx = t.normalize(xs, xp=np)
    back = t.denormalize(idx, xp=np)
    assert np.max(np.abs(back - xs)) <= 604800.0 / (1 << 21)
