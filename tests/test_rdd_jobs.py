"""SpatialRDD provider + ingest job tests (geomesa-spark-core /
geomesa-jobs analogs)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.jobs import run_ingest
from geomesa_tpu.parallel import save_rdd, spatial_rdd

MS_2018 = 1514764800000
DAY = 86_400_000


@pytest.fixture
def store():
    ds = TpuDataStore()
    ds.create_schema("pts", "name:String,v:Int,dtg:Date,*geom:Point")
    rng = np.random.default_rng(3)
    n = 1000
    ds.write("pts", {
        "name": np.asarray([f"n{i % 3}" for i in range(n)], dtype=object),
        "v": rng.integers(0, 100, n),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * DAY, n),
        "geom": (rng.uniform(-120, 120, n), rng.uniform(-50, 50, n)),
    })
    return ds


def test_store_rdd_partitions_and_collect(store):
    rdd = spatial_rdd({"store": store}, "pts", num_partitions=4)
    assert rdd.num_partitions == 4
    assert rdd.count() == 1000
    assert len(rdd.collect()) == 1000
    # query filter applies before partitioning
    rdd = spatial_rdd({"store": store}, "pts",
                      "BBOX(geom, 0, -50, 120, 50)", num_partitions=3)
    x, _ = store._store("pts").batch.geom_xy()
    assert rdd.count() == int((x >= 0).sum())


def test_rdd_spatial_locality(store):
    """Z-ordered partitioning: partitions are contiguous key-space slabs,
    so per-partition bboxes overlap much less than random splits."""
    rdd = spatial_rdd({"store": store}, "pts", num_partitions=4)
    boxes = []
    for p in rdd.partitions:
        x, y = p.geom_xy()
        boxes.append((x.min(), y.min(), x.max(), y.max()))
    # not all partitions should span the whole world
    spans = [(b[2] - b[0]) * (b[3] - b[1]) for b in boxes]
    world = 240.0 * 100.0
    assert min(spans) < 0.5 * world


def test_rdd_aggregate(store):
    rdd = spatial_rdd({"store": store}, "pts", num_partitions=4)
    total = rdd.aggregate(lambda b: int(b.column("v").sum()),
                          lambda a, b: a + b)
    assert total == int(store._store("pts").batch.column("v").sum())


def test_rdd_to_arrow(store):
    table = spatial_rdd({"store": store}, "pts", num_partitions=4).to_arrow()
    assert table.num_rows == 1000
    assert "name" in table.column_names


def test_rdd_save_roundtrip(store):
    rdd = spatial_rdd({"store": store}, "pts", "name = 'n1'")
    dst = TpuDataStore()
    n = save_rdd(rdd, {"store": dst}, "pts")
    assert n == rdd.count() > 0
    assert dst.get_count("pts") == n


def test_converter_rdd(tmp_path, store):
    for i in range(3):
        (tmp_path / f"f{i}.csv").write_text(
            "\n".join(f"a{j},{j},{MS_2018},-{i}.5,4{i}.0"
                      for j in range(10)) + "\n")
    params = {
        "paths": [str(tmp_path / f"f{i}.csv") for i in range(3)],
        "sft": store.get_schema("pts"),
        "converter": {
            "type": "csv",
            "fields": [
                {"name": "name", "transform": "$0"},
                {"name": "v", "transform": "toInt($1)"},
                {"name": "dtg", "transform": "toLong($2)"},
                {"name": "geom", "transform": "point($3,$4)"},
            ],
        },
    }
    rdd = spatial_rdd(params, "pts")
    assert rdd.num_partitions == 3 and rdd.count() == 30
    # filtered read
    rdd = spatial_rdd(params, "pts", "BBOX(geom,-1,39,0,41)")
    assert rdd.count() == 10


def test_fs_rdd(tmp_path):
    from geomesa_tpu.fs import FileSystemDataStore
    fs = FileSystemDataStore(str(tmp_path))
    fs.create_schema("evt", "dtg:Date,*geom:Point",
                     scheme={"scheme": "datetime", "datetime-step": "daily"})
    rng = np.random.default_rng(5)
    n = 200
    fs.write("evt", {
        "dtg": rng.integers(MS_2018, MS_2018 + 3 * DAY, n),
        "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)),
    })
    rdd = spatial_rdd({"fs": fs}, "evt")
    assert rdd.count() == n
    assert rdd.num_partitions >= 3  # one per day partition
    # temporal pruning reduces partitions read
    rdd2 = spatial_rdd(
        {"fs": fs}, "evt",
        "dtg DURING 2018-01-01T00:00:00Z/2018-01-01T23:59:59Z")
    assert rdd2.num_partitions <= 2 and 0 < rdd2.count() < n


def test_ingest_job(tmp_path, store):
    files = []
    for i in range(6):
        p = tmp_path / f"in{i}.csv"
        p.write_text("\n".join(
            f"x{j},{j},{MS_2018 + j},{i}.25,1.5" for j in range(20)) + "\n")
        files.append(str(p))
    bad = tmp_path / "bad.csv"
    bad.write_text("x,notanint,0,0.0,0.0\n")
    files.append(str(bad))
    config = {
        "type": "csv",
        "fields": [
            {"name": "name", "transform": "$0"},
            {"name": "v", "transform": "toInt($1)"},
            {"name": "dtg", "transform": "toLong($2)"},
            {"name": "geom", "transform": "point($3,$4)"},
        ],
        "options": {"error-mode": "skip"},
    }
    before = store.get_count("pts")
    result = run_ingest(store, "pts", config, files, workers=3)
    assert result.ingested == 120 and result.files == 7
    assert result.failed >= 1
    assert store.get_count("pts") == before + 120


def test_distributed_ingest_single_process(tmp_path):
    """run_distributed_ingest: parse → build_multihost end-to-end (the
    DistributedConverterIngest analog; single-process degenerate case)."""
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.jobs import run_distributed_ingest

    sft = parse_spec("pts", "name:String,dtg:Date,*geom:Point")
    config = {
        "type": "csv",
        "fields": [
            {"name": "name", "transform": "toString($0)"},
            {"name": "dtg", "transform": "toLong($1)"},
            {"name": "geom", "transform": "point($2, $3)"},
        ],
    }
    rng = np.random.default_rng(3)
    paths = []
    all_rows = []
    for f in range(3):
        rows = [(f"u{f}_{i}", 1514764800000 + i * 60_000,
                 float(rng.uniform(-74.5, -73.5)),
                 float(rng.uniform(40.2, 41.8))) for i in range(50)]
        all_rows.extend(rows)
        p = tmp_path / f"f{f}.csv"
        p.write_text("\n".join(
            f"{n},{t},{x},{y}" for n, t, x, y in rows) + "\n")
        paths.append(str(p))
    idx, result = run_distributed_ingest(sft, config, paths)
    assert result.files == 3 and result.failed == 0
    assert idx.total() == result.ingested == len(all_rows)
    box = (-74.2, 40.5, -73.8, 41.5)
    hits = idx.query([box], None, None)
    xs = np.array([r[2] for r in all_rows])
    ys = np.array([r[3] for r in all_rows])
    # file parse order is nondeterministic (as_completed), so compare
    # hit COUNTS against the oracle mask over all rows
    want = np.count_nonzero((xs >= box[0]) & (xs <= box[2])
                            & (ys >= box[1]) & (ys <= box[3]))
    assert len(hits) == want


def test_distributed_ingest_path_split():
    from geomesa_tpu.jobs import local_paths_for_process
    paths = [f"p{i}" for i in range(7)]
    shares = [local_paths_for_process(paths, i, 3) for i in range(3)]
    assert sorted(sum(shares, [])) == sorted(paths)
    assert max(len(s) for s in shares) - min(len(s) for s in shares) <= 1
