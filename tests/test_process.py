"""Analytic processes vs brute-force oracles (reference: geomesa-process)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.geometry import Point, Polygon
from geomesa_tpu.process import (
    density_process,
    knn_process,
    proximity_process,
    sample_positions,
    stats_process,
    tube_select,
)
from geomesa_tpu.process.knn import haversine_m

MS_2018 = 1514764800000
N = 30_000


@pytest.fixture(scope="module")
def store():
    rng = np.random.default_rng(5)
    ds = TpuDataStore()
    ds.create_schema("ais", "vessel:String,dtg:Date,*geom:Point")
    ds.write("ais", {
        "vessel": rng.choice([f"v{i}" for i in range(50)], N),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * 86_400_000, N),
        "geom": (rng.uniform(-5.0, 5.0, N), rng.uniform(45.0, 55.0, N)),
    })
    return ds


def test_knn_matches_bruteforce(store):
    x0, y0, k = 1.0, 50.0, 25
    pos, dist = knn_process(store, "ais", x0, y0, k)
    batch = store._store("ais").batch
    bx, by = batch.geom_xy()
    all_d = haversine_m(x0, y0, bx, by)
    expected = np.sort(all_d)[:k]
    np.testing.assert_allclose(np.sort(dist), expected)
    assert len(pos) == k


def test_knn_with_time(store):
    tlo, thi = MS_2018, MS_2018 + 86_400_000
    pos, dist = knn_process(store, "ais", 0.0, 50.0, 10, tlo, thi)
    batch = store._store("ais").batch
    t = batch.column("dtg")
    assert np.all((t[pos] >= tlo) & (t[pos] <= thi))
    bx, by = batch.geom_xy()
    mask = (t >= tlo) & (t <= thi)
    expected = np.sort(haversine_m(0.0, 50.0, bx[mask], by[mask]))[:10]
    np.testing.assert_allclose(np.sort(dist), expected)


def test_knn_sparse_area(store):
    # far from the data cloud: expanding rounds must still find k
    pos, dist = knn_process(store, "ais", 20.0, 50.0, 5)
    assert len(pos) == 5
    assert np.all(np.diff(dist) >= 0)


def test_tube_select(store):
    track = np.array([[-2.0, 47.0], [0.0, 50.0], [2.0, 53.0]])
    times = np.array([MS_2018, MS_2018 + 3_600_000, MS_2018 + 7_200_000])
    buffer_m, tbuf = 50_000.0, 1_800_000
    got = tube_select(store, "ais", track, times, buffer_m, tbuf)
    # oracle: exact distance to track + time interpolation
    batch = store._store("ais").batch
    bx, by = batch.geom_xy()
    t = batch.column("dtg").astype(np.float64)
    from geomesa_tpu.process.tube import _point_segment_dist_deg
    dd, tt = _point_segment_dist_deg(bx, by, track[:-1, 0], track[:-1, 1],
                                     track[1:, 0], track[1:, 1])
    seg = np.argmin(dd, axis=1)
    rows = np.arange(len(bx))
    tb = tt[rows, seg]
    cx = track[:-1, 0][seg] + tb * (track[1:, 0] - track[:-1, 0])[seg]
    cy = track[:-1, 1][seg] + tb * (track[1:, 1] - track[:-1, 1])[seg]
    dist_ok = haversine_m(bx, by, cx, cy) <= buffer_m
    t_interp = times[:-1].astype(float)[seg] + tb * (times[1:] - times[:-1]).astype(float)[seg]
    time_ok = np.abs(t - t_interp) <= tbuf
    expected = np.flatnonzero(dist_ok & time_ok)
    np.testing.assert_array_equal(got, expected)
    assert len(expected) > 0


def test_proximity_point(store):
    got = proximity_process(store, "ais", [Point(0.0, 50.0)], 30_000.0)
    batch = store._store("ais").batch
    bx, by = batch.geom_xy()
    expected = np.flatnonzero(haversine_m(0.0, 50.0, bx, by) <= 30_000.0)
    np.testing.assert_array_equal(got, expected)
    assert len(expected) > 0


def test_proximity_polygon(store):
    poly = Polygon([[-1.0, 49.0], [1.0, 49.0], [1.0, 51.0], [-1.0, 51.0]])
    got = proximity_process(store, "ais", [poly], 10_000.0)
    batch = store._store("ais").batch
    bx, by = batch.geom_xy()
    from geomesa_tpu.geometry.predicates import point_in_polygon
    inside = point_in_polygon(bx, by, poly)
    assert np.all(np.isin(np.flatnonzero(inside), got))


def test_density_process(store):
    env = (-5.0, 45.0, 5.0, 55.0)
    grid = density_process(store, "ais", "INCLUDE", env, 64, 64)
    assert grid.sum() == pytest.approx(N)
    # weighted
    grid_w = density_process(store, "ais", "INCLUDE", env, 64, 64,
                             weight_attr="dtg")
    assert grid_w.sum() > grid.sum()


def test_stats_process(store):
    s = stats_process(store, "ais", "BBOX(geom, -1, 49, 1, 51)",
                      "Count();MinMax(dtg)")
    batch = store._store("ais").batch
    bx, by = batch.geom_xy()
    mask = (bx >= -1) & (bx <= 1) & (by >= 49) & (by <= 51)
    assert s.stats[0].count == mask.sum()
    assert s.stats[1].min == batch.column("dtg")[mask].min()


def test_sampling():
    pos = np.arange(100)
    assert len(sample_positions(pos, 10)) == 10
    groups = np.repeat(np.arange(5), 20)
    got = sample_positions(pos, 7, group_keys=groups)
    # each group of 20 keeps ceil(20/7)=3
    assert len(got) == 15
    assert len(sample_positions(pos, 1)) == 100


def test_arrow_conversion_process(store):
    import io as _io

    pa = pytest.importorskip("pyarrow")

    from geomesa_tpu.process import arrow_conversion_process

    flt = "bbox(geom, -2, 47, 2, 53)"
    data = arrow_conversion_process(ds=store, type_name="ais", query=flt,
                                    dictionary_fields=("vessel",),
                                    sort_field="dtg")
    table = pa.ipc.open_stream(_io.BytesIO(data)).read_all()
    want = len(store.query("ais", flt))
    assert table.num_rows == want > 0
    dtg = table.column("dtg").cast(pa.int64()).to_numpy()
    assert (np.diff(dtg) >= 0).all()


def test_bin_conversion_process(store):
    from geomesa_tpu.io.bin_encoder import decode_bin
    from geomesa_tpu.process import bin_conversion_process

    data = bin_conversion_process(store, "ais")
    n = len(store.query("ais"))
    assert len(data) == 16 * n
    cols = decode_bin(data)
    bx, by = store.query("ais").geom_xy()
    np.testing.assert_allclose(cols["lon"], bx.astype(np.float32))
    np.testing.assert_allclose(cols["lat"], by.astype(np.float32))
    assert bin_conversion_process(store, "ais",
                                  "bbox(geom, 100, 10, 101, 11)") == b""


def test_tube_select_nofill(store):
    """NoGapFill (the reference's default TubeBuilder mode): vertex-only
    buffers, each with its own time slab — no interpolation across gaps."""
    track = np.array([[-2.0, 47.0], [0.0, 50.0], [2.0, 53.0]])
    times = np.array([MS_2018, MS_2018 + 3_600_000, MS_2018 + 7_200_000])
    buffer_m, tbuf = 50_000.0, 1_800_000
    got = tube_select(store, "ais", track, times, buffer_m, tbuf,
                      gap_fill="nofill")
    batch = store._store("ais").batch
    bx, by = batch.geom_xy()
    t = batch.column("dtg").astype(np.float64)
    d = haversine_m(bx[:, None], by[:, None],
                    track[None, :, 0], track[None, :, 1])
    ok = (d <= buffer_m) & (
        np.abs(t[:, None] - times[None, :].astype(float)) <= tbuf)
    expected = np.flatnonzero(ok.any(axis=1))
    np.testing.assert_array_equal(got, expected)
    # nofill is a subset of the line corridor around the same vertices
    line = tube_select(store, "ais", track, times, buffer_m, tbuf)
    assert set(got) <= set(line)


def test_tube_select_bad_mode(store):
    track = np.array([[-2.0, 47.0], [0.0, 50.0]])
    times = np.array([MS_2018, MS_2018 + 3_600_000])
    import pytest as _pytest
    with _pytest.raises(ValueError, match="gap_fill"):
        tube_select(store, "ais", track, times, 1000.0, 1000,
                    gap_fill="bogus")
