"""Resource observability (ISSUE 9): storage/HBM accounting with the
accounted-vs-actual reconciliation audit, EXPLAIN ANALYZE with
estimate-vs-actual, the /debug/storage + /explain web surfaces, JSONL
trace rotation, and the merge_snapshots edge cases.

The storage acceptance shape: a warm multi-generation lean store
(full + keys + host tiers, warmed caches) whose /debug/storage totals
reconcile with independently summed array nbytes within the tolerances
documented in obs/resource.py.
"""

import io
import json

import numpy as np
import pytest

from geomesa_tpu import obs
from geomesa_tpu.audit import InMemoryAuditWriter
from geomesa_tpu.config import clear_property, set_property
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.metrics import (
    PLAN_ESTIMATE_RATIO, Gauge, MetricRegistry, merge_snapshots, registry,
)
from geomesa_tpu.obs.resource import (
    index_actual_nbytes, publish_storage_gauges, storage_report,
)

MS = 1514764800000
DAY = 86_400_000

LEAN_Q = ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
          "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")


def _mk_lean_store(audit=None, n=40_000):
    rng = np.random.default_rng(31)
    ds = TpuDataStore(audit_writer=audit, user="res-test")
    # tight HBM budget => real tiering: live full-tier run, demoted
    # keys runs, host spills — every residency class the storage
    # report accounts for
    ds.create_schema(
        "evt", "score:Double,dtg:Date,*geom:Point;"
               "geomesa.index.profile=lean,"
               "geomesa.lean.generation.slots=16384,"
               "geomesa.lean.compaction.factor=0,"
               "geomesa.lean.hbm.budget=700000")
    for s in range(0, n, 16_000):
        m = min(16_000, n - s)
        ds.write("evt", {
            "score": rng.uniform(0, 100, m),
            "dtg": rng.integers(MS, MS + 14 * DAY, m),
            "geom": (rng.uniform(-75, -73, m), rng.uniform(40, 42, m))})
    return ds


@pytest.fixture(scope="module")
def lean_ds():
    audit = InMemoryAuditWriter()
    ds = _mk_lean_store(audit=audit)
    ds.query("evt", LEAN_Q)          # warm: builds + stacks host runs
    ds._res_audit = audit
    return ds


def _call(app, method, path):
    cap = {}

    def sr(status, headers):
        cap["status"] = int(status.split()[0])
        cap["headers"] = dict(headers)

    qs = ""
    if "?" in path:
        path, qs = path.split("?", 1)
    body = b"".join(app({
        "REQUEST_METHOD": method, "PATH_INFO": path, "QUERY_STRING": qs,
        "CONTENT_LENGTH": "0", "wsgi.input": io.BytesIO(b"")}, sr))
    return cap["status"], cap["headers"], body.decode()


# -- storage accounting (tentpole a) ---------------------------------------

def test_storage_report_reconciles_on_warm_multigeneration_store(lean_ds):
    """ACCEPTANCE: byte totals reconcile with summed array nbytes on a
    warm multi-generation store, within the documented tolerances."""
    rep = storage_report(lean_ds)
    recon = rep["reconciliation"]
    assert recon["within_tolerance"], recon
    # device accounting must be EXACT: constants vs actual dtypes
    assert recon["device"]["accounted"] == recon["device"]["actual"] > 0
    # host spill present (the tight budget forces it) and the
    # accounted view never UNDERSTATES actual residency
    assert recon["host"]["actual"] > 0
    assert recon["host"]["accounted"] >= recon["host"]["actual"]
    # the z3 index entry carries per-generation residency detail
    z3 = rep["schemas"]["evt"]["indexes"]["z3"]
    gens = z3["generations"]
    assert len(gens) >= 3
    assert {g["tier"] for g in gens} >= {"keys", "host"}
    assert sum(g["device_bytes"] for g in gens) == z3["device_bytes"]
    assert sum(g["host_bytes"] for g in gens) == z3["host_bytes"]
    assert z3["rows"] == sum(g["rows"] for g in gens) == 40_000
    # column store accounted: 40k rows x (score f64 + dtg i64 + x + y)
    assert rep["schemas"]["evt"]["batch_host_bytes"] == 40_000 * 32


def test_storage_report_audit_is_independent(lean_ds):
    """The actual-nbytes walk re-derives device bytes from the arrays
    themselves — agreeing with the constant-based accounting is the
    audit (a dtype drift would break this, not slide by silently)."""
    z3 = lean_ds._store("evt")._indexes["z3"]
    audit = index_actual_nbytes(z3)
    assert audit["device_bytes"] == z3.device_bytes()
    st = z3.storage_stats()
    assert st["device_bytes"] == audit["device_bytes"]
    assert st["sentinel_bytes"] >= 0
    assert st["hbm_budget_bytes"] == 700000


def test_density_and_sketch_caches_report_bytes():
    from geomesa_tpu.index.z3_lean import LeanZ3Index
    rng = np.random.default_rng(37)
    idx = LeanZ3Index(period="week", generation_slots=8192,
                      payload_on_device=False)
    for _ in range(3):
        idx.append(rng.uniform(-75, -73, 8192), rng.uniform(40, 42, 8192),
                   rng.integers(MS, MS + 14 * DAY, 8192))
    idx.block()
    box = [(-74.5, 40.5, -73.5, 41.5)]
    args = (box, MS + 2 * DAY, MS + 9 * DAY, (-180, -90, 180, 90), 64, 64)
    idx.density(*args)
    idx.density(*args)                       # warm: sealed partials cached
    st = idx.storage_stats()
    assert st["caches"]["density"]["bytes"] > 0
    assert st["caches"]["density"]["partials"] >= 2
    audit = index_actual_nbytes(idx)
    assert audit["cache_bytes"] == (st["caches"]["density"]["bytes"]
                                    + st["caches"]["sketch"]["bytes"])


def test_sharded_lean_storage_stats():
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.lean import ShardedLeanZ3Index
    rng = np.random.default_rng(41)
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=1024,
                             payload_on_device=False)
    for _ in range(2):
        m = 8 * 1024
        idx.append(rng.uniform(-75, -73, m), rng.uniform(40, 42, m),
                   rng.integers(MS, MS + 14 * DAY, m))
    idx.block()
    st = idx.storage_stats()
    assert st["device_bytes"] == idx.device_bytes() > 0
    audit = index_actual_nbytes(idx)
    assert audit["device_bytes"] == st["device_bytes"]
    assert st["rows"] == len(idx) == 16 * 1024


def test_debug_storage_endpoint_and_gauges(lean_ds):
    from geomesa_tpu.web import WebApp
    app = WebApp(lean_ds)
    status, _, body = _call(app, "GET", "/debug/storage")
    assert status == 200
    rep = json.loads(body)
    assert rep["reconciliation"]["within_tolerance"]
    assert rep["totals"]["device_bytes"] > 0
    # the walk refreshed the storage.* gauges → scrapeable from prom
    status, _, prom = _call(app, "GET", "/metrics.prom")
    assert status == 200
    assert ("geomesa_storage_total_device_bytes "
            f"{float(rep['totals']['device_bytes'])!r}") in prom \
        or "geomesa_storage_total_device_bytes" in prom
    assert "# TYPE geomesa_storage_total_device_bytes gauge" in prom
    assert "geomesa_storage_evt_z3_device_bytes" in prom


def test_stale_storage_gauges_retire_on_republish():
    """A dropped schema's gauges must disappear on the next publish —
    phantom resident bytes would outlive the memory they described."""
    rng = np.random.default_rng(43)
    ds = TpuDataStore(user="stale")
    ds.create_schema("tmp", "dtg:Date,*geom:Point")
    n = 2_000
    ds.write("tmp", {"dtg": rng.integers(MS, MS + DAY, n),
                     "geom": (rng.uniform(-75, -73, n),
                              rng.uniform(40, 42, n))})
    publish_storage_gauges(ds)
    assert "storage.tmp.batch_bytes" in registry.names()
    ds.remove_schema("tmp")
    publish_storage_gauges(ds)
    assert "storage.tmp.batch_bytes" not in registry.names()
    assert "storage.total.device_bytes" in registry.names()


def test_publish_tracks_gauges_per_store(lean_ds):
    """A second store's publish must not retire the first store's live
    gauges (per-store key tracking, not a module global)."""
    rng = np.random.default_rng(47)
    other = TpuDataStore(user="other")
    other.create_schema("aux", "dtg:Date,*geom:Point")
    n = 1_000
    other.write("aux", {"dtg": rng.integers(MS, MS + DAY, n),
                        "geom": (rng.uniform(-75, -73, n),
                                 rng.uniform(40, 42, n))})
    publish_storage_gauges(lean_ds)
    assert "storage.evt.batch_bytes" in registry.names()
    publish_storage_gauges(other)
    assert "storage.evt.batch_bytes" in registry.names()
    assert "storage.aux.batch_bytes" in registry.names()


def test_reconciliation_tolerance_is_one_directional():
    """Overstatement within tolerance passes; understatement beyond
    float slack fails — real bytes exceeding the budget's belief is
    the dangerous direction."""
    from geomesa_tpu.obs.resource import _reconcile
    assert _reconcile(130, 100, "host")["ok"]          # +30% < 35%
    assert not _reconcile(140, 100, "host")["ok"]      # +40% > 35%
    assert not _reconcile(70, 100, "host")["ok"]       # -30% understates
    assert _reconcile(100, 100, "device")["ok"]
    assert not _reconcile(95, 100, "device")["ok"]
    assert _reconcile(0, 0, "cache")["ok"]


def test_gauge_metric_snapshot_and_merge():
    reg = MetricRegistry()
    reg.gauge("storage.total.device_bytes").set(100)
    assert isinstance(reg._metrics["storage.total.device_bytes"], Gauge)
    snap = reg.snapshot()
    assert snap["storage.total.device_bytes"] == {"value": 100.0}
    other = {"storage.total.device_bytes": {"value": 28.0}}
    merged = merge_snapshots([snap, other])
    assert merged["storage.total.device_bytes"]["value"] == 128.0


# -- EXPLAIN ANALYZE (tentpole b) ------------------------------------------

def test_planned_query_span_carries_estimate_and_actuals(lean_ds):
    """ACCEPTANCE: every planned query span carries the estimate,
    actual scanned/matched, and the ratio feeds a scrapeable metric."""
    h0 = registry.histogram(PLAN_ESTIMATE_RATIO).count
    got = lean_ds.query_result("evt", LEAN_Q)
    assert registry.histogram(PLAN_ESTIMATE_RATIO).count == h0 + 1
    tr = obs.tracer.ring.traces()[-1]
    assert tr.name == "query"
    a = tr.root_span.attributes
    assert a["plan.estimate.rows"] > 0
    assert a["plan.actual.scanned"] >= a["plan.actual.matched"] > 0
    assert a["plan.actual.matched"] == len(got.positions)
    assert a["plan.estimate.ratio"] == pytest.approx(
        (a["plan.estimate.rows"] + 1) / (a["plan.actual.scanned"] + 1),
        rel=1e-3)
    plan = [s for s in tr.spans if s.name == "query.plan"][-1]
    assert plan.attributes["plan.estimate.rows"] == a["plan.estimate.rows"]
    assert "full" in plan.attributes["plan.options"]
    # scrapeable from /metrics.prom
    from geomesa_tpu.web import WebApp
    _, _, prom = _call(WebApp(lean_ds), "GET", "/metrics.prom")
    assert 'geomesa_plan_estimate_ratio{quantile="0.5"}' in prom


def test_explain_analyze_api(lean_ds):
    res = lean_ds.explain_analyze("evt", LEAN_Q)
    s = res.summary
    assert s["strategy"] == "z3"
    assert s["estimate_rows"] > 0
    assert s["actual_scanned"] > 0
    assert s["actual_matched"] == res.result_summary["hits"] > 0
    assert s["estimate_ratio"] > 0
    assert "full" in s["options"]
    tree = res.tree()
    assert tree["name"] == "query"
    names = {c["name"] for c in tree["children"]}
    assert {"query.plan", "query.scan", "query.post_filter"} <= names
    text = res.render()
    assert "strategy=z3" in text and "Plan narration:" in text
    assert "Estimate audit" in text


def test_explain_analyze_forces_capture_under_never_sampler(lean_ds):
    """An explicit explain request must trace even with sampling off —
    the capture path bypasses the sampler (but not the shared ring)."""
    set_property("geomesa.obs.sampler", "never")
    try:
        r0 = len(obs.tracer.ring)
        res = lean_ds.explain_analyze("evt", LEAN_Q)
        assert res.trace is not None
        assert res.summary["estimate_rows"] > 0
        assert len(obs.tracer.ring) == r0       # never-sampled: not exported
    finally:
        clear_property("geomesa.obs.sampler")


def test_capture_keeps_slow_log_silent_when_sampler_never(lean_ds):
    """'never' is a true off switch (module doc): a captured slow query
    must not leak into the shared slow log — and with tracing disabled
    entirely, neither ring nor slow log may grow."""
    set_property("geomesa.obs.sampler", "never")
    set_property("geomesa.obs.slow.ms", 0.0001)   # everything is "slow"
    try:
        s0 = len(obs.tracer.slow_log)
        res = lean_ds.explain_analyze("evt", LEAN_Q)
        assert res.trace is not None              # capture still records
        assert len(obs.tracer.slow_log) == s0
    finally:
        clear_property("geomesa.obs.sampler")
        clear_property("geomesa.obs.slow.ms")
    set_property("geomesa.obs.enabled", False)
    set_property("geomesa.obs.slow.ms", 0.0001)
    try:
        r0, s0 = len(obs.tracer.ring), len(obs.tracer.slow_log)
        res = lean_ds.explain_analyze("evt", LEAN_Q)
        assert res.trace is not None
        assert len(obs.tracer.ring) == r0
        assert len(obs.tracer.slow_log) == s0
    finally:
        clear_property("geomesa.obs.enabled")
        clear_property("geomesa.obs.slow.ms")


def test_explain_endpoint(lean_ds):
    from geomesa_tpu.web import WebApp
    app = WebApp(lean_ds)
    status, _, body = _call(
        app, "GET", "/explain?schema=evt&cql=" + LEAN_Q.replace(" ", "%20"))
    assert status == 200
    out = json.loads(body)
    assert out["summary"]["estimate_rows"] > 0
    assert out["summary"]["actual_matched"] > 0
    assert out["plans"][0]["name"] == "query"
    status, headers, text = _call(
        app, "GET", "/explain?schema=evt&format=text")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    assert "EXPLAIN ANALYZE schema:evt" in text
    status, _, _ = _call(app, "GET", "/explain")
    assert status == 400
    status, _, _ = _call(app, "GET", "/explain?schema=nope")
    assert status == 404


def test_explain_endpoint_sql(lean_ds):
    from geomesa_tpu.web import WebApp
    app = WebApp(lean_ds)
    status, _, body = _call(
        app, "GET",
        "/explain?sql=SELECT%20count(*)%20FROM%20evt%20WHERE%20"
        "score%20%3E%2050")
    assert status == 200
    out = json.loads(body)
    assert out["target"] == "sql"
    assert out["plans"], "the SQL's store queries must be captured"


# -- satellite: JSONL trace rotation ---------------------------------------

def test_jsonl_exporter_rotates_at_size_cap(tmp_path):
    path = str(tmp_path / "traces.jsonl")
    exp = obs.JsonlExporter(path, max_bytes=4096)
    t = obs.Tracer(sampler=obs.AlwaysSampler(), exporters=[exp])
    for i in range(200):
        with t.span("query", schema="rot", i=i):
            pass
    exp.close()
    import os
    assert os.path.exists(path + ".1"), "rotation must have happened"
    live = os.path.getsize(path)
    rolled = os.path.getsize(path + ".1")
    assert live + rolled <= 4096 + 512      # bounded by the cap (+1 line)
    # both files still hold valid JSONL, newest trace last in the live
    lines = open(path).read().splitlines()
    assert json.loads(lines[-1])["name"] == "query"
    assert json.loads(open(path + ".1").read().splitlines()[0])


def test_jsonl_rotation_option_is_live(tmp_path):
    path = str(tmp_path / "t2.jsonl")
    set_property("geomesa.obs.trace.max_bytes", 2048)
    try:
        exp = obs.JsonlExporter(path)       # cap from the option
        t = obs.Tracer(sampler=obs.AlwaysSampler(), exporters=[exp])
        for i in range(100):
            with t.span("query", i=i):
                pass
        exp.close()
        import os
        assert os.path.getsize(path) <= 2048
    finally:
        clear_property("geomesa.obs.trace.max_bytes")


# -- satellite: merge_snapshots edge cases ---------------------------------

def test_merge_snapshots_empty_inputs():
    assert merge_snapshots([]) == {}
    assert merge_snapshots([{}, {}]) == {}


def test_merge_snapshots_disjoint_metrics_and_buckets():
    a, b = MetricRegistry(), MetricRegistry()
    a.counter("lean.only_a").inc(2)
    b.counter("lean.only_b").inc(5)
    # disjoint value ranges → disjoint bucket tables
    for v in (0.01, 0.02, 0.03):
        a.histogram("lean.h").update(v)
    for v in (10_000.0, 20_000.0, 40_000.0):
        b.histogram("lean.h").update(v)
    merged = merge_snapshots([a.snapshot(buckets=True),
                              b.snapshot(buckets=True)])
    assert merged["lean.only_a"] == {"count": 2}
    assert merged["lean.only_b"] == {"count": 5}
    h = merged["lean.h"]
    assert h["count"] == 6
    assert h["min"] == 0.01 and h["max"] == 40_000.0
    # p50 must sit between the two disjoint clusters' extremes
    assert 0.01 <= h["p50"] <= 10_000.0
    assert h["p99"] >= 10_000.0


def test_merge_snapshots_one_sided_histogram():
    """A metric present on one process only (e.g. host spill happened
    on a single worker) must merge as itself."""
    a, b = MetricRegistry(), MetricRegistry()
    for v in (1.0, 2.0, 4.0):
        a.timer("lean.t").update(v)
    b.counter("lean.c").inc()
    merged = merge_snapshots([a.snapshot(buckets=True),
                              b.snapshot(buckets=True)])
    assert merged["lean.t"]["count"] == 3
    assert merged["lean.t"]["min"] == 1.0
    assert merged["lean.t"]["max"] == 4.0
    assert merged["lean.t"]["p50"] == pytest.approx(2.0, rel=0.16)


def test_merge_snapshots_zero_only_histogram():
    """All-zero updates live in the zero bucket (no log bucket) — the
    merge must not divide by an empty table."""
    a = MetricRegistry()
    for _ in range(4):
        a.histogram("lean.z").update(0.0)
    merged = merge_snapshots([a.snapshot(buckets=True)])
    assert merged["lean.z"]["count"] == 4
    assert merged["lean.z"]["p50"] == 0.0
    assert merged["lean.z"]["p99"] == 0.0


def test_merge_snapshots_still_rejects_bucketless_histograms():
    a = MetricRegistry()
    a.timer("lean.t").update(3.0)
    with pytest.raises(ValueError, match="buckets=True"):
        merge_snapshots([a.snapshot()])     # plain snapshot: no tables
