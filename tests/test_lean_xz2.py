"""Lean XZ2 tier (round-4 VERDICT #4): non-point schemas (polygons /
lines) at the lean profile's scale — the XZ2 sequence code on the
generational device/host residency machinery, INTERSECTS ECQL
oracle-exact through the facade, snapshots via per-part WKB.

Reference: XZ2SFC.scala:54-77, XZ2IndexKeySpace.scala:44.
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.geometry.types import Polygon
from geomesa_tpu.index.xz2_lean import LeanXZ2Index

MS = 1514764800000


@pytest.fixture(scope="module")
def polys():
    rng = np.random.default_rng(31)
    n = 40_000
    cx = rng.uniform(-170, 170, n)
    cy = rng.uniform(-80, 80, n)
    w = rng.uniform(0.001, 0.05, n)
    geoms = [Polygon([(a - d, b - d), (a + d, b - d), (a + d, b + d),
                      (a - d, b + d)]) for a, b, d in zip(cx, cy, w)]
    kind = rng.choice(np.array(["road", "building", "park"], object), n)
    return cx, cy, w, geoms, kind


def _box_oracle(cx, cy, w, box):
    return np.flatnonzero((cx + w >= box[0]) & (cx - w <= box[2])
                          & (cy + w >= box[1]) & (cy - w <= box[3]))


def test_index_candidates_cover_with_spills(polys):
    cx, cy, w, geoms, _ = polys
    slots = 1 << 12
    idx = LeanXZ2Index(generation_slots=slots,
                       hbm_budget_bytes=3 * slots * 20)
    bb = np.stack([cx - w, cy - w, cx + w, cy + w], axis=1)
    for lo in range(0, len(cx), 7000):
        idx.append_bboxes(bb[lo:lo + 7000], base_gid=lo)
    assert idx.tier_counts()["host"] >= 1
    box = (-80.0, 30.0, -60.0, 50.0)
    q = Polygon([(box[0], box[1]), (box[2], box[1]),
                 (box[2], box[3]), (box[0], box[3])])
    cand = idx.query(q, exact=False)
    want = set(_box_oracle(cx, cy, w, box))
    assert want.issubset(set(cand.tolist()))   # candidate superset


@pytest.fixture(scope="module")
def poly_store(polys):
    cx, cy, w, geoms, kind = polys
    ds = TpuDataStore()
    ds.create_schema("osm", "kind:String:index=true,*geom:Polygon;"
                            "geomesa.index.profile=lean")
    for lo in range(0, len(cx), 10_000):
        ds.write("osm", {"kind": kind[lo:lo + 10_000],
                         "geom": geoms[lo:lo + 10_000]})
    return ds


def test_store_lean_kind_and_indices(poly_store, polys):
    st = poly_store._store("osm")
    assert st.lean and st.lean_kind == "xz2"
    assert st.query_indices == {"xz2", "id", "attr"}
    assert isinstance(st.index("xz2"), LeanXZ2Index)
    with pytest.raises(ValueError, match="xz2/id only"):
        st.index("z3")


def test_store_intersects_oracle_exact(poly_store, polys):
    cx, cy, w, *_ = polys
    box = (-80.0, 30.0, -60.0, 50.0)
    q = ("INTERSECTS(geom, POLYGON((-80 30, -60 30, -60 50, -80 50, "
         "-80 30)))")
    r = poly_store.query_result("osm", q)
    assert r.strategy.index == "xz2"
    np.testing.assert_array_equal(np.sort(r.positions),
                                  _box_oracle(cx, cy, w, box))


def test_store_bbox_and_attr_and_id(poly_store, polys):
    cx, cy, w, _, kind = polys
    r = poly_store.query_result("osm", "BBOX(geom, 0, 0, 20, 20)")
    np.testing.assert_array_equal(
        np.sort(r.positions), _box_oracle(cx, cy, w, (0, 0, 20, 20)))
    r2 = poly_store.query_result("osm", "kind = 'park'")
    assert r2.strategy.index == "attr:kind"
    np.testing.assert_array_equal(np.sort(r2.positions),
                                  np.flatnonzero(kind == "park"))
    one = poly_store.query_result("osm", "IN ('17')")
    assert list(one.positions) == [17]


def test_store_deletes_and_snapshot_roundtrip(tmp_path, polys):
    cx, cy, w, geoms, kind = polys
    n = 20_000
    ds = TpuDataStore(str(tmp_path))
    ds.create_schema("osm", "kind:String:index=true,*geom:Polygon;"
                            "geomesa.index.profile=lean")
    ds.write("osm", {"kind": kind[:n], "geom": geoms[:n]})
    box = (-80.0, 30.0, -60.0, 50.0)
    q = ("INTERSECTS(geom, POLYGON((-80 30, -60 30, -60 50, -80 50, "
         "-80 30)))")
    want = _box_oracle(cx[:n], cy[:n], w[:n], box)
    assert ds.delete("osm", [str(i) for i in want[:3]]) == 3
    ds.flush("osm")
    ds.persist_stats("osm")
    ds2 = TpuDataStore(str(tmp_path))
    r = ds2.query_result("osm", q)
    np.testing.assert_array_equal(np.sort(r.positions), want[3:])
    # post-reload writes keep column agreement (bbox reconstructed)
    ds2.write("osm", {"kind": kind[:100], "geom": geoms[:100]})
    assert ds2.get_count("osm") == n - 3 + 100


def test_sharded_lean_xz2_matches_single_chip(polys):
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.attr_lean import ShardedLeanXZ2Index

    cx, cy, w, geoms, kind = polys
    n = 20_000
    spec = ("kind:String:index=true,*geom:Polygon;"
            "geomesa.index.profile=lean")
    dsm = TpuDataStore(mesh=device_mesh())
    dsm.create_schema("osm", spec)
    plain = TpuDataStore()
    plain.create_schema("osm", spec)
    for lo in range(0, n, 10_000):
        chunk = {"kind": kind[lo:lo + 10_000],
                 "geom": geoms[lo:lo + 10_000]}
        dsm.write("osm", chunk)
        plain.write("osm", chunk)
    st = dsm._store("osm")
    assert isinstance(st.index("xz2"), ShardedLeanXZ2Index)
    for q in ("INTERSECTS(geom, POLYGON((-80 30, -60 30, -60 50, "
              "-80 50, -80 30)))",
              "BBOX(geom, 0, 0, 20, 20)",
              "kind = 'park'"):
        a = dsm.query_result("osm", q)
        b = plain.query_result("osm", q)
        np.testing.assert_array_equal(np.sort(a.positions),
                                      np.sort(b.positions))


class TestLeanXZ3:
    """Polygons WITH TIME at the lean tier: (bin, code) keys on the
    attribute core (XZ3IndexKeySpace.scala's [2B bin][8B code])."""

    def _store(self, mesh=None):
        rng = np.random.default_rng(37)
        n = 30_000
        cx = rng.uniform(-170, 170, n)
        cy = rng.uniform(-80, 80, n)
        w = rng.uniform(0.001, 0.05, n)
        t = rng.integers(MS, MS + 14 * 86_400_000, n)
        geoms = [Polygon([(a - d, b - d), (a + d, b - d),
                          (a + d, b + d), (a - d, b + d)])
                 for a, b, d in zip(cx, cy, w)]
        ds = TpuDataStore(mesh=mesh)
        ds.create_schema("osm", "kind:String:index=true,dtg:Date,"
                                "*geom:Polygon;"
                                "geomesa.index.profile=lean")
        kind = rng.choice(np.array(["a", "b", "rare"], object), n,
                          p=[.6, .39, .01])
        for lo in range(0, n, 10_000):
            ds.write("osm", {"kind": kind[lo:lo + 10_000],
                             "dtg": t[lo:lo + 10_000],
                             "geom": geoms[lo:lo + 10_000]})
        return ds, cx, cy, w, t, kind

    def test_kind_and_spatiotemporal_oracle(self):
        from geomesa_tpu.index.xz2_lean import LeanXZ3Index
        ds, cx, cy, w, t, kind = self._store()
        st = ds._store("osm")
        assert st.lean_kind == "xz3"
        assert st.query_indices == {"xz3", "id", "attr"}
        assert isinstance(st.index("xz3"), LeanXZ3Index)
        lo, hi = MS + 2 * 86_400_000, MS + 9 * 86_400_000
        q = ("INTERSECTS(geom, POLYGON((-80 30, -60 30, -60 50, "
             "-80 50, -80 30))) AND dtg DURING "
             "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
        r = ds.query_result("osm", q)
        assert r.strategy.index == "xz3"
        want = np.flatnonzero((cx + w >= -80) & (cx - w <= -60)
                              & (cy + w >= 30) & (cy - w <= 50)
                              & (t >= lo) & (t <= hi))
        np.testing.assert_array_equal(np.sort(r.positions), want)

    def test_spatial_only_open_interval_fallback(self):
        ds, cx, cy, w, t, kind = self._store()
        r = ds.query_result("osm", "BBOX(geom, 0, 0, 20, 20)")
        assert r.strategy.index == "xz3"
        want = np.flatnonzero((cx + w >= 0) & (cx - w <= 20)
                              & (cy + w >= 0) & (cy - w <= 20))
        np.testing.assert_array_equal(np.sort(r.positions), want)

    def test_temporal_only(self):
        ds, cx, cy, w, t, kind = self._store()
        r = ds.query_result(
            "osm", "dtg DURING 2018-01-02T00:00:00Z/"
                   "2018-01-04T00:00:00Z")
        lo, hi = MS + 86_400_000, MS + 3 * 86_400_000
        want = np.flatnonzero((t >= lo) & (t <= hi))
        np.testing.assert_array_equal(np.sort(r.positions), want)

    def test_attr_tier_composes(self):
        ds, cx, cy, w, t, kind = self._store()
        r = ds.query_result("osm", "kind = 'rare'")
        assert r.strategy.index == "attr:kind"
        np.testing.assert_array_equal(np.sort(r.positions),
                                      np.flatnonzero(kind == "rare"))

    def test_mesh_variant_matches(self):
        from geomesa_tpu.parallel import device_mesh
        from geomesa_tpu.parallel.attr_lean import ShardedLeanXZ3Index
        dsm, cx, cy, w, t, kind = self._store(mesh=device_mesh())
        st = dsm._store("osm")
        assert isinstance(st.index("xz3"), ShardedLeanXZ3Index)
        lo, hi = MS + 2 * 86_400_000, MS + 9 * 86_400_000
        q = ("INTERSECTS(geom, POLYGON((-80 30, -60 30, -60 50, "
             "-80 50, -80 30))) AND dtg DURING "
             "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
        r = dsm.query_result("osm", q)
        want = np.flatnonzero((cx + w >= -80) & (cx - w <= -60)
                              & (cy + w >= 30) & (cy - w <= 50)
                              & (t >= lo) & (t <= hi))
        np.testing.assert_array_equal(np.sort(r.positions), want)


def test_fullfat_polygon_temporal_only_fixed():
    """Pre-existing planner bug (review r5): a temporal-only query on a
    full-fat polygon schema chose xz3 with NO geometry and silently
    returned zero hits."""
    ds = TpuDataStore()
    ds.create_schema("p", "dtg:Date,*geom:Polygon")
    ds.write("p", {"dtg": np.array([MS, MS + 86_400_000 * 5]),
                   "geom": [Polygon([(0, 0), (1, 0), (1, 1), (0, 1)]),
                            Polygon([(2, 2), (3, 2), (3, 3),
                                     (2, 3)])]})
    r = ds.query_result(
        "p", "dtg DURING 2018-01-01T00:00:00Z/2018-01-02T00:00:00Z")
    assert list(r.positions) == [0]


def test_fullfat_xz3_only_schema_spatial_fallback():
    """A full-fat polygon schema restricted to xz3 (xz2 disabled) still
    answers pure-spatial queries: the strategy falls back to xz3 with
    an open interval, which the index clamps to the data extent
    (review r5 — this used to crash in _time_windows_by_bin)."""
    ds = TpuDataStore()
    ds.create_schema("p", "dtg:Date,*geom:Polygon;"
                          "geomesa.indices.enabled=xz3,id")
    ds.write("p", {"dtg": np.array([MS, MS + 86_400_000]),
                   "geom": [Polygon([(0, 0), (1, 0), (1, 1), (0, 1)]),
                            Polygon([(5, 5), (6, 5), (6, 6), (5, 6)])]})
    r = ds.query_result("p", "BBOX(geom, -1, -1, 2, 2)")
    assert r.strategy.index == "xz3"
    assert list(r.positions) == [0]
