"""Schema spec parsing (reference: SimpleFeatureTypes spec strings)."""

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, parse_spec
from geomesa_tpu.geometry import Point, Polygon


def test_parse_basic_spec():
    sft = parse_spec(
        "gdelt",
        "name:String,age:Int,weight:Double,dtg:Date,*geom:Point:srid=4326;"
        "geomesa.z3.interval=day,geomesa.xz.precision=10",
    )
    assert sft.name == "gdelt"
    assert sft.attribute_names == ["name", "age", "weight", "dtg", "geom"]
    assert sft.default_geom == "geom"
    assert sft.dtg_field == "dtg"
    assert sft.z3_interval == "day"
    assert sft.xz_precision == 10
    assert sft.is_points
    assert sft.attribute("geom").options["srid"] == "4326"


def test_default_geom_inferred():
    sft = parse_spec("t", "a:String,geom:Polygon,dtg:Date")
    assert sft.default_geom == "geom"
    assert not sft.is_points


def test_spec_roundtrip():
    spec = "name:String,dtg:Date,*geom:Point;geomesa.z3.interval=week"
    sft = parse_spec("t", spec)
    sft2 = parse_spec("t", sft.spec_string())
    assert sft == sft2


def test_indexed_attribute():
    sft = parse_spec("t", "name:String:index=true,dtg:Date,*geom:Point")
    assert sft.attribute("name").indexed
    assert not sft.attribute("dtg").indexed


def test_enabled_indices():
    sft = parse_spec("t", "dtg:Date,*geom:Point;geomesa.indices.enabled='z3,id'")
    assert sft.enabled_indices == ["z3", "id"]


def test_duplicate_names_rejected():
    with pytest.raises(ValueError):
        parse_spec("t", "a:Int,a:String")


def test_batch_from_dict_points():
    sft = parse_spec("t", "name:String,dtg:Date,*geom:Point")
    batch = FeatureBatch.from_dict(
        sft,
        {
            "name": ["a", "b"],
            "dtg": np.array([1000, 2000], dtype=np.int64),
            "geom": (np.array([1.0, 2.0]), np.array([3.0, 4.0])),
        },
    )
    assert len(batch) == 2
    x, y = batch.geom_xy()
    np.testing.assert_array_equal(x, [1.0, 2.0])
    np.testing.assert_array_equal(batch.geom_bbox()[:, 1], [3.0, 4.0])
    sub = batch.take(np.array([1]))
    assert sub.column("name")[0] == "b"
    assert len(batch.concat(sub)) == 3


def test_batch_from_dict_polygons():
    sft = parse_spec("t", "name:String,*geom:Polygon")
    polys = [
        Polygon([[0, 0], [1, 0], [1, 1]]),
        Polygon([[5, 5], [6, 5], [6, 6]]),
    ]
    batch = FeatureBatch.from_dict(sft, {"name": ["a", "b"], "geom": polys})
    assert batch.geoms is not None
    np.testing.assert_allclose(batch.geom_bbox()[1], [5, 5, 6, 6])
