"""Z2 index hit-set equality vs brute-force oracle (incl. multi-bbox OR —
BASELINE config 2 shape)."""

import numpy as np
import pytest

from geomesa_tpu.index import Z2PointIndex


def oracle(x, y, boxes):
    boxes = np.atleast_2d(boxes)
    m = np.zeros(len(x), dtype=bool)
    for b in boxes:
        m |= (x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
    return np.flatnonzero(m)


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(17)
    n = 300_000
    # clustered + uniform mix, world-wide
    xu = rng.uniform(-180, 180, n // 2)
    yu = rng.uniform(-90, 90, n // 2)
    xc = rng.normal(2.35, 0.5, n // 2).clip(-180, 180)   # Paris cluster
    yc = rng.normal(48.85, 0.5, n // 2).clip(-90, 90)
    return np.concatenate([xu, xc]), np.concatenate([yu, yc])


@pytest.fixture(scope="module")
def index(dataset):
    return Z2PointIndex.build(*dataset)


def test_single_bbox(index, dataset):
    x, y = dataset
    box = (2.0, 48.5, 2.7, 49.1)
    np.testing.assert_array_equal(index.query([box]), oracle(x, y, box))


def test_multi_bbox_or(index, dataset):
    x, y = dataset
    boxes = [(2.0, 48.5, 2.7, 49.1), (-123.3, 37.2, -121.7, 38.1),
             (139.0, 35.0, 140.5, 36.2)]
    np.testing.assert_array_equal(index.query(boxes), oracle(x, y, boxes))


def test_overlapping_boxes_no_duplicates(index, dataset):
    x, y = dataset
    boxes = [(2.0, 48.5, 2.7, 49.1), (2.3, 48.7, 3.0, 49.3)]
    got = index.query(boxes)
    assert len(got) == len(np.unique(got))
    np.testing.assert_array_equal(got, oracle(x, y, boxes))


def test_world_query(index, dataset):
    x, y = dataset
    got = index.query([(-180.0, -90.0, 180.0, 90.0)])
    np.testing.assert_array_equal(got, np.arange(len(x)))


def test_empty(index):
    # box with no data (mid-pacific sliver)
    got = index.query([(-179.99, -0.001, -179.98, 0.001)])
    assert isinstance(got, np.ndarray)


def test_antimeridian_edges(index, dataset):
    x, y = dataset
    for box in [(-180.0, -90.0, -179.0, 90.0), (179.0, -90.0, 180.0, 90.0)]:
        np.testing.assert_array_equal(index.query([box]), oracle(x, y, box))


def test_budget_exactness(index, dataset):
    x, y = dataset
    box = (0.0, 40.0, 25.0, 55.0)
    np.testing.assert_array_equal(index.query([box], max_ranges=8),
                                  oracle(x, y, box))
