"""Z2 index hit-set equality vs brute-force oracle (incl. multi-bbox OR —
BASELINE config 2 shape)."""

import numpy as np
import pytest

from geomesa_tpu.index import Z2PointIndex


def oracle(x, y, boxes):
    boxes = np.atleast_2d(boxes)
    m = np.zeros(len(x), dtype=bool)
    for b in boxes:
        m |= (x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
    return np.flatnonzero(m)


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(17)
    n = 300_000
    # clustered + uniform mix, world-wide
    xu = rng.uniform(-180, 180, n // 2)
    yu = rng.uniform(-90, 90, n // 2)
    xc = rng.normal(2.35, 0.5, n // 2).clip(-180, 180)   # Paris cluster
    yc = rng.normal(48.85, 0.5, n // 2).clip(-90, 90)
    return np.concatenate([xu, xc]), np.concatenate([yu, yc])


@pytest.fixture(scope="module")
def index(dataset):
    return Z2PointIndex.build(*dataset)


def test_single_bbox(index, dataset):
    x, y = dataset
    box = (2.0, 48.5, 2.7, 49.1)
    np.testing.assert_array_equal(index.query([box]), oracle(x, y, box))


def test_multi_bbox_or(index, dataset):
    x, y = dataset
    boxes = [(2.0, 48.5, 2.7, 49.1), (-123.3, 37.2, -121.7, 38.1),
             (139.0, 35.0, 140.5, 36.2)]
    np.testing.assert_array_equal(index.query(boxes), oracle(x, y, boxes))


def test_overlapping_boxes_no_duplicates(index, dataset):
    x, y = dataset
    boxes = [(2.0, 48.5, 2.7, 49.1), (2.3, 48.7, 3.0, 49.3)]
    got = index.query(boxes)
    assert len(got) == len(np.unique(got))
    np.testing.assert_array_equal(got, oracle(x, y, boxes))


def test_world_query(index, dataset):
    x, y = dataset
    got = index.query([(-180.0, -90.0, 180.0, 90.0)])
    np.testing.assert_array_equal(got, np.arange(len(x)))


def test_empty(index):
    # box with no data (mid-pacific sliver)
    got = index.query([(-179.99, -0.001, -179.98, 0.001)])
    assert isinstance(got, np.ndarray)


def test_antimeridian_edges(index, dataset):
    x, y = dataset
    for box in [(-180.0, -90.0, -179.0, 90.0), (179.0, -90.0, 180.0, 90.0)]:
        np.testing.assert_array_equal(index.query([box]), oracle(x, y, box))


def test_budget_exactness(index, dataset):
    x, y = dataset
    box = (0.0, 40.0, 25.0, 55.0)
    np.testing.assert_array_equal(index.query([box], max_ranges=8),
                                  oracle(x, y, box))


def test_z2_query_many_matches_singles():
    import numpy as np
    from geomesa_tpu.index import Z2PointIndex
    rng = np.random.default_rng(13)
    n = 20_000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    idx = Z2PointIndex.build(x, y)
    queries = []
    for _ in range(9):
        x0, y0 = rng.uniform(-170, 150), rng.uniform(-80, 60)
        queries.append([(x0, y0, x0 + rng.uniform(1, 20),
                         y0 + rng.uniform(1, 20))])
    batched = idx.query_many(queries)
    for boxes, got in zip(queries, batched):
        np.testing.assert_array_equal(got, idx.query(boxes))
        b = boxes[0]
        brute = np.flatnonzero((x >= b[0]) & (x <= b[2])
                               & (y >= b[1]) & (y <= b[3]))
        np.testing.assert_array_equal(got, brute)


def test_query_windows_untimed_routes_to_z2():
    """Untimed windows scan the z2 index (tight ranges) — and mixed
    timed/untimed batches merge back in order."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    MS = 1514764800000
    rng = np.random.default_rng(2)
    n = 5000
    ds = TpuDataStore()
    ds.create_schema("w", "v:Int,dtg:Date,*geom:Point")
    x = rng.uniform(-10, 10, n)
    y = rng.uniform(40, 50, n)
    t = rng.integers(MS, MS + 7 * 86_400_000, n)
    ds.write("w", {"v": np.arange(n), "dtg": t, "geom": (x, y)})
    windows = [
        ([(-5, 42, 0, 47)], None, None),                       # untimed
        ([(-5, 42, 0, 47)], MS, MS + 2 * 86_400_000),          # timed
        ([(2, 44, 4, 46)], None, None),                        # untimed
    ]
    hits = ds.query_windows("w", windows)
    b0 = np.flatnonzero((x >= -5) & (x <= 0) & (y >= 42) & (y <= 47))
    np.testing.assert_array_equal(hits[0], b0)
    b1 = np.flatnonzero((x >= -5) & (x <= 0) & (y >= 42) & (y <= 47)
                        & (t >= MS) & (t <= MS + 2 * 86_400_000))
    np.testing.assert_array_equal(hits[1], b1)
    b2 = np.flatnonzero((x >= 2) & (x <= 4) & (y >= 44) & (y <= 46))
    np.testing.assert_array_equal(hits[2], b2)


def test_density_world_matches_grid_histogram():
    """z-prefix boundary histogram == the masked scatter histogram over
    the world envelope (clamping semantics included)."""
    import jax.numpy as jnp
    from geomesa_tpu.index.z2 import Z2PointIndex
    from geomesa_tpu.ops.density import density_grid

    rng = np.random.default_rng(17)
    n = 80_003
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    # include exact boundary values (clamp into edge cells)
    x[:3] = [-180.0, 180.0, 0.0]
    y[:3] = [-90.0, 90.0, 0.0]
    idx = Z2PointIndex.build(x, y)
    for w, h in [(256, 128), (64, 64), (16, 8)]:
        fast = idx.density_world(w, h)
        ref = np.asarray(density_grid(
            jnp.asarray(x), jnp.asarray(y), jnp.ones(n),
            jnp.ones(n, bool), (-180.0, -90.0, 180.0, 90.0), w, h))
        np.testing.assert_allclose(fast, ref, err_msg=f"{w}x{h}")
    with pytest.raises(ValueError):
        idx.density_world(100, 64)
