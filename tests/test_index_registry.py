"""Index registry + per-schema index configuration + QUERY_INDEX hint
(VERDICT r1 §2.2 partial: index factory/manager — the reference's
GeoMesaFeatureIndexFactory SPI, per-schema geomesa.indices config, and
the forced-index query hint, planning/StrategyDecider.scala:67-79)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql
from geomesa_tpu.index.registry import (
    IndexDescriptor, available_indices, get_index, register_index,
    supported_indices,
)
from geomesa_tpu.planning.planner import Query

MS = 1514764800000
DAY = 86_400_000
N = 5_003


def _store(spec):
    rng = np.random.default_rng(19)
    ds = TpuDataStore()
    ds.create_schema("ev", spec)
    ds.write("ev", {
        "name": rng.choice(["a", "b", "c"], N),
        "dtg": rng.integers(MS, MS + 14 * DAY, N),
        "geom": (rng.uniform(-75.0, -73.0, N), rng.uniform(40.0, 42.0, N)),
    })
    return ds


def test_builtin_registrations():
    assert {"z3", "z2", "xz2", "xz3", "id", "attr"} <= set(
        available_indices())
    with pytest.raises(KeyError):
        get_index("nope")


def test_supported_indices_by_schema():
    from geomesa_tpu.features.feature_type import parse_spec
    pts = parse_spec("a", "name:String,dtg:Date,*geom:Point")
    assert {"z3", "z2", "xz2", "xz3", "id"} <= set(supported_indices(pts))
    nodtg = parse_spec("b", "name:String,*geom:Point")
    sup = supported_indices(nodtg)
    assert "z3" not in sup and "z2" in sup
    polys = parse_spec("c", "dtg:Date,*geom:Polygon")
    sup = supported_indices(polys)
    assert "z2" not in sup and "xz2" in sup and "xz3" in sup


def test_enabled_indices_restrict_planner():
    """A schema restricted to attr+id must not use spatial indexes: the
    bbox query degrades to a full scan, still exact."""
    ds = _store("name:String:index=true,dtg:Date,*geom:Point;"
                "geomesa.indices.enabled='attr,id'")
    ecql = "BBOX(geom, -74.5, 40.5, -73.5, 41.5)"
    r = ds.query_result("ev", ecql)
    assert r.strategy.index == "full"
    want = np.flatnonzero(
        evaluate_filter(parse_ecql(ecql), ds._store("ev").batch))
    np.testing.assert_array_equal(np.sort(r.positions), want)
    # the attribute path still works
    assert ds.query_result("ev", "name = 'a'").strategy.index == "attr:name"
    # direct access to a disabled index raises
    with pytest.raises(ValueError, match="disabled"):
        ds._store("ev").z3_index()


def test_enabled_indices_query_windows_falls_back():
    ds = _store("name:String,dtg:Date,*geom:Point;"
                "geomesa.indices.enabled='xz2,xz3,id'")
    windows = [([(-74.5, 40.5, -73.5, 41.5)], MS, MS + 7 * DAY)]
    hits = ds.query_windows("ev", windows)
    st = ds._store("ev")
    assert "z3" not in st._indexes  # fast path not taken
    x, y = st.batch.geom_xy()
    t = st.batch.column("dtg")
    want = np.flatnonzero(
        (x >= -74.5) & (x <= -73.5) & (y >= 40.5) & (y <= 41.5)
        & (t >= MS) & (t <= MS + 7 * DAY))
    np.testing.assert_array_equal(np.sort(hits[0]), want)


def test_query_index_hint_forces_strategy():
    ds = _store("name:String:index=true,dtg:Date,*geom:Point")
    ecql = ("name = 'a' AND BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg "
            "DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    # unforced: the attribute index wins on cost for a selective equality
    default_idx = ds.query_result("ev", ecql).strategy.index
    q = Query.of(ecql, hints={"QUERY_INDEX": "z3"})
    r = ds.query_result("ev", q)
    assert r.strategy.index == "z3" != default_idx or \
        default_idx == "z3"  # cost model may already pick z3
    want = np.flatnonzero(
        evaluate_filter(parse_ecql(ecql), ds._store("ev").batch))
    np.testing.assert_array_equal(np.sort(r.positions), want)
    # forcing the attribute index works via its prefix name
    r2 = ds.query_result("ev", Query.of(ecql, hints={"QUERY_INDEX": "attr"}))
    assert r2.strategy.index == "attr:name"
    np.testing.assert_array_equal(np.sort(r2.positions), want)
    # an inapplicable hint raises
    with pytest.raises(ValueError, match="QUERY_INDEX"):
        ds.query_result("ev", Query.of("name = 'a'",
                                       hints={"QUERY_INDEX": "xz2"}))


def test_custom_index_registration():
    """Third-party index types plug in by name and build through the
    generic accessor (the SPI role)."""
    class GridCountIndex:
        def __init__(self, counts):
            self.counts = counts

    def build(store):
        x, y = store.batch.geom_xy()
        h, _, _ = np.histogram2d(np.asarray(x), np.asarray(y), bins=8)
        return GridCountIndex(h)

    register_index(IndexDescriptor(
        "grid-count", applicable=lambda sft: bool(sft.geom_field),
        build=build))
    try:
        ds = _store("name:String,dtg:Date,*geom:Point")
        idx = ds._store("ev").index("grid-count")
        assert isinstance(idx, GridCountIndex)
        assert idx.counts.sum() == N
        # cached on repeat access
        assert ds._store("ev").index("grid-count") is idx
    finally:
        from geomesa_tpu.index import registry as reg
        reg._REGISTRY.pop("grid-count", None)
