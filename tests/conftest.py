"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding/collective
path (shard_map, psum over the mesh) is exercised without TPU hardware —
the analog of the reference's in-memory `TestGeoMesaDataStore` +
Accumulo MockInstance strategy (SURVEY.md §4): full stack, zero infra.
The env vars must be set before jax initializes its backends.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(574)
