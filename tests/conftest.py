"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding/collective
path (shard_map, psum over the mesh) is exercised without TPU hardware —
the analog of the reference's in-memory `TestGeoMesaDataStore` +
Accumulo MockInstance strategy (SURVEY.md §4): full stack, zero infra.

Two environment quirks handled here:
* ``JAX_PLATFORMS`` is forced (not defaulted) to cpu — the container env
  pins it to the axon TPU platform.
* The axon PJRT plugin is registered by ``sitecustomize`` at interpreter
  start (before this conftest); its client creation *blocks* whenever the
  TPU tunnel is unavailable, and ``xla_bridge.backends()`` initializes
  every registered factory.  Deregistering the factory keeps CPU test
  runs hermetic and immune to tunnel outages.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize imported jax before this file ran, baking jax_platforms from
# the env; update the live config as well as the env var
jax.config.update("jax_platforms", "cpu")

from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# CI-sized attribute-index generations: the production 16M-slot default
# would make every CPU append sort a 16M-slot run per indexed attribute
# (the same sizing discipline as the multihost worker's sharded lean
# generations — ROUND4.md "CI at 10x speed"); rollover/spill paths get
# exercised MORE at this size, not less
from geomesa_tpu.index.attr_lean import LeanAttrIndex  # noqa: E402
from geomesa_tpu.parallel.attr_lean import ShardedLeanAttrIndex  # noqa: E402

LeanAttrIndex.GENERATION_SLOTS = 1 << 16
ShardedLeanAttrIndex.GENERATION_SLOTS = 1 << 13


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(574)


@pytest.fixture(scope="session")
def gm_lint_tree():
    """ONE timed gm-lint full-tree pass shared by every in-process
    analyzer assertion (the zzzz clean-tree gate, the metric-lint
    delegation test) — the pass is pure ast but still ~3 s, so tier-1
    pays it once."""
    import time

    from geomesa_tpu.analysis import analyze
    from geomesa_tpu.analysis.walker import PACKAGE_ROOT

    t0 = time.perf_counter()
    findings = analyze(PACKAGE_ROOT)
    return findings, time.perf_counter() - t0
