"""REST layer tests: exercise the WSGI app without sockets."""

import io
import json

import numpy as np
import pytest

from geomesa_tpu.audit import InMemoryAuditWriter
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.web import WebApp

MS_2018 = 1514764800000


def call(app, method, path, body=None):
    """Invoke the WSGI app directly; returns (status:int, parsed-or-text)."""
    raw = json.dumps(body).encode() if body is not None else b""
    captured = {}

    def start_response(status, headers):
        captured["status"] = int(status.split()[0])
        captured["headers"] = dict(headers)

    qs = ""
    if "?" in path:
        path, qs = path.split("?", 1)
    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": path,
        "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(raw)),
        "wsgi.input": io.BytesIO(raw),
    }
    chunks = app(environ, start_response)
    text = b"".join(chunks).decode()
    ctype = captured["headers"].get("Content-Type", "")
    parsed = json.loads(text) if "json" in ctype and text else text
    return captured["status"], parsed


@pytest.fixture
def app():
    audit = InMemoryAuditWriter()
    ds = TpuDataStore(audit_writer=audit, user="tester")
    ds.create_schema("pts", "name:String:index=true,age:Int,"
                            "dtg:Date,*geom:Point")
    rng = np.random.default_rng(7)
    n = 200
    ds.write("pts", {
        "name": np.asarray([f"n{i % 5}" for i in range(n)], dtype=object),
        "age": rng.integers(0, 90, n),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * 86_400_000, n),
        "geom": (rng.uniform(-10, 10, n), rng.uniform(40, 50, n)),
    })
    return WebApp(ds, audit_writer=audit)


def test_version_and_schemas(app):
    status, body = call(app, "GET", "/api/version")
    assert status == 200 and body["framework"] == "geomesa-tpu"
    status, body = call(app, "GET", "/api/schemas")
    assert status == 200 and body == ["pts"]
    status, body = call(app, "GET", "/api/schemas/pts")
    assert status == 200 and body["dtg"] == "dtg"
    assert any(a["default"] for a in body["attributes"])
    status, body = call(app, "GET", "/api/schemas/nope")
    assert status == 404


def test_schema_create_delete(app):
    status, body = call(app, "POST", "/api/schemas",
                        {"name": "t2", "spec": "a:Int,*geom:Point"})
    assert status == 201 and body["name"] == "t2"
    # duplicate -> 400
    status, _ = call(app, "POST", "/api/schemas",
                     {"name": "t2", "spec": "a:Int,*geom:Point"})
    assert status == 400
    status, _ = call(app, "DELETE", "/api/schemas/t2")
    assert status == 204
    status, body = call(app, "GET", "/api/schemas")
    assert body == ["pts"]


def test_data_query(app):
    status, body = call(app, "GET", "/api/data/pts?cql=BBOX(geom,-10,40,0,50)")
    assert status == 200 and body["type"] == "FeatureCollection"
    assert 0 < len(body["features"]) < 200
    for f in body["features"]:
        x, y = f["geometry"]["coordinates"]
        assert -10 <= x <= 0 and 40 <= y <= 50
    # csv + max
    status, text = call(app, "GET", "/api/data/pts?format=csv&max=5")
    assert status == 200 and len(text.strip().splitlines()) == 6
    status, _ = call(app, "GET", "/api/data/pts?format=nope")
    assert status == 400
    status, _ = call(app, "GET", "/api/data/missing")
    assert status == 404


def test_data_ingest(app):
    fc = {"type": "FeatureCollection", "features": [
        {"type": "Feature", "id": f"new{i}",
         "geometry": {"type": "Point", "coordinates": [100.0 + i, 0.5]},
         "properties": {"name": "added", "age": 33,
                        "dtg": MS_2018}}
        for i in range(3)
    ]}
    status, body = call(app, "POST", "/api/data/pts", fc)
    assert status == 200 and body["ingested"] == 3, body
    status, got = call(app, "GET", "/api/data/pts?cql=name='added'")
    assert len(got["features"]) == 3
    ids = {f["id"] for f in got["features"]}
    assert ids == {"new0", "new1", "new2"}


def test_stats_endpoints(app):
    status, body = call(app, "GET", "/api/stats/pts/count")
    assert status == 200 and body["count"] == 200
    status, body = call(app, "GET",
                        "/api/stats/pts/count?cql=BBOX(geom,-10,40,0,50)")
    assert 0 < body["count"] < 200
    status, body = call(app, "GET", "/api/stats/pts/bounds")
    b = body["bounds"]
    assert -10 <= b["minx"] <= b["maxx"] <= 10
    status, body = call(app, "GET", "/api/stats/pts/minmax?attribute=age")
    assert 0 <= body["bounds"][0] <= body["bounds"][1] < 90
    status, body = call(app, "GET",
                        "/api/stats/pts/histogram?attribute=age&bins=10")
    assert sum(body["counts"]) == 200
    status, body = call(app, "GET", "/api/stats/pts/topk?attribute=name")
    assert status == 200
    status, _ = call(app, "GET", "/api/stats/pts/minmax")
    assert status == 400


def test_audit_and_metrics(app):
    call(app, "GET", "/api/data/pts?cql=BBOX(geom,-10,40,0,50)")
    status, events = call(app, "GET", "/api/audit/pts")
    assert status == 200 and len(events) >= 1
    assert events[-1]["user"] == "tester"
    assert events[-1]["hits"] > 0
    status, snap = call(app, "GET", "/api/metrics")
    assert status == 200 and any(k.startswith("web.") for k in snap)


def test_unknown_route(app):
    status, body = call(app, "GET", "/api/nope")
    assert status == 404


def test_bad_params_return_400(app):
    status, _ = call(app, "GET", "/api/data/pts?max=abc")
    assert status == 400
    status, _ = call(app, "GET", "/api/stats/pts/histogram?attribute=age&bins=x")
    assert status == 400
    status, _ = call(app, "GET", "/api/audit/pts?since=notafloat")
    assert status == 400
    # non-numeric attribute -> 400, unknown attribute -> 404
    status, _ = call(app, "GET", "/api/stats/pts/histogram?attribute=name")
    assert status == 400
    status, _ = call(app, "GET", "/api/stats/pts/histogram?attribute=nope")
    assert status == 404


def test_histogram_respects_visibility():
    """The histogram endpoint must not leak rows the caller cannot see."""
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.security import StaticAuthorizationsProvider

    ds = TpuDataStore(auth_provider=StaticAuthorizationsProvider(["user"]))
    ds.create_schema("v", "age:Int,dtg:Date,*geom:Point")
    ds.write("v", {"age": np.asarray([10, 20]), "dtg": np.asarray([0, 0]),
                   "geom": (np.zeros(2), np.zeros(2))}, visibility="user")
    ds.write("v", {"age": np.asarray([1000]), "dtg": np.asarray([0]),
                   "geom": (np.zeros(1), np.zeros(1))}, visibility="admin")
    app2 = WebApp(ds)
    status, body = call(app2, "GET",
                        "/api/stats/v/histogram?attribute=age&bins=4")
    assert status == 200
    assert sum(body["counts"]) == 2 and body["hi"] <= 20.0


def test_blob_rest_roundtrip(tmp_path):
    from geomesa_tpu.blob import GeoIndexedBlobStore

    bs = GeoIndexedBlobStore(blob_dir=str(tmp_path / "blobs"))
    app2 = WebApp(TpuDataStore(), blob=bs)

    def call_raw(method, path, body=b""):
        captured = {}

        def sr(status, headers):
            captured["status"] = int(status.split()[0])
            captured["ct"] = dict(headers).get("Content-Type")

        qs = ""
        if "?" in path:
            path, qs = path.split("?", 1)
        out = b"".join(app2({
            "REQUEST_METHOD": method, "PATH_INFO": path, "QUERY_STRING": qs,
            "CONTENT_LENGTH": str(len(body)), "wsgi.input": io.BytesIO(body),
        }, sr))
        return captured["status"], out, captured.get("ct")

    s, body, _ = call_raw("POST", "/api/blob?wkt=POINT%20(10%2020)"
                                  "&filename=f.bin&dtg=0", b"\x01payload")
    assert s == 201
    bid = json.loads(body)["id"]
    s, data, ct = call_raw("GET", f"/api/blob/{bid}")
    assert s == 200 and data == b"\x01payload"
    assert ct == "application/octet-stream"
    s, body, _ = call_raw("GET", "/api/blob?cql=BBOX(geom,5,15,15,25)")
    assert json.loads(body)["ids"] == [bid]
    s, _, _ = call_raw("DELETE", f"/api/blob/{bid}")
    assert s == 204
    s, _, _ = call_raw("GET", f"/api/blob/{bid}")
    assert s == 404


def test_attribute_level_visibility():
    from geomesa_tpu.security import StaticAuthorizationsProvider

    ds = TpuDataStore(auth_provider=StaticAuthorizationsProvider(["user"]))
    ds.create_schema("av", "name:String,ssn:String,dtg:Date,*geom:Point")
    ds.write("av", {"name": np.asarray(["a", "b"], dtype=object),
                    "ssn": np.asarray(["111", "222"], dtype=object),
                    "dtg": np.zeros(2, np.int64),
                    "geom": (np.zeros(2), np.zeros(2))},
             attribute_visibilities={"ssn": "admin"})
    got = ds.query("av")
    assert list(got.column("name")) == ["a", "b"]   # row visible
    assert list(got.column("ssn")) == [None, None]  # guarded attr nulled
    # privileged caller sees values
    ds._auth_provider = StaticAuthorizationsProvider(["admin"])
    got = ds.query("av")
    assert list(got.column("ssn")) == ["111", "222"]


def test_wcs_endpoints():
    """WCS-shaped raster serving (the geomesa-accumulo-raster WCS role):
    capabilities, coverage description, and a GetCoverage mosaic in
    PNG and npy formats."""
    from geomesa_tpu.raster import RasterStore
    from geomesa_tpu.web.app import WebApp

    rs = RasterStore("dem")
    rs.put(np.arange(64, dtype=np.float64).reshape(8, 8), (0, 0, 8, 8))
    rs.put(np.ones((8, 8)) * 5.0, (8, 0, 16, 8))
    wapp = WebApp(TpuDataStore(), raster={"dem": rs})

    def raw(path):
        captured = {}

        def sr(status, headers):
            captured["status"] = int(status.split()[0])
            captured["headers"] = dict(headers)

        qs = ""
        if "?" in path:
            path, qs = path.split("?", 1)
        env = {"REQUEST_METHOD": "GET", "PATH_INFO": path,
               "QUERY_STRING": qs, "CONTENT_LENGTH": "0",
               "wsgi.input": io.BytesIO(b"")}
        body = b"".join(wapp(env, sr))
        return captured["status"], captured["headers"], body

    s, h, b = raw("/wcs?request=GetCapabilities")
    assert s == 200 and b"<name>dem</name>" in b
    s, h, b = raw("/wcs?request=DescribeCoverage&coverage=dem")
    assert s == 200 and b"lonLatEnvelope" in b and b"resolutions" in b
    s, h, b = raw("/wcs?request=GetCoverage&coverage=dem&"
                  "bbox=0,0,16,8&width=16&height=8&format=png")
    assert s == 200 and h["Content-Type"] == "image/png"
    assert b.startswith(b"\x89PNG")
    s, h, b = raw("/wcs?request=GetCoverage&coverage=dem&"
                  "bbox=0,0,16,8&width=16&height=8&format=npy")
    assert s == 200
    grid = np.load(io.BytesIO(b))
    assert grid.shape == (8, 16)
    # right half is the constant-5 tile
    np.testing.assert_allclose(grid[:, 8:], 5.0)
    s, _, _ = raw("/wcs?request=GetCoverage&coverage=nope")
    assert s == 404
