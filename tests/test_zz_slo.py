"""SLO plane (ISSUE 20): stage attribution math, window folding and
burn rates, edge-triggered alerts, exemplar joins, the web middleware
feed, strict-400 endpoint hardening, and the obs satellites (span cap,
scrape cache).

Named ``zz`` so the config-mutating runs land late in the suite
ordering, after the correctness suites have exercised clean defaults.
"""

from __future__ import annotations

import io
import itertools
import json
import re

import numpy as np
import pytest

from geomesa_tpu import config
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.metrics import METRIC_NAMESPACES, registry
from geomesa_tpu.obs import SLO_STAGES, Span, Trace, attribute, slo_plane, \
    tracer
from geomesa_tpu.obs.slo import _parse_objectives
from geomesa_tpu.web import WebApp

MS_2018 = 1_514_764_800_000

_SLO_OPTS = ("geomesa.slo.enabled", "geomesa.slo.objectives",
             "geomesa.slo.burn.alert", "geomesa.slo.tenants.max",
             "geomesa.obs.trace.max.spans",
             "geomesa.obs.scrape.min.interval.ms")

_ids = itertools.count(1)


@pytest.fixture(autouse=True)
def _clean_slo_state():
    for n in _SLO_OPTS:
        config.clear_property(n)
    slo_plane.reset()
    yield
    for n in _SLO_OPTS:
        config.clear_property(n)
    slo_plane.reset()


def _mk_span(trace_id, parent_id, name, ms, **attrs):
    sp = Span(trace_id, parent_id, name, dict(attrs))
    sp.duration_ms = float(ms)
    return sp


def _mk_trace(cls="query", root_ms=100.0, root_attrs=None, children=()):
    """Hand-build a finished trace: ``children`` is a list of
    ``(name, ms, parent_key)`` where parent_key is None (child of
    root) or the index of an earlier child."""
    tid = f"slotest{next(_ids):08x}"
    t = Trace(tid)
    root = _mk_span(tid, None, cls, root_ms, **(root_attrs or {}))
    t.root_span = root
    made: list[Span] = []
    for name, ms, parent_key in children:
        pid = (root.span_id if parent_key is None
               else made[parent_key].span_id)
        made.append(_mk_span(tid, pid, name, ms))
    # finish order: children first, root last (span() appends on exit)
    t.spans = made + [root]
    return t


def call(app, method, path, body=None, headers=None):
    raw = json.dumps(body).encode() if body is not None else b""
    captured = {}

    def start_response(status, hdrs):
        captured["status"] = int(status.split()[0])
        captured["headers"] = dict(hdrs)

    qs = ""
    if "?" in path:
        path, qs = path.split("?", 1)
    environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
               "QUERY_STRING": qs, "CONTENT_LENGTH": str(len(raw)),
               "wsgi.input": io.BytesIO(raw)}
    environ.update(headers or {})
    chunks = app(environ, start_response)
    text = b"".join(chunks).decode()
    ctype = captured["headers"].get("Content-Type", "")
    parsed = json.loads(text) if "json" in ctype and text else text
    return captured["status"], parsed


@pytest.fixture
def app():
    ds = TpuDataStore(user="slo-tester")
    ds.create_schema("pts", "name:String,dtg:Date,*geom:Point")
    rng = np.random.default_rng(9)
    n = 100
    ds.write("pts", {
        "name": np.asarray([f"n{i % 4}" for i in range(n)], dtype=object),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * 86_400_000, n),
        "geom": (rng.uniform(-10, 10, n), rng.uniform(40, 50, n)),
    })
    return WebApp(ds)


# -- attribution math ------------------------------------------------------

def test_attribution_exclusive_time_and_residual():
    t = _mk_trace(root_ms=100.0, children=[
        ("query.plan", 10.0, None),
        ("query.materialize", 40.0, None),
        ("query.scan.device", 30.0, 1),   # nested under materialize
    ])
    att = attribute(t)
    assert att is not None and att["class"] == "query"
    st = att["stages"]
    assert set(st) == set(SLO_STAGES)
    assert st["plan"] == pytest.approx(10.0)
    # materialize bills only its EXCLUSIVE 10ms; the wrapped device
    # dispatch keeps its 30ms — no double-billing
    assert st["materialize"] == pytest.approx(10.0)
    assert st["device_scan"] == pytest.approx(30.0)
    assert st["unattributed"] == pytest.approx(50.0)
    # in-root stages + residual always reconstruct the root wall
    in_root = sum(ms for s, ms in st.items()
                  if s not in ("queue", "web_drain"))
    assert in_root == pytest.approx(att["root_ms"])


def test_attribution_out_of_root_queue_rides_token_attr():
    t = _mk_trace(root_ms=50.0,
                  root_attrs={"admission.queue_ms": 7.5},
                  children=[("query.plan", 50.0, None)])
    att = attribute(t)
    assert att["stages"]["queue"] == pytest.approx(7.5)
    # queue time is OUTSIDE the root wall: total grows, residual not
    assert att["total_ms"] == pytest.approx(57.5)
    assert att["stages"]["unattributed"] == pytest.approx(0.0)


def test_attribution_rider_vs_leader_dispatch():
    # rider: no serving.fuse span — the stamped attribute is the only
    # record of the batch the leader ran on its behalf
    rider = _mk_trace(root_ms=20.0, root_attrs={
        "coalesce.ms": 5.0, "fused.dispatch.ms": 12.0})
    att = attribute(rider)
    assert att["stages"]["coalesce"] == pytest.approx(5.0)
    assert att["stages"]["device_scan"] == pytest.approx(12.0)
    # leader: the fuse span IS in its trace — counting the attribute
    # too would double-bill the dispatch
    leader = _mk_trace(root_ms=20.0, root_attrs={
        "coalesce.ms": 1.0, "fused.dispatch.ms": 12.0},
        children=[("serving.fuse", 12.0, None)])
    att = attribute(leader)
    assert att["stages"]["device_scan"] == pytest.approx(12.0)


def test_attribution_error_flag_and_no_root():
    t = _mk_trace(root_attrs={"error": "ValueError"})
    assert attribute(t)["error"] is True
    empty = Trace("noroot")
    assert attribute(empty) is None


def test_parse_objectives_handles_dotted_class_and_garbage():
    objs = _parse_objectives(
        "query:250:0.99, tile.render:250:0.999, bogus, a:b:c,")
    assert set(objs) == {"query", "tile.render"}
    assert objs["tile.render"].latency_ms == 250.0
    assert objs["tile.render"].target == pytest.approx(0.999)


# -- plane ingestion / burn / alerts ---------------------------------------

def test_finish_hook_folds_registry_and_windows():
    config.set_property("geomesa.slo.objectives", "query:100:0.9")
    req0 = registry.counter("slo.query.requests").count
    ten0 = registry.counter("slo.tenant.acme_co.requests").count
    t = _mk_trace(root_ms=250.0,
                  root_attrs={"tenant": "acme co"},   # sanitized label
                  children=[("query.plan", 250.0, None)])
    slo_plane.on_trace_finish(t, retained=False)
    assert registry.counter("slo.query.requests").count == req0 + 1
    assert registry.counter("slo.tenant.acme_co.requests").count == ten0 + 1
    # 250ms > the 100ms objective: the request burns budget
    assert slo_plane.burn("query", 300.0) == pytest.approx(
        1.0 / (1.0 - 0.9))
    # class without an objective is ignored entirely
    other0 = registry.counter("slo.nope.requests").count
    slo_plane.on_trace_finish(_mk_trace(cls="nope"), retained=False)
    assert registry.counter("slo.nope.requests").count == other0


def test_burn_math_mixed_good_bad():
    config.set_property("geomesa.slo.objectives", "query:100:0.9")
    for ms in (50.0, 50.0, 50.0, 200.0):   # 1 bad of 4
        slo_plane.on_trace_finish(_mk_trace(root_ms=ms), retained=False)
    # bad_fraction 0.25 over budget 0.1 -> burn 2.5 in BOTH windows
    assert slo_plane.burn("query", 300.0) == pytest.approx(2.5)
    assert slo_plane.burn("query", 3600.0) == pytest.approx(2.5)


def test_alert_edge_trigger_and_rearm():
    config.set_property("geomesa.slo.objectives", "query:100:0.9")
    config.set_property("geomesa.slo.burn.alert", 1.0)
    fired0 = registry.counter("alert.slo.fired").count
    for _ in range(3):   # all bad -> burn 10 > 1 in both windows
        slo_plane.on_trace_finish(_mk_trace(root_ms=500.0),
                                  retained=False)
    assert registry.counter("alert.slo.fired").count == fired0 + 1
    alerts = slo_plane.alerts()
    assert alerts and alerts[0]["class"] == "query"
    assert alerts[0]["burn_short"] > 1.0
    # still burning: edge-triggered, no refire
    slo_plane.on_trace_finish(_mk_trace(root_ms=500.0), retained=False)
    assert registry.counter("alert.slo.fired").count == fired0 + 1
    # short window drops under a raised threshold -> re-arms ...
    config.set_property("geomesa.slo.burn.alert", 1000.0)
    slo_plane.on_trace_finish(_mk_trace(root_ms=500.0), retained=False)
    # ... and the next crossing fires a SECOND alert
    config.set_property("geomesa.slo.burn.alert", 1.0)
    slo_plane.on_trace_finish(_mk_trace(root_ms=500.0), retained=False)
    assert registry.counter("alert.slo.fired").count == fired0 + 2
    assert len(slo_plane.alerts(cls="query")) == 2


def test_tenant_label_bound_overflows_to_other():
    config.set_property("geomesa.slo.objectives", "query:100:0.9")
    config.set_property("geomesa.slo.tenants.max", 2)
    for t in ("alpha", "beta", "gamma", "delta"):
        slo_plane.on_trace_finish(
            _mk_trace(root_ms=10.0, root_attrs={"tenant": t}),
            retained=False)
    assert slo_plane._tenants == {"alpha", "beta"}
    assert registry.counter("slo.tenant.other.requests").count >= 2


def test_exemplar_only_for_retained_traces():
    config.set_property("geomesa.slo.objectives", "query:100:0.9")
    slo_plane.on_trace_finish(_mk_trace(root_ms=40.0), retained=False)
    assert slo_plane._exemplars["query"].exemplars() == []
    kept = _mk_trace(root_ms=40.0)
    slo_plane.on_trace_finish(kept, retained=True)
    ex = slo_plane._exemplars["query"].exemplars()
    assert ex and ex[0]["trace_id"] == kept.trace_id
    # and the rendered OpenMetrics line carries the join key
    expo = slo_plane.exposition()
    assert f'# {{trace_id="{kept.trace_id}"}}' in expo
    assert "geomesa_slo_query_latency_ms_bucket" in expo
    assert 'le="+Inf"' in expo


def test_slo_disabled_is_inert():
    config.set_property("geomesa.slo.enabled", False)
    req0 = registry.counter("slo.query.requests").count
    slo_plane.on_trace_finish(_mk_trace(root_ms=500.0), retained=True)
    assert registry.counter("slo.query.requests").count == req0
    assert slo_plane.exposition() == ""


# -- end-to-end: real traces through the tracer ----------------------------

def test_real_query_trace_attributes_and_report(app):
    status, _ = call(app, "GET",
                     "/api/data/pts?cql=BBOX(geom,-10,40,10,50)",
                     headers={"HTTP_X_TENANT": "acme"})
    assert status == 200
    rep = slo_plane.report()
    assert rep["enabled"] is True
    q = rep["classes"]["query"]
    assert q["objective"]["latency_ms"] == 250.0
    # the ledger covered SOME of the root wall on a real query
    snap = registry.snapshot()
    assert snap.get("slo.query.requests", {}).get("count", 0) >= 1
    stage_keys = [k for k in snap if k.startswith("slo.query.stage.")]
    assert stage_keys, "no stage timers recorded for a real query"
    # the web middleware fed the endpoint RED family too
    assert snap.get("slo.web.data.requests", {}).get("count", 0) >= 1


def test_exemplar_joins_metrics_prom_to_traces(app):
    status, _ = call(app, "GET",
                     "/api/data/pts?cql=BBOX(geom,-10,40,10,50)")
    assert status == 200
    status, body = call(app, "GET", "/metrics.prom")
    assert status == 200
    assert "geomesa_slo_query_burn_5m" in body
    assert "geomesa_slo_query_burn_1h" in body
    ids = re.findall(
        r'geomesa_slo_query_latency_ms_bucket\{le="[^"]+"\} \d+ '
        r'# \{trace_id="([0-9a-f]+)"\}', body)
    assert ids, "no parseable exemplar in the exposition"
    resolved = [i for i in ids if tracer.find(i) is not None]
    assert resolved, "no exemplar trace_id resolves in the tracer"
    status, tr = call(app, "GET", f"/traces/{resolved[0]}")
    assert status == 200 and tr["trace_id"] == resolved[0]


# -- endpoint hardening ----------------------------------------------------

def test_debug_slo_endpoint(app):
    status, body = call(app, "GET", "/debug/slo")
    assert status == 200
    assert "classes" in body and "alerts_active" in body
    status, _ = call(app, "POST", "/debug/slo")
    assert status == 405


def test_debug_alerts_strict_400s(app):
    status, body = call(app, "GET", "/debug/alerts")
    assert status == 200 and body == {"alerts": []}
    status, _ = call(app, "GET", "/debug/alerts?limit=0")
    assert status == 200
    status, _ = call(app, "GET", "/debug/alerts?limit=-1")
    assert status == 400
    status, _ = call(app, "GET", "/debug/alerts?limit=zap")
    assert status == 400
    status, body = call(app, "GET", "/debug/alerts?class=bogus")
    assert status == 400 and "bogus" in body["error"]
    status, _ = call(app, "GET", "/debug/alerts?class=query")
    assert status == 200
    status, _ = call(app, "POST", "/debug/alerts")
    assert status == 405


def test_traces_schema_filter(app):
    status, _ = call(app, "GET",
                     "/api/data/pts?cql=BBOX(geom,-10,40,10,50)")
    assert status == 200
    status, body = call(app, "GET", "/traces?schema=pts")
    assert status == 200 and body
    assert all(t["attributes"].get("schema") == "pts" for t in body)
    status, body = call(app, "GET", "/traces?schema=nope")
    assert status == 200 and body == []
    status, _ = call(app, "GET", "/traces?schema=")
    assert status == 400


# -- obs satellites --------------------------------------------------------

def test_trace_span_cap_drops_and_counts():
    config.set_property("geomesa.obs.trace.max.spans", 2)
    d0 = registry.counter("obs.trace.spans.dropped").count
    with tracer.span("query", schema="cap") as root:
        for _ in range(4):
            with tracer.span("query.plan"):
                pass
    assert registry.counter("obs.trace.spans.dropped").count == d0 + 2
    assert root.attributes.get("spans.dropped") == 2


def test_scrape_cache_serves_identical_body(app):
    config.set_property("geomesa.obs.scrape.min.interval.ms", 60_000.0)
    c0 = registry.counter("obs.scrape.cached").count
    status, first = call(app, "GET", "/metrics.prom")
    assert status == 200
    status, second = call(app, "GET", "/metrics.prom")
    assert status == 200
    assert second == first            # byte-identical cached body
    assert registry.counter("obs.scrape.cached").count == c0 + 1
    # the scrape self-timer recorded the RENDERED scrape only
    assert registry.timer("obs.scrape.ms").count >= 1


def test_slo_namespaces_registered():
    assert "slo" in METRIC_NAMESPACES
    assert "alert" in METRIC_NAMESPACES
