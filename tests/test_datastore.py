"""End-to-end datastore tests against brute-force oracles — the analog of
the reference's TestGeoMesaDataStore-based suite (full planner/keyspace/
filter stack, zero infra; SURVEY.md §4)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.features import FeatureBatch
from geomesa_tpu.filters import evaluate_filter, parse_ecql
from geomesa_tpu.geometry import Polygon
from geomesa_tpu.planning.planner import Query

MS_2018 = 1514764800000
N = 50_000


@pytest.fixture(scope="module")
def store(rng_mod):
    rng = rng_mod
    ds = TpuDataStore()
    ds.create_schema(
        "events",
        "name:String:index=true,score:Double,dtg:Date,*geom:Point;"
        "geomesa.z3.interval=week",
    )
    ds.write("events", {
        "name": rng.choice(["alpha", "beta", "gamma", "delta"], N),
        "score": rng.uniform(0, 100, N),
        "dtg": rng.integers(MS_2018, MS_2018 + 21 * 86_400_000, N),
        "geom": (rng.uniform(-75.0, -73.0, N), rng.uniform(40.0, 42.0, N)),
    })
    return ds


@pytest.fixture(scope="module")
def rng_mod():
    return np.random.default_rng(1234)


def oracle(store, ecql):
    st = store._store("events")
    return np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))


QUERIES = [
    # z3 path
    "BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z",
    # z2 path (no time)
    "BBOX(geom, -74.2, 40.8, -73.9, 41.1)",
    # attribute path
    "name = 'alpha'",
    # attribute + residual
    "name = 'beta' AND score > 90",
    # temporal only (z3 whole-world)
    "dtg DURING 2018-01-05T00:00:00Z/2018-01-06T00:00:00Z",
    # OR of boxes
    "BBOX(geom, -74.9, 40.1, -74.6, 40.4) OR BBOX(geom, -73.4, 41.6, -73.1, 41.9)",
    # full scan (unindexed attribute predicate)
    "score < 1.5",
    # intersects polygon + time
    "INTERSECTS(geom, POLYGON ((-74.5 40.5, -74 40.5, -74 41.5, -74.5 41.5, -74.5 40.5))) AND dtg AFTER 2018-01-10T00:00:00Z",
]


@pytest.mark.parametrize("ecql", QUERIES)
def test_query_matches_oracle(store, ecql):
    got = store.query_result("events", ecql)
    np.testing.assert_array_equal(np.sort(got.positions), oracle(store, ecql))


def test_strategy_selection(store):
    r = store.query_result(
        "events",
        "BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND "
        "dtg DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    assert r.strategy.index == "z3"
    r = store.query_result("events", "BBOX(geom, -74.2, 40.8, -73.9, 41.1)")
    assert r.strategy.index == "z2"
    r = store.query_result("events", "name = 'alpha'")
    assert r.strategy.index == "attr:name"
    r = store.query_result("events", "IN ('17', '23', '99999999')")
    assert r.strategy.index == "id"
    np.testing.assert_array_equal(r.positions, [17, 23])
    r = store.query_result("events", "score < 1.5")
    assert r.strategy.index == "full"


def test_sort_and_limit(store):
    q = Query.of("name = 'alpha'", sort_by="score", sort_desc=True,
                 max_features=10)
    batch = store.query("events", q)
    assert len(batch) == 10
    scores = batch.column("score")
    assert np.all(np.diff(scores) <= 0)


def test_projection(store):
    q = Query.of("name = 'gamma'", properties=["name", "geom"])
    batch = store.query("events", q)
    assert set(batch.columns) == {"name", "geom_x", "geom_y"}


def test_counts_and_bounds(store):
    assert store.get_count("events") == N
    env = store.get_bounds("events")
    assert -75.0 <= env.xmin <= -74.9 and 41.9 <= env.ymax <= 42.0
    lo, hi = store.get_attribute_bounds("events", "score")
    assert 0 <= lo < 1 and 99 < hi <= 100


def test_explain(store):
    text = store.explain(
        "events", "BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND "
        "dtg DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    assert "Strategy selection" in text
    assert "chosen: z3" in text
    assert "hits" in text


def test_exclude(store):
    assert len(store.query("events", "EXCLUDE")) == 0


def test_polygon_schema_xz2(rng_mod):
    rng = rng_mod
    ds = TpuDataStore()
    ds.create_schema("buildings", "kind:String,*geom:Polygon")
    n = 5000
    cx, cy = rng.uniform(-10, 10, n), rng.uniform(40, 50, n)
    polys = [Polygon([[x - .05, y - .05], [x + .05, y - .05],
                      [x + .05, y + .05], [x - .05, y + .05]])
             for x, y in zip(cx, cy)]
    batch = FeatureBatch.from_dict(ds.get_schema("buildings"),
                                   {"kind": ["b"] * n, "geom": polys})
    ds.write("buildings", batch)
    ecql = "INTERSECTS(geom, POLYGON ((0 44, 3 44, 3 46, 0 46, 0 44)))"
    r = ds.query_result("buildings", ecql)
    assert r.strategy.index == "xz2"
    st = ds._store("buildings")
    expected = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(r.positions), expected)
    assert len(expected) > 0


def test_polygon_schema_xz3(rng_mod):
    rng = rng_mod
    ds = TpuDataStore()
    ds.create_schema("tracks", "kind:String,dtg:Date,*geom:Polygon")
    n = 4000
    cx, cy = rng.uniform(-10, 10, n), rng.uniform(40, 50, n)
    polys = [Polygon([[x - .05, y - .05], [x + .05, y - .05],
                      [x + .05, y + .05], [x - .05, y + .05]])
             for x, y in zip(cx, cy)]
    dtg = rng.integers(MS_2018, MS_2018 + 10 * 86_400_000, n)
    batch = FeatureBatch.from_dict(
        ds.get_schema("tracks"),
        {"kind": ["t"] * n, "dtg": dtg, "geom": polys})
    ds.write("tracks", batch)
    ecql = ("INTERSECTS(geom, POLYGON ((0 44, 3 44, 3 46, 0 46, 0 44))) AND "
            "dtg DURING 2018-01-02T00:00:00Z/2018-01-05T00:00:00Z")
    r = ds.query_result("tracks", ecql)
    assert r.strategy.index == "xz3"
    st = ds._store("tracks")
    expected = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(r.positions), expected)
    assert len(expected) > 0


def test_catalog_persistence(tmp_path):
    ds = TpuDataStore(str(tmp_path))
    ds.create_schema("s1", "a:Int,dtg:Date,*geom:Point")
    ds.write("s1", {"a": [1], "dtg": [MS_2018], "geom": (np.r_[0.0], np.r_[0.0])})
    ds.persist_stats("s1")
    ds2 = TpuDataStore(str(tmp_path))
    assert ds2.type_names == ["s1"]
    assert ds2.get_schema("s1").dtg_field == "dtg"
    ds2.load_stats("s1")
    assert ds2._store("s1")._stats["count"].count == 1


def test_schema_lifecycle():
    ds = TpuDataStore()
    ds.create_schema("a", "x:Int,*geom:Point")
    with pytest.raises(ValueError):
        ds.create_schema("a", "x:Int,*geom:Point")
    ds.remove_schema("a")
    assert ds.type_names == []


def test_catalog_version_handshake(tmp_path):
    """A catalog from a NEWER framework version refuses to open (the
    distributed version-mismatch check, GeoMesaDataStore.scala:433-500)."""
    from geomesa_tpu.datastore import CatalogVersionError

    d = str(tmp_path / "cat")
    ds = TpuDataStore(d)
    ds.create_schema("t", "v:Int,*geom:Point")
    # same version reopens fine
    assert TpuDataStore(d).type_names == ["t"]
    with open(f"{d}/catalog.version", "w") as f:
        f.write("999")
    with pytest.raises(CatalogVersionError):
        TpuDataStore(d)


def test_catalog_schema_lock(tmp_path):
    """Schema mutations take the catalog file lock (multi-process safety);
    nested use must not deadlock."""
    d = str(tmp_path / "cat")
    ds = TpuDataStore(d)
    ds.create_schema("a", "v:Int,*geom:Point")
    ds.remove_schema("a")
    ds.create_schema("a", "v:Int,*geom:Point")
    assert ds.type_names == ["a"]


def test_back_compat_catalog_fixture():
    """Frozen v1 catalog (tests/data/catalog_v1, written 2026-07) must
    keep loading and answering queries in future versions — the
    reference's BackCompatibilityTest pattern (replaying old serialized
    data against current code)."""
    import os
    d = os.path.join(os.path.dirname(__file__), "data", "catalog_v1")
    ds = TpuDataStore(d)
    assert ds.type_names == ["legacy"]
    assert ds.get_count("legacy") == 500
    got = ds.query("legacy", "BBOX(geom, -10, 40, 0, 50) AND name = 'n1'")
    x, _ = got.geom_xy()
    assert len(got) > 0 and (x <= 0).all()
    assert set(got.column("name")) == {"n1"}


def test_update_schema_rename_moves_catalog_files(tmp_path):
    """Renaming a schema must move its persisted artifacts: a reload
    must see only the new name, with the data intact."""
    d = str(tmp_path / "cat")
    ds = TpuDataStore(d)
    ds.create_schema("old", "v:Int,dtg:Date,*geom:Point")
    ds.write("old", {"v": np.arange(5), "dtg": np.zeros(5, np.int64),
                     "geom": (np.zeros(5), np.zeros(5))})
    ds.flush("old")
    from geomesa_tpu.features.feature_type import parse_spec
    ds.update_schema("old", parse_spec("new", "v:Int,dtg:Date,*geom:Point"))
    ds2 = TpuDataStore(d)
    assert ds2.type_names == ["new"]
    assert ds2.get_count("new") == 5


def test_z3_fid_strategy_auto_ids():
    """geomesa.fid.strategy=z3 generates z-prefixed UUID auto ids."""
    ds = TpuDataStore()
    ds.create_schema("zf", "v:Int,dtg:Date,*geom:Point;"
                           "geomesa.fid.strategy=z3")
    rng = np.random.default_rng(0)
    n = 50
    ds.write("zf", {"v": np.arange(n),
                    "dtg": rng.integers(1514764800000,
                                        1515364800000, n),
                    "geom": (rng.uniform(-10, 10, n),
                             rng.uniform(40, 50, n))})
    batch = ds.query("zf")
    assert len(set(batch.ids)) == n
    assert all(len(i) == 36 and i[14] == "4" for i in batch.ids)


def test_incremental_write_appends_z3_index():
    """A write after the z3 index exists merges into it (no rebuild) and
    stays oracle-exact."""
    rng = np.random.default_rng(91)
    ds = TpuDataStore()
    ds.create_schema("inc", "name:String,dtg:Date,*geom:Point")
    n0, m = 20_000, 3_000
    x = rng.uniform(-75, -73, n0); y = rng.uniform(40, 42, n0)
    t = rng.integers(MS_2018, MS_2018 + 14 * 86_400_000, n0)
    ds.write("inc", {"name": np.array(["a"] * n0, object), "dtg": t,
                     "geom": (x, y)})
    ecql = ("BBOX(geom,-74.6,40.3,-73.4,41.7) AND dtg DURING "
            "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    _ = ds.query("inc", ecql)  # builds the z3 index
    st = ds._store("inc")
    z3_before = st._indexes.get("z3")
    assert z3_before is not None

    nx = rng.uniform(-75, -73, m); ny = rng.uniform(40, 42, m)
    nt = rng.integers(MS_2018, MS_2018 + 14 * 86_400_000, m)
    ds.write("inc", {"name": np.array(["b"] * m, object), "dtg": nt,
                     "geom": (nx, ny)})
    # same object, incrementally extended — not a rebuild
    assert st._indexes.get("z3") is z3_before
    assert len(z3_before) == n0 + m

    res = ds.query_result("inc", ecql)
    ax = np.concatenate([x, nx]); ay = np.concatenate([y, ny])
    at = np.concatenate([t, nt])
    want = np.flatnonzero(
        (ax >= -74.6) & (ax <= -73.4) & (ay >= 40.3) & (ay <= 41.7)
        & (at >= MS_2018 + 2 * 86_400_000)
        & (at <= MS_2018 + 9 * 86_400_000))
    np.testing.assert_array_equal(np.sort(res.positions), want)

    # deletion invalidates: next write must NOT append to a stale index
    ds.delete("inc", [st.batch.ids[0]])
    ds.write("inc", {"name": np.array(["c"], object),
                     "dtg": np.array([MS_2018 + 86_400_000]),
                     "geom": (np.array([-74.0]), np.array([41.0]))})
    res2 = ds.query_result("inc", ecql)
    st2 = ds._store("inc")
    oracle2 = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st2.batch))
    np.testing.assert_array_equal(np.sort(res2.positions), oracle2)


def test_interleaved_writes_no_full_rebuild(rng_mod):
    """Interleaved write/query keeps every index incremental: z3 and z2
    append in place, xz/attr/id serve their covered rows plus the
    appended tail as candidates — no full rebuild per write (round-3
    next #5; build counters prove it)."""
    rng = rng_mod
    ds = TpuDataStore()
    ds.create_schema("iw", "name:String:index=true,dtg:Date,*geom:Point")
    n0 = 30_000

    def rows(k, tag):
        return {"name": np.array([tag] * k, object),
                "dtg": rng.integers(MS_2018, MS_2018 + 14 * 86_400_000, k),
                "geom": (rng.uniform(-75, -73, k),
                         rng.uniform(40, 42, k))}

    ds.write("iw", rows(n0, "a"))
    st = ds._store("iw")
    queries = [
        "BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
        "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z",   # z3
        "BBOX(geom,-74.2,40.8,-73.9,41.1)",              # z2
        "name = 'a'",                                    # attr
    ]
    for q in queries:
        ds.query("iw", q)
    base_counts = dict(st.build_counts)
    # 6 interleaved small writes + every query flavor each round
    for i in range(6):
        ds.write("iw", rows(500, f"t{i}"))
        for q in queries + [f"name = 't{i}'", "IN ('3', '77')"]:
            res = ds.query_result("iw", q)
            want = np.flatnonzero(
                evaluate_filter(parse_ecql(q), st.batch))
            np.testing.assert_array_equal(np.sort(res.positions), want)
    # z3/z2 appended in place; attr/id kept with tails (500*6 = 3000
    # rows < the compaction threshold of 4096) — no rebuilds at all
    assert st.build_counts.get("z3") == base_counts.get("z3") == 1
    assert st.build_counts.get("z2") == base_counts.get("z2") == 1
    assert st.build_counts.get("attr:name", 0) <= 1
    # tails exist and cover exactly the appended rows
    assert len(st.index_tail("attr:name")) == 3000
    # a large write crosses the threshold: the next attr query compacts
    ds.write("iw", rows(6000, "big"))
    _ = ds.query("iw", "name = 'big'")
    assert st.index_tail("attr:name") is None or \
        len(st.index_tail("attr:name")) == 0
    assert st.build_counts["attr:name"] == 2


def test_auto_ids_never_reused_after_delete(tmp_path):
    """Auto feature-ids come from a monotonic counter, not len(batch):
    delete+write must mint FRESH ids (the reference's id generators never
    recycle, utils/uuid/Z3FeatureIdGenerator.scala)."""
    ds = TpuDataStore(str(tmp_path / "cat"))
    ds.create_schema("fid", "v:Int,dtg:Date,*geom:Point")

    def rows(k):
        return {"v": np.arange(k), "dtg": np.full(k, MS_2018),
                "geom": (np.linspace(-10, 10, k), np.full(k, 45.0))}

    ds.write("fid", rows(4))                      # ids 0..3
    ds.delete("fid", ["0", "1"])
    ds.write("fid", rows(2))                      # must be 4,5 — not 2,3
    ids = sorted(ds.query("fid").ids)
    assert ids == ["2", "3", "4", "5"]
    # id-index lookups hit exactly one row per id
    assert len(ds.query("fid", "IN ('3')")) == 1

    # explicit numeric ids advance the counter too
    ds.write("fid", rows(1), ids=np.array(["100"], object))
    ds.write("fid", rows(1))
    assert "101" in set(ds.query("fid").ids)

    # the counter survives a catalog round trip — even when the highest
    # ids were deleted before the flush (persisted __meta__, not just
    # re-derived from surviving rows)
    ds.delete("fid", ["100", "101"])
    ds.flush("fid")
    ds2 = TpuDataStore(str(tmp_path / "cat"))
    ds2.write("fid", rows(1))
    all_ids = list(ds2.query("fid").ids)
    assert len(set(all_ids)) == len(all_ids) == 5
    assert "102" in set(all_ids)
    assert not {"100", "101"} & set(all_ids)


def test_explicit_id_collisions_rejected_at_write():
    ds = TpuDataStore()
    ds.create_schema("wid", "v:Int,dtg:Date,*geom:Point")
    row = {"v": np.array([1]), "dtg": np.array([MS_2018]),
           "geom": (np.array([-74.0]), np.array([41.0]))}
    ds.write("wid", row, ids=np.array(["a"], object))
    with pytest.raises(ValueError, match="already exists"):
        ds.write("wid", row, ids=np.array(["a"], object))
    two = {"v": np.array([1, 2]), "dtg": np.full(2, MS_2018),
           "geom": (np.array([-74.0, -73.5]), np.array([41.0, 41.2]))}
    with pytest.raises(ValueError, match="within the write batch"):
        ds.write("wid", two, ids=np.array(["b", "b"], object))
    # unicode digit chars must not crash the counter math ('²' passes
    # isdigit but not int parsing)
    ds.write("wid", row, ids=np.array(["²"], object))
    ds.write("wid", row)
    assert len(ds.query("wid")) == 3


def test_duplicate_explicit_ids_rejected_by_id_index():
    from geomesa_tpu.index.id import IdIndex
    with pytest.raises(ValueError, match="duplicate feature id"):
        IdIndex.build(np.array(["a", "b", "a"], object))


def test_sampling_hints(store):
    """SAMPLING / SAMPLE_BY query hints thin results 1-in-n (the
    reference's SamplingIterator hints)."""
    full = store.query_result("events", "name = 'alpha'").positions
    q = Query.of("name = 'alpha'", hints={"SAMPLING": 4})
    got = store.query_result("events", q).positions
    np.testing.assert_array_equal(got, full[::4])
    # per-group sampling keeps at least one row per group
    q2 = Query.of("INCLUDE", hints={"SAMPLING": 1000, "SAMPLE_BY": "name"})
    got2 = store.query("events", q2)
    assert set(got2.column("name")) == {"alpha", "beta", "gamma", "delta"}
    assert len(got2) < 100


def test_stats_mode_boundary_merge(tmp_path):
    """A catalog whose stats were written per-process (multihost
    {name}.pN.stats.json) still answers when reopened single-host: the
    per-process sketches merge, and next_fid takes the max."""
    import json
    import os

    cat = tmp_path / "cat"
    ds = TpuDataStore(str(cat))
    ds.create_schema("evt", "v:Double,dtg:Date,*geom:Point")
    ds.write("evt", {"v": np.array([1.0, 5.0]),
                     "dtg": np.full(2, 1514764800000),
                     "geom": (np.zeros(2), np.zeros(2))})
    ds.persist_stats("evt")
    shared = cat / "evt.stats.json"
    raw = json.loads(shared.read_text())
    # simulate a multihost-written catalog: two per-process files with
    # disjoint observations, no shared file
    half = dict(raw)
    half["__meta__"] = {"next_fid": 7}
    (cat / "evt.p0.stats.json").write_text(json.dumps(half))
    half2 = dict(raw)
    half2["__meta__"] = {"next_fid": 11}
    (cat / "evt.p1.stats.json").write_text(json.dumps(half2))
    os.remove(shared)
    ds2 = TpuDataStore(str(cat))
    st = ds2._store("evt")
    # merged count doubles (two copies of the same sketch), proving the
    # merge path ran; next_fid is the max over processes
    assert st._stats["count"].count == 4
    assert st.next_fid >= 11


def test_stats_stale_shared_does_not_shadow(tmp_path):
    """Recency picks the sketch source across topology boundaries: a
    stale shared stats file must not shadow newer per-process files,
    and next_fid maxes over EVERY artifact (ids are never reused)."""
    import json
    import os
    import time

    cat = tmp_path / "cat"
    ds = TpuDataStore(str(cat))
    ds.create_schema("evt", "v:Double,dtg:Date,*geom:Point")
    ds.write("evt", {"v": np.array([2.0]),
                     "dtg": np.full(1, 1514764800000),
                     "geom": (np.zeros(1), np.zeros(1))})
    ds.persist_stats("evt")
    shared = cat / "evt.stats.json"
    raw = json.loads(shared.read_text())
    newer = dict(raw)
    newer["__meta__"] = {"next_fid": 40}
    (cat / "evt.p0.stats.json").write_text(json.dumps(newer))
    # shared carries the HIGHEST fid but is older than the .p0 file
    stale = dict(raw)
    stale["__meta__"] = {"next_fid": 99}
    shared.write_text(json.dumps(stale))
    old = time.time() - 1000
    os.utime(shared, (old, old))
    ds2 = TpuDataStore(str(cat))
    st = ds2._store("evt")
    assert st._stats["count"].count == 1    # .p0 sketches, not doubled
    assert st.next_fid >= 99                # fid still maxes over ALL


def test_schema_name_validation():
    ds = TpuDataStore()
    for bad in ("evt.p2", "a.lean", "x y", ""):
        with pytest.raises(ValueError, match="invalid schema name|"
                                             "unsupported"):
            ds.create_schema(bad, "dtg:Date,*geom:Point")
    ds.create_schema("ok-Name_2", "dtg:Date,*geom:Point")


def test_stats_generation_counter_beats_mtime(tmp_path):
    """The monotonic ``__meta__`` generation counter decides stats-source
    arbitration when present; mtime skew (shared-dir clock drift) cannot
    pick the stale artifact (round-4 ADVICE)."""
    import json
    import os
    import time

    cat = tmp_path / "cat"
    ds = TpuDataStore(str(cat))
    ds.create_schema("evt", "v:Double,dtg:Date,*geom:Point")
    ds.write("evt", {"v": np.array([2.0]),
                     "dtg": np.full(1, 1514764800000),
                     "geom": (np.zeros(1), np.zeros(1))})
    ds.persist_stats("evt")
    assert ds._store("evt").stats_generation == 1
    ds.persist_stats("evt")
    assert ds._store("evt").stats_generation == 2
    shared = cat / "evt.stats.json"
    raw = json.loads(shared.read_text())
    # a per-process artifact with a HIGHER generation but an OLDER mtime
    # (cross-host clock skew shape) must still win the arbitration
    newer = dict(raw)
    newer["__meta__"] = {"next_fid": 40, "generation": 9}
    newer["count"] = {"kind": "count", "count": 123}
    p0 = cat / "evt.p0.stats.json"
    p0.write_text(json.dumps(newer))
    old = time.time() - 1000
    os.utime(p0, (old, old))
    ds2 = TpuDataStore(str(cat))
    st = ds2._store("evt")
    assert st._stats["count"].count == 123   # generation beat mtime
    assert st.stats_generation == 9          # counter restored monotone


def test_stats_missing_default_key_reseeded(tmp_path):
    """An artifact family that never carried a default sketch (or whose
    merge dropped it) must not leave ``_stats['count']`` missing after
    reopen — unconditional indexing would brick the catalog open
    (round-4 ADVICE)."""
    import json

    cat = tmp_path / "cat"
    ds = TpuDataStore(str(cat))
    ds.create_schema("evt", "v:Double,dtg:Date,*geom:Point")
    ds.write("evt", {"v": np.array([1.0]),
                     "dtg": np.full(1, 1514764800000),
                     "geom": (np.zeros(1), np.zeros(1))})
    ds.persist_stats("evt")
    shared = cat / "evt.stats.json"
    raw = json.loads(shared.read_text())
    stripped = {"__meta__": raw["__meta__"],
                "v_minmax": raw["v_minmax"]}   # no "count" at all
    shared.write_text(json.dumps(stripped))
    ds2 = TpuDataStore(str(cat))               # must not raise
    st = ds2._store("evt")
    assert "count" in st._stats                # re-seeded default
    assert "v_minmax" in st._stats


def test_remove_schema_tolerates_vanished_stats_file(tmp_path, monkeypatch):
    """An externally deleted per-process stats file between listdir and
    remove must not crash remove_schema mid-cleanup (round-4 ADVICE)."""
    cat = tmp_path / "cat"
    ds = TpuDataStore(str(cat))
    ds.create_schema("evt", "dtg:Date,*geom:Point")
    ghost = str(cat / "evt.p3.stats.json")
    real = TpuDataStore._proc_stats_files
    monkeypatch.setattr(
        TpuDataStore, "_proc_stats_files",
        lambda self, name: real(self, name) + [ghost])
    ds.remove_schema("evt")                    # must not raise
    assert "evt" not in ds.type_names


def test_incompatible_histogram_merge_drops_key(tmp_path):
    """Per-process histograms binned over local bounds cannot merge —
    the catalog still opens and the sketch is dropped, not fatal."""
    import json

    cat = tmp_path / "cat"
    ds = TpuDataStore(str(cat))
    ds.create_schema("evt", "v:Double:index=true,dtg:Date,*geom:Point")
    ds.write("evt", {"v": np.array([1.0, 2.0]),
                     "dtg": np.full(2, 1514764800000),
                     "geom": (np.zeros(2), np.zeros(2))})
    ds.persist_stats("evt")
    raw = json.loads((cat / "evt.stats.json").read_text())
    from geomesa_tpu.stats.stat import Histogram
    h0 = Histogram("v", 16, 0.0, 10.0)
    h1 = Histogram("v", 16, 5.0, 50.0)
    a = dict(raw); a["v_histogram"] = h0.to_json()
    b = dict(raw); b["v_histogram"] = h1.to_json()
    (cat / "evt.p0.stats.json").write_text(json.dumps(a))
    (cat / "evt.p1.stats.json").write_text(json.dumps(b))
    (cat / "evt.stats.json").unlink()
    ds2 = TpuDataStore(str(cat))           # must not raise
    st = ds2._store("evt")
    assert "v_histogram" not in st._stats
    assert st._stats["count"].count == 4   # other sketches merged
