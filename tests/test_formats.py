"""Converter formats: XML, fixed-width, Avro, JDBC, Shapefile, OSM
(reference: geomesa-convert-{xml,fixedwidth,avro,jdbc,shp,osm})."""

import sqlite3
import struct

import numpy as np
import pytest

from geomesa_tpu.features.feature_type import parse_spec
from geomesa_tpu.io.converters import converter_from_config
from geomesa_tpu.io.formats import read_shapefile

SFT = parse_spec("obs", "name:String,value:Int,dtg:Date,*geom:Point")


def test_xml_converter():
    xml = """<doc>
      <feature station="A"><name>alpha</name><v>3</v>
        <pos><lon>1.5</lon><lat>50.5</lat></pos>
        <when>2018-01-01T00:00:00Z</when></feature>
      <feature station="B"><name>beta</name><v>4</v>
        <pos><lon>2.5</lon><lat>51.5</lat></pos>
        <when>2018-01-02T00:00:00Z</when></feature>
    </doc>"""
    conv = converter_from_config(SFT, {
        "type": "xml", "feature-path": "feature",
        "id-field": "$@station",
        "fields": [
            {"name": "name", "transform": "$name"},
            {"name": "value", "transform": "toint($v)"},
            {"name": "dtg", "transform": "isodate($when)"},
            {"name": "geom",
             "transform": "point(todouble($pos/lon), todouble($pos/lat))"},
        ],
    })
    batch = conv.convert(xml)
    assert len(batch) == 2
    assert list(batch.ids) == ["A", "B"]
    assert list(batch.column("name")) == ["alpha", "beta"]
    np.testing.assert_array_equal(batch.column("value"), [3, 4])
    np.testing.assert_allclose(batch.geom_xy()[0], [1.5, 2.5])


def test_fixed_width_converter():
    text = "alpha 003 1.50 50.50\nbeta  004 2.50 51.50\n"
    conv = converter_from_config(SFT, {
        "type": "fixed-width",
        "fields": [
            {"name": "name", "start": 0, "width": 6},
            {"name": "value", "start": 6, "width": 3,
             "transform": "toint($value)"},
            {"name": "geom", "start": 0, "width": 0,
             "transform": "point(todouble($x), todouble($y))"},
            {"name": "x", "start": 10, "width": 4},
            {"name": "y", "start": 15, "width": 5},
        ],
    })
    batch = conv.convert(text)
    assert len(batch) == 2
    assert list(batch.column("name")) == ["alpha", "beta"]
    np.testing.assert_array_equal(batch.column("value"), [3, 4])
    np.testing.assert_allclose(batch.geom_xy()[1], [50.5, 51.5])


def test_avro_converter(tmp_path):
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.io.avro import to_avro

    batch = FeatureBatch.from_dict(SFT, {
        "name": np.array(["a", "b"], dtype=object),
        "value": np.array([1, 2], dtype=np.int32),
        "dtg": np.array([1000, 2000], dtype=np.int64),
        "geom": (np.array([1.0, 2.0]), np.array([10.0, 20.0])),
    }, ids=["f1", "f2"])
    path = str(tmp_path / "obs.avro")
    to_avro(batch, path)
    conv = converter_from_config(SFT, {"type": "avro"})
    out = conv.convert(path)
    assert len(out) == 2
    assert list(out.ids) == ["f1", "f2"]
    np.testing.assert_array_equal(out.column("value"), [1, 2])


def test_jdbc_converter(tmp_path):
    db = str(tmp_path / "obs.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE obs (name TEXT, value INT, t INT, x REAL, y REAL)")
    conn.executemany("INSERT INTO obs VALUES (?,?,?,?,?)",
                     [("a", 1, 1000, 1.0, 10.0), ("b", 2, 2000, 2.0, 20.0)])
    conn.commit()
    conn.close()
    conv = converter_from_config(SFT, {
        "type": "jdbc",
        "query": "SELECT name, value, t, x, y FROM obs ORDER BY name",
        "fields": [
            {"name": "name"},
            {"name": "value", "transform": "toint($2)"},
            {"name": "dtg", "transform": "millistodate($t)"},
            {"name": "geom", "transform": "point(todouble($x), todouble($y))"},
        ],
    })
    batch = conv.convert(db)
    assert len(batch) == 2
    np.testing.assert_array_equal(batch.column("value"), [1, 2])
    np.testing.assert_array_equal(batch.column("dtg"), [1000, 2000])


def _write_test_shapefile(path, geoms_points, dbf_rows):
    """Hand-rolled tiny .shp (point type) + .dbf for the reader test."""
    recs = b""
    for i, (x, y) in enumerate(geoms_points):
        content = struct.pack("<i", 1) + struct.pack("<dd", x, y)
        recs += struct.pack(">ii", i + 1, len(content) // 2) + content
    total_words = (100 + len(recs)) // 2
    hdr = struct.pack(">i", 9994) + b"\x00" * 20 + struct.pack(">i", total_words)
    hdr += struct.pack("<ii", 1000, 1)  # version, shape type point
    hdr += struct.pack("<8d", 0, 0, 0, 0, 0, 0, 0, 0)
    with open(path, "wb") as f:
        f.write(hdr + recs)
    # dbf: one C field "name" width 8, one N field "v" width 4
    nrec = len(dbf_rows)
    fields = [("name", "C", 8, 0), ("v", "N", 4, 0)]
    hdr_size = 32 + 32 * len(fields) + 1
    rec_size = 1 + 8 + 4
    out = bytearray()
    out += bytes([3, 118, 1, 1]) + struct.pack("<ihh", nrec, hdr_size, rec_size)
    out += b"\x00" * 20
    for name, t, ln, dec in fields:
        out += name.encode().ljust(11, b"\x00") + t.encode()
        out += b"\x00" * 4 + bytes([ln, dec]) + b"\x00" * 14
    out += b"\x0d"
    for name, v in dbf_rows:
        out += b" " + name.encode().ljust(8)[:8] + str(v).rjust(4).encode()
    with open(str(path)[:-4] + ".dbf", "wb") as f:
        f.write(bytes(out))


def test_shapefile_reader_and_converter(tmp_path):
    shp = str(tmp_path / "pts.shp")
    _write_test_shapefile(shp, [(1.0, 10.0), (2.0, 20.0)],
                          [("a", 1), ("b", 2)])
    geoms, attrs = read_shapefile(shp)
    assert len(geoms) == 2 and geoms[0].x == 1.0 and geoms[1].y == 20.0
    assert list(attrs["name"]) == ["a", "b"]
    assert list(attrs["v"]) == [1, 2]

    sft = parse_spec("pts", "name:String,v:Int,*geom:Point")
    conv = converter_from_config(sft, {
        "type": "shp",
        "fields": [
            {"name": "name"},
            {"name": "v", "transform": "toint($v)"},
            {"name": "geom", "transform": "$geometry"},
        ],
    })
    batch = conv.convert(shp)
    assert len(batch) == 2
    np.testing.assert_allclose(batch.geom_xy()[0], [1.0, 2.0])


def test_osm_converter():
    osm = """<osm version="0.6">
      <node id="101" lat="50.5" lon="1.5">
        <tag k="amenity" v="cafe"/><tag k="name" v="First"/></node>
      <node id="102" lat="51.5" lon="2.5">
        <tag k="name" v="Second"/></node>
    </osm>"""
    sft = parse_spec("poi", "name:String,*geom:Point")
    conv = converter_from_config(sft, {
        "type": "osm",
        "id-field": "$id",
        "fields": [
            {"name": "name"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
    })
    batch = conv.convert(osm)
    assert len(batch) == 2
    assert list(batch.ids) == ["101", "102"]
    assert list(batch.column("name")) == ["First", "Second"]
    np.testing.assert_allclose(batch.geom_xy()[0], [1.5, 2.5])


def test_unknown_type_rejected():
    with pytest.raises(ValueError):
        converter_from_config(SFT, {"type": "nope"})


def test_gml_and_leaflet_export():
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.io.export import to_gml, to_leaflet
    import xml.etree.ElementTree as ET

    batch = FeatureBatch.from_dict(SFT, {
        "name": np.array(["a", "<b>"], dtype=object),
        "value": np.array([1, 2], dtype=np.int32),
        "dtg": np.array([1000, 2000], dtype=np.int64),
        "geom": (np.array([1.0, 2.0]), np.array([10.0, 20.0])),
    }, ids=["f1", "f2"])
    gml = to_gml(batch)
    root = ET.fromstring(gml)  # well-formed
    ns = {"gml": "http://www.opengis.net/gml", "geomesa": "http://geomesa.org"}
    members = root.findall("gml:featureMember", ns)
    assert len(members) == 2
    pos = members[0].find(".//gml:pos", ns).text
    assert pos == "1 10"
    assert members[1].find(".//geomesa:name", ns).text == "<b>"

    html = to_leaflet(batch)
    assert "leaflet" in html and '"FeatureCollection"' in html


def test_gml_polygon_roundtrip_wellformed():
    from geomesa_tpu.features.batch import FeatureBatch
    from geomesa_tpu.geometry.types import Polygon
    from geomesa_tpu.io.export import to_gml
    import xml.etree.ElementTree as ET

    sft = parse_spec("areas", "name:String,*geom:Polygon")
    shell = np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], dtype=float)
    hole = np.array([[1, 1], [2, 1], [2, 2], [1, 2], [1, 1]], dtype=float)
    batch = FeatureBatch.from_dict(sft, {
        "name": np.array(["p"], dtype=object),
        "geom": [Polygon(shell, (hole,))],
    })
    root = ET.fromstring(to_gml(batch))
    ns = {"gml": "http://www.opengis.net/gml"}
    assert root.find(".//gml:exterior", ns) is not None
    assert root.find(".//gml:interior", ns) is not None


def test_in_filter_mixed_type_values():
    """Mixed-type In lists must not silently match nothing (np.array
    promotes [1,'a'] to a string dtype; the isin fast path must bail)."""
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.filters.ast import In
    from geomesa_tpu.filters.evaluate import evaluate_filter

    ds = TpuDataStore()
    sft = ds.create_schema("mix", "v:Int,*geom:Point")
    ds.write("mix", {"v": np.arange(10), "geom": (np.zeros(10), np.zeros(10))})
    batch = ds._store("mix").batch
    mask = evaluate_filter(In("v", (1, 2, 3, 4, "a")), batch)
    assert mask.sum() == 4 and mask[1] and mask[4]


def test_shapefile_null_shapes(tmp_path):
    """Null-shape (type 0) records are dropped, not fatal."""
    import struct

    from geomesa_tpu.io.formats import ShapefileConverter
    from geomesa_tpu.features.feature_type import parse_spec

    def rec(num, content):
        return struct.pack(">ii", num, len(content) // 2) + content

    pt = struct.pack("<i dd", 1, 3.0, 4.0)
    null = struct.pack("<i", 0)
    body = rec(1, pt) + rec(2, null) + rec(3, struct.pack("<i dd", 1, 5.0, 6.0))
    header = struct.pack(">i", 9994) + b"\x00" * 20 + struct.pack(
        ">i", (100 + len(body)) // 2) + struct.pack("<ii", 1000, 1) + b"\x00" * 64
    path = tmp_path / "t.shp"
    path.write_bytes(header + body)
    sft = parse_spec("shp", "*geom:Point")
    conv = ShapefileConverter(sft, {
        "type": "shp", "fields": [{"name": "geom", "transform": "$geometry"}]})
    batch = conv.convert(str(path))
    assert len(batch) == 2
    x, y = batch.geom_xy()
    np.testing.assert_allclose(x, [3.0, 5.0])
