"""Z-range decomposition vs brute-force oracles.

Includes the reference's golden case (Z2Test.scala "calculate ranges"):
box (2,2)-(3,6) in normalized space decomposes to exactly 3 merged ranges.
"""

import numpy as np
import pytest

from geomesa_tpu.curve import zranges
from geomesa_tpu.curve.zorder import interleave2, interleave3


def z2(x, y):
    return int(interleave2(np.int64(x), np.int64(y), xp=np))


def covered_set(ranges):
    out = set()
    for lo, hi in ranges:
        out.update(range(int(lo), int(hi) + 1))
    return out


def brute_set_2d(xmin, ymin, xmax, ymax):
    out = set()
    for x in range(xmin, xmax + 1):
        for y in range(ymin, ymax + 1):
            out.add(z2(x, y))
    return out


def test_golden_z2_case():
    # reference Z2Test: ZRange(Z2(2,2), Z2(3,6)) -> 3 ranges
    ranges = zranges([[2, 2]], [[3, 6]], dims=2, bits=31)
    assert ranges.shape == (3, 2)
    expected = [
        (z2(2, 2), z2(3, 3)),
        (z2(2, 4), z2(3, 5)),
        (z2(2, 6), z2(3, 6)),
    ]
    got = [tuple(r) for r in ranges]
    assert sorted(got) == sorted(expected)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_exact_cover_2d(seed):
    rng = np.random.default_rng(seed)
    bits = 8
    for _ in range(5):
        x = np.sort(rng.integers(0, 1 << bits, 2))
        y = np.sort(rng.integers(0, 1 << bits, 2))
        ranges = zranges([[x[0], y[0]]], [[x[1], y[1]]], dims=2, bits=bits,
                         max_ranges=10**9)
        assert covered_set(ranges) == brute_set_2d(x[0], y[0], x[1], y[1])


def test_exact_cover_3d():
    rng = np.random.default_rng(42)
    bits = 5
    for _ in range(4):
        lo = rng.integers(0, 1 << bits, 3)
        hi = np.minimum(lo + rng.integers(0, 8, 3), (1 << bits) - 1)
        ranges = zranges([lo], [hi], dims=3, bits=bits, max_ranges=10**9)
        brute = set()
        for x in range(lo[0], hi[0] + 1):
            for y in range(lo[1], hi[1] + 1):
                for t in range(lo[2], hi[2] + 1):
                    brute.add(int(interleave3(np.int64(x), np.int64(y), np.int64(t), xp=np)))
        assert covered_set(ranges) == brute


def test_multiple_boxes_merged():
    bits = 8
    r = zranges([[0, 0], [1, 0]], [[1, 1], [3, 3]], dims=2, bits=bits,
                max_ranges=10**9)
    want = brute_set_2d(0, 0, 1, 1) | brute_set_2d(1, 0, 3, 3)
    assert covered_set(r) == want
    # ranges must be disjoint and sorted
    assert np.all(r[1:, 0] > r[:-1, 1] + 1 - 1)


def test_budget_produces_superset():
    bits = 10
    box = ([[3, 5]], [[900, 700]])
    exact = zranges(*box, dims=2, bits=bits, max_ranges=10**9)
    budget = zranges(*box, dims=2, bits=bits, max_ranges=20)
    assert len(budget) <= 20
    assert len(budget) < len(exact)
    assert covered_set(exact) <= covered_set(budget)


def test_full_domain():
    r = zranges([[0, 0]], [[(1 << 8) - 1, (1 << 8) - 1]], dims=2, bits=8)
    assert r.shape == (1, 2)
    assert r[0, 0] == 0 and r[0, 1] == (1 << 16) - 1


def test_single_cell():
    r = zranges([[37, 91]], [[37, 91]], dims=2, bits=8)
    assert r.shape == (1, 2)
    assert r[0, 0] == r[0, 1] == z2(37, 91)
