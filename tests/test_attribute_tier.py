"""Tiered attribute index: secondary date keys narrow equality/IN scans
(the reference's AttributeIndexKeySpace + DateIndexKeySpace tier,
api/GeoMesaFeatureIndex.scala:248-338)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql
from geomesa_tpu.index.attribute import AttributeIndex
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.planning.strategy import StrategyDecider

MS_2018 = 1514764800000
DAY = 86_400_000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    n = 20_000
    return {
        "name": rng.choice(["a", "b", "c", "d", "e"], n),
        "dtg": rng.integers(MS_2018, MS_2018 + 30 * DAY, n),
    }


def test_build_orders_secondary_within_runs(data):
    idx = AttributeIndex.build("name", data["name"], secondary=data["dtg"])
    # within each value run, secondary must be sorted
    vals = idx.values
    for v in np.unique(vals):
        lo = np.searchsorted(vals, v, "left")
        hi = np.searchsorted(vals, v, "right")
        run = idx.secondary[lo:hi]
        assert np.all(run[:-1] <= run[1:])


@pytest.mark.parametrize("window", [
    (MS_2018 + 5 * DAY, MS_2018 + 9 * DAY),
    (None, MS_2018 + 2 * DAY),
    (MS_2018 + 25 * DAY, None),
    (MS_2018 + 40 * DAY, MS_2018 + 50 * DAY),  # empty
])
def test_equals_with_window_exact(data, window):
    idx = AttributeIndex.build("name", data["name"], secondary=data["dtg"])
    lo, hi = window
    got = idx.query_equals("c", (lo, hi))
    mask = data["name"] == "c"
    if lo is not None:
        mask &= data["dtg"] >= lo
    if hi is not None:
        mask &= data["dtg"] <= hi
    np.testing.assert_array_equal(got, np.flatnonzero(mask))


def test_in_with_window_exact(data):
    idx = AttributeIndex.build("name", data["name"], secondary=data["dtg"])
    lo, hi = MS_2018 + 3 * DAY, MS_2018 + 6 * DAY
    got = idx.query_in(["a", "e"], (lo, hi))
    mask = np.isin(data["name"], ["a", "e"]) & (data["dtg"] >= lo) & (data["dtg"] <= hi)
    np.testing.assert_array_equal(got, np.flatnonzero(mask))


def test_untier_matches_legacy(data):
    flat = AttributeIndex.build("name", data["name"])
    tiered = AttributeIndex.build("name", data["name"], secondary=data["dtg"])
    np.testing.assert_array_equal(flat.query_equals("b"),
                                  tiered.query_equals("b"))
    np.testing.assert_array_equal(flat.query_range("b", "d"),
                                  tiered.query_range("b", "d"))


@pytest.fixture(scope="module")
def store(data):
    ds = TpuDataStore()
    n = len(data["dtg"])
    rng = np.random.default_rng(7)
    ds.create_schema(
        "tiered", "name:String:index=true,dtg:Date,*geom:Point")
    ds.write("tiered", {
        "name": data["name"],
        "dtg": data["dtg"],
        "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)),
    })
    return ds


def test_planner_end_to_end_attr_plus_time(store):
    ecql = ("name = 'c' AND dtg DURING "
            "2018-01-03T00:00:00Z/2018-01-05T00:00:00Z")
    res = store.query_result("tiered", ecql)
    st = store._store("tiered")
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(res.positions), want)
    assert res.strategy.index == "attr:name"
    # the tier must carry the intervals into the strategy
    assert res.strategy.intervals


def test_tier_discounts_strategy_cost(store):
    st = store._store("tiered")
    decider = StrategyDecider(st.sft, st.stats_map(), len(st.batch))
    plain = decider.decide(parse_ecql("name = 'c'"))
    tiered = decider.decide(parse_ecql(
        "name = 'c' AND dtg DURING 2018-01-03T00:00:00Z/2018-01-05T00:00:00Z"))
    assert tiered.index == "attr:name"
    assert tiered.cost < plain.cost


def test_tier_narrows_candidates(store):
    """The scan itself (pre-residual-filter) must return fewer candidates
    with the tier refinement than without — the point of the tier.
    This schema has point geom + dtg, so the index carries the Z3 tier
    (the reference's default secondary for such schemas)."""
    from geomesa_tpu.index.z3 import plan_z3_query

    st = store._store("tiered")
    idx = st.attribute_index("name")
    assert idx.sec_z is not None  # z3 tier selected
    full = idx.query_equals("c")
    lo, hi = MS_2018 + 2 * DAY, MS_2018 + 4 * DAY
    plan = plan_z3_query([(-180.0, -90.0, 180.0, 90.0)], lo, hi,
                         st.sft.z3_interval, 256)
    narrowed = idx.query_equals(
        "c", None, (plan.rbin, plan.rzlo, plan.rzhi))
    assert 0 < len(narrowed) < len(full)
    # spatial narrowing too: a small bbox plan shrinks further
    plan_sp = plan_z3_query([(-5.0, -5.0, 5.0, 5.0)], lo, hi,
                            st.sft.z3_interval, 256)
    spatial = idx.query_equals(
        "c", None, (plan_sp.rbin, plan_sp.rzlo, plan_sp.rzhi))
    assert len(spatial) < len(narrowed)


def test_z3_tier_planner_exact(store):
    """attr = X AND bbox AND time through the planner: exact results,
    attr strategy chosen with the z3-tier refinement wired in."""
    ecql = ("name = 'b' AND BBOX(geom, -5, -5, 5, 5) AND dtg DURING "
            "2018-01-02T00:00:00Z/2018-01-06T00:00:00Z")
    res = store.query_result("tiered", ecql)
    st = store._store("tiered")
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(res.positions), want)
    assert res.strategy.index == "attr:name"
    assert res.strategy.geometries  # spatial tier info reached the plan
