"""Tiered attribute index: secondary date keys narrow equality/IN scans
(the reference's AttributeIndexKeySpace + DateIndexKeySpace tier,
api/GeoMesaFeatureIndex.scala:248-338)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql
from geomesa_tpu.index.attribute import AttributeIndex
from geomesa_tpu.planning.planner import Query
from geomesa_tpu.planning.strategy import StrategyDecider

MS_2018 = 1514764800000
DAY = 86_400_000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    n = 20_000
    return {
        "name": rng.choice(["a", "b", "c", "d", "e"], n),
        "dtg": rng.integers(MS_2018, MS_2018 + 30 * DAY, n),
    }


def test_build_orders_secondary_within_runs(data):
    idx = AttributeIndex.build("name", data["name"], secondary=data["dtg"])
    # within each value run, secondary must be sorted
    vals = idx.values
    for v in np.unique(vals):
        lo = np.searchsorted(vals, v, "left")
        hi = np.searchsorted(vals, v, "right")
        run = idx.secondary[lo:hi]
        assert np.all(run[:-1] <= run[1:])


@pytest.mark.parametrize("window", [
    (MS_2018 + 5 * DAY, MS_2018 + 9 * DAY),
    (None, MS_2018 + 2 * DAY),
    (MS_2018 + 25 * DAY, None),
    (MS_2018 + 40 * DAY, MS_2018 + 50 * DAY),  # empty
])
def test_equals_with_window_exact(data, window):
    idx = AttributeIndex.build("name", data["name"], secondary=data["dtg"])
    lo, hi = window
    got = idx.query_equals("c", (lo, hi))
    mask = data["name"] == "c"
    if lo is not None:
        mask &= data["dtg"] >= lo
    if hi is not None:
        mask &= data["dtg"] <= hi
    np.testing.assert_array_equal(got, np.flatnonzero(mask))


def test_in_with_window_exact(data):
    idx = AttributeIndex.build("name", data["name"], secondary=data["dtg"])
    lo, hi = MS_2018 + 3 * DAY, MS_2018 + 6 * DAY
    got = idx.query_in(["a", "e"], (lo, hi))
    mask = np.isin(data["name"], ["a", "e"]) & (data["dtg"] >= lo) & (data["dtg"] <= hi)
    np.testing.assert_array_equal(got, np.flatnonzero(mask))


def test_untier_matches_legacy(data):
    flat = AttributeIndex.build("name", data["name"])
    tiered = AttributeIndex.build("name", data["name"], secondary=data["dtg"])
    np.testing.assert_array_equal(flat.query_equals("b"),
                                  tiered.query_equals("b"))
    np.testing.assert_array_equal(flat.query_range("b", "d"),
                                  tiered.query_range("b", "d"))


@pytest.fixture(scope="module")
def store(data):
    ds = TpuDataStore()
    n = len(data["dtg"])
    rng = np.random.default_rng(7)
    ds.create_schema(
        "tiered", "name:String:index=true,dtg:Date,*geom:Point")
    ds.write("tiered", {
        "name": data["name"],
        "dtg": data["dtg"],
        "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)),
    })
    return ds


def test_planner_end_to_end_attr_plus_time(store):
    ecql = ("name = 'c' AND dtg DURING "
            "2018-01-03T00:00:00Z/2018-01-05T00:00:00Z")
    res = store.query_result("tiered", ecql)
    st = store._store("tiered")
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(res.positions), want)
    assert res.strategy.index == "attr:name"
    # the tier must carry the intervals into the strategy
    assert res.strategy.intervals


def test_tier_discounts_strategy_cost(store):
    st = store._store("tiered")
    decider = StrategyDecider(st.sft, st.stats_map(), len(st.batch))
    plain = decider.decide(parse_ecql("name = 'c'"))
    tiered = decider.decide(parse_ecql(
        "name = 'c' AND dtg DURING 2018-01-03T00:00:00Z/2018-01-05T00:00:00Z"))
    assert tiered.index == "attr:name"
    assert tiered.cost < plain.cost


def test_tier_narrows_candidates(store):
    """The scan itself (pre-residual-filter) must return fewer candidates
    with the tier refinement than without — the point of the tier.
    This schema has point geom + dtg, so the index carries the Z3 tier
    (the reference's default secondary for such schemas)."""
    from geomesa_tpu.index.z3 import plan_z3_query

    st = store._store("tiered")
    idx = st.attribute_index("name")
    assert idx.sec_z is not None  # z3 tier selected
    full = idx.query_equals("c")
    lo, hi = MS_2018 + 2 * DAY, MS_2018 + 4 * DAY
    plan = plan_z3_query([(-180.0, -90.0, 180.0, 90.0)], lo, hi,
                         st.sft.z3_interval, 256)
    narrowed = idx.query_equals(
        "c", None, (plan.rbin, plan.rzlo, plan.rzhi))
    assert 0 < len(narrowed) < len(full)
    # spatial narrowing too: a small bbox plan shrinks further
    plan_sp = plan_z3_query([(-5.0, -5.0, 5.0, 5.0)], lo, hi,
                            st.sft.z3_interval, 256)
    spatial = idx.query_equals(
        "c", None, (plan_sp.rbin, plan_sp.rzlo, plan_sp.rzhi))
    assert len(spatial) < len(narrowed)


def test_z3_tier_planner_exact(store):
    """attr = X AND bbox AND time through the planner: exact results,
    attr strategy chosen with the z3-tier refinement wired in."""
    ecql = ("name = 'b' AND BBOX(geom, -5, -5, 5, 5) AND dtg DURING "
            "2018-01-02T00:00:00Z/2018-01-06T00:00:00Z")
    res = store.query_result("tiered", ecql)
    st = store._store("tiered")
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(res.positions), want)
    assert res.strategy.index == "attr:name"
    assert res.strategy.geometries  # spatial tier info reached the plan


def test_sharded_attribute_z3_tier_candidate_parity():
    """The mesh attribute index materializes the z3 tier (fused rank|bin
    + z keys): equality + bbox/time queries produce candidate sets
    matching the single-chip z3-tiered index — not the whole value run
    (round-3 next #6)."""
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.attribute import ShardedAttributeIndex

    rng = np.random.default_rng(9)
    n = 30_000
    name = rng.choice(["a", "b", "c", "d"], n).astype(object)
    dtg = rng.integers(MS_2018, MS_2018 + 30 * DAY, n)
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)

    from geomesa_tpu.curve import to_binned_time
    from geomesa_tpu.curve.sfc import z3_sfc
    from geomesa_tpu.curve.binnedtime import TimePeriod
    bins, offs = to_binned_time(dtg.astype(np.int64), TimePeriod.WEEK)
    sfc = z3_sfc(TimePeriod.WEEK)
    z = sfc.index(x, y, offs.astype(np.float64), xp=np)

    single = AttributeIndex.build_z3("name", name, bins, z)
    sharded = ShardedAttributeIndex.build(
        "name", name, mesh=device_mesh(), sec_bins=bins, sec_z=z)
    assert sharded.tier == "z3" and sharded.sec_z is not None

    from geomesa_tpu.index.z3 import plan_z3_query
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS_2018 + 5 * DAY, MS_2018 + 12 * DAY
    plan = plan_z3_query([box], lo, hi, TimePeriod.WEEK, 256)
    ranges = (plan.rbin, plan.rzlo, plan.rzhi)

    got = sharded.query_equals("c", z3_ranges=ranges)
    want = single.query_equals("c", z3_ranges=ranges)
    np.testing.assert_array_equal(got, np.sort(want))
    # the tier genuinely narrows: candidates far fewer than the value run
    assert 0 < len(got) < (name == "c").sum() * 0.9

    got_in = sharded.query_in(["a", "d"], z3_ranges=ranges)
    want_in = single.query_in(["a", "d"], z3_ranges=ranges)
    np.testing.assert_array_equal(got_in, np.sort(np.unique(want_in)))


def test_mesh_store_attr_query_uses_z3_tier():
    """Through the store: attr+bbox+time queries on a mesh store route
    z3-tier refined candidates and stay oracle-exact."""
    from geomesa_tpu.parallel import device_mesh

    rng = np.random.default_rng(10)
    n = 20_000
    ds = TpuDataStore(mesh=device_mesh())
    ds.create_schema("evt", "name:String:index=true,dtg:Date,*geom:Point")
    ds.write("evt", {
        "name": rng.choice(["a", "b", "c"], n).astype(object),
        "dtg": rng.integers(MS_2018, MS_2018 + 21 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n))})
    st = ds._store("evt")
    idx = st.attribute_index("name")
    assert idx.tier == "z3"
    ecql = ("name = 'b' AND BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg "
            "DURING 2018-01-05T00:00:00Z/2018-01-12T00:00:00Z")
    got = ds.query_result(
        "evt", Query.of(ecql, hints={"QUERY_INDEX": "attr"}))
    assert got.strategy.index == "attr:name"
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(got.positions), want)
