"""SQL text front-end: SELECT statements lowered onto the store planner
(the reference's GeoMesaSparkSQL + SQLRules user surface — round-3
next #10)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql
from geomesa_tpu.sql import parse_sql, sql_query

MS = 1514764800000
DAY = 86_400_000


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(3)
    n = 20_000
    store = TpuDataStore()
    store.create_schema(
        "evt", "name:String:index=true,score:Double,dtg:Date,*geom:Point")
    store.write("evt", {
        "name": rng.choice(["a", "b", "c"], n).astype(object),
        "score": rng.uniform(0, 100, n),
        "dtg": rng.integers(MS, MS + 14 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n))})
    return store


def test_select_star_where_ecql(ds):
    got = sql_query(ds, "SELECT * FROM evt WHERE "
                        "BBOX(geom, -74.5, 40.5, -73.5, 41.5)")
    st = ds._store("evt")
    want = np.flatnonzero(evaluate_filter(
        parse_ecql("BBOX(geom, -74.5, 40.5, -73.5, 41.5)"), st.batch))
    assert len(got) == len(want)


def test_spatial_st_call_rewrites_to_ecql(ds):
    sql = ("SELECT name, score FROM evt WHERE st_intersects(geom, "
           "st_geomFromWKT('POLYGON((-74.5 40.5, -73.5 40.5, -73.5 41.5,"
           " -74.5 41.5, -74.5 40.5))')) AND name = 'a'")
    got = sql_query(ds, sql)
    st = ds._store("evt")
    want = np.flatnonzero(evaluate_filter(parse_ecql(
        "INTERSECTS(geom, POLYGON((-74.5 40.5, -73.5 40.5, -73.5 41.5, "
        "-74.5 41.5, -74.5 40.5))) AND name = 'a'"), st.batch))
    assert len(got) == len(want)
    assert set(got.columns) == {"name", "score"}


def test_order_by_limit(ds):
    got = sql_query(ds, "SELECT name, score FROM evt WHERE name = 'b' "
                        "ORDER BY score DESC LIMIT 5")
    scores = got.column("score")
    assert len(scores) == 5
    st = ds._store("evt")
    b_scores = st.batch.column("score")[st.batch.column("name") == "b"]
    np.testing.assert_allclose(scores, np.sort(b_scores)[::-1][:5])


def test_group_by_aggregates(ds):
    out = sql_query(ds, "SELECT count(*) AS n, avg(score) AS avg_s, "
                        "max(score) AS mx FROM evt GROUP BY name "
                        "ORDER BY n DESC")
    st = ds._store("evt")
    names = st.batch.column("name")
    assert list(out["name"]) == sorted(
        set(names), key=lambda v: -int((names == v).sum()))
    for i, v in enumerate(out["name"]):
        m = names == v
        assert out["n"][i] == m.sum()
        assert out["avg_s"][i] == pytest.approx(
            st.batch.column("score")[m].mean())
        assert out["mx"][i] == pytest.approx(
            st.batch.column("score")[m].max())


def test_group_by_order_by_unknown_column(ds):
    # ADVICE r4: ordering by a column outside the aggregation output is
    # a validation error with the supported-grammar message, not a bare
    # KeyError
    with pytest.raises(ValueError, match="ORDER BY column 'score'"):
        sql_query(ds, "SELECT count(*) AS n FROM evt GROUP BY name "
                      "ORDER BY score")


def test_global_count(ds):
    n = sql_query(ds, "SELECT count(*) FROM evt WHERE name = 'c'")
    st = ds._store("evt")
    assert n == int((st.batch.column("name") == "c").sum())


def test_global_aggregates_without_group_by(ds):
    # round-3 VERDICT weak #8: sum(col)/avg(col) global used to cliff
    out = sql_query(ds, "SELECT sum(score) AS s, avg(score) AS a, "
                        "min(score) AS lo, max(score) AS hi, "
                        "count(score) AS n FROM evt WHERE name = 'a'")
    st = ds._store("evt")
    sel = st.batch.column("score")[st.batch.column("name") == "a"]
    assert out["n"] == len(sel)
    assert out["s"] == pytest.approx(sel.sum())
    assert out["a"] == pytest.approx(sel.mean())
    assert (out["lo"], out["hi"]) == (sel.min(), sel.max())
    empty = sql_query(ds, "SELECT sum(score) AS s FROM evt "
                          "WHERE name = 'nope'")
    assert empty["s"] is None
    with pytest.raises(ValueError, match="single row"):
        sql_query(ds, "SELECT sum(score) AS s FROM evt ORDER BY s")


def test_parse_errors():
    with pytest.raises(ValueError, match="unsupported SQL"):
        parse_sql("DELETE FROM evt")
    with pytest.raises(ValueError, match="GROUP BY"):
        parse_sql("SELECT name, sum(score) FROM evt")
    p = parse_sql("SELECT * FROM evt WHERE st_dwithin(geom, "
                  "st_geomFromWKT('POINT(0 0)'), 1000) LIMIT 3;")
    assert p.where == "DWITHIN(geom, POINT(0 0), 1000, meters)"
    assert p.limit == 3


def test_cli_sql_command(tmp_path):
    import io
    from contextlib import redirect_stdout

    from geomesa_tpu.cli.main import build_parser

    ds = TpuDataStore(str(tmp_path / "cat"))
    ds.create_schema("pts", "v:Int,dtg:Date,*geom:Point")
    ds.write("pts", {"v": np.arange(4), "dtg": np.full(4, MS),
                     "geom": (np.array([-74.0, -73.5, 10.0, 11.0]),
                              np.array([40.7, 41.0, 5.0, 6.0]))})
    ds.flush("pts")
    parser = build_parser()
    args = parser.parse_args([
        "sql", "-c", str(tmp_path / "cat"),
        "SELECT * FROM pts WHERE BBOX(geom, -75, 40, -73, 42)"])
    buf = io.StringIO()
    with redirect_stdout(buf):
        args.fn(args)
    lines = buf.getvalue().strip().splitlines()
    assert lines[0].startswith("fid,v,dtg")
    assert len(lines) == 3  # header + 2 hits
    assert "POINT" in lines[1]


def test_group_by_rejects_stray_columns(ds):
    with pytest.raises(ValueError, match="GROUP BY"):
        sql_query(ds, "SELECT score, count(*) AS n FROM evt GROUP BY name")


class TestHaving:
    """HAVING filters GROUP BY output rows — by alias, by the group
    column, or by an un-projected aggregate (computed hidden)."""

    def _store(self):
        import numpy as np

        from geomesa_tpu.datastore import TpuDataStore
        ds = TpuDataStore()
        ds.create_schema("t", "name:String,v:Int,dtg:Date,*geom:Point")
        names = np.array(["a"] * 5 + ["b"] * 3 + ["c"] * 2, object)
        ds.write("t", {"name": names, "v": np.arange(10),
                       "dtg": np.full(10, 1514764800000),
                       "geom": (np.zeros(10), np.zeros(10))})
        return ds

    def test_having_on_alias(self):
        import numpy as np
        ds = self._store()
        out = sql_query(ds, "SELECT count(*) AS n FROM t GROUP BY name "
                            "HAVING n >= 3 ORDER BY n DESC")
        assert list(out["name"]) == ["a", "b"]
        assert list(np.asarray(out["n"])) == [5, 3]

    def test_having_on_unprojected_aggregate(self):
        ds = self._store()
        out = sql_query(ds, "SELECT name FROM t GROUP BY name "
                            "HAVING sum(v) > 10 AND count(*) < 4")
        # sums: a=0+1+2+3+4=10, b=5+6+7=18, c=8+9=17
        assert list(out["name"]) == ["b", "c"]
        assert set(out) == {"name"} | set()  # hidden aggs dropped

    def test_having_on_group_column_string(self):
        ds = self._store()
        out = sql_query(ds, "SELECT count(*) AS n FROM t GROUP BY name "
                            "HAVING name != 'a'")
        assert list(out["name"]) == ["b", "c"]

    def test_having_requires_group_by(self):
        ds = self._store()
        with pytest.raises(ValueError, match="HAVING requires GROUP"):
            sql_query(ds, "SELECT count(*) FROM t HAVING count(*) > 1")

    def test_having_malformed_numeric_literal_grammar_error(self):
        # '1e' and '+-3' matched the old sloppy literal class and blew
        # up in float() with a raw ValueError (round-4 ADVICE)
        ds = self._store()
        for bad in ("1e", "+-3", "1.2.3", "e5"):
            with pytest.raises(ValueError, match="not a number"):
                sql_query(ds, "SELECT count(*) AS n FROM t "
                              f"GROUP BY name HAVING n > {bad}")

    def test_having_string_vs_numeric_aggregate_parse_error(self):
        # a quoted literal ordered against count()/sum() used to surface
        # as a numpy TypeError at evaluation (round-4 ADVICE)
        ds = self._store()
        with pytest.raises(ValueError, match="is numeric"):
            sql_query(ds, "SELECT count(*) AS n FROM t GROUP BY name "
                          "HAVING sum(v) > 'abc'")

    def test_having_string_vs_numeric_alias_parse_error(self):
        # same check through an ALIAS of a numeric aggregate
        ds = self._store()
        with pytest.raises(ValueError, match="is numeric"):
            sql_query(ds, "SELECT count(*) AS n FROM t GROUP BY name "
                          "HAVING n > 'abc'")

    def test_having_unterminated_string_literal_rejected(self):
        # a missing close quote must not silently parse as '' or
        # swallow the quote into the value
        ds = self._store()
        for bad in ("'b", "'a'b'"):
            with pytest.raises(ValueError, match="unterminated|"
                                                 "unsupported HAVING"):
                sql_query(ds, "SELECT name FROM t GROUP BY name "
                              f"HAVING max(name) >= {bad}")

    def test_having_string_vs_min_max_stays_legal(self):
        # min/max inherit the column type — string comparisons are fine
        ds = self._store()
        out = sql_query(ds, "SELECT name FROM t GROUP BY name "
                            "HAVING max(name) >= 'b'")
        assert list(out["name"]) == ["b", "c"]

    def test_having_unknown_alias_rejected(self):
        ds = self._store()
        with pytest.raises(ValueError, match="HAVING references"):
            sql_query(ds, "SELECT count(*) AS n FROM t GROUP BY name "
                          "HAVING z > 1")


def test_select_distinct():
    import numpy as np

    from geomesa_tpu.datastore import TpuDataStore
    ds = TpuDataStore()
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    ds.write("t", {"name": np.array(["b", "a", "b", "c"], object),
                   "dtg": np.full(4, 1514764800000),
                   "geom": (np.zeros(4), np.zeros(4))})
    out = sql_query(ds, "SELECT DISTINCT name FROM t ORDER BY name")
    assert list(out["name"]) == ["a", "b", "c"]
    assert set(out) == {"name"}
    with pytest.raises(ValueError, match="single column"):
        sql_query(ds, "SELECT DISTINCT name, dtg FROM t")


def test_alias_shadowing_group_column_rejected():
    import numpy as np

    from geomesa_tpu.datastore import TpuDataStore
    ds = TpuDataStore()
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    ds.write("t", {"name": np.array(["a"], object),
                   "dtg": np.full(1, 1514764800000),
                   "geom": (np.zeros(1), np.zeros(1))})
    with pytest.raises(ValueError, match="collides with the GROUP BY"):
        sql_query(ds, "SELECT count(*) AS name FROM t GROUP BY name")


class TestExpressionProjections:
    """SELECT-list st_* expressions (the reference's SQLTypes UDF
    surface): push-down scan, per-hit evaluation, dict-of-columns
    result."""

    def _store(self):
        import numpy as np

        from geomesa_tpu.datastore import TpuDataStore
        ds = TpuDataStore()
        ds.create_schema("t", "name:String,v:Double,dtg:Date,"
                              "*geom:Point")
        self.x = np.array([-74.0, 2.3, 116.4])
        self.y = np.array([40.7, 48.8, 39.9])
        ds.write("t", {"name": np.array(["a", "b", "c"], object),
                       "v": np.array([1.0, 2.0, 3.0]),
                       "dtg": np.full(3, 1514764800000),
                       "geom": (self.x, self.y)})
        return ds

    def test_st_x_y_with_plain_columns(self):
        import numpy as np
        ds = self._store()
        out = sql_query(ds, "SELECT st_x(geom) AS lon, st_y(geom) AS "
                            "lat, name FROM t ORDER BY lon")
        order = np.argsort(self.x)
        np.testing.assert_allclose(out["lon"], self.x[order])
        np.testing.assert_allclose(out["lat"], self.y[order])
        assert list(out["name"]) == list(
            np.array(["a", "b", "c"], object)[order])

    def test_st_astext_and_translate(self):
        ds = self._store()
        out = sql_query(ds, "SELECT st_asText(geom) FROM t WHERE "
                            "name = 'a'")
        assert out["st_astext_geom"][0] == "POINT (-74 40.7)"
        out = sql_query(ds, "SELECT st_translate(geom, 1, 2) AS g "
                            "FROM t WHERE name = 'a'")
        g = out["g"][0]
        assert abs(g.x - -73.0) < 1e-12 and abs(g.y - 42.7) < 1e-12

    def test_pushed_filter_and_limit(self):
        ds = self._store()
        out = sql_query(ds, "SELECT st_x(geom) AS lon FROM t WHERE "
                            "BBOX(geom,-80,35,10,50) LIMIT 1")
        assert len(out["lon"]) == 1

    def test_exprs_reject_aggregate_mix(self):
        ds = self._store()
        with pytest.raises(ValueError, match="expression projections"):
            sql_query(ds, "SELECT st_x(geom), count(*) FROM t "
                          "GROUP BY name")

    def test_unknown_function_rejected(self):
        ds = self._store()
        with pytest.raises(ValueError, match="not a projectable"):
            sql_query(ds, "SELECT st_nonsense(geom) FROM t")


def test_expr_order_by_unprojected_schema_column():
    import numpy as np

    from geomesa_tpu.datastore import TpuDataStore
    ds = TpuDataStore()
    ds.create_schema("t", "v:Double,dtg:Date,*geom:Point")
    ds.write("t", {"v": np.array([3.0, 1.0, 2.0]),
                   "dtg": np.full(3, 1514764800000),
                   "geom": (np.array([1.0, 2.0, 3.0]),
                            np.zeros(3))})
    out = sql_query(ds, "SELECT st_x(geom) AS lon FROM t ORDER BY v")
    np.testing.assert_allclose(out["lon"], [2.0, 3.0, 1.0])


def test_expr_secondary_packed_geometry_rejected():
    import numpy as np

    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry.types import Polygon
    ds = TpuDataStore()
    ds.create_schema("t", "dtg:Date,*shape:Polygon")
    poly = Polygon(np.array([(0.0, 0), (1, 0), (1, 1), (0.0, 0)]))
    ds.write("t", {"dtg": np.full(1, 1514764800000), "shape": [poly]})
    out = sql_query(ds, "SELECT st_asText(shape) AS w FROM t")
    assert out["w"][0].startswith("POLYGON")


def test_expr_validation_pre_scan():
    """Unknown function, bad arity, non-geometry column, and unknown
    ORDER BY all raise ValueError before any scan runs."""
    import numpy as np

    from geomesa_tpu.datastore import TpuDataStore
    ds = TpuDataStore()
    ds.create_schema("t", "v:Double,dtg:Date,*geom:Point")
    ds.write("t", {"v": np.ones(3), "dtg": np.full(3, 1514764800000),
                   "geom": (np.zeros(3), np.zeros(3))})
    with pytest.raises(ValueError, match="argument"):
        sql_query(ds, "SELECT st_bufferPoint(geom) FROM t")
    with pytest.raises(ValueError, match="needs a geometry column"):
        sql_query(ds, "SELECT st_x(v) FROM t")
    with pytest.raises(ValueError, match="projection output or the"):
        sql_query(ds, "SELECT st_x(geom) AS lon FROM t ORDER BY bogus")
    # optional args within bounds still pass
    out = sql_query(ds, "SELECT st_bufferPoint(geom, 1000, 8) AS b "
                        "FROM t LIMIT 1")
    assert len(out["b"]) == 1


def test_order_by_geometry_valued_alias_rejected():
    import numpy as np

    from geomesa_tpu.datastore import TpuDataStore
    ds = TpuDataStore()
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.write("t", {"dtg": np.full(2, 1514764800000),
                   "geom": (np.zeros(2), np.zeros(2))})
    with pytest.raises(ValueError, match="produces geometry values"):
        sql_query(ds, "SELECT st_translate(geom, 1, 2) AS g FROM t "
                      "ORDER BY g")


class TestGroupByExpression:
    """GROUP BY an expression alias (the round-4 weak-#7 wall:
    ``GROUP BY st_geohash(geom)``): one scan, the key computed on the
    hit batch, the shared reduction, HAVING/ORDER/LIMIT composing."""

    def _store(self):
        import numpy as np

        from geomesa_tpu.datastore import TpuDataStore
        rng = np.random.default_rng(3)
        n = 20_000
        ds = TpuDataStore()
        ds.create_schema("t", "v:Double,dtg:Date,*geom:Point")
        x = rng.uniform(-75, -73, n)
        y = rng.uniform(40, 42, n)
        v = rng.uniform(0, 10, n)
        ds.write("t", {"v": v, "dtg": np.full(n, 1514764800000),
                       "geom": (x, y)})
        return ds, x, y, v

    def test_geohash_group_matches_pandas(self):
        import numpy as np
        import pandas as pd

        from geomesa_tpu.sql.functions import st_geoHash
        ds, x, y, v = self._store()
        out = sql_query(ds, "SELECT st_geohash(geom, 4) AS gh, "
                            "count(*) AS n, sum(v) AS sv FROM t "
                            "GROUP BY gh HAVING n > 100 "
                            "ORDER BY n DESC LIMIT 5")
        df = pd.DataFrame({"gh": np.asarray(st_geoHash((x, y), 4)),
                           "v": v})
        want = df.groupby("gh").agg(
            n=("gh", "size"), sv=("v", "sum")).reset_index()
        want = want[want.n > 100].sort_values(
            "n", ascending=False).head(5)
        assert list(out["gh"]) == list(want.gh)
        assert list(np.asarray(out["n"])) == list(want.n)
        np.testing.assert_allclose(np.asarray(out["sv"]),
                                   want.sv.to_numpy())

    def test_where_pushes_down(self):
        import numpy as np
        import pandas as pd

        from geomesa_tpu.sql.functions import st_geoHash
        ds, x, y, v = self._store()
        out = sql_query(ds, "SELECT st_geohash(geom, 3) AS gh, "
                            "count(*) AS n FROM t WHERE v > 5 "
                            "GROUP BY gh")
        m = v > 5
        want = pd.DataFrame(
            {"gh": np.asarray(st_geoHash((x[m], y[m]), 3))}
        ).groupby("gh").size()
        got = dict(zip(out["gh"], np.asarray(out["n"]).tolist()))
        assert got == want.to_dict()

    def test_geometry_valued_key_rejected(self):
        ds, *_ = self._store()
        with pytest.raises(ValueError, match="produces geometry"):
            sql_query(ds, "SELECT st_centroid(geom) AS c, count(*) "
                          "AS n FROM t GROUP BY c")

    def test_non_key_expression_still_rejected(self):
        ds, *_ = self._store()
        with pytest.raises(ValueError, match="only as the group key"):
            sql_query(ds, "SELECT st_x(geom) AS lon, count(*) AS n "
                          "FROM t GROUP BY v")

    def test_expr_distinct_idiom(self):
        import numpy as np
        ds, x, y, v = self._store()
        out = sql_query(ds, "SELECT st_geohash(geom, 3) AS gh FROM t "
                            "GROUP BY gh")
        from geomesa_tpu.sql.functions import st_geoHash
        want = sorted(set(np.asarray(st_geoHash((x, y), 3)).tolist()))
        assert sorted(out["gh"].tolist()) == want
        assert set(out) == {"gh"}

    def test_alias_shadowing_schema_attr_rejected(self):
        ds, *_ = self._store()
        with pytest.raises(ValueError, match="shadows a schema"):
            sql_query(ds, "SELECT st_geohash(geom, 3) AS v, min(v) AS "
                          "mv FROM t GROUP BY v")

    def test_geohash_on_polygon_rejected_pre_scan(self):
        import numpy as np

        from geomesa_tpu.datastore import TpuDataStore
        from geomesa_tpu.geometry.types import Polygon
        ds = TpuDataStore()
        ds.create_schema("p", "v:Int,*geom:Polygon")
        ds.write("p", {"v": np.array([1]),
                       "geom": [Polygon([(0, 0), (1, 0), (1, 1),
                                         (0, 1)])]})
        with pytest.raises(ValueError, match="Point column"):
            sql_query(ds, "SELECT st_geohash(geom, 4) AS gh, count(*) "
                          "AS n FROM p GROUP BY gh")
