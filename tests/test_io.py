"""Converters, exports and BIN encoding (reference: geomesa-convert,
tools export formats, BinaryOutputEncoder)."""

import json

import numpy as np
import pytest

from geomesa_tpu.features import parse_spec
from geomesa_tpu.io import (
    EvaluationContext,
    converter_from_config,
    decode_bin,
    encode_bin,
    from_parquet,
    to_arrow,
    to_csv,
    to_geojson,
    to_parquet,
)

CSV = """2018-01-01 10:00:00,alice,42,-74.1,40.7
2018-01-01 11:30:00,bob,7,2.35,48.85
2018-01-02 09:15:00,carol,99,139.7,35.6
"""


@pytest.fixture
def sft():
    return parse_spec("people", "name:String,age:Int,dtg:Date,*geom:Point")


@pytest.fixture
def csv_converter(sft):
    return converter_from_config(sft, {
        "type": "delimited-text",
        "format": "CSV",
        "id-field": "md5($1)",
        "fields": [
            {"name": "dtg", "transform": "date('yyyy-MM-dd HH:mm:ss', $0)"},
            {"name": "name", "transform": "$1"},
            {"name": "age", "transform": "toInt($2)"},
            {"name": "geom", "transform": "point($3, $4)"},
        ],
    })


def test_csv_converter(csv_converter):
    ec = EvaluationContext()
    batch = csv_converter.convert(CSV, ec)
    assert len(batch) == 3 and ec.success == 3 and ec.failure == 0
    assert batch.column("name")[1] == "bob"
    assert batch.column("age")[2] == 99
    x, y = batch.geom_xy()
    np.testing.assert_allclose(x, [-74.1, 2.35, 139.7])
    # 2018-01-01T10:00:00Z
    assert batch.column("dtg")[0] == 1514764800000 + 10 * 3_600_000
    # md5 ids are deterministic
    assert batch.ids[0] == __import__("hashlib").md5(b"alice").hexdigest()


def test_json_converter(sft):
    conv = converter_from_config(sft, {
        "type": "json",
        "fields": [
            {"name": "dtg", "transform": "millisToDate($ts)"},
            {"name": "name", "transform": "$user.name"},
            {"name": "age", "transform": "toInt($age)"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
    })
    src = "\n".join(json.dumps(r) for r in [
        {"ts": 1514764800000, "user": {"name": "a"}, "age": 1, "lon": 0.5, "lat": 1.5},
        {"ts": 1514764800001, "user": {"name": "b"}, "age": 2, "lon": 2.5, "lat": 3.5},
    ])
    batch = conv.convert(src)
    assert len(batch) == 2
    assert list(batch.column("name")) == ["a", "b"]
    np.testing.assert_allclose(batch.geom_xy()[1], [1.5, 3.5])


def test_geojson_converter():
    sft = parse_spec("places", "title:String,*geom:Point")
    conv = converter_from_config(sft, {
        "type": "geojson",
        "fields": [
            {"name": "title", "transform": "$title"},
            {"name": "geom", "transform": "$geometry"},
        ],
    })
    fc = {"type": "FeatureCollection", "features": [
        {"type": "Feature", "id": "f1",
         "geometry": {"type": "Point", "coordinates": [1.0, 2.0]},
         "properties": {"title": "spot"}},
    ]}
    batch = conv.convert(json.dumps(fc))
    assert len(batch) == 1
    assert batch.column("title")[0] == "spot"


def test_error_mode(sft):
    conv = converter_from_config(sft, {
        "type": "csv",
        "fields": [{"name": "age", "transform": "toInt($1)"}],
        "options": {"error-mode": "skip"},
    })
    ec = EvaluationContext()
    batch = conv.convert("x,notanumber\n", ec)
    assert len(batch) == 0 and ec.failure == 1
    conv2 = converter_from_config(sft, {
        "type": "csv",
        "fields": [{"name": "age", "transform": "toInt($1)"}],
        "options": {"error-mode": "raise"},
    })
    with pytest.raises(Exception):
        conv2.convert("x,notanumber\n")


@pytest.fixture
def batch(sft):
    return __import__("geomesa_tpu.features", fromlist=["FeatureBatch"]).FeatureBatch.from_dict(
        sft,
        {
            "name": ["a", "b"],
            "age": [1, 2],
            "dtg": np.array([1514764800000, 1514764900000]),
            "geom": (np.array([0.0, 1.0]), np.array([2.0, 3.0])),
        },
        ids=["f1", "f2"],
    )


def test_arrow_roundtrip(batch, tmp_path):
    pytest.importorskip("pyarrow")
    table = to_arrow(batch)
    assert table.num_rows == 2
    assert b"geomesa_tpu.sft" in (table.schema.metadata or {})
    path = str(tmp_path / "out.parquet")
    to_parquet(batch, path)
    back = from_parquet(path)
    assert len(back) == 2
    np.testing.assert_array_equal(back.column("age"), batch.column("age"))
    np.testing.assert_array_equal(back.column("dtg"), batch.column("dtg"))
    np.testing.assert_allclose(back.geom_xy()[0], batch.geom_xy()[0])
    assert list(back.ids) == ["f1", "f2"]


def test_csv_export(batch):
    text = to_csv(batch)
    lines = text.strip().splitlines()
    assert lines[0] == "id,name,age,dtg,geom"
    assert "POINT (0.0 2.0)" in lines[1]
    assert "2018-01-01T00:00:00.000" in lines[1]


def test_geojson_export(batch):
    fc = json.loads(to_geojson(batch))
    assert fc["type"] == "FeatureCollection"
    assert fc["features"][1]["geometry"]["coordinates"] == [1.0, 3.0]
    assert fc["features"][0]["properties"]["name"] == "a"


def test_bin_roundtrip():
    x = np.array([-74.1, 2.35], dtype=np.float32)
    y = np.array([40.7, 48.85], dtype=np.float32)
    t = np.array([1514764800000, 1514764900000])
    blob = encode_bin(x, y, t, track=np.array(["v1", "v2"]))
    assert len(blob) == 32  # 2 × 16 bytes
    back = decode_bin(blob)
    np.testing.assert_allclose(back["lon"], x)
    np.testing.assert_allclose(back["lat"], y)
    np.testing.assert_array_equal(back["dtg_ms"], t // 1000 * 1000)
    # labelled variant
    blob24 = encode_bin(x, y, t, track=["v1", "v2"], label=["ab", "cdefghij"])
    assert len(blob24) == 48
    back24 = decode_bin(blob24, labelled=True)
    assert list(back24["label"]) == ["ab", "cdefghij"]


class TestExpressionRegistry:
    """The converter function registry breadth (String/Math/Misc/
    Collection FunctionFactory analogs)."""

    def _ev(self, text, cols):
        from geomesa_tpu.io.expressions import parse_expression
        return parse_expression(text).evaluate(cols)

    def test_string_functions(self):
        import numpy as np
        cols = {"s": np.array(["  'Hello'  ", "  World  "], dtype=object)}
        assert list(self._ev("stripQuotes(trim($s))", cols)) == ["Hello", "World"]
        assert list(self._ev("capitalize(lowercase(trim($s)))", cols)) == ["'hello'", "World"]
        assert list(self._ev("strlen(trim($s))", cols)) == [7, 5]
        assert list(self._ev("replace(trim($s), 'l', 'L')", cols)) == ["'HeLLo'", "WorLd"]
        assert list(self._ev("remove(trim($s), 'o')", cols)) == ["'Hell'", "Wrld"]
        assert list(self._ev("regexReplace('[lo]+', '_', trim($s))", cols)) == ["'He_'", "W_r_d"]
        assert list(self._ev("substr(trim($s), 1, 4)", cols)) == ["Hel", "orl"]
        assert list(self._ev("stripPrefix(trim($s), 'W')", cols))[1] == "orld"
        assert list(self._ev("stripSuffix(trim($s), 'd')", cols))[1] == "Worl"

    def test_printf_mkstring(self):
        import numpy as np
        cols = {"a": np.array(["x", "y"], dtype=object),
                "b": np.array([1, 2])}
        assert list(self._ev("printf('%s-%s', $a, $b)", cols)) == ["x-1", "y-2"]
        assert list(self._ev("mkstring('|', $a, $b)", cols)) == ["x|1", "y|2"]

    def test_math_functions(self):
        import numpy as np
        cols = {"a": np.array([1.0, 2.0]), "b": np.array([3.0, 5.0])}
        np.testing.assert_allclose(self._ev("add($a, $b, 1)", cols), [5, 8])
        np.testing.assert_allclose(self._ev("subtract($b, $a)", cols), [2, 3])
        np.testing.assert_allclose(self._ev("multiply($a, $b)", cols), [3, 10])
        np.testing.assert_allclose(self._ev("divide($b, $a)", cols), [3, 2.5])
        np.testing.assert_allclose(self._ev("mean($a, $b)", cols), [2, 3.5])
        np.testing.assert_allclose(self._ev("min($a, $b)", cols), [1, 2])
        np.testing.assert_allclose(self._ev("max($a, $b)", cols), [3, 5])

    def test_misc_functions(self):
        import numpy as np
        import pytest
        cols = {"v": np.array(["a", "", None], dtype=object)}
        out = self._ev("emptyToNull($v)", cols)
        assert out[0] == "a" and out[1] is None and out[2] is None
        out = self._ev("withDefault($v, 'dflt')", cols)
        assert list(out) == ["a", "", "dflt"]
        with pytest.raises(ValueError, match="require"):
            self._ev("require($v)", cols)
        assert list(self._ev("lineNo()", cols)) == [0, 1, 2]
        assert list(self._ev("intToBoolean($x)", {"x": np.array([0, 3])})) == [False, True]
        assert list(self._ev("base64Decode(base64Encode($v))",
                             {"v": np.array(["ab"], dtype=object)})) == ["ab"]

    def test_collections(self):
        import numpy as np
        cols = {"csv": np.array(["a,b,c", "d,e,f"], dtype=object)}
        lists = self._ev("list($csv)", cols)
        assert lists[0] == ["a", "b", "c"]
        assert list(self._ev("listItem(list($csv), 1)", cols)) == ["b", "e"]


class TestExpressionEdgeCases:
    """Regressions: empty columns, ragged lists, single-eval semantics."""

    def _ev(self, text, cols):
        from geomesa_tpu.io.expressions import parse_expression
        return parse_expression(text).evaluate(cols)

    def test_with_default_empty_column(self):
        import numpy as np
        out = self._ev("withDefault($v, 'd')",
                       {"v": np.array([], dtype=object)})
        assert len(out) == 0

    def test_list_item_ragged(self):
        import numpy as np
        cols = {"csv": np.array(["a,b,c", "d,e"], dtype=object)}
        out = self._ev("listItem(list($csv), 2)", cols)
        assert out[0] == "c" and out[1] is None

    def test_printf_no_args(self):
        import numpy as np
        out = self._ev("printf('hello')", {"x": np.array([1, 2, 3])})
        assert list(out) == ["hello"] * 3

    def test_mkstring_single_column_eval(self):
        import numpy as np
        from geomesa_tpu.io import expressions as ex

        calls = {"n": 0}
        orig = ex._Ref.evaluate

        def counting(self, cols):
            calls["n"] += 1
            return orig(self, cols)

        ex._Ref.evaluate = counting
        try:
            cols = {"a": np.array(["x"] * 100, dtype=object),
                    "b": np.array(["y"] * 100, dtype=object)}
            out = self._ev("mkstring('|', $a, $b)", cols)
        finally:
            ex._Ref.evaluate = orig
        assert list(out)[:1] == ["x|y"]
        assert calls["n"] == 2  # once per argument, not per row


def test_uuidz3_and_typed_geometry_functions():
    import numpy as np
    from geomesa_tpu.io.expressions import parse_expression

    cols = {"x": np.array([-74.0, 30.0]), "y": np.array([40.7, -10.0]),
            "t": np.array([1514764800000, 1514851200000])}
    ids = parse_expression("uuidZ3($x, $y, $t)").evaluate(cols)
    assert len(ids) == 2 and len(set(ids)) == 2
    assert all(len(s) == 36 for s in ids)  # uuid-shaped

    wkts = {"w": np.array(["LINESTRING (0 0, 1 1)"], dtype=object)}
    geoms = parse_expression("linestring($w)").evaluate(wkts)
    assert type(geoms[0]).__name__ == "LineString"
    import pytest
    with pytest.raises(ValueError, match="polygon"):
        parse_expression("polygon($w)").evaluate(wkts)


def test_shapefile_export_roundtrip(tmp_path):
    """to_shapefile writes .shp/.shx/.dbf that our own reader (and hence
    the converter stack) reads back identically."""
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.features import FeatureBatch
    from geomesa_tpu.io.export import to_shapefile
    from geomesa_tpu.io.formats import read_shapefile

    sft = parse_spec("pts", "name:String,age:Int,*geom:Point")
    batch = FeatureBatch.from_dict(sft, {
        "name": ["alice", "bob", "carol"],
        "age": [30, 41, 25],
        "geom": (np.array([-74.0, 2.35, 139.7]),
                 np.array([40.7, 48.85, 35.6])),
    })
    path = str(tmp_path / "people.shp")
    to_shapefile(batch, path)
    geoms, attrs = read_shapefile(path, str(tmp_path / "people.dbf"))
    assert len(geoms) == 3
    np.testing.assert_allclose([g.x for g in geoms], [-74.0, 2.35, 139.7])
    assert [s.strip() for s in attrs["name"]] == ["alice", "bob", "carol"]
    assert [int(v) for v in attrs["age"]] == [30, 41, 25]


def test_shapefile_export_polygons(tmp_path):
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.features import FeatureBatch
    from geomesa_tpu.geometry import Polygon
    from geomesa_tpu.io.export import to_shapefile
    from geomesa_tpu.io.formats import read_shapefile

    hole = Polygon([(0, 0), (10, 0), (10, 10), (0, 10)],
                   (np.array([(4, 4), (6, 4), (6, 6), (4, 6)], float),))
    plain = Polygon([(20, 20), (24, 20), (24, 24), (20, 24)])
    sft = parse_spec("areas", "name:String,*geom:Polygon")
    batch = FeatureBatch.from_dict(sft, {
        "name": ["holed", "plain"], "geom": [hole, plain]})
    path = str(tmp_path / "areas")
    to_shapefile(batch, path)
    geoms, _ = read_shapefile(path + ".shp", path + ".dbf")
    assert len(geoms) == 2
    assert len(geoms[0].holes) == 1
    assert geoms[0].envelope.as_tuple() == (0.0, 0.0, 10.0, 10.0)
    assert geoms[1].envelope.as_tuple() == (20.0, 20.0, 24.0, 24.0)


def test_expression_functions_round2():
    """Round-2 expression additions: named date formats, dateToString,
    parseList/parseMap/mapValue, cast aliases, projectFrom."""
    from geomesa_tpu.io.expressions import parse_expression as pe

    cols = {
        "d": np.array(["20180105", "20180203"], dtype=object),
        "l": np.array(["1;2;3", "4"], dtype=object),
        "m": np.array(["a->1,b->2", ""], dtype=object),
        "n": np.array(["7", "8"], dtype=object),
    }
    ms = pe("basicDate($d)").evaluate(cols)
    np.testing.assert_array_equal(ms, [1515110400000, 1517616000000])
    assert list(pe("dateToString('yyyy-MM-dd', basicDate($d))")
                .evaluate(cols)) == ["2018-01-05", "2018-02-03"]
    assert list(pe("isoLocalDate($d)").evaluate(
        {"d": np.array(["2018-01-05"], dtype=object)})) == [1515110400000]
    lst = pe("parseList('int', $l, ';')").evaluate(cols)
    assert lst[0] == [1, 2, 3] and lst[1] == [4]
    mv = pe("mapValue(parseMap('string->int', $m), 'b')").evaluate(cols)
    assert mv[0] == 2 and mv[1] is None
    np.testing.assert_array_equal(pe("stringToLong($n)").evaluate(cols),
                                  [7, 8])
    assert pe("stringToBoolean($n)").evaluate(
        {"n": np.array(["true", "0"], dtype=object)}).tolist() == [True, False]
    assert pe("string2bytes($n)").evaluate(cols)[0] == b"7"
    now = pe("now()").evaluate(cols)
    assert len(now) == 2 and now[0] > 1_600_000_000_000
    # projectFrom: web-mercator meters back to lon/lat degrees
    x, y = pe("projectFrom('EPSG:3857', point($x, $y))").evaluate({
        "x": np.array([0.0]), "y": np.array([0.0])})
    assert abs(x[0]) < 1e-9 and abs(y[0]) < 1e-9


def test_date_to_string_millis_with_trailing_literal():
    """SSS followed by a literal ('Z') renders 3-digit millis — the old
    endswith('000') fixup left 6-digit microseconds (ADVICE r2)."""
    from geomesa_tpu.io.expressions import parse_expression as pe

    from geomesa_tpu.io.expressions import _fn_date_to_string, _Lit

    cols = {"t": np.array([1515110400123, 1515110400000], dtype=np.int64)}
    got = list(_fn_date_to_string(
        cols, _Lit("yyyy-MM-dd'T'HH:mm:ss.SSS'Z'"), pe("$t")))
    assert got == ["2018-01-05T00:00:00.123Z", "2018-01-05T00:00:00.000Z"]
