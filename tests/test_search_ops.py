"""searchsorted2 / expand_ranges kernels vs numpy equivalents."""

import jax.numpy as jnp
import numpy as np
import pytest

from geomesa_tpu.ops import expand_ranges, searchsorted2


def ref_searchsorted2(hi, lo, qh, ql, side):
    # composite via python tuples
    keys = list(zip(hi.tolist(), lo.tolist()))
    out = []
    import bisect
    for q in zip(qh.tolist(), ql.tolist()):
        fn = bisect.bisect_left if side == "left" else bisect.bisect_right
        out.append(fn(keys, q))
    return np.array(out)


def test_searchsorted2_matches_bisect(rng):
    n = 5000
    hi = np.sort(rng.integers(0, 50, n))
    lo = rng.integers(0, 1 << 40, n)
    # sort lexicographically
    order = np.lexsort((lo, hi))
    hi, lo = hi[order], lo[order]
    qh = rng.integers(-1, 52, 200)
    ql = rng.integers(0, 1 << 40, 200)
    for side in ("left", "right"):
        got = np.asarray(searchsorted2(jnp.asarray(hi), jnp.asarray(lo),
                                       jnp.asarray(qh), jnp.asarray(ql), side=side))
        np.testing.assert_array_equal(got, ref_searchsorted2(hi, lo, qh, ql, side))


def test_searchsorted2_empty_and_single():
    hi = jnp.asarray(np.array([5], dtype=np.int64))
    lo = jnp.asarray(np.array([7], dtype=np.int64))
    q = jnp.asarray(np.array([4, 5, 6], dtype=np.int64))
    ql = jnp.asarray(np.array([9, 7, 0], dtype=np.int64))
    got = np.asarray(searchsorted2(hi, lo, q, ql, side="left"))
    np.testing.assert_array_equal(got, [0, 0, 1])
    got_r = np.asarray(searchsorted2(hi, lo, q, ql, side="right"))
    np.testing.assert_array_equal(got_r, [0, 1, 1])


def test_expand_ranges_basic():
    starts = jnp.asarray(np.array([10, 100, 1000]))
    counts = jnp.asarray(np.array([3, 0, 2]))
    idx, valid, rid = expand_ranges(starts, counts, capacity=8)
    np.testing.assert_array_equal(np.asarray(idx)[np.asarray(valid)],
                                  [10, 11, 12, 1000, 1001])
    np.testing.assert_array_equal(np.asarray(rid)[np.asarray(valid)],
                                  [0, 0, 0, 2, 2])
    assert int(np.asarray(valid).sum()) == 5


def test_expand_ranges_exact_capacity():
    starts = jnp.asarray(np.array([0, 5]))
    counts = jnp.asarray(np.array([2, 2]))
    idx, valid, _ = expand_ranges(starts, counts, capacity=4)
    assert np.asarray(valid).all()
    np.testing.assert_array_equal(np.asarray(idx), [0, 1, 5, 6])


def test_coded_pos_bits_boundaries():
    from geomesa_tpu.ops.search import coded_pos_bits

    # 20 pos bits + 11 qid bits = 31 → int32-eligible layout
    assert coded_pos_bits(1 << 20, 1 << 11) == 20
    # one more pos bit overflows 31 → int64 fallback layout
    assert coded_pos_bits(1 << 21, 1 << 11) == 40
    assert coded_pos_bits(2, 2) == 1
    assert coded_pos_bits((1 << 40), 2) == 40
    # multihost gids span > 2^40 (process << 40 | row): the layout must
    # widen, not truncate process bits into the qid field
    assert coded_pos_bits(1 << 41, 4) == 41
    assert coded_pos_bits(1 << 42, 1 << 21) == 42
    with pytest.raises(ValueError, match="coded layout overflow"):
        coded_pos_bits(1 << 60, 1 << 10)


def test_query_many_int64_wire_path(monkeypatch):
    """Force the 40-bit int64 coding and check exactness (the layout used
    for shards too big for the int32 wire)."""
    import numpy as np

    from geomesa_tpu.index import z3 as z3mod

    monkeypatch.setattr(z3mod, "coded_pos_bits", lambda n, q: 40)
    rng = np.random.default_rng(8)
    n = 20_000
    ms = 1514764800000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(ms, ms + 14 * 86_400_000, n)
    idx = z3mod.Z3PointIndex.build(x, y, t, period="week")
    windows = [
        ([(-74.5, 40.5, -73.5, 41.5)], ms, ms + 7 * 86_400_000),
        ([(-74.2, 40.1, -73.8, 40.9)], ms + 86_400_000, ms + 3 * 86_400_000),
    ]
    out = idx.query_many(windows)
    for (boxes, lo, hi), hits in zip(windows, out):
        b = boxes[0]
        want = np.flatnonzero(
            (x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
            & (t >= lo) & (t <= hi))
        np.testing.assert_array_equal(hits, want)


def test_pack_wire_total_survives_int32(monkeypatch):
    """A candidate total ≥ 2^31 must survive the int32 wire (split-word
    header) so capacity overflow is detected, not silently wrapped."""
    import jax.numpy as jnp
    import numpy as np

    from geomesa_tpu.ops.search import _TOTAL_SPLIT, pack_wire

    big = (1 << 31) + 12345
    wire = np.asarray(pack_wire(
        jnp.int64(big), jnp.arange(4, dtype=jnp.int32),
        jnp.ones(4, dtype=bool), jnp.int32))
    decoded = (int(wire[0]) << _TOTAL_SPLIT) | int(wire[1])
    assert decoded == big
