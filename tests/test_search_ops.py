"""searchsorted2 / expand_ranges kernels vs numpy equivalents."""

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.ops import expand_ranges, searchsorted2


def ref_searchsorted2(hi, lo, qh, ql, side):
    # composite via python tuples
    keys = list(zip(hi.tolist(), lo.tolist()))
    out = []
    import bisect
    for q in zip(qh.tolist(), ql.tolist()):
        fn = bisect.bisect_left if side == "left" else bisect.bisect_right
        out.append(fn(keys, q))
    return np.array(out)


def test_searchsorted2_matches_bisect(rng):
    n = 5000
    hi = np.sort(rng.integers(0, 50, n))
    lo = rng.integers(0, 1 << 40, n)
    # sort lexicographically
    order = np.lexsort((lo, hi))
    hi, lo = hi[order], lo[order]
    qh = rng.integers(-1, 52, 200)
    ql = rng.integers(0, 1 << 40, 200)
    for side in ("left", "right"):
        got = np.asarray(searchsorted2(jnp.asarray(hi), jnp.asarray(lo),
                                       jnp.asarray(qh), jnp.asarray(ql), side=side))
        np.testing.assert_array_equal(got, ref_searchsorted2(hi, lo, qh, ql, side))


def test_searchsorted2_empty_and_single():
    hi = jnp.asarray(np.array([5], dtype=np.int64))
    lo = jnp.asarray(np.array([7], dtype=np.int64))
    q = jnp.asarray(np.array([4, 5, 6], dtype=np.int64))
    ql = jnp.asarray(np.array([9, 7, 0], dtype=np.int64))
    got = np.asarray(searchsorted2(hi, lo, q, ql, side="left"))
    np.testing.assert_array_equal(got, [0, 0, 1])
    got_r = np.asarray(searchsorted2(hi, lo, q, ql, side="right"))
    np.testing.assert_array_equal(got_r, [0, 1, 1])


def test_expand_ranges_basic():
    starts = jnp.asarray(np.array([10, 100, 1000]))
    counts = jnp.asarray(np.array([3, 0, 2]))
    idx, valid, rid = expand_ranges(starts, counts, capacity=8)
    np.testing.assert_array_equal(np.asarray(idx)[np.asarray(valid)],
                                  [10, 11, 12, 1000, 1001])
    np.testing.assert_array_equal(np.asarray(rid)[np.asarray(valid)],
                                  [0, 0, 0, 2, 2])
    assert int(np.asarray(valid).sum()) == 5


def test_expand_ranges_exact_capacity():
    starts = jnp.asarray(np.array([0, 5]))
    counts = jnp.asarray(np.array([2, 2]))
    idx, valid, _ = expand_ranges(starts, counts, capacity=4)
    assert np.asarray(valid).all()
    np.testing.assert_array_equal(np.asarray(idx), [0, 1, 5, 6])
