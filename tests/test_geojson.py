"""GeoJSON API tests (reference: GeoJsonQueryTest / GeoJsonGtIndexTest /
GeoJsonServletTest behaviors)."""

import json

import pytest

from geomesa_tpu.geojson import GeoJsonApp, GeoJsonIndex
from geomesa_tpu.geojson.query import json_path_get


def feat(fid, x, y, props=None, geom=None):
    return {"type": "Feature", "id": fid,
            "geometry": geom or {"type": "Point", "coordinates": [x, y]},
            "properties": props or {}}


@pytest.fixture
def idx():
    gj = GeoJsonIndex()
    gj.create_index("test", dtg_path="$.properties.dtg", points=True)
    gj.add("test", {"type": "FeatureCollection", "features": [
        feat("0", 30, 10, {"name": "n0", "score": 1,
                           "dtg": "2018-01-01T00:00:00Z"}),
        feat("1", 31, 10, {"name": "n1", "score": 5,
                           "dtg": "2018-01-02T00:00:00Z"}),
        feat("2", 32, 10, {"name": "n2", "score": 9,
                           "dtg": "2018-01-03T00:00:00Z",
                           "nested": {"tag": "x"}}),
    ]})
    return gj


def test_json_path_get():
    d = {"properties": {"a": {"b": [1, 2, {"c": 7}]}}, "id": "z"}
    assert json_path_get(d, "$.id") == "z"
    assert json_path_get(d, "a.b[2].c") == 7
    assert json_path_get(d, "$.properties.a.b[0]") == 1
    assert json_path_get(d, "missing") is None


def test_add_get_delete(idx):
    assert idx.get("test", "1")[0]["properties"]["name"] == "n1"
    assert idx.get("test", ["0", "2"])[0]["id"] == "0"
    assert idx.delete("test", "1") == 1
    assert idx.get("test", "1") == []
    assert len(idx.query("test", "{}")) == 2


def test_add_assigns_and_rejects_dup_ids(idx):
    ids = idx.add("test", feat("99", 0, 0, {"dtg": 0}))
    assert ids == ["99"]
    with pytest.raises(ValueError):
        idx.add("test", feat("99", 0, 0, {"dtg": 0}))


def test_query_equality_and_compare(idx):
    assert [f["id"] for f in idx.query("test", '{"name": "n1"}')] == ["1"]
    assert [f["id"] for f in
            idx.query("test", '{"score": {"$gte": 5}}')] == ["1", "2"]
    assert [f["id"] for f in
            idx.query("test", '{"score": {"$lt": 5}}')] == ["0"]
    # implicit AND of multiple keys
    assert [f["id"] for f in
            idx.query("test", '{"score": {"$gt": 0}, "name": "n2"}')] == ["2"]
    # json-path equality from document root
    assert [f["id"] for f in
            idx.query("test", '{"$.properties.nested.tag": "x"}')] == ["2"]


def test_query_spatial(idx):
    q = '{"geometry": {"$bbox": [30.5, 9, 32.5, 11]}}'
    assert [f["id"] for f in idx.query("test", q)] == ["1", "2"]
    q = ('{"geometry": {"$intersects": {"$geometry": '
         '{"type": "Point", "coordinates": [30, 10]}}}}')
    assert [f["id"] for f in idx.query("test", q)] == ["0"]
    q = ('{"geometry": {"$within": {"$geometry": {"type": "Polygon", '
         '"coordinates": [[[29,9],[31.5,9],[31.5,11],[29,11],[29,9]]]}}}}')
    assert [f["id"] for f in idx.query("test", q)] == ["0", "1"]
    q = ('{"geometry": {"$dwithin": {"$geometry": '
         '{"type": "Point", "coordinates": [30, 10]}, '
         '"$dist": 120, "$unit": "kilometers"}}}')
    assert [f["id"] for f in idx.query("test", q)] == ["0", "1"]


def test_query_or_and_combined(idx):
    q = '{"$or": [{"name": "n0"}, {"name": "n2"}]}'
    assert [f["id"] for f in idx.query("test", q)] == ["0", "2"]
    q = ('{"$or": [{"geometry": {"$bbox": [31.5, 9, 33, 11]}}, '
         '{"score": {"$lt": 2}}]}')
    assert [f["id"] for f in idx.query("test", q)] == ["0", "2"]


def test_query_transform(idx):
    out = idx.query("test", '{"score": {"$gt": 4}}',
                    transform={"n": "name", "fid": "$.id"})
    assert out == [{"n": "n1", "fid": "1"}, {"n": "n2", "fid": "2"}]


def test_update_via_id_path():
    gj = GeoJsonIndex()
    gj.create_index("u", id_path="$.properties.pk")
    gj.add("u", feat(None, 1, 1, {"pk": "a", "v": 1}))
    gj.update("u", feat(None, 2, 2, {"pk": "a", "v": 2}))
    assert gj.get("u", "a")[0]["properties"]["v"] == 2
    with pytest.raises(KeyError):
        gj.update("u", feat(None, 3, 3, {"pk": "nope"}))


def test_non_point_extents_index():
    gj = GeoJsonIndex()
    gj.create_index("polys")
    poly = {"type": "Polygon",
            "coordinates": [[[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]]]}
    gj.add("polys", feat("p", 0, 0, {}, geom=poly))
    gj.add("polys", feat("q", 0, 0, {},
                         geom={"type": "Point", "coordinates": [10, 10]}))
    hits = gj.query("polys", '{"geometry": {"$bbox": [1, 1, 2, 2]}}')
    assert [f["id"] for f in hits] == ["p"]


def wsgi(app, method, path, body=None):
    import io
    raw = json.dumps(body).encode() if body is not None else b""
    cap = {}

    def sr(status, headers):
        cap["status"] = int(status.split()[0])

    qs = ""
    if "?" in path:
        path, qs = path.split("?", 1)
    out = b"".join(app({
        "REQUEST_METHOD": method, "PATH_INFO": path, "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(raw)), "wsgi.input": io.BytesIO(raw),
    }, sr))
    return cap["status"], (json.loads(out) if out else None)


def test_servlet_roundtrip():
    from urllib.parse import quote
    app = GeoJsonApp()
    s, _ = wsgi(app, "POST", "/geojson/index/t?points=true")
    assert s == 201
    s, body = wsgi(app, "POST", "/geojson/index/t/features",
                   feat("f1", 5, 5, {"kind": "a"}))
    assert s == 201 and body["ids"] == ["f1"]
    s, body = wsgi(app, "GET", "/geojson/index/t/features/f1")
    assert s == 200 and body["properties"]["kind"] == "a"
    q = quote(json.dumps({"geometry": {"$bbox": [0, 0, 10, 10]}}))
    s, body = wsgi(app, "GET", f"/geojson/index/t/query?q={q}")
    assert s == 200 and len(body["features"]) == 1
    s, _ = wsgi(app, "DELETE", "/geojson/index/t/features/f1")
    assert s == 204
    s, body = wsgi(app, "GET", "/geojson/index/t/features/f1")
    assert s == 404
    s, body = wsgi(app, "GET", "/geojson/index")
    assert body == ["t"]
    s, _ = wsgi(app, "DELETE", "/geojson/index/t")
    assert s == 204


def test_servlet_mounted_under_webapp():
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.web import WebApp
    app = WebApp(TpuDataStore(), geojson=GeoJsonIndex())
    s, _ = wsgi(app, "POST", "/geojson/index/m")
    assert s == 201
    s, body = wsgi(app, "GET", "/geojson/index")
    assert body == ["m"]


def test_mongo_range_idiom_multiple_ops(idx):
    """{"$gte": a, "$lt": b} — both operators must apply (AND)."""
    hits = idx.query("test", '{"score": {"$gte": 5, "$lt": 9}}')
    assert [f["id"] for f in hits] == ["1"]


def test_add_is_atomic(idx):
    """A failing feature mid-collection must leave the index unchanged."""
    idx.query("test", '{"geometry": {"$bbox": [0, 0, 60, 60]}}')  # cache batch
    bad = {"type": "FeatureCollection", "features": [
        feat("ok1", 1, 1, {"dtg": 0}),
        {"type": "Feature", "id": "broken", "geometry": None,
         "properties": {}},
    ]}
    with pytest.raises(ValueError):
        idx.add("test", bad)
    assert idx.get("test", "ok1") == []
    # index still consistent: spatial query works and sees only original rows
    hits = idx.query("test", '{"geometry": {"$bbox": [29, 9, 33, 11]}}')
    assert len(hits) == 3


def test_auto_ids_survive_delete():
    gj = GeoJsonIndex()
    gj.create_index("auto")
    a, b = (gj.add("auto", feat(None, i, i))[0] for i in range(2))
    gj.delete("auto", a)
    c = gj.add("auto", feat(None, 5, 5))[0]
    assert c not in (a, b) and len(gj.query("auto", "{}")) == 2
