"""Arrow-native streaming result path (ISSUE 14): IPC round trips with
multi-chunk delta dictionaries, byte-exact parity against the row-wise
encoder, null geometries, empty results, visibility exclusion, the
zero-per-row-object probe, the per-generation device gather, the
``query.materialize`` span/metric surfaces, the ``geomesa.arrow.*``
knobs, and the chunked ``/query?format=arrow`` web endpoint with its
strict-400 CQL/SQL hardening."""

import gc
import io
import json

import numpy as np
import pytest

pa = pytest.importorskip(
    "pyarrow", reason="arrow tests need the optional [arrow] extra")

from geomesa_tpu.config import clear_property, set_property  # noqa: E402
from geomesa_tpu.datastore import TpuDataStore  # noqa: E402

MS = 1_514_764_800_000   # 2018-01-01
DAY = 86_400_000

LEAN_SPEC = ("name:String,score:Double,dtg:Date,*geom:Point;"
             "geomesa.index.profile=lean,"
             "geomesa.lean.generation.slots=16384,"
             "geomesa.lean.compaction.factor=0")

ECQL = ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
        "2018-01-02T00:00:00Z/2018-01-09T00:00:00Z")


def _write_slices(ds, name, n, seed=11, names=("ais", "gdelt", "osm"),
                  step=16_384):
    rng = np.random.default_rng(seed)
    for lo in range(0, n, step):
        m = min(step, n - lo)
        ds.write(name, {
            "name": np.array(names, dtype=object)[
                rng.integers(0, len(names), m)],
            "score": rng.uniform(0, 100, m),
            "dtg": rng.integers(MS, MS + 14 * DAY, m),
            "geom": (rng.uniform(-75, -73, m), rng.uniform(40, 42, m)),
        })


FIXTURE_ROWS = 60_000


@pytest.fixture(scope="module")
def ds():
    store = TpuDataStore(user="arrow-test")
    store.create_schema("evt", LEAN_SPEC)
    _write_slices(store, "evt", FIXTURE_ROWS)
    return store


def _reference_ipc(ds, name, ecql, schema, chunk):
    """The row-wise path encoded chunk-by-chunk under ``schema`` with
    shared delta dictionaries — the parity oracle."""
    from geomesa_tpu.arrow.schema import encode_record_batch
    res = ds.query_result(name, ecql)
    st = ds._store(name)
    sink = io.BytesIO()
    writer = pa.ipc.new_stream(
        sink, schema,
        options=pa.ipc.IpcWriteOptions(emit_dictionary_deltas=True))
    dicts: dict = {}
    for s in range(0, len(res.positions), chunk):
        fb = st.batch.take(res.positions[s:s + chunk])
        writer.write_batch(encode_record_batch(fb, schema, dicts))
    writer.close()
    return sink.getvalue(), res


# -- round trip + parity ---------------------------------------------------

def test_multi_chunk_delta_dictionary_roundtrip(ds):
    """≥3 chunks, a dictionary attribute, stock-pyarrow readable, and
    the decoded values equal the row-wise result."""
    stream = ds.query_arrow("evt", ECQL, chunk_rows=2048,
                            dictionary_fields=("name",))
    blob = stream.to_ipc_bytes()
    table = pa.ipc.open_stream(io.BytesIO(blob)).read_all()
    res = ds.query_result("evt", ECQL)
    assert len(res.positions) > 3 * 2048          # genuinely multi-chunk
    assert table.num_rows == len(res.positions)
    assert isinstance(table.schema.field("name").type, pa.DictionaryType)
    assert (table.column("name").to_pylist()
            == list(res.batch.column("name")))
    assert table.column("__fid__").to_pylist() == list(res.batch.ids)
    np.testing.assert_array_equal(
        table.column("score").to_numpy(), res.batch.column("score"))
    np.testing.assert_array_equal(
        table.column("dtg").cast(pa.int64()).to_numpy(),
        res.batch.column("dtg"))
    geom = table.column("geom").combine_chunks().flatten().to_numpy()
    gx, gy = res.batch.geom_xy()
    np.testing.assert_array_equal(geom[0::2], gx)
    np.testing.assert_array_equal(geom[1::2], gy)


def test_byte_exact_vs_rowwise_encoding(ds):
    """The streamed IPC bytes are IDENTICAL to encoding the row-wise
    materialized batch chunk-by-chunk (the bench gate's parity)."""
    stream = ds.query_arrow("evt", ECQL, chunk_rows=4096,
                            dictionary_fields=("name",))
    got = stream.to_ipc_bytes()
    want, _ = _reference_ipc(ds, "evt", ECQL, stream.schema, 4096)
    assert got == want


def test_batches_stream_lazily(ds):
    """Chunks encode as the consumer pulls (emitted as generations
    complete, not buffered): pulling ONE batch must emit exactly one
    materialize chunk."""
    from geomesa_tpu.metrics import ARROW_CHUNKS, registry
    stream = ds.query_arrow("evt", ECQL, chunk_rows=1024)
    before = registry.counter(ARROW_CHUNKS).count
    first = next(iter(stream))
    assert first.num_rows == 1024
    assert registry.counter(ARROW_CHUNKS).count == before + 1


def test_empty_result_is_valid_stream(ds):
    stream = ds.query_arrow("evt", "BBOX(geom, 10, 10, 11, 11)")
    blob = stream.to_ipc_bytes()
    table = pa.ipc.open_stream(io.BytesIO(blob)).read_all()
    assert table.num_rows == 0
    assert "geom" in table.schema.names and "score" in table.schema.names


def test_sort_and_max_features_through_stream(ds):
    from geomesa_tpu.planning.planner import Query
    q = Query.of(ECQL, sort_by="score", sort_desc=True, max_features=300)
    table = ds.query_arrow("evt", q, chunk_rows=128,
                           dictionary_fields=()).to_table()
    assert table.num_rows == 300
    scores = table.column("score").to_numpy()
    assert (np.diff(scores) <= 0).all()
    ref = ds.query_result("evt", Query.of(
        ECQL, sort_by="score", sort_desc=True, max_features=300))
    np.testing.assert_array_equal(scores, ref.batch.column("score"))
    assert table.column("__fid__").to_pylist() == list(ref.batch.ids)


def test_attr_strategy_query_streams(ds):
    """An attribute-index strategy query rides the same stream (the
    scale index still serves the device payload gather)."""
    ecql = "name = 'ais' AND BBOX(geom,-74.6,40.4,-73.4,41.6)"
    table = ds.query_arrow("evt", ecql, chunk_rows=4096).to_table()
    res = ds.query_result("evt", ecql)
    assert table.num_rows == len(res.positions) > 0
    assert set(table.column("name").to_pylist()) == {"ais"}


# -- zero per-row objects --------------------------------------------------

def test_zero_per_row_python_objects(ds):
    """Object-count probe: draining a ~40k-row stream must allocate a
    CONSTANT number of live Python objects (spans, buffers), not
    O(rows) — the contract that makes the path 50x the row-wise one.
    The row-wise take() is probed alongside as a positive control that
    the probe can see per-row allocation."""
    wide = "BBOX(geom,-75,40,-73,42)"
    res, _ = ds._query_result_ex("evt", wide, materialize=False)
    n_hits = len(res.positions)
    assert n_hits >= 20_000

    def drain():
        return sum(rb.num_rows
                   for rb in ds.query_arrow("evt", wide,
                                            chunk_rows=8192,
                                            dictionary_fields=()))

    drain()                                  # warm: compile + caches
    gc.collect()
    before = len(gc.get_objects())
    assert drain() == n_hits
    gc.collect()
    grown = len(gc.get_objects()) - before
    assert grown < 2000, f"stream leaked {grown} objects for {n_hits} rows"

    # positive control: the row-wise path DOES materialize O(rows)
    # objects (per-row id strings are untracked, but the probe rides
    # the same scale via the ids object array contents)
    st = ds._store("evt")
    fb = st.batch.take(res.positions)
    assert len(fb.ids) == n_hits
    assert all(isinstance(i, str) for i in fb.ids[:10])


# -- device gather ---------------------------------------------------------

def test_gather_payload_matches_host_payload():
    from geomesa_tpu.index.z3_lean import LeanZ3Index
    rng = np.random.default_rng(3)
    n = 40_000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.integers(MS, MS + 14 * DAY, n)
    idx = LeanZ3Index(period="week", generation_slots=8192)
    step = 8192
    for lo in range(0, n, step):
        idx.append(x[lo:lo + step], y[lo:lo + step], t[lo:lo + step])
    idx.block()
    assert idx.tier_counts()["full"] >= 2     # device gather engaged
    pos = np.sort(rng.choice(n, 5000, replace=False)).astype(np.int64)
    gx, gy, gt = idx.gather_payload(pos)
    np.testing.assert_array_equal(gx, x[pos])     # bit-exact
    np.testing.assert_array_equal(gy, y[pos])
    np.testing.assert_array_equal(gt, t[pos])
    # unsorted positions (a sort-by result order) scatter back exactly
    shuf = rng.permutation(pos)
    gx2, gy2, gt2 = idx.gather_payload(shuf)
    np.testing.assert_array_equal(gx2, x[shuf])
    np.testing.assert_array_equal(gt2, t[shuf])
    # empty
    ex, ey, et = idx.gather_payload(np.empty(0, np.int64))
    assert len(ex) == len(ey) == len(et) == 0


def test_gather_payload_mixed_tiers():
    """Demoted (keys/host) generations fall back to the host payload;
    values stay bit-exact across the tier split."""
    from geomesa_tpu.index.z3_lean import FULL_BYTES, LeanZ3Index
    rng = np.random.default_rng(9)
    n = 30_000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.integers(MS, MS + 14 * DAY, n)
    slots = 4096
    # budget fits ~2 full generations: older ones demote/spill
    idx = LeanZ3Index(period="week", generation_slots=slots,
                      hbm_budget_bytes=slots * FULL_BYTES * 6)
    for lo in range(0, n, slots):
        idx.append(x[lo:lo + slots], y[lo:lo + slots], t[lo:lo + slots])
    idx.block()
    tiers = idx.tier_counts()
    assert tiers["full"] >= 1 and (tiers["keys"] + tiers["host"]) >= 1
    pos = np.arange(0, n, 3, dtype=np.int64)
    gx, gy, gt = idx.gather_payload(pos)
    np.testing.assert_array_equal(gx, x[pos])
    np.testing.assert_array_equal(gy, y[pos])
    np.testing.assert_array_equal(gt, t[pos])


def test_sharded_gather_payload_matches():
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.lean import ShardedLeanZ3Index
    rng = np.random.default_rng(13)
    n = 20_000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.integers(MS, MS + 14 * DAY, n)
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=8192)
    idx.append(x, y, t)
    pos = np.sort(rng.choice(n, 4000, replace=False)).astype(np.int64)
    gx, gy, gt = idx.gather_payload(pos)
    np.testing.assert_array_equal(gx, x[pos])
    np.testing.assert_array_equal(gy, y[pos])
    np.testing.assert_array_equal(gt, t[pos])


# -- visibility / masking --------------------------------------------------

def test_visibility_masked_rows_excluded_from_stream():
    class Auth:
        auths = frozenset()

        def get_authorizations(self):
            return self.auths

    auth = Auth()
    rng = np.random.default_rng(5)
    store = TpuDataStore(auth_provider=auth)
    store.create_schema("sec", "dtg:Date,*geom:Point;"
                               "geomesa.index.profile=lean")
    m = 1000
    store.write("sec", {"dtg": rng.integers(MS, MS + DAY, m),
                        "geom": (rng.uniform(-75, -73, m),
                                 rng.uniform(40, 42, m))})
    store.write("sec", {"dtg": rng.integers(MS, MS + DAY, m),
                        "geom": (rng.uniform(-75, -73, m),
                                 rng.uniform(40, 42, m))},
                visibility="admin")
    table = store.query_arrow("sec", "BBOX(geom,-75,40,-73,42)",
                              chunk_rows=256).to_table()
    assert table.num_rows == m                 # admin rows excluded
    fids = np.asarray(table.column("__fid__").to_pylist(), dtype=object)
    assert int(max(int(f) for f in fids)) < m
    auth.auths = frozenset(["admin"])
    table = store.query_arrow("sec", "BBOX(geom,-75,40,-73,42)",
                              chunk_rows=256).to_table()
    assert table.num_rows == 2 * m


def test_tombstoned_rows_excluded_from_stream(ds):
    rng = np.random.default_rng(7)
    store = TpuDataStore()
    store.create_schema("del", LEAN_SPEC)
    _write_slices(store, "del", 2000, seed=21)
    store.delete("del", ["5", "17", "99"])
    table = store.query_arrow("del", "INCLUDE", chunk_rows=512).to_table()
    assert table.num_rows == 1997
    fids = set(table.column("__fid__").to_pylist())
    assert {"5", "17", "99"}.isdisjoint(fids)


# -- null geometries / non-point ------------------------------------------

def test_null_secondary_geometry_roundtrip():
    """A never-populated secondary point attribute ships as a null
    fixed-size-list column and round-trips through the reader."""
    from geomesa_tpu.arrow.reader import read_feature_batch
    store = TpuDataStore()
    store.create_schema("ng", "name:String,*geom:Point,alt:Point,dtg:Date")
    rng = np.random.default_rng(2)
    n = 50
    store.write("ng", {
        "name": np.array(["a", "b"], dtype=object)[rng.integers(0, 2, n)],
        "dtg": rng.integers(MS, MS + DAY, n),
        "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))})
    stream = store.query_arrow("ng", "INCLUDE", chunk_rows=16)
    blob = stream.to_ipc_bytes()
    table = pa.ipc.open_stream(io.BytesIO(blob)).read_all()
    assert table.num_rows == n
    alt = table.column("alt")
    assert alt.null_count == n
    back = read_feature_batch(blob, store.get_schema("ng"))
    assert len(back) == n
    assert "alt_x" not in back.columns         # never-populated stays absent


def test_non_point_lean_schema_streams_wkb():
    from geomesa_tpu.geometry.types import Polygon
    store = TpuDataStore()
    store.create_schema(
        "poly", "name:String,*geom:Polygon;geomesa.index.profile=lean")
    rng = np.random.default_rng(31)
    polys = []
    for i in range(200):
        cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
        d = rng.uniform(0.01, 0.5)
        polys.append(Polygon([(cx - d, cy - d), (cx + d, cy - d),
                              (cx + d, cy + d), (cx - d, cy + d)]))
    store.write("poly", {
        "name": np.array([f"p{i % 7}" for i in range(200)], dtype=object),
        "geom": polys})
    table = store.query_arrow("poly", "INCLUDE", chunk_rows=64).to_table()
    assert table.num_rows == 200
    from geomesa_tpu.geometry.wkb import wkb_decode
    g0 = wkb_decode(table.column("geom").to_pylist()[0])
    assert g0.geom_type == "Polygon"
    # byte-exact vs the row-wise encoder here too (WKB branch shared)
    stream = store.query_arrow("poly", "INCLUDE", chunk_rows=64,
                               dictionary_fields=("name",))
    got = stream.to_ipc_bytes()
    want, _ = _reference_ipc(store, "poly", "INCLUDE", stream.schema, 64)
    assert got == want


# -- knobs / spans / metrics ----------------------------------------------

def test_chunk_rows_option_default(ds):
    set_property("geomesa.arrow.chunk.rows", 512)
    try:
        batches = list(ds.query_arrow("evt", ECQL,
                                      dictionary_fields=()))
    finally:
        clear_property("geomesa.arrow.chunk.rows")
    assert all(b.num_rows <= 512 for b in batches)
    assert batches[0].num_rows == 512


def test_auto_dictionary_threshold(ds):
    # 3 distinct names <= threshold -> dictionary-encoded by default
    s1 = ds.query_arrow("evt", ECQL)
    assert isinstance(s1.schema.field("name").type, pa.DictionaryType)
    # threshold below the cardinality -> plain utf8
    set_property("geomesa.arrow.dictionary.threshold", 2)
    try:
        s2 = ds.query_arrow("evt", ECQL)
    finally:
        clear_property("geomesa.arrow.dictionary.threshold")
    assert s2.schema.field("name").type == pa.utf8()


def test_materialize_span_and_metrics(ds):
    from geomesa_tpu.metrics import ARROW_ROWS, registry
    from geomesa_tpu.obs import tracer
    rows0 = registry.counter(ARROW_ROWS).count
    table = ds.query_arrow("evt", ECQL, chunk_rows=4096).to_table()
    assert registry.counter(ARROW_ROWS).count - rows0 == table.num_rows
    snap = registry.snapshot()
    assert "query.evt.materialize_ms" in snap
    assert snap["query.evt.materialize_ms"]["count"] > 0
    ring = tracer.ring
    names = [s.name for t in ring.traces()[-40:] for s in t.spans]
    assert "query.materialize" in names


def test_stream_warm_repeats_recompile_free(ds):
    from geomesa_tpu.obs import compile_count

    def drain():
        return sum(rb.num_rows
                   for rb in ds.query_arrow("evt", ECQL,
                                            chunk_rows=4096,
                                            dictionary_fields=()))

    drain()                                   # warm
    c0 = compile_count()
    for _ in range(2):
        drain()
    assert compile_count() - c0 == 0


# -- web endpoint ----------------------------------------------------------

def _call(app, method, path):
    captured = {}

    def start_response(status, headers):
        captured["status"] = int(status.split()[0])
        captured["headers"] = dict(headers)

    qs = ""
    if "?" in path:
        path, qs = path.split("?", 1)
    environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
               "QUERY_STRING": qs, "CONTENT_LENGTH": "0",
               "wsgi.input": io.BytesIO(b"")}
    chunks = app(environ, start_response)
    return captured["status"], b"".join(chunks), captured["headers"]


@pytest.fixture()
def app(ds):
    from geomesa_tpu.web import WebApp
    return WebApp(ds)


def test_query_endpoint_streams_arrow(app, ds):
    import urllib.parse
    q = urllib.parse.quote(ECQL)
    status, body, headers = _call(
        app, "GET", f"/query?schema=evt&cql={q}&chunk_rows=2048")
    assert status == 200
    assert headers["Content-Type"] == "application/vnd.apache.arrow.stream"
    assert "Content-Length" not in headers     # chunked: length unknown
    table = pa.ipc.open_stream(io.BytesIO(body)).read_all()
    assert table.num_rows == len(ds.query_result("evt", ECQL).positions)


def test_query_endpoint_stream_buffer_cap(app):
    """With a tiny flush threshold the response body is produced in
    many chunks (one per batch), not one blob."""
    set_property("geomesa.arrow.stream.buffer.bytes", 1)
    try:
        captured = {}

        def start_response(status, headers):
            captured["status"] = int(status.split()[0])

        environ = {"REQUEST_METHOD": "GET", "PATH_INFO": "/query",
                   "QUERY_STRING":
                       "schema=evt&chunk_rows=4096",
                   "CONTENT_LENGTH": "0", "wsgi.input": io.BytesIO(b"")}
        chunks = [c for c in app(environ, start_response)]
    finally:
        clear_property("geomesa.arrow.stream.buffer.bytes")
    assert captured["status"] == 200
    assert len(chunks) > 3
    table = pa.ipc.open_stream(io.BytesIO(b"".join(chunks))).read_all()
    assert table.num_rows == FIXTURE_ROWS


def test_query_endpoint_strict_400s(app):
    # malformed CQL → 400 with the parse error, never a 500
    status, body, _ = _call(app, "GET",
                            "/query?schema=evt&cql=BBOX(geom,")
    assert status == 400
    assert b"parse error" in body.lower()
    # missing schema / unknown schema / bad params
    assert _call(app, "GET", "/query")[0] == 400
    assert _call(app, "GET", "/query?schema=nope")[0] == 404
    assert _call(app, "GET",
                 "/query?schema=evt&format=csv")[0] == 400
    assert _call(app, "GET",
                 "/query?schema=evt&chunk_rows=0")[0] == 400
    assert _call(app, "GET",
                 "/query?schema=evt&chunk_rows=abc")[0] == 400
    assert _call(app, "GET",
                 "/query?schema=evt&dicts=nope")[0] == 400


def test_data_endpoint_malformed_cql_400(app):
    status, body, _ = _call(
        app, "GET", "/api/data/evt?cql=name%20LIKE")
    assert status == 400
    assert b"parse error" in body.lower()
    # unknown predicate soup is a 400 too, not a 500 traceback
    status, _, _ = _call(app, "GET", "/api/data/evt?cql=%3D%3D%3D")
    assert status == 400


def test_explain_malformed_sql_and_cql_400(app):
    status, body, _ = _call(app, "GET",
                            "/explain?sql=SELEKT%20*%20FROM%20evt")
    assert status == 400
    assert b"parse error" in body.lower()
    status, _, _ = _call(app, "GET",
                         "/explain?schema=evt&cql=BBOX(geom,")
    assert status == 400


def test_query_endpoint_explicit_dicts(app):
    status, body, _ = _call(
        app, "GET", "/query?schema=evt&dicts=name&chunk_rows=65536")
    assert status == 200
    table = pa.ipc.open_stream(io.BytesIO(body)).read_all()
    assert isinstance(table.schema.field("name").type, pa.DictionaryType)
    # dicts=none disables auto encoding
    status, body, _ = _call(
        app, "GET", "/query?schema=evt&dicts=none&chunk_rows=65536")
    assert status == 200
    table = pa.ipc.open_stream(io.BytesIO(body)).read_all()
    assert table.schema.field("name").type == pa.utf8()


def test_audit_event_still_emitted_for_stream(ds):
    """The streaming path goes through the ONE audit emission path:
    query counters tick exactly as for row-wise queries."""
    from geomesa_tpu.metrics import registry
    c0 = registry.counter("query.evt.count").count
    list(ds.query_arrow("evt", ECQL, chunk_rows=65536))
    assert registry.counter("query.evt.count").count == c0 + 1
