"""Lean attribute tier (round-4 VERDICT #1): the generational lexicoded
attribute index — sorted (key, dtg, gid) runs with device/host residency
under an HBM budget — restoring index-served attribute access and
cost-based attr-vs-z3 selection on lean schemas at any scale.

Reference parity targets: AttributeIndexKey.scala:38-52 (lexicoded
typeRegistry), DateIndexKeySpace (the date secondary tier),
AttributeFilterStrategy.scala (strategy costing),
GeoMesaFeatureIndex.getQueryStrategy:248-338 (tiered range assembly).
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql
from geomesa_tpu.index.attr_lean import (
    LeanAttrIndex, encode_attr_value, encode_attr_values,
)

MS = 1514764800000
DAY = 86_400_000


# -- encoding: order parity with the natural value order ----------------

def test_encode_int64_order():
    rng = np.random.default_rng(1)
    v = rng.integers(-10 ** 17, 10 ** 17, 5000)
    k = encode_attr_values(v, "long")
    np.testing.assert_array_equal(np.sort(v), v[np.argsort(k)])


def test_encode_float64_order_and_edge_values():
    rng = np.random.default_rng(2)
    v = np.r_[rng.normal(0, 1e3, 5000),
              [0.0, -0.0, np.inf, -np.inf, 1e-308, -1e-308, 1e308,
               -1e308]]
    k = encode_attr_values(v, "double")
    np.testing.assert_array_equal(np.sort(v), v[np.argsort(k, kind="stable")])
    # -0.0 and +0.0 encode equal (equality queries must match both)
    assert encode_attr_value(-0.0, "double") == \
        encode_attr_value(0.0, "double")


def test_encode_string_prefix_order():
    words = np.array(["", "a", "ab", "abc", "abcdefgh", "abcdefghi",
                      "zzz", "Zebra", "mid", "middle"], object)
    k = encode_attr_values(words, "string")
    byts = [w.encode("utf-8")[:8] for w in words]
    want = sorted(range(len(words)), key=lambda j: byts[j])
    assert list(np.argsort(k, kind="stable")) == want
    # >8-byte strings share their prefix key (ties -> residual filter)
    assert encode_attr_value("abcdefghi", "string") == \
        encode_attr_value("abcdefghX", "string")


def test_encode_clamps_below_sentinel():
    # int64 max (and an all-0xff string) must never equal the sentinel
    # key, or open-ended range seeks would sweep the generation padding
    k = encode_attr_value(np.iinfo(np.int64).max, "long")
    assert k == np.iinfo(np.int64).max - 1
    k2 = encode_attr_value("\xff" * 8, "string")
    assert k2 < np.iinfo(np.int64).max


def test_unindexable_type_rejected():
    with pytest.raises(TypeError, match="not indexable"):
        LeanAttrIndex("b", "bytes")


# -- the index: differential vs brute force, with spills ----------------

@pytest.fixture(scope="module")
def attr_data():
    rng = np.random.default_rng(5)
    n = 60_000
    names = rng.choice(np.array(["alpha", "beta", "gamma", "delta",
                                 "rare"], object), n,
                       p=[.4, .3, .2, .099, .001])
    vals = rng.integers(0, 1000, n)
    dtg = rng.integers(MS, MS + 14 * DAY, n)
    return names, vals, dtg


def _spilled_pair(attr_data, slots=1 << 12):
    names, vals, dtg = attr_data
    idx_s = LeanAttrIndex("name", "string", generation_slots=slots,
                          hbm_budget_bytes=3 * slots * 20)
    idx_v = LeanAttrIndex("v", "long", generation_slots=slots,
                          hbm_budget_bytes=3 * slots * 20)
    for lo in range(0, len(names), 7000):
        sl = slice(lo, lo + 7000)
        idx_s.append(names[sl], dtg[sl])
        idx_v.append(vals[sl], dtg[sl])
    return idx_s, idx_v


def test_index_differential_with_spills(attr_data):
    names, vals, dtg = attr_data
    idx_s, idx_v = _spilled_pair(attr_data)
    assert idx_s.tier_counts()["host"] >= 1   # budget forced spills
    # string equality: exact for <8-byte-unique values
    got = np.sort(idx_s.query_equals("gamma"))
    np.testing.assert_array_equal(got, np.flatnonzero(names == "gamma"))
    # equality + date window narrows THROUGH the (key, sec) sort
    w = (MS + 2 * DAY, MS + 5 * DAY)
    got_w = np.sort(idx_s.query_equals("gamma", sec_window=w))
    want_w = np.flatnonzero((names == "gamma") & (dtg >= w[0])
                            & (dtg <= w[1]))
    np.testing.assert_array_equal(got_w, want_w)
    assert len(got_w) < len(got)
    # IN (including an absent value)
    got_in = np.sort(idx_s.query_in(["alpha", "nope", "delta"]))
    np.testing.assert_array_equal(
        got_in,
        np.flatnonzero(np.isin(names.astype(str), ["alpha", "delta"])))
    # numeric range: candidates cover the exact set, inclusive superset
    got_r = np.sort(idx_v.query_range(100, 300, True, False))
    exact = set(np.flatnonzero((vals >= 100) & (vals < 300)))
    sup = set(np.flatnonzero((vals >= 100) & (vals <= 300)))
    assert exact.issubset(set(got_r)) and set(got_r).issubset(sup)
    # open-ended range must NOT sweep sentinel padding
    got_o = np.sort(idx_v.query_range(900, None))
    np.testing.assert_array_equal(got_o, np.flatnonzero(vals >= 900))
    # prefix
    got_p = np.sort(idx_s.query_prefix("de"))
    np.testing.assert_array_equal(
        got_p, np.flatnonzero(np.char.startswith(names.astype(str),
                                                 "de")))


def test_index_fixed_dispatches(attr_data):
    names, vals, dtg = attr_data
    slots = 1 << 13
    idx = LeanAttrIndex("v", "long", generation_slots=slots,
                        hbm_budget_bytes=100 * slots * 20)
    idx.append(vals, dtg)
    assert idx.tier_counts()["host"] == 0
    before = idx.dispatch_count
    idx.query_equals(vals[0])
    # one totals probe + one gather over every device generation
    assert idx.dispatch_count - before == 2


# -- the store: planner integration, oracle-exact -----------------------

N = 120_000


@pytest.fixture(scope="module")
def lean_attr_store():
    rng = np.random.default_rng(7)
    ds = TpuDataStore()
    ds.create_schema(
        "evt", "name:String:index=true,score:Double:index=true,"
               "dtg:Date,*geom:Point;geomesa.index.profile=lean")
    names = rng.choice(np.array(["alpha", "beta", "gamma", "delta",
                                 "rare"], object), N,
                       p=[.4, .3, .2, .099, .001])
    score = rng.uniform(0, 100, N)
    x = rng.uniform(-75, -73, N)
    y = rng.uniform(40, 42, N)
    t = rng.integers(MS, MS + 14 * DAY, N)
    for lo in range(0, N, 50_000):
        sl = slice(lo, lo + 50_000)
        ds.write("evt", {"name": names[sl], "score": score[sl],
                         "dtg": t[sl], "geom": (x[sl], y[sl])})
    return ds, names, score, x, y, t


def _oracle(ds, ecql):
    st = ds._store("evt")
    fb = st.batch.take(np.arange(len(st.batch)))
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), fb))
    if st.tombstone is not None:
        want = want[~st.tombstone[want]]
    return want


def test_store_offers_attr_strategy(lean_attr_store):
    ds, *_ = lean_attr_store
    st = ds._store("evt")
    assert st.query_indices == {"z3", "id", "attr"}
    assert sorted(st._lean_attr_names()) == ["name", "score"]
    exp = ds.explain("evt", "name = 'rare'")
    assert "attr:name" in exp


@pytest.mark.parametrize("ecql", [
    "name = 'rare'",
    "name = 'rare' AND BBOX(geom, -75, 40, -73, 42)",
    "name IN ('rare', 'delta')",
    "name LIKE 'ga%'",
    "score > 99.5",
    "score BETWEEN 10.0 AND 10.6",
    "name = 'alpha' AND dtg DURING "
    "2018-01-02T00:00:00Z/2018-01-03T00:00:00Z",
])
def test_store_attr_queries_oracle_exact(lean_attr_store, ecql):
    ds, *_ = lean_attr_store
    r = ds.query_result("evt", ecql)
    np.testing.assert_array_equal(np.sort(r.positions), _oracle(ds, ecql))


def test_store_attr_strategy_chosen_when_selective(lean_attr_store):
    ds, *_ = lean_attr_store
    r = ds.query_result("evt",
                        "name = 'rare' AND BBOX(geom, -75, 40, -73, 42)")
    assert r.strategy.index == "attr:name"
    # a tiny bbox flips the cost decision back to z3
    r2 = ds.query_result(
        "evt", "name = 'alpha' AND "
               "BBOX(geom, -74.01, 40.99, -73.99, 41.01)")
    assert r2.strategy.index == "z3"
    np.testing.assert_array_equal(
        np.sort(r2.positions),
        _oracle(ds, "name = 'alpha' AND "
                    "BBOX(geom, -74.01, 40.99, -73.99, 41.01)"))


def test_store_attr_tombstones_fold_in():
    rng = np.random.default_rng(11)
    n = 30_000
    ds = TpuDataStore()
    ds.create_schema("evt", "name:String:index=true,dtg:Date,"
                            "*geom:Point;geomesa.index.profile=lean")
    names = rng.choice(np.array(["a", "b", "rare"], object), n,
                       p=[.6, .39, .01])
    ds.write("evt", {"name": names,
                     "dtg": rng.integers(MS, MS + 7 * DAY, n),
                     "geom": (rng.uniform(-75, -73, n),
                              rng.uniform(40, 42, n))})
    rare = np.flatnonzero(names == "rare")[:5]
    assert ds.delete("evt", [str(i) for i in rare]) == 5
    r = ds.query_result("evt", "name = 'rare'")
    np.testing.assert_array_equal(
        np.sort(r.positions),
        np.setdiff1d(np.flatnonzero(names == "rare"), rare))


def test_store_attr_snapshot_roundtrip(tmp_path):
    rng = np.random.default_rng(13)
    n = 30_000
    ds = TpuDataStore(str(tmp_path))
    ds.create_schema("evt", "name:String:index=true,dtg:Date,"
                            "*geom:Point;geomesa.index.profile=lean")
    names = rng.choice(np.array(["a", "b", "rare"], object), n,
                       p=[.6, .39, .01])
    ds.write("evt", {"name": names,
                     "dtg": rng.integers(MS, MS + 7 * DAY, n),
                     "geom": (rng.uniform(-75, -73, n),
                              rng.uniform(40, 42, n))})
    ds.flush("evt")
    ds.persist_stats("evt")
    ds2 = TpuDataStore(str(tmp_path))
    r = ds2.query_result("evt", "name = 'rare'")
    assert r.strategy.index == "attr:name"
    np.testing.assert_array_equal(np.sort(r.positions),
                                  np.flatnonzero(names == "rare"))


def test_sharded_lean_attr_matches_single_chip():
    """The mesh variant (ShardedLeanAttrIndex) answers every planner
    query shape identically to the single-chip store — including with
    host-spilled generations (per-shard budget)."""
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.attr_lean import ShardedLeanAttrIndex

    rng = np.random.default_rng(23)
    n = 40_000
    data = {
        "name": rng.choice(np.array(["alpha", "beta", "gamma", "rare"],
                                    object), n, p=[.5, .3, .19, .01]),
        "score": rng.uniform(0, 100, n),
        "dtg": rng.integers(MS, MS + 14 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n))}
    spec = ("name:String:index=true,score:Double:index=true,dtg:Date,"
            "*geom:Point;geomesa.index.profile=lean")
    ds = TpuDataStore(mesh=device_mesh())
    ds.create_schema("evt", spec)
    plain = TpuDataStore()
    plain.create_schema("evt", spec)
    for lo in range(0, n, 15_000):
        sl = slice(lo, lo + 15_000)
        chunk = {"name": data["name"][sl], "score": data["score"][sl],
                 "dtg": data["dtg"][sl],
                 "geom": (data["geom"][0][sl], data["geom"][1][sl])}
        ds.write("evt", chunk)
        plain.write("evt", chunk)
    st = ds._store("evt")
    assert isinstance(st.attribute_index("name"), ShardedLeanAttrIndex)
    for ecql in ("name = 'rare'",
                 "name = 'rare' AND BBOX(geom, -75, 40, -73, 42)",
                 "name IN ('rare', 'gamma')",
                 "score > 99.5",
                 "name LIKE 'be%'",
                 "name = 'alpha' AND dtg DURING "
                 "2018-01-02T00:00:00Z/2018-01-03T00:00:00Z"):
        a = ds.query_result("evt", ecql)
        b = plain.query_result("evt", ecql)
        np.testing.assert_array_equal(np.sort(a.positions),
                                      np.sort(b.positions))
    assert any(s.index.startswith("attr:")
               for s in [ds.query_result("evt", "name = 'rare'").strategy])


def test_sharded_lean_attr_budget_spills_oracle_exact():
    """Per-shard budget pressure spills attr generations to host; the
    stacked composite bisection still answers exactly."""
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.attr_lean import ShardedLeanAttrIndex

    rng = np.random.default_rng(31)
    n = 60_000
    names = rng.choice(np.array(["a", "b", "c", "rare"], object), n,
                       p=[.5, .3, .19, .01])
    dtg = rng.integers(MS, MS + 14 * DAY, n)
    slots = 1 << 10
    idx = ShardedLeanAttrIndex(
        "name", "string", mesh=device_mesh(), generation_slots=slots,
        hbm_budget_bytes=3 * slots * 24)
    for lo in range(0, n, 9_000):
        sl = slice(lo, lo + 9_000)
        idx.append(names[sl], dtg[sl], base_gid=lo)
    assert idx.tier_counts()["host"] >= 1
    got = np.sort(idx.query_equals("rare"))
    np.testing.assert_array_equal(got, np.flatnonzero(names == "rare"))
    w = (MS + 2 * DAY, MS + 5 * DAY)
    got_w = np.sort(idx.query_equals("a", sec_window=w))
    np.testing.assert_array_equal(
        got_w, np.flatnonzero((names == "a") & (dtg >= w[0])
                              & (dtg <= w[1])))


def test_unservable_indexed_attr_falls_back_to_scan():
    """An indexed attribute the lean lexicode cannot serve (e.g. bool)
    must not be OFFERED as a strategy — the query falls back to a scan
    instead of erroring (review r5)."""
    ds = TpuDataStore()
    ds.create_schema("evt", "name:String:index=true,"
                            "flag:Boolean:index=true,dtg:Date,"
                            "*geom:Point;geomesa.index.profile=lean")
    n = 1000
    rng = np.random.default_rng(2)
    flags = rng.choice([True, False], n)
    ds.write("evt", {"name": np.full(n, "a", object), "flag": flags,
                     "dtg": np.full(n, MS),
                     "geom": (rng.uniform(-1, 1, n),
                              rng.uniform(-1, 1, n))})
    r = ds.query_result("evt", "flag = true")   # must not raise
    assert r.strategy.index == "full"
    np.testing.assert_array_equal(np.sort(r.positions),
                                  np.flatnonzero(flags))
    # the servable attribute still index-serves
    r2 = ds.query_result("evt", "name = 'a'")
    assert r2.strategy.index == "attr:name"


def test_lean_attr_index_incremental_single_build(lean_attr_store):
    ds, *_ = lean_attr_store
    st = ds._store("evt")
    # chunked writes maintain ONE index incrementally — no rebuilds
    assert st.build_counts.get("attr:name") == 1
    assert st.build_counts.get("attr:score") == 1
    assert st._index_coverage["attr:name"] == N
