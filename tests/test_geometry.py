"""Geometry model: WKT roundtrips, packing, and predicate correctness."""

import numpy as np
import pytest

from geomesa_tpu.geometry import (
    Envelope,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
    geometry_from_wkt,
    geometry_intersects,
    geometry_to_wkt,
    pack_geometries,
    point_in_polygon,
    points_in_packed_polygon,
    segments_intersect,
)

SQUARE = Polygon([[0, 0], [10, 0], [10, 10], [0, 10]])
DONUT = Polygon([[0, 0], [10, 0], [10, 10], [0, 10]],
                holes=[[[3, 3], [7, 3], [7, 7], [3, 7]]])


def test_wkt_roundtrip():
    cases = [
        "POINT (30 10)",
        "LINESTRING (30 10, 10 30, 40 40)",
        "POLYGON ((30 10, 40 40, 20 40, 10 20, 30 10))",
        "POLYGON ((35 10, 45 45, 15 40, 10 20, 35 10), (20 30, 35 35, 30 20, 20 30))",
        "MULTIPOINT ((10 40), (40 30), (20 20), (30 10))",
        "MULTILINESTRING ((10 10, 20 20, 10 40), (40 40, 30 30, 40 20, 30 10))",
        "MULTIPOLYGON (((30 20, 45 40, 10 40, 30 20)), ((15 5, 40 10, 10 20, 5 10, 15 5)))",
    ]
    for wkt in cases:
        g = geometry_from_wkt(wkt)
        g2 = geometry_from_wkt(geometry_to_wkt(g))
        assert g.envelope == g2.envelope
        assert g.geom_type == g2.geom_type


def test_envelope_ops():
    a = Envelope(0, 0, 10, 10)
    b = Envelope(5, 5, 15, 15)
    assert a.intersects(b)
    assert a.intersection(b) == Envelope(5, 5, 10, 10)
    assert not a.intersects(Envelope(11, 11, 12, 12))
    assert a.expand(b) == Envelope(0, 0, 15, 15)
    assert Envelope.WHOLE_WORLD.contains(a)


def test_point_in_square():
    px = np.array([5.0, -1.0, 10.0, 0.0, 15.0])
    py = np.array([5.0, 5.0, 5.0, 0.0, 5.0])
    got = point_in_polygon(px, py, SQUARE)
    # boundary points count as inside (JTS intersects semantics)
    np.testing.assert_array_equal(got, [True, False, True, True, False])


def test_point_in_donut():
    px = np.array([1.0, 5.0, 3.0, 8.0])
    py = np.array([1.0, 5.0, 3.0, 8.0])
    got = point_in_polygon(px, py, DONUT)
    # (5,5) is inside the hole → outside; (3,3) is on the hole boundary →
    # boundary of the polygon → inside
    np.testing.assert_array_equal(got, [True, False, True, True])


def test_point_in_polygon_random_vs_matplotlib_style(rng):
    # independent oracle: winding number via angle sum (slow but different)
    poly = Polygon([[0, 0], [4, 0], [4, 1], [1, 1], [1, 3], [4, 3], [4, 4], [0, 4]])
    px = rng.uniform(-1, 5, 500)
    py = rng.uniform(-1, 5, 500)
    got = point_in_polygon(px, py, poly, include_boundary=False)
    shell = poly.shell
    for i in range(0, 500, 17):
        x, y = px[i], py[i]
        # ray casting scalar oracle
        inside = False
        for j in range(len(shell) - 1):
            x1, y1 = shell[j]
            x2, y2 = shell[j + 1]
            if (y1 > y) != (y2 > y) and x < x1 + (y - y1) / (y2 - y1) * (x2 - x1):
                inside = not inside
        assert bool(got[i]) == inside, (x, y)


def test_multipolygon_containment():
    mp = MultiPolygon((
        Polygon([[0, 0], [2, 0], [2, 2], [0, 2]]),
        Polygon([[5, 5], [7, 5], [7, 7], [5, 7]]),
    ))
    px = np.array([1.0, 6.0, 3.5])
    py = np.array([1.0, 6.0, 3.5])
    np.testing.assert_array_equal(point_in_polygon(px, py, mp), [True, True, False])


def test_segments_intersect():
    a1 = np.array([[0.0, 0.0], [0.0, 0.0], [0.0, 0.0]])
    a2 = np.array([[10.0, 10.0], [1.0, 0.0], [0.0, 1.0]])
    b1 = np.array([[0.0, 10.0], [5.0, 5.0]])
    b2 = np.array([[10.0, 0.0], [6.0, 6.0]])
    got = segments_intersect(a1, a2, b1, b2)
    assert got[0, 0]          # X crossing
    assert not got[1, 0]      # far apart
    assert not got[2, 1]
    # touching endpoint counts
    t = segments_intersect(np.array([[0.0, 0.0]]), np.array([[1.0, 1.0]]),
                           np.array([[1.0, 1.0]]), np.array([[2.0, 0.0]]))
    assert t[0, 0]


def test_geometry_intersects_dispatch():
    sq = SQUARE
    assert geometry_intersects(Point(5, 5), sq)
    assert not geometry_intersects(Point(50, 50), sq)
    assert geometry_intersects(LineString([[-5, 5], [15, 5]]), sq)   # crosses
    assert geometry_intersects(LineString([[-5, -5], [5, 15]]), sq)  # crosses
    assert not geometry_intersects(LineString([[-5, -5], [-1, 15]]), sq)
    other = Polygon([[8, 8], [12, 8], [12, 12], [8, 12]])
    assert geometry_intersects(sq, other)
    assert geometry_intersects(other, sq)
    disjoint = Polygon([[20, 20], [30, 20], [30, 30], [20, 30]])
    assert not geometry_intersects(sq, disjoint)
    # polygon fully inside the other
    inner = Polygon([[4, 4], [6, 4], [6, 6], [4, 6]])
    assert geometry_intersects(sq, inner)
    assert geometry_intersects(inner, sq)
    # polygon inside a donut hole does NOT intersect
    hole_dweller = Polygon([[4, 4], [6, 4], [6, 6], [4, 6]])
    assert not geometry_intersects(DONUT, hole_dweller)


def test_pack_roundtrip():
    geoms = [
        Point(1, 2),
        LineString([[0, 0], [1, 1], [2, 0]]),
        DONUT,
        MultiPolygon((Polygon([[0, 0], [1, 0], [1, 1]]),
                      Polygon([[5, 5], [6, 5], [6, 6]]))),
        MultiPoint([[1, 1], [2, 2]]),
        MultiLineString((LineString([[0, 0], [1, 0]]), LineString([[2, 2], [3, 3]]))),
    ]
    packed = pack_geometries(geoms)
    assert len(packed) == len(geoms)
    for i, g in enumerate(geoms):
        back = packed.geometry(i)
        assert back.geom_type == g.geom_type
        assert back.envelope == g.envelope
    np.testing.assert_allclose(packed.bbox[0], [1, 2, 1, 2])


def test_packed_point_in_polygon():
    packed = pack_geometries([SQUARE, DONUT])
    px = np.array([5.0, 5.0])
    py = np.array([5.0, 1.0])
    np.testing.assert_array_equal(points_in_packed_polygon(px, py, packed, 0),
                                  [True, True])
    np.testing.assert_array_equal(points_in_packed_polygon(px, py, packed, 1),
                                  [False, True])


def test_packed_take_concat_vectorized_equivalence():
    """Offset-arithmetic take/concat match the per-object rebuild path."""
    import numpy as np
    from geomesa_tpu.geometry.packed import pack_geometries
    from geomesa_tpu.geometry.types import (
        LineString, MultiPolygon, Point, Polygon,
    )
    rng = np.random.default_rng(0)

    def rand_geom():
        k = rng.integers(0, 4)
        cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
        if k == 0:
            return Point(cx, cy)
        if k == 1:
            return LineString(np.column_stack(
                [cx + rng.uniform(-1, 1, 5), cy + rng.uniform(-1, 1, 5)]))
        if k == 2:
            return Polygon([(cx, cy), (cx + 1, cy), (cx + 1, cy + 1),
                            (cx, cy + 1)],
                           holes=[[(cx + .2, cy + .2), (cx + .4, cy + .2),
                                   (cx + .4, cy + .4)]])
        return MultiPolygon((Polygon([(cx, cy), (cx + 1, cy),
                                      (cx + 1, cy + 1)]),
                             Polygon([(cx + 2, cy), (cx + 3, cy),
                                      (cx + 3, cy + 1)])))

    geoms = [rand_geom() for _ in range(500)]
    packed = pack_geometries(geoms)
    pos = rng.choice(500, 120, replace=False)
    sub = packed.take(pos)
    ref = pack_geometries([packed.geometry(int(i)) for i in pos])
    np.testing.assert_array_equal(sub.kinds, ref.kinds)
    np.testing.assert_allclose(sub.coords, ref.coords)
    np.testing.assert_array_equal(sub.ring_offsets, ref.ring_offsets)
    np.testing.assert_array_equal(sub.part_ring_offsets,
                                  ref.part_ring_offsets)
    np.testing.assert_array_equal(sub.geom_part_offsets,
                                  ref.geom_part_offsets)
    cat = packed.concat(sub)
    assert len(cat) == 620
    assert type(cat.geometry(len(packed))) is type(sub.geometry(0))
    np.testing.assert_allclose(cat.bbox[500:], sub.bbox)


def test_packed_take_accepts_boolean_mask():
    import numpy as np
    from geomesa_tpu.geometry.packed import pack_geometries
    from geomesa_tpu.geometry.types import Point, Polygon

    packed = pack_geometries([Point(0, 0),
                              Polygon([(0, 0), (1, 0), (1, 1)]),
                              Point(2, 2)])
    sub = packed.take(np.array([True, False, True]))
    assert len(sub) == 2
    assert list(sub.kinds) == [0, 0]  # the two points


def test_packed_intersects_matches_scalar_oracle():
    """The batched exact re-check (packed_intersects) is test-for-test
    identical to geometry_intersects across the type lattice, including
    polygons with holes and multi-part geometries (round-3 next #4)."""
    import numpy as np
    from geomesa_tpu.geometry import (
        LineString, MultiPoint, MultiPolygon, Point, Polygon,
    )
    from geomesa_tpu.geometry.packed import pack_geometries
    from geomesa_tpu.geometry.predicates import (
        geometry_intersects, packed_intersects,
    )

    rng = np.random.default_rng(5)

    def rand_poly(cx, cy, r, k=6):
        ang = np.sort(rng.uniform(0, 2 * np.pi, k))
        pts = np.stack([cx + r * np.cos(ang), cy + r * np.sin(ang)], axis=1)
        return Polygon(np.vstack([pts, pts[:1]]))

    def rand_line(cx, cy, r, k=4):
        return LineString(np.stack(
            [cx + rng.uniform(-r, r, k), cy + rng.uniform(-r, r, k)],
            axis=1))

    geoms = []
    for _ in range(1200):
        t = rng.integers(0, 5)
        cx, cy = rng.uniform(-5, 5, 2)
        r = rng.uniform(0.05, 1.0)
        geoms.append(
            [Point(cx, cy), rand_poly(cx, cy, r), rand_line(cx, cy, r),
             MultiPoint(rng.uniform(-5, 5, (3, 2))),
             MultiPolygon((rand_poly(cx, cy, r),
                           rand_poly(cx + 1, cy, r * .5)))][t])
    packed = pack_geometries(geoms)
    queries = [
        rand_poly(0, 0, 3, 8),
        Polygon([[-2, -2], [2, -2], [2, 2], [-2, 2], [-2, -2]],
                ([[-1, -1], [1, -1], [1, 1], [-1, 1], [-1, -1]],)),
        rand_line(0, 0, 4, 6),
        MultiPoint(np.array([[0.0, 0.0], [1.5, 1.5]])),
        Point(*map(float, rng.uniform(-2, 2, 2))),
    ]
    for q in queries:
        want = np.array([geometry_intersects(g, q) for g in geoms])
        got = packed_intersects(packed, q)
        np.testing.assert_array_equal(got, want)
    # positions subset form
    pos = np.arange(0, len(geoms), 3)
    got = packed_intersects(packed, queries[0], pos)
    want = np.array([geometry_intersects(geoms[i], queries[0])
                     for i in pos])
    np.testing.assert_array_equal(got, want)


def test_packed_from_boxes_matches_object_packing():
    """The vectorized envelope-array constructor must produce the same
    packed layout as pack_geometries over equivalent Polygon objects
    (the object-free bulk-ingest path of the polygon scale proof)."""
    import numpy as np

    from geomesa_tpu.geometry.packed import (
        pack_geometries, packed_from_boxes,
    )
    from geomesa_tpu.geometry.predicates import (
        geometry_intersects, point_in_polygon,
    )
    from geomesa_tpu.geometry.types import Polygon

    rng = np.random.default_rng(9)
    n = 500
    x0 = rng.uniform(-170, 170, n)
    y0 = rng.uniform(-80, 80, n)
    w = rng.uniform(0.01, 0.5, n)
    bb = np.stack([x0, y0, x0 + w, y0 + w], axis=1)
    fast = packed_from_boxes(bb)
    np.testing.assert_allclose(fast.bbox, bb)
    assert len(fast) == n
    # object-path equivalence on a sample
    for i in (0, 7, n - 1):
        b = bb[i]
        obj = pack_geometries([Polygon(
            [(b[0], b[1]), (b[2], b[1]), (b[2], b[3]),
             (b[0], b[3])])]).geometry(0)
        g = fast.geometry(int(i))
        assert geometry_intersects(g, obj)
        # interior point containment agrees
        cx, cy = (b[0] + b[2]) / 2, (b[1] + b[3]) / 2
        assert point_in_polygon(np.array([cx]), np.array([cy]), g)[0]
    # take/concat roundtrip on the vectorized layout
    sub = fast.take(np.array([3, 100, 400]))
    assert len(sub) == 3
    np.testing.assert_allclose(sub.bbox, bb[[3, 100, 400]])
