"""CLI command tree: create-schema → ingest → export/explain/stats over a
filesystem catalog (reference: geomesa-tools Runner commands)."""

import json

import numpy as np
import pytest

from geomesa_tpu.cli.main import main

CSV = """2018-01-01 10:00:00,alice,-74.1,40.7
2018-01-01 11:30:00,bob,2.35,48.85
2018-01-02 09:15:00,carol,139.7,35.6
"""

CONV = {
    "type": "delimited-text",
    "format": "CSV",
    "fields": [
        {"name": "dtg", "transform": "date('yyyy-MM-dd HH:mm:ss', $0)"},
        {"name": "name", "transform": "$1"},
        {"name": "geom", "transform": "point($2, $3)"},
    ],
}


@pytest.fixture
def catalog(tmp_path):
    cat = str(tmp_path / "cat")
    csv_path = tmp_path / "data.csv"
    csv_path.write_text(CSV)
    conv_path = tmp_path / "conv.json"
    conv_path.write_text(json.dumps(CONV))
    main(["create-schema", "-c", cat, "-f", "people",
          "-s", "name:String,dtg:Date,*geom:Point"])
    main(["ingest", "-c", cat, "-f", "people", "-C", str(conv_path),
          str(csv_path)])
    return cat, tmp_path


def test_roundtrip_and_counts(catalog, capsys):
    cat, tmp = catalog
    main(["get-type-names", "-c", cat])
    main(["stats-count", "-c", cat, "-f", "people"])
    out = capsys.readouterr().out
    assert "people" in out and "3" in out


def test_export_csv(catalog, capsys):
    cat, tmp = catalog
    main(["export", "-c", cat, "-f", "people", "-q",
          "BBOX(geom, -80, 30, 10, 50)"])
    out = capsys.readouterr().out
    assert "alice" in out and "bob" in out and "carol" not in out


def test_export_geojson_file(catalog):
    cat, tmp = catalog
    out_path = str(tmp / "out.geojson")
    main(["export", "-c", cat, "-f", "people", "-F", "geojson",
          "-o", out_path])
    fc = json.loads(open(out_path).read())
    assert len(fc["features"]) == 3


def test_export_parquet_and_reingest(catalog, capsys):
    cat, tmp = catalog
    pq = str(tmp / "out.parquet")
    main(["export", "-c", cat, "-f", "people", "-F", "parquet", "-o", pq])
    cat2 = str(tmp / "cat2")
    main(["create-schema", "-c", cat2, "-f", "people",
          "-s", "name:String,dtg:Date,*geom:Point"])
    main(["ingest", "-c", cat2, "-f", "people", pq])
    capsys.readouterr()
    main(["stats-count", "-c", cat2, "-f", "people"])
    assert capsys.readouterr().out.strip() == "3"


def test_explain_and_describe(catalog, capsys):
    cat, tmp = catalog
    main(["explain", "-c", cat, "-f", "people", "-q",
          "BBOX(geom, -80, 30, 10, 50) AND dtg DURING 2018-01-01T00:00:00Z/2018-01-02T00:00:00Z"])
    out = capsys.readouterr().out
    assert "chosen: z3" in out
    main(["describe-schema", "-c", cat, "-f", "people"])
    out = capsys.readouterr().out
    assert "*geom" in out


def test_stats_commands(catalog, capsys):
    cat, tmp = catalog
    main(["stats-bounds", "-c", cat, "-f", "people"])
    main(["stats-top-k", "-c", cat, "-f", "people", "-a", "name"])
    out = capsys.readouterr().out
    assert "alice" in out
    main(["version"])
    assert "geomesa-tpu" in capsys.readouterr().out


def test_bin_export(catalog, tmp_path):
    cat, tmp = catalog
    out_path = str(tmp / "out.bin")
    main(["export", "-c", cat, "-f", "people", "-F", "bin", "-o", out_path])
    from geomesa_tpu.io import decode_bin
    back = decode_bin(open(out_path, "rb").read())
    assert len(back["lon"]) == 3


def test_catalog_persists_across_processes(catalog, capsys):
    cat, tmp = catalog
    # a brand-new datastore instance (fresh "process") sees the data
    main(["stats-count", "-c", cat, "-f", "people"])
    assert capsys.readouterr().out.strip() == "3"
    main(["remove-schema", "-c", cat, "-f", "people"])
    capsys.readouterr()
    main(["get-type-names", "-c", cat])
    assert "people" not in capsys.readouterr().out


def test_cli_fs_partitions(tmp_path, capsys):
    import numpy as np
    from geomesa_tpu.cli.main import main
    from geomesa_tpu.fs import FileSystemDataStore

    root = str(tmp_path / "fsroot")
    fs = FileSystemDataStore(root)
    fs.create_schema("evt", "dtg:Date,*geom:Point")
    rng = np.random.default_rng(0)
    n = 100
    fs.write("evt", {
        "dtg": rng.integers(1514764800000, 1514764800000 + 2 * 86_400_000, n),
        "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))})
    fs.write("evt", {
        "dtg": rng.integers(1514764800000, 1514764800000 + 2 * 86_400_000, n),
        "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))})
    main(["fs-partitions", "-r", root, "-f", "evt"])
    out = capsys.readouterr().out
    assert "2 file(s)" in out
    main(["fs-partitions", "-r", root, "-f", "evt", "--compact"])
    out = capsys.readouterr().out
    assert "compacted evt" in out and "1 file(s)" in out


def test_cli_migrate_and_index_versions(tmp_path, capsys):
    cat = str(tmp_path / "cat")
    main(["create-schema", "-c", cat, "-f", "legacy",
          "-s", "name:String,dtg:Date,*geom:Point;"
                "geomesa.index.versions='z3:1,z2:1'"])
    main(["index-versions", "-c", cat, "-f", "legacy"])
    out = capsys.readouterr().out
    assert "z3: v1" in out and "z2: v1" in out
    main(["migrate-schema", "-c", cat, "-f", "legacy"])
    out = capsys.readouterr().out
    assert "z3 v1 -> v2" in out
    main(["index-versions", "-c", cat, "-f", "legacy"])
    out = capsys.readouterr().out
    assert "z3: v2" in out
    # idempotent
    main(["migrate-schema", "-c", cat, "-f", "legacy"])
    assert "already at current" in capsys.readouterr().out


def test_export_shapefile(catalog, tmp_path):
    cat, _ = catalog
    out = str(tmp_path / "out.shp")
    main(["export", "-c", cat, "-f", "people", "-F", "shp", "-o", out])
    from geomesa_tpu.io.formats import read_shapefile
    geoms, attrs = read_shapefile(out, str(tmp_path / "out.dbf"))
    assert len(geoms) == 3
    assert {s.strip() for s in attrs["name"]} == {"alice", "bob", "carol"}


def test_cli_sql_shapes(catalog, capsys):
    """The sql command renders all three result shapes: rows, GROUP BY
    arrays, and global-aggregate scalars (one header + one value
    row)."""
    cat, _ = catalog
    main(["sql", "-c", cat, "SELECT count(*) FROM people"])
    assert capsys.readouterr().out.strip() == "3"
    main(["sql", "-c", cat,
          "SELECT count(*) AS n FROM people GROUP BY name"])
    out = capsys.readouterr().out.strip().splitlines()
    assert out[0] == "name,n" and len(out) == 4
    main(["sql", "-c", cat, "SELECT count(*) AS n FROM people"])
    assert capsys.readouterr().out.strip().splitlines() == ["n", "3"]


def test_cli_flush_checkpoint(tmp_path, capsys):
    cat = str(tmp_path / "cat2")
    main(["create-schema", "-c", cat, "-f", "evt",
          "-s", "dtg:Date,*geom:Point;geomesa.index.profile=lean"])
    from geomesa_tpu.datastore import TpuDataStore

    ds = TpuDataStore(cat)
    ds.write("evt", {"dtg": np.full(5, 1514764800000),
                     "geom": (np.zeros(5), np.zeros(5))})
    ds.flush("evt")
    capsys.readouterr()
    main(["flush", "-c", cat, "-f", "evt"])
    assert "lean snapshot" in capsys.readouterr().out
    ds2 = TpuDataStore(cat)
    assert len(ds2._store("evt").batch) == 5
