"""REAL multi-process multihost validation: two OS processes join one
JAX distributed system (gloo over localhost) and run the
multi-controller build + collective queries — the genuine
`jax.distributed` path, not a monkeypatched simulation (VERDICT r1
weak #8 taken all the way)."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r'''
import os, sys
proc = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
from jax._src import xla_bridge as _xb
_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["GEOMESA_REPO"])
from geomesa_tpu.parallel.multihost import (
    global_device_mesh, initialize_distributed,
)
initialize_distributed(f"localhost:{port}", num_processes=2,
                       process_id=proc)
assert jax.process_count() == 2

import numpy as np
import geomesa_tpu  # noqa: F401  (x64)
from geomesa_tpu.parallel.scan import GID_PROC_SHIFT, ShardedZ3Index

mesh = global_device_mesh()
rng = np.random.default_rng(proc)
n_local = 1000 + proc * 17          # deliberately uneven
MS = 1514764800000
x = rng.uniform(-75, -73, n_local)
y = rng.uniform(40, 42, n_local)
t = rng.integers(MS, MS + 7 * 86_400_000, n_local)
idx = ShardedZ3Index.build_multihost(x, y, t, period="week", mesh=mesh)
assert idx.total() == 2017, idx.total()

box = (-74.5, 40.5, -73.5, 41.5)
hits = idx.query([box], None, None)
procs = np.asarray(hits) >> GID_PROC_SHIFT
rows = np.asarray(hits) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
mine = np.sort(rows[procs == proc])
brute = np.flatnonzero((x >= box[0]) & (x <= box[2])
                       & (y >= box[1]) & (y <= box[3]))
assert np.array_equal(mine, brute), (len(mine), len(brute))

count = idx.range_count([box], MS, MS + 7 * 86_400_000)
assert count >= len(hits)
grid = idx.density([box], MS, MS + 7 * 86_400_000, box, 16, 16)
# the density psum spans both processes' rows
assert grid.sum() == len(hits), (grid.sum(), len(hits))

# distributed converter ingest: every process parses its file share,
# the global index assembles collectively (run_distributed_ingest)
from geomesa_tpu.features.feature_type import parse_spec
from geomesa_tpu.jobs import run_distributed_ingest
work = os.environ["GEOMESA_WORK"]
paths = []
for f in range(3):   # shared file list; each process parses its share
    p = os.path.join(work, f"f{f}.csv")
    if proc == 0:    # one writer; files exist before both processes read
        frng = np.random.default_rng(100 + f)
        rows = [f"u{f}_{i},{MS + i * 60_000},"
                f"{frng.uniform(-74.5, -73.5):.6f},"
                f"{frng.uniform(40.2, 41.8):.6f}" for i in range(40)]
        with open(p + ".tmp", "w") as fh:
            fh.write("\n".join(rows) + "\n")
        os.replace(p + ".tmp", p)
    paths.append(p)
import time as _time
while not all(os.path.exists(p) for p in paths):
    _time.sleep(0.05)
sft = parse_spec("pts", "name:String,dtg:Date,*geom:Point")
config = {"type": "csv", "fields": [
    {"name": "name", "transform": "toString($0)"},
    {"name": "dtg", "transform": "toLong($1)"},
    {"name": "geom", "transform": "point($2, $3)"},
]}
ing_idx, result = run_distributed_ingest(sft, config, paths,
                                         period="week", mesh=mesh)
assert ing_idx.total() == 120, ing_idx.total()  # 3 files x 40 rows
ing_hits = ing_idx.query([(-75.0, 40.0, -73.0, 42.0)], None, None)
assert len(ing_hits) == 120

print(f"MULTIHOST-OK proc={proc} total={idx.total()} "
      f"hits={len(hits)} mine={len(mine)} count={count} "
      f"ingested={result.ingested}", flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_multihost(tmp_path):
    # subprocess timeouts below bound the runtime; no plugin marks needed
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = str(_free_port())
    env = dict(os.environ)
    env["GEOMESA_REPO"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["GEOMESA_WORK"] = str(tmp_path)
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost workers timed out")
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "MULTIHOST-OK" in out
    # both processes saw the same global hit count
    import re
    hits = [re.search(r"hits=(\d+)", o).group(1) for o in outs]
    assert hits[0] == hits[1]
