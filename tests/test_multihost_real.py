"""REAL multi-process multihost validation: two OS processes join one
JAX distributed system (gloo over localhost) and run the
multi-controller build + collective queries — the genuine
`jax.distributed` path, not a monkeypatched simulation (VERDICT r1
weak #8 taken all the way)."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r'''
import os, sys
proc = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
from jax._src import xla_bridge as _xb
_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["GEOMESA_REPO"])
from geomesa_tpu.parallel.multihost import (
    global_device_mesh, initialize_distributed,
)
initialize_distributed(f"localhost:{port}", num_processes=2,
                       process_id=proc)
assert jax.process_count() == 2

import numpy as np
import geomesa_tpu  # noqa: F401  (x64)
from geomesa_tpu.parallel.scan import GID_PROC_SHIFT, ShardedZ3Index

mesh = global_device_mesh()
rng = np.random.default_rng(proc)
n_local = 1000 + proc * 17          # deliberately uneven
MS = 1514764800000
x = rng.uniform(-75, -73, n_local)
y = rng.uniform(40, 42, n_local)
t = rng.integers(MS, MS + 7 * 86_400_000, n_local)
idx = ShardedZ3Index.build_multihost(x, y, t, period="week", mesh=mesh)
assert idx.total() == 2017, idx.total()

box = (-74.5, 40.5, -73.5, 41.5)
hits = idx.query([box], None, None)
procs = np.asarray(hits) >> GID_PROC_SHIFT
rows = np.asarray(hits) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
mine = np.sort(rows[procs == proc])
brute = np.flatnonzero((x >= box[0]) & (x <= box[2])
                       & (y >= box[1]) & (y <= box[3]))
assert np.array_equal(mine, brute), (len(mine), len(brute))

count = idx.range_count([box], MS, MS + 7 * 86_400_000)
assert count >= len(hits)
grid = idx.density([box], MS, MS + 7 * 86_400_000, box, 16, 16)
# the density psum spans both processes' rows
assert grid.sum() == len(hits), (grid.sum(), len(hits))
# weighted density: per-process LOCAL weight tables, offset by row
# bases inside the kernel (ADVICE r2: the masked-gid lookup read every
# process's rows from table offset 0).  Row-distinct weights (the x
# coordinate) would expose any base-offset error immediately.
from jax.experimental import multihost_utils as _mhu
wgrid = idx.density([box], MS, MS + 7 * 86_400_000, box, 16, 16,
                    weights=np.abs(x))
my_contrib = np.abs(x[brute]).sum()
want_w = float(np.asarray(
    _mhu.process_allgather(np.float64(my_contrib))).sum())
assert abs(wgrid.sum() - want_w) < 1e-6, (wgrid.sum(), want_w)

# distributed converter ingest: every process parses its file share,
# the global index assembles collectively (run_distributed_ingest)
from geomesa_tpu.features.feature_type import parse_spec
from geomesa_tpu.jobs import run_distributed_ingest
work = os.environ["GEOMESA_WORK"]
paths = []
for f in range(3):   # shared file list; each process parses its share
    p = os.path.join(work, f"f{f}.csv")
    if proc == 0:    # one writer; files exist before both processes read
        frng = np.random.default_rng(100 + f)
        rows = [f"u{f}_{i},{MS + i * 60_000},"
                f"{frng.uniform(-74.5, -73.5):.6f},"
                f"{frng.uniform(40.2, 41.8):.6f}" for i in range(40)]
        with open(p + ".tmp", "w") as fh:
            fh.write("\n".join(rows) + "\n")
        os.replace(p + ".tmp", p)
    paths.append(p)
import time as _time
while not all(os.path.exists(p) for p in paths):
    _time.sleep(0.05)
sft = parse_spec("pts", "name:String,dtg:Date,*geom:Point")
config = {"type": "csv", "fields": [
    {"name": "name", "transform": "toString($0)"},
    {"name": "dtg", "transform": "toLong($1)"},
    {"name": "geom", "transform": "point($2, $3)"},
]}
ing_idx, result = run_distributed_ingest(sft, config, paths,
                                         period="week", mesh=mesh)
assert ing_idx.total() == 120, ing_idx.total()  # 3 files x 40 rows
ing_hits = ing_idx.query([(-75.0, 40.0, -73.0, 42.0)], None, None)
assert len(ing_hits) == 120

# ---- batched multi-window scans decode process bits correctly ----
# (ADVICE r2 medium: qid<<pos_bits must clear the full multihost gid
# span; proc>=1 hits used to decode process-stripped into wrong windows)
win_a = (-74.5, 40.5, -73.5, 41.5)
win_b = (-74.9, 40.1, -74.0, 41.9)
parts = idx.query_many([([win_a], None, None), ([win_b], None, None)])
for w, got_w in zip((win_a, win_b), parts):
    pr = np.asarray(got_w) >> GID_PROC_SHIFT
    rw = np.asarray(got_w) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
    mine_w = np.sort(rw[pr == proc])
    brute_w = np.flatnonzero((x >= w[0]) & (x <= w[2])
                             & (y >= w[1]) & (y <= w[3]))
    assert np.array_equal(mine_w, brute_w), (len(mine_w), len(brute_w))

# ---- device stats + count-min over multihost (per-process values) ----
from geomesa_tpu.parallel import sharded_frequency_scan, sharded_stats_scan
vals_local = np.arange(n_local, dtype=np.float64) % 50
stats_r = sharded_stats_scan(idx, [box], MS, MS + 7 * 86_400_000,
                             values=vals_local, hist_bins=10,
                             hist_range=(0, 50))
my_sel = brute  # box covers the full time range
# count matches the density total (both processes' hits)
assert stats_r["count"] == int(grid.sum()), (stats_r["count"], grid.sum())
freq = sharded_frequency_scan(idx, [box], MS, MS + 7 * 86_400_000,
                              vals_local)
# oracle: host sketch over BOTH processes' selected values (allgather)
from geomesa_tpu.stats.stat import Frequency
from geomesa_tpu.parallel.multihost import allgather_concat
all_vals = allgather_concat(vals_local[my_sel])
host_f = Frequency("v")
from geomesa_tpu.features.feature_type import parse_spec as _ps
from geomesa_tpu.features.batch import FeatureBatch as _FB
sft_f = _ps("f", "v:Double,dtg:Date,*geom:Point")
host_f.observe(_FB.from_dict(sft_f, {
    "v": all_vals, "dtg": np.full(len(all_vals), MS),
    "geom": (np.zeros(len(all_vals)), np.zeros(len(all_vals)))}))
assert np.array_equal(freq.table, host_f.table), "multihost CMS mismatch"
# string CMS (VERDICT r4 #8): per-process digests + device histograms
from geomesa_tpu.parallel.multihost import allgather_strings
names_local = np.array([f"n{i % 7}" for i in range(n_local)], dtype=object)
freq_s = sharded_frequency_scan(idx, [box], MS, MS + 7 * 86_400_000,
                                names_local)
host_fs = Frequency("v")
all_names = allgather_strings(names_local[my_sel])
host_fs.observe(_FB.from_dict(
    _ps("fs", "v:String,dtg:Date,*geom:Point"),
    {"v": all_names, "dtg": np.full(len(all_names), MS),
     "geom": (np.zeros(len(all_names)), np.zeros(len(all_names)))}))
assert np.array_equal(freq_s.table, host_fs.table), "string CMS mismatch"

# ---- multihost append on the raw index ----
m_new = 60 + proc * 7
nx2 = rng.uniform(-74.4, -73.6, m_new); ny2 = rng.uniform(40.6, 41.4, m_new)
nt2 = rng.integers(MS, MS + 7 * 86_400_000, m_new)
idx.append(nx2, ny2, nt2)
assert idx.total() == 2017 + 60 + 67, idx.total()
hits2 = idx.query([box], None, None)
ax = np.r_[x, nx2]; ay = np.r_[y, ny2]
procs2 = np.asarray(hits2) >> GID_PROC_SHIFT
rows2 = np.asarray(hits2) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
mine2 = np.sort(rows2[procs2 == proc])
brute2 = np.flatnonzero((ax >= box[0]) & (ax <= box[2])
                        & (ay >= box[1]) & (ay <= box[3]))
assert np.array_equal(mine2, brute2), (len(mine2), len(brute2))

# ---- the STORE, multihost mode: create_schema -> write -> append ->
# query/stats through the full planner with residual filtering on
# gid-decoded local candidates; NO process holds the full dataset ----
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql

ds = TpuDataStore(mesh=mesh, multihost=True)
ds.create_schema("evt", "name:String:index=true,score:Double,"
                        "dtg:Date,*geom:Point")
n_rows = 800 + proc * 13
sx = rng.uniform(-75, -73, n_rows); sy = rng.uniform(40, 42, n_rows)
stt = rng.integers(MS, MS + 14 * 86_400_000, n_rows)
ds.write("evt", {
    "name": rng.choice(["alpha", "beta", "gamma"], n_rows).astype(object),
    "score": rng.uniform(0, 100, n_rows),
    "dtg": stt, "geom": (sx, sy)})
st = ds._store("evt")
assert len(st.batch) == n_rows     # data stays distributed
assert ds.get_count("evt") == 800 + 813, ds.get_count("evt")

for ecql in (
    "BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
    "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z",
    "name = 'alpha' AND score > 50",
    "BBOX(geom,-74.2,40.8,-73.9,41.1)",
):
    got = ds.query_result("evt", ecql)
    want_local = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    gp = np.asarray(got.positions) >> GID_PROC_SHIFT
    gr = np.asarray(got.positions) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
    assert np.array_equal(np.sort(gr[gp == proc]), want_local), ecql
    # the local result batch is exactly this process's hit rows
    assert len(got.batch) == len(want_local), ecql

# append through the store (incremental multihost z3 append)
z3_obj = st._indexes.get("z3")
assert z3_obj is not None and z3_obj._multihost
m2 = 40 + proc * 5
ds.write("evt", {
    "name": np.array(["delta"] * m2, dtype=object),
    "score": rng.uniform(0, 100, m2),
    "dtg": rng.integers(MS, MS + 14 * 86_400_000, m2),
    "geom": (rng.uniform(-75, -73, m2), rng.uniform(40, 42, m2))})
assert st._indexes.get("z3") is z3_obj        # appended, not rebuilt
ecql = ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
        "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
got = ds.query_result("evt", ecql)
want_local = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
gp = np.asarray(got.positions) >> GID_PROC_SHIFT
gr = np.asarray(got.positions) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
assert np.array_equal(np.sort(gr[gp == proc]), want_local)
assert ds.get_count("evt") == 800 + 813 + 40 + 45

# zero-local-hit divergence: an id filter whose hits ALL live on
# process 0 — process 1 must still enter the collectives (stats_process
# monoid merge, get_count via positions) instead of short-circuiting
from geomesa_tpu.process import stats_process
one = ds.query_result("evt", "IN ('p0.0')")
assert len(one.positions) == 1
assert len(one.batch) == (1 if proc == 0 else 0)
assert ds.get_count("evt", "IN ('p0.0')") == 1
st_one = stats_process(ds, "evt", "IN ('p0.0')", "Count()")
assert st_one.count == 1, st_one.count

# analytics across processes: kNN's exact distances measure on each
# process's own rows and (gid, dist) pairs allgather — the 10 nearest
# must match a brute-force over BOTH processes' coordinates
from geomesa_tpu.process import knn_process
from geomesa_tpu.process.knn import haversine_m
qx, qy = -74.0, 41.0
kpos, kdist = knn_process(ds, "evt", qx, qy, 10)
assert len(kpos) == 10 and np.all(np.diff(kdist) >= 0)
bx, by = st.batch.geom_xy()
my_d = haversine_m(qx, qy, bx, by)
all_d = np.sort(allgather_concat(my_d))
np.testing.assert_allclose(np.sort(kdist), all_d[:10], rtol=1e-12)

# query_arrow with zero LOCAL hits (ADVICE r4): proc 1 holds none of
# the 'p0.0' hits but must still enter the mesh reduce with its empty
# local group and return the schema'd empty table, not None
tbl = ds.query_arrow_table("evt", "IN ('p0.0')")
assert tbl is not None and tbl.num_rows == (1 if proc == 0 else 0), tbl
assert "name" in tbl.schema.names

# string attribute bounds for a restricted caller (ADVICE r4): the
# per-process (min,max) pairs must ride the string collective — the
# float64 allgather raised ValueError on object columns
class _Auth:
    def get_authorizations(self):
        return frozenset(["u"])

ds_r = TpuDataStore(mesh=mesh, multihost=True, auth_provider=_Auth())
ds_r.create_schema("sec", "name:String,dtg:Date,*geom:Point")
sec_names = ["bb", "cc"] if proc == 0 else ["aa", "zz"]
ds_r.write("sec", {"name": np.array(sec_names, dtype=object),
                   "dtg": np.full(2, MS),
                   "geom": (np.zeros(2), np.zeros(2))},
           visibility=("u" if proc == 0 else "admin"))
nb = ds_r.get_attribute_bounds("sec", "name")
assert nb == ("bb", "cc"), nb   # proc 1's rows are hidden from this caller

# ---- LEAN profile, multihost (round-4 VERDICT #4): the sharded
# generational index through the store facade with per-process local
# rows, gid hits, prefixed implicit ids, tombstone deletes ----
from geomesa_tpu.parallel.lean import ShardedLeanZ3Index
# CI-sized generations: the production default (4M slots/shard) makes
# every CPU-mesh append sort a 4M-slot run per shard — minutes of pure
# sort time across the worker; 16k slots exercise identical code paths
ShardedLeanZ3Index.GENERATION_SLOTS = 1 << 14
from geomesa_tpu.parallel.attr_lean import ShardedLeanAttrIndex
ShardedLeanAttrIndex.GENERATION_SLOTS = 1 << 13
dsl = TpuDataStore(mesh=mesh, multihost=True)
dsl.create_schema("lean", "name:String:index=true,score:Double,"
                          "dtg:Date,*geom:Point;"
                          "geomesa.index.profile=lean")
nl = 700 + proc * 11
lx = rng.uniform(-75, -73, nl); ly = rng.uniform(40, 42, nl)
lt = rng.integers(MS, MS + 14 * 86_400_000, nl)
lsc = rng.uniform(0, 100, nl)
lnm = rng.choice(np.array(["aa", "bb", "rare"], object), nl,
                 p=[.6, .37, .03])
dsl.write("lean", {"name": lnm, "score": lsc, "dtg": lt,
                   "geom": (lx, ly)})
lst = dsl._store("lean")
assert isinstance(lst.index("z3"), ShardedLeanZ3Index)
assert len(lst.batch) == nl                  # data stays distributed
assert dsl.get_count("lean") == 700 + 711
lecql = ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
         "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z AND score > 25")
lgot = dsl.query_result("lean", lecql)
lfb = lst.batch.take(np.arange(nl))   # local-rows oracle batch
lwant = np.flatnonzero(evaluate_filter(parse_ecql(lecql), lfb))
lp = np.asarray(lgot.positions) >> GID_PROC_SHIFT
lr = np.asarray(lgot.positions) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
assert np.array_equal(np.sort(lr[lp == proc]), lwant), (
    len(lr[lp == proc]), len(lwant))
assert len(lgot.batch) == len(lwant)
# round-5: the sharded lean ATTRIBUTE tier under multihost — equality
# served from the (key, sec, gid) generational runs, candidates fetched
# globally, residual-filtered per process, survivors allgathered
assert isinstance(lst.attribute_index("name"), ShardedLeanAttrIndex)
aecql = "name = 'rare'"
agot = dsl.query_result("lean", aecql)
assert agot.strategy.index == "attr:name", agot.strategy
awant = np.flatnonzero(evaluate_filter(parse_ecql(aecql), lfb))
ap = np.asarray(agot.positions) >> GID_PROC_SHIFT
ar = np.asarray(agot.positions) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
assert np.array_equal(np.sort(ar[ap == proc]), awant), (
    len(ar[ap == proc]), len(awant))
# equality + time window rides the (key, sec) date tier
awin = ("name = 'aa' AND dtg DURING "
        "2018-01-03T00:00:00Z/2018-01-05T00:00:00Z")
agot2 = dsl.query_result("lean", awin)
awant2 = np.flatnonzero(evaluate_filter(parse_ecql(awin), lfb))
ap2 = np.asarray(agot2.positions) >> GID_PROC_SHIFT
ar2 = np.asarray(agot2.positions) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
assert np.array_equal(np.sort(ar2[ap2 == proc]), awant2)
print(f"[p{proc}] sharded lean attr tier: eq={len(agot.positions)} "
      f"eq+win={len(agot2.positions)}")

# tight per-shard budget: attr generations spill to the OWNING process,
# the stacked host bisection still answers, and both processes see the
# same GLOBAL candidate list
slots_a = 1 << 9
aidx = ShardedLeanAttrIndex("name", "string", mesh=mesh,
                            multihost=True, generation_slots=slots_a,
                            hbm_budget_bytes=slots_a * 24 * 2)
na = 4000   # equal per process: every append is collective
anm = rng.choice(np.array(["x", "y", "rareish"], object), na,
                 p=[.5, .47, .03])
adt = rng.integers(MS, MS + 14 * 86_400_000, na)
for s in range(0, na, 1000):
    aidx.append(anm[s:s + 1000], adt[s:s + 1000], base_gid=s)
atc = aidx.tier_counts()
assert atc["host"] >= 1, atc
acand = aidx.query_equals("rareish")
acp = np.asarray(acand) >> GID_PROC_SHIFT
acr = np.asarray(acand) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
assert np.array_equal(np.sort(acr[acp == proc]),
                      np.flatnonzero(anm == "rareish"))
print(f"[p{proc}] sharded lean attr spill: {atc} "
      f"cand={len(acand)}")

# prefixed implicit id lookup: one row of proc 0
one_l = dsl.query_result("lean", "IN ('p0.5')")
assert len(one_l.positions) == 1
assert len(one_l.batch) == (1 if proc == 0 else 0)
# incremental collective append
ml = 30 + proc * 3
dsl.write("lean", {"name": np.full(ml, "aa", dtype=object),
                   "score": rng.uniform(0, 100, ml),
                   "dtg": rng.integers(MS, MS + 14 * 86_400_000, ml),
                   "geom": (rng.uniform(-75, -73, ml),
                            rng.uniform(40, 42, ml))})
assert dsl.get_count("lean") == 700 + 711 + 30 + 33
# tombstone delete of proc-0 rows, agreed count on both processes
assert dsl.delete("lean", ["p0.5", "p0.6"]) == 2
assert dsl.get_count("lean") == 700 + 711 + 30 + 33 - 2
after_l = dsl.query_result("lean", "IN ('p0.5')")
assert len(after_l.positions) == 0
lenv = dsl.get_bounds("lean")
assert lenv is not None and -75.0 <= lenv.xmin <= lenv.xmax <= -73.0

# ---- tiered sharded lean under multihost: a tight per-shard budget
# forces payload drops AND host spills symmetrically on both processes
# (demotions derive from process-invariant metadata); spilled runs
# live on the OWNING process and hits still agree globally ----
slots_t = 1 << 9
tiered = ShardedLeanZ3Index(period="week", mesh=mesh, multihost=True,
                            generation_slots=slots_t,
                            hbm_budget_bytes=slots_t * 20 * 3)
ntr = 6000   # equal per process: every append is collective
tx = rng.uniform(-75, -73, ntr); ty = rng.uniform(40, 42, ntr)
tt = rng.integers(MS, MS + 14 * 86_400_000, ntr)
for s in range(0, ntr, 2000):
    tiered.append(tx[s:s + 2000], ty[s:s + 2000], tt[s:s + 2000])
tc = tiered.tier_counts()
assert tc["host"] >= 1 and tc["full"] == 0, tc
assert tiered.generations[-1].tier == "keys"
assert tiered.host_key_bytes() > 0          # this process spilled runs
tbox = (-74.5, 40.5, -73.5, 41.5)
tlo, thi = MS + 2 * 86_400_000, MS + 9 * 86_400_000
tgot = tiered.query([tbox], tlo, thi)
tp_ = tgot >> GID_PROC_SHIFT
tr_ = tgot & ((np.int64(1) << GID_PROC_SHIFT) - 1)
tmask = ((tx >= tbox[0]) & (tx <= tbox[2]) & (ty >= tbox[1])
         & (ty <= tbox[3]) & (tt >= tlo) & (tt <= thi))
assert np.array_equal(np.sort(tr_[tp_ == proc]), np.flatnonzero(tmask))
print(f"[p{proc}] tiered sharded lean: {tc} hits={len(tgot)}")

# ---- multihost lean snapshots: each process flushes its LOCAL rows
# into its own {name}.lean.pN dir and a fresh store reloads them (the
# per-process suffix must resolve at reload time, when the batch is
# empty) ----
snap_cat = os.path.join(work, "snapcat")
# one process creates the shared-catalog schema (concurrent
# create_schema of the same name is a documented check-then-act
# rejection — the reference's distributed-lock contract); the other
# opens the catalog after the barrier and loads it
if proc == 0:
    snap = TpuDataStore(snap_cat, mesh=mesh, multihost=True)
    snap.create_schema("snp", "score:Double,dtg:Date,*geom:Point;"
                              "geomesa.index.profile=lean")
_mhu.process_allgather(np.int32(proc))      # schema visible on disk
if proc != 0:
    snap = TpuDataStore(snap_cat, mesh=mesh, multihost=True)
assert snap.get_schema("snp") is not None
ns = 500 + proc * 7
sx = rng.uniform(-75, -73, ns); sy = rng.uniform(40, 42, ns)
stt = rng.integers(MS, MS + 14 * 86_400_000, ns)
snap.write("snp", {"score": rng.uniform(0, 100, ns), "dtg": stt,
                   "geom": (sx, sy)})
snap.flush("snp")
assert os.path.isdir(os.path.join(snap_cat, f"snp.lean.p{proc}"))
snap2 = TpuDataStore(snap_cat, mesh=mesh, multihost=True)
sst = snap2._store("snp")
assert len(sst.batch) == ns, (len(sst.batch), ns)
sq = ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
      "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
sgot = snap2.query_result("snp", sq)
sfb = sst.batch.take(np.arange(ns))
swant = np.flatnonzero(evaluate_filter(parse_ecql(sq), sfb))
sp_ = np.asarray(sgot.positions) >> GID_PROC_SHIFT
sr_ = np.asarray(sgot.positions) & ((np.int64(1) << GID_PROC_SHIFT) - 1)
assert np.array_equal(np.sort(sr_[sp_ == proc]), swant)
print(f"[p{proc}] lean snapshot reload: {ns} rows, "
      f"{len(swant)} local hits oracle-exact")

# ---- lambda persistence flush -> multihost LEAN store (VERDICT r4
# #10): per-process stream writes, collective flush, lean query sees
# every process's rows ----
from geomesa_tpu.lambda_store import LambdaDataStore
lam_p = TpuDataStore(mesh=mesh, multihost=True)
lam_p.create_schema("llean", "name:String,dtg:Date,*geom:Point;"
                             "geomesa.index.profile=lean")
clk = [1000.0]
lam = LambdaDataStore(lam_p, expiry_ms=1000, clock=lambda: clk[0])
lam.stream.create_schema("llean", "name:String,dtg:Date,*geom:Point")
for i in range(3 + proc):            # uneven per-process stream loads
    lam.write("llean", f"s{proc}_{i}",
              {"name": f"p{proc}", "dtg": MS,
               "geom": (-74.0 - 0.01 * i, 40.5 + 0.01 * i)})
clk[0] += 2.0
assert lam.persist("llean") == 3 + proc
assert lam_p.get_count("llean") == 7          # 3 + 4 across processes
lres2 = lam_p.query_result("llean", "BBOX(geom,-75,40,-73,42)")
assert len(lres2.positions) == 7
# one process flushing alone: the peer enters the collectives too
if proc == 0:
    lam.write("llean", "solo", {"name": "p0", "dtg": MS,
                                "geom": (-74.5, 41.0)})
clk[0] += 2.0
assert lam.persist("llean") == (1 if proc == 0 else 0)
assert lam_p.get_count("llean") == 8

# merged global stats + bounds
env = ds.get_bounds("evt")
assert env is not None and env.xmin >= -75.0 and env.xmax <= -73.0
topk = ds.stat("evt", "name_topk")
assert topk is not None and topk.topk(1)[0][0] in ("alpha", "beta", "gamma")

print(f"MULTIHOST-OK proc={proc} total={idx.total()} "
      f"hits={len(hits)} mine={len(mine)} count={count} "
      f"store_hits={len(got.positions)} "
      f"ingested={result.ingested}", flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_multihost(tmp_path):
    # subprocess timeouts below bound the runtime; no plugin marks needed
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = str(_free_port())
    env = dict(os.environ)
    env["GEOMESA_REPO"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["GEOMESA_WORK"] = str(tmp_path)
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost workers timed out")
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "MULTIHOST-OK" in out
    # both processes saw the same global hit count
    import re
    hits = [re.search(r"hits=(\d+)", o).group(1) for o in outs]
    assert hits[0] == hits[1]
