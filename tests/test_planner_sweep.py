"""Planner correctness sweep: every strategy's hit set must equal the
full-filter oracle across tricky filter shapes (the reference's
*IdxStrategyTest correctness-vs-baseline pattern, SURVEY.md §4)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import parse_ecql
from geomesa_tpu.filters.evaluate import evaluate_filter

MS = 1514764800000
DAY = 86_400_000

QUERIES = [
    "BBOX(geom,-10,-10,10,10)",
    "NOT BBOX(geom,-10,-10,10,10)",
    "BBOX(geom,-10,-10,10,10) AND v > 0",
    "BBOX(geom,-10,-10,10,10) OR BBOX(geom,100,0,120,20)",
    "(BBOX(geom,-10,-10,10,10) OR name = 'n1') AND score < 0.5",
    "name = 'n1' AND dtg DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z",
    "name IN ('n1','n2') AND NOT cat = 'c0'",
    "v BETWEEN -10 AND 10 AND BBOX(geom,0,0,90,85)",
    "dtg AFTER 2018-01-15T00:00:00Z",
    "dtg BEFORE 2018-01-02T00:00:00Z OR dtg AFTER 2018-01-20T00:00:00Z",
    "INTERSECTS(geom, POLYGON((0 0, 40 0, 40 40, 0 40, 0 0)))",
    "NOT (name = 'n1' OR name = 'n2')",
    "score >= 0.99 OR v = 0",
    "BBOX(geom,-180,-85,180,85) AND name LIKE 'n%'",
    "(name = 'n3' AND BBOX(geom,-50,-50,50,50)) "
    "OR (cat = 'c2' AND dtg BEFORE 2018-01-02T00:00:00Z)",
    "DWITHIN(geom, POINT(5 5), 3)",
]


@pytest.fixture(scope="module")
def store():
    rng = np.random.default_rng(123)
    n = 20_000
    ds = TpuDataStore()
    ds.create_schema(
        "t", "name:String:index=true,cat:String,v:Int,score:Double,"
             "dtg:Date,*geom:Point")
    ds.write("t", {
        "name": np.asarray([f"n{i % 7}" for i in range(n)], dtype=object),
        "cat": np.asarray([f"c{i % 3}" for i in range(n)], dtype=object),
        "v": rng.integers(-50, 50, n),
        "score": rng.uniform(0, 1, n),
        "dtg": rng.integers(MS, MS + 21 * DAY, n),
        "geom": (rng.uniform(-180, 180, n), rng.uniform(-85, 85, n)),
    })
    return ds


@pytest.mark.parametrize("ecql", QUERIES)
def test_strategy_hits_equal_oracle(store, ecql):
    got = store.query_result("t", ecql).positions
    oracle = np.flatnonzero(
        evaluate_filter(parse_ecql(ecql), store._store("t").batch))
    np.testing.assert_array_equal(np.sort(got), oracle)


def test_or_split_uses_indexes(store):
    """A top-level OR whose branches each have an index scans per branch
    and unions (FilterSplitter's disjunction handling) — exactly."""
    q = ("(name = 'n3' AND BBOX(geom,-50,-50,50,50)) "
         "OR name = 'n1' OR BBOX(geom,100,0,120,20)")
    ex = store.explain("t", q)
    assert "OR-split" in ex
    got = store.query_result("t", q).positions
    oracle = np.flatnonzero(
        evaluate_filter(parse_ecql(q), store._store("t").batch))
    np.testing.assert_array_equal(np.sort(got), oracle)


def test_or_split_respects_block_full_scans(store):
    """With full scans blocked, indexable ORs still run (via or-split);
    unindexable filters still raise."""
    from geomesa_tpu.config import clear_property, set_property

    set_property("geomesa.scan.block.full.table", True)
    try:
        r = store.query_result("t", "name = 'n1' OR BBOX(geom, 0, 0, 5, 5)")
        assert r.strategy.index == "or-split"
        with pytest.raises(RuntimeError):
            store.query("t", "score < 2")  # unindexed attribute
    finally:
        clear_property("geomesa.scan.block.full.table")


def test_multi_interval_auto_batch(store):
    """Disjoint time windows over one bbox route through query_many in a
    single dispatch (VERDICT r1 item 8), exactly."""
    q = ("BBOX(geom,-60,-60,60,60) AND (dtg DURING "
         "2018-01-02T00:00:00Z/2018-01-04T00:00:00Z OR dtg DURING "
         "2018-01-10T00:00:00Z/2018-01-12T00:00:00Z)")
    ex = store.explain("t", q)
    assert "Auto-batched" in ex and "time windows" in ex
    got = store.query_result("t", q).positions
    oracle = np.flatnonzero(
        evaluate_filter(parse_ecql(q), store._store("t").batch))
    np.testing.assert_array_equal(np.sort(got), oracle)


def test_or_split_auto_batches_z3_branches(store):
    """An OR of spatio-temporal conjunctions plus an attribute branch:
    the z3 branches batch into one dispatch inside the or-split."""
    q = ("(BBOX(geom,-60,-60,-20,-20) AND dtg DURING "
         "2018-01-02T00:00:00Z/2018-01-06T00:00:00Z) "
         "OR (BBOX(geom,20,20,60,60) AND dtg DURING "
         "2018-01-10T00:00:00Z/2018-01-14T00:00:00Z) "
         "OR name = 'n5'")
    ex = store.explain("t", q)
    assert "OR-split" in ex
    assert "Auto-batched 2 z3 windows" in ex
    got = store.query_result("t", q).positions
    oracle = np.flatnonzero(
        evaluate_filter(parse_ecql(q), store._store("t").batch))
    np.testing.assert_array_equal(np.sort(got), oracle)
