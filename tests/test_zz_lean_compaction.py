"""LSM generation compaction + sealed-generation density partial
caching (the lean tiered store's maintenance lifecycle).

Covers the ISSUE-2 acceptance surface: merge correctness vs oracle
(hit sets identical pre/mid/post compact), the 1B-shaped scaled-down
ingest (≥ 20 appends forcing ≥ 15 demotions) ending at ≤ 8 generations,
budget-exhausted compaction resuming cleanly, memory accounting
releasing merged runs' slack slots, cached density partials
invalidating when sealed generations compact away, the ≥ 5× warm
repeat density speedup, and the satellite regressions (sql_join
multihost gate, string-None encoding, sharded attr slot burn,
bench record fallback, partial-window density divergence bound).

Named ``test_zz_*`` deliberately: this is the heavyweight lifecycle
suite (many-generation builds, device merges), so it runs at the END of
the alphabetical tier-1 order, after the fast unit suites.
"""

import json
import time

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.index.attr_lean import LeanAttrIndex, encode_attr_values
from geomesa_tpu.index.z3_lean import LeanZ3Index

MS = 1514764800000
DAY = 86_400_000
WORLD = (-180.0, -90.0, 180.0, 90.0)
SLOTS = 1 << 12
BOX = (-74.5, 40.5, -73.5, 41.5)
T_LO, T_HI = MS + 2 * DAY, MS + 9 * DAY


def _data(n, seed=7):
    rng = np.random.default_rng(seed)
    return (rng.uniform(-75, -73, n), rng.uniform(40, 42, n),
            rng.integers(MS, MS + 14 * DAY, n))


def _oracle(x, y, t, box, lo, hi):
    m = ((x >= box[0]) & (x <= box[2])
         & (y >= box[1]) & (y <= box[3]))
    if lo is not None:
        m = m & (t >= lo)
    if hi is not None:
        m = m & (t <= hi)
    return np.flatnonzero(m)


def _brute_grid(x, y, sel, env, w, h):
    g = np.zeros((h, w))
    gx = np.clip(((x[sel] - env[0]) / (env[2] - env[0]) * w).astype(int),
                 0, w - 1)
    gy = np.clip(((y[sel] - env[1]) / (env[3] - env[1]) * h).astype(int),
                 0, h - 1)
    np.add.at(g, (gy, gx), 1.0)
    return g


def _clone(src_idx):
    """Structural clone of a built index: generations share the SOURCE's
    immutable jnp columns / host runs (merges always allocate fresh
    arrays, never mutate), so compaction tests can reuse one expensive
    streamed build."""
    from geomesa_tpu.index.z3_lean import _Generation
    idx = LeanZ3Index(period="week", generation_slots=SLOTS,
                      payload_on_device=False,
                      hbm_budget_bytes=src_idx.hbm_budget_bytes)
    for g in src_idx.generations:
        ng = _Generation.__new__(_Generation)
        for slot in _Generation.__slots__:
            setattr(ng, slot, getattr(g, slot))
        idx.generations.append(ng)
    idx._payload = list(src_idx._payload)
    idx._flat = src_idx._flat
    idx._n_rows = src_idx._n_rows
    idx.t_min_ms = src_idx.t_min_ms
    idx.t_max_ms = src_idx.t_max_ms
    idx._gen_counter = src_idx._gen_counter
    return idx


@pytest.fixture(scope="module")
def built20():
    """One 20-generation streamed build shared (via _clone) by every
    test that only compacts/queries it."""
    return _streamed(20)


def _streamed(n_gens, payload=False, budget=None, factor=None,
              seed=7):
    x, y, t = _data(n_gens * SLOTS, seed=seed)
    idx = LeanZ3Index(period="week", generation_slots=SLOTS,
                      payload_on_device=payload,
                      hbm_budget_bytes=budget,
                      compaction_factor=factor)
    for lo in range(0, len(x), SLOTS):
        sl = slice(lo, lo + SLOTS)
        idx.append(x[sl], y[sl], t[sl])
    return idx, x, y, t


# -- compaction correctness -----------------------------------------------
def test_compact_keys_tier_oracle_exact_and_log_generations(built20):
    src_idx, x, y, t = built20
    idx = _clone(src_idx)
    assert len(idx.generations) == 20
    before = idx.query([BOX], T_LO, T_HI)
    stats = idx.compact()
    assert stats["merged_groups"] >= 4
    assert len(idx.generations) <= 8
    after = idx.query([BOX], T_LO, T_HI)
    np.testing.assert_array_equal(before, after)
    np.testing.assert_array_equal(after,
                                  _oracle(x, y, t, BOX, T_LO, T_HI))


def test_demotion_heavy_ingest_compacts_host_runs():
    """The 1B-shaped scaled-down analogue: ≥ 20 appends under a budget
    forcing ≥ 15 demotions to host RAM; compaction folds the spilled
    runs and every query/density result stays oracle-exact."""
    budget = 6 * SLOTS * 16   # sentinel + ~5 device keys generations
    idx, x, y, t = _streamed(21, budget=budget)
    tiers = idx.tier_counts()
    assert tiers["host"] >= 15
    before_q = idx.query([BOX], T_LO, T_HI)
    before_d = idx.density([BOX], T_LO, T_HI, WORLD, 64, 32)
    stats = idx.compact()
    assert len(idx.generations) <= 8
    assert idx.tier_counts()["host"] <= 2
    np.testing.assert_array_equal(idx.query([BOX], T_LO, T_HI),
                                  before_q)
    np.testing.assert_array_equal(
        idx.query([BOX], T_LO, T_HI),
        _oracle(x, y, t, BOX, T_LO, T_HI))
    np.testing.assert_array_equal(
        idx.density([BOX], T_LO, T_HI, WORLD, 64, 32), before_d)
    # whole-extent density stays exact over the merged runs
    np.testing.assert_array_equal(
        idx.density([WORLD], None, None, WORLD, 64, 32),
        _brute_grid(x, y, np.ones(len(x), bool), WORLD, 64, 32))
    assert stats["generations"] == len(idx.generations)


def test_budget_exhausted_compaction_resumes(built20):
    src_idx, x, y, t = built20
    idx = _clone(src_idx)
    want = _oracle(x, y, t, BOX, T_LO, T_HI)
    gens0 = len(idx.generations)
    stats = idx.compact(budget_ms=0.0)
    # progress is guaranteed (≥ 1 group) but the deadline stops it
    assert stats["merged_groups"] == 1
    assert len(idx.generations) < gens0
    # mid-compaction state serves exact results
    np.testing.assert_array_equal(idx.query([BOX], T_LO, T_HI), want)
    rounds = 0
    while idx.compact(budget_ms=0.0)["merged_groups"]:
        rounds += 1
        assert rounds < 50
    assert len(idx.generations) <= 8
    np.testing.assert_array_equal(idx.query([BOX], T_LO, T_HI), want)


def test_compact_factor_one_clamps_and_terminates(built20):
    """factor=1 would re-merge a run into its own size class forever;
    the shared planner clamps to 2 (index/lsm.py)."""
    idx = _clone(built20[0])
    stats = idx.compact(factor=1)
    assert stats["merged_groups"] >= 1
    assert len(idx.generations) <= 8


def test_opportunistic_compaction_bounds_generations():
    """With the trigger enabled, a 24-flush stream never accumulates
    24 runs — the post-append merges keep the count O(log)."""
    idx, x, y, t = _streamed(24, factor=4)
    assert idx.compactions >= 4
    assert len(idx.generations) <= 8
    np.testing.assert_array_equal(
        idx.query([BOX], T_LO, T_HI),
        _oracle(x, y, t, BOX, T_LO, T_HI))


def test_attr_index_compaction_oracle():
    vals = np.random.default_rng(3).integers(0, 50, 40_000)
    sec = np.random.default_rng(4).integers(MS, MS + DAY, 40_000)
    idx = LeanAttrIndex("v", "int", generation_slots=1 << 12)
    for lo in range(0, len(vals), 1 << 12):
        idx.append(vals[lo:lo + (1 << 12)], sec[lo:lo + (1 << 12)],
                   base_gid=lo)
    assert len(idx.generations) == 10
    want = np.flatnonzero(vals == 7)
    np.testing.assert_array_equal(idx.query_equals(7), want)
    stats = idx.compact()
    assert stats["merged_groups"] >= 2
    assert len(idx.generations) <= 4
    np.testing.assert_array_equal(idx.query_equals(7), want)
    np.testing.assert_array_equal(
        idx.query_range(10, 20), np.flatnonzero((vals >= 10)
                                                & (vals <= 20)))


# -- memory accounting ----------------------------------------------------
def test_sharded_compaction_releases_slack_slots():
    """Sharded generations seal with slack (rollover keeps m_pad
    headroom); the merged run is sized to the consumed slots only, so
    device residency DROPS by exactly the released slack."""
    import jax
    from geomesa_tpu.parallel.lean import ShardedLeanZ3Index
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("shard",))
    # 200-row appends consume 32 slots/step (m_pad), so a 120-slot
    # generation seals at 96 consumed slots with 24 slots of slack
    slots = 120
    idx = ShardedLeanZ3Index(period="week", mesh=mesh,
                             generation_slots=slots,
                             payload_on_device=False)
    x, y, t = _data(30 * 200, seed=5)
    for lo in range(0, len(x), 200):
        sl = slice(lo, lo + 200)
        idx.append(x[sl], y[sl], t[sl])
    sealed = idx.generations[:-1]
    assert len(sealed) >= 4
    slack = sum(g.slots - g.n_slots for g in sealed[:4])
    assert slack > 0       # rollover sealed them below capacity
    before = idx.device_bytes()
    hits0 = idx.query([BOX], T_LO, T_HI)
    stats = idx.compact()
    assert stats["merged_groups"] >= 1
    assert idx.device_bytes() < before
    np.testing.assert_array_equal(idx.query([BOX], T_LO, T_HI), hits0)
    np.testing.assert_array_equal(
        hits0, _oracle(x, y, t, BOX, T_LO, T_HI))


def test_single_chip_accounting_consistent_after_compact(built20):
    src_idx, x, y, t = built20
    idx = _clone(src_idx)
    from geomesa_tpu.index.z3_lean import KEYS_BYTES
    before = idx.device_bytes()
    idx.compact()
    # merged runs carry zero padding: residency never grows, and the
    # accounting equals the live structure exactly
    assert idx.device_bytes() <= before
    assert idx.device_bytes() == sum(
        g.capacity * KEYS_BYTES for g in idx.generations
        if g.tier == "keys")
    assert idx._fits()


# -- sealed-generation density partial cache ------------------------------
def test_warm_repeat_density_5x_faster_and_exact():
    # 48 generations: the regime the cache targets — cold cost scales
    # with generation count while warm stays at the live-only floor
    # (48 keeps the measured ratio ~13x on an idle host, so the 5x
    # assertion holds through CI contention)
    idx, x, y, t = _streamed(48)
    want = _brute_grid(x, y, _oracle(x, y, t, BOX, T_LO, T_HI),
                       WORLD, 256, 128)
    # compile both the all-generations (cold) and live-only (warm)
    # program shapes first, so the timed ratio compares WORK, not
    # first-call compiles
    idx.density([BOX], T_LO, T_HI, WORLD, 256, 128)
    idx.density([BOX], T_LO, T_HI, WORLD, 256, 128)
    idx._density_cache.clear()
    t0 = time.perf_counter()
    cold = idx.density([BOX], T_LO, T_HI, WORLD, 256, 128)
    cold_dt = time.perf_counter() - t0
    d0 = idx.dispatch_count
    warm_dt = float("inf")
    for _ in range(3):     # best-of-3 damps shared-CI timer noise
        t0 = time.perf_counter()
        warm = idx.density([BOX], T_LO, T_HI, WORLD, 256, 128)
        warm_dt = min(warm_dt, time.perf_counter() - t0)
    np.testing.assert_array_equal(warm, cold)
    # BOX×window is cell-inclusive on keys tiers: mass may exceed the
    # value-exact oracle only by boundary-cell points
    assert warm.sum() >= want.sum()
    # each warm call re-scans ONLY the live generation: one probe +
    # one scan per repeat
    assert idx.dispatch_count - d0 <= 6
    assert cold_dt >= 5 * warm_dt, (cold_dt, warm_dt)


def test_density_cache_hits_and_misses_counted(built20):
    from geomesa_tpu.metrics import (
        LEAN_DENSITY_CACHE_HITS, LEAN_DENSITY_CACHE_MISSES,
        registry,
    )
    idx = _clone(built20[0])
    h0 = registry.counter(LEAN_DENSITY_CACHE_HITS).count
    m0 = registry.counter(LEAN_DENSITY_CACHE_MISSES).count
    idx.density([BOX], T_LO, T_HI, WORLD, 32, 16)
    assert registry.counter(LEAN_DENSITY_CACHE_MISSES).count - m0 == 19
    idx.density([BOX], T_LO, T_HI, WORLD, 32, 16)
    assert registry.counter(LEAN_DENSITY_CACHE_HITS).count - h0 == 19


def test_cached_partials_invalidate_when_generations_compact_away(
        built20):
    src_idx, x, y, t = built20
    idx = _clone(src_idx)
    g1 = idx.density([BOX], T_LO, T_HI, WORLD, 64, 32)
    spec_caches = list(idx._density_cache.values())
    assert spec_caches and len(spec_caches[0]) == 19
    idx.compact()
    live_ids = {g.gen_id for g in idx.generations}
    for cache in idx._density_cache.values():
        assert set(cache) <= live_ids   # no stale partials survive
    g2 = idx.density([BOX], T_LO, T_HI, WORLD, 64, 32)
    np.testing.assert_array_equal(g1, g2)
    # and the re-seeded cache serves the compacted shape
    np.testing.assert_array_equal(
        idx.density([BOX], T_LO, T_HI, WORLD, 64, 32), g1)


def test_density_cache_survives_demotion_and_lru_bounds_specs(
        built20):
    from geomesa_tpu.metrics import (
        LEAN_DENSITY_CACHE_MISSES, registry,
    )
    idx = _clone(built20[0])
    g1 = idx.density([BOX], T_LO, T_HI, WORLD, 16, 8)
    # demotion does not change a sealed generation's rows: its cached
    # partial keeps serving after the spill (keys-tier and host-tier
    # scans share the cell-granular contract)
    for g in idx.generations[:-1]:
        g.spill_to_host()
    idx._host_stack = None
    m0 = registry.counter(LEAN_DENSITY_CACHE_MISSES).count
    g2 = idx.density([BOX], T_LO, T_HI, WORLD, 16, 8)
    np.testing.assert_array_equal(g1, g2)
    assert registry.counter(LEAN_DENSITY_CACHE_MISSES).count == m0
    # the spec LRU stays bounded
    for i in range(LeanZ3Index.DENSITY_CACHE_SPECS + 2):
        idx.density([BOX], T_LO + i * 1000, T_HI, WORLD, 16, 8)
    assert len(idx._density_cache) <= LeanZ3Index.DENSITY_CACHE_SPECS


# -- store-level lifecycle ------------------------------------------------
def _lean_store(n=80_000, factor=0, budget=None):
    """A lean store in the many-generation regime.  ``budget`` forces
    the 1B-shaped tiering: sealed generations demote to keys/host —
    the runs compaction targets (full-tier runs never merge; under
    pressure they demote first, exactly the 1B profile)."""
    rng = np.random.default_rng(17)
    ds = TpuDataStore()
    budget = budget if budget is not None else 16 * SLOTS * 16
    ds.create_schema(
        "evt", "name:String:index=true,score:Double,dtg:Date,"
               "*geom:Point;geomesa.index.profile=lean,"
               f"geomesa.lean.generation.slots={SLOTS},"
               f"geomesa.lean.hbm.budget={budget},"
               f"geomesa.lean.compaction.factor={factor}")
    for s in range(0, n, SLOTS):
        m = min(SLOTS, n - s)
        ds.write("evt", {
            "name": rng.choice(["a", "b", "c"], m).astype(object),
            "score": rng.uniform(0, 100, m),
            "dtg": rng.integers(MS, MS + 14 * DAY, m),
            "geom": (rng.uniform(-75, -73, m),
                     rng.uniform(40, 42, m))})
    return ds


def test_store_compact_api_and_job_oracle_exact():
    from geomesa_tpu.jobs import run_compaction
    from geomesa_tpu.process.knn import knn_process

    ds = _lean_store()
    st = ds._store("evt")
    assert len(st.index("z3").generations) >= 19
    ecql = (f"BBOX(geom,{BOX[0]},{BOX[1]},{BOX[2]},{BOX[3]}) AND "
            "dtg DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    before = ds.query_result("evt", ecql).positions
    knn_before = knn_process(ds, "evt", -74.0, 41.0, 10)[0]
    stats = run_compaction(ds, "evt")
    assert stats["z3"]["merged_groups"] >= 1
    assert len(st.index("z3").generations) <= 8
    np.testing.assert_array_equal(
        ds.query_result("evt", ecql).positions, before)
    np.testing.assert_array_equal(
        knn_process(ds, "evt", -74.0, 41.0, 10)[0], knn_before)
    # attribute index compacted through the same call
    assert "attr:name" in stats
    # a second call converges to a no-op
    assert ds.compact("evt")["z3"]["merged_groups"] == 0


def test_store_opportunistic_compaction_via_option():
    ds = _lean_store(factor=4)
    st = ds._store("evt")
    assert st.index("z3").compactions >= 1
    assert len(st.index("z3").generations) <= 8


# -- satellite regressions ------------------------------------------------
def test_sql_join_multihost_gated(monkeypatch):
    import jax

    from geomesa_tpu.sql.join import sql_join

    rng = np.random.default_rng(1)
    ds = TpuDataStore()
    for name in ("a", "b"):
        ds.create_schema(name, "site:String,score:Double,dtg:Date,"
                               "*geom:Point")
        ds.write(name, {
            "site": rng.choice(["x", "y"], 100).astype(object),
            "score": rng.uniform(0, 100, 100),
            "dtg": rng.integers(MS, MS + DAY, 100),
            "geom": (rng.uniform(-75, -73, 100),
                     rng.uniform(40, 42, 100))})
    sql = ("SELECT a.site, b.score FROM a a JOIN b b "
           "ON a.site = b.site LIMIT 5")
    assert sql_join(ds, sql)   # single-process joins still work
    # multihost MODE on one process holds all rows locally — allowed
    ds._store("b").multihost = True
    assert sql_join(ds, sql)
    # ...but with real peer processes the pairing would silently drop
    # cross-process rows — gated loudly
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(NotImplementedError, match="multihost"):
        sql_join(ds, sql)


def test_encode_strings_none_path_independent():
    # fast astype('S8') path (plain ASCII + None)
    fast = encode_attr_values(
        np.array(["abc", None, ""], dtype=object), "string")
    # forced fallback path (non-ASCII entry)
    slow = encode_attr_values(
        np.array(["abc", None, "", "é"], dtype=object), "string")
    np.testing.assert_array_equal(fast, slow[:3])
    # None encodes as the EMPTY key, not as the string "None"
    assert fast[1] == fast[2]
    assert fast[1] != encode_attr_values(np.array(["None"]),
                                         "string")[0]


def test_sharded_attr_append_reuses_padded_region():
    import jax
    from geomesa_tpu.parallel.attr_lean import ShardedLeanAttrIndex
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("shard",))
    idx = ShardedLeanAttrIndex("v", "int", mesh=mesh,
                               generation_slots=64)
    vals = np.arange(30, dtype=np.int64)
    # ten 3-row collective steps: the old append burned m_pad (= 8)
    # slots per step — 80 slots, spilling into a second generation;
    # fill-tracking consumes 3 per step and packs all 30 rows into one
    for i in range(10):
        sl = slice(3 * i, 3 * i + 3)
        idx.append(vals[sl], np.full(3, MS), base_gid=3 * i)
    assert len(idx.generations) == 1
    assert idx.generations[-1].n_slots == 30
    for probe in (0, 13, 29):
        np.testing.assert_array_equal(idx.query_equals(probe),
                                      np.array([probe]))


def test_scale_stanza_skips_corrupt_record(tmp_path, monkeypatch):
    import bench
    here = tmp_path
    (here / "STORE_SCALE_r05.json").write_text("{corrupt")
    (here / "STORE_SCALE_r04.json").write_text(
        json.dumps({"rows": 42}))
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(here))
    monkeypatch.setenv("SCALE_LIVE_N", "0")
    monkeypatch.setenv("STORE_SCALE_LIVE_N", "0")
    out = bench._scale_stanza()
    # the older round's parseable record wins; no error survives
    assert out["store_recorded"] == {"rows": 42}
    assert "store_recorded_error" not in out


def test_partial_window_density_divergence_pinned():
    """Pin the cell-granular over-inclusion bound documented at the
    density_process API: on a DEMOTED (keys/host) store, a
    partial-window grid may exceed the materializing fallback only by
    points within one z cell of the window boundary — and only
    upward (no true hit is ever excluded)."""
    from geomesa_tpu.process.density import density_process

    ds = _lean_store(n=60_000)
    st = ds._store("evt")
    idx = st.index("z3")
    # demote everything sealed: partial-window masks now run at cell
    # granularity on every sealed generation
    for g in idx.generations[:-1]:
        g.spill_to_host()
    idx._host_stack = None
    x, y = st.batch.geom_xy()
    t = np.asarray(st.batch.column("dtg"), np.int64)
    ecql = (f"BBOX(geom,{BOX[0]},{BOX[1]},{BOX[2]},{BOX[3]}) AND "
            "dtg DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    w, h = 256, 128
    grid = density_process(ds, "evt", ecql, WORLD, w, h)
    lo = MS + 2 * DAY
    hi = MS + 9 * DAY
    true_hits = _oracle(x, y, t, BOX, lo, hi)
    exact = _brute_grid(x, y, true_hits, WORLD, w, h)
    # one z cell in each dimension (21-bit lon/lat; time cell within
    # the week bin)
    eps_x = 360.0 / (1 << 21)
    eps_y = 180.0 / (1 << 21)
    eps_t = 7 * DAY / (1 << 21)
    expanded = _oracle(x, y, t,
                       (BOX[0] - eps_x, BOX[1] - eps_y,
                        BOX[2] + eps_x, BOX[3] + eps_y),
                       lo - eps_t, hi + eps_t)
    bound = len(expanded) - len(true_hits)
    diff = grid.sum() - exact.sum()
    assert 0 <= diff <= bound
    # world-aligned pow2 grid: binning is exact, so over-inclusion is
    # the ONLY divergence — per-cell the push-down never undercounts
    assert (grid - exact >= -1e-9).all()
