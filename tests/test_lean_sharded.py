"""ShardedLeanZ3Index: the lean generational index over the 8-device
virtual mesh (round-4 VERDICT #4) — per-shard sorted runs, collective
probe/scan, oracle-equal hits."""

import numpy as np
import pytest

from geomesa_tpu.parallel import device_mesh
from geomesa_tpu.parallel.lean import ShardedLeanZ3Index

MS = 1514764800000
DAY = 86_400_000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(23)
    n = 50_000
    return (rng.uniform(-75, -73, n), rng.uniform(40, 42, n),
            rng.integers(MS, MS + 14 * DAY, n))


def _brute(x, y, t, boxes, lo, hi):
    m = np.zeros(len(x), dtype=bool)
    for b in np.atleast_2d(np.asarray(boxes)):
        m |= ((x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3]))
    if lo is not None:
        m &= t >= lo
    if hi is not None:
        m &= t <= hi
    return np.flatnonzero(m)


def test_sharded_lean_build_query_oracle(data):
    x, y, t = data
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=1 << 13)
    for s in range(0, len(x), 20_000):   # chunks straddle generations
        sl = slice(s, min(s + 20_000, len(x)))
        idx.append(x[sl], y[sl], t[sl])
    assert idx.total() == len(x)
    assert len(idx.generations) >= 2     # rolled over at least once
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    got = idx.query([box], lo, hi)
    np.testing.assert_array_equal(got, _brute(x, y, t, [box], lo, hi))


def test_sharded_lean_query_many_fixed_dispatches(data):
    x, y, t = data
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=1 << 13)
    idx.append(x, y, t)
    windows = [([(-74.5, 40.5, -73.5, 41.5)], MS + 2 * DAY, MS + 9 * DAY),
               ([(-74.2, 40.1, -73.1, 41.2)], None, None),
               ([(-74.9, 41.5, -74.6, 41.9)], MS, MS + 4 * DAY)]
    before = idx.dispatch_count
    got = idx.query_many(windows)
    assert idx.dispatch_count - before == 2   # one probe + one scan
    for g, (bxs, lo, hi) in zip(got, windows):
        np.testing.assert_array_equal(g, _brute(x, y, t, bxs, lo, hi))


def test_sharded_lean_matches_single_chip(data):
    from geomesa_tpu.index.z3_lean import LeanZ3Index

    x, y, t = data
    sharded = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                                 generation_slots=1 << 13)
    single = LeanZ3Index(period="week", generation_slots=1 << 14,
                         payload_on_device=False)
    sharded.append(x, y, t)
    single.append(x, y, t)
    box = (-74.5, 40.5, -73.5, 41.5)
    np.testing.assert_array_equal(
        sharded.query([box], MS + DAY, MS + 10 * DAY),
        single.query([box], MS + DAY, MS + 10 * DAY))


def test_sharded_lean_big_scan_falls_back_per_generation(data):
    """Candidate totals past BATCH_SCAN_BUDGET route through
    per-generation dispatches sized by each generation's own total —
    never a silent truncation."""
    x, y, t = data
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=1 << 12)
    idx.append(x, y, t)
    assert len(idx.generations) >= 2
    idx.BATCH_SCAN_BUDGET = 1 << 10
    before = idx.dispatch_count
    got = idx.query([(-180, -90, 180, 90)], None, None)
    np.testing.assert_array_equal(got, np.arange(len(x)))
    assert idx.dispatch_count - before == 1 + len(idx.generations)


def test_sharded_lean_oversized_append_chunks(data):
    """One append larger than generation_slots x shards loops through
    multiple generation rollovers instead of crashing."""
    x, y, t = data
    mesh = device_mesh()
    slots = 1 << 9
    idx = ShardedLeanZ3Index(period="week", mesh=mesh,
                             generation_slots=slots)
    n = 3 * slots * int(mesh.devices.size)   # 3 generations' worth
    idx.append(x[:n], y[:n], t[:n])
    assert idx.total() == n
    assert len(idx.generations) >= 3
    box = (-74.5, 40.5, -73.5, 41.5)
    np.testing.assert_array_equal(
        idx.query([box], None, None),
        _brute(x[:n], y[:n], t[:n], [box], None, None))


def test_sharded_lean_empty_and_payload_provider(data):
    x, y, t = data
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=1 << 13)
    assert idx.query([(-75, 40, -73, 42)], None, None).size == 0
    idx.payload_provider = lambda: (x, y, t)
    idx.append(x, y, t)
    assert idx._payload == [] and idx._flat is None
    got = idx.query([(-74.5, 40.5, -73.5, 41.5)], None, None)
    np.testing.assert_array_equal(
        got, _brute(x, y, t, [(-74.5, 40.5, -73.5, 41.5)], None, None))


def test_sharded_lean_default_full_tier(data):
    """New generations carry per-shard payload by default: the exact
    mask runs fused on device and the tier stays ``full`` under the
    default budget."""
    x, y, t = data
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=1 << 13)
    idx.append(x, y, t)
    tiers = idx.tier_counts()
    assert tiers["full"] == len(idx.generations)
    assert idx.generations[0].x is not None


def test_sharded_lean_budget_demotes_payload_then_spills(data):
    """Tight per-shard budgets demote oldest-first — payload drops
    before key runs spill, the active generation never spills — and
    queries stay oracle-exact across the mixed-tier regime."""
    x, y, t = data
    slots = 1 << 10
    # keys sentinel (20 B/slot) + two keys generations: forces every
    # payload off and the oldest runs to host once 3+ generations exist
    budget = slots * 20 * 3
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=slots,
                             hbm_budget_bytes=budget)
    for s in range(0, len(x), 15_000):
        sl = slice(s, min(s + 15_000, len(x)))
        idx.append(x[sl], y[sl], t[sl])
    assert len(idx.generations) >= 4
    tiers = idx.tier_counts()
    assert tiers["host"] >= 1, tiers
    assert tiers["full"] == 0, tiers
    assert idx.generations[-1].tier != "host"
    assert idx.host_key_bytes() > 0
    # per-shard residency honors the budget
    assert idx._per_shard_resident() <= budget
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    np.testing.assert_array_equal(idx.query([box], lo, hi),
                                  _brute(x, y, t, [box], lo, hi))
    np.testing.assert_array_equal(idx.query([box], None, None),
                                  _brute(x, y, t, [box], None, None))


def test_sharded_lean_mixed_full_keys_oracle(data):
    """A budget that keeps the NEWEST generation full-fat while older
    payloads drop serves one query through the fused device-exact path
    AND the keys candidate path together (payload drops strictly
    oldest-first, so full + keys is the live mixed-tier regime)."""
    x, y, t = data
    slots = 1 << 12
    # sentinels (keys 20 + full 44 B/slot) + one full gen + two keys
    budget = slots * (20 + 44) + slots * 44 + 2 * slots * 20
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=slots,
                             hbm_budget_bytes=budget)
    for s in range(0, len(x), 10_000):
        sl = slice(s, min(s + 10_000, len(x)))
        idx.append(x[sl], y[sl], t[sl])
    tiers = idx.tier_counts()
    assert tiers["full"] >= 1 and tiers["keys"] >= 1, tiers
    assert idx.generations[-1].tier == "full"
    assert sum(tiers.values()) == len(idx.generations)
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    np.testing.assert_array_equal(idx.query([box], lo, hi),
                                  _brute(x, y, t, [box], lo, hi))
