"""Tiered stat-sketch push-down with sealed-generation sketch caching
(ISSUE 3).

Covers the acceptance surface: sketch merge algebra property tests
(``observe(a)+observe(b) == observe(a‖b)`` per stat type vs a numpy
oracle, associativity/commutativity, Frequency/TopK bounded-error
contracts under merge), the ``Count();MinMax;Histogram`` bbox+time
push-down on a multi-generation lean store returning oracle-identical
results with ZERO host candidate materialization (asserted via the
``lean.sketch.materialized_fallbacks`` counter), the ≥5x warm repeat
via the sealed-generation sketch cache on a ≥20-run store,
compaction-mints-new-generation cache invalidation, the per-tier
fallback contract (strings / selective bbox / GroupBy), Z3Histogram
cell push-down, the sharded variants, and the satellites
(device-kind-keyed pallas tuning, bench regression gate).

Named ``test_zz_*`` deliberately: this is a heavyweight lifecycle
suite (multi-generation store builds, device folds), so it runs at the
END of the alphabetical tier-1 order, after the fast unit suites (the
test_zz_lean_compaction convention).
"""

import json
import time

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.metrics import (
    LEAN_SKETCH_CACHE_HITS, LEAN_SKETCH_CACHE_MISSES, LEAN_SKETCH_SCANS,
    LEAN_STATS_MATERIALIZED, registry,
)
from geomesa_tpu.stats.sketch import (
    RunSketch, SketchFold, decode_attr_key, fold_attr_runs,
)
from geomesa_tpu.stats.stat import (
    CountStat, DescriptiveStats, EnumerationStat, Frequency, Histogram,
    MinMax, TopK, Z3HistogramStat, parse_stat,
)

MS = 1514764800000
DAY = 86_400_000
WORLD = "BBOX(geom,-180,-90,180,90)"
DURING = ("dtg DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
T_LO, T_HI = MS + 2 * DAY, MS + 9 * DAY


def _counter(name):
    return registry.counter(name).count


# -- sketch merge algebra: observe(a)+observe(b) == observe(a‖b) --------

class _DictBatch(dict):
    """Column dict with row semantics (len = rows, like FeatureBatch)."""

    def __len__(self):
        return len(next(iter(self.values())))


def _split_cases():
    rng = np.random.default_rng(17)
    n = 5_000
    cols = {
        "f": rng.normal(10.0, 4.0, n),
        "i": rng.integers(-50, 50, n).astype(np.int64),
        "s": rng.choice(np.array(["a", "b", "c", "dd"], object), n),
    }
    cut = n // 3
    return cols, cut


STAT_FACTORIES = [
    lambda: CountStat(),
    lambda: MinMax("f"),
    lambda: MinMax("i"),
    lambda: Histogram("f", 24, -5.0, 25.0),
    lambda: Frequency("i", 4, 128),
    lambda: Frequency("s", 4, 128),
    lambda: TopK("s", 3),
    lambda: EnumerationStat("i"),
    lambda: DescriptiveStats("f"),
]


@pytest.mark.parametrize("factory", STAT_FACTORIES,
                         ids=lambda f: type(f()).__name__ + "_" +
                         (getattr(f(), "attr", "") or "n"))
def test_observe_split_equals_observe_whole(factory):
    cols, cut = _split_cases()
    whole = factory()
    whole.observe(_DictBatch(cols))
    a, b = factory(), factory()
    a.observe(_DictBatch({k: v[:cut] for k, v in cols.items()}))
    b.observe(_DictBatch({k: v[cut:] for k, v in cols.items()}))
    merged = a + b
    if isinstance(whole, DescriptiveStats):
        assert merged.n == whole.n
        assert np.isclose(merged.mean, whole.mean)
        assert np.isclose(merged.variance, whole.variance)
        assert merged.min == whole.min and merged.max == whole.max
    elif isinstance(whole, TopK):
        # space-saving contract under merge: capacity bounded, and
        # reported counts never UNDER-estimate the true counts of the
        # values they report (bounded-error, not exact)
        assert len(merged.counters) <= merged._capacity
        u, c = np.unique(cols["s"].astype(str), return_counts=True)
        true = dict(zip(u.tolist(), c.tolist()))
        for v, cnt in merged.counters.items():
            assert cnt >= true.get(v, 0)
        # the true top-1 value must survive the merge at its true rank
        top1 = max(true, key=true.get)
        assert merged.topk(1)[0][0] == top1
    else:
        assert merged.to_json() == whole.to_json()


def test_merge_associative_commutative():
    cols, _ = _split_cases()
    thirds = np.array_split(np.arange(len(cols["f"])), 3)
    for factory in STAT_FACTORIES:
        parts = []
        for idx in thirds:
            s = factory()
            s.observe(_DictBatch({k: v[idx] for k, v in cols.items()}))
            parts.append(s)
        a, b, c = parts
        if isinstance(a, DescriptiveStats):
            # Welford merges associate/commute up to fp rounding
            x1, x2 = (a + b) + c, a + (b + c)
            y1, y2 = a + b, b + a
            for u, v in ((x1, x2), (y1, y2)):
                assert u.n == v.n
                assert np.isclose(u.mean, v.mean)
                assert np.isclose(u.m2, v.m2)
            continue
        assert ((a + b) + c).to_json() == (a + (b + c)).to_json()
        if not isinstance(a, TopK):   # space-saving eviction is
            assert (a + b).to_json() == (b + a).to_json()  # order-dep

    # Frequency bounded-error contract under merge: the count-min
    # estimate never under-counts, over-counts by at most the total
    f_parts = []
    for idx in thirds:
        f = Frequency("i", 4, 64)
        f.observe({"i": cols["i"][idx]})
        f_parts.append(f)
    merged = f_parts[0] + f_parts[1] + f_parts[2]
    u, c = np.unique(cols["i"], return_counts=True)
    for v, cnt in zip(u.tolist(), c.tolist()):
        est = merged.count(int(v))
        assert cnt <= est <= len(cols["i"])


def test_run_sketch_monoid_and_fold_split():
    rng = np.random.default_rng(3)
    k = np.sort(rng.integers(0, 1000, 900))
    s = rng.integers(0, 100, 900)
    fold = SketchFold(slo=10, shi=80, bins=8, hlo=0.0, hhi=1000.0,
                      depth=2, width=32, want_values=True)
    whole = fold_attr_runs([(k, s)], fold, "long")[0]
    a, b = fold_attr_runs([(k[:400], s[:400]), (k[400:], s[400:])],
                          fold, "long")
    merged = a + b
    assert merged.to_json() == whole.to_json()
    # associativity + identity
    c = fold_attr_runs([(k[:100], s[:100])], fold, "long")[0]
    assert ((a + b) + c).to_json() == (a + (b + c)).to_json()
    assert (RunSketch() + whole).to_json() == whole.to_json()
    # the fold matches the numpy oracle
    m = (s >= 10) & (s <= 80)
    assert whole.count == int(m.sum())
    assert decode_attr_key(whole.kmin, "long") == int(k[m].min())
    u, cnt = np.unique(k[m], return_counts=True)
    assert whole.values == dict(zip(u.tolist(), cnt.tolist()))


# -- the acceptance push-down on a multi-generation lean store ----------

#: enough sealed runs that the cold fold's work dwarfs per-call
#: overhead — the 5x warm assertion must not ride a ~15ms measurement
#: (cold folds N_RUNS runs; warm folds one 4-run padded bucket)
N_RUNS = 40
SLOTS = 1 << 12


@pytest.fixture(scope="module")
def lean_store():
    rng = np.random.default_rng(11)
    n = N_RUNS * SLOTS
    ds = TpuDataStore()
    ds.create_schema(
        "evt", "name:String:index=true,score:Double:index=true,"
               "k:Int:index=true,dtg:Date,*geom:Point;"
               "geomesa.index.profile=lean,"
               f"geomesa.lean.generation.slots={SLOTS},"
               "geomesa.lean.compaction.factor=0")
    data = {
        "x": rng.uniform(-75, -73, n), "y": rng.uniform(40, 42, n),
        "t": rng.integers(MS, MS + 14 * DAY, n),
        "score": rng.normal(50.0, 20.0, n),
        "k": rng.integers(0, 40, n),
        "name": rng.choice(np.array(["a", "b", "c"], object), n),
    }
    for lo in range(0, n, SLOTS):
        sl = slice(lo, lo + SLOTS)
        ds.write("evt", {"name": data["name"][sl],
                         "score": data["score"][sl],
                         "k": data["k"][sl], "dtg": data["t"][sl],
                         "geom": (data["x"][sl], data["y"][sl])})
    return ds, data


def test_pushdown_oracle_exact_zero_materialization(lean_store):
    ds, d = lean_store
    st = ds._store("evt")
    assert len(st._lean_attr_index("score").generations) >= 20
    m0 = _counter(LEAN_STATS_MATERIALIZED)
    s0 = _counter(LEAN_SKETCH_SCANS)
    got = ds.stats("evt", f"{WORLD} AND {DURING}",
                   "Count();MinMax(score);Histogram(score,20,0,100)")
    m = (d["t"] >= T_LO) & (d["t"] <= T_HI)
    col = d["score"][m]
    assert got.stats[0].count == int(m.sum())
    assert got.stats[1].min == col.min()
    assert got.stats[1].max == col.max()
    oracle = Histogram("score", 20, 0.0, 100.0)
    oracle.observe({"score": col})
    np.testing.assert_array_equal(got.stats[2].counts, oracle.counts)
    # ZERO host candidate materialization (the acceptance counter)
    assert _counter(LEAN_STATS_MATERIALIZED) == m0
    assert _counter(LEAN_SKETCH_SCANS) == s0 + 1


def test_pushdown_more_stat_kinds_oracle_exact(lean_store):
    ds, d = lean_store
    m = (d["t"] >= T_LO) & (d["t"] <= T_HI)
    m0 = _counter(LEAN_STATS_MATERIALIZED)
    got = ds.stats(
        "evt", f"{WORLD} AND {DURING}",
        "DescriptiveStats(score);Frequency(k,4,256);"
        "Enumeration(k);TopK(k)")
    desc, freq, enum, topk = got.stats
    col = d["score"][m]
    assert desc.n == int(m.sum())
    assert np.isclose(desc.mean, col.mean())
    assert np.isclose(desc.stddev, col.std(ddof=1))
    oracle_f = Frequency("k", 4, 256)
    oracle_f.observe({"k": d["k"][m]})
    np.testing.assert_array_equal(freq.table, oracle_f.table)
    u, c = np.unique(d["k"][m], return_counts=True)
    true = dict(zip(u.tolist(), c.tolist()))
    assert enum.counts == true
    for v, cnt in topk.topk():
        assert true[v] == cnt
    assert _counter(LEAN_STATS_MATERIALIZED) == m0


def test_warm_repeat_5x_via_sealed_generation_cache(lean_store):
    ds, _ = lean_store
    st = ds._store("evt")
    idx = st._lean_attr_index("score")
    assert len(idx.generations) >= 20
    spec = "Count();MinMax(score);Histogram(score,20,0,100)"
    q = f"{WORLD} AND {DURING}"
    ds.stats("evt", q, spec)       # compiles the cold (all-run) shape
    idx._sketch_cache.clear()
    h0 = _counter(LEAN_SKETCH_CACHE_HITS)
    t0 = time.perf_counter()
    cold = ds.stats("evt", q, spec)
    cold_s = time.perf_counter() - t0
    ds.stats("evt", q, spec)       # compiles the live-only shape
    # behavioral invariant first: every sealed run served from cache
    assert _counter(LEAN_SKETCH_CACHE_HITS) - h0 \
        >= len(idx.generations) - 1
    warm_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        warm = ds.stats("evt", q, spec)
        warm_times.append(time.perf_counter() - t0)
    warm_s = min(warm_times)   # cleanest run: scheduler noise only
    #                            ever slows a measurement down
    assert cold.to_json() == warm.to_json()
    assert cold_s >= 5.0 * warm_s, (cold_s, warm_s)


def test_fallbacks_materialize_and_are_counted(lean_store):
    ds, d = lean_store
    q = f"{WORLD} AND {DURING}"
    m = (d["t"] >= T_LO) & (d["t"] <= T_HI)
    # string-valued stat: prefix keys alias — must materialize (and
    # still be correct through the fallback)
    m0 = _counter(LEAN_STATS_MATERIALIZED)
    got = ds.stats("evt", q, "Enumeration(name)")
    u, c = np.unique(d["name"][m].astype(str), return_counts=True)
    assert got.counts == dict(zip(u.tolist(), c.tolist()))
    assert _counter(LEAN_STATS_MATERIALIZED) == m0 + 1
    # selective bbox: attr keys carry no geometry — fallback
    got = ds.stats("evt",
                   f"BBOX(geom,-74.5,40.5,-73.5,41.5) AND {DURING}",
                   "MinMax(score)")
    sel = (m & (d["x"] >= -74.5) & (d["x"] <= -73.5)
           & (d["y"] >= 40.5) & (d["y"] <= 41.5))
    assert got.min == d["score"][sel].min()
    assert _counter(LEAN_STATS_MATERIALIZED) == m0 + 2
    # GroupBy is never pushable
    ds.stats("evt", q, "GroupBy(k,Count())")
    assert _counter(LEAN_STATS_MATERIALIZED) == m0 + 3


def test_count_rides_attr_fold_for_selective_time(lean_store):
    """Pure Count() with a selective time window on a NON-full-tier
    store was previously unanswerable without materialization (the z3
    count gate needs t_open); it now rides any indexed numeric
    attribute's fold — sec is the raw dtg, exact at any window."""
    ds, d = lean_store
    m0 = _counter(LEAN_STATS_MATERIALIZED)
    got = ds.stats("evt", f"{WORLD} AND {DURING}", "Count()")
    m = (d["t"] >= T_LO) & (d["t"] <= T_HI)
    assert got.count == int(m.sum())
    assert _counter(LEAN_STATS_MATERIALIZED) == m0


def test_z3histogram_pushdown_whole_extent(lean_store):
    ds, d = lean_store
    m0 = _counter(LEAN_STATS_MATERIALIZED)
    got = ds.stats("evt", "INCLUDE", "Z3Histogram(geom,dtg,week,10)")
    oracle = Z3HistogramStat("geom", "dtg", "week", 10)

    class _B:
        def geom_xy(self, g):
            return d["x"], d["y"]

        def column(self, c):
            return d["t"]

    oracle.observe(_B())
    assert got.counts == oracle.counts
    assert _counter(LEAN_STATS_MATERIALIZED) == m0
    # selective TIME window: z3 cells are time-cell-granular — fallback
    ds.stats("evt", f"{WORLD} AND {DURING}",
             "Z3Histogram(geom,dtg,week,10)")
    assert _counter(LEAN_STATS_MATERIALIZED) == m0 + 1


def test_compaction_mints_new_generations_and_invalidates(lean_store):
    """Compaction folds sealed runs into fresh gen_ids; their cached
    sketch partials must drop (stale grids double-count) and the next
    scan must re-fold + re-cache with results unchanged."""
    ds, d = lean_store
    st = ds._store("evt")
    idx = st._lean_attr_index("k")
    fold = SketchFold(slo=T_LO, shi=T_HI, bins=8, hlo=0.0, hhi=40.0)
    before = idx.sketch_scan(fold)
    cache = idx._sketch_cache.spec_cache(fold)
    dead = [g.gen_id for g in idx.generations[:-1]]
    assert any(gid in cache for gid in dead)
    stats = idx.compact(factor=4)
    assert stats["merged_groups"] >= 1
    assert all(gid not in cache for gid in dead
               if gid not in {g.gen_id for g in idx.generations})
    after = idx.sketch_scan(fold)
    assert before.to_json() == after.to_json()
    m = (d["t"] >= T_LO) & (d["t"] <= T_HI)
    assert after.count == int(m.sum())
    np.testing.assert_array_equal(
        after.hist,
        np.bincount(np.clip((d["k"][m] * 8 // 40), 0, 7),
                    minlength=8))


def test_sketch_cache_lru_and_byte_ceiling():
    from geomesa_tpu.index.partial_cache import PartialCache
    pc = PartialCache(max_specs=2, max_bytes=10_000)
    a = pc.spec_cache("a")
    pc.add(a, 1, np.zeros(500, np.int64))   # 4000 B
    b = pc.spec_cache("b")
    pc.add(b, 1, np.zeros(500, np.int64))
    assert len(pc) == 2
    pc.spec_cache("c")                       # LRU evicts "a"
    assert len(pc) == 2 and "a" not in set(iter(pc))
    # ceiling: an insert that would bust max_bytes is refused
    c = pc.spec_cache("c")
    pc.add(c, 1, np.zeros(2_000, np.int64))  # 16000 B > ceiling
    assert 1 not in c
    pc.drop_generations([1])
    assert all(1 not in d for d in pc.values())


def test_xz2_facade_sketch_scan_counts():
    """The XZ facades expose the core's fold surface: a whole-window
    Count over the generational runs, sealed partials cached."""
    from geomesa_tpu.index.xz2_lean import LeanXZ2Index
    rng = np.random.default_rng(31)
    n = 3_000
    cx = rng.uniform(-170, 170, n)
    cy = rng.uniform(-80, 80, n)
    bb = np.column_stack([cx - .01, cy - .01, cx + .01, cy + .01])
    idx = LeanXZ2Index(generation_slots=1 << 10)
    for lo in range(0, n, 1 << 10):
        idx.append_bboxes(bb[lo:lo + (1 << 10)], base_gid=lo)
    part = idx.sketch_scan(SketchFold())
    assert part.count == n
    assert idx.sketch_scan(SketchFold()).count == n   # warm/cached


def test_xz2_store_attr_stats_pushdown():
    """Non-point lean stores (the xz2 kind) push attribute stats
    through the same pipeline — covered-extent spatial no-op + exact
    numeric folds."""
    rng = np.random.default_rng(33)
    n = 4_000
    ds = TpuDataStore()
    ds.create_schema("polys", "v:Int:index=true,*poly:Polygon;"
                              "geomesa.index.profile=lean")
    from geomesa_tpu.geometry.types import Polygon
    cx = rng.uniform(-170, 170, n)
    cy = rng.uniform(-80, 80, n)
    v = rng.integers(0, 25, n)
    polys = [Polygon([(a - .01, b - .01), (a + .01, b - .01),
                      (a + .01, b + .01), (a - .01, b + .01)])
             for a, b in zip(cx, cy)]
    ds.write("polys", {"v": v, "poly": polys})
    m0 = _counter(LEAN_STATS_MATERIALIZED)
    got = ds.stats("polys", "INCLUDE", "Count();MinMax(v)")
    assert got.stats[0].count == n
    assert got.stats[1].min == v.min() and got.stats[1].max == v.max()
    assert _counter(LEAN_STATS_MATERIALIZED) == m0


# -- sharded variants ---------------------------------------------------

def test_sharded_store_pushdown_oracle_exact():
    from geomesa_tpu.parallel import device_mesh
    rng = np.random.default_rng(21)
    n = 24_000
    ds = TpuDataStore(mesh=device_mesh())
    ds.create_schema(
        "mevt", "score:Double:index=true,dtg:Date,*geom:Point;"
                "geomesa.index.profile=lean,"
                "geomesa.lean.generation.slots=1024,"
                "geomesa.lean.compaction.factor=0")
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + 14 * DAY, n)
    sc = rng.normal(50.0, 20.0, n)
    step = 6000
    for lo in range(0, n, step):
        sl = slice(lo, lo + step)
        ds.write("mevt", {"score": sc[sl], "dtg": t[sl],
                          "geom": (x[sl], y[sl])})
    st = ds._store("mevt")
    assert len(st._lean_attr_index("score").generations) > 1
    m0 = _counter(LEAN_STATS_MATERIALIZED)
    got = ds.stats("mevt", f"{WORLD} AND {DURING}",
                   "Count();MinMax(score);Histogram(score,20,0,100)")
    m = (t >= T_LO) & (t <= T_HI)
    assert got.stats[0].count == int(m.sum())
    assert got.stats[1].min == sc[m].min()
    oracle = Histogram("score", 20, 0.0, 100.0)
    oracle.observe({"score": sc[m]})
    np.testing.assert_array_equal(got.stats[2].counts, oracle.counts)
    assert _counter(LEAN_STATS_MATERIALIZED) == m0
    # warm repeat serves sealed runs from the (global-partial) cache
    h0 = _counter(LEAN_SKETCH_CACHE_HITS)
    again = ds.stats("mevt", f"{WORLD} AND {DURING}",
                     "Count();MinMax(score);Histogram(score,20,0,100)")
    assert again.to_json() == got.to_json()
    assert _counter(LEAN_SKETCH_CACHE_HITS) > h0


# -- satellites ---------------------------------------------------------

def test_pallas_tuning_keyed_by_device_kind(tmp_path, monkeypatch):
    """A win measured on one chip must not gate kernels on another
    (ISSUE 3 satellite): records carry the device string; apply_tuning
    ignores foreign-device and legacy un-attributed entries."""
    from geomesa_tpu.ops import pallas_kernels as pk
    path = tmp_path / "tuning.json"
    monkeypatch.setattr(pk, "_tuning_path", lambda: str(path))
    gate = pk.GATES["density"]
    monkeypatch.setattr(gate, "disabled", False)
    monkeypatch.setattr(gate, "measured_win", None)
    pk.record_tuning({"density": 0.5})
    rec = json.loads(path.read_text())
    assert rec["density"] == {"win": 0.5, "device": pk.device_kind()}
    assert gate.disabled and gate.measured_win == 0.5
    # foreign-device entry: ignored entirely
    gate.disabled = False
    gate.measured_win = None
    pk.apply_tuning({"density": {"win": 0.1,
                                 "device": "TPU v999 imaginary"}})
    assert not gate.disabled and gate.measured_win is None
    # legacy bare-float entry (pre-device files): ignored, not crashed
    pk.apply_tuning({"density": 0.1, "hist1d": "garbage"})
    assert not gate.disabled
    # same-device re-record overwrites; foreign entries survive merge
    path.write_text(json.dumps(
        {"z2_scan": {"win": 0.2, "device": "TPU v999 imaginary"}}))
    pk.record_tuning({"density": 2.0})
    rec = json.loads(path.read_text())
    assert rec["z2_scan"]["device"] == "TPU v999 imaginary"
    assert rec["density"]["win"] == 2.0
    assert not pk.GATES["z2_scan"].disabled


def test_bench_regression_gate():
    import bench
    prior = {"value": 100_000, "extra": {
        "density_256x128_ms": 100.0, "knn25_4m_ms": 50.0,
        "bbox_scan_feats_per_sec": 1000, "scan_hits": 500,
        "compaction": {"warm_speedup": 10.0},
        "pallas_wins": {"density": 2.0}}}
    current = {"value": 100_000, "extra": {
        "density_256x128_ms": 150.0,      # 1.5x slower → flagged
        "knn25_4m_ms": 55.0,              # within tolerance
        "bbox_scan_feats_per_sec": 500,   # rate halved → flagged
        "scan_hits": 100,                 # not directional → ignored
        "compaction": {"warm_speedup": 4.0},   # speedup down → flagged
        "pallas_wins": {"density": 1.9}}}      # within tolerance
    regs = bench.compare_bench_records(current, prior)
    names = {r["metric"] for r in regs}
    assert names == {"extra.density_256x128_ms",
                     "extra.bbox_scan_feats_per_sec",
                     "extra.compaction.warm_speedup"}
    assert regs[0]["ratio"] == max(r["ratio"] for r in regs)
    assert all(r["ratio"] > 1.2 for r in regs)
    # identical records → clean
    assert bench.compare_bench_records(prior, prior) == []
    # metrics absent from the current record never flag
    assert bench.compare_bench_records({"extra": {}}, prior) == []


def test_bench_regression_gate_reads_latest_record(tmp_path,
                                                   monkeypatch):
    import bench
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"n": 3, "parsed": {"value": 100,
                            "extra": {"z2_or3_ms": 10.0}}}))
    (tmp_path / "BENCH_r05.json").write_text(json.dumps(
        {"n": 5, "parsed": {"value": 200,
                            "extra": {"z2_or3_ms": 40.0}}}))
    monkeypatch.setattr(bench.os.path, "dirname", lambda p: str(tmp_path))
    regs = bench._regression_gate(
        {"value": 200, "extra": {"z2_or3_ms": 100.0}})
    # compared against r05 (40ms), not r03 (10ms)
    assert len(regs) == 1 and regs[0]["ratio"] == 2.5


def test_store_scale_record_gains_stats_pushdown_fields():
    """The bench's 1B scale pointer must surface the stats-push-down
    stanza fields once a store-scale record carries them."""
    import bench
    rec = {"rows": 10 ** 9, "stats_pushdown_cold_ms": 4000.0,
           "stats_pushdown_warm_ms": 300.0,
           "stats_pushdown_speedup": 13.3,
           "stats_materialized_fallbacks": 0}
    full = {"metric": "m", "value": 1, "unit": "u", "vs_baseline": 1.0,
            "extra": {"bbox_time_scan_features_per_sec": 1,
                      "batched_windows_per_sec": 1,
                      "chunked_append_keys_per_sec": 1,
                      "density_256x128_ms": 1, "z2_or3_ms": 1,
                      "xz2_query_ms": 1, "knn25_4m_ms": 1,
                      "tube40_4m_ms": 1, "device": "d",
                      "scale": {"store_recorded": rec}}}
    compact = bench._compact_summary(full)
    assert compact["extra"]["store_1b"][
        "stats_pushdown_cold_ms"] == 4000.0
    assert compact["extra"]["store_1b"][
        "stats_materialized_fallbacks"] == 0
