"""Distributed stats scan + arrow reduce (VERDICT r1 item 5): collective
moments on the mesh vs brute force; per-shard monoid merges vs
single-pass observes; per-shard delta arrow streams vs a single writer."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.features import FeatureBatch
from geomesa_tpu.features.feature_type import parse_spec
from geomesa_tpu.parallel import (
    ShardedZ3Index, device_mesh, merged_arrow, merged_stats,
    sharded_stats_scan,
)

MS = 1514764800000
DAY = 86_400_000
N = 20_011


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(41)
    x = rng.uniform(-75.0, -73.0, N)
    y = rng.uniform(40.0, 42.0, N)
    t = rng.integers(MS, MS + 14 * DAY, N)
    v = rng.uniform(0, 100, N)
    return x, y, t, v


@pytest.fixture(scope="module")
def idx(data):
    x, y, t, _ = data
    return ShardedZ3Index.build(x, y, t, period="week", mesh=device_mesh())


def test_sharded_stats_scan_moments(idx, data):
    x, y, t, v = data
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS + DAY, MS + 8 * DAY
    mask = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
            & (t >= tlo) & (t <= thi))
    got = sharded_stats_scan(idx, [box], tlo, thi, values=v)
    assert got["count"] == mask.sum()
    assert got["sum"] == pytest.approx(v[mask].sum())
    assert got["sumsq"] == pytest.approx((v[mask] ** 2).sum())
    assert got["min"] == pytest.approx(v[mask].min())
    assert got["max"] == pytest.approx(v[mask].max())


def test_sharded_stats_scan_histogram(idx, data):
    x, y, t, v = data
    box = (-74.8, 40.2, -73.2, 41.8)
    tlo, thi = MS, MS + 14 * DAY
    mask = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
            & (t >= tlo) & (t <= thi))
    got = sharded_stats_scan(idx, [box], tlo, thi, values=v,
                             hist_bins=16, hist_range=(0.0, 100.0))
    w = 100.0 / 16
    b = np.clip((v[mask] / w).astype(int), 0, 15)
    want = np.bincount(b, minlength=16)
    np.testing.assert_array_equal(got["histogram"], want)
    assert got["histogram"].sum() == mask.sum()


def test_sharded_stats_scan_default_x(idx, data):
    """Without a value table the moments are over the x coordinate."""
    x, y, t, _ = data
    box = (-74.5, 40.5, -73.5, 41.5)
    got = sharded_stats_scan(idx, [box], None, None)
    mask = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3]))
    assert got["count"] == mask.sum()
    assert got["min"] == pytest.approx(x[mask].min())


# -- host-merge reducers -------------------------------------------------
@pytest.fixture(scope="module")
def batch(data):
    x, y, t, v = data
    rng = np.random.default_rng(43)
    sft = parse_spec(
        "obs", "name:String:index=true,score:Double,dtg:Date,*geom:Point")
    names = np.array(["a", "b", "c", "d", "e"], dtype=object)[
        rng.integers(0, 5, N)]
    return FeatureBatch.from_dict(sft, {
        "name": names, "score": v, "dtg": t, "geom": (x, y)})


@pytest.mark.parametrize("spec", [
    "Count()",
    "MinMax(score)",
    "Enumeration(name)",
    "Histogram(score,20,0,100)",
    "DescriptiveStats(score)",
])
def test_merged_stats_equal_single_pass(batch, spec):
    from geomesa_tpu.stats.stat import parse_stat
    single = parse_stat(spec)
    single.observe(batch)
    merged = merged_stats(batch, spec, 8)
    a, b = merged.to_json(), single.to_json()
    assert set(a) == set(b)
    for k, va in a.items():
        if isinstance(va, float):  # merge order perturbs float sums (m2)
            assert va == pytest.approx(b[k], rel=1e-12)
        else:
            assert va == b[k]


def test_merged_stats_topk_sane(batch):
    merged = merged_stats(batch, "TopK(name)", 8)
    top = dict(merged.topk(5))
    names = batch.column("name")
    true_counts = {n: int((names == n).sum()) for n in "abcde"}
    # every true top value is present with its exact count (space-saving
    # merge is exact when capacity exceeds cardinality)
    for n, c in true_counts.items():
        assert top[n] == c


def test_merged_arrow_equals_single_writer(batch):
    pytest.importorskip("pyarrow")
    merged = merged_arrow(batch, batch.sft, 8,
                          dictionary_fields=("name",), sort_field="score")
    assert merged.num_rows == len(batch)
    got = np.asarray(merged.column("score"))
    assert np.all(np.diff(got) >= 0)  # k-way merge preserved the sort
    # decoded name values match the batch (as multisets)
    names = sorted(merged.column("name").to_pylist())
    assert names == sorted(batch.column("name").tolist())


def test_mesh_store_query_arrow_matches_plain():
    pytest.importorskip("pyarrow")
    rng = np.random.default_rng(47)
    n = 5_003
    data = {
        "name": np.array(["a", "b", "c"], dtype=object)[
            rng.integers(0, 3, n)],
        "score": rng.uniform(0, 10, n),
        "dtg": rng.integers(MS, MS + 7 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
    }
    spec = "name:String:index=true,score:Double,dtg:Date,*geom:Point"
    plain = TpuDataStore()
    mesh = TpuDataStore(mesh=device_mesh())
    for ds in (plain, mesh):
        ds.create_schema("obs", spec)
        ds.write("obs", data)
    ecql = "BBOX(geom, -74.5, 40.5, -73.5, 41.5)"
    ta = plain.query_arrow_table("obs", ecql, dictionary_fields=("name",),
                           sort_field="score")
    tb = mesh.query_arrow_table("obs", ecql, dictionary_fields=("name",),
                          sort_field="score")
    assert ta.num_rows == tb.num_rows
    np.testing.assert_allclose(np.asarray(ta.column("score")),
                               np.asarray(tb.column("score")))
    assert (ta.column("name").to_pylist() == tb.column("name").to_pylist())


def test_mesh_store_stats_process_distributed():
    from geomesa_tpu.process import stats_process
    rng = np.random.default_rng(53)
    n = 4_001
    data = {
        "name": np.array(["a", "b"], dtype=object)[rng.integers(0, 2, n)],
        "score": rng.uniform(0, 10, n),
        "dtg": rng.integers(MS, MS + 7 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
    }
    spec = "name:String:index=true,score:Double,dtg:Date,*geom:Point"
    plain = TpuDataStore()
    mesh = TpuDataStore(mesh=device_mesh())
    for ds in (plain, mesh):
        ds.create_schema("obs", spec)
        ds.write("obs", data)
    ecql = "BBOX(geom, -74.5, 40.5, -73.5, 41.5)"
    a = stats_process(plain, "obs", ecql, "MinMax(score)")
    b = stats_process(mesh, "obs", ecql, "MinMax(score)")
    assert a.to_json() == b.to_json()


def test_shard_of_gids_residency_after_append():
    """Placement segments map every gid (build + append blocks) to the
    shard that actually holds it; the reduce protocols group by this."""
    rng = np.random.default_rng(61)
    n, m = 4_000, 900
    x = rng.uniform(-75, -73, n); y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + 7 * DAY, n)
    mesh = device_mesh()
    idx = ShardedZ3Index.build(x, y, t, period="week", mesh=mesh)
    idx.append(rng.uniform(-75, -73, m), rng.uniform(40, 42, m),
               rng.integers(MS, MS + 7 * DAY, m))
    n_shards = int(mesh.devices.size)
    sh = idx.shard_of_gids(np.arange(n + m))
    assert sh.min() >= 0 and sh.max() < n_shards
    # build rows: contiguous blocks of ceil(n/n_shards)
    per = -(-n // n_shards)
    np.testing.assert_array_equal(sh[:n], np.arange(n) // per)
    # append rows: blocks of the append's per-shard slot count
    counts = np.bincount(sh[n:], minlength=n_shards)
    assert counts.sum() == m and counts.max() <= -(-m // n_shards) * 2


def test_mesh_arrow_unsorted_row_order_parity():
    pytest.importorskip("pyarrow")
    """Without a sort field the merged arrow table restores the exact
    single-chip row order (positions order), even though streams are
    residency-grouped."""
    rng = np.random.default_rng(67)
    n = 3_511
    data = {
        "name": np.array(["a", "b", "c"], dtype=object)[
            rng.integers(0, 3, n)],
        "score": rng.uniform(0, 10, n),
        "dtg": rng.integers(MS, MS + 7 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
    }
    spec = "name:String:index=true,score:Double,dtg:Date,*geom:Point"
    plain = TpuDataStore()
    mesh = TpuDataStore(mesh=device_mesh())
    for ds in (plain, mesh):
        ds.create_schema("obs", spec)
        ds.write("obs", data)
        ds.write("obs", {k: (v if not isinstance(v, tuple)
                             else (v[0][:100], v[1][:100]))
                         if not isinstance(v, np.ndarray) else v[:100]
                         for k, v in data.items()})  # append block
    ecql = "BBOX(geom, -74.5, 40.5, -73.5, 41.5)"
    ta = plain.query_arrow_table("obs", ecql, dictionary_fields=("name",))
    tb = mesh.query_arrow_table("obs", ecql, dictionary_fields=("name",))
    assert ta.num_rows == tb.num_rows
    np.testing.assert_allclose(np.asarray(ta.column("score")),
                               np.asarray(tb.column("score")))
    assert ta.column("__fid__").to_pylist() == tb.column("__fid__").to_pylist()


def test_merged_sketches_under_adversarial_skew():
    """All heavy hitters on ONE shard (the merge-contract stress from
    VERDICT r2 weak #8): TopK/Frequency partials must survive the
    monoid merge with exact counts when capacity exceeds cardinality."""
    sft = parse_spec("skew", "name:String,score:Double,dtg:Date,*geom:Point")
    n = 8_000
    names = np.array(["rare%d" % (i % 50) for i in range(n)], dtype=object)
    names[:1000] = "heavy_a"   # heavy hitters land entirely in shard 0
    names[1000:1800] = "heavy_b"
    rng = np.random.default_rng(71)
    batch = FeatureBatch.from_dict(sft, {
        "name": names, "score": rng.uniform(0, 1, n),
        "dtg": rng.integers(MS, MS + DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n))})
    merged = merged_stats(batch, "TopK(name)", 8)
    top = dict(merged.topk(2))
    assert top["heavy_a"] == 1000 and top["heavy_b"] == 800
    freq = merged_stats(batch, "Frequency(name)", 8)
    # count-min never undercounts and is near-exact at this cardinality
    assert freq.count("heavy_a") >= 1000
    assert freq.count("heavy_b") >= 800
    assert freq.count("heavy_a") <= 1000 + n // 50


def test_sharded_frequency_scan_matches_host_sketch():
    """Device count-min sketch (per-shard hash+hist partials + psum)
    produces the SAME table as the host Frequency observe over the
    matching rows — bit-identical hashes, exact counts."""
    from geomesa_tpu.parallel import sharded_frequency_scan
    from geomesa_tpu.stats.stat import Frequency

    rng = np.random.default_rng(77)
    n = 30_000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + 7 * DAY, n)
    vals = rng.integers(0, 50, n).astype(np.float64)
    idx = ShardedZ3Index.build(x, y, t, period="week", mesh=device_mesh())
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + DAY, MS + 5 * DAY
    got = sharded_frequency_scan(idx, [box], lo, hi, vals)
    sel = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
           & (t >= lo) & (t <= hi))
    host = Frequency("v")
    sft = parse_spec("f", "v:Double,dtg:Date,*geom:Point")
    host.observe(FeatureBatch.from_dict(sft, {
        "v": vals[sel], "dtg": t[sel], "geom": (x[sel], y[sel])}))
    np.testing.assert_array_equal(got.table, host.table)
    # point estimates agree too
    for v in (0.0, 7.0, 23.0):
        assert got.count(v) == host.count(v)


def test_sharded_frequency_scan_strings_match_host_sketch():
    """STRING columns ride the device CMS too (VERDICT r4 #8): the
    host digests the UTF-8 bytes once and the device's seeded-splitmix
    path produces the identical table — Frequency's primary use in the
    reference is string attributes (utils/stats/Frequency.scala)."""
    from geomesa_tpu.parallel import sharded_frequency_scan
    from geomesa_tpu.stats.stat import Frequency

    rng = np.random.default_rng(85)
    n = 20_000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + 7 * DAY, n)
    # adversarial skew: heavy hitters + a unicode long-tail
    vals = np.array(["tail_%d" % (i % 200) for i in range(n)],
                    dtype=object)
    vals[:6000] = "heavy_α"
    vals[6000:9000] = "heavy_β"
    idx = ShardedZ3Index.build(x, y, t, period="week", mesh=device_mesh())
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + DAY, MS + 5 * DAY
    got = sharded_frequency_scan(idx, [box], lo, hi, vals)
    sel = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
           & (t >= lo) & (t <= hi))
    host = Frequency("v")
    sft = parse_spec("f", "v:String,dtg:Date,*geom:Point")
    host.observe(FeatureBatch.from_dict(sft, {
        "v": vals[sel], "dtg": t[sel], "geom": (x[sel], y[sel])}))
    np.testing.assert_array_equal(got.table, host.table)
    for v in ("heavy_α", "heavy_β", "tail_7", "missing"):
        assert got.count(v) == host.count(v)
    # count-min contract holds through the device path
    assert got.count("heavy_α") >= int((vals[sel] == "heavy_α").sum())


def test_stats_process_pushes_down_string_frequency():
    """Frequency(string) over a bbox+time filter takes the device CMS
    push-down on a mesh store and matches the single-chip store."""
    from geomesa_tpu.process import stats_process

    rng = np.random.default_rng(87)
    n = 8_000
    data = {
        "name": rng.choice(["alpha", "beta", "gamma"], n).astype(object),
        "dtg": rng.integers(MS, MS + 7 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
    }
    spec = "name:String,dtg:Date,*geom:Point"
    plain = TpuDataStore()
    mesh = TpuDataStore(mesh=device_mesh())
    for ds in (plain, mesh):
        ds.create_schema("obs", spec)
        ds.write("obs", data)
    ecql = ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
            "2018-01-02T00:00:00Z/2018-01-05T00:00:00Z")
    a = stats_process(plain, "obs", ecql, "Frequency(name)")
    b = stats_process(mesh, "obs", ecql, "Frequency(name)")
    np.testing.assert_array_equal(a.table, b.table)
    assert a.count("alpha") == b.count("alpha")


def test_stats_process_pushes_down_frequency():
    """Frequency(numeric) over a bbox+time filter takes the device CMS
    push-down on a mesh store and matches the host observe."""
    from geomesa_tpu.process import stats_process
    from geomesa_tpu.stats.stat import Frequency

    rng = np.random.default_rng(79)
    n = 8_000
    data = {
        "score": rng.integers(0, 30, n).astype(np.float64),
        "dtg": rng.integers(MS, MS + 7 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
    }
    spec = "score:Double,dtg:Date,*geom:Point"
    plain = TpuDataStore()
    mesh = TpuDataStore(mesh=device_mesh())
    for ds in (plain, mesh):
        ds.create_schema("obs", spec)
        ds.write("obs", data)
    ecql = ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
            "2018-01-02T00:00:00Z/2018-01-05T00:00:00Z")
    a = stats_process(plain, "obs", ecql, "Frequency(score)")
    b = stats_process(mesh, "obs", ecql, "Frequency(score)")
    np.testing.assert_array_equal(a.table, b.table)
    assert a.count(7.0) == b.count(7.0)


def test_sharded_frequency_exact_for_big_int64():
    """Integer columns travel as exact int64 (float64 would collapse
    values past 2^53 and diverge from the host hash)."""
    from geomesa_tpu.parallel import sharded_frequency_scan
    from geomesa_tpu.stats.stat import Frequency

    rng = np.random.default_rng(81)
    n = 4_000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + DAY, n)
    vals = (1 << 60) + rng.integers(0, 4, n)   # adjacent big ints
    idx = ShardedZ3Index.build(x, y, t, period="week", mesh=device_mesh())
    got = sharded_frequency_scan(idx, [(-75, 40, -73, 42)], None, None,
                                 vals)
    host = Frequency("v")
    sft = parse_spec("f", "v:Long,dtg:Date,*geom:Point")
    host.observe(FeatureBatch.from_dict(sft, {
        "v": vals, "dtg": t, "geom": (x, y)}))
    np.testing.assert_array_equal(got.table, host.table)


def test_sharded_frequency_nan_inf_matches_host():
    """Non-finite / out-of-range floats canonicalize to numpy's
    float64->int64 result before hashing, keeping the device table
    bit-identical to the host sketch even with NaN/inf values."""
    from geomesa_tpu.parallel import sharded_frequency_scan
    from geomesa_tpu.stats.stat import Frequency

    rng = np.random.default_rng(83)
    n = 2_000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + DAY, n)
    vals = rng.uniform(0, 10, n)
    vals[::7] = np.nan
    vals[1::11] = np.inf
    vals[2::13] = -np.inf
    vals[3::17] = 1e300
    idx = ShardedZ3Index.build(x, y, t, period="week", mesh=device_mesh())
    got = sharded_frequency_scan(idx, [(-75, 40, -73, 42)], None, None,
                                 vals)
    host = Frequency("v")
    sft = parse_spec("f", "v:Double,dtg:Date,*geom:Point")
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        host.observe(FeatureBatch.from_dict(sft, {
            "v": vals, "dtg": t, "geom": (x, y)}))
    np.testing.assert_array_equal(got.table, host.table)
