"""Sharded index/aggregation over the 8-device CPU mesh vs single-chip
oracles (the reference's multi-node-without-a-cluster strategy,
SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from geomesa_tpu.ops.density import density_grid
from geomesa_tpu.parallel import ShardedZ3Index, device_mesh

MS_2018 = 1514764800000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    n = 100_003  # deliberately not divisible by 8
    x = rng.uniform(-75.0, -73.0, n)
    y = rng.uniform(40.0, 42.0, n)
    t = rng.integers(MS_2018, MS_2018 + 14 * 86_400_000, n)
    return x, y, t


@pytest.fixture(scope="module")
def sharded(data):
    assert len(jax.devices()) == 8
    return ShardedZ3Index.build(*data, period="week", mesh=device_mesh())


def test_total(sharded, data):
    assert sharded.total() == len(data[0])


def test_range_count_covers_true_hits(sharded, data):
    x, y, t = data
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018 + 2 * 86_400_000, MS_2018 + 9 * 86_400_000
    count = sharded.range_count([box], tlo, thi)
    true = np.count_nonzero(
        (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
        & (t >= tlo) & (t <= thi))
    # candidate count is a superset of the true hits, bounded by total
    assert true <= count <= len(x)
    assert count < len(x)  # the index actually prunes


def test_density_matches_oracle(sharded, data):
    x, y, t = data
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018, MS_2018 + 7 * 86_400_000
    env = box
    W = H = 64
    grid = sharded.density([box], tlo, thi, env, W, H)
    assert grid.shape == (H, W)
    mask = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
            & (t >= tlo) & (t <= thi))
    assert grid.sum() == pytest.approx(mask.sum())
    # oracle histogram
    dx = (env[2] - env[0]) / W
    dy = (env[3] - env[1]) / H
    ix = np.clip(((x - env[0]) / dx).astype(int), 0, W - 1)
    iy = np.clip(((y - env[1]) / dy).astype(int), 0, H - 1)
    oracle = np.zeros((H, W))
    np.add.at(oracle, (iy[mask], ix[mask]), 1.0)
    np.testing.assert_allclose(grid, oracle)


def test_density_weighted(sharded, data):
    x, y, t = data
    box = (-74.5, 40.5, -73.5, 41.5)
    w_host = np.arange(len(x), dtype=np.float64) % 7
    grid = sharded.density([box], MS_2018, MS_2018 + 7 * 86_400_000, box,
                           32, 32, weights=w_host)
    mask = ((x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
            & (t >= MS_2018) & (t <= MS_2018 + 7 * 86_400_000))
    assert grid.sum() == pytest.approx(w_host[mask].sum())


def test_single_device_density_kernel(data):
    import jax.numpy as jnp
    x, y, t = data
    env = (-75.0, 40.0, -73.0, 42.0)
    mask = np.ones(len(x), dtype=bool)
    grid = np.asarray(density_grid(
        jnp.asarray(x), jnp.asarray(y), jnp.ones(len(x)),
        jnp.asarray(mask), env, 128, 128))
    assert grid.sum() == pytest.approx(len(x))


def test_sharded_query_exact(sharded, data):
    """Full distributed query: per-shard packed scans, exact global hits."""
    x, y, t = data
    idx = sharded
    MS = MS_2018
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS + 86_400_000, MS + 6 * 86_400_000
    hits = idx.query([box], tlo, thi)
    brute = np.flatnonzero(
        (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
        & (t >= tlo) & (t <= thi))
    assert np.array_equal(np.sort(hits), np.sort(brute))
    # tiny capacity forces the overflow-retry path
    hits2 = idx.query([box], tlo, thi, capacity=8)
    assert np.array_equal(np.sort(hits2), np.sort(brute))


def test_ring_range_counts_match_replicated(sharded):
    """Ring-rotated sharded-range counts must equal the replicated-plan
    psum count in aggregate, and per-range sums must be consistent."""
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018 + 2 * 86_400_000, MS_2018 + 9 * 86_400_000
    per_range = sharded.range_counts_ring([box], tlo, thi)
    total = sharded.range_count([box], tlo, thi)
    assert per_range.sum() == total
    assert (per_range >= 0).all()
    # range count not divisible by mesh size exercises the padding path
    assert len(per_range) >= 1


def test_ring_range_counts_oracle(sharded, data):
    """Per-range counts vs a host brute-force count over the same plan."""
    from geomesa_tpu.index.z3 import plan_z3_query
    from geomesa_tpu.curve import TimePeriod, to_binned_time
    from geomesa_tpu.curve.sfc import z3_sfc

    x, y, t = data
    box = (-74.3, 40.2, -73.6, 41.7)
    tlo, thi = MS_2018 + 86_400_000, MS_2018 + 12 * 86_400_000
    plan = plan_z3_query([box], tlo, thi, TimePeriod.WEEK, 512)
    per_range = sharded.range_counts_ring([box], tlo, thi, max_ranges=512)
    assert len(per_range) == plan.num_ranges

    sfc = z3_sfc(TimePeriod.WEEK)
    bins, offs = to_binned_time(np.asarray(t, np.int64), TimePeriod.WEEK)
    z = np.asarray(sfc.index(x, y, offs.astype(np.float64), xp=np))
    want = np.zeros(plan.num_ranges, dtype=np.int64)
    for i in range(plan.num_ranges):
        want[i] = np.count_nonzero(
            (bins == plan.rbin[i]) & (z >= plan.rzlo[i]) & (z <= plan.rzhi[i]))
    np.testing.assert_array_equal(per_range, want)


def test_build_multihost_matches_build(data):
    """Single-process run of the multi-controller build path
    (make_array_from_process_local_data) must produce an identical
    index + query results to the scatter build."""
    from geomesa_tpu.parallel import global_device_mesh
    from geomesa_tpu.parallel.scan import ShardedZ3Index

    x, y, t = data
    mesh = global_device_mesh()
    a = ShardedZ3Index.build(x, y, t, period="week", mesh=mesh)
    b = ShardedZ3Index.build_multihost(x, y, t, period="week", mesh=mesh)
    assert b.total() == a.total() == len(x)
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018 + 2 * 86_400_000, MS_2018 + 9 * 86_400_000
    np.testing.assert_array_equal(
        np.sort(a.query([box], tlo, thi)), np.sort(b.query([box], tlo, thi)))
    assert a.range_count([box], tlo, thi) == b.range_count([box], tlo, thi)


def test_unrank_position_single_process(sharded):
    """Single-process layout: positions are original row indices."""
    assert sharded.unrank_position(0) == (0, 0)
    assert sharded.unrank_position(12345) == (0, 12345)


def test_unrank_position_multihost_coding():
    """Multihost gids code (process, local_row) in the high bits."""
    from geomesa_tpu.parallel.scan import GID_PROC_SHIFT
    gid = (np.int64(3) << GID_PROC_SHIFT) | 4321
    assert ShardedZ3Index.unrank_position(gid) == (3, 4321)


def test_sharded_query_many_matches_per_window(sharded, data):
    """Collective batched windows == per-window collective queries."""
    x, y, t = data
    windows = [
        ([(-74.5, 40.5, -73.5, 41.5)],
         MS_2018 + 86_400_000, MS_2018 + 6 * 86_400_000),
        ([(-74.9, 40.1, -74.4, 40.9), (-73.9, 41.1, -73.2, 41.9)],
         MS_2018, MS_2018 + 3 * 86_400_000),
        ([(-74.2, 40.8, -74.0, 41.0)],
         MS_2018 + 8 * 86_400_000, MS_2018 + 13 * 86_400_000),
    ]
    batched = sharded.query_many(windows)
    assert len(batched) == len(windows)
    for got, (boxes, lo, hi) in zip(batched, windows):
        brute = np.flatnonzero(
            np.any([(x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
                    for b in boxes], axis=0)
            & (t >= lo) & (t <= hi))
        np.testing.assert_array_equal(np.sort(got), brute)


def test_sharded_append_exact(data):
    """Distributed append: interleaved appends/queries keep hit sets
    oracle-equal, per-shard capacity grows, one steady-state compile."""
    x, y, t = data
    n0 = 40_001
    idx = ShardedZ3Index.build(
        x[:n0], y[:n0], t[:n0], period="week", mesh=device_mesh())
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018 + 86_400_000, MS_2018 + 6 * 86_400_000

    def oracle(n):
        return np.flatnonzero(
            (x[:n] >= box[0]) & (x[:n] <= box[2])
            & (y[:n] >= box[1]) & (y[:n] <= box[3])
            & (t[:n] >= tlo) & (t[:n] <= thi))

    np.testing.assert_array_equal(idx.query([box], tlo, thi), oracle(n0))
    # append in three uneven slices, querying between appends
    cuts = [n0, 55_000, 55_003, 90_000, len(x)]
    for a, b in zip(cuts[:-1], cuts[1:]):
        idx.append(x[a:b], y[a:b], t[a:b])
        assert idx.total() == b
        np.testing.assert_array_equal(idx.query([box], tlo, thi), oracle(b))
    # density over the appended index still matches
    grid = idx.density([box], tlo, thi, box, 32, 32)
    assert grid.sum() == pytest.approx(len(oracle(len(x))))


def test_sharded_append_empty_and_fresh_rows(sharded):
    """Appending zero rows is a no-op; appended row timestamps extend the
    data time extent used for open-bound clamping."""
    rng = np.random.default_rng(3)
    idx = ShardedZ3Index.build(
        rng.uniform(-75, -73, 1000), rng.uniform(40, 42, 1000),
        rng.integers(MS_2018, MS_2018 + 86_400_000, 1000),
        period="week", mesh=device_mesh())
    n = idx.total()
    idx.append([], [], [])
    assert idx.total() == n
    t_new = MS_2018 + 20 * 86_400_000
    idx.append([-74.0], [41.0], [t_new])
    assert idx.total() == n + 1
    assert idx.t_max_ms == t_new
    hits = idx.query([(-74.1, 40.9, -73.9, 41.1)], None, None)
    assert n in hits  # the appended row (gid == n) is found


def test_fetch_global_allgather_path(data, monkeypatch):
    """Simulated multi-process run: with process_count patched to 2, the
    collective fetch path (_fetch_global → multihost_utils.
    process_allgather) executes in CI and query results stay exact
    (VERDICT r1 weak #8)."""
    from jax.experimental import multihost_utils
    from geomesa_tpu.parallel import scan as scan_mod

    x, y, t = data
    idx = ShardedZ3Index.build(x, y, t, period="week", mesh=device_mesh())
    calls = {"n": 0}
    real_allgather = multihost_utils.process_allgather

    def fake_allgather(a, tiled=False):
        calls["n"] += 1
        # every shard is addressable in CI, so the gather of the global
        # value is the value itself; the REAL call would hit a collective
        # barrier waiting for process 1, so emulate its result instead
        return np.asarray(a)

    monkeypatch.setattr(scan_mod.jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather", fake_allgather)
    try:
        box = (-74.5, 40.5, -73.5, 41.5)
        tlo, thi = MS_2018 + 86_400_000, MS_2018 + 6 * 86_400_000
        hits = idx.query([box], tlo, thi)
        ring = idx.range_counts_ring([box], tlo, thi)
    finally:
        monkeypatch.setattr(multihost_utils, "process_allgather",
                            real_allgather)
    assert calls["n"] >= 2  # packed scan + totals, ring counts
    brute = np.flatnonzero(
        (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
        & (t >= tlo) & (t <= thi))
    np.testing.assert_array_equal(np.sort(hits), brute)
    assert ring.sum() >= len(brute)


def test_agreed_padded_local_uneven_processes(monkeypatch):
    """Non-uniform per-process row counts agree on max-count padding
    (the multihost block layout never silently truncates)."""
    import jax
    from jax.experimental import multihost_utils
    from geomesa_tpu.parallel import multihost as mh

    monkeypatch.setattr(jax, "process_count", lambda: 3)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda a: np.asarray([5, 11, 2], dtype=np.int64))
    # every process pads to ceil(11/4)*4 = 12 local rows over 4 shards
    assert mh._agreed_padded_local(5, 4) == 12
    assert mh._agreed_padded_local(11, 4) == 12
    # and to the exact multiple when counts align
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda a: np.asarray([8, 8], dtype=np.int64))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert mh._agreed_padded_local(8, 4) == 8


def test_multihost_gid_coding_per_process(monkeypatch):
    """build_multihost stamps gids with the producing process index, so
    results identify (process, local_row) without uniform-block math."""
    import jax
    from geomesa_tpu.parallel import global_device_mesh
    from geomesa_tpu.parallel.scan import GID_PROC_SHIFT

    rng = np.random.default_rng(8)
    n = 256
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS_2018, MS_2018 + 7 * 86_400_000, n)
    monkeypatch.setattr(jax, "process_index", lambda: 2)
    idx = ShardedZ3Index.build_multihost(
        x, y, t, period="week", mesh=global_device_mesh())
    hits = idx.query([(-74.5, 40.5, -73.5, 41.5)], None, None)
    assert len(hits)
    procs = hits >> GID_PROC_SHIFT
    assert (procs == 2).all()  # every gid carries the producing process
    rows = hits & ((np.int64(1) << GID_PROC_SHIFT) - 1)
    brute = np.flatnonzero(
        (x >= -74.5) & (x <= -73.5) & (y >= 40.5) & (y <= 41.5))
    np.testing.assert_array_equal(np.sort(rows), brute)


def test_sharded_two_phase_read(data):
    """Large-capacity collective queries take the two-phase compacted
    read (hits-sized head transfer) and stay exact; capacity decays."""
    from geomesa_tpu.parallel import scan as scan_mod
    x, y, t = data
    idx = ShardedZ3Index.build(x, y, t, period="week", mesh=device_mesh())
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018 + 86_400_000, MS_2018 + 6 * 86_400_000
    brute = np.flatnonzero(
        (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
        & (t >= tlo) & (t <= thi))
    big = scan_mod.SHARDED_TWO_PHASE_MIN_CAPACITY
    hits = idx.query([box], tlo, thi, capacity=big)
    np.testing.assert_array_equal(np.sort(hits), brute)
    # the sticky capacity decayed toward the observed candidate volume
    assert idx._capacity < big
    # and the follow-up (single-phase) query still agrees
    np.testing.assert_array_equal(np.sort(idx.query([box], tlo, thi)), brute)


def test_ring_query_matches_replicated(sharded, data):
    """Ring-parallel full query (plan sharded + rotated, data
    stationary) returns the exact hit set of the replicated-plan scan."""
    x, y, t = data
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018 + 86_400_000, MS_2018 + 6 * 86_400_000
    ring = sharded.query_ring([box], tlo, thi)
    rep = sharded.query([box], tlo, thi)
    np.testing.assert_array_equal(ring, np.sort(rep))
    # overflow-retry path with a tiny per-hop capacity
    ring2 = sharded.query_ring([box], tlo, thi, capacity=64)
    np.testing.assert_array_equal(ring2, np.sort(rep))
    # range count not divisible by mesh size exercises plan padding
    ring3 = sharded.query_ring([box], tlo, thi, max_ranges=509)
    np.testing.assert_array_equal(ring3, np.sort(rep))


def test_huge_plan_routes_through_ring(sharded, data, monkeypatch):
    """Plans above the per-device replication threshold take the ring
    path automatically and stay exact."""
    calls = {"ring": 0}
    orig = ShardedZ3Index._query_ring_plan

    def spy(self, plan, capacity=None):
        calls["ring"] += 1
        return orig(self, plan, capacity)

    monkeypatch.setattr(ShardedZ3Index, "_query_ring_plan", spy)
    monkeypatch.setattr(ShardedZ3Index, "RING_MIN_RANGES_PER_DEVICE", 8)
    x, y, t = data
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018 + 86_400_000, MS_2018 + 6 * 86_400_000
    hits = sharded.query([box], tlo, thi, max_ranges=2000)
    assert calls["ring"] == 1
    brute = np.flatnonzero(
        (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
        & (t >= tlo) & (t <= thi))
    np.testing.assert_array_equal(np.sort(hits), brute)


def test_ring_query_probe_avoids_retry(sharded, data, monkeypatch):
    """With no explicit capacity the ring query probes totals first and
    sizes the buffer so the full ring program compiles exactly once —
    no capacity-walk recompiles (VERDICT r2 weak #7)."""
    from geomesa_tpu.parallel import scan as scan_mod
    compiles = []
    orig = scan_mod._z3_ring_hop_program

    def spy(mesh, capacity):
        compiles.append(capacity)
        return orig(mesh, capacity)

    monkeypatch.setattr(scan_mod, "_z3_ring_hop_program", spy)
    x, y, t = data
    box = (-74.5, 40.5, -73.5, 41.5)
    tlo, thi = MS_2018 + 86_400_000, MS_2018 + 6 * 86_400_000
    ring = sharded.query_ring([box], tlo, thi)
    rep = sharded.query([box], tlo, thi)
    np.testing.assert_array_equal(ring, np.sort(rep))
    assert len(compiles) == 1, compiles
