"""Resilience layer (ISSUE 16): deadline/partial semantics, admission
control, degraded execution, the circuit breaker, the deterministic
fault-injection chaos matrix, eager interceptor wiring, and the
bounded web serving path.

Named ``zz`` so the chaos runs land late in the suite ordering, after
the correctness suites have exercised the clean paths.
"""

from __future__ import annotations

import gc
import sys
import types

import numpy as np
import pytest

from geomesa_tpu import config
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.resilience import (
    Backpressure, CancelScope, CircuitBreaker, FAULT_POINTS,
    FaultInjected, QueryTimeout, admission_gate, breaker, check_cancel,
    classify_device_failure, deadline_scope, fault_point,
)

MS_2018 = 1_514_764_800_000
DAY = 86_400_000
BBOX = "BBOX(geom,-76,39,-73,42)"


def _clear(*names):
    for n in names:
        config.clear_property(n)


@pytest.fixture(autouse=True)
def _clean_resilience_config():
    """Every test starts and ends with the layer fully disarmed."""
    names = ("geomesa.resilience.fault.points",
             "geomesa.resilience.fault.seed",
             "geomesa.resilience.admission.max.concurrent",
             "geomesa.resilience.admission.queue.ms",
             "geomesa.resilience.hbm.headroom",
             "geomesa.resilience.retry.max",
             "geomesa.resilience.breaker.threshold",
             "geomesa.resilience.breaker.cooldown.s")
    _clear(*names)
    breaker.reset()
    # streams abandoned by OTHER suites release their admission token
    # via ArrowStream.__del__ — collect them, then zero the gate so the
    # inflight assertions here are order-independent
    gc.collect()
    admission_gate.reset()
    yield
    _clear(*names)
    breaker.reset()


def _mk_store(name: str, n: int = 3000, slots: int = 256) -> TpuDataStore:
    ds = TpuDataStore()
    ds.create_schema(
        name,
        "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
        f"geomesa.lean.generation.slots={slots},"
        "geomesa.lean.compaction.factor=0")
    rng = np.random.default_rng(11)
    ds.write(name, {
        "dtg": rng.integers(MS_2018, MS_2018 + 13 * DAY, n),
        "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n))})
    return ds


# -- deadline / cancellation units -----------------------------------------

def test_expired_deadline_raises_query_timeout():
    ds = _mk_store("rz_t1")
    with pytest.raises(QueryTimeout):
        ds.query_result("rz_t1", BBOX, timeout_ms=1e-6)


def test_expired_deadline_partial_returns_flagged_result():
    ds = _mk_store("rz_t2")
    res = ds.query_result("rz_t2", BBOX, timeout_ms=1e-6,
                          partial_results=True)
    assert res.timed_out is True
    # partial means "exact over what WAS scanned" — with an
    # already-expired deadline that is nothing
    assert len(res.positions) == 0


def test_no_timeout_is_unaffected():
    ds = _mk_store("rz_t3", n=500)
    res = ds.query_result("rz_t3", BBOX)
    assert res.timed_out is False
    assert len(res.positions) == 500


def test_generous_timeout_returns_full_result():
    ds = _mk_store("rz_t4", n=500)
    res = ds.query_result("rz_t4", BBOX, timeout_ms=60_000.0)
    assert res.timed_out is False
    assert len(res.positions) == 500


def test_query_windows_timeout():
    ds = _mk_store("rz_t5")
    with pytest.raises(QueryTimeout):
        ds.query_windows(
            "rz_t5",
            [([(-76.0, 39.0, -73.0, 42.0)], MS_2018, MS_2018 + 13 * DAY)],
            timeout_ms=1e-6)
    outs = ds.query_windows(
        "rz_t5",
        [([(-76.0, 39.0, -73.0, 42.0)], MS_2018, MS_2018 + 13 * DAY)],
        timeout_ms=1e-6, partial_results=True)
    assert len(outs) == 1 and len(outs[0]) == 0


def test_cancel_scope_poll_latches_once():
    sc = CancelScope(timeout_ms=1e-6, partial=True)
    assert sc.poll() is True
    assert sc.timed_out is True
    assert sc.poll() is True          # latched, idempotent
    with deadline_scope(scope=sc):
        assert check_cancel("unit") is True   # partial → True, no raise


def test_check_cancel_no_scope_is_free():
    assert check_cancel("unit") is False


def test_expired_arrow_stream_is_wellformed_eos():
    pa = pytest.importorskip("pyarrow")
    ds = _mk_store("rz_t6", n=400)
    stream = ds.query_arrow("rz_t6", BBOX, chunk_rows=64,
                            timeout_ms=1e-6, partial_results=True)
    blob = stream.to_ipc_bytes()
    # a stock reader opens the truncated stream cleanly: schema header
    # + end-of-stream, zero rows delivered
    table = pa.ipc.open_stream(blob).read_all()
    assert table.num_rows == 0
    gc.collect()
    assert admission_gate.inflight == 0


# -- admission control ------------------------------------------------------

def test_backpressure_sheds_when_slots_held():
    ds = _mk_store("rz_a1", n=200)
    config.set_property("geomesa.resilience.admission.max.concurrent", 1)
    config.set_property("geomesa.resilience.admission.queue.ms", 5.0)
    tok = admission_gate.acquire("rz_a1")
    try:
        with pytest.raises(Backpressure) as ei:
            ds.query_result("rz_a1", BBOX)
        assert ei.value.retry_after_s > 0
    finally:
        tok.release()
    # slot free again: the same query admits and runs
    assert len(ds.query_result("rz_a1", BBOX).positions) == 200
    assert admission_gate.inflight == 0


def test_hbm_budget_sheds():
    from geomesa_tpu.metrics import registry as metrics
    ds = _mk_store("rz_a2", n=100)
    g = metrics.gauge("storage.total.device_bytes")
    prior = g.value
    config.set_property("geomesa.resilience.hbm.headroom", 1024)
    config.set_property("geomesa.resilience.admission.queue.ms", 5.0)
    g.set(1 << 30)
    try:
        with pytest.raises(Backpressure):
            ds.query_result("rz_a2", BBOX)
        # back under budget → admitted again (prior may itself exceed
        # the tiny test headroom when earlier suites published real
        # storage bytes, so prove recovery at 0, then restore)
        g.set(0)
        assert len(ds.query_result("rz_a2", BBOX).positions) == 100
    finally:
        g.set(prior)


def test_admission_token_release_is_idempotent():
    tok = admission_gate.acquire("unit")
    assert admission_gate.inflight >= 1
    tok.release()
    tok.release()
    assert admission_gate.inflight == 0


def test_no_leaked_tokens_after_100_cycles():
    ds = _mk_store("rz_a3", n=300)
    config.set_property("geomesa.resilience.admission.max.concurrent", 4)
    for i in range(100):
        if i % 10 == 3:
            # streamed drains release from the generator's finally
            for _ in ds.query_arrow("rz_a3", BBOX, chunk_rows=128):
                pass
        elif i % 10 == 7:
            with pytest.raises(QueryTimeout):
                ds.query_result("rz_a3", BBOX, timeout_ms=1e-6)
        else:
            ds.query_result("rz_a3", BBOX)
    gc.collect()
    assert admission_gate.inflight == 0


# -- degraded execution / breaker -------------------------------------------

def test_classifier():
    assert classify_device_failure(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory while trying "
                     "to allocate")) == "transient"
    assert classify_device_failure(RuntimeError("XLA hlo broke")) == "poison"
    assert classify_device_failure(ValueError("whatever")) == "poison"


def test_circuit_breaker_trip_and_halfopen():
    config.set_property("geomesa.resilience.breaker.threshold", 2)
    config.set_property("geomesa.resilience.breaker.cooldown.s", 0.0)
    cb = CircuitBreaker()
    key = ("unit", 1)
    assert cb.allows(key)
    cb.record_failure(key)
    assert cb.allows(key)
    cb.record_failure(key)
    # cooldown 0 → instantly half-open: one probe dispatch allowed,
    # and a success fully closes the circuit
    assert cb.allows(key)
    cb.record_success(key)
    cb.record_failure(key)
    assert cb.allows(key)


def test_degraded_query_stays_exact():
    """The degraded-mode contract: a transient device failure demotes
    the generation to host and the query still returns exactly the
    un-degraded result."""
    from geomesa_tpu.metrics import RESILIENCE_DEGRADED, registry as metrics
    ds = _mk_store("rz_d1", n=1500, slots=256)
    baseline = sorted(ds.query_result("rz_d1", BBOX).positions.tolist())
    before = metrics.counter(RESILIENCE_DEGRADED).count
    config.set_property("geomesa.resilience.fault.points",
                        "device.dispatch:1=oom")
    degraded = sorted(ds.query_result("rz_d1", BBOX).positions.tolist())
    assert degraded == baseline
    assert metrics.counter(RESILIENCE_DEGRADED).count > before
    # and the store keeps serving exactly after disarming
    config.clear_property("geomesa.resilience.fault.points")
    assert sorted(ds.query_result("rz_d1", BBOX).positions.tolist()) \
        == baseline


def test_poison_dispatch_propagates():
    ds = _mk_store("rz_d2", n=800, slots=256)
    config.set_property("geomesa.resilience.fault.points",
                        "device.dispatch:1=error")
    with pytest.raises(FaultInjected):
        ds.query_result("rz_d2", BBOX)
    config.clear_property("geomesa.resilience.fault.points")
    assert len(ds.query_result("rz_d2", BBOX).positions) == 800


# -- fault-injection harness ------------------------------------------------

def test_unknown_fault_point_spec_rejected():
    config.set_property("geomesa.resilience.fault.points", "no.such.point")
    with pytest.raises(ValueError, match="no.such.point"):
        fault_point("ingest.append")


def test_fault_trigger_fires_on_exact_nth_hit():
    from geomesa_tpu.resilience.faults import FaultRegistry
    config.set_property("geomesa.resilience.fault.points",
                        "arrow.flush:2=error")
    reg = FaultRegistry()
    reg.maybe_fail("arrow.flush")           # hit 1: armed for hit 2
    with pytest.raises(FaultInjected):
        reg.maybe_fail("arrow.flush")       # hit 2 fires
    reg.maybe_fail("arrow.flush")           # hit 3: past the trigger


def test_probabilistic_fault_is_seed_deterministic():
    from geomesa_tpu.resilience.faults import FaultRegistry

    def fire_pattern():
        config.set_property("geomesa.resilience.fault.points",
                            "host.spill:0.5=error")
        config.set_property("geomesa.resilience.fault.seed", 42)
        reg = FaultRegistry()
        out = []
        for _ in range(32):
            try:
                reg.maybe_fail("host.spill")
                out.append(False)
            except FaultInjected:
                out.append(True)
        return out

    a, b = fire_pattern(), fire_pattern()
    assert a == b and any(a) and not all(a)


# -- the chaos matrix: fault point x operation ------------------------------

def test_chaos_ingest_append_loses_only_that_slice():
    ds = _mk_store("rz_c1", n=500)
    config.set_property("geomesa.resilience.fault.points",
                        "ingest.append:1=error")
    with pytest.raises(FaultInjected):
        ds.write("rz_c1", {
            "dtg": np.full(50, MS_2018, dtype=np.int64),
            "geom": (np.full(50, -74.5), np.full(50, 40.5))})
    # atomic slice loss: the failed write added nothing
    assert len(ds.query_result("rz_c1", BBOX).positions) == 500
    config.clear_property("geomesa.resilience.fault.points")
    ds.write("rz_c1", {
        "dtg": np.full(50, MS_2018, dtype=np.int64),
        "geom": (np.full(50, -74.5), np.full(50, 40.5))})
    assert len(ds.query_result("rz_c1", BBOX).positions) == 550


def test_chaos_host_spill_leaves_generation_queryable():
    ds = _mk_store("rz_c2", n=1200, slots=256)
    idx = ds._store("rz_c2")._indexes["z3"]
    baseline = len(ds.query_result("rz_c2", BBOX).positions)
    gen = next(g for g in idx.generations if g.tier == "full")
    config.set_property("geomesa.resilience.fault.points",
                        "host.spill:1=error")
    with pytest.raises(FaultInjected):
        idx._spill(gen)
    # the fault fired BEFORE any transfer: the generation is still
    # device-resident and the store serves the identical result
    assert gen.tier == "full"
    config.clear_property("geomesa.resilience.fault.points")
    assert len(ds.query_result("rz_c2", BBOX).positions) == baseline
    # a clean spill afterwards works and stays exact
    idx._spill(gen)
    assert gen.tier == "host"
    assert len(ds.query_result("rz_c2", BBOX).positions) == baseline


def test_chaos_arrow_flush_releases_admission_slot():
    ds = _mk_store("rz_c3", n=400)
    config.set_property("geomesa.resilience.admission.max.concurrent", 2)
    config.set_property("geomesa.resilience.fault.points",
                        "arrow.flush:1=error")
    from geomesa_tpu.arrow.stream import ipc_chunks
    stream = ds.query_arrow("rz_c3", BBOX, chunk_rows=64)
    with pytest.raises(FaultInjected):
        for _ in ipc_chunks(stream):
            pass
    del stream
    gc.collect()
    assert admission_gate.inflight == 0
    config.clear_property("geomesa.resilience.fault.points")
    assert len(ds.query_result("rz_c3", BBOX).positions) == 400


def test_abandoned_stream_releases_admission_slot():
    # a stream created but NEVER iterated: the drain generator's
    # finally can't run (its body was never entered), so the release
    # must come from ArrowStream.close()/__del__
    ds = _mk_store("rz_c6", n=200)
    stream = ds.query_arrow("rz_c6", BBOX, chunk_rows=64)
    assert admission_gate.inflight == 1
    del stream
    gc.collect()
    assert admission_gate.inflight == 0
    # explicit close works too, and is idempotent
    stream = ds.query_arrow("rz_c6", BBOX, chunk_rows=64)
    stream.close()
    stream.close()
    assert admission_gate.inflight == 0


def test_chaos_killed_web_drain_counts_abort_and_releases_token():
    pytest.importorskip("pyarrow")
    from geomesa_tpu.metrics import registry as metrics
    from geomesa_tpu.web.app import WebApp
    ds = _mk_store("rz_c4", n=400)
    app = WebApp(ds)
    config.set_property("geomesa.resilience.admission.max.concurrent", 2)
    config.set_property("geomesa.resilience.fault.points",
                        "arrow.flush:1=error")
    before = metrics.counter("web.stream_aborted").count
    body = app({"PATH_INFO": "/query", "REQUEST_METHOD": "GET",
                "QUERY_STRING": "schema=rz_c4"}, lambda s, h: None)
    with pytest.raises(FaultInjected):
        for _ in body:
            pass
    del body
    gc.collect()
    assert metrics.counter("web.stream_aborted").count == before + 1
    assert admission_gate.inflight == 0
    config.clear_property("geomesa.resilience.fault.points")
    assert len(ds.query_result("rz_c4", BBOX).positions) == 400


def test_chaos_compaction_interrupt_resumes():
    from geomesa_tpu.index.lsm import compact_incremental
    merged: list = []
    groups = [["a"], ["b"], ["c"]]

    def plan():
        return [g for g in groups if g[0] not in merged]

    def merge_one(group):
        merged.append(group[0])

    config.set_property("geomesa.resilience.fault.points",
                        "compaction.merge_step:1=error")
    with pytest.raises(FaultInjected):
        compact_incremental(plan, merge_one)
    # interrupted BEFORE the first merge: nothing half-applied
    assert merged == []
    # the next compact() replans from the survivors and finishes
    assert compact_incremental(plan, merge_one) == 3
    assert merged == ["a", "b", "c"]


def test_chaos_grid_covers_every_cataloged_point():
    """Every point in the FAULT_POINTS declaration has a chaos test in
    this module exercising it by name (the matrix stays total as
    points are added)."""
    import pathlib
    src = pathlib.Path(__file__).read_text(encoding="utf-8")
    for point in FAULT_POINTS:
        assert src.count(f'"{point}') >= 1, f"no chaos arm for {point}"


# -- recompile cleanliness --------------------------------------------------

def test_warm_timeout_queries_do_not_recompile():
    from geomesa_tpu.obs import compile_count
    ds = _mk_store("rz_r1", n=600)
    ds.query_result("rz_r1", BBOX)                         # warm
    ds.query_result("rz_r1", BBOX, timeout_ms=60_000.0)    # warm w/ scope
    c0 = compile_count()
    ds.query_result("rz_r1", BBOX)
    ds.query_result("rz_r1", BBOX, timeout_ms=30_000.0)
    ds.query_result("rz_r1", BBOX, timeout_ms=45_000.0,
                    partial_results=True)
    assert compile_count() - c0 == 0


# -- eager interceptor wiring (satellite) -----------------------------------

def _install_test_interceptors():
    mod = types.ModuleType("rz_test_interceptors")

    class RewriteToBBox:
        """Rewrites every query to the test bbox — the 'inject a
        default spatial bound' interceptor shape."""

        def rewrite(self, sft, query):
            from geomesa_tpu.planning.planner import Query
            return Query.of(BBOX, max_features=query.max_features)

    class RejectAll:
        def rewrite(self, sft, query):
            raise ValueError("rejected by policy interceptor")

    mod.RewriteToBBox = RewriteToBBox
    mod.RejectAll = RejectAll
    sys.modules["rz_test_interceptors"] = mod


def test_interceptor_rewrite_wired_at_schema_load():
    _install_test_interceptors()
    ds = TpuDataStore()
    ds.create_schema(
        "rz_i1",
        "dtg:Date,*geom:Point;geomesa.query.interceptors="
        "rz_test_interceptors:RewriteToBBox")
    # resolved EAGERLY: the instance exists before any query runs
    assert type(ds._interceptors["rz_i1"][0]).__name__ == "RewriteToBBox"
    n = 10
    ds.write("rz_i1", {
        "dtg": np.full(n, MS_2018, dtype=np.int64),
        "geom": (np.full(n, -74.5), np.full(n, 40.5))})
    ds.write("rz_i1", {
        "dtg": np.full(n, MS_2018, dtype=np.int64),
        "geom": (np.full(n, 10.0), np.full(n, 10.0))})   # outside bbox
    # INCLUDE is rewritten to the bbox: only the in-bbox rows return
    assert len(ds.query_result("rz_i1", "INCLUDE").positions) == n


def test_interceptor_reject_applies():
    _install_test_interceptors()
    ds = TpuDataStore()
    ds.create_schema(
        "rz_i2",
        "dtg:Date,*geom:Point;geomesa.query.interceptors="
        "rz_test_interceptors:RejectAll")
    with pytest.raises(ValueError, match="rejected by policy"):
        ds.query_result("rz_i2", BBOX)


def test_typoed_interceptor_fails_create_schema_not_first_query():
    ds = TpuDataStore()
    with pytest.raises((ImportError, AttributeError)):
        ds.create_schema(
            "rz_i3",
            "dtg:Date,*geom:Point;geomesa.query.interceptors="
            "no_such_module:Nope")


# -- bounded web serving (satellite) ----------------------------------------

def test_bounded_app_sheds_503_on_saturation():
    import json
    from geomesa_tpu.web.wsgi import BoundedApp

    def app(environ, start_response):
        start_response("200 OK", [("Content-Type", "text/plain")])
        return [b"ok"]

    bounded = BoundedApp(app, max_concurrent=1)
    bounded._sem.acquire()        # simulate one request in flight
    seen = []
    body = bounded({}, lambda s, h: seen.append((s, h)))
    assert seen[0][0].startswith("503")
    assert any(h[0] == "Retry-After" for h in seen[0][1])
    assert json.loads(b"".join(body))["error"]
    bounded._sem.release()
    seen.clear()
    assert b"".join(bounded({}, lambda s, h: seen.append((s, h)))) == b"ok"
    assert seen[0][0].startswith("200")
    # the slot is back after the body drained
    assert bounded._sem.acquire(blocking=False)
    bounded._sem.release()


def test_router_maps_backpressure_and_timeout():
    from geomesa_tpu.web.wsgi import Router

    def shed(method, params, environ):
        raise Backpressure("too busy", retry_after_s=2.0)

    def slow(method, params, environ):
        raise QueryTimeout("deadline", elapsed_ms=10.0)

    router = Router([(r"^/shed$", shed), (r"^/slow$", slow)])
    seen = []
    router.dispatch({"PATH_INFO": "/shed"},
                    lambda s, h: seen.append((s, h)))
    assert seen[0][0].startswith("503")
    assert ("Retry-After", "2") in seen[0][1]
    seen.clear()
    router.dispatch({"PATH_INFO": "/slow"},
                    lambda s, h: seen.append((s, h)))
    assert seen[0][0].startswith("504")


def test_query_stream_accepts_timeout_params():
    pytest.importorskip("pyarrow")
    import pyarrow as pa
    from geomesa_tpu.web.app import WebApp
    ds = _mk_store("rz_w1", n=300)
    app = WebApp(ds)
    seen = []
    body = app({"PATH_INFO": "/query", "REQUEST_METHOD": "GET",
                "QUERY_STRING": "schema=rz_w1&timeout_ms=60000"},
               lambda s, h: seen.append((s, h)))
    blob = b"".join(body)
    assert seen[0][0].startswith("200")
    assert pa.ipc.open_stream(blob).read_all().num_rows == 300
    seen.clear()
    body = app({"PATH_INFO": "/query", "REQUEST_METHOD": "GET",
                "QUERY_STRING": ("schema=rz_w1&timeout_ms=1"
                                 "&partial=1")},
               lambda s, h: seen.append((s, h)))
    blob = b"".join(body)
    assert seen[0][0].startswith("200")
    # expired partial stream: fewer (possibly zero) rows, valid EOS
    assert pa.ipc.open_stream(blob).read_all().num_rows <= 300
    gc.collect()
    assert admission_gate.inflight == 0
