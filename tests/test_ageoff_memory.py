"""Age-off (TTL), query timeout, memory engine, column groups, json-path
attributes, and enrichment caches."""

import time

import numpy as np
import pytest

from geomesa_tpu.age_off import age_off, parse_duration_ms
from geomesa_tpu.datastore import TpuDataStore

MS_2018 = 1514764800000
DAY = 86_400_000
NOW = int(time.time() * 1000)


def test_parse_duration():
    assert parse_duration_ms("7 days") == 7 * DAY
    assert parse_duration_ms("12 hours") == 12 * 3_600_000
    assert parse_duration_ms("30 minutes") == 1_800_000
    assert parse_duration_ms("45 seconds") == 45_000
    assert parse_duration_ms("500 ms") == 500
    assert parse_duration_ms(1234) == 1234
    with pytest.raises(ValueError):
        parse_duration_ms("7 fortnights")


class TestAgeOff:
    def _store(self):
        ds = TpuDataStore()
        ds.create_schema("t", "v:Int,dtg:Date,*geom:Point")
        ds.write("t", {
            "v": np.arange(4),
            "dtg": np.asarray([NOW - 10 * DAY, NOW - 5 * DAY,
                               NOW - DAY, NOW]),
            "geom": (np.zeros(4), np.zeros(4)),
        })
        return ds

    def test_physical_age_off(self):
        ds = self._store()
        assert age_off(ds, "t", retention="7 days", dry_run=True) == 1
        assert ds.get_count("t") == 4
        assert age_off(ds, "t", retention="7 days") == 1
        assert ds.get_count("t") == 3
        assert age_off(ds, "t", retention="2 days") == 1
        assert sorted(ds.query("t").column("v")) == [2, 3]

    def test_scan_time_age_off_interceptor(self):
        ds = TpuDataStore()
        ds.create_schema(
            "live", "v:Int,dtg:Date,*geom:Point;geomesa.age.off='3 days'")
        ds.write("live", {
            "v": np.arange(3),
            "dtg": np.asarray([NOW - 10 * DAY, NOW - DAY, NOW]),
            "geom": (np.zeros(3), np.zeros(3)),
        })
        # rows older than retention are hidden at query time but not deleted
        assert sorted(ds.query("live").column("v")) == [1, 2]
        assert ds._store("live").batch is not None
        assert len(ds._store("live").batch) == 3


def test_query_timeout():
    from geomesa_tpu.config import clear_property, set_property
    from geomesa_tpu.planning.planner import QueryTimeoutError

    ds = TpuDataStore()
    ds.create_schema("q", "v:Int,dtg:Date,*geom:Point")
    ds.write("q", {"v": np.arange(10), "dtg": np.zeros(10, dtype=np.int64),
                   "geom": (np.zeros(10), np.zeros(10))})
    set_property("geomesa.query.timeout", -1)  # deadline already passed
    try:
        with pytest.raises(QueryTimeoutError):
            ds.query("q", "v > 3")
    finally:
        clear_property("geomesa.query.timeout")
    assert len(ds.query("q", "v > 3")) == 6


class TestGeoCQEngine:
    def _engine(self):
        from geomesa_tpu.features.feature_type import parse_spec
        from geomesa_tpu.memory import GeoCQEngine
        sft = parse_spec("m", "name:String,age:Int,dtg:Date,*geom:Point")
        eng = GeoCQEngine(sft)
        for i in range(100):
            eng.insert(f"f{i}", {"name": f"n{i % 5}", "age": i,
                                 "dtg": MS_2018 + i * 1000},
                       x=-75 + i * 0.01, y=40 + i * 0.01)
        return eng

    def test_equality_hash_index(self):
        eng = self._engine()
        got = eng.query("name = 'n3'")
        assert len(got) == 20
        assert set(got.column("name")) == {"n3"}

    def test_range_sorted_index(self):
        eng = self._engine()
        assert len(eng.query("age >= 90")) == 10
        assert len(eng.query("age BETWEEN 10 AND 19")) == 10
        assert len(eng.query("age < 5 OR age >= 95")) == 10

    def test_spatial_bucket_index(self):
        eng = self._engine()
        got = eng.query("BBOX(geom, -74.8, 40.2, -74.7, 40.3)")
        xs, _ = got.geom_xy()
        assert len(got) > 0 and (xs >= -74.8).all() and (xs <= -74.7).all()

    def test_incremental_update_remove(self):
        eng = self._engine()
        eng.insert("f0", {"name": "changed", "age": 500, "dtg": 0}, 0.0, 0.0)
        assert len(eng) == 100  # replaced, not added
        assert len(eng.query("age = 500")) == 1
        assert len(eng.query("name = 'n0'")) == 19
        assert eng.remove("f0") and not eng.remove("f0")
        assert len(eng) == 99
        assert len(eng.query("age = 500")) == 0

    def test_in_and_id_filters(self):
        eng = self._engine()
        assert len(eng.query("name IN ('n0', 'n1')")) == 40
        assert len(eng.query("IN ('f1', 'f2', 'nope')")) == 2

    def test_during(self):
        eng = self._engine()
        got = eng.query(
            "dtg DURING 2018-01-01T00:00:10Z/2018-01-01T00:00:19Z")
        assert len(got) == 10


def test_column_groups():
    ds = TpuDataStore()
    ds.create_schema("cg", "a:String:column-groups=small,"
                           "b:String:column-groups=small|big,"
                           "c:String,dtg:Date,*geom:Point")
    sft = ds.get_schema("cg")
    assert sft.column_groups["small"] == ["geom", "dtg", "a", "b"]
    assert sft.column_groups["big"] == ["geom", "dtg", "b"]
    ds.write("cg", {"a": np.asarray(["x"], dtype=object),
                    "b": np.asarray(["y"], dtype=object),
                    "c": np.asarray(["z"], dtype=object),
                    "dtg": np.asarray([0]),
                    "geom": (np.zeros(1), np.zeros(1))})
    from geomesa_tpu.planning.planner import Query
    out = ds.query("cg", Query.of("INCLUDE", hints={"COLUMN_GROUP": "small"}))
    assert "a" in out.columns and "b" in out.columns
    assert "c" not in out.columns
    with pytest.raises(ValueError):
        ds.query("cg", Query.of("INCLUDE", hints={"COLUMN_GROUP": "nope"}))


def test_json_path_attribute_queries():
    ds = TpuDataStore()
    ds.create_schema("j", "attrs:Json,dtg:Date,*geom:Point")
    docs = ['{"user": {"age": 30, "name": "ann"}, "tags": ["a", "b"]}',
            '{"user": {"age": 10, "name": "bob"}}',
            '{"user": {"name": "cat"}}']
    ds.write("j", {"attrs": np.asarray(docs, dtype=object),
                   "dtg": np.zeros(3, dtype=np.int64),
                   "geom": (np.zeros(3), np.zeros(3))})
    assert len(ds.query("j", '"$.attrs.user.age" > 18')) == 1
    assert len(ds.query("j", '"$.attrs.user.age" <= 30')) == 2
    got = ds.query("j", "\"$.attrs.user.name\" = 'cat'")
    assert len(got) == 1
    assert len(ds.query("j", "\"$.attrs.tags[0]\" = 'a'")) == 1
    # missing paths are never hits
    assert len(ds.query("j", '"$.attrs.nope.deep" > 0')) == 0


def test_enrichment_cache_lookup(tmp_path):
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.io.converters import converter_from_config
    from geomesa_tpu.io.enrichment import clear_caches

    csv_cache = tmp_path / "vessels.csv"
    csv_cache.write_text("mmsi,flag,vtype\n123,US,cargo\n456,NO,tanker\n")
    sft = parse_spec("e", "flag:String,vtype:String,*geom:Point")
    conv = converter_from_config(sft, {
        "type": "csv",
        "caches": {
            "vessels": {"type": "csv", "path": str(csv_cache),
                        "key-column": "mmsi"},
            "owners": {"type": "inline",
                       "data": {"123": {"owner": "acme"}}},
        },
        "fields": [
            {"name": "flag",
             "transform": "cacheLookup('vessels', $0, 'flag')"},
            {"name": "vtype",
             "transform": "cacheLookup('vessels', $0, 'vtype')"},
            {"name": "geom", "transform": "point($1,$2)"},
        ],
    })
    batch = conv.convert("123,1.0,2.0\n456,3.0,4.0\n999,5.0,6.0\n")
    assert list(batch.column("flag")) == ["US", "NO", None]
    assert list(batch.column("vtype")) == ["cargo", "tanker", None]
    clear_caches()


def test_quoted_reserved_word_properties():
    from geomesa_tpu.filters.ecql import parse_ecql
    from geomesa_tpu.filters.ast import PropertyCompare
    f = parse_ecql('"contains" = \'x\'')
    assert isinstance(f, PropertyCompare) and f.prop == "contains"
    assert parse_ecql('"IN" = 5').prop == "IN"


def test_json_path_bracket_first_segment():
    ds = TpuDataStore()
    ds.create_schema("ja", "props:Json,dtg:Date,*geom:Point")
    ds.write("ja", {"props": np.asarray(
        ['[{"name": "first"}, {"name": "second"}]', '[]'], dtype=object),
        "dtg": np.zeros(2, dtype=np.int64),
        "geom": (np.zeros(2), np.zeros(2))})
    assert len(ds.query("ja", "\"$.props[0].name\" = 'first'")) == 1
    assert len(ds.query("ja", "\"$.props[1].name\" = 'second'")) == 1


def test_memory_engine_sparse_attributes():
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.memory import GeoCQEngine
    eng = GeoCQEngine(parse_spec("s", "name:String,age:Int,*geom:Point"))
    eng.insert("1", {"name": "a"}, 0, 0)          # no age
    eng.insert("2", {"name": "b", "age": 30}, 1, 1)
    got = eng.query("INCLUDE")
    assert len(got) == 2
    got = eng.query("age > 10")
    assert list(got.ids) == ["2"]


def test_enrichment_caches_scoped_per_converter(tmp_path):
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.io.converters import converter_from_config
    sft = parse_spec("e2", "v:String,*geom:Point")
    mk = lambda val: converter_from_config(sft, {
        "type": "csv",
        "caches": {"shared": {"type": "inline",
                              "data": {"k": {"f": val}}}},
        "fields": [
            {"name": "v", "transform": "cacheLookup('shared', $0, 'f')"},
            {"name": "geom", "transform": "point($1,$2)"},
        ]})
    c1, c2 = mk("one"), mk("two")
    # constructing c2 must not clobber c1's same-named cache
    assert list(c1.convert("k,0,0\n").column("v")) == ["one"]
    assert list(c2.convert("k,0,0\n").column("v")) == ["two"]


def test_blob_id_path_traversal_rejected(tmp_path):
    from geomesa_tpu.blob import GeoIndexedBlobStore
    from geomesa_tpu.geometry.types import Point
    bs = GeoIndexedBlobStore(blob_dir=str(tmp_path / "b"))
    with pytest.raises(ValueError):
        bs.put(b"x", geometry=Point(0, 0), blob_id="../escape")
    assert bs.get("../../etc/passwd") is None
    bs.delete_blob("../../etc/passwd")  # no-op, no exception


def test_json_path_malformed_and_none_semantics():
    ds = TpuDataStore()
    ds.create_schema("jm", "attrs:Json,name:String,dtg:Date,*geom:Point")
    ds.write("jm", {
        "attrs": np.asarray(['{"a": 30}', '{bad', '{}'], dtype=object),
        "name": np.asarray(["x", None, "Nellie"], dtype=object),
        "dtg": np.zeros(3, dtype=np.int64),
        "geom": (np.zeros(3), np.zeros(3))})
    # malformed json row is a non-match, not a crash
    assert len(ds.query("jm", '"$.attrs.a" = 30')) == 1
    # None values do not match <>
    assert len(ds.query("jm", '"$.attrs.a" <> 30')) == 0
    # None does not match LIKE (str(None) = 'None' must not leak)
    assert len(ds.query("jm", "name LIKE 'N%'")) == 1


def test_memory_engine_concurrent_churn():
    import threading
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.memory import GeoCQEngine
    eng = GeoCQEngine(parse_spec("c", "v:Int,*geom:Point"))
    stop = threading.Event()
    errors = []

    def churn():
        i = 0
        while not stop.is_set():
            eng.insert(f"f{i % 50}", {"v": i}, i % 10, i % 10)
            if i % 3 == 0:
                eng.remove(f"f{(i + 25) % 50}")
            i += 1

    def read():
        while not stop.is_set():
            try:
                eng.query("v >= 0")
                eng.query("BBOX(geom, 0, 0, 5, 5)")
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=churn) for _ in range(2)] + [
        threading.Thread(target=read) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors


def test_polling_truncation_recovery(tmp_path):
    from geomesa_tpu.features.feature_type import parse_spec
    from geomesa_tpu.io.converters import converter_from_config
    from geomesa_tpu.stream import PollingStreamSource
    sft = parse_spec("tr", "v:Int,*geom:Point")
    conv = converter_from_config(sft, {
        "type": "csv",
        "fields": [{"name": "v", "transform": "toInt($0)"},
                   {"name": "geom", "transform": "point($1,$2)"}]})
    got = []
    src = PollingStreamSource(str(tmp_path / "*.log"), conv, got.append)
    f = tmp_path / "r.log"
    f.write_text("1,0,0\n2,0,0\n")
    assert src.poll_once() == 2
    f.write_text("9,0,0\n")  # truncation (logrotate copytruncate)
    assert src.poll_once() == 1
    assert [int(b.column("v")[0]) for b in got][-1] == 9
