"""Query/join/track/transform/unique/route processes vs brute-force oracles
(reference: geomesa-process QueryProcess, JoinProcess, Point2PointProcess,
TrackLabelProcess, HashAttributeProcess, DateOffsetProcess, UniqueProcess,
MinMaxProcess, RouteSearchProcess)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.geometry import LineString
from geomesa_tpu.process import (
    date_offset_process,
    hash_attribute_color_process,
    hash_attribute_process,
    join_process,
    min_max_process,
    point2point_process,
    query_process,
    route_search_process,
    track_label_process,
    unique_process,
)
from geomesa_tpu.process.route import bearing_deg
from geomesa_tpu.process.transform import parse_iso_duration_ms

MS_2018 = 1514764800000
N = 5_000


@pytest.fixture(scope="module")
def store():
    rng = np.random.default_rng(11)
    ds = TpuDataStore()
    ds.create_schema(
        "ships", "vessel:String:index=true,kind:Int,dtg:Date,*geom:Point")
    ds.write("ships", {
        "vessel": rng.choice([f"v{i}" for i in range(20)], N),
        "kind": rng.integers(0, 5, N).astype(np.int32),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * 86_400_000, N),
        "geom": (rng.uniform(-5.0, 5.0, N), rng.uniform(45.0, 55.0, N)),
    })
    ds.create_schema("meta", "vessel:String:index=true,flag:String,*geom:Point")
    ds.write("meta", {
        "vessel": np.array([f"v{i}" for i in range(30)], dtype=object),
        "flag": np.array(["ok" if i % 2 == 0 else "bad" for i in range(30)],
                         dtype=object),
        "geom": (np.zeros(30), np.zeros(30)),
    })
    return ds


def test_query_process_projects_and_filters(store):
    batch = query_process(store, "ships", "kind = 2", properties=["vessel"])
    assert set(batch.columns) == {"vessel"}
    oracle = store._store("ships").batch
    assert len(batch) == int(np.sum(oracle.column("kind") == 2))


def test_join_process(store):
    sec, vals = join_process(store, "ships", "meta", "vessel",
                             primary_filter="kind = 1")
    prim = store._store("ships").batch
    expect = np.unique(prim.column("vessel")[prim.column("kind") == 1]
                       .astype(str))
    np.testing.assert_array_equal(np.sort(vals.astype(str)), expect)
    assert set(sec.column("vessel").astype(str)) <= set(expect)
    # every joined vessel that exists in meta is present
    meta_vessels = set(store._store("meta").batch.column("vessel").astype(str))
    assert set(sec.column("vessel").astype(str)) == set(expect) & meta_vessels


def test_join_process_with_filter(store):
    sec, _ = join_process(store, "ships", "meta", "vessel",
                          join_filter="flag = 'ok'")
    assert np.all(sec.column("flag").astype(str) == "ok")


def test_unique_process_histogram(store):
    values, counts = unique_process(store, "ships", "vessel",
                                    histogram=True, sort_by_count=True)
    oracle = store._store("ships").batch.column("vessel").astype(str)
    ev, ec = np.unique(oracle, return_counts=True)
    assert sorted(values.tolist()) == sorted(ev.tolist())
    assert np.all(np.diff(counts) <= 0)
    assert counts.sum() == N


def test_unique_process_filtered_sorted(store):
    values = unique_process(store, "ships", "vessel", "kind = 0", sort="DESC")
    oracle = store._store("ships").batch
    ev = np.unique(
        oracle.column("vessel")[oracle.column("kind") == 0].astype(str))
    np.testing.assert_array_equal(values, ev[::-1])


def test_min_max_process(store):
    lo, hi = min_max_process(store, "ships", "dtg", cached=False)
    col = store._store("ships").batch.column("dtg")
    assert (lo, hi) == (col.min(), col.max())
    cached = min_max_process(store, "ships", "dtg", cached=True)
    assert cached is not None


def test_point2point_and_track_label():
    sft_spec = "vessel:String,dtg:Date,*geom:Point"
    ds = TpuDataStore()
    ds.create_schema("trk", sft_spec)
    ds.write("trk", {
        "vessel": np.array(["a", "b", "a", "b", "a"], dtype=object),
        "dtg": np.array([3, 1, 1, 2, 2]) * 3_600_000 + MS_2018,
        "geom": (np.array([3.0, 1.0, 1.0, 2.0, 2.0]),
                 np.array([30.0, 10.0, 10.0, 20.0, 20.0])),
    })
    batch = ds._store("trk").batch
    lines = point2point_process(batch, "vessel", "dtg")
    # a: (1,10)->(2,20)->(3,30); b: (1,10)->(2,20)  => 3 segments
    assert len(lines) == 3
    assert set(lines.column("vessel").astype(str)) == {"a", "b"}
    assert np.all(lines.column("dtg_start") < lines.column("dtg_end"))
    # geometry endpoints follow time order
    g0 = lines.geoms.geometry(0)
    assert g0.coords.shape == (2, 2)

    # break on day: same points, times split across days
    ds.create_schema("trk2", sft_spec)
    ds.write("trk2", {
        "vessel": np.array(["a", "a", "a"], dtype=object),
        "dtg": MS_2018 + np.array([0, 3_600_000, 86_400_000 + 3_600_000]),
        "geom": (np.array([0.0, 1.0, 2.0]), np.array([0.0, 1.0, 2.0])),
    })
    b2 = ds._store("trk2").batch
    assert len(point2point_process(b2, "vessel", "dtg")) == 2
    assert len(point2point_process(b2, "vessel", "dtg", break_on_day=True)) == 1
    # min_points prunes small groups
    assert len(point2point_process(b2, "vessel", "dtg", min_points=4)) == 0

    labels = track_label_process(batch, "vessel", "dtg")
    assert len(labels) == 2
    lv = batch.column("vessel")[labels].astype(str)
    lt = batch.column("dtg")[labels]
    assert set(lv) == {"a", "b"}
    for v in ("a", "b"):
        mask = batch.column("vessel").astype(str) == v
        assert lt[lv == v][0] == batch.column("dtg")[mask].max()


def test_hash_attribute_process(store):
    batch = store._store("ships").batch
    out = hash_attribute_process(batch, "vessel", 7)
    h = out.column("hash")
    assert h.dtype == np.int64 and np.all((h >= 0) & (h < 7))
    # deterministic and equal for equal values
    v = batch.column("vessel").astype(str)
    for val in np.unique(v)[:3]:
        assert len(np.unique(h[v == val])) == 1
    colored = hash_attribute_color_process(batch, "vessel", 7)
    assert all(str(c).startswith("#") for c in colored.column("hash")[:10])


def test_date_offset_process(store):
    batch = store._store("ships").batch
    out = date_offset_process(batch, "dtg", "P1D")
    np.testing.assert_array_equal(
        out.column("dtg"), batch.column("dtg") + 86_400_000)
    assert parse_iso_duration_ms("-PT2H30M") == -9_000_000
    assert parse_iso_duration_ms("PT10S") == 10_000
    with pytest.raises(ValueError):
        parse_iso_duration_ms("1 day")


def test_route_search():
    # route due north along lon=0; ships with matching/opposing headings
    ds = TpuDataStore()
    ds.create_schema("fleet", "heading:Double,*geom:Point")
    x = np.array([0.001, 0.001, 0.001, 2.0, 0.001])
    y = np.array([50.0, 50.5, 51.0, 50.0, 50.2])
    heading = np.array([0.0, 180.0, 90.0, 0.0, 350.0])
    ds.write("fleet", {"heading": heading, "geom": (x, y)})
    route = LineString(np.array([[0.0, 49.5], [0.0, 51.5]]))

    hits = route_search_process(
        ds, "fleet", [route], buffer_m=5_000.0, heading_threshold_deg=30.0,
        heading_field="heading")
    # northbound ships near the route: indices 0 and 4 (350° within 30° of 0°)
    np.testing.assert_array_equal(hits, [0, 4])

    both = route_search_process(
        ds, "fleet", [route], buffer_m=5_000.0, heading_threshold_deg=30.0,
        heading_field="heading", bidirectional=True)
    np.testing.assert_array_equal(both, [0, 1, 4])  # southbound matches too

    none = route_search_process(
        ds, "fleet", [], buffer_m=5_000.0, heading_threshold_deg=30.0,
        heading_field="heading")
    assert len(none) == 0


def test_bearing_deg():
    assert abs(bearing_deg(0.0, 0.0, 0.0, 1.0) - 0.0) < 1e-9      # north
    assert abs(bearing_deg(0.0, 0.0, 1.0, 0.0) - 90.0) < 1e-6     # east
    assert abs(bearing_deg(0.0, 0.0, 0.0, -1.0) - 180.0) < 1e-9   # south
