"""Stats sketches: observe/merge/serialize roundtrips and estimation
accuracy (reference: geomesa-utils stats suite)."""

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, parse_spec
from geomesa_tpu.stats import (
    CountStat,
    DescriptiveStats,
    EnumerationStat,
    Frequency,
    Histogram,
    MinMax,
    SeqStat,
    TopK,
    parse_stat,
    stat_from_json,
)

MS_2018 = 1514764800000


@pytest.fixture
def batch(rng):
    sft = parse_spec("t", "name:String,val:Double,dtg:Date,*geom:Point")
    n = 10_000
    return FeatureBatch.from_dict(
        sft,
        {
            "name": rng.choice(["a", "b", "c", "d"], n, p=[0.5, 0.3, 0.15, 0.05]),
            "val": rng.normal(50, 10, n),
            "dtg": rng.integers(MS_2018, MS_2018 + 10 * 86_400_000, n),
            "geom": (rng.uniform(-10, 10, n), rng.uniform(40, 50, n)),
        },
    )


def halves(batch):
    n = len(batch)
    return batch.take(np.arange(n // 2)), batch.take(np.arange(n // 2, n))


def test_count_merge(batch):
    a, b = halves(batch)
    s1, s2 = CountStat(), CountStat()
    s1.observe(a)
    s2.observe(b)
    assert (s1 + s2).count == len(batch)


def test_minmax(batch):
    s = MinMax("val")
    s.observe(batch)
    col = batch.column("val")
    assert s.min == col.min() and s.max == col.max()
    a, b = halves(batch)
    s1, s2 = MinMax("val"), MinMax("val")
    s1.observe(a)
    s2.observe(b)
    m = s1 + s2
    assert (m.min, m.max) == (s.min, s.max)


def test_histogram_estimate(batch):
    h = Histogram("val", 50, 0.0, 100.0)
    h.observe(batch)
    assert h.total == len(batch)
    est = h.estimate_range(40.0, 60.0)
    true = np.count_nonzero((batch.column("val") >= 40) & (batch.column("val") <= 60))
    assert abs(est - true) / true < 0.1
    # merge equals whole
    a, b = halves(batch)
    h1 = Histogram("val", 50, 0.0, 100.0)
    h2 = Histogram("val", 50, 0.0, 100.0)
    h1.observe(a)
    h2.observe(b)
    np.testing.assert_array_equal((h1 + h2).counts, h.counts)


def test_frequency(batch):
    f = Frequency("name")
    f.observe(batch)
    true_a = np.count_nonzero(batch.column("name") == "a")
    # count-min overestimates but never underestimates
    assert f.count("a") >= true_a
    assert f.count("a") <= true_a * 1.2 + 100
    a, b = halves(batch)
    f1, f2 = Frequency("name"), Frequency("name")
    f1.observe(a)
    f2.observe(b)
    np.testing.assert_array_equal((f1 + f2).table, f.table)


def test_topk(batch):
    t = TopK("name", k=2)
    t.observe(batch)
    top = t.topk()
    assert top[0][0] == "a" and top[1][0] == "b"


def test_enumeration(batch):
    e = EnumerationStat("name")
    e.observe(batch)
    assert sum(e.counts.values()) == len(batch)
    assert e.counts["a"] == np.count_nonzero(batch.column("name") == "a")


def test_descriptive(batch):
    d = DescriptiveStats("val")
    d.observe(batch)
    col = batch.column("val")
    assert abs(d.mean - col.mean()) < 1e-9
    assert abs(d.stddev - col.std(ddof=1)) < 1e-6
    a, b = halves(batch)
    d1, d2 = DescriptiveStats("val"), DescriptiveStats("val")
    d1.observe(a)
    d2.observe(b)
    m = d1 + d2
    assert abs(m.mean - d.mean) < 1e-9
    assert abs(m.variance - d.variance) < 1e-6


def test_parser_and_seq(batch):
    s = parse_stat("Count();MinMax(val);Histogram(val,10,0,100)")
    assert isinstance(s, SeqStat)
    s.observe(batch)
    assert s.stats[0].count == len(batch)
    assert not s.is_empty


def test_groupby(batch):
    g = parse_stat("GroupBy(name,Count())")
    g.observe(batch)
    total = sum(sub.count for sub in g.groups.values())
    assert total == len(batch)
    assert g.groups["a"].count == np.count_nonzero(batch.column("name") == "a")


def test_json_roundtrip(batch):
    import json
    for spec in ["Count()", "MinMax(val)", "Histogram(val,10,0,100)",
                 "Frequency(name)", "TopK(name)", "Enumeration(name)",
                 "DescriptiveStats(val)", "GroupBy(name,Count())",
                 "Count();MinMax(val)"]:
        s = parse_stat(spec)
        s.observe(batch)
        blob = json.dumps(s.to_json())
        back = stat_from_json(json.loads(blob))
        assert back.to_json() == s.to_json(), spec


def test_z3_histogram(batch):
    s = parse_stat("Z3Histogram(geom,dtg,week,8)")
    s.observe(batch)
    assert sum(s.counts.values()) == len(batch)
    a, b = halves(batch)
    s1 = parse_stat("Z3Histogram(geom,dtg,week,8)")
    s2 = parse_stat("Z3Histogram(geom,dtg,week,8)")
    s1.observe(a)
    s2.observe(b)
    assert (s1 + s2).counts == s.counts


def test_stats_analyze_builds_range_histograms():
    """stats-analyze adds numeric-attribute histograms that sharpen
    range-cost estimates (StatsBasedEstimator role)."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore

    rng = np.random.default_rng(0)
    n = 5000
    ds = TpuDataStore()
    ds.create_schema("h", "v:Int:index=true,dtg:Date,*geom:Point")
    ds.write("h", {"v": rng.integers(0, 1000, n),
                   "dtg": np.zeros(n, np.int64),
                   "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))})
    before = ds.query_result("h", "v BETWEEN 10 AND 20").strategy.cost
    ds.stats_analyze("h")
    after = ds.query_result("h", "v BETWEEN 10 AND 20").strategy.cost
    assert after < before / 2           # histogram sharpened the estimate
    assert ds.stat("h", "v_histogram") is not None


def test_observe_shared_matches_per_stat_observe():
    """The shared-intermediate observe path (factorize-based for object
    strings, incl. the None → "None" convention) must fold identically
    to each stat's own observe()."""
    import numpy as np

    from geomesa_tpu.stats.stat import (
        CountStat, EnumerationStat, MinMax, TopK, observe_shared,
    )
    rng = np.random.default_rng(5)
    n = 10_000
    names = rng.choice(np.array(["a", "b", "c", None], object), n,
                       p=[.5, .3, .15, .05])
    vals = rng.uniform(0, 10, n)
    batch = {"name": names, "v": vals}
    shared = {"name_topk": TopK("name"),
              "name_enumeration": EnumerationStat("name"),
              "v_minmax": MinMax("v"), "count": CountStat()}
    solo = {"name_topk": TopK("name"),
            "name_enumeration": EnumerationStat("name"),
            "v_minmax": MinMax("v"), "count": CountStat()}
    observe_shared(shared, batch)
    for s in solo.values():
        s.observe(batch)
    assert shared["count"].count == solo["count"].count
    assert shared["v_minmax"].bounds == solo["v_minmax"].bounds
    assert shared["name_enumeration"].counts == \
        solo["name_enumeration"].counts
    assert shared["name_topk"].counters == solo["name_topk"].counters
    assert shared["name_enumeration"].counts.get("None") == \
        int(sum(v is None for v in names))
