"""Differential tests: native (C++) range decomposition vs numpy fallback.

The native library (geomesa_tpu/native/geomesa_native.cpp) implements the
same level-synchronous sweeps as curve/ranges.py and curve/{xz2,xz3}.py —
same emit order, same budget arithmetic — so outputs must be identical
array-for-array, including under budget truncation.
"""

import os

import numpy as np
import pytest

from geomesa_tpu import native
from geomesa_tpu.curve import ranges as ranges_mod
from geomesa_tpu.curve.xz2 import xz2_sfc
from geomesa_tpu.curve.xz3 import xz3_sfc

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _py_zranges(mins, maxs, dims, bits, max_ranges=None, max_levels=None):
    """Run the numpy path with the native dispatch disabled."""
    orig = native.zranges_native
    native.zranges_native = lambda *a, **k: None
    try:
        return ranges_mod.zranges(mins, maxs, dims=dims, bits=bits,
                                  max_ranges=max_ranges, max_levels=max_levels)
    finally:
        native.zranges_native = orig


def _py_xz_ranges(sfc, queries, max_ranges=None):
    orig = native.xz_ranges_native
    native.xz_ranges_native = lambda *a, **k: None
    try:
        return sfc.ranges(queries, max_ranges=max_ranges)
    finally:
        native.xz_ranges_native = orig


def test_native_loads():
    assert native.available()


@pytest.mark.parametrize("dims,bits", [(2, 31), (2, 8), (3, 21), (3, 5)])
def test_zranges_differential(dims, bits):
    rng = np.random.default_rng(1234 + dims * 100 + bits)
    hi = (1 << bits) - 1
    for trial in range(25):
        n_boxes = int(rng.integers(1, 5))
        a = rng.integers(0, hi + 1, size=(n_boxes, dims))
        b = rng.integers(0, hi + 1, size=(n_boxes, dims))
        mins, maxs = np.minimum(a, b), np.maximum(a, b)
        budget = int(rng.choice([4, 32, 2000]))
        levels = None if trial % 3 else int(rng.integers(1, bits + 1))
        got = ranges_mod.zranges(mins, maxs, dims=dims, bits=bits,
                                 max_ranges=budget, max_levels=levels)
        want = _py_zranges(mins, maxs, dims=dims, bits=bits,
                           max_ranges=budget, max_levels=levels)
        np.testing.assert_array_equal(got, want)


def test_zranges_full_domain_and_point():
    # whole domain → single range
    got = ranges_mod.zranges([[0, 0]], [[(1 << 8) - 1, (1 << 8) - 1]],
                             dims=2, bits=8)
    np.testing.assert_array_equal(got, [[0, (1 << 16) - 1]])
    # single cell
    got = ranges_mod.zranges([[3, 5]], [[3, 5]], dims=2, bits=8,
                             max_ranges=10_000)
    want = _py_zranges([[3, 5]], [[3, 5]], dims=2, bits=8, max_ranges=10_000)
    np.testing.assert_array_equal(got, want)
    assert got.shape == (1, 2)


@pytest.mark.parametrize("g", [6, 12])
def test_xz2_ranges_differential(g):
    sfc = xz2_sfc(g)
    rng = np.random.default_rng(99 + g)
    for _ in range(25):
        n = int(rng.integers(1, 4))
        x = np.sort(rng.uniform(-180, 180, size=(n, 2)), axis=1)
        y = np.sort(rng.uniform(-90, 90, size=(n, 2)), axis=1)
        queries = np.stack([x[:, 0], y[:, 0], x[:, 1], y[:, 1]], axis=1)
        budget = int(rng.choice([8, 100, 2000]))
        got = sfc.ranges(queries, max_ranges=budget)
        want = _py_xz_ranges(sfc, queries, max_ranges=budget)
        np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("g", [6, 12])
def test_xz3_ranges_differential(g):
    sfc = xz3_sfc("week", g)
    rng = np.random.default_rng(7 + g)
    zmax = sfc.z_hi
    for _ in range(20):
        n = int(rng.integers(1, 4))
        x = np.sort(rng.uniform(-180, 180, size=(n, 2)), axis=1)
        y = np.sort(rng.uniform(-90, 90, size=(n, 2)), axis=1)
        z = np.sort(rng.uniform(0, zmax, size=(n, 2)), axis=1)
        queries = np.stack(
            [x[:, 0], y[:, 0], z[:, 0], x[:, 1], y[:, 1], z[:, 1]], axis=1)
        budget = int(rng.choice([8, 100, 2000]))
        got = sfc.ranges(queries, max_ranges=budget)
        want = _py_xz_ranges(sfc, queries, max_ranges=budget)
        np.testing.assert_array_equal(got, want)


def test_env_kill_switch(monkeypatch):
    # GEOMESA_TPU_NATIVE=0 must be honored by a fresh loader state
    monkeypatch.setenv("GEOMESA_TPU_NATIVE", "0")
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", False)
    assert not native.available()
    # and zranges still works via numpy
    out = ranges_mod.zranges([[0, 0]], [[7, 7]], dims=2, bits=4)
    assert out.shape[0] >= 1
