"""Filter AST / ECQL / extraction / evaluation (reference: geomesa-filter)."""

import numpy as np
import pytest

from geomesa_tpu.features import FeatureBatch, parse_spec
from geomesa_tpu.filters import (
    And, BBox, During, Exclude, In, Include, Intersects, Like, Not, Or,
    PropertyCompare, evaluate_filter, extract_geometries, extract_intervals,
    parse_ecql, to_cnf,
)
from geomesa_tpu.filters.ecql import parse_iso_ms
from geomesa_tpu.geometry import Polygon

MS_2018 = 1514764800000


def test_parse_bbox_and_during():
    f = parse_ecql(
        "BBOX(geom, -10, 35, 15, 52) AND "
        "dtg DURING 2018-01-01T00:00:00Z/2018-01-08T00:00:00Z"
    )
    assert isinstance(f, And)
    bbox, during = f.filters
    assert isinstance(bbox, BBox) and bbox.xmin == -10 and bbox.ymax == 52
    assert isinstance(during, During)
    assert during.lo_ms == MS_2018
    assert during.hi_ms == MS_2018 + 7 * 86_400_000


def test_parse_iso():
    assert parse_iso_ms("2018-01-01T00:00:00Z") == MS_2018
    assert parse_iso_ms("2018-01-01T00:00:00.500Z") == MS_2018 + 500


def test_parse_intersects_wkt():
    f = parse_ecql("INTERSECTS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))")
    assert isinstance(f, Intersects)
    assert f.geometry.envelope.as_tuple() == (0.0, 0.0, 10.0, 10.0)


def test_parse_logic_and_comparisons():
    f = parse_ecql("name = 'alice' OR (age >= 21 AND NOT flag = 'x')")
    assert isinstance(f, Or)
    assert isinstance(f.filters[1], And)
    assert isinstance(f.filters[1].filters[1], Not)
    f2 = parse_ecql("vessel IN ('a', 'b', 'c')")
    assert isinstance(f2, In) and f2.values == ("a", "b", "c")
    f3 = parse_ecql("name LIKE 'foo%'")
    assert isinstance(f3, Like)
    assert parse_ecql("INCLUDE") is Include
    assert parse_ecql("EXCLUDE") is Exclude


def test_parse_quoted_escapes():
    f = parse_ecql("name = 'o''brien'")
    assert f.value == "o'brien"


def test_cnf():
    a = PropertyCompare("a", "=", 1)
    b = PropertyCompare("b", "=", 2)
    c = PropertyCompare("c", "=", 3)
    f = Or((And((a, b)), c))
    cnf = to_cnf(f)
    assert isinstance(cnf, And)
    for clause in cnf.filters:
        assert isinstance(clause, Or)
    # not-pushdown: ¬(a ∧ b) → ¬a ∨ ¬b
    cnf2 = to_cnf(Not(And((a, b))))
    assert isinstance(cnf2, Or)
    assert all(isinstance(p, Not) for p in cnf2.filters)


def test_extract_geometries_and():
    f = parse_ecql("BBOX(geom, 0, 0, 10, 10) AND BBOX(geom, 5, 5, 20, 20)")
    vals = extract_geometries(f, "geom")
    assert len(vals.values) == 1
    assert vals.values[0].envelope.as_tuple() == (5.0, 5.0, 10.0, 10.0)
    # disjoint AND
    f2 = parse_ecql("BBOX(geom, 0, 0, 1, 1) AND BBOX(geom, 5, 5, 6, 6)")
    assert extract_geometries(f2, "geom").disjoint


def test_extract_geometries_or():
    f = parse_ecql("BBOX(geom, 0, 0, 1, 1) OR BBOX(geom, 5, 5, 6, 6)")
    vals = extract_geometries(f, "geom")
    assert len(vals.values) == 2
    # OR with an unconstrained branch → unconstrained
    f2 = parse_ecql("BBOX(geom, 0, 0, 1, 1) OR name = 'x'")
    assert not extract_geometries(f2, "geom").values


def test_extract_intervals():
    f = parse_ecql(
        "dtg DURING 2018-01-01T00:00:00Z/2018-01-08T00:00:00Z AND dtg AFTER 2018-01-03T00:00:00Z"
    )
    vals = extract_intervals(f, "dtg")
    assert len(vals.values) == 1
    lo, hi = vals.values[0]
    assert lo == parse_iso_ms("2018-01-03T00:00:00Z") + 1
    assert hi == parse_iso_ms("2018-01-08T00:00:00Z")
    # disjoint
    f2 = parse_ecql(
        "dtg BEFORE 2018-01-01T00:00:00Z AND dtg AFTER 2018-02-01T00:00:00Z")
    assert extract_intervals(f2, "dtg").disjoint


@pytest.fixture
def batch():
    sft = parse_spec("t", "name:String,age:Int,dtg:Date,*geom:Point")
    return FeatureBatch.from_dict(
        sft,
        {
            "name": ["alice", "bob", "carol", "dave"],
            "age": [30, 17, 25, 40],
            "dtg": np.array([MS_2018, MS_2018 + 1000, MS_2018 + 2000, MS_2018 + 3000]),
            "geom": (np.array([0.0, 5.0, 20.0, 5.0]), np.array([0.0, 5.0, 20.0, 6.0])),
        },
    )


def test_evaluate_bbox(batch):
    mask = evaluate_filter(parse_ecql("BBOX(geom, -1, -1, 10, 10)"), batch)
    np.testing.assert_array_equal(mask, [True, True, False, True])


def test_evaluate_intersects_polygon(batch):
    f = parse_ecql("INTERSECTS(geom, POLYGON ((4 4, 6 4, 6 7, 4 7, 4 4)))")
    np.testing.assert_array_equal(evaluate_filter(f, batch),
                                  [False, True, False, True])


def test_evaluate_compound(batch):
    f = parse_ecql("age >= 21 AND BBOX(geom, -1, -1, 10, 10) AND name <> 'dave'")
    np.testing.assert_array_equal(evaluate_filter(f, batch),
                                  [True, False, False, False])


def test_evaluate_during(batch):
    f = parse_ecql(
        "dtg DURING 2018-01-01T00:00:01Z/2018-01-01T00:00:02Z")
    np.testing.assert_array_equal(evaluate_filter(f, batch),
                                  [False, True, True, False])


def test_evaluate_in_like_not(batch):
    np.testing.assert_array_equal(
        evaluate_filter(parse_ecql("name IN ('alice', 'dave')"), batch),
        [True, False, False, True])
    np.testing.assert_array_equal(
        evaluate_filter(parse_ecql("name LIKE 'a%'"), batch),
        [True, False, False, False])
    np.testing.assert_array_equal(
        evaluate_filter(parse_ecql("NOT name = 'bob'"), batch),
        [True, False, True, True])


def test_evaluate_polygon_batch():
    sft = parse_spec("t", "*geom:Polygon")
    polys = [
        Polygon([[0, 0], [2, 0], [2, 2], [0, 2]]),
        Polygon([[10, 10], [12, 10], [12, 12], [10, 12]]),
        Polygon([[1, 1], [3, 1], [3, 3], [1, 3]]),
    ]
    batch = FeatureBatch.from_dict(sft, {"geom": polys})
    f = parse_ecql("INTERSECTS(geom, POLYGON ((1.5 1.5, 5 1.5, 5 5, 1.5 5, 1.5 1.5)))")
    np.testing.assert_array_equal(evaluate_filter(f, batch), [True, False, True])


def test_dwithin_non_point_query_geometries():
    """DWITHIN with linestring/polygon query geometries over point
    features, and point queries over packed-geometry features."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry.types import LineString, Polygon

    ds = TpuDataStore()
    ds.create_schema("pnt", "v:Int,*geom:Point")
    ds.write("pnt", {"v": np.arange(4),
                     "geom": (np.array([0.0, 1.0, 5.0, 2.5]),
                              np.array([0.0, 1.0, 5.0, 0.0]))})
    got = ds.query("pnt", "DWITHIN(geom, LINESTRING(0 0, 2 2), 0.8)")
    assert sorted(got.column("v")) == [0, 1]
    got = ds.query("pnt",
                   "DWITHIN(geom, POLYGON((2 -1, 3 -1, 3 1, 2 1, 2 -1)), 0.6)")
    assert sorted(got.column("v")) == [3]

    ds.create_schema("gm", "v:Int,*geom:Geometry")
    ds.write("gm", {"v": np.arange(2), "geom": [
        Polygon([(0, 0), (1, 0), (1, 1), (0, 1)]),
        LineString([(10, 10), (12, 12)])]})
    assert list(ds.query("gm", "DWITHIN(geom, POINT(1.5 0.5), 0.6)")
                .column("v")) == [0]
    assert list(ds.query("gm", "DWITHIN(geom, POINT(11 10.9), 0.2)")
                .column("v")) == [1]
    # inside the polygon -> distance 0
    assert list(ds.query("gm", "DWITHIN(geom, POINT(0.5 0.5), 0.01)")
                .column("v")) == [0]


def test_dwithin_mid_segment_and_secondary_point_prop():
    """Mid-segment closest approach counts (not just vertices), and
    spatial predicates on a secondary Point property must not fall
    through to the default packed geometry column."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry.types import LineString, Polygon

    ds = TpuDataStore()
    ds.create_schema("seg", "v:Int,*geom:Geometry")
    ds.write("seg", {"v": np.arange(1),
                     "geom": [LineString([(-100, 1.4), (100, 1.4)])]})
    got = ds.query(
        "seg", "DWITHIN(geom, POLYGON((-1 0, 1 0, 1 1, -1 1, -1 0)), 0.5)")
    assert list(got.column("v")) == [0]  # true distance 0.4, mid-segment

    ds.create_schema("two", "v:Int,p:Point,*geom:Geometry")
    ds.write("two", {"v": np.arange(2),
                     "p": [(0.0, 0.0), (50.0, 50.0)],
                     "geom": [Polygon([(49, 49), (51, 49), (51, 51),
                                       (49, 51)]),
                              Polygon([(-1, -1), (1, -1), (1, 1),
                                       (-1, 1)])]})
    got = ds.query("two", "DWITHIN(p, POINT(0 0), 0.1)")
    assert list(got.column("v")) == [0]  # row whose p is at the origin
    got = ds.query("two", "BBOX(p, 40, 40, 60, 60)")
    assert list(got.column("v")) == [1]


def test_within_contains_exact_for_packed_geometries():
    """WITHIN/CONTAINS are exact (not envelope approximations): an
    L-shaped query whose envelope contains a feature must not claim
    containment when the feature pokes into the notch."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry.types import Polygon

    # L-shape covering everything except the notch [5,10]x[5,10]
    l_shape = ("POLYGON((0 0, 10 0, 10 5, 5 5, 5 10, 0 10, 0 0))")
    ds = TpuDataStore()
    ds.create_schema("w", "v:Int,*geom:Geometry")
    ds.write("w", {"v": np.arange(3), "geom": [
        Polygon([(1, 1), (2, 1), (2, 2), (1, 2)]),     # inside the L
        Polygon([(6, 6), (8, 6), (8, 8), (6, 8)]),     # inside the NOTCH
        Polygon([(4, 4), (7, 4), (7, 4.8), (4, 4.8)]),  # in lower arm
    ]})
    got = ds.query("w", f"WITHIN(geom, {l_shape})")
    assert sorted(got.column("v")) == [0, 2]  # notch square is NOT within
    # CONTAINS: which features contain a small square in the lower arm
    got = ds.query("w", "CONTAINS(geom, POLYGON((6.5 6.5, 7 6.5, 7 7, 6.5 7, 6.5 6.5)))")
    assert sorted(got.column("v")) == [1]


def test_within_rejects_hole_overlap():
    """a covering a hole of b is NOT within b (hole strictly inside a)."""
    import numpy as np
    from geomesa_tpu.geometry.predicates import geometry_within
    from geomesa_tpu.geometry.types import Polygon

    b = Polygon([(0, 0), (10, 0), (10, 10), (0, 10)],
                holes=[[(4, 4), (6, 4), (6, 6), (4, 6)]])
    a_over_hole = Polygon([(3, 3), (7, 3), (7, 7), (3, 7)])
    a_clear = Polygon([(1, 1), (3, 1), (3, 3), (1, 3)])
    assert not geometry_within(a_over_hole, b)
    assert geometry_within(a_clear, b)


def test_secondary_nonpoint_geometry_prop_raises():
    """Spatial predicates on a secondary NON-point geometry property must
    refuse (the packed column stores only the default geometry)."""
    import numpy as np
    import pytest as _pytest
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.filters.ast import DWithin, Intersects
    from geomesa_tpu.filters.evaluate import evaluate_filter
    from geomesa_tpu.geometry.types import Point, Polygon

    ds = TpuDataStore()
    ds.create_schema("sec", "v:Int,other:Geometry,*geom:Geometry")
    ds.write("sec", {"v": np.arange(1),
                     "other": [Polygon([(0, 0), (1, 0), (1, 1), (0, 1)])],
                     "geom": [Polygon([(5, 5), (6, 5), (6, 6), (5, 6)])]})
    batch = ds._store("sec").batch
    with _pytest.raises(KeyError):
        evaluate_filter(DWithin("other", Point(0.5, 0.5), 1.0), batch)
    with _pytest.raises(KeyError):
        evaluate_filter(
            Intersects("other", Polygon([(0, 0), (1, 0), (1, 1)])), batch)


def test_within_lineal_midpoint_violations():
    from geomesa_tpu.geometry.predicates import geometry_within
    from geomesa_tpu.geometry.types import LineString, Polygon

    l_path = LineString([(0, 0), (1, 0), (1, 1)])
    assert not geometry_within(LineString([(0, 0), (1, 1)]), l_path)
    assert geometry_within(LineString([(0, 0), (1, 0)]), l_path)
    # chord across the notch of an L polygon: endpoints touch, body leaves
    l_poly = Polygon([(0, 0), (10, 0), (10, 5), (5, 5), (5, 10), (0, 10)])
    assert not geometry_within(LineString([(10, 5), (5, 10)]), l_poly)
    assert geometry_within(LineString([(1, 1), (4, 4)]), l_poly)


def test_disjoint_beyond_equals():
    """DISJOINT/BEYOND as exact complements; EQUALS exact geometry match
    (the remaining ECQL spatial relations)."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.filters import evaluate_filter, parse_ecql

    rng = np.random.default_rng(4)
    n = 5000
    ds = TpuDataStore()
    ds.create_schema("pts", "name:String,*geom:Point")
    x = rng.uniform(-20, 20, n); y = rng.uniform(-20, 20, n)
    x[17], y[17] = 3.25, -4.5  # exact-equality target
    ds.write("pts", {"name": np.array(["p"] * n, object), "geom": (x, y)})

    def positions(ecql):
        return np.sort(ds.query_result("pts", ecql).positions)

    poly = "POLYGON ((-5 -5, 5 -5, 5 5, -5 5, -5 -5))"
    got_in = positions(f"INTERSECTS(geom, {poly})")
    got_out = positions(f"DISJOINT(geom, {poly})")
    assert len(got_in) + len(got_out) == n
    assert len(np.intersect1d(got_in, got_out)) == 0

    got_near = positions("DWITHIN(geom, POINT (0 0), 3.0, kilometers)")
    got_far = positions("BEYOND(geom, POINT (0 0), 3.0, kilometers)")
    assert len(got_near) + len(got_far) == n

    got_eq = positions("EQUALS(geom, POINT (3.25 -4.5))")
    assert 17 in got_eq
    want = np.flatnonzero((x == 3.25) & (y == -4.5))
    np.testing.assert_array_equal(got_eq, want)


def test_equals_polygon_packed():
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry import geometry_from_wkt

    ds = TpuDataStore()
    ds.create_schema("polys", "name:String,*geom:Polygon")
    w1 = "POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))"
    w2 = "POLYGON ((1 1, 3 1, 3 3, 1 3, 1 1))"
    ds.write("polys", {"name": np.array(["a", "b"], object),
                       "geom": [geometry_from_wkt(w1), geometry_from_wkt(w2)]})
    hits = ds.query_result("polys", f"EQUALS(geom, {w1})").positions
    np.testing.assert_array_equal(hits, [0])
    assert len(ds.query_result(
        "polys",
        "EQUALS(geom, POLYGON ((0 0, 9 0, 9 9, 0 9, 0 0)))").positions) == 0


def test_dwithin_meters_haversine_exact():
    """Units suffix means meters (reference metersMultiplier); point
    columns get the exact great-circle test."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.process.knn import haversine_m

    rng = np.random.default_rng(12)
    n = 20_000
    ds = TpuDataStore()
    ds.create_schema("p", "name:String,*geom:Point")
    x = rng.uniform(-1, 1, n); y = rng.uniform(44, 46, n)
    ds.write("p", {"name": np.array(["v"] * n, object), "geom": (x, y)})
    got = np.sort(ds.query_result(
        "p", "DWITHIN(geom, POINT (0 45), 30, kilometers)").positions)
    want = np.flatnonzero(haversine_m(0.0, 45.0, x, y) <= 30_000.0)
    np.testing.assert_array_equal(got, want)
    # 30km at lat 45 is ~0.38 deg lon; a degrees reading would match far more
    assert len(got) < np.count_nonzero(
        (np.abs(x) <= 30) & (np.abs(y - 45) <= 30))


def test_equals_topological():
    """EQUALS matches rotated ring starts and reversed orientation
    (JTS-equals semantics, not textual WKT equality)."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry import geometry_from_wkt

    ds = TpuDataStore()
    ds.create_schema("tp", "name:String,*geom:Polygon")
    ds.write("tp", {"name": np.array(["a"], object),
                    "geom": [geometry_from_wkt(
                        "POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))")]})
    # rotated start
    hits = ds.query_result(
        "tp", "EQUALS(geom, POLYGON ((2 0, 2 2, 0 2, 0 0, 2 0)))").positions
    np.testing.assert_array_equal(hits, [0])
    # reversed orientation
    hits = ds.query_result(
        "tp", "EQUALS(geom, POLYGON ((0 0, 0 2, 2 2, 2 0, 0 0)))").positions
    np.testing.assert_array_equal(hits, [0])
    # different polygon
    assert len(ds.query_result(
        "tp", "EQUALS(geom, POLYGON ((0 0, 3 0, 3 3, 0 3, 0 0)))").positions) == 0


def test_touches_crosses_overlaps_point_schema():
    """TOUCHES/CROSSES/OVERLAPS through the full store stack on point
    features: touches = boundary contact only; crosses/overlaps are
    impossible for dimension-0 features."""
    from geomesa_tpu.datastore import TpuDataStore
    ds = TpuDataStore()
    ds.create_schema("pts", "name:String,*geom:Point")
    ds.write("pts", {
        "name": np.array(["edge", "inside", "outside"], dtype=object),
        "geom": (np.array([4.0, 2.0, 9.0]), np.array([2.0, 2.0, 9.0])),
    })
    poly = "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"
    got = ds.query("pts", f"TOUCHES(geom, {poly})")
    assert list(got.column("name")) == ["edge"]
    assert len(ds.query("pts", f"CROSSES(geom, {poly})")) == 0
    assert len(ds.query("pts", f"OVERLAPS(geom, {poly})")) == 0


def test_touches_crosses_overlaps_polygon_schema():
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry import LineString, Polygon
    ds = TpuDataStore()
    ds.create_schema("areas", "name:String,*geom:Geometry")
    sq = lambda x0, y0, s: Polygon([(x0, y0), (x0 + s, y0),
                                    (x0 + s, y0 + s), (x0, y0 + s)])
    geoms = [sq(4, 0, 4),                       # shares edge with query
             sq(2, 2, 4),                       # overlaps query
             sq(1, 1, 1),                       # within query
             sq(20, 20, 2),                     # disjoint
             LineString(np.array([[-1.0, 2.0], [5.0, 2.0]]))]  # crosses
    ds.write("areas", {
        "name": np.array(["touch", "overlap", "inner", "far", "line"],
                         dtype=object),
        "geom": geoms,
    })
    q = "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"
    assert list(ds.query("areas", f"TOUCHES(geom, {q})")
                .column("name")) == ["touch"]
    assert list(ds.query("areas", f"OVERLAPS(geom, {q})")
                .column("name")) == ["overlap"]
    assert list(ds.query("areas", f"CROSSES(geom, {q})")
                .column("name")) == ["line"]
    # oracle cross-check: every new predicate result is a subset of
    # INTERSECTS
    inter = set(ds.query("areas", f"INTERSECTS(geom, {q})").column("name"))
    assert {"touch", "overlap", "inner", "line"} == inter
