"""Fused serving plane (ISSUE 17): the concurrent-client fusion
correctness matrix (bit-exact vs solo), per-tenant fairness, deadline
composition, recompile-free warm bucketing, admission interplay, and
the AdmissionGate FIFO/metrics satellites.

Named ``zz`` so the concurrency runs land late in the suite ordering,
after the correctness suites have exercised the clean solo paths.
"""

from __future__ import annotations

import gc
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import config
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.metrics import registry
from geomesa_tpu.resilience import (
    Backpressure, QueryTimeout, admission_gate,
)
from geomesa_tpu.serving import FusionScheduler, extract_fused_window
from geomesa_tpu.serving.fusion import _FuseQueue, _Member

MS_2018 = 1_514_764_800_000
DAY = 86_400_000
BBOX = "BBOX(geom,-76,39,-73,42)"

_SERVING_OPTS = ("geomesa.serving.fuse.enabled",
                 "geomesa.serving.fuse.window.ms",
                 "geomesa.serving.fuse.max.batch",
                 "geomesa.serving.tenant.queue.max",
                 "geomesa.serving.tenant.quantum")


@pytest.fixture(autouse=True)
def _clean_serving_config():
    for n in _SERVING_OPTS:
        config.clear_property(n)
    config.clear_property("geomesa.resilience.admission.max.concurrent")
    config.clear_property("geomesa.resilience.admission.queue.ms")
    gc.collect()
    admission_gate.reset()
    yield
    for n in _SERVING_OPTS:
        config.clear_property(n)
    config.clear_property("geomesa.resilience.admission.max.concurrent")
    config.clear_property("geomesa.resilience.admission.queue.ms")
    admission_gate.reset()


def _mk_store(name: str, n: int = 3000, slots: int = 256) -> TpuDataStore:
    ds = TpuDataStore()
    ds.create_schema(
        name,
        "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
        f"geomesa.lean.generation.slots={slots},"
        "geomesa.lean.compaction.factor=0")
    rng = np.random.default_rng(11)
    ds.write(name, {
        "dtg": rng.integers(MS_2018, MS_2018 + 13 * DAY, n),
        "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n))})
    return ds


def _run_threads(fns):
    errs = []

    def wrap(fn):
        try:
            fn()
        except BaseException as e:  # surfaced after join
            errs.append(e)

    ts = [threading.Thread(target=wrap, args=(fn,)) for fn in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]


# -- fused vs solo: the bit-exactness matrix -------------------------------

def test_fused_bit_exact_concurrent_matrix():
    """Mixed bbox / bbox+time / OR-of-bbox clients fused concurrently
    return exactly the positions AND rows solo execution returns."""
    ds = _mk_store("sv_m1")
    queries = [f"BBOX(geom,{-75 + i * 0.03:.2f},40,"
               f"{-74.5 + i * 0.03:.2f},41)" for i in range(6)]
    queries += [
        "BBOX(geom,-75,40,-74.5,41) AND dtg DURING "
        "2018-01-01T00:00:00Z/2018-01-05T00:00:00Z",
        "BBOX(geom,-74.7,40.2,-74.3,40.8) AND dtg DURING "
        "2018-01-03T00:00:00Z/2018-01-09T00:00:00Z",
        "BBOX(geom,-75,40,-74.8,40.5) OR BBOX(geom,-74.2,40.5,-74,41)",
    ]
    solo = [ds.query_result("sv_m1", q) for q in queries]
    fused_before = registry.counter("serving.fused.requests").count
    results: list = [None] * len(queries)

    def run(i):
        def go():
            results[i] = ds.query_fused("sv_m1", queries[i],
                                        tenant=f"t{i % 3}")
        return go

    _run_threads([run(i) for i in range(len(queries))])
    for s, r in zip(solo, results):
        assert r.strategy.index == "fused"
        np.testing.assert_array_equal(s.positions, r.positions)
        for col in s.batch.columns:
            np.testing.assert_array_equal(
                np.asarray(s.batch.columns[col]),
                np.asarray(r.batch.columns[col]))
    assert (registry.counter("serving.fused.requests").count
            - fused_before) == len(queries)


def test_fused_bit_exact_with_tombstones():
    ds = _mk_store("sv_m2")
    # lean implicit ids: row r <=> str(r)
    assert ds.delete("sv_m2", [str(r) for r in range(400)]) == 400
    q = BBOX
    solo = ds.query_result("sv_m2", q)
    results: list = [None] * 4
    _run_threads([
        (lambda i=i: results.__setitem__(
            i, ds.query_fused("sv_m2", q))) for i in range(4)])
    for r in results:
        assert r.strategy.index == "fused"
        np.testing.assert_array_equal(solo.positions, r.positions)


def test_fused_empty_riders():
    """Riders whose window contains nothing demux empty, exactly like
    solo, without perturbing the non-empty members of the batch."""
    ds = _mk_store("sv_m3")
    hit, miss = BBOX, "BBOX(geom,10,10,11,11)"
    solo_hit = ds.query_result("sv_m3", hit).positions
    results: list = [None] * 4
    qs = [hit, miss, hit, miss]
    _run_threads([
        (lambda i=i: results.__setitem__(
            i, ds.query_fused("sv_m3", qs[i]))) for i in range(4)])
    np.testing.assert_array_equal(results[0].positions, solo_hit)
    np.testing.assert_array_equal(results[2].positions, solo_hit)
    assert len(results[1].positions) == 0
    assert len(results[3].positions) == 0


def test_mixed_schema_isolation():
    """Two schemas fusing concurrently never cross-contaminate: each
    schema's compatibility key is its own coalescing queue."""
    ds = _mk_store("sv_a")
    ds.create_schema(
        "sv_b",
        "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
        "geomesa.lean.generation.slots=256,"
        "geomesa.lean.compaction.factor=0")
    rng = np.random.default_rng(7)
    nb = 1000
    ds.write("sv_b", {
        "dtg": rng.integers(MS_2018, MS_2018 + 13 * DAY, nb),
        "geom": (rng.uniform(-75, -74, nb), rng.uniform(40, 41, nb))})
    solo_a = ds.query_result("sv_a", BBOX).positions
    solo_b = ds.query_result("sv_b", BBOX).positions
    out: dict = {}
    _run_threads(
        [(lambda i=i: out.__setitem__(
            ("a", i), ds.query_fused("sv_a", BBOX))) for i in range(3)]
        + [(lambda i=i: out.__setitem__(
            ("b", i), ds.query_fused("sv_b", BBOX))) for i in range(3)])
    for i in range(3):
        np.testing.assert_array_equal(out[("a", i)].positions, solo_a)
        np.testing.assert_array_equal(out[("b", i)].positions, solo_b)


def test_incompatible_queries_bypass():
    """Interceptor-free compatibility gates: projections, sorts,
    limits, id/attribute filters, and non-lean schemas all take the
    solo path untouched."""
    ds = _mk_store("sv_byp")
    before = registry.counter("serving.bypass").count
    r = ds.query_fused("sv_byp", "INCLUDE")      # not a bbox predicate
    assert r.strategy.index != "fused"
    from geomesa_tpu.planning.planner import Query
    r = ds.query_fused("sv_byp", Query.of(BBOX, max_features=5))
    assert r.strategy.index != "fused"
    assert len(r.positions) == 5
    assert registry.counter("serving.bypass").count >= before + 2


def test_fuse_disabled_bypasses():
    ds = _mk_store("sv_off", n=500)
    config.set_property("geomesa.serving.fuse.enabled", False)
    r = ds.query_fused("sv_off", BBOX)
    assert r.strategy.index != "fused"
    assert len(r.positions) == 500


def test_extract_fused_window_shapes():
    ds = _mk_store("sv_ex", n=10)
    sft = ds.get_schema("sv_ex")
    from geomesa_tpu.filters.ast import (
        And, BBox, During, IdFilter, Include, Or,
    )
    b = BBox("geom", -75, 40, -74, 41)
    assert extract_fused_window(sft, b) == (((-75, 40, -74, 41),),
                                            None, None)
    boxes, lo, hi = extract_fused_window(
        sft, And((b, During("dtg", 5, 9))))
    assert boxes == ((-75, 40, -74, 41),) and (lo, hi) == (5, 9)
    assert extract_fused_window(
        sft, Or((b, BBox("geom", 0, 0, 1, 1))))[0] == (
        (-75, 40, -74, 41), (0, 0, 1, 1))
    assert extract_fused_window(sft, Include) is None
    assert extract_fused_window(sft, IdFilter(("x",))) is None
    assert extract_fused_window(
        sft, And((b, During("other", 1, 2)))) is None


# -- deadline composition --------------------------------------------------

def test_expired_rider_drops_without_poisoning_batch():
    """A rider whose deadline is already spent drops out before
    dispatch; live members of the same fused cycle stay bit-exact."""
    ds = _mk_store("sv_d1")
    solo = ds.query_result("sv_d1", BBOX).positions
    results: list = [None] * 3
    failures: list = []

    def live(i):
        results[i] = ds.query_fused("sv_d1", BBOX)

    def dead_raises():
        try:
            ds.query_fused("sv_d1", BBOX, timeout_ms=1e-6)
        except QueryTimeout:
            failures.append("raised")

    def dead_partial():
        r = ds.query_fused("sv_d1", BBOX, timeout_ms=1e-6,
                           partial_results=True)
        assert r.timed_out is True
        failures.append("partial")

    _run_threads([lambda: live(0), lambda: live(1), lambda: live(2),
                  dead_raises, dead_partial])
    assert sorted(failures) == ["partial", "raised"]
    for r in results:
        np.testing.assert_array_equal(solo, r.positions)
    assert admission_gate.inflight == 0


def test_fused_generous_timeout_exact():
    ds = _mk_store("sv_d2", n=500)
    r = ds.query_fused("sv_d2", BBOX, timeout_ms=60_000.0)
    assert r.timed_out is False and len(r.positions) == 500


# -- per-tenant fairness ---------------------------------------------------

def test_drr_assembly_includes_starved_tenant():
    """Deficit-round-robin batch assembly: a tenant flooding the queue
    cannot push another tenant's head-of-line request out of the
    batch, even when the flood arrived first."""
    sched = FusionScheduler()
    q = _FuseQueue()

    def enq(tenant):
        m = _Member(((0.0, 0.0, 1.0, 1.0),), tenant, None, False)
        m.enqueued_at = time.perf_counter()
        dq = q.tenants.get(tenant)
        if dq is None:
            from collections import deque
            dq = q.tenants[tenant] = deque()
            q.rr.append(tenant)
        dq.append(m)
        q.size += 1
        return m

    leader = enq("flood")
    flood = [enq("flood") for _ in range(20)]
    quiet = enq("quiet")
    batch = sched._assemble(q, leader, max_batch=8, quantum=4)
    assert len(batch) == 8
    assert quiet in batch, "flooded tenant starved the quiet one"
    # the flood still gets the lion's share of the batch
    assert sum(1 for m in batch if m.tenant == "flood") == 7
    # FIFO within a tenant: the flood's earliest riders ride first
    assert all(m in batch for m in flood[:6])


def test_tenant_queue_ceiling_sheds():
    """A tenant at its queue.max ceiling sheds Backpressure instead of
    growing the queue; other tenants are unaffected."""
    config.set_property("geomesa.serving.tenant.queue.max", 1)
    config.set_property("geomesa.serving.fuse.window.ms", 1000.0)
    config.set_property("geomesa.serving.fuse.max.batch", 64)
    sched = FusionScheduler()
    n_done = []

    def dispatch(ws):
        return [np.empty(0, dtype=np.int64) for _ in ws]

    def leader():
        sched.submit(("k",), ((0, 0, 1, 1),), dispatch, tenant="hot",
                     schema="s")
        n_done.append("leader")

    t = threading.Thread(target=leader)
    t.start()
    deadline = time.time() + 5.0
    while sched.queued == 0 and time.time() < deadline:
        time.sleep(0.002)
    shed_before = registry.counter("serving.tenant.shed").count
    with pytest.raises(Backpressure):
        sched.submit(("k",), ((0, 0, 1, 1),), dispatch, tenant="hot",
                     schema="s")
    assert registry.counter("serving.tenant.shed").count == \
        shed_before + 1
    assert registry.counter("serving.tenant.shed.hot").count >= 1
    # a different tenant still enters the same batch
    ok = []

    def other():
        sched.submit(("k",), ((0, 0, 1, 1),), dispatch, tenant="cool",
                     schema="s")
        ok.append(True)

    t2 = threading.Thread(target=other)
    t2.start()
    t.join(10)
    t2.join(10)
    assert n_done == ["leader"] and ok == [True]


# -- warm-path recompile & token hygiene -----------------------------------

def test_warm_fused_path_recompile_free():
    """Capacity bucketing: batch sizes pad to powers of two, so once a
    bucket is warm re-dispatching ANY size in it is recompile-free."""
    from geomesa_tpu.obs import compile_count
    ds = _mk_store("sv_w1")
    w = (((-75.0, 40.0, -74.0, 41.0),), MS_2018, MS_2018 + 13 * DAY)
    solo = ds.query_result(
        "sv_w1", "BBOX(geom,-75,40,-74,41) AND dtg DURING "
        "2018-01-01T00:00:00Z/2018-01-14T00:00:00Z").positions
    # warm the 1-, 2- and 4-window buckets
    for n in (1, 2, 3, 4):
        ds._fused_windows_dispatch("sv_w1", [w] * n)
    before = compile_count()
    for n in (1, 2, 3, 4):
        hits = ds._fused_windows_dispatch("sv_w1", [w] * n)
        assert len(hits) == n
        for h in hits:
            np.testing.assert_array_equal(h, solo)
    assert compile_count() == before, "warm fused path recompiled"


def test_no_leaked_admission_tokens_across_fused_cycles():
    """100 fused cycles (mixed solo/concurrent, expired riders, empty
    windows) leave the admission gate at zero in-flight."""
    ds = _mk_store("sv_t1", n=800)
    for i in range(40):
        ds.query_fused("sv_t1", BBOX, tenant=f"t{i % 4}")
    for _ in range(20):
        _run_threads([
            lambda: ds.query_fused("sv_t1", BBOX),
            lambda: ds.query_fused("sv_t1", "BBOX(geom,10,10,11,11)"),
            lambda: ds.query_fused("sv_t1", BBOX, timeout_ms=1e-6,
                                   partial_results=True),
        ])
    assert admission_gate.inflight == 0
    assert ds._fusion.queued == 0


# -- AdmissionGate satellites ----------------------------------------------

def test_admission_fifo_ticket_ordering():
    """Queued acquires admit in ARRIVAL order: a late arrival cannot
    barge past long-queued waiters when a slot frees (satellite pin)."""
    config.set_property("geomesa.resilience.admission.max.concurrent", 1)
    config.set_property("geomesa.resilience.admission.queue.ms", 30_000.0)
    admission_gate.reset()
    first = admission_gate.acquire("fifo")
    order: list = []
    lock = threading.Lock()
    started = threading.Semaphore(0)

    def waiter(i):
        started.release()
        tok = admission_gate.acquire("fifo")
        with lock:
            order.append(i)
        time.sleep(0.002)
        tok.release()

    threads = []
    for i in range(5):
        t = threading.Thread(target=waiter, args=(i,))
        threads.append(t)
        t.start()
        started.acquire()
        # the waiter thread has STARTED; give it time to enqueue its
        # ticket before the next one starts, so arrival order is known
        for _ in range(200):
            if admission_gate._ticket_count() >= i + 1:
                break
            time.sleep(0.001)
    first.release()
    for t in threads:
        t.join(30)
    assert order == [0, 1, 2, 3, 4]
    assert admission_gate.inflight == 0


def test_disabled_gate_records_admission_metrics():
    """Satellite pin: the disabled-gate fast path counts
    resilience.admission.admitted and samples the queue timer, so
    dashboards don't undercount when the gate is off."""
    admission_gate.reset()
    admitted = registry.counter("resilience.admission.admitted").count
    timer_n = registry.timer("resilience.admission.queue_ms").count
    tok = admission_gate.acquire("off")
    try:
        assert registry.counter(
            "resilience.admission.admitted").count == admitted + 1
        assert registry.timer(
            "resilience.admission.queue_ms").count == timer_n + 1
    finally:
        tok.release()
    assert admission_gate.inflight == 0


def test_serving_metrics_visible_in_prom():
    """The serving.* family is scrapeable at /metrics.prom."""
    ds = _mk_store("sv_p1", n=500)
    _run_threads([
        (lambda: ds.query_fused("sv_p1", BBOX)) for _ in range(3)])
    from geomesa_tpu.obs import prometheus_text
    text = prometheus_text(registry.snapshot())
    assert "serving_fused_batches" in text or \
        "serving.fused.batches" in text
    assert "serving_fanin" in text or "serving.fanin" in text
    assert "serving_coalesce_ms" in text or \
        "serving.coalesce_ms" in text
