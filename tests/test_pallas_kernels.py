"""Pallas kernels vs XLA/NumPy oracles (interpret mode on CPU)."""

import numpy as np
import pytest

from geomesa_tpu.curve import TimePeriod, max_offset, z3_sfc
from geomesa_tpu.ops.density import density_grid
from geomesa_tpu.ops.pallas_kernels import density_grid_pallas, z3_mask_pallas


@pytest.mark.parametrize("n,w,h", [(1000, 32, 32), (5000, 64, 48), (100, 7, 5)])
def test_density_pallas_matches_xla(n, w, h):
    rng = np.random.default_rng(n)
    x = rng.uniform(-10, 10, n)
    y = rng.uniform(-5, 5, n)
    wts = rng.uniform(0.5, 2.0, n)
    mask = rng.random(n) > 0.3
    env = (-10.0, -5.0, 10.0, 5.0)

    ref = np.asarray(density_grid(x, y, wts, mask, env, w, h))
    got = np.asarray(density_grid_pallas(x, y, wts, mask, env, w, h))
    assert got.shape == (h, w)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # total mass conserved
    np.testing.assert_allclose(got.sum(), wts[mask].sum(), rtol=1e-5)


def test_density_pallas_empty_mask():
    n = 256
    x = np.zeros(n)
    y = np.zeros(n)
    got = np.asarray(
        density_grid_pallas(x, y, np.ones(n), np.zeros(n, bool),
                            (-1.0, -1.0, 1.0, 1.0), 16, 16))
    assert got.sum() == 0


def test_z3_mask_pallas_matches_oracle():
    rng = np.random.default_rng(7)
    n = 3000
    sfc = z3_sfc(TimePeriod.WEEK)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.uniform(0, float(max_offset(TimePeriod.WEEK)), n)
    z = np.asarray(sfc.index(x, y, t, xp=np)).astype(np.int64)

    boxes = [(-60.0, -30.0, 20.0, 40.0), (100.0, 10.0, 140.0, 55.0)]
    ixy = np.array(
        [
            [
                sfc.lon.normalize_scalar(b[0]), sfc.lat.normalize_scalar(b[1]),
                sfc.lon.normalize_scalar(b[2]), sfc.lat.normalize_scalar(b[3]),
            ]
            for b in boxes
        ],
        dtype=np.int32,
    )
    it = np.asarray(sfc.time.normalize(t, xp=np)).astype(np.int64)
    tlo = np.full(n, int(it.min() + 5), np.int32)
    thi = np.full(n, int(it.max() - 5), np.int32)

    got = np.asarray(z3_mask_pallas(z, ixy, tlo, thi))

    ix = np.asarray(sfc.lon.normalize(x, xp=np)).astype(np.int64)
    iy = np.asarray(sfc.lat.normalize(y, xp=np)).astype(np.int64)
    in_box = np.zeros(n, bool)
    for b in ixy:
        in_box |= (ix >= b[0]) & (iy >= b[1]) & (ix <= b[2]) & (iy <= b[3])
    want = in_box & (it >= tlo) & (it <= thi)
    assert want.any() and not want.all()
    np.testing.assert_array_equal(got, want)


def test_density_sorted_matches_scatter():
    """Sort-based segment-sum histogram vs the XLA scatter oracle,
    weighted + masked."""
    import numpy as np
    import jax.numpy as jnp

    from geomesa_tpu.ops.density import density_grid, density_grid_sorted

    rng = np.random.default_rng(77)
    n = 50_000
    x = jnp.asarray(rng.uniform(-180, 180, n))
    y = jnp.asarray(rng.uniform(-90, 90, n))
    w = jnp.asarray(rng.uniform(0, 2, n).astype(np.float32))
    m = jnp.asarray(rng.random(n) < 0.7)
    env = (-180.0, -90.0, 180.0, 90.0)
    a = np.asarray(density_grid(x, y, w, m, env, 64, 32))
    b = np.asarray(density_grid_sorted(x, y, w, m, env, 64, 32))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-4)
    # all-masked edge
    b0 = np.asarray(density_grid_sorted(
        x, y, w, jnp.zeros(n, bool), env, 64, 32))
    assert b0.sum() == 0


def test_z2_mask_pallas_oracle():
    """Fused z2 decode + R-box mask == the XLA int-space test (round-3
    next #8 kernel #1)."""
    import numpy as np
    import jax.numpy as jnp
    from geomesa_tpu.curve.zorder import interleave2
    from geomesa_tpu.ops.pallas_kernels import z2_mask_pallas

    rng = np.random.default_rng(12)
    n = 50_000
    ix = rng.integers(0, 1 << 31, n).astype(np.int64)
    iy = rng.integers(0, 1 << 31, n).astype(np.int64)
    z = np.asarray(interleave2(ix, iy, xp=np)).astype(np.int64)
    boxes = np.array([[1 << 29, 1 << 28, 3 << 29, 3 << 29],
                      [0, 0, 1 << 27, 1 << 27]], dtype=np.int32)
    got = np.asarray(z2_mask_pallas(jnp.asarray(z), boxes))
    want = np.zeros(n, bool)
    for b in boxes:
        want |= (ix >= b[0]) & (iy >= b[1]) & (ix <= b[2]) & (iy <= b[3])
    np.testing.assert_array_equal(got, want)


def test_hist1d_pallas_oracle():
    """MXU one-hot 1-D histogram == bincount (exact for unit weights;
    round-3 next #8 kernel #2)."""
    import numpy as np
    import jax.numpy as jnp
    from geomesa_tpu.ops.pallas_kernels import hist1d_pallas

    rng = np.random.default_rng(13)
    n = 40_000
    vals = rng.integers(0, 100, n)
    mask = rng.random(n) > 0.25
    got = np.asarray(hist1d_pallas(
        jnp.asarray(vals), jnp.ones(n, dtype=jnp.float32),
        jnp.asarray(mask), 100))
    want = np.bincount(vals[mask], minlength=100).astype(np.float32)
    np.testing.assert_array_equal(got, want)
    # weighted: f32 accumulation order differs — tolerance-bounded
    w = rng.uniform(0, 3, n)
    got = np.asarray(hist1d_pallas(
        jnp.asarray(vals), jnp.asarray(w, dtype=jnp.float32),
        jnp.asarray(mask), 100))
    want = np.bincount(vals[mask], weights=w[mask], minlength=100)
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=3e-4)
