"""gm-lint static analysis (ISSUE 13): the analyzer framework, its
five checks against known-bad fixtures (findings asserted exactly),
pragma/baseline round trips, the CLEAN-TREE gate over geomesa_tpu/
(this file IS the tier-1 wiring — 'zzzz' collects after everything),
the jax-free import contract, the strict-option runtime mode, and
pinned regression tests for the genuine violations the checks
surfaced (missing device_span wrappers, unlocked shared obs state).
"""

import json
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from geomesa_tpu.analysis import (
    Baseline, BaselineError, all_checks, analyze,
)
from geomesa_tpu.analysis.baseline import DEFAULT_BASELINE_PATH
from geomesa_tpu.analysis.checks import check_by_id
from geomesa_tpu.analysis.walker import PACKAGE_ROOT

FIXTURES = Path(__file__).parent / "data" / "lint_fixtures"

MS = 1514764800000
DAY = 86_400_000
WORLD = (-180.0, -90.0, 180.0, 90.0)


def _fixture_findings(name: str, check_id: str):
    return analyze(FIXTURES, checks=[check_by_id(check_id)],
                   files=[FIXTURES / name])


# -- per-check fixture exactness ------------------------------------------
def test_host_sync_fixture_exact():
    got = _fixture_findings("fixture_host_sync.py", "host-sync")
    assert [(f.line, f.check_id) for f in got] == [
        (23, "host-sync"),   # .item()
        (27, "host-sync"),   # block_until_ready
        (31, "host-sync"),   # np.asarray on jitted call
        (35, "host-sync"),   # np.asarray through a jit-builder
        (39, "host-sync"),   # int() on a jnp expression
    ]
    msgs = "\n".join(f.message for f in got)
    assert ".item()" in msgs and "block_until_ready" in msgs
    # messages name the enclosing function — the line-independent
    # baseline key must be unique per violation site
    assert "(in `bad_item`)" in got[0].message
    assert "(in `bad_block`)" in got[1].message
    # the device_span block and the pragma'd line stayed silent
    assert not [f for f in got if f.line > 41]


def test_recompile_hazard_fixture_exact():
    got = _fixture_findings("fixture_recompile.py", "recompile-hazard")
    assert [(f.line, f.check_id) for f in got] == [
        (17, "recompile-hazard"),   # mutable-global capture
        (21, "recompile-hazard"),   # unhashable static default
        (31, "recompile-hazard"),   # unhashable static call value
        (32, "recompile-hazard"),   # per-call-varying static value
        (33, "recompile-hazard"),   # unhashable POSITIONAL static
        (34, "recompile-hazard"),   # varying POSITIONAL static
    ]
    assert "closes over module global `_MUTABLE_TABLE`" in got[0].message
    assert "varies per call" in got[3].message
    # positional args map through static_argnums-resolved names
    assert "static argument `k`" in got[4].message
    assert "varies per call" in got[5].message


def test_guarded_by_fixture_exact():
    got = _fixture_findings("fixture_guarded.py", "guarded-by")
    assert [(f.line, f.check_id) for f in got] == [
        (17, "guarded-by"),   # unlocked read
        (26, "guarded-by"),   # touch after the with block closed
    ]
    # the locked write, __init__, and the `holds:` method stayed silent
    assert all("bad_" in f.message for f in got)


def test_config_option_fixture_exact():
    got = _fixture_findings("fixture_options.py", "config-option")
    assert [(f.line, f.check_id) for f in got] == [
        (4, "config-option"), (8, "config-option"),
    ]
    assert all("not declared in config.py" in f.message for f in got)


def test_taxonomy_fixture_exact():
    got = _fixture_findings("fixture_taxonomy.py", "taxonomy")
    assert [(f.line, f.check_id) for f in got] == [
        (8, "taxonomy"),    # metric namespace typo
        (10, "taxonomy"),   # obs_count namespace typo
        (11, "taxonomy"),   # span outside the documented taxonomy
    ]
    assert "lena.compaction.merges" in got[0].message
    assert "span taxonomy" in got[2].message


def test_taxonomy_skips_dynamic_prefix(tmp_path):
    """A metric name whose FIRST segment is an unresolvable f-string
    hole (f"{prefix}.hits") is out of static reach — skipped, not
    flagged as a namespace violation (the runtime walk covers it)."""
    (tmp_path / "dyn.py").write_text(
        "from geomesa_tpu.metrics import registry\n"
        "\n"
        "\n"
        "def emit(prefix):\n"
        '    registry.counter(f"{prefix}.hits").inc()\n')
    got = analyze(tmp_path, checks=[check_by_id("taxonomy")],
                  files=[tmp_path / "dyn.py"])
    assert got == [], [f.render() for f in got]


# -- pragmas --------------------------------------------------------------
def test_pragma_same_line_standalone_and_file(tmp_path):
    bad = 'OPTION = "geomesa.not.a.real.option"\n'
    (tmp_path / "plain.py").write_text(bad)
    (tmp_path / "sameline.py").write_text(
        'OPTION = "geomesa.not.a.real.option"'
        "  # gm-lint: disable=config-option fixture reason\n")
    (tmp_path / "above.py").write_text(
        "# gm-lint: disable=config-option fixture reason\n" + bad)
    (tmp_path / "whole.py").write_text(
        "# gm-lint: disable-file=config-option fixture reason\n"
        + bad + bad)
    check = [check_by_id("config-option")]
    assert len(analyze(tmp_path, checks=check,
                       files=[tmp_path / "plain.py"])) == 1
    for name in ("sameline.py", "above.py", "whole.py"):
        assert analyze(tmp_path, checks=check,
                       files=[tmp_path / name]) == [], name
    # a pragma for a DIFFERENT check suppresses nothing
    (tmp_path / "wrong.py").write_text(
        'OPTION = "geomesa.not.a.real.option"'
        "  # gm-lint: disable=host-sync wrong check\n")
    assert len(analyze(tmp_path, checks=check,
                       files=[tmp_path / "wrong.py"])) == 1


def test_pragma_in_docstring_is_not_a_pragma(tmp_path):
    """Pragma syntax QUOTED in a docstring (e.g. documentation of the
    pragma grammar itself) must suppress nothing — only real comment
    tokens are pragmas."""
    (tmp_path / "doc.py").write_text(
        '"""Suppress with `# gm-lint: disable-file=config-option`.\n'
        '"""\n'
        'OPTION = "geomesa.not.a.real.option"\n')
    findings = analyze(tmp_path, checks=[check_by_id("config-option")],
                       files=[tmp_path / "doc.py"])
    assert [f.line for f in findings] == [3]


def test_import_edges_resolve_through_package_init(tmp_path):
    """Relative imports inside a package ``__init__`` resolve to the
    package's OWN submodules (``from .kern import fast``), so device
    dispatches re-exported there are known to host-sync — a package
    __init__'s modname is the package, not a sibling module."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "kern.py").write_text(
        "import jax\n\n@jax.jit\ndef fast(z):\n    return z\n")
    (pkg / "__init__.py").write_text(
        "import numpy as np\n"
        "from .kern import fast\n\n\n"
        "def use(z):\n"
        "    return np.asarray(fast(z))\n")
    findings = analyze(tmp_path, checks=[check_by_id("host-sync")])
    assert [(f.file, f.line) for f in findings] == [("pkg/__init__.py", 6)]


# -- baseline -------------------------------------------------------------
def test_baseline_round_trip(tmp_path):
    findings = _fixture_findings("fixture_options.py", "config-option")
    assert findings
    ledger = Baseline.from_findings(findings, "fixture debt, tracked")
    path = tmp_path / "baseline.json"
    ledger.save(path)
    loaded = Baseline.load(path)
    new, baselined, stale = loaded.split(findings)
    assert new == [] and len(baselined) == len(findings) and stale == []
    # baselines match on (check, file, message) — line drift is fine
    drifted = [type(f)(f.file, f.line + 40, f.check_id, f.message)
               for f in findings]
    assert loaded.split(drifted)[0] == []


def test_baseline_rejects_missing_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "entries": [
        {"check": "host-sync", "file": "x.py", "message": "m",
         "justification": "  "}]}))
    with pytest.raises(BaselineError, match="justification"):
        Baseline.load(path)


@pytest.fixture()
def tree_findings(gm_lint_tree):
    """The session-scoped full-tree pass (tests/conftest.py) — shared
    with the metric-lint delegation test; the CLI tests still run
    their own subprocess passes, that IS what they test."""
    return gm_lint_tree


def test_baseline_does_not_absorb_new_identical_violation(tmp_path):
    """The line-independent key must not grandfather a NEW violation
    of the same class in the same file: site-qualified messages keep
    each key unique, so only the baselined function stays quiet."""
    (tmp_path / "m.py").write_text(
        "import jax\n\n\ndef a(x):\n    jax.block_until_ready(x)\n\n\n"
        "def b(x):\n    jax.block_until_ready(x)\n")
    found = analyze(tmp_path, checks=[check_by_id("host-sync")],
                    files=[tmp_path / "m.py"])
    assert len(found) == 2 and found[0].message != found[1].message
    ledger = Baseline.from_findings([found[0]], "tracked fixture debt")
    new, baselined, _ = ledger.split(found)
    assert new == [found[1]] and baselined == [found[0]]


def test_guarded_by_decl_is_comment_token_and_binds_by_ast(tmp_path):
    """A docstring QUOTING the guarded-by grammar declares nothing,
    and a real declaration binds to the next self-assignment however
    long its comment block runs (the old 4-line window dropped it)."""
    (tmp_path / "t.py").write_text(
        "import threading\n"
        "\n"
        "\n"
        "class T:\n"
        '    """Docs quote `#: guarded-by: self._lock` harmlessly."""\n'
        "\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        #: guarded-by: self._lock — a long explanation that\n"
        "        #: runs across several comment lines before the\n"
        "        #: attribute assignment, more than the old four-line\n"
        "        #: window ever allowed, and still binds\n"
        "        self._entries = {}\n"
        "        self._other = {}\n"
        "\n"
        "    def bad(self):\n"
        "        return len(self._entries)\n"
        "\n"
        "    def fine(self):\n"
        "        return len(self._other)\n")
    got = analyze(tmp_path, checks=[check_by_id("guarded-by")],
                  files=[tmp_path / "t.py"])
    assert [f.line for f in got] == [17], [f.render() for f in got]


def test_committed_baseline_entries_all_justified_and_live(tree_findings):
    ledger = Baseline.load()          # raises on unjustified entries
    for (check, file, _msg), just in ledger.entries.items():
        assert len(just) > 20, (check, file)
    # no stale debt: every committed entry still matches a finding
    assert ledger.split(tree_findings[0])[2] == []


# -- the clean-tree tier-1 gate -------------------------------------------
def test_tree_clean_and_fast(tree_findings):
    """Zero unbaselined findings over geomesa_tpu/ — and the analyzer
    stays well under the 10 s budget so tier-1 wall time is safe."""
    findings, elapsed = tree_findings
    new, baselined, _stale = Baseline.load().split(findings)
    assert new == [], "\n".join(f.render() for f in new)
    assert baselined, "expected the documented block() baseline entries"
    print(f"\ngm-lint: {len(findings)} finding(s) "
          f"({len(baselined)} baselined) over geomesa_tpu/ "
          f"in {elapsed:.2f}s")
    assert elapsed < 10.0, f"analyzer took {elapsed:.2f}s (budget 10s)"


# -- CLI ------------------------------------------------------------------
def _cli(*args, cwd=None):
    return subprocess.run(
        [sys.executable, "-m", "geomesa_tpu.analysis", *args],
        capture_output=True, text=True, cwd=cwd,
        timeout=120)


def test_cli_fail_on_new_clean_tree_exits_zero():
    proc = _cli("--fail-on-new")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_single_file_matches_baseline():
    """A bare file argument reports package-root-relative paths (the
    baseline key space): `--fail-on-new` on a file whose only finding
    is baselined exits 0, and the finding file is index/z3_lean.py,
    not '.' — the single-file CLI regression."""
    target = PACKAGE_ROOT / "index" / "z3_lean.py"
    proc = _cli("--check", "host-sync", "--format", "json", str(target))
    out = json.loads(proc.stdout)
    reported = {f["file"] for f in out["findings"]}
    assert reported <= {"index/z3_lean.py"}, reported
    proc = _cli("--fail-on-new", "--check", "host-sync", str(target))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # a SUBPACKAGE directory re-roots the same way
    proc = _cli("--fail-on-new", str(PACKAGE_ROOT / "index"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # a subset run must not call unmatched ledger entries stale
    assert "stale" not in proc.stdout


def test_cli_analyzer_own_tree_is_loudly_excluded():
    """Pointing the CLI at the analyzer's own package is a usage
    error (exit 2 + message), never a silent 0-finding 'clean'."""
    target = PACKAGE_ROOT / "analysis" / "walker.py"
    proc = _cli(str(target))
    assert proc.returncode == 2
    assert "excluded" in proc.stderr


def test_cli_findings_exit_one_and_json_format():
    proc = _cli("--check", "config-option", "--format", "json",
                str(FIXTURES / "fixture_options.py"))
    assert proc.returncode == 1
    out = json.loads(proc.stdout)
    assert out["checks"] == ["config-option"]
    assert [f["line"] for f in out["findings"]] == [4, 8]
    assert all(f["check"] == "config-option" for f in out["findings"])
    assert out["elapsed_s"] >= 0


def test_cli_list_checks_and_unknown_check():
    proc = _cli("--list-checks")
    assert proc.returncode == 0
    for check in all_checks():
        assert check.id in proc.stdout
    assert _cli("--check", "nope").returncode == 2


def test_cli_survives_ascii_locale():
    """Cold-CI shards may run under LC_ALL=C: every analyzer file read
    pins encoding='utf-8', so non-ASCII in sources/baseline (em
    dashes) must not crash the gate."""
    import os
    env = dict(os.environ, LC_ALL="C", LANG="C",
               PYTHONCOERCECLOCALE="0", PYTHONUTF8="0")
    proc = subprocess.run(
        [sys.executable, "-m", "geomesa_tpu.analysis", "--fail-on-new"],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_analyzer_import_is_jax_free():
    """The cold-CI contract (ISSUE 13 satellite): importing and
    running the analyzer never pulls in jax or numpy — pure ast."""
    code = ("import sys; import geomesa_tpu.analysis as a; "
            "from geomesa_tpu.analysis.checks import CHECKS; "
            "assert len(CHECKS) == 6; "
            "assert 'jax' not in sys.modules, 'jax imported'; "
            "assert 'numpy' not in sys.modules, 'numpy imported'; "
            "print('ok')")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "ok"


# -- strict-option runtime mode (ISSUE 13 satellite) ----------------------
def test_set_property_warns_on_unregistered_name():
    from geomesa_tpu import config
    config._warned.discard("geomesa.lean.compactoin.factor")
    with pytest.warns(config.UnknownOptionWarning, match="compactoin"):
        config.set_property("geomesa.lean.compactoin.factor", 2)
    config.clear_property("geomesa.lean.compactoin.factor")
    # registered names and non-geomesa names stay silent
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        config.set_property("geomesa.scan.ranges.target", 2000)
        config.clear_property("geomesa.scan.ranges.target")
        config.set_property("myapp.private.knob", 1)
        config.clear_property("myapp.private.knob")


def test_strict_mode_raises_on_typo():
    from geomesa_tpu import config
    config.set_property("geomesa.config.strict", True)
    try:
        with pytest.raises(ValueError, match="unregistered option"):
            config.set_property("geomesa.lean.compaction.factr", 0)
        # ad-hoc SystemProperty lookup hits the same gate
        with pytest.raises(ValueError, match="unregistered option"):
            config.SystemProperty("geomesa.nope.nope", 1).get()
        # clearing is inherently safe: a stale typo'd override must be
        # removable WHILE strict is on (warns, never raises)
        config._warned.discard("geomesa.lean.compaction.factr")
        with pytest.warns(config.UnknownOptionWarning):
            config.clear_property("geomesa.lean.compaction.factr")
    finally:
        config.clear_property("geomesa.config.strict")


def test_known_option_names_cover_declarations():
    from geomesa_tpu import config
    names = config.known_option_names()
    assert {"geomesa.scan.ranges.target", "geomesa.obs.enabled",
            "geomesa.index.profile", "geomesa.lean.hbm.budget",
            "geomesa.config.strict"} <= names


# -- pinned regressions for the violations the checks surfaced ------------
def test_density_sweep_dispatch_is_traced_device_span():
    """The whole-extent density sweep used to materialize its device
    dispatch OUTSIDE device_span (unattributed sync — the exact
    host-sync class).  Pin: the sweep emits a query.scan.device span
    with stage=sweep and real device_ms, rolled up to the root."""
    from geomesa_tpu import obs
    from geomesa_tpu.index.z3_lean import LeanZ3Index
    rng = np.random.default_rng(31)
    idx = LeanZ3Index(period="week", generation_slots=4096,
                      payload_on_device=False)
    idx.append(rng.uniform(-75, -73, 4096), rng.uniform(40, 42, 4096),
               rng.integers(MS, MS + 14 * DAY, 4096))
    idx.block()
    with obs.tracer.capture() as cap:
        with obs.span("query"):
            idx.density([WORLD], None, None, WORLD, 64, 32)
    traces = cap.traces()
    assert traces
    sweep = [s for t in traces for s in t.spans
             if s.name == "query.scan.device"
             and s.attributes.get("stage") == "sweep"]
    assert sweep, "sweep dispatch lost its device_span again"
    assert all(s.attributes["device_ms"] >= 0 for s in sweep)
    root = traces[-1].root_span
    assert root.attributes.get("device_ms", 0) > 0


def test_sharded_cells_dispatch_is_traced_device_span():
    """Same class of fix in the sharded z3_cell_counts fold: the
    _cells_program dispatch now runs under device_span."""
    from geomesa_tpu import obs
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.lean import ShardedLeanZ3Index
    rng = np.random.default_rng(32)
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=1 << 13)
    idx.append(rng.uniform(-75, -73, 8192), rng.uniform(40, 42, 8192),
               rng.integers(MS, MS + 14 * DAY, 8192))
    with obs.tracer.capture() as cap:
        with obs.span("query"):
            counts = idx.z3_cell_counts(4)
    assert counts
    cells = [s for t in cap.traces() for s in t.spans
             if s.name == "query.scan.device"
             and s.attributes.get("stage") == "z3_cells"]
    assert cells, "sharded z3_cells dispatch lost its device_span again"


def test_full_fat_packed_scan_is_traced_device_span():
    """The full-fat z2/z3 packed scans dispatched outside device_span
    too (the lean families were instrumented in PR 3, these were not).
    Pin: a Z3PointIndex query emits a query.scan.device span with
    stage=packed."""
    from geomesa_tpu import obs
    from geomesa_tpu.curve import TimePeriod
    from geomesa_tpu.index import Z3PointIndex
    rng = np.random.default_rng(33)
    idx = Z3PointIndex.build(
        rng.uniform(-75, -73, 4096), rng.uniform(40, 42, 4096),
        rng.integers(MS, MS + 14 * DAY, 4096), period=TimePeriod.WEEK)
    with obs.tracer.capture() as cap:
        with obs.span("query"):
            idx.query([(-74.5, 40.5, -73.5, 41.5)],
                      MS + 2 * DAY, MS + 9 * DAY)
    packed = [s for t in cap.traces() for s in t.spans
              if s.name == "query.scan.device"
              and s.attributes.get("stage") in ("packed", "two_phase")]
    assert packed, "full-fat scan dispatch lost its device_span again"
    # device_ms must be REAL: the XLA-fallback thunk materializes
    # inside the span (a lazy return would attribute ~0 and block in
    # run_packed_query instead)
    assert all(s.attributes.get("device_ms", -1) >= 0 for s in packed)
    # the batched-windows dispatch is instrumented too (stage
    # packed_many — it was the one un-instrumented full-fat site)
    with obs.tracer.capture() as cap:
        with obs.span("query"):
            idx.query_many([([(-74.5, 40.5, -73.5, 41.5)],
                             MS + 2 * DAY, MS + 9 * DAY),
                            ([(-74.2, 40.8, -73.8, 41.2)],
                             MS, MS + 5 * DAY)])
    many = [s for t in cap.traces() for s in t.spans
            if s.name == "query.scan.device"
            and s.attributes.get("stage") == "packed_many"]
    assert many, "query_many dispatch lost its device_span again"


def test_periodic_reporter_start_stop_race_safe():
    """PeriodicReporter._thread is guarded now: concurrent
    start()/stop() storms must end with the reporter fully stopped
    and at most one daemon ever live."""
    from geomesa_tpu.metrics import MetricRegistry, PeriodicReporter

    class Sink:
        def report(self):
            pass

    rep = PeriodicReporter(Sink(), interval_s=30.0)
    errors = []

    def storm():
        try:
            for _ in range(50):
                rep.start()
                rep.stop(final_report=False)
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    threads = [threading.Thread(target=storm) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rep.stop(final_report=False)
    assert errors == []
    assert rep._thread is None


def test_partial_cache_concurrent_access_safe():
    """PartialCache._specs is lock-guarded now: query threads touching
    specs while a scraper walks stats() must never corrupt the LRU or
    raise (dict-changed-size — the pre-fix failure mode)."""
    from geomesa_tpu.index.partial_cache import PartialCache

    class Part:
        nbytes = 64

    pc = PartialCache(max_specs=4, max_bytes=4096)
    errors = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for i in range(400):
                spec = ("spec", int(rng.integers(0, 8)))
                cache = pc.spec_cache(spec)
                pc.add(cache, i, Part())
                pc.stats()
                pc.cached_bytes()
                if i % 50 == 0:
                    pc.drop_generations(range(i))
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert len(pc) <= 4
    assert pc.stats()["bytes"] <= 4096 + 64 * 6  # ceiling, ± in-flight


def test_write_baseline_refuses_subsets_and_keeps_justifications(tmp_path):
    """--write-baseline on a --check/path subset is a usage error (it
    would silently drop every entry the subset cannot see); a full-run
    rewrite preserves each existing entry's written justification."""
    import shutil
    path = tmp_path / "b.json"
    proc = _cli("--check", "taxonomy", "--write-baseline", "r",
                "--baseline", str(path))
    assert proc.returncode == 2 and not path.exists()
    shutil.copy(DEFAULT_BASELINE_PATH, path)
    proc = _cli("--write-baseline", "generic new-entry reason",
                "--baseline", str(path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    entries = json.loads(path.read_text())["entries"]
    assert entries, "full run lost the block() entries"
    assert all("ingest-timing barrier" in e["justification"]
               for e in entries), "justifications were flattened"


def test_recompile_positional_mapping_stops_at_star(tmp_path):
    """Positions past a *splat are statically unknowable — they must
    not be mis-mapped onto parameter names (false positives on calls
    like `f(*args, capacity=...)`)."""
    (tmp_path / "s.py").write_text(
        "import functools\n"
        "\n"
        "import jax\n"
        "\n"
        "\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def scale(x, k):\n"
        "    return x * k\n"
        "\n"
        "\n"
        "def caller(xs, x):\n"
        "    scale(*xs, [1, 2])\n"
        "    return scale(x, [1, 2])\n")
    got = analyze(tmp_path, checks=[check_by_id("recompile-hazard")],
                  files=[tmp_path / "s.py"])
    assert [f.line for f in got] == [13], [f.render() for f in got]


def test_default_baseline_path_is_committed():
    assert DEFAULT_BASELINE_PATH.exists()
    data = json.loads(DEFAULT_BASELINE_PATH.read_text())
    assert data["version"] == 1
