"""LeanZ3Index: tiered generational index (the 500M–1B single-chip
scale path — scale_proof.py runs it on the real chip; this file keeps
the logic under the fast CI loop).

Round-4 coverage: sentinel-generation bucket padding does no extra
dispatches (VERDICT #9), the full tier's device-side exact mask equals
the keys tier's host mask and the brute-force oracle (VERDICT #7), and
host-spilled runs answer queries exactly (VERDICT #2 groundwork)."""

import numpy as np
import pytest

from geomesa_tpu.index.z3 import Z3PointIndex
from geomesa_tpu.index.z3_lean import LeanZ3Index

MS = 1514764800000
DAY = 86_400_000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    n = 60_000
    return (rng.uniform(-75, -73, n), rng.uniform(40, 42, n),
            rng.integers(MS, MS + 14 * DAY, n))


def _brute(x, y, t, boxes, lo, hi):
    m = np.zeros(len(x), dtype=bool)
    for b in np.atleast_2d(np.asarray(boxes)):
        m |= ((x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3]))
    if lo is not None:
        m &= t >= lo
    if hi is not None:
        m &= t <= hi
    return np.flatnonzero(m)


@pytest.mark.parametrize("payload_on_device", [True, False])
def test_generational_build_query_oracle(data, payload_on_device):
    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 14,
                      payload_on_device=payload_on_device)
    for s in range(0, len(x), 25_000):  # slices straddle generations
        sl = slice(s, s + 25_000)
        idx.append(x[sl], y[sl], t[sl])
    assert len(idx) == len(x)
    assert len(idx.generations) == -(-len(x) // (1 << 14))
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    got = idx.query([box], lo, hi)
    np.testing.assert_array_equal(got, _brute(x, y, t, [box], lo, hi))
    # parity with the full-fat index
    full = Z3PointIndex.build(x, y, t, period="week")
    np.testing.assert_array_equal(got, np.sort(full.query([box], lo, hi)))


def test_open_time_bounds_and_multi_box(data):
    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 15)
    idx.append(x, y, t)
    boxes = [(-74.9, 40.1, -74.6, 40.4), (-73.4, 41.6, -73.1, 41.9)]
    got = idx.query(boxes, None, None)
    np.testing.assert_array_equal(got, _brute(x, y, t, boxes, None, None))


def test_query_many_batched_windows(data):
    """Multi-window scans run all windows × all generations in a fixed
    number of dispatches and match per-window brute force + the
    full-fat index's query_many."""
    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 14)
    idx.append(x, y, t)
    rng = np.random.default_rng(9)
    windows = []
    for _ in range(7):
        cx = float(rng.uniform(-74.8, -73.2))
        cy = float(rng.uniform(40.2, 41.8))
        lo = MS + int(rng.integers(0, 9)) * DAY
        windows.append(([(cx - .3, cy - .3, cx + .3, cy + .3)],
                        lo, lo + 3 * DAY))
    windows.append(([(-74.5, 40.5, -73.5, 41.5)], None, None))
    before = idx.dispatch_count
    got = idx.query_many(windows)
    # one totals probe + one scan for the single populated tier
    assert idx.dispatch_count - before == 2
    full = Z3PointIndex.build(x, y, t, period="week")
    want = full.query_many(windows)
    for g, w, (bxs, lo, hi) in zip(got, want, windows):
        np.testing.assert_array_equal(g, _brute(x, y, t, bxs, lo, hi))
        np.testing.assert_array_equal(g, np.sort(w))


def test_sentinel_padding_no_extra_dispatches(data):
    """Bucket padding uses the shared EMPTY sentinel generation: 5 real
    generations pad to 8 but the padded slots carry 8-slot all-sentinel
    columns (zero seeks match), and the query still runs in the fixed
    dispatch count (VERDICT r3 weak #5 / next #9)."""
    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 14,
                      payload_on_device=False)
    idx.append(x[:30_000], y[:30_000], t[:30_000])
    # 30000 rows / 16384 slots -> 2 generations; add 3 more tiny ones
    for i in range(3):
        idx.generations[-1].n = idx.generations[-1].capacity  # force roll
        s = 30_000 + i * 1000
        idx.append(x[s:s + 1000], y[s:s + 1000], t[s:s + 1000])
    assert len(idx.generations) == 5
    from geomesa_tpu.index.z3_lean import _GEN_BUCKET
    assert _GEN_BUCKET == 4  # 5 gens pad to 8
    # the shared per-instance sentinel generation is full-size (uniform
    # program shapes -> one compile per bucket) and matches zero seeks
    sb, sz, sp = idx._sentinel_cols("keys")
    assert sb.shape == (1 << 14,) and int(sp[0]) == -1
    before = idx.dispatch_count
    box = (-74.5, 40.5, -73.5, 41.5)
    got = idx.query([box], MS + 2 * DAY, MS + 9 * DAY)
    assert idx.dispatch_count - before == 2  # probe + one tier scan
    rows = np.concatenate([np.arange(30_000),
                           np.arange(30_000, 33_000)])
    xs, ys, ts = x[rows], y[rows], t[rows]
    np.testing.assert_array_equal(
        got, _brute(xs, ys, ts, [box], MS + 2 * DAY, MS + 9 * DAY))


def test_full_tier_device_exact_mask_matches_host(data):
    """The full tier's fused device mask (VERDICT #7) returns exactly
    the host-masked hit set — verified across the tier boundary by
    querying the same data in both configurations."""
    x, y, t = data
    dev = LeanZ3Index(period="week", generation_slots=1 << 14,
                      payload_on_device=True)
    host = LeanZ3Index(period="week", generation_slots=1 << 14,
                       payload_on_device=False)
    dev.append(x, y, t)
    host.append(x, y, t)
    assert dev.tier_counts()["full"] == len(dev.generations)
    assert host.tier_counts()["keys"] == len(host.generations)
    windows = [([(-74.5, 40.5, -73.5, 41.5)], MS + 2 * DAY, MS + 9 * DAY),
               ([(-74.2, 40.1, -73.1, 41.2)], None, None)]
    for gd, gh, (bxs, lo, hi) in zip(dev.query_many(windows),
                                     host.query_many(windows), windows):
        np.testing.assert_array_equal(gd, gh)
        np.testing.assert_array_equal(gd, _brute(x, y, t, bxs, lo, hi))


def test_budget_demotes_payload_then_spills(data):
    """Under HBM pressure payload drops first (full → keys), then key
    runs spill to host RAM (keys → host), oldest first; queries stay
    oracle-exact across every mix (VERDICT #2 groundwork)."""
    x, y, t = data
    slots = 1 << 14
    # budget fits ~2 keys-tier generations only: 4 generations of data
    # force payload drops AND at least one host spill
    idx = LeanZ3Index(period="week", generation_slots=slots,
                      hbm_budget_bytes=3 * slots * 16,
                      payload_on_device=True)
    idx.append(x, y, t)   # 60k rows -> 4 generations
    tiers = idx.tier_counts()
    assert tiers["host"] >= 1          # spill happened
    assert tiers["full"] == 0          # payloads all dropped
    assert idx.device_bytes() <= 3 * slots * 16
    assert idx.host_key_bytes() > 0
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    np.testing.assert_array_equal(idx.query([box], lo, hi),
                                  _brute(x, y, t, [box], lo, hi))
    # appends continue after spills (a fresh device generation opens)
    rng = np.random.default_rng(11)
    nx = rng.uniform(-74.4, -73.6, 500)
    ny = rng.uniform(40.6, 41.4, 500)
    nt = rng.integers(MS, MS + 14 * DAY, 500)
    idx.append(nx, ny, nt)
    ax, ay, at = np.r_[x, nx], np.r_[y, ny], np.r_[t, nt]
    np.testing.assert_array_equal(idx.query([box], lo, hi),
                                  _brute(ax, ay, at, [box], lo, hi))


def test_budget_reserves_live_generation_payload(data):
    """The NEWEST generation keeps its device payload under budget
    pressure (round-4 VERDICT #5): older payloads drop and older key
    runs spill to host to make room, so the hot window is served by the
    fused device-exact path at any store size."""
    x, y, t = data
    slots = 1 << 14
    # budget holds: live full gen (40 B/slot) + both sentinels
    # (16 + 40 B/slot) + ~1 keys-tier gen (16 B/slot); 4 generations of
    # data must therefore end mixed full/keys/host with full >= 1
    budget = slots * (40 + 16 + 40 + 16 + 8)
    idx = LeanZ3Index(period="week", generation_slots=slots,
                      hbm_budget_bytes=budget, payload_on_device=True)
    idx.append(x, y, t)   # 60k rows -> 4 generations
    tiers = idx.tier_counts()
    assert tiers["full"] >= 1
    assert idx.generations[-1].tier == "full"   # the LIVE one
    assert tiers["host"] >= 1                   # others made room
    assert idx.device_bytes() <= budget
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    np.testing.assert_array_equal(idx.query([box], lo, hi),
                                  _brute(x, y, t, [box], lo, hi))
    # a hot-window query touching only the live generation's rows is
    # answered exactly too (served from the fused device path)
    np.testing.assert_array_equal(
        idx.query([box], None, None),
        _brute(x, y, t, [box], None, None))


def test_host_stack_flat_seek_many_runs(monkeypatch):
    """50+ host-spilled runs answer a query batch with a BOUNDED number
    of searchsorted/bisection passes — the stacked seek is flat in run
    count (round-4 VERDICT #9), not a Python loop per run per bin."""
    rng = np.random.default_rng(21)
    n = 60_000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + 21 * DAY, n)
    slots = 1 << 10
    idx = LeanZ3Index(period="week", generation_slots=slots,
                      hbm_budget_bytes=2 * slots * (16 + 16 + 40),
                      payload_on_device=False)
    idx.append(x, y, t)
    tiers = idx.tier_counts()
    assert tiers["host"] >= 50
    import geomesa_tpu.index.z3_lean as zl
    calls = {"searchsorted": 0, "bisect": 0}
    real_ss = np.searchsorted
    real_bs = zl._bisect_segments

    def count_ss(*a, **k):
        calls["searchsorted"] += 1
        return real_ss(*a, **k)

    def count_bs(*a, **k):
        calls["bisect"] += 1
        return real_bs(*a, **k)

    monkeypatch.setattr(zl.np, "searchsorted", count_ss)
    monkeypatch.setattr(zl, "_bisect_segments", count_bs)
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    got = idx.query([box], lo, hi)
    # flat: exactly 2 bisection passes serve all 50+ runs (the old path
    # did 2 searchsorted calls x runs x distinct bins); the global
    # searchsorted count (numpy is patched module-wide, so planning
    # bookkeeping is included) must stay below one call per run
    assert calls["bisect"] == 2
    assert calls["searchsorted"] < tiers["host"]
    np.testing.assert_array_equal(got, _brute(x, y, t, [box], lo, hi))
    # spill MORE runs: the stack rebuilds and stays exact
    x2 = rng.uniform(-74.4, -73.6, 5_000)
    y2 = rng.uniform(40.6, 41.4, 5_000)
    t2 = rng.integers(MS, MS + 21 * DAY, 5_000)
    idx.append(x2, y2, t2)
    ax, ay, at = np.r_[x, x2], np.r_[y, y2], np.r_[t, t2]
    np.testing.assert_array_equal(idx.query([box], lo, hi),
                                  _brute(ax, ay, at, [box], lo, hi))


def test_empty_and_budget_bookkeeping():
    idx = LeanZ3Index(period="week")
    # open bounds on an empty index must not crash in planning
    assert len(idx.query([(-75, 40, -73, 42)], None, None)) == 0
    assert idx.device_bytes() == 0
    idx2 = LeanZ3Index(period="week", generation_slots=1 << 14,
                       payload_on_device=False)
    rng = np.random.default_rng(4)
    idx2.append(rng.uniform(-75, -73, 100), rng.uniform(40, 42, 100),
                rng.integers(MS, MS + DAY, 100))
    assert idx2.device_bytes() == (1 << 14) * 16
    idx3 = LeanZ3Index(period="week", generation_slots=1 << 14,
                       payload_on_device=True)
    idx3.append(rng.uniform(-75, -73, 100), rng.uniform(40, 42, 100),
                rng.integers(MS, MS + DAY, 100))
    assert idx3.device_bytes() == (1 << 14) * 40
    idx2.block()


def test_big_capacity_falls_back_per_generation(data):
    """Huge candidate sets route through per-generation buffers sized by
    each generation's own total (the batched shared-capacity buffer
    would cost G × max-total slots of HBM): one probe + one dispatch
    per populated generation."""
    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 14,
                      payload_on_device=False)
    idx.append(x, y, t)
    idx.BATCH_SCAN_BUDGET = 1 << 14
    before = idx.dispatch_count
    # whole-world query: totals ~= all rows → capacity blows the
    # (shrunken) batched budget → per-generation path
    got = idx.query([(-180, -90, 180, 90)], None, None)
    np.testing.assert_array_equal(got, np.arange(len(x)))
    assert idx.dispatch_count - before == 1 + len(idx.generations)


def test_payload_provider_shares_store_columns(data):
    """With a payload provider the index retains NO payload of its own
    (the store owns the single host copy — VERDICT #1 groundwork)."""
    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 14,
                      payload_on_device=False)
    idx.payload_provider = lambda: (x, y, t)
    idx.append(x, y, t)
    assert idx._payload == [] and idx._flat is None
    box = (-74.5, 40.5, -73.5, 41.5)
    np.testing.assert_array_equal(
        idx.query([box], None, None),
        _brute(x, y, t, [box], None, None))
