"""LeanZ3Index: keys-on-device / payload-on-host generational index
(the 500M+ single-chip scale path — scale_proof.py runs it on the real
chip; this file keeps the logic under the fast CI loop)."""

import numpy as np
import pytest

from geomesa_tpu.index.z3 import Z3PointIndex
from geomesa_tpu.index.z3_lean import LeanZ3Index

MS = 1514764800000
DAY = 86_400_000


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    n = 60_000
    return (rng.uniform(-75, -73, n), rng.uniform(40, 42, n),
            rng.integers(MS, MS + 14 * DAY, n))


def test_generational_build_query_oracle(data):
    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 14)
    for s in range(0, len(x), 25_000):  # slices straddle generations
        sl = slice(s, s + 25_000)
        idx.append(x[sl], y[sl], t[sl])
    assert len(idx) == len(x)
    assert len(idx.generations) == -(-len(x) // (1 << 14))
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = MS + 2 * DAY, MS + 9 * DAY
    got = idx.query([box], lo, hi)
    want = np.flatnonzero((x >= box[0]) & (x <= box[2]) & (y >= box[1])
                          & (y <= box[3]) & (t >= lo) & (t <= hi))
    np.testing.assert_array_equal(got, want)
    # parity with the full-fat index
    full = Z3PointIndex.build(x, y, t, period="week")
    np.testing.assert_array_equal(got, np.sort(full.query([box], lo, hi)))


def test_open_time_bounds_and_multi_box(data):
    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 15)
    idx.append(x, y, t)
    boxes = [(-74.9, 40.1, -74.6, 40.4), (-73.4, 41.6, -73.1, 41.9)]
    got = idx.query(boxes, None, None)
    m = np.zeros(len(x), dtype=bool)
    for b in boxes:
        m |= ((x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3]))
    np.testing.assert_array_equal(got, np.flatnonzero(m))


def test_empty_and_budget_bookkeeping():
    idx = LeanZ3Index(period="week")
    # open bounds on an empty index must not crash in planning
    assert len(idx.query([(-75, 40, -73, 42)], None, None)) == 0
    assert idx.device_bytes() == 0
    idx2 = LeanZ3Index(period="week", generation_slots=1 << 14)
    rng = np.random.default_rng(4)
    idx2.append(rng.uniform(-75, -73, 100), rng.uniform(40, 42, 100),
                rng.integers(MS, MS + DAY, 100))
    assert idx2.device_bytes() == (1 << 14) * 16
    idx2.block()


def test_big_capacity_falls_back_per_generation(monkeypatch, data):
    """Huge candidate sets route through per-generation buffers sized by
    each generation's own total (the batched shared-capacity buffer
    would cost G × max-total slots of HBM)."""
    from geomesa_tpu.index import z3_lean as mod

    x, y, t = data
    idx = LeanZ3Index(period="week", generation_slots=1 << 14)
    idx.append(x, y, t)
    calls = {"single": 0}
    orig = mod._lean_scan

    def spy(*a, **k):
        calls["single"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(mod, "_lean_scan", spy)
    monkeypatch.setattr(LeanZ3Index, "BATCH_SCAN_BUDGET", 1 << 14)
    # whole-world query: totals ~= all rows → capacity blows the
    # (shrunken) batched budget → per-generation path
    got = idx.query([(-180, -90, 180, 90)], None, None)
    np.testing.assert_array_equal(got, np.arange(len(x)))
    assert calls["single"] == len(idx.generations)
