"""SQL joins (round-4 VERDICT #8): inner equi-join + spatial join
between two schemas with per-side predicate push-down, validated
against a pandas oracle.  Reference surface: GeoMesaSparkSQL.scala +
SQLRules.scala (join relations with push-down on each side)."""

import numpy as np
import pandas as pd
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.sql import explain_join, sql_query

MS = 1514764800000
DAY = 86_400_000


@pytest.fixture(scope="module")
def stores():
    rng = np.random.default_rng(17)
    ds = TpuDataStore()
    n1, n2 = 3000, 5000
    ds.create_schema("evt", "site:String:index=true,score:Double,"
                            "dtg:Date,*geom:Point")
    ds.create_schema("obs", "site:String:index=true,kind:String,"
                            "val:Double,dtg:Date,*geom:Point")
    sites = np.array([f"s{i}" for i in range(40)], object)
    e = {"site": rng.choice(sites, n1),
         "score": rng.uniform(0, 100, n1),
         "dtg": rng.integers(MS, MS + 7 * DAY, n1),
         "geom": (rng.uniform(-75, -73, n1), rng.uniform(40, 42, n1))}
    o = {"site": rng.choice(sites, n2),
         "kind": rng.choice(np.array(["x", "y"], object), n2),
         "val": rng.uniform(0, 10, n2),
         "dtg": rng.integers(MS, MS + 7 * DAY, n2),
         "geom": (rng.uniform(-75, -73, n2), rng.uniform(40, 42, n2))}
    ds.write("evt", e)
    ds.write("obs", o)
    return ds, e, o


def test_equi_join_matches_pandas(stores):
    ds, e, o = stores
    out = sql_query(ds, "SELECT a.site, a.score, b.val FROM evt a "
                        "JOIN obs b ON a.site = b.site "
                        "WHERE a.score > 90 AND b.kind = 'x'")
    le = pd.DataFrame({"site": e["site"], "score": e["score"]})
    ro = pd.DataFrame({"site": o["site"], "kind": o["kind"],
                       "val": o["val"]})
    want = le[le.score > 90].merge(ro[ro.kind == "x"], on="site")
    got = pd.DataFrame({"site": out["a.site"], "score": out["a.score"],
                        "val": out["b.val"]})
    assert len(got) == len(want)
    key = lambda d: d.sort_values(["site", "score", "val"]) \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(key(got),
                                  key(want[["site", "score", "val"]]))


def test_equi_join_select_star_and_limit(stores):
    ds, e, o = stores
    out = sql_query(ds, "SELECT * FROM evt a JOIN obs b "
                        "ON a.site = b.site WHERE a.score > 99 LIMIT 7")
    assert "a.site" in out and "b.val" in out
    assert len(out["a.site"]) <= 7


def test_equi_join_pushdown_visible_in_explain(stores):
    ds, *_ = stores
    plan = explain_join(ds, "SELECT a.site, b.val FROM evt a JOIN obs "
                            "b ON a.site = b.site WHERE a.score > 90 "
                            "AND b.kind = 'x'")
    assert "left side" in plan and "right side" in plan
    assert "semi-join IN push-down" in plan
    # each side's WHERE went to ITS scan
    assert "score > 90" in plan and "kind = 'x'" in plan


def test_spatial_join_points_in_polygons():
    rng = np.random.default_rng(23)
    ds = TpuDataStore()
    ds.create_schema("regions", "rid:Integer,*geom:Polygon")
    ds.create_schema("pts", "pid:Integer,dtg:Date,*geom:Point")
    # 12 disjoint square regions + labeled points, some outside any
    boxes = []
    rid = []
    for i in range(12):
        x0 = -75.0 + (i % 4) * 0.6
        y0 = 40.0 + (i // 4) * 0.8
        boxes.append((x0, y0, x0 + 0.4, y0 + 0.5))
        rid.append(i)
    from geomesa_tpu.geometry.types import Polygon
    polys = [Polygon([(b[0], b[1]), (b[2], b[1]), (b[2], b[3]),
                      (b[0], b[3])]) for b in boxes]
    ds.write("regions", {"rid": np.array(rid), "geom": polys})
    n = 4000
    px = rng.uniform(-75.2, -72.2, n)
    py = rng.uniform(39.8, 42.4, n)
    ds.write("pts", {"pid": np.arange(n),
                     "dtg": np.full(n, MS),
                     "geom": (px, py)})
    out = sql_query(ds, "SELECT a.rid, b.pid FROM regions a JOIN pts b "
                        "ON st_intersects(a.geom, b.geom)")
    # pandas/numpy oracle: point-in-box pairs (boundary-inclusive)
    want = set()
    for i, b in enumerate(boxes):
        inside = np.flatnonzero((px >= b[0]) & (px <= b[2])
                                & (py >= b[1]) & (py <= b[3]))
        want.update((rid[i], int(p)) for p in inside)
    got = set(zip(out["a.rid"].tolist(), out["b.pid"].tolist()))
    assert got == want
    # per-side push-down composes with the spatial ON
    out2 = sql_query(ds, "SELECT a.rid, b.pid FROM regions a JOIN pts "
                         "b ON st_intersects(a.geom, b.geom) "
                         "WHERE a.rid = 3 AND b.pid < 2000")
    want2 = {(r, p) for r, p in want if r == 3 and p < 2000}
    got2 = set(zip(out2["a.rid"].tolist(), out2["b.pid"].tolist()))
    assert got2 == want2


def test_dwithin_join_point_to_point():
    rng = np.random.default_rng(29)
    ds = TpuDataStore()
    ds.create_schema("anchor", "aid:Integer,dtg:Date,*geom:Point")
    ds.create_schema("near", "nid:Integer,dtg:Date,*geom:Point")
    ax = np.array([-74.0, -73.5])
    ay = np.array([40.7, 41.2])
    ds.write("anchor", {"aid": np.arange(2), "dtg": np.full(2, MS),
                        "geom": (ax, ay)})
    n = 2000
    nx = rng.uniform(-74.3, -73.2, n)
    ny = rng.uniform(40.4, 41.5, n)
    ds.write("near", {"nid": np.arange(n), "dtg": np.full(n, MS),
                      "geom": (nx, ny)})
    out = sql_query(ds, "SELECT a.aid, b.nid FROM anchor a JOIN near b "
                        "ON st_dwithin(a.geom, b.geom, 20000)")
    from geomesa_tpu.process.knn import haversine_m
    want = set()
    for i in range(2):
        d = haversine_m(ax[i], ay[i], nx, ny)
        want.update((i, int(j)) for j in np.flatnonzero(d <= 20000))
    got = set(zip(out["a.aid"].tolist(), out["b.nid"].tolist()))
    assert got == want


def test_join_word_in_literal_not_hijacked(stores):
    ds, e, _ = stores
    # 'join' inside a string literal must stay a normal query (review)
    out = sql_query(ds, "SELECT count(*) FROM evt WHERE site = 'join'")
    assert out == 0


def test_join_where_between_survives_and_split(stores):
    ds, e, o = stores
    out = sql_query(ds, "SELECT a.site, b.val FROM evt a JOIN obs b "
                        "ON a.site = b.site "
                        "WHERE a.score BETWEEN 95 AND 99 "
                        "AND b.kind = 'y'")
    le = pd.DataFrame({"site": e["site"], "score": e["score"]})
    ro = pd.DataFrame({"site": o["site"], "kind": o["kind"],
                       "val": o["val"]})
    want = le[(le.score >= 95) & (le.score <= 99)].merge(
        ro[ro.kind == "y"], on="site")
    assert len(out["a.site"]) == len(want)


def test_equi_join_null_keys_never_match():
    ds = TpuDataStore()
    ds.create_schema("l", "k:String,dtg:Date,*geom:Point")
    ds.create_schema("r", "k:String,dtg:Date,*geom:Point")
    ds.write("l", {"k": np.array(["a", None, "b"], object),
                   "dtg": np.full(3, MS),
                   "geom": (np.zeros(3), np.zeros(3))})
    ds.write("r", {"k": np.array([None, "a", None], object),
                   "dtg": np.full(3, MS),
                   "geom": (np.zeros(3), np.zeros(3))})
    out = sql_query(ds, "SELECT a.k, b.k AS rk FROM l a JOIN r b "
                        "ON a.k = b.k")
    # SQL: NULL = NULL is not true — only the 'a' pair joins
    assert list(out["a.k"]) == ["a"] and list(out["rk"]) == ["a"]


def test_dwithin_join_high_latitude_pairs_survive():
    # at 70N one longitude degree is ~38km; an under-padded window
    # would drop a 15km-east pair (review)
    ds = TpuDataStore()
    ds.create_schema("anchor", "aid:Integer,dtg:Date,*geom:Point")
    ds.create_schema("near", "nid:Integer,dtg:Date,*geom:Point")
    ds.write("anchor", {"aid": np.array([0]), "dtg": np.array([MS]),
                        "geom": (np.array([10.0]), np.array([70.0]))})
    # ~15km due east at 70N is ~0.39 degrees of longitude
    ds.write("near", {"nid": np.array([0]), "dtg": np.array([MS]),
                      "geom": (np.array([10.39]), np.array([70.0]))})
    out = sql_query(ds, "SELECT a.aid, b.nid FROM anchor a JOIN near b "
                        "ON st_dwithin(a.geom, b.geom, 16000)")
    assert len(out["a.aid"]) == 1


def test_spatial_join_points_left_polygons_right():
    rng = np.random.default_rng(41)
    ds = TpuDataStore()
    ds.create_schema("pts", "pid:Integer,dtg:Date,*geom:Point")
    ds.create_schema("regions", "rid:Integer,*geom:Polygon")
    from geomesa_tpu.geometry.types import Polygon
    boxes = [(-75.0 + i * 0.6, 40.0, -75.0 + i * 0.6 + 0.4, 40.5)
             for i in range(4)]
    ds.write("regions", {"rid": np.arange(4),
                         "geom": [Polygon([(b[0], b[1]), (b[2], b[1]),
                                           (b[2], b[3]), (b[0], b[3])])
                                  for b in boxes]})
    n = 1000
    px = rng.uniform(-75.2, -72.4, n)
    py = rng.uniform(39.8, 40.7, n)
    ds.write("pts", {"pid": np.arange(n), "dtg": np.full(n, MS),
                     "geom": (px, py)})
    out = sql_query(ds, "SELECT a.pid, b.rid FROM pts a JOIN regions b "
                        "ON st_intersects(a.geom, b.geom)")
    want = set()
    for i, b in enumerate(boxes):
        inside = np.flatnonzero((px >= b[0]) & (px <= b[2])
                                & (py >= b[1]) & (py <= b[3]))
        want.update((int(p), i) for p in inside)
    got = set(zip(out["a.pid"].tolist(), out["b.rid"].tolist()))
    assert got == want and len(got) > 0


def test_join_shape_in_literal_not_hijacked(stores):
    ds, *_ = stores
    out = sql_query(ds, "SELECT count(*) FROM evt WHERE "
                        "site = 'x FROM one two JOIN three'")
    assert out == 0


def test_join_where_alias_token_inside_literal(stores):
    ds, e, o = stores
    # 'a.x'-shaped DATA inside a right-side literal must not be
    # rewritten or counted as a left-side reference
    out = sql_query(ds, "SELECT a.site, b.val FROM evt a JOIN obs b "
                        "ON a.site = b.site WHERE b.kind = 'a.x'")
    assert len(out["a.site"]) == 0   # no such kind — but no error


def test_equi_join_float_nan_keys_never_match():
    ds = TpuDataStore()
    ds.create_schema("l", "v:Double,dtg:Date,*geom:Point")
    ds.create_schema("r", "v:Double,dtg:Date,*geom:Point")
    ds.write("l", {"v": np.array([1.0, np.nan]),
                   "dtg": np.full(2, MS),
                   "geom": (np.zeros(2), np.zeros(2))})
    ds.write("r", {"v": np.array([np.nan, 1.0]),
                   "dtg": np.full(2, MS),
                   "geom": (np.zeros(2), np.zeros(2))})
    out = sql_query(ds, "SELECT a.v, b.v AS rv FROM l a JOIN r b "
                        "ON a.v = b.v")
    assert list(out["a.v"]) == [1.0]


def test_dwithin_polygon_left_errors_loudly_before_scan():
    ds = TpuDataStore()
    ds.create_schema("regions", "rid:Integer,*geom:Polygon")
    ds.create_schema("pts", "pid:Integer,dtg:Date,*geom:Point")
    from geomesa_tpu.geometry.types import Polygon
    ds.write("regions", {"rid": np.array([0]),
                         "geom": [Polygon([(0, 0), (1, 0), (1, 1),
                                           (0, 1)])]})
    ds.write("pts", {"pid": np.array([0]), "dtg": np.array([MS]),
                     "geom": (np.array([50.0]), np.array([50.0]))})
    with pytest.raises(ValueError, match="point-to-point"):
        sql_query(ds, "SELECT a.rid, b.pid FROM regions a JOIN pts b "
                      "ON st_dwithin(a.geom, b.geom, 1000)")


class TestJoinGrammar:
    def _ds(self):
        ds = TpuDataStore()
        ds.create_schema("t1", "k:String,dtg:Date,*geom:Point")
        ds.create_schema("t2", "k:String,dtg:Date,*geom:Point")
        for nm in ("t1", "t2"):
            ds.write(nm, {"k": np.array(["a"], object),
                          "dtg": np.array([MS]),
                          "geom": (np.zeros(1), np.zeros(1))})
        return ds

    def test_same_alias_rejected(self):
        with pytest.raises(ValueError, match="aliases must differ"):
            sql_query(self._ds(), "SELECT a.k FROM t1 a JOIN t2 a "
                                  "ON a.k = a.k")

    def test_cross_side_where_rejected(self):
        with pytest.raises(ValueError, match="exactly one side"):
            sql_query(self._ds(), "SELECT a.k FROM t1 a JOIN t2 b "
                                  "ON a.k = b.k WHERE a.k = b.k")

    def test_group_by_rejected_loudly(self):
        with pytest.raises(ValueError, match="SELECT/ON/WHERE/LIMIT"):
            sql_query(self._ds(), "SELECT a.k FROM t1 a JOIN t2 b "
                                  "ON a.k = b.k GROUP BY a.k")

    def test_unqualified_projection_rejected(self):
        with pytest.raises(ValueError, match="qualified columns"):
            sql_query(self._ds(), "SELECT k FROM t1 a JOIN t2 b "
                                  "ON a.k = b.k")

    def test_duplicate_output_name_rejected(self):
        with pytest.raises(ValueError, match="duplicate output"):
            sql_query(self._ds(), "SELECT a.k AS k, b.k AS k FROM t1 a "
                                  "JOIN t2 b ON a.k = b.k")