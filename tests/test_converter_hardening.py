"""Converter robustness (VERDICT r1 item 9): per-record error modes,
index validators, malformed-row fuzzing — the reference's
AbstractConverter error handling + SimpleFeatureValidator suite
(geomesa-convert-common/.../convert2/AbstractConverter.scala)."""

import numpy as np
import pytest

from geomesa_tpu.features.feature_type import parse_spec
from geomesa_tpu.io.converters import (
    EvaluationContext, converter_from_config,
)

SPEC = "name:String,age:Int,dtg:Date,*geom:Point"


@pytest.fixture
def sft():
    return parse_spec("people", SPEC)


def _conv(sft, **opts):
    return converter_from_config(sft, {
        "type": "csv",
        "id-field": "$0",
        "fields": [
            {"name": "name", "transform": "toString($1)"},
            {"name": "age", "transform": "toInt($2)"},
            {"name": "dtg", "transform": "toLong($3)"},
            {"name": "geom", "transform": "point($4, $5)"},
        ],
        "options": opts,
    })


GOOD = "id1,alice,30,1514764800000,-74.0,40.7\n"
BAD_INT = "id2,bob,notanumber,1514764800000,-74.1,40.8\n"
GOOD2 = "id3,carol,41,1514851200000,-73.9,40.6\n"


def test_skip_mode_salvages_good_records(sft):
    """One malformed row must not poison the batch: per-record retry
    keeps the clean rows (skip-bad-records semantics)."""
    ec = EvaluationContext()
    batch = _conv(sft, **{"error-mode": "skip"}).convert(
        GOOD + BAD_INT + GOOD2, ec)
    assert len(batch) == 2
    assert ec.success == 2 and ec.failure == 1
    assert list(batch.column("name")) == ["alice", "carol"]
    assert list(batch.ids) == ["id1", "id3"]
    assert any("row 1" in e for e in ec.errors)


def test_raise_mode_propagates(sft):
    with pytest.raises(Exception):
        _conv(sft, **{"error-mode": "raise"}).convert(GOOD + BAD_INT)


def test_log_mode_salvages_and_logs(sft, caplog):
    import logging
    ec = EvaluationContext()
    with caplog.at_level(logging.WARNING, logger="geomesa_tpu.convert"):
        batch = _conv(sft, **{"error-mode": "log"}).convert(
            GOOD + BAD_INT, ec)
    assert len(batch) == 1 and ec.failure == 1
    assert any("row-by-row" in r.message for r in caplog.records)


def test_validator_zindex_drops_out_of_bounds(sft):
    """z-index validator: lon/lat outside WGS84 or dtg outside the index
    epoch are dropped and counted."""
    rows = (GOOD
            + "id4,dan,20,1514764800000,-374.0,40.0\n"      # bad lon
            + "id5,eve,21,1514764800000,-74.0,95.0\n"       # bad lat
            + "id6,fay,22,-5,-74.0,40.0\n"                  # dtg < epoch
            + GOOD2)
    ec = EvaluationContext()
    batch = _conv(sft, validators=["z-index"]).convert(rows, ec)
    assert len(batch) == 2
    assert ec.failure == 3
    assert list(batch.ids) == ["id1", "id3"]
    assert any("z-index" in e for e in ec.errors)


def test_validator_raise_mode(sft):
    rows = GOOD + "id4,dan,20,1514764800000,-374.0,40.0\n"
    conv = _conv(sft, **{"error-mode": "raise"}, validators=["z-index"])
    with pytest.raises(ValueError, match="validator"):
        conv.convert(rows)


def test_validator_has_dtg_on_null(sft):
    conv = converter_from_config(sft, {
        "type": "json",
        "fields": [
            {"name": "name", "transform": "toString($title)"},
            {"name": "dtg", "transform": "toLong($when)"},
            {"name": "geom", "transform": "point($x, $y)"},
        ],
        "options": {"validators": ["has-dtg"]},
    })
    ec = EvaluationContext()
    rows = ('{"title": "a", "when": 1514764800000, "x": 1.0, "y": 2.0}\n'
            '{"title": "b", "when": null, "x": 1.0, "y": 2.0}\n')
    batch = conv.convert(rows, ec)
    assert len(batch) == 1
    assert ec.failure == 1


def test_unknown_validator_rejected(sft):
    conv = _conv(sft, validators=["bogus"])
    with pytest.raises(ValueError, match="unknown validator"):
        conv.convert(GOOD)


def test_fuzz_malformed_rows_never_crash(sft):
    """Random corruption of a clean CSV: skip mode must never raise and
    accounting must add up (success + failure == parseable rows)."""
    rng = np.random.default_rng(61)
    base = [
        f"id{i},user{i},{20 + i % 50},{1514764800000 + i * 1000},"
        f"{-75 + (i % 100) * 0.01},{40 + (i % 100) * 0.01}"
        for i in range(200)
    ]
    corruptions = [
        lambda r: r.replace(",", ";;", 1),          # broken delimiter
        lambda r: r.rsplit(",", 2)[0] + ",NaN,NaN",  # NaN coords
        lambda r: r.replace("user", "\x00bin", 1),   # control chars
        lambda r: ",".join(r.split(",")[:3]),        # truncated row
        lambda r: r + ",extra,cols",                 # surplus columns
        lambda r: r.replace(str(1514764800000), "not-a-time", 1),
    ]
    conv = _conv(sft, **{"error-mode": "skip"}, validators=["z-index"])
    for trial in range(5):
        rows = list(base)
        for _ in range(20):
            i = rng.integers(0, len(rows))
            rows[i] = corruptions[rng.integers(0, len(corruptions))](rows[i])
        ec = EvaluationContext()
        try:
            batch = conv.convert("\n".join(rows) + "\n", ec)
        except Exception as e:  # pragma: no cover
            pytest.fail(f"skip mode raised on malformed input: {e!r}")
        assert len(batch) == ec.success
        assert ec.success <= len(rows)
        assert ec.success + ec.failure >= len(rows) - 20
