"""Sealed-generation density pyramids + /tiles serving (ISSUE 18).

Pins the exactness matrix of docs/density.md: a pyramid-served grid is
bit-identical to the direct density scan at the same resolution across
every tier mix (full / keys / host, single-chip and sharded), tiles
slice out of that path and reassemble exactly, compaction invalidates
merged-away pyramids and the merged run inherits its parents' sum,
pyramid-served generations record zero-byte heat touches, an
interrupted build (``pyramid.build`` fault point) leaves results exact
and resumes, and the ``/tiles/{z}/{x}/{y}`` endpoint hardens malformed
requests to 400/404 while staying recompile-free when warm.
"""

import io
import json

import numpy as np
import pytest

from geomesa_tpu import config
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.index.pyramid import pyramid_spec, tile_env
from geomesa_tpu.index.z3_lean import LeanZ3Index
from geomesa_tpu.metrics import (
    PYRAMID_SERVE_FALLBACKS,
    PYRAMID_SERVE_HITS,
    registry as metrics,
)

MS = 1514764800000
DAY = 86_400_000
WORLD = (-180.0, -90.0, 180.0, 90.0)
SLOTS = 1 << 12


def _data(n, seed=3):
    rng = np.random.default_rng(seed)
    return (rng.uniform(-75, -73, n), rng.uniform(40, 42, n),
            rng.integers(MS, MS + 14 * DAY, n))


def _brute_grid(x, y, sel, env, w, h):
    g = np.zeros((h, w))
    gx = np.clip(((x[sel] - env[0]) / (env[2] - env[0]) * w).astype(int),
                 0, w - 1)
    gy = np.clip(((y[sel] - env[1]) / (env[3] - env[1]) * h).astype(int),
                 0, h - 1)
    np.add.at(g, (gy, gx), 1.0)
    return g


def _streamed(n_gens, payload=False, budget=None, seed=3):
    x, y, t = _data(n_gens * SLOTS, seed=seed)
    idx = LeanZ3Index(period="week", generation_slots=SLOTS,
                      payload_on_device=payload,
                      hbm_budget_bytes=budget,
                      compaction_factor=0)
    for lo in range(0, len(x), SLOTS):
        sl = slice(lo, lo + SLOTS)
        idx.append(x[sl], y[sl], t[sl])
    return idx, x, y, t


def _hits():
    return metrics.counter(PYRAMID_SERVE_HITS).count


# -- bit-exactness matrix --------------------------------------------------
@pytest.mark.parametrize("payload,budget", [
    (True, None),                 # all full
    (False, None),                # all keys
    (False, 3 * SLOTS * 16),      # mixed keys/host (forced demotions)
])
def test_pyramid_served_density_bit_exact_all_tiers(payload, budget):
    idx, x, y, t = _streamed(6, payload=payload, budget=budget)
    all_m = np.ones(len(x), bool)
    direct = idx.density([WORLD], None, None, WORLD, 128, 128)
    np.testing.assert_array_equal(
        direct, _brute_grid(x, y, all_m, WORLD, 128, 128))
    built = idx.build_pyramids(base=128)
    assert built == len(idx.generations) - 1   # every sealed gen
    assert idx.build_pyramids(base=128) == 0   # idempotent resume
    before = _hits()
    served = idx.density([WORLD], None, None, WORLD, 128, 128)
    assert _hits() - before == built           # sealed gens off-pyramid
    np.testing.assert_array_equal(served, direct)
    # every level the 2x2 ladder carries is bit-exact too (64 -> 1)
    w = 64
    while w >= 1:
        np.testing.assert_array_equal(
            idx.density([WORLD], None, None, WORLD, w, w),
            _brute_grid(x, y, all_m, WORLD, w, w))
        w //= 2


def test_pyramid_never_stales_live_appends():
    """Build-behind contract: appends after a build land in the live
    generation, which is always rescanned — pyramid serving can never
    hide new rows."""
    idx, x, y, t = _streamed(3)
    idx.build_pyramids(base=64)
    x2, y2, t2 = _data(500, seed=11)
    idx.append(x2, y2, t2)
    ax, ay = np.concatenate([x, x2]), np.concatenate([y, y2])
    np.testing.assert_array_equal(
        idx.density([WORLD], None, None, WORLD, 64, 64),
        _brute_grid(ax, ay, np.ones(len(ax), bool), WORLD, 64, 64))


def test_empty_index_builds_nothing_and_serves_zeros():
    idx = LeanZ3Index(period="week", generation_slots=SLOTS)
    assert idx.build_pyramids(base=64) == 0
    assert idx.density([WORLD], None, None, WORLD, 64, 64).sum() == 0


# -- tiles -----------------------------------------------------------------
def test_tiles_reassemble_exactly_and_fall_back_past_base():
    idx, x, y, t = _streamed(4)
    idx.build_pyramids(base=128)
    all_m = np.ones(len(x), bool)
    want = _brute_grid(x, y, all_m, WORLD, 128, 128)
    # z=0: the whole world in one 64-px tile == the 64x64 ladder level
    np.testing.assert_array_equal(
        idx.density_tile(0, 0, 0, tile=64),
        _brute_grid(x, y, all_m, WORLD, 64, 64))
    # z=1: four 64-px tiles reassemble into the 128 base grid (slippy
    # y=0 is the NORTH row; grid row 0 is south)
    assembled = np.zeros((128, 128))
    for ty in range(2):
        for tx in range(2):
            assembled[(1 - ty) * 64:(2 - ty) * 64,
                      tx * 64:(tx + 1) * 64] = \
                idx.density_tile(1, tx, ty, tile=64)
    np.testing.assert_array_equal(assembled, want)
    # finer than the pyramid base: direct bbox scan fallback, counted
    config.set_property("geomesa.density.pyramid.base", 128)
    try:
        fb = metrics.counter(PYRAMID_SERVE_FALLBACKS).count
        tz, txx, tyy = 2, 1, 1    # (-90..0, 0..45): inside the data
        g = idx.density_tile(tz, txx, tyy, tile=64)
        assert metrics.counter(PYRAMID_SERVE_FALLBACKS).count == fb + 1
        env = tile_env(tz, txx, tyy)
        m = ((x >= env[0]) & (x <= env[2])
             & (y >= env[1]) & (y <= env[3]))
        np.testing.assert_array_equal(
            g, _brute_grid(x, y, m, env, 64, 64))
    finally:
        config.clear_property("geomesa.density.pyramid.base")


# -- compaction: invalidation + inheritance --------------------------------
def test_compaction_inherits_summed_pyramids_and_drops_dead():
    idx, x, y, t = _streamed(12)     # keys tier: what compaction merges
    built = idx.build_pyramids(base=64)
    assert built == 11
    cache = idx._pyramid_cache.spec_cache(pyramid_spec(64))
    pre_ids = set(cache)
    stats = idx.compact()
    assert stats["merged_groups"] >= 1
    live_ids = {g.gen_id for g in idx.generations}
    post_ids = set(idx._pyramid_cache.spec_cache(pyramid_spec(64)))
    assert post_ids <= live_ids              # dead gens invalidated
    assert post_ids - pre_ids                # merged runs inherited
    # inheritance is the SUM of the parents: no rebuild needed, and the
    # pyramid-served grid is still bit-exact after the merge
    assert idx.build_pyramids(base=64) == 0
    before = _hits()
    np.testing.assert_array_equal(
        idx.density([WORLD], None, None, WORLD, 64, 64),
        _brute_grid(x, y, np.ones(len(x), bool), WORLD, 64, 64))
    assert _hits() - before == len(idx.generations) - 1


# -- zero-byte heat touches (the PR-5 cache-hit convention) ----------------
def test_pyramid_served_scans_record_zero_byte_heat():
    from geomesa_tpu.obs.heat import heat_tracker

    idx, x, y, t = _streamed(4)
    idx.heat_scope = ("pyr_heat_t", "z3")
    idx.density([WORLD], None, None, WORLD, 64, 64)   # cold: scans all
    idx.build_pyramids(base=64)
    sealed = [g.gen_id for g in idx.generations[:-1]]
    live = idx.generations[-1].gen_id

    def snap(gid):
        e = heat_tracker._entries.get(("pyr_heat_t", "z3", gid))
        return (e.scans, e.bytes_read) if e else (0, 0)

    before = {gid: snap(gid) for gid in sealed + [live]}
    idx.density([WORLD], None, None, WORLD, 64, 64)   # warm: pyramids
    for gid in sealed + [live]:
        scans0, bytes0 = before[gid]
        scans1, bytes1 = snap(gid)
        assert scans1 == scans0 + 1    # the touch IS recorded...
        assert bytes1 == bytes0        # ...at zero bytes read (the
        #                                live partial is row-count-keyed)
    # an append invalidates the live partial: the next sweep reads it
    x2, y2, t2 = _data(100, seed=13)
    idx.append(x2, y2, t2)
    live = idx.generations[-1].gen_id
    b0 = snap(live)[1]
    idx.density([WORLD], None, None, WORLD, 64, 64)
    assert snap(live)[1] > b0          # live gen really rescanned


# -- fault injection -------------------------------------------------------
def test_interrupted_build_stays_exact_and_resumes():
    from geomesa_tpu.resilience import FaultInjected

    idx, x, y, t = _streamed(5)
    want = _brute_grid(x, y, np.ones(len(x), bool), WORLD, 64, 64)
    config.set_property("geomesa.resilience.fault.points",
                        "pyramid.build:2")
    try:
        with pytest.raises(FaultInjected):
            idx.build_pyramids(base=64)
    finally:
        config.clear_property("geomesa.resilience.fault.points")
    cache = idx._pyramid_cache.spec_cache(pyramid_spec(64))
    assert len(cache) == 1            # first gen built, rest missing
    # unbuilt generations keep sweeping: results exact mid-build
    np.testing.assert_array_equal(
        idx.density([WORLD], None, None, WORLD, 64, 64), want)
    # the next pass resumes with exactly the missing generations
    assert idx.build_pyramids(base=64) == 3
    np.testing.assert_array_equal(
        idx.density([WORLD], None, None, WORLD, 64, 64), want)


# -- build-on-seal trigger (jobs) ------------------------------------------
def test_build_on_seal_trigger_runs_pyramid_jobs():
    from geomesa_tpu.obs.jobs import jobs_registry

    config.set_property("geomesa.density.pyramid.build", "seal")
    config.set_property("geomesa.density.pyramid.base", 64)
    try:
        ds = TpuDataStore()
        ds.create_schema(
            "sealed", "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
                      f"geomesa.lean.generation.slots={SLOTS},"
                      "geomesa.lean.compaction.factor=0")
        x, y, t = _data(3 * SLOTS + 100)
        for lo in range(0, len(x), SLOTS):
            sl = slice(lo, lo + SLOTS)
            ds.write("sealed", {"dtg": t[sl], "geom": (x[sl], y[sl])})
        idx = ds._store("sealed")._lean_index()
        cache = idx._pyramid_cache.spec_cache(pyramid_spec(64))
        sealed = [g.gen_id for g in idx.generations[:-1]]
        assert sealed and all(gid in cache for gid in sealed)
        jobs = jobs_registry.jobs(kind="pyramid")
        assert jobs and all(j.state == "succeeded" for j in jobs)
    finally:
        config.clear_property("geomesa.density.pyramid.build")
        config.clear_property("geomesa.density.pyramid.base")


# -- sharded variant -------------------------------------------------------
def test_sharded_pyramid_exact_and_compaction_inherits():
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.lean import ShardedLeanZ3Index

    slots = 1 << 9                      # per-SHARD slots: a generation
    step = slots * len(device_mesh().devices.ravel())   # seals per step
    x, y, t = _data(8 * step)
    idx = ShardedLeanZ3Index(period="week", mesh=device_mesh(),
                             generation_slots=slots,
                             hbm_budget_bytes=slots * 20 * 3)
    for lo in range(0, len(x), step):
        sl = slice(lo, lo + step)
        idx.append(x[sl], y[sl], t[sl])
    assert idx.tier_counts()["host"] >= 1
    want = _brute_grid(x, y, np.ones(len(x), bool), WORLD, 64, 64)
    built = idx.build_pyramids(base=64)
    assert built == len(idx.generations) - 1
    before = _hits()
    np.testing.assert_array_equal(
        idx.density([WORLD], None, None, WORLD, 64, 64), want)
    assert _hits() - before == built
    np.testing.assert_array_equal(
        idx.density_tile(0, 0, 0, tile=32),
        _brute_grid(x, y, np.ones(len(x), bool), WORLD, 32, 32))
    idx.compact()
    assert idx.build_pyramids(base=64) == 0   # merged runs inherited
    np.testing.assert_array_equal(
        idx.density([WORLD], None, None, WORLD, 64, 64), want)


# -- /tiles endpoint -------------------------------------------------------
def call(app, method, path, body=None):
    raw = json.dumps(body).encode() if body is not None else b""
    captured = {}

    def start_response(status, headers):
        captured["status"] = int(status.split()[0])
        captured["headers"] = dict(headers)

    qs = ""
    if "?" in path:
        path, qs = path.split("?", 1)
    environ = {
        "REQUEST_METHOD": method,
        "PATH_INFO": path,
        "QUERY_STRING": qs,
        "CONTENT_LENGTH": str(len(raw)),
        "wsgi.input": io.BytesIO(raw),
    }
    chunks = app(environ, start_response)
    body = b"".join(chunks)
    ctype = captured["headers"].get("Content-Type", "")
    parsed = (json.loads(body.decode())
              if "json" in ctype and body else body)
    return captured["status"], parsed


@pytest.fixture(scope="module")
def tile_app():
    from geomesa_tpu.web import WebApp

    ds = TpuDataStore(user="tiler")
    ds.create_schema(
        "pts", "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
               f"geomesa.lean.generation.slots={SLOTS},"
               "geomesa.lean.compaction.factor=0")
    x, y, t = _data(3 * SLOTS)
    ds.write("pts", {"dtg": t, "geom": (x, y)})
    ds.build_pyramids("pts")
    return WebApp(ds), (x, y, t)


def test_tiles_endpoint_serves_json_and_png(tile_app):
    app, (x, y, t) = tile_app
    status, body = call(app, "GET", "/tiles/0/0/0?schema=pts")
    assert status == 200
    assert body["z"] == 0 and body["tile"] == 256
    grid = np.asarray(body["grid"])
    np.testing.assert_array_equal(
        grid, _brute_grid(x, y, np.ones(len(x), bool),
                          WORLD, 256, 256))
    assert body["total"] == len(x)
    status, png = call(app, "GET",
                       "/tiles/0/0/0?schema=pts&format=png")
    assert status == 200
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


def test_tiles_endpoint_cql_filter_and_timeout_param(tile_app):
    app, (x, y, t) = tile_app
    cql = "BBOX(geom, -75, 40, -74, 41)"
    status, body = call(
        app, "GET",
        f"/tiles/0/0/0?schema=pts&cql={cql}&timeout_ms=60000")
    assert status == 200
    m = (x >= -75) & (x <= -74) & (y >= 40) & (y <= 41)
    assert body["total"] == int(m.sum())


def test_tiles_endpoint_request_hardening(tile_app):
    app, _ = tile_app
    cases = [
        ("/tiles/abc/0/0?schema=pts", 400),        # malformed z
        ("/tiles/0/0/0.5?schema=pts", 400),        # malformed y
        ("/tiles/0/0/0", 400),                     # missing schema
        ("/tiles/0/0/0?schema=nope", 404),         # unknown schema
        ("/tiles/1/2/0?schema=pts", 400),          # x out of range at z
        ("/tiles/-1/0/0?schema=pts", 400),         # negative zoom
        ("/tiles/31/0/0?schema=pts", 400),         # zoom past ceiling
        ("/tiles/0/0/0?schema=pts&cql=NOT%20CQL(", 400),   # bad CQL
        ("/tiles/0/0/0?schema=pts&format=gif", 400),       # bad format
        ("/tiles/0/0/0?schema=pts&tile=0", 400),           # bad tile px
        ("/tiles/0/0/0?schema=pts&tile=9999", 400),        # tile ceiling
    ]
    for path, want in cases:
        status, _body = call(app, "GET", path)
        assert status == want, path
    status, _body = call(app, "POST", "/tiles/0/0/0?schema=pts",
                         body={})
    assert status == 405


def test_warm_tile_serving_is_recompile_free(tile_app):
    from geomesa_tpu.obs import compile_count

    app, _ = tile_app
    for tx, ty in ((0, 0), (0, 1), (1, 0), (1, 1)):
        call(app, "GET", f"/tiles/1/{tx}/{ty}?schema=pts")   # warm-up
    before = compile_count()
    for tx, ty in ((0, 0), (0, 1), (1, 0), (1, 1)):
        status, _b = call(app, "GET",
                          f"/tiles/1/{tx}/{ty}?schema=pts")
        assert status == 200
    assert compile_count() - before == 0


def test_store_tile_with_visibility_masks_falls_back_exact():
    """An auth provider forces the density_process path (pyramids sum
    EVERY row; visibility filtering happens at materialization) — the
    tile counts only the rows the caller may see."""
    class Auth:
        def get_authorizations(self):
            return ["user"]

    ds = TpuDataStore(auth_provider=Auth())
    ds.create_schema(
        "vis", "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
               f"geomesa.lean.generation.slots={SLOTS},"
               "geomesa.lean.compaction.factor=0")
    x, y, t = _data(SLOTS)
    half = SLOTS // 2
    ds.write("vis", {"dtg": t[:half], "geom": (x[:half], y[:half])},
             visibility="user")
    ds.write("vis", {"dtg": t[half:], "geom": (x[half:], y[half:])},
             visibility="admin")
    ds.build_pyramids("vis")
    grid = ds.density_tile("vis", 0, 0, 0, tile=64)
    vis = np.zeros(SLOTS, bool)
    vis[:half] = True
    np.testing.assert_array_equal(
        grid, _brute_grid(x, y, vis, WORLD, 64, 64))


def test_store_tile_with_tombstones_falls_back_exact():
    """Deleted rows force the density_process path (pyramids would
    over-count them); the tile is exact over the surviving rows."""
    ds = TpuDataStore(user="tiler")
    ds.create_schema(
        "del", "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
               f"geomesa.lean.generation.slots={SLOTS},"
               "geomesa.lean.compaction.factor=0")
    x, y, t = _data(2 * SLOTS)
    ds.write("del", {"dtg": t, "geom": (x, y)})
    ds.build_pyramids("del")
    assert ds.delete("del", [str(i) for i in range(500)]) == 500
    alive = np.ones(len(x), bool)
    alive[:500] = False
    grid = ds.density_tile("del", 0, 0, 0, tile=64)
    np.testing.assert_array_equal(
        grid, _brute_grid(x, y, alive, WORLD, 64, 64))
