"""Sketch-driven cost-based planning + adaptive replanning (ISSUE 19):
the cardinality-estimator tiers (z3 cell-count sketches, attribute
histogram/count-min folds), ``plan.estimate.source`` stamping, the
named selectivity fallbacks, mid-query replan semantics (exactly once,
bit-exact, never on a well-predicted query), decide_with_options
thread-safety, and warm-plan dispatch discipline (docs/planning.md).

Named ``zz`` so the scan-heavy lean runs land late in suite ordering.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from geomesa_tpu import config
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.features.feature_type import parse_spec
from geomesa_tpu.metrics import PLAN_REPLANNED, registry
from geomesa_tpu.planning import StrategyDecider
from geomesa_tpu.planning.adaptive import (
    ReplanSignal, check_replan, replan_scope,
)
from geomesa_tpu.planning.planner import Query

MS_2018 = 1_514_764_800_000
DAY = 86_400_000
SLOTS = 512
N = 12 * SLOTS

#: bbox over the dense cluster: tiny against the data extent, so the
#: stats spatial-fraction heuristic underestimates it brutally
HOT = "BBOX(geom,-74.06,39.99,-73.99,40.06)"

_PLANNING_OPTS = ("geomesa.planning.estimator.enabled",
                  "geomesa.planning.estimator.min.rows",
                  "geomesa.planning.selectivity.equals.default",
                  "geomesa.planning.selectivity.range.default",
                  "geomesa.planning.replan.threshold",
                  "geomesa.planning.replan.min.rows")


@pytest.fixture(autouse=True)
def _clean_planning_config():
    for n in _PLANNING_OPTS:
        config.clear_property(n)
    # the fixture store is far below the production min.rows gate —
    # open it so these tests exercise the sketch tier directly
    config.set_property("geomesa.planning.estimator.min.rows", 0)
    yield
    for n in _PLANNING_OPTS:
        config.clear_property(n)


def _skewed_store() -> TpuDataStore:
    """A multi-generation lean store with 85% of the points in a dense
    cluster and the rest spread wide — the regime where whole-store
    fraction heuristics mispredict and per-generation sketches don't."""
    rng = np.random.default_rng(23)
    ds = TpuDataStore()
    ds.create_schema(
        "evt", "name:String:index=true,score:Double:index=true,"
               "dtg:Date,*geom:Point;geomesa.index.profile=lean,"
               f"geomesa.lean.generation.slots={SLOTS},"
               "geomesa.lean.compaction.factor=0")
    for lo in range(0, N, SLOTS):
        m = min(SLOTS, N - lo)
        dense = int(m * 0.85)
        ds.write("evt", {
            "name": np.where(rng.uniform(size=m) < 0.9, "hot",
                             "cold").astype(object),
            "score": rng.uniform(0.0, 100.0, m),
            "dtg": rng.integers(MS_2018, MS_2018 + 14 * DAY, m),
            "geom": (np.concatenate(
                         [rng.uniform(-74.05, -74.0, dense),
                          rng.uniform(-80.0, -70.0, m - dense)]),
                     np.concatenate(
                         [rng.uniform(40.0, 40.05, dense),
                          rng.uniform(35.0, 45.0, m - dense)]))})
    return ds


@pytest.fixture(scope="module")
def store():
    return _skewed_store()


# -- replan-scope mechanics (pure, no store) ---------------------------

def test_check_replan_outside_scope_is_noop():
    check_replan("query.scan.probe", 10**9)  # must not raise


def test_replan_scope_triggers_on_underestimate_once():
    with pytest.raises(ReplanSignal) as ei:
        with replan_scope(10.0, 8.0, min_rows=0):
            check_replan("query.scan.probe", 1000)
    sig = ei.value
    assert sig.observed == 1000 and sig.estimate == 10.0
    assert sig.point == "query.scan.probe"


def test_replan_scope_disarms_after_signal():
    try:
        with replan_scope(10.0, 8.0, min_rows=0):
            try:
                check_replan("query.scan.probe", 1000)
            except ReplanSignal:
                pass
            check_replan("query.scan.probe", 10**6)  # disarmed: no raise
    except ReplanSignal:
        pytest.fail("scope re-fired after disarming")


def test_replan_scope_respects_min_rows_and_threshold():
    with replan_scope(10.0, 8.0, min_rows=4096):
        check_replan("query.scan.probe", 1000)   # under the floor
    with replan_scope(100.0, 8.0, min_rows=0):
        check_replan("query.scan.probe", 500)    # under 8x(100+1)
    with replan_scope(100.0, 0.0, min_rows=0):
        check_replan("query.scan.probe", 10**9)  # threshold<=0 disarms


# -- estimator tiers ---------------------------------------------------

def test_z3_sketch_estimate_bounds(store):
    est = store._store("evt").estimator()
    assert est is not None
    full = est.z3_rows([(-180.0, -90.0, 180.0, 90.0)],
                       [(MS_2018, MS_2018 + 14 * DAY)])
    assert full == N
    hot = est.z3_rows([(-74.06, 39.99, -73.99, 40.06)],
                      [(MS_2018, MS_2018 + 14 * DAY)])
    hits = len(store.query_result("evt", Query.of(HOT)).positions)
    # the estimate integrates the scan's own covering at cell
    # granularity: an upper bound on candidates, nowhere near total
    assert hits <= hot <= N
    assert hot >= 0.5 * N  # the skew IS visible to the sketch
    cold = est.z3_rows([(-77.06, 42.99, -76.99, 43.06)],
                       [(MS_2018, MS_2018 + 14 * DAY)])
    assert cold < 0.1 * N


def test_attr_sketch_estimates(store):
    est = store._store("evt").estimator()
    hot = est.attr_equals_rows("name", ("hot",))
    truth = len(store.query_result("evt", Query.of("name = 'hot'"))
                .positions)
    assert hot is not None
    # count-min overcounts only; bound the error band
    assert truth <= hot <= 1.25 * truth
    half = est.attr_range_rows("score", 0.0, 50.0)
    assert half is not None
    assert 0.3 * N <= half <= 0.7 * N
    # unanswerable tiers report None, never a fake number
    assert est.attr_equals_rows("nosuch", ("x",)) is None


def test_estimator_warm_estimates_do_no_dispatch(store):
    st = store._store("evt")
    est = st.estimator()
    est.z3_rows([(-74.06, 39.99, -73.99, 40.06)],
                [(MS_2018, MS_2018 + 3 * DAY)])
    idx = st._indexes["z3"]
    d0 = idx.dispatch_count
    for _ in range(5):
        est.z3_rows([(-75.0, 39.0, -73.0, 41.0)],
                    [(MS_2018, MS_2018 + 7 * DAY)])
    assert idx.dispatch_count == d0  # cached per generation signature


def test_size_max_ranges_monotone_and_bounded(store):
    est = store._store("evt").estimator()
    vals = [est.size_max_ranges(x)
            for x in (0, 100, 10_000, 1_000_000, 10**9)]
    assert vals == sorted(vals)
    assert vals[0] >= 512 and vals[-1] <= 1 << 14


def test_estimate_source_stamped_sketch(store):
    res = store.explain_analyze("evt", HOT)
    assert res.summary["estimate_source"] == "sketch"
    assert res.summary["replanned"] is False
    assert "(sketch)" in res.render()


def test_estimate_source_heuristic_when_estimator_off(store):
    config.set_property("geomesa.planning.estimator.enabled", False)
    config.set_property("geomesa.planning.replan.threshold", 0.0)
    res = store.explain_analyze("evt", HOT)
    assert res.summary["estimate_source"] in ("stats", "heuristic")


# -- named selectivity fallbacks (satellite: no bare magic) ------------

def test_selectivity_defaults_are_configurable():
    sft = parse_spec(
        "t", "name:String:index=true,dtg:Date,*geom:Point")
    d = StrategyDecider(sft, stats={}, total_count=1000)
    from geomesa_tpu.filters import parse_ecql
    cost, source = d._attr_cost("name", "equals", "x")
    assert (cost, source) == (100.0, "heuristic")  # total * 0.1
    cost, source = d._attr_cost("name", "range", (None, "x", True, True))
    assert (cost, source) == (250.0, "heuristic")  # total * 0.25
    config.set_property("geomesa.planning.selectivity.equals.default",
                        0.5)
    config.set_property("geomesa.planning.selectivity.range.default",
                        0.9)
    assert d._attr_cost("name", "equals", "x")[0] == 500.0
    assert d._attr_cost("name", "range", (None, "x", True, True))[0] == 900.0
    # the configured selectivity flows into real plans
    chosen, _ = d.decide_with_options(parse_ecql("name = 'x'"))
    assert chosen.cost == 500.0 and chosen.source == "heuristic"


# -- fraction edge cases (satellite d) ---------------------------------

def _decider(stats: dict, total: int = 1000) -> StrategyDecider:
    sft = parse_spec("t", "dtg:Date,*geom:Point")
    return StrategyDecider(sft, stats=stats, total_count=total)


class _Box:
    def __init__(self, x0, y0, x1, y1):
        self._t = (x0, y0, x1, y1)

    @property
    def envelope(self):
        return self

    def as_tuple(self):
        return self._t

    @property
    def area(self):
        x0, y0, x1, y1 = self._t
        return (x1 - x0) * (y1 - y0)


def test_spatial_fraction_empty_stats_uses_world_fraction():
    d = _decider({})
    assert d._spatial_fraction(()) == 1.0
    f = d._spatial_fraction((_Box(-180, -90, 180, 90),))
    assert f == 1.0
    assert d._spatial_fraction((_Box(0, 0, 3.6, 1.8),)) == \
        pytest.approx(1e-4)


def test_spatial_fraction_degenerate_extent():
    from geomesa_tpu.stats.stat import BBoxStat
    bb = BBoxStat("geom", xmin=5.0, ymin=7.0, xmax=5.0, ymax=7.0)
    d = _decider({"geom_bbox": bb})
    assert d._spatial_fraction((_Box(0, 0, 10, 10),)) == 1.0
    assert d._spatial_fraction((_Box(20, 20, 30, 30),)) == 0.0


def test_spatial_fraction_query_outside_extent():
    from geomesa_tpu.stats.stat import BBoxStat
    bb = BBoxStat("geom", xmin=0.0, ymin=0.0, xmax=10.0, ymax=10.0)
    d = _decider({"geom_bbox": bb})
    assert d._spatial_fraction((_Box(20, 20, 30, 30),)) == 0.0
    assert d._spatial_fraction((_Box(0, 0, 10, 10),)) == 1.0
    assert d._spatial_fraction((_Box(0, 0, 5, 10),)) == pytest.approx(0.5)


def test_temporal_fraction_edges():
    from geomesa_tpu.stats.stat import MinMax
    d = _decider({})
    assert d._temporal_fraction(()) == 1.0           # no interval
    assert d._temporal_fraction(((0, 10),)) == 0.1   # no stat: fallback
    mm = MinMax("dtg", 1000.0, 1000.0)               # degenerate span
    d = _decider({"dtg_minmax": mm})
    assert d._temporal_fraction(((0, 10),)) == 0.1
    mm2 = MinMax("dtg", 0.0, 1000.0)
    d = _decider({"dtg_minmax": mm2})
    assert d._temporal_fraction(((0, 500),)) == pytest.approx(0.5)
    # open-ended intervals clamp to the data extent
    assert d._temporal_fraction(((None, 500),)) == pytest.approx(0.5)
    assert d._temporal_fraction(((500, None),)) == pytest.approx(0.5)
    assert d._temporal_fraction(((None, None),)) == 1.0
    # fully outside the extent covers nothing
    assert d._temporal_fraction(((2000, 3000),)) == 0.0


# -- decide_with_options thread-safety (satellite c) -------------------

def test_decide_with_options_is_per_call(store):
    from geomesa_tpu.filters import parse_ecql
    st = store._store("evt")
    d = StrategyDecider(st.sft, stats=st.stats_map(),
                        total_count=N, estimator=st.estimator())
    filters = [parse_ecql(HOT), parse_ecql("name = 'hot'"),
               parse_ecql("score < 10.0"), parse_ecql("IN ('7')")]
    results: dict = {}

    def run(i: int):
        f = filters[i % len(filters)]
        for _ in range(25):
            chosen, options = d.decide_with_options(f)
            got = {o.index for o in options}
            ok = results.setdefault(i, True)
            # every per-call option set must contain its own chosen
            # strategy — a cross-thread clobber of shared state would
            # surface as a foreign option list
            results[i] = ok and chosen.index in got and chosen == min(
                options, key=lambda o: o.cost)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(results.values())
    # the mirror still exists for embedders, per-call returns don't
    # depend on it
    assert isinstance(d.last_options, tuple) and d.last_options


# -- adaptive replanning end-to-end ------------------------------------

def test_mispredict_replans_exactly_once_bit_exact(store):
    # non-adaptive oracle first
    config.set_property("geomesa.planning.replan.threshold", 0.0)
    oracle = np.sort(store.query_result("evt", Query.of(HOT)).positions)
    # heuristic plan of the skewed hot box underestimates -> replan
    config.set_property("geomesa.planning.estimator.enabled", False)
    config.set_property("geomesa.planning.replan.threshold", 2.0)
    config.set_property("geomesa.planning.replan.min.rows", 64)
    before = registry.counter(PLAN_REPLANNED).count
    res = store.explain_analyze("evt", HOT)
    assert registry.counter(PLAN_REPLANNED).count - before == 1
    assert res.summary["replanned"] is True
    assert res.summary["estimate_source"] == "observed"
    assert "REPLANNED" in res.render()
    adaptive = np.sort(
        store.query_result("evt", Query.of(HOT)).positions)
    assert np.array_equal(adaptive, oracle)


def test_well_predicted_query_never_replans(store):
    config.set_property("geomesa.planning.replan.threshold", 2.0)
    config.set_property("geomesa.planning.replan.min.rows", 64)
    before = registry.counter(PLAN_REPLANNED).count
    res = store.explain_analyze("evt", HOT)  # sketch-fed: predicted
    assert registry.counter(PLAN_REPLANNED).count == before
    assert res.summary["replanned"] is False


def test_forced_index_hint_never_replans(store):
    config.set_property("geomesa.planning.estimator.enabled", False)
    config.set_property("geomesa.planning.replan.threshold", 2.0)
    config.set_property("geomesa.planning.replan.min.rows", 64)
    before = registry.counter(PLAN_REPLANNED).count
    q = Query.of(HOT)
    q.hints["QUERY_INDEX"] = "z3"
    store.query_result("evt", q)
    assert registry.counter(PLAN_REPLANNED).count == before
