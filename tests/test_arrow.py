"""Arrow subsystem tests: schema mapping, delta-dictionary writer/reader
round trips, sorted merge, ArrowDataStore, datastore query_arrow."""

import io

import numpy as np
import pytest

pa = pytest.importorskip(
    "pyarrow", reason="arrow tests need the optional [arrow] extra")

from geomesa_tpu.arrow import (
    ArrowDataStore, DeltaWriter, merge_deltas, read_feature_batch,
    sft_to_arrow_schema,
)
from geomesa_tpu.arrow.schema import encode_record_batch
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.features.feature_type import parse_spec
from geomesa_tpu.geometry.types import Polygon

MS0 = 1514764800000  # 2018-01-01


def _sft():
    return parse_spec("tracks", "name:String,age:Int,dtg:Date,*geom:Point")


def _batch(sft, n, seed=0, names=("alice", "bob", "carol")):
    rng = np.random.default_rng(seed)
    return FeatureBatch.from_dict(sft, {
        "name": np.array([names[i % len(names)] for i in range(n)],
                         dtype=object),
        "age": rng.integers(0, 90, n).astype(np.int32),
        "dtg": rng.integers(MS0, MS0 + 7 * 86_400_000, n),
        "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n)),
    }, ids=[f"s{seed}-{i}" for i in range(n)])


def test_schema_mapping():
    sft = _sft()
    schema = sft_to_arrow_schema(sft, dictionary_fields=("name",))
    assert schema.field("name").type == pa.dictionary(pa.int32(), pa.utf8())
    assert schema.field("age").type == pa.int32()
    assert schema.field("dtg").type == pa.timestamp("ms")
    assert schema.field("geom").type == pa.list_(pa.float64(), 2)
    assert schema.field("__fid__").type == pa.utf8()
    meta = schema.metadata
    assert b"geomesa_tpu.sft" in meta


def test_encode_record_batch_dictionary_codes():
    sft = _sft()
    schema = sft_to_arrow_schema(sft, dictionary_fields=("name",))
    b = _batch(sft, 6)
    rb = encode_record_batch(b, schema, {})
    col = rb.column(rb.schema.get_field_index("name"))
    assert isinstance(col, pa.DictionaryArray)
    decoded = col.dictionary_decode().to_pylist()
    assert decoded == list(b.columns["name"])


def test_delta_writer_growing_dictionary_standard_readable():
    """Dictionaries grow across batches; the IPC stream stays readable by
    stock pyarrow and decodes to the concatenated input."""
    sft = _sft()
    w = DeltaWriter(sft, dictionary_fields=("name",))
    b1 = _batch(sft, 5, seed=1, names=("alice", "bob"))
    b2 = _batch(sft, 5, seed=2, names=("carol", "alice", "dave"))
    w.write(b1)
    w.write(b2)
    data = w.finish()

    table = pa.ipc.open_stream(io.BytesIO(data)).read_all()
    assert table.num_rows == 10
    names = table.column("name").to_pylist()
    assert names == list(b1.columns["name"]) + list(b2.columns["name"])
    # reader path → FeatureBatch
    rt = read_feature_batch(data, sft)
    assert len(rt) == 10
    x, y = rt.geom_xy()
    ex = np.concatenate([b1.columns["geom_x"], b2.columns["geom_x"]])
    np.testing.assert_allclose(x, ex)
    assert list(rt.ids) == list(b1.ids) + list(b2.ids)


def test_delta_writer_sorted_batches_and_merge():
    sft = _sft()
    streams = []
    for seed in (1, 2, 3):
        w = DeltaWriter(sft, dictionary_fields=("name",), sort_field="dtg")
        w.write(_batch(sft, 20, seed=seed))
        streams.append(w.finish())
    # each stream's batch is internally sorted
    t0 = pa.ipc.open_stream(io.BytesIO(streams[0])).read_all()
    dtg = t0.column("dtg").cast(pa.int64()).to_numpy()
    assert (np.diff(dtg) >= 0).all()
    merged = merge_deltas(streams, sort_field="dtg")
    assert merged.num_rows == 60
    md = merged.column("dtg").cast(pa.int64()).to_numpy()
    assert (np.diff(md) >= 0).all()
    # dictionary columns are decoded to plain values in the merge
    assert merged.schema.field("name").type == pa.utf8()


def test_merge_deltas_reverse_and_empty():
    sft = _sft()
    w = DeltaWriter(sft, sort_field="dtg", reverse=True)
    w.write(_batch(sft, 10))
    merged = merge_deltas([w.finish()], sort_field="dtg", reverse=True)
    md = merged.column("dtg").cast(pa.int64()).to_numpy()
    assert (np.diff(md) <= 0).all()
    empty = DeltaWriter(sft)
    assert merge_deltas([empty.finish()]) is None


def test_non_point_geometry_rides_as_wkb():
    sft = parse_spec("polys", "name:String,*geom:Polygon")
    poly = Polygon(np.array([[0, 0], [2, 0], [2, 2], [0, 0]], dtype=float))
    b = FeatureBatch.from_dict(sft, {"name": ["a"], "geom": [poly]},
                               ids=["p1"])
    w = DeltaWriter(sft)
    w.write(b)
    rt = read_feature_batch(w.finish(), sft)
    g = rt.geoms.geometry(0)
    assert g.geom_type == "Polygon"
    np.testing.assert_allclose(g.shell, poly.shell)


def test_arrow_datastore_roundtrip(tmp_path):
    root = str(tmp_path / "arrow_store")
    ds = ArrowDataStore(root, dictionary_fields=("name",), sort_field="dtg")
    sft = ds.create_schema("tracks", "name:String,age:Int,dtg:Date,*geom:Point")
    ds.write("tracks", _batch(sft, 30, seed=1))
    ds.write("tracks", _batch(sft, 20, seed=2))
    out = ds.query("tracks")
    assert len(out) == 50
    hits = ds.query("tracks", "bbox(geom, -74.8, 40.2, -74.2, 40.8)")
    bx, by = out.geom_xy()
    want = int(np.count_nonzero((bx >= -74.8) & (bx <= -74.2)
                                & (by >= 40.2) & (by <= 40.8)))
    assert len(hits) == want
    ds.close()

    # reopen: schemas persist, appends merge with existing data
    ds2 = ArrowDataStore(root)
    assert ds2.type_names == ["tracks"]
    sft2 = ds2.get_schema("tracks")
    ds2.write("tracks", _batch(sft2, 5, seed=3))
    assert ds2.count("tracks") == 55
    ds2.remove_schema("tracks")
    assert ds2.type_names == []


def test_datastore_query_arrow_table():
    ds = TpuDataStore()
    sft = ds.create_schema("t", "name:String,age:Int,dtg:Date,*geom:Point")
    ds.write("t", _batch(sft, 200, seed=4))
    table = ds.query_arrow_table(
        "t", "bbox(geom, -74.9, 40.1, -74.1, 40.9)",
        dictionary_fields=("name",), sort_field="dtg", batch_size=64)
    assert table.num_rows > 0
    dtg = table.column("dtg").cast(pa.int64()).to_numpy()
    assert (np.diff(dtg) >= 0).all()
    # empty result returns an empty table with the right schema
    empty = ds.query_arrow_table("t", "bbox(geom, 10, 10, 11, 11)")
    assert empty.num_rows == 0
    assert "geom" in empty.schema.names
