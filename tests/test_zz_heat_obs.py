"""Workload heat observability (ISSUE 12): per-generation access
temperature with storage-placement join, write-path spans, the
background-job registry, and the web surfaces + param hardening that
ride along.

The heat acceptance shape: a time-partitioned multi-generation lean
store queried repeatedly over a narrow window — the generations that
window draws from must rank hotter than generations every query
merely probes, and every ranked row must join its current device/host
placement from the storage accounting.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from geomesa_tpu.config import clear_property, set_property
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.jobs import CompactionJob, run_compaction, run_ingest
from geomesa_tpu.metrics import registry
from geomesa_tpu.obs import tracer
from geomesa_tpu.obs.heat import (
    HeatTracker, heat_report, heat_tracker, publish_heat_gauges,
)
from geomesa_tpu.obs.jobs import jobs_registry

MS = 1514764800000
DAY = 86_400_000

HOT_Q = ("BBOX(geom,-75,40,-73,42) AND dtg DURING "
         "2018-01-08T00:00:00Z/2018-01-10T00:00:00Z")


def _mk_partitioned_store(name="hevt", slots=4096, budget=None):
    """Lean z3 store with TIME-PARTITIONED generations: slice i holds
    days [3i, 3i+3), one generation per slice — so a narrow time
    window draws from specific generations (the skewed-access shape
    the autopilot needs to see)."""
    rng = np.random.default_rng(11)
    ud = (f"geomesa.index.profile=lean,"
          f"geomesa.lean.generation.slots={slots},"
          f"geomesa.lean.compaction.factor=0")
    if budget:
        ud += f",geomesa.lean.hbm.budget={budget}"
    ds = TpuDataStore(user="heat-test")
    ds.create_schema(name, f"dtg:Date,*geom:Point;{ud}")
    for i in range(4):
        lo = MS + 3 * i * DAY
        ds.write(name, {
            "dtg": rng.integers(lo, lo + 3 * DAY, slots),
            "geom": (rng.uniform(-75, -73, slots),
                     rng.uniform(40, 42, slots))})
    return ds


def _call(app, method, path):
    cap = {}

    def sr(status, headers):
        cap["status"] = int(status.split()[0])
        cap["headers"] = dict(headers)

    qs = ""
    if "?" in path:
        path, qs = path.split("?", 1)
    body = b"".join(app({
        "REQUEST_METHOD": method, "PATH_INFO": path, "QUERY_STRING": qs,
        "CONTENT_LENGTH": "0", "wsgi.input": io.BytesIO(b"")}, sr))
    return cap["status"], cap["headers"], body.decode()


# -- access temperature (tentpole a) ---------------------------------------

def test_heat_ranks_skewed_access_hot_over_cold():
    """ACCEPTANCE: generations a repeated narrow-window query draws
    from rank above generations it only probes, and every ranked row
    joins its current placement from the storage report."""
    ds = _mk_partitioned_store()
    for _ in range(5):
        ds.query("hevt", HOT_Q)
    rep = ds.heat_report()
    rows = [r for r in rep["generations"]
            if (r["schema"], r["index"]) == ("hevt", "z3")]
    assert len(rows) == 4
    hot = [r for r in rows if r["rows_matched"] > 0]
    cold = [r for r in rows if r["rows_matched"] == 0]
    assert hot and cold, "expected a skewed hot/cold split"
    # every hot generation ranks strictly above every cold one
    assert max(r["rank"] for r in hot) < min(r["rank"] for r in cold)
    assert all(r["temperature"] > 0 for r in hot)
    # cold generations were still probed (scans counted, zero weight)
    assert all(r["scans"] >= 5 for r in cold)
    assert all(r["temperature"] == 0.0 for r in cold)
    # placement join: every row carries its CURRENT tier + bytes from
    # the storage accounting, consistent with the storage report
    st = ds._store("hevt")._indexes["z3"].storage_stats()
    by_gen = {g["gen_id"]: g for g in st["generations"]}
    for r in rows:
        p = r["placement"]
        assert p["tier"] == by_gen[r["gen_id"]]["tier"]
        assert p["rows"] == by_gen[r["gen_id"]]["rows"]
        assert p["device_bytes"] == by_gen[r["gen_id"]]["device_bytes"]
    # aggregates cover the index
    agg = rep["indexes"]["hevt.z3"]
    assert agg["generations"] == 4 and agg["scans"] >= 20


def test_untouched_generations_appear_cold():
    """Generations no query ever touched still appear in the report
    (temperature 0) — the autopilot must see the coldest data, not
    just the warmest."""
    ds = _mk_partitioned_store(name="cold1")
    rep = ds.heat_report()     # no queries at all
    rows = [r for r in rep["generations"] if r["schema"] == "cold1"]
    assert len(rows) == 4
    assert all(r["temperature"] == 0.0 and r["scans"] == 0
               for r in rows)
    assert all(r["placement"]["rows"] > 0 for r in rows)


def test_temperature_decays_with_tau():
    """The documented formula: a touch contributes exp(-(now-t)/τ)."""
    tr = HeatTracker(tau_s=10.0)
    tr.record(("s", "z3"), [(1, "keys", 100, 1600, 7)], now=0.0)
    snap = tr.snapshot(now=0.0)
    assert snap[("s", "z3", 1)]["temperature"] == pytest.approx(1.0)
    assert snap[("s", "z3", 1)]["rows_matched"] == 7
    snap = tr.snapshot(now=10.0)
    assert snap[("s", "z3", 1)]["temperature"] == pytest.approx(
        np.exp(-1.0))
    # a second touch stacks on the decayed score
    tr.record(("s", "z3"), [(1, "keys", 100, 1600, 3)], now=10.0)
    snap = tr.snapshot(now=10.0)
    assert snap[("s", "z3", 1)]["temperature"] == pytest.approx(
        1.0 + np.exp(-1.0))
    # zero-match probes count scans but add no heat
    tr.record(("s", "z3"), [(2, "keys", 100, 1600, 0)], now=10.0)
    snap = tr.snapshot(now=10.0)
    assert snap[("s", "z3", 2)]["temperature"] == 0.0
    assert snap[("s", "z3", 2)]["scans"] == 1


def test_compaction_merges_inherit_temperature():
    """LSM maintenance must not reset hot data to cold: the merged
    generation inherits its sources' decayed temperatures."""
    # the 700 kB budget demotes sealed runs to the keys tier, where
    # the size-tiered planner can group them
    ds = _mk_partitioned_store(name="cmp1", budget=700000)
    for _ in range(3):
        ds.query("cmp1", "BBOX(geom,-75,40,-73,42)")   # heat all gens
    idx = ds._store("cmp1")._indexes["z3"]
    before = heat_tracker.snapshot()
    total_before = sum(v["temperature"] for k, v in before.items()
                      if k[0] == "cmp1")
    assert total_before > 0
    stats = idx.compact(factor=2)
    assert stats["merged_groups"] >= 1
    rep = ds.heat_report()
    rows = [r for r in rep["generations"] if r["schema"] == "cmp1"]
    # the merged run carries forward its sources' heat (within decay
    # slack over the test's wall time)
    assert sum(r["temperature"] for r in rows) == pytest.approx(
        total_before, rel=0.05)
    live_ids = {g.gen_id for g in idx.generations}
    assert {r["gen_id"] for r in rows} == live_ids


def test_tracker_bounds_entries():
    tr = HeatTracker(tau_s=10.0, max_entries=20)
    for g in range(100):
        tr.record(("s", "z3"), [(g, "keys", 1, 16, 1)], now=float(g))
    assert len(tr) <= 20
    # the hottest (latest) entries survive the eviction
    assert ("s", "z3", 99) in tr.snapshot(now=100.0)


def test_heat_disabled_records_nothing():
    tr_len = len(heat_tracker)
    set_property("geomesa.obs.heat.enabled", False)
    try:
        ds = _mk_partitioned_store(name="hoff")
        ds.query("hoff", "BBOX(geom,-75,40,-73,42)")
        assert not any(k[0] == "hoff"
                       for k in heat_tracker.snapshot())
        assert len(heat_tracker) <= tr_len + 1
    finally:
        clear_property("geomesa.obs.heat.enabled")


def test_heat_gauges_publish_and_retire():
    ds = _mk_partitioned_store(name="hg1")
    ds.query("hg1", "BBOX(geom,-75,40,-73,42)")
    rep = publish_heat_gauges(ds)
    assert rep["indexes"]
    names = registry.names()
    assert "heat.hg1.z3.temperature" in names
    assert "heat.total.temperature" in names
    # schema removal retires its keys on the next publish
    ds.remove_schema("hg1")
    heat_tracker.drop(("hg1", "z3"),
                      [r["gen_id"] for r in rep["generations"]
                       if r["schema"] == "hg1"])
    publish_heat_gauges(ds)
    assert "heat.hg1.z3.temperature" not in registry.names()


def test_heat_overhead_proxy_on_warm_queries():
    """Fast proxy for the 5% overhead budget: warm repeated queries
    with heat tracking + tracing at defaults vs fully off.  CI timing
    is noisy at ms scale, so the proxy bounds the tax at 15% on
    min-of-9 — the bench stanza (`_heat_stanza`) holds the real ≤5%
    budget at scale."""
    ds = _mk_partitioned_store(name="hperf", slots=16384)
    idx = ds._store("hperf")._indexes["z3"]
    win = [([(-75.0, 40.0, -73.0, 42.0)], MS + 2 * i * DAY,
            MS + (2 * i + 2) * DAY) for i in range(4)]

    def best_of(n):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            idx.query_many(win)
            best = min(best, time.perf_counter() - t0)
        return best

    idx.query_many(win)                    # warm/compile
    on = best_of(12)
    set_property("geomesa.obs.heat.enabled", False)
    set_property("geomesa.obs.enabled", False)
    try:
        idx.query_many(win)                # settle
        off = best_of(12)
    finally:
        clear_property("geomesa.obs.heat.enabled")
        clear_property("geomesa.obs.enabled")
    assert on <= off * 1.15, (on, off)


# -- write-path spans (tentpole b) -----------------------------------------

def test_write_trace_covers_encode_index_seal_observe_device():
    ds = _mk_partitioned_store(name="wsp1", slots=2048)
    rng = np.random.default_rng(3)
    with tracer.capture() as cap:
        ds.write("wsp1", {
            "dtg": rng.integers(MS, MS + DAY, 5000),
            "geom": (rng.uniform(-75, -73, 5000),
                     rng.uniform(40, 42, 5000))})
    traces = cap.traces()
    assert len(traces) == 1
    t = traces[0]
    assert t.root_span.name == "write"
    assert t.root_span.attributes["schema"] == "wsp1"
    assert t.root_span.attributes["rows"] == 5000
    names = [s.name for s in t.spans]
    for expect in ("write.encode", "write.index", "write.seal",
                   "write.observe", "write.device", "write"):
        assert expect in names, names
    # 5000 rows over 2048 slots seals at least two generations
    assert names.count("write.seal") >= 2
    idx_spans = [s for s in t.spans if s.name == "write.index"]
    assert {s.attributes["index"] for s in idx_spans} == {"z3"}
    # device attribution: the block-until-ready wait rolled up
    dev = [s for s in t.spans if s.name == "write.device"]
    assert dev and "device_ms" in dev[0].attributes
    assert "device_ms" in t.root_span.attributes
    assert registry.counter("write.seals").count >= 2


def test_write_spill_traced_under_budget_pressure():
    """A tight HBM budget forces device→host spills mid-ingest; the
    spill is a device span with honest block-until-ready ms."""
    ds = _mk_partitioned_store(name="wsp2", slots=8192, budget=600000)
    rng = np.random.default_rng(4)
    with tracer.capture() as cap:
        for _ in range(2):
            ds.write("wsp2", {
                "dtg": rng.integers(MS, MS + DAY, 8192),
                "geom": (rng.uniform(-75, -73, 8192),
                         rng.uniform(40, 42, 8192))})
    spills = [s for t in cap.traces() for s in t.spans
              if s.name == "write.spill"]
    assert spills, "expected spills under a 600 kB budget"
    assert all(s.attributes.get("kind") == "device" for s in spills)
    assert all("device_ms" in s.attributes for s in spills)
    assert registry.counter("write.spills").count >= len(spills)


def test_write_block_opt_out_skips_device_span():
    set_property("geomesa.obs.write.block", False)
    try:
        ds = _mk_partitioned_store(name="wsp3", slots=2048)
        rng = np.random.default_rng(5)
        with tracer.capture() as cap:
            ds.write("wsp3", {
                "dtg": rng.integers(MS, MS + DAY, 1000),
                "geom": (rng.uniform(-75, -73, 1000),
                         rng.uniform(40, 42, 1000))})
        names = [s.name for t in cap.traces() for s in t.spans]
        assert "write.device" not in names
        assert "write.index" in names
    finally:
        clear_property("geomesa.obs.write.block")


# -- background-job registry (tentpole c) ----------------------------------

def test_compaction_job_registers_with_phases_and_outcome():
    ds = _mk_partitioned_store(name="job1")
    out = run_compaction(ds, "job1")
    rec = jobs_registry.jobs(kind="compaction", limit=1)[0]
    assert rec.state == "succeeded"
    assert rec.kind == "compaction"
    assert [p["name"] for p in rec.phases] == ["compact"]
    assert rec.phases[0]["ms"] >= 0
    assert rec.progress["merged_groups"] == sum(
        v["merged_groups"] for v in out.values())
    assert rec.duration_ms > 0 and rec.end_ts >= rec.start_ts


def test_failed_job_records_terminal_outcome():
    """ACCEPTANCE: a crashed job is visible with state=failed and the
    error — not vanished."""
    ds = _mk_partitioned_store(name="job2")
    with pytest.raises(KeyError):
        CompactionJob(ds, "no_such_schema").run()
    rec = jobs_registry.jobs(kind="compaction", state="failed",
                             limit=1)[0]
    assert rec.state == "failed"
    assert "no_such_schema" in rec.error
    assert registry.counter("job.compaction.failures").count >= 1


def test_ingest_job_registers_with_progress(tmp_path):
    ds = TpuDataStore(user="heat-test")
    ds.create_schema("ipts", "name:String,v:Int,dtg:Date,*geom:Point")
    files = []
    for i in range(3):
        p = tmp_path / f"in{i}.csv"
        p.write_text("\n".join(
            f"x{j},{j},{MS + j},{i}.25,1.5" for j in range(20)) + "\n")
        files.append(str(p))
    config = {
        "type": "csv",
        "fields": [
            {"name": "name", "transform": "$0"},
            {"name": "v", "transform": "toInt($1)"},
            {"name": "dtg", "transform": "toLong($2)"},
            {"name": "geom", "transform": "point($3,$4)"},
        ],
        "options": {"error-mode": "skip"},
    }
    result = run_ingest(ds, "ipts", config, files, workers=2)
    assert result.ingested == 60
    rec = jobs_registry.jobs(kind="ingest", limit=1)[0]
    assert rec.state == "succeeded"
    assert [p["name"] for p in rec.phases] == ["setup", "ingest"]
    assert rec.progress == {"files": 3, "ingested": 60, "failed": 0}
    assert rec.detail["schema"] == "ipts"


# -- web surfaces + param hardening (satellites) ---------------------------

def test_debug_heat_endpoint_and_paging():
    from geomesa_tpu.web import WebApp
    ds = _mk_partitioned_store(name="web1")
    for _ in range(3):
        ds.query("web1", HOT_Q)
    app = WebApp(ds)
    status, _, body = _call(app, "GET", "/debug/heat")
    assert status == 200
    rep = json.loads(body)
    rows = [r for r in rep["generations"] if r["schema"] == "web1"]
    assert len(rows) == 4
    assert rows == sorted(rows, key=lambda r: r["rank"])
    # heat gauges refreshed by the report land in the prom scrape
    status, _, text = _call(app, "GET", "/metrics.prom")
    assert status == 200
    assert "geomesa_heat_web1_z3_temperature" in text.replace(".", "_")
    # paging truncates the ranked list
    status, _, body = _call(app, "GET", "/debug/heat?limit=2")
    assert status == 200
    assert len(json.loads(body)["generations"]) == 2
    status, _, _ = _call(app, "GET", "/debug/heat?limit=nope")
    assert status == 400
    status, _, _ = _call(app, "GET", "/debug/heat?limit=-1")
    assert status == 400


def test_debug_jobs_endpoint_and_filters():
    from geomesa_tpu.web import WebApp
    ds = _mk_partitioned_store(name="web2")
    run_compaction(ds, "web2")
    app = WebApp(ds)
    status, _, body = _call(app, "GET", "/debug/jobs?kind=compaction")
    assert status == 200
    jobs = json.loads(body)["jobs"]
    assert jobs and jobs[0]["kind"] == "compaction"
    assert jobs[0]["state"] == "succeeded"
    assert jobs[0]["phases"]
    status, _, body = _call(app, "GET", "/debug/jobs?limit=1")
    assert status == 200 and len(json.loads(body)["jobs"]) == 1
    status, _, _ = _call(app, "GET", "/debug/jobs?state=exploded")
    assert status == 400
    status, _, _ = _call(app, "GET", "/debug/jobs?limit=zz")
    assert status == 400


def test_traces_paging_and_param_400s():
    from geomesa_tpu.web import WebApp
    ds = _mk_partitioned_store(name="web3")
    for _ in range(4):
        ds.query("web3", "BBOX(geom,-75,40,-73,42)")
    app = WebApp(ds)
    status, _, body = _call(app, "GET", "/traces")
    assert status == 200
    n_all = len(json.loads(body))
    assert n_all >= 4
    status, _, body = _call(app, "GET", "/traces?limit=2")
    assert status == 200
    page = json.loads(body)
    assert len(page) == 2
    # newest-last contract: the page is the TAIL of the full list
    status, _, body = _call(app, "GET", "/traces")
    assert [t["trace_id"] for t in page] == \
        [t["trace_id"] for t in json.loads(body)[-2:]]
    for bad in ("/traces?limit=abc", "/traces?limit=-5",
                "/traces?slow=maybe"):
        status, _, _ = _call(app, "GET", bad)
        assert status == 400, bad


def test_debug_storage_audit_param():
    from geomesa_tpu.web import WebApp
    ds = _mk_partitioned_store(name="web4")
    app = WebApp(ds)
    status, _, body = _call(app, "GET", "/debug/storage")
    assert status == 200
    assert "reconciliation" in json.loads(body)
    status, _, body = _call(app, "GET", "/debug/storage?audit=0")
    assert status == 200
    assert "reconciliation" not in json.loads(body)
    status, _, _ = _call(app, "GET", "/debug/storage?audit=banana")
    assert status == 400


def test_explain_malformed_cql_is_400():
    from geomesa_tpu.web import WebApp
    ds = _mk_partitioned_store(name="web5")
    app = WebApp(ds)
    status, _, body = _call(
        app, "GET", "/explain?schema=web5&cql=BBOX((")
    assert status == 400, body
    status, _, _ = _call(app, "GET", "/explain")
    assert status == 400


# -- reporter restart + concurrent rotation (satellite) --------------------

def test_periodic_reporter_stop_then_restart(tmp_path):
    """stop() must leave the scheduler restartable: a second start()
    spins a FRESH thread that keeps reporting."""
    from geomesa_tpu.metrics import (
        DelimitedFileReporter, MetricRegistry, PeriodicReporter,
    )
    reg = MetricRegistry()
    reg.counter("obs.test.restarts").inc()
    path = tmp_path / "metrics.csv"
    pr = PeriodicReporter(DelimitedFileReporter(reg, str(path)),
                          interval_s=0.02)
    pr.start()
    t1 = pr._thread
    time.sleep(0.08)
    pr.stop()
    assert pr._thread is None
    n_stopped = path.read_text().count("obs.test.restarts")
    assert n_stopped >= 1
    pr.start()                     # restart after stop
    t2 = pr._thread
    assert t2 is not None and t2 is not t1 and t2.is_alive()
    time.sleep(0.08)
    pr.stop()
    assert path.read_text().count("obs.test.restarts") > n_stopped
    # idempotent stop
    pr.stop()


def test_jsonl_rotation_under_concurrent_writer_and_query_threads(
        tmp_path):
    """The write-path spans make writer-thread + query-thread trace
    emission real: drive both through a size-capped JsonlExporter and
    assert every line stays valid JSON and retention stays bounded
    across rotations (no torn lines, no lost sink)."""
    from geomesa_tpu.obs import JsonlExporter, Tracer

    path = tmp_path / "traces.jsonl"
    cap = 20_000
    tr = Tracer(exporters=[JsonlExporter(str(path), max_bytes=cap)])
    stop = threading.Event()
    errors: list = []

    def emit(kind: str):
        try:
            while not stop.is_set():
                with tr.span(kind, payload="x" * 120):
                    with tr.span(f"{kind}.child"):
                        pass
        except Exception as e:  # noqa: BLE001 — surface in the test
            errors.append(e)

    threads = [threading.Thread(target=emit, args=("write",)),
               threading.Thread(target=emit, args=("query",))]
    for t in threads:
        t.start()
    time.sleep(0.4)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    assert not errors
    tr.exporters[0].close()
    assert path.exists()
    # rotation happened (enough concurrent traffic to pass the cap)
    assert (tmp_path / "traces.jsonl.1").exists()
    kinds = set()
    for f in (path, tmp_path / "traces.jsonl.1"):
        assert f.stat().st_size <= cap
        for line in f.read_text().splitlines():
            rec = json.loads(line)       # no torn/interleaved lines
            kinds.add(rec["spans"][-1]["name"])
    assert kinds == {"write", "query"}
