"""XZ2 curve vs a pure-python descent oracle + the reference's
containment/disjoint query cases (XZ2SFCTest.scala)."""

import math

import numpy as np
import pytest

from geomesa_tpu.curve.xz2 import XZ2SFC, xz2_sfc

G = 12


def py_index(sfc: XZ2SFC, xmin, ymin, xmax, ymax):
    """Direct double-precision descent, following the paper definitions."""
    g = sfc.g
    xs, ys = sfc.x_hi - sfc.x_lo, sfc.y_hi - sfc.y_lo
    nxmin = (xmin - sfc.x_lo) / xs
    nymin = (ymin - sfc.y_lo) / ys
    nxmax = (xmax - sfc.x_lo) / xs
    nymax = (ymax - sfc.y_lo) / ys
    max_dim = max(nxmax - nxmin, nymax - nymin)
    if max_dim <= 0.0:
        l1 = g
    else:
        l1 = int(math.floor(math.log(max_dim) / math.log(0.5)))
    if l1 >= g:
        length = g
    else:
        w2 = 0.5 ** (l1 + 1)
        fits = lambda mn, mx: mx <= math.floor(mn / w2) * w2 + 2 * w2
        length = l1 + 1 if fits(nxmin, nxmax) and fits(nymin, nymax) else l1
    x, y = nxmin, nymin
    lo_x, lo_y, hi_x, hi_y = 0.0, 0.0, 1.0, 1.0
    cs = 0
    for i in range(length):
        xc, yc = (lo_x + hi_x) / 2, (lo_y + hi_y) / 2
        q = (0 if x < xc else 1) + (0 if y < yc else 2)
        cs += 1 + q * (4 ** (g - i) - 1) // 3
        if x < xc:
            hi_x = xc
        else:
            lo_x = xc
        if y < yc:
            hi_y = yc
        else:
            lo_y = yc
    return cs


@pytest.fixture(scope="module")
def sfc():
    return xz2_sfc(G)


def test_index_matches_oracle(sfc, rng):
    for _ in range(300):
        x0, x1 = np.sort(rng.uniform(-180, 180, 2))
        y0, y1 = np.sort(rng.uniform(-90, 90, 2))
        got = int(sfc.index(x0, y0, x1, y1, xp=np))
        assert got == py_index(sfc, x0, y0, x1, y1), (x0, y0, x1, y1)


def test_point_index_matches_oracle(sfc, rng):
    for _ in range(100):
        x = rng.uniform(-180, 180)
        y = rng.uniform(-90, 90)
        assert int(sfc.index(x, y, x, y, xp=np)) == py_index(sfc, x, y, x, y)


def test_extremes(sfc):
    # whole world: l1=0 but the l1+1 refinement fits (a 1x1 object spans
    # two 0.5-cells on each axis), so length=1 and the code is 1 — matches
    # the reference's own formula
    assert int(sfc.index(-180.0, -90.0, 180.0, 90.0, xp=np)) == py_index(
        sfc, -180.0, -90.0, 180.0, 90.0) == 1
    # corners
    assert int(sfc.index(-180.0, -90.0, -180.0, -90.0, xp=np)) == py_index(
        sfc, -180.0, -90.0, -180.0, -90.0)
    assert int(sfc.index(180.0, 90.0, 180.0, 90.0, xp=np)) == py_index(
        sfc, 180.0, 90.0, 180.0, 90.0)


def _code_in_ranges(code, ranges):
    return any(lo <= code <= hi for lo, hi in ranges)


def test_reference_polygon_query_cases(sfc):
    # mirror of XZ2SFCTest "index polygons and query them"
    poly = int(sfc.index(10.0, 10.0, 12.0, 12.0, xp=np))
    matching = [
        (9.0, 9.0, 13.0, 13.0),
        (-180.0, -90.0, 180.0, 90.0),
        (0.0, 0.0, 180.0, 90.0),
        (0.0, 0.0, 20.0, 20.0),
        (11.0, 11.0, 13.0, 13.0),
        (9.0, 9.0, 11.0, 11.0),
        (10.5, 10.5, 11.5, 11.5),
        (11.0, 11.0, 11.0, 11.0),
    ]
    disjoint = [
        (-180.0, -90.0, 8.0, 8.0),
        (0.0, 0.0, 8.0, 8.0),
        (9.0, 9.0, 9.5, 9.5),
        (20.0, 20.0, 180.0, 90.0),
    ]
    for w in matching:
        assert _code_in_ranges(poly, sfc.ranges([w])), w
    for w in disjoint:
        assert not _code_in_ranges(poly, sfc.ranges([w])), w


def test_reference_point_query_cases(sfc):
    poly = int(sfc.index(11.0, 11.0, 11.0, 11.0, xp=np))
    matching = [
        (9.0, 9.0, 13.0, 13.0),
        (-180.0, -90.0, 180.0, 90.0),
        (0.0, 0.0, 180.0, 90.0),
        (0.0, 0.0, 20.0, 20.0),
        (11.0, 11.0, 13.0, 13.0),
        (9.0, 9.0, 11.0, 11.0),
        (10.5, 10.5, 11.5, 11.5),
        (11.0, 11.0, 11.0, 11.0),
    ]
    disjoint = [
        (-180.0, -90.0, 8.0, 8.0),
        (0.0, 0.0, 8.0, 8.0),
        (9.0, 9.0, 9.5, 9.5),
        (12.5, 12.5, 13.5, 13.5),
        (20.0, 20.0, 180.0, 90.0),
    ]
    for w in matching:
        assert _code_in_ranges(poly, sfc.ranges([w])), w
    for w in disjoint:
        assert not _code_in_ranges(poly, sfc.ranges([w])), w


def test_ranges_cover_all_intersecting_objects(sfc, rng):
    """The core correctness invariant: any object bbox intersecting the
    query window must have its sequence code inside the covering ranges."""
    n = 2000
    cx = rng.uniform(-170, 170, n)
    cy = rng.uniform(-80, 80, n)
    w = rng.exponential(1.0, n).clip(0, 30)
    h = rng.exponential(1.0, n).clip(0, 30)
    xmin, xmax = cx - w / 2, cx + w / 2
    ymin, ymax = cy - h / 2, cy + h / 2
    xmin, xmax = xmin.clip(-180, 180), xmax.clip(-180, 180)
    ymin, ymax = ymin.clip(-90, 90), ymax.clip(-90, 90)
    codes = sfc.index(xmin, ymin, xmax, ymax, xp=np)
    for window in [(-10.0, -10.0, 10.0, 10.0), (50.0, 20.0, 51.0, 21.0),
                   (-180.0, -90.0, -100.0, 0.0)]:
        ranges = sfc.ranges([window])
        intersects = (
            (xmax >= window[0]) & (xmin <= window[2])
            & (ymax >= window[1]) & (ymin <= window[3])
        )
        in_ranges = np.zeros(n, dtype=bool)
        for lo, hi in ranges:
            in_ranges |= (codes >= lo) & (codes <= hi)
        missed = np.flatnonzero(intersects & ~in_ranges)
        assert missed.size == 0, (window, missed[:5])


def test_budget_produces_superset(sfc, rng):
    window = (-10.0, -10.0, 40.0, 30.0)
    exact = sfc.ranges([window], max_ranges=10**9)
    tight = sfc.ranges([window], max_ranges=30)
    assert len(tight) < len(exact)
    # every code covered by exact must be covered by tight
    n = 1000
    x = rng.uniform(-15, 45, n)
    y = rng.uniform(-15, 35, n)
    codes = sfc.index(x, y, x + 0.1, y + 0.1, xp=np)
    cov_exact = np.zeros(n, bool)
    for lo, hi in exact:
        cov_exact |= (codes >= lo) & (codes <= hi)
    cov_tight = np.zeros(n, bool)
    for lo, hi in tight:
        cov_tight |= (codes >= lo) & (codes <= hi)
    assert (cov_exact <= cov_tight).all()
