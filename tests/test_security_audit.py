"""Security (visibility/auth), audit, interceptors, metrics."""

import numpy as np
import pytest

from geomesa_tpu.audit import InMemoryAuditWriter, JsonlAuditWriter
from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.metrics import (
    DelimitedFileReporter, LoggingReporter, MetricRegistry,
)
from geomesa_tpu.security import (
    StaticAuthorizationsProvider, parse_visibility, visibility_mask,
)

MS_2018 = 1514764800000


# -- visibility expression grammar (VisibilityEvaluator.scala semantics) ----

def test_visibility_parse_eval():
    assert parse_visibility("").evaluate(set())
    assert parse_visibility("admin").evaluate({"admin"})
    assert not parse_visibility("admin").evaluate({"user"})
    assert parse_visibility("admin&user").evaluate({"admin", "user"})
    assert not parse_visibility("admin&user").evaluate({"admin"})
    assert parse_visibility("admin|user").evaluate({"user"})
    assert parse_visibility("(a&b)|c").evaluate({"c"})
    assert parse_visibility("(a&b)|c").evaluate({"a", "b"})
    assert not parse_visibility("(a&b)|c").evaluate({"a"})
    assert parse_visibility('"od-1:x"&b').evaluate({"od-1:x", "b"})


def test_visibility_mixed_ops_require_parens():
    with pytest.raises(ValueError):
        parse_visibility("a&b|c")
    with pytest.raises(ValueError):
        parse_visibility("a&(b")
    with pytest.raises(ValueError):
        parse_visibility("a &")


def test_visibility_mask_vectorized():
    col = np.array(["admin", "", "admin&user", "user|ops", "admin"],
                   dtype=object)
    mask = visibility_mask(col, {"admin"})
    np.testing.assert_array_equal(mask, [True, True, False, False, True])
    mask = visibility_mask(col, {"user"})
    np.testing.assert_array_equal(mask, [False, True, False, True, False])


# -- row-level security through the datastore -------------------------------

def _store_with_vis(auths):
    ds = TpuDataStore(
        auth_provider=StaticAuthorizationsProvider(auths), user="tester")
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    n = 100
    rng = np.random.default_rng(11)
    cols = lambda: {
        "name": np.array(["f"] * n, dtype=object),
        "dtg": np.full(n, MS_2018, dtype=np.int64),
        "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n)),
    }
    ds.write("t", cols(), visibility="admin")
    ds.write("t", cols(), visibility="")
    ds.write("t", cols(), visibility="secret&ops")
    return ds


def test_query_visibility_filtering():
    ds = _store_with_vis({"admin"})
    out = ds.query("t", "BBOX(geom,-76,39,-73,42)")
    assert len(out) == 200  # admin rows + public rows
    ds2 = _store_with_vis(set())
    assert len(ds2.query("t", "BBOX(geom,-76,39,-73,42)")) == 100
    ds3 = _store_with_vis({"secret", "ops", "admin"})
    assert len(ds3.query("t", "BBOX(geom,-76,39,-73,42)")) == 300


def test_write_invalid_visibility_rejected():
    ds = TpuDataStore()
    ds.create_schema("t", "dtg:Date,*geom:Point")
    with pytest.raises(ValueError):
        ds.write("t", {"dtg": np.array([MS_2018]),
                       "geom": (np.array([-75.0]), np.array([40.0]))},
                 visibility="a&b|c")


# -- audit ------------------------------------------------------------------

def test_audit_events(tmp_path):
    mem = InMemoryAuditWriter()
    ds = TpuDataStore(audit_writer=mem, user="alice")
    ds.create_schema("t", "dtg:Date,*geom:Point")
    n = 50
    rng = np.random.default_rng(3)
    ds.write("t", {"dtg": np.full(n, MS_2018, dtype=np.int64),
                   "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n))})
    ds.query("t", "BBOX(geom,-76,39,-73,42)")
    events = mem.query_events("t")
    assert len(events) == 1
    ev = events[0]
    assert ev.user == "alice" and ev.hits == n
    assert "BBox" in ev.filter or "bbox" in ev.filter.lower()
    assert ev.plan_time_ms >= 0 and ev.scan_time_ms >= 0

    jl = JsonlAuditWriter(str(tmp_path / "audit.jsonl"))
    jl.write_event(ev)
    line = (tmp_path / "audit.jsonl").read_text().strip()
    assert '"user": "alice"' in line


# -- interceptors -----------------------------------------------------------

def test_guarded_interceptor_blocks_full_scan():
    ds = TpuDataStore()
    ds.create_schema(
        "t",
        "dtg:Date,*geom:Point;"
        "geomesa.query.interceptors="
        "geomesa_tpu.planning.interceptor:GuardedQueryInterceptor")
    n = 10
    rng = np.random.default_rng(5)
    ds.write("t", {"dtg": np.full(n, MS_2018, dtype=np.int64),
                   "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n))})
    with pytest.raises(ValueError, match="full-table scan blocked"):
        ds.query("t", "INCLUDE")
    assert len(ds.query("t", "BBOX(geom,-76,39,-73,42)")) == n


# -- metrics ----------------------------------------------------------------

def test_metrics_registry_and_reporters(tmp_path, caplog):
    reg = MetricRegistry()
    reg.counter("c").inc(3)
    with reg.timer("t"):
        pass
    reg.histogram("h").update(2.0)
    reg.histogram("h").update(4.0)
    snap = reg.snapshot()
    assert snap["c"]["count"] == 3
    assert snap["h"]["mean"] == 3.0 and snap["h"]["max"] == 4.0
    assert snap["t"]["count"] == 1

    path = tmp_path / "metrics.csv"
    DelimitedFileReporter(reg, str(path)).report()
    text = path.read_text()
    assert "c" in text and "count=3" in text

    import logging
    with caplog.at_level(logging.INFO, logger="geomesa_tpu.metrics"):
        LoggingReporter(reg).report()
    assert any("c" in r.message for r in caplog.records)


def test_query_metrics_increment():
    from geomesa_tpu.metrics import registry
    before = registry.counter("query.mt.count").count
    ds = TpuDataStore()
    ds.create_schema("mt", "dtg:Date,*geom:Point")
    ds.write("mt", {"dtg": np.array([MS_2018]),
                    "geom": (np.array([-75.0]), np.array([40.0]))})
    ds.query("mt", "BBOX(geom,-76,39,-73,42)")
    assert registry.counter("query.mt.count").count == before + 1


# -- review regressions ------------------------------------------------------

def test_visibility_survives_flush_reload(tmp_path):
    cat = str(tmp_path / "cat")
    ds = TpuDataStore(cat, auth_provider=StaticAuthorizationsProvider(set()))
    ds.create_schema("t", "dtg:Date,*geom:Point")
    n = 20
    rng = np.random.default_rng(2)
    mk = lambda: {"dtg": np.full(n, MS_2018, dtype=np.int64),
                  "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n))}
    ds.write("t", mk(), visibility="admin")
    ds.write("t", mk())
    ds.flush("t")

    ds2 = TpuDataStore(cat, auth_provider=StaticAuthorizationsProvider(set()))
    assert len(ds2.query("t", "BBOX(geom,-76,39,-73,42)")) == n  # not 2n
    ds3 = TpuDataStore(
        cat, auth_provider=StaticAuthorizationsProvider({"admin"}))
    assert len(ds3.query("t", "BBOX(geom,-76,39,-73,42)")) == 2 * n
    # write after reload must not crash on missing visibilities
    ds2.write("t", mk(), visibility="admin")
    assert len(ds2.query("t", "BBOX(geom,-76,39,-73,42)")) == n


def test_max_features_fills_from_authorized_rows():
    from geomesa_tpu.planning.planner import Query

    ds = _store_with_vis(set())  # only the public 100 visible
    q = Query.of("BBOX(geom,-76,39,-73,42)", max_features=50)
    out = ds.query("t", q)
    assert len(out) == 50  # limit filled from authorized rows


def test_interceptor_cache_invalidated_on_update_schema():
    from geomesa_tpu.features.feature_type import parse_spec

    ds = TpuDataStore()
    ds.create_schema("t", "dtg:Date,*geom:Point")
    n = 5
    rng = np.random.default_rng(8)
    ds.write("t", {"dtg": np.full(n, MS_2018, dtype=np.int64),
                   "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n))})
    assert len(ds.query("t", "INCLUDE")) == n  # caches empty interceptors
    sft = parse_spec(
        "t",
        "dtg:Date,*geom:Point;geomesa.query.interceptors="
        "geomesa_tpu.planning.interceptor:GuardedQueryInterceptor")
    ds.update_schema("t", sft)
    with pytest.raises(ValueError, match="full-table scan blocked"):
        ds.query("t", "INCLUDE")


def test_audit_covers_empty_store_queries():
    mem = InMemoryAuditWriter()
    ds = TpuDataStore(audit_writer=mem, user="bob")
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.query("t", "BBOX(geom,-76,39,-73,42)")  # empty store
    assert len(mem.query_events("t")) == 1


def test_stats_do_not_leak_restricted_rows():
    ds = _store_with_vis(set())   # caller sees only the public 100
    assert ds.get_count("t") == 100
    env = ds.get_bounds("t")
    assert env is not None
    topk = ds.stat("t", "name_topk")
    if topk is not None:
        assert sum(topk.counters.values()) <= 100
    lo, hi = ds.get_attribute_bounds("t", "dtg")
    assert lo >= MS_2018

    ds_all = _store_with_vis({"admin", "secret", "ops"})
    assert ds_all.get_count("t") == 300


def test_timer_concurrent_blocks():
    import threading as th
    reg = MetricRegistry()
    t = reg.timer("shared")
    errs = []

    def work():
        try:
            for _ in range(50):
                with t:
                    pass
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [th.Thread(target=work) for _ in range(4)]
    [x.start() for x in threads]
    [x.join() for x in threads]
    assert not errs
    assert t.count == 200 and t.min >= 0.0


def test_attr_visibility_survives_delete_and_flush(tmp_path):
    """Attribute guards stay aligned after deletes and persist across a
    catalog reload."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.security import StaticAuthorizationsProvider

    d = str(tmp_path / "cat")
    ds = TpuDataStore(d, auth_provider=StaticAuthorizationsProvider(["u"]))
    ds.create_schema("s", "name:String,ssn:String,dtg:Date,*geom:Point")
    ds.write("s", {"name": np.asarray(["open"], dtype=object),
                   "ssn": np.asarray(["PUBLIC"], dtype=object),
                   "dtg": np.zeros(1, np.int64),
                   "geom": (np.zeros(1), np.zeros(1))}, ids=["a"])
    ds.write("s", {"name": np.asarray(["guard"], dtype=object),
                   "ssn": np.asarray(["SECRET"], dtype=object),
                   "dtg": np.zeros(1, np.int64),
                   "geom": (np.zeros(1), np.zeros(1))}, ids=["b"],
             attribute_visibilities={"ssn": "admin"})
    ds.delete("s", ["a"])
    got = ds.query("s")
    assert list(got.column("ssn")) == [None]  # still guarded post-delete
    ds.flush("s")
    ds2 = TpuDataStore(d, auth_provider=StaticAuthorizationsProvider(["u"]))
    assert list(ds2.query("s").column("ssn")) == [None]  # survives reload
    import pytest as _pytest
    with _pytest.raises(KeyError):
        ds.write("s", {"name": np.asarray(["x"], dtype=object),
                       "ssn": np.asarray(["y"], dtype=object),
                       "dtg": np.zeros(1, np.int64),
                       "geom": (np.zeros(1), np.zeros(1))},
                 attribute_visibilities={"typo": "admin"})


def test_attr_visibility_not_probeable_via_filters():
    """Guarded values must be invisible to FILTERS and sketches, not just
    nulled in results (no CQL probing / stats side channels)."""
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.security import StaticAuthorizationsProvider

    ds = TpuDataStore(auth_provider=StaticAuthorizationsProvider(["u"]))
    ds.create_schema("pv", "name:String,ssn:String:index=true,"
                           "age:Int,dtg:Date,*geom:Point")
    ds.write("pv", {"name": np.asarray(["a"], dtype=object),
                    "ssn": np.asarray(["111"], dtype=object),
                    "age": np.asarray([42]),
                    "dtg": np.zeros(1, np.int64),
                    "geom": (np.zeros(1), np.zeros(1))},
             attribute_visibilities={"ssn": "admin", "age": "admin"})
    # filter probing returns nothing
    assert len(ds.query("pv", "ssn = '111'")) == 0
    assert len(ds.query("pv", "age = 42")) == 0
    assert len(ds.query("pv", "age > 0")) == 0
    # the row itself is still visible
    got = ds.query("pv")
    assert list(got.column("name")) == ["a"]
    assert list(got.column("ssn")) == [None]
    # stats do not leak guarded attributes
    assert ds.get_attribute_bounds("pv", "age") is None
    assert ds.stat("pv", "ssn_topk") is None
    # guarding the dtg field is rejected (indexes scan it unmasked)
    import pytest as _pytest
    with _pytest.raises(ValueError):
        ds.write("pv", {"name": np.asarray(["b"], dtype=object),
                        "ssn": np.asarray(["2"], dtype=object),
                        "age": np.asarray([1]),
                        "dtg": np.zeros(1, np.int64),
                        "geom": (np.zeros(1), np.zeros(1))},
                 attribute_visibilities={"dtg": "admin"})


def test_sort_by_guarded_column_does_not_crash():
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.planning.planner import Query
    from geomesa_tpu.security import StaticAuthorizationsProvider

    ds = TpuDataStore(auth_provider=StaticAuthorizationsProvider(["u"]))
    ds.create_schema("so", "age:Int,dtg:Date,*geom:Point")
    ds.write("so", {"age": np.asarray([3, 1, 2]),
                    "dtg": np.zeros(3, np.int64),
                    "geom": (np.zeros(3), np.zeros(3))},
             attribute_visibilities={"age": "admin"})
    got = ds.query("so", Query.of("INCLUDE", sort_by="age"))
    assert len(got) == 3 and list(got.column("age")) == [None] * 3


def test_proximity_empty_schema():
    import numpy as np
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.geometry.types import Point
    from geomesa_tpu.process.proximity import proximity_process

    ds = TpuDataStore()
    ds.create_schema("e", "v:Int,dtg:Date,*geom:Point")
    got = proximity_process(ds, "e", [Point(0, 0)], 1000)
    assert len(got) == 0
