"""Seeded differential fuzz: random data/queries vs independent oracles.

The heavyweight sweep (more trials, all periods, larger N) runs ad hoc;
these seeded versions pin the same properties in CI time:

* Z3/Z2 hit sets == brute force, across all time periods and boundary
  coordinates/timestamps.
* XZ2/XZ3 candidates are SUPERSETS of every bbox-intersecting geometry
  (lossy-by-design, never lossy the wrong way).
* point_in_polygon agrees with matplotlib's Path implementation away
  from polygon boundaries.
"""

import numpy as np
import pytest

from geomesa_tpu.curve import TimePeriod
from geomesa_tpu.geometry.types import LineString, Point, Polygon
from geomesa_tpu.index import Z2PointIndex, Z3PointIndex
from geomesa_tpu.index.xz2 import XZ2Index
from geomesa_tpu.index.xz3 import XZ3Index

MS = 1514764800000
DAY = 86_400_000


@pytest.mark.parametrize("period", [TimePeriod.DAY, TimePeriod.WEEK,
                                    TimePeriod.MONTH, TimePeriod.YEAR])
def test_fuzz_z3_all_periods(period):
    rng = np.random.default_rng(hash(period.value) % 2**32)
    n = 5000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    span = 200 * DAY
    t = rng.integers(MS, MS + span, n)
    x[0], y[0] = -180.0, -90.0
    x[1], y[1] = 180.0, 90.0
    t[2], t[3] = MS, MS + span - 1
    idx = Z3PointIndex.build(x, y, t, period=period)
    for _ in range(4):
        x0, y0 = rng.uniform(-180, 175), rng.uniform(-90, 85)
        box = (x0, y0, min(180, x0 + rng.uniform(0.1, 60)),
               min(90, y0 + rng.uniform(0.1, 60)))
        tlo = int(rng.integers(MS - DAY, MS + span))
        thi = tlo + int(rng.integers(1, span))
        got = idx.query([box], tlo, thi)
        want = np.flatnonzero(
            (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
            & (t >= tlo) & (t <= thi))
        np.testing.assert_array_equal(got, want)


def test_fuzz_z2_multibox():
    rng = np.random.default_rng(11)
    n = 8000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    idx = Z2PointIndex.build(x, y)
    for _ in range(8):
        boxes = []
        for _ in range(int(rng.integers(1, 5))):
            x0, y0 = rng.uniform(-180, 180), rng.uniform(-90, 90)
            boxes.append((x0, y0, min(180, x0 + rng.uniform(0, 40)),
                          min(90, y0 + rng.uniform(0, 40))))
        want = np.zeros(n, bool)
        for b in boxes:
            want |= (x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
        np.testing.assert_array_equal(idx.query(boxes), np.flatnonzero(want))


def _rand_geom(rng):
    kind = rng.integers(0, 3)
    cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
    if kind == 0:
        return Point(cx, cy)
    if kind == 1:
        return LineString(np.column_stack(
            [cx + rng.uniform(-2, 2, 4), cy + rng.uniform(-2, 2, 4)]))
    w, h = rng.uniform(0.01, 3), rng.uniform(0.01, 3)
    return Polygon([(cx - w, cy - h), (cx + w, cy - h),
                    (cx + w, cy + h), (cx - w, cy + h)])


def test_fuzz_xz_candidate_supersets():
    rng = np.random.default_rng(5)
    n = 800
    geoms = [_rand_geom(rng) for _ in range(n)]
    t = rng.integers(MS, MS + 30 * DAY, n)
    xz2 = XZ2Index.build(geoms, g=12)
    xz3 = XZ3Index.build(geoms, t, period="week", g=10)
    for _ in range(5):
        qx, qy = rng.uniform(-170, 170), rng.uniform(-80, 80)
        qw, qh = rng.uniform(0.5, 30), rng.uniform(0.5, 30)
        q = Polygon([(qx - qw, qy - qh), (qx + qw, qy - qh),
                     (qx + qw, qy + qh), (qx - qw, qy + qh)])
        qe = q.envelope
        inter = np.array([
            g.envelope.xmin <= qe.xmax and g.envelope.xmax >= qe.xmin
            and g.envelope.ymin <= qe.ymax and g.envelope.ymax >= qe.ymin
            for g in geoms])
        cand2 = set(int(i) for i in xz2.query(q, exact=False))
        assert set(np.flatnonzero(inter)) <= cand2
        tlo = int(rng.integers(MS, MS + 30 * DAY))
        thi = tlo + int(rng.integers(1, 10 * DAY))
        cand3 = set(int(i) for i in xz3.query(q, tlo, thi, exact=False))
        want3 = set(np.flatnonzero(inter & (t >= tlo) & (t <= thi)))
        assert want3 <= cand3


def test_fuzz_point_in_polygon_vs_matplotlib():
    mpath = pytest.importorskip("matplotlib.path")
    from geomesa_tpu.geometry.predicates import (
        point_in_polygon, points_on_rings,
    )
    rng = np.random.default_rng(3)
    for _ in range(10):
        k = int(rng.integers(3, 9))
        ang = np.sort(rng.uniform(0, 2 * np.pi, k))
        r = rng.uniform(0.5, 5, k)
        cx, cy = rng.uniform(-50, 50, 2)
        ring = np.column_stack([cx + r * np.cos(ang), cy + r * np.sin(ang)])
        poly = Polygon(ring)
        px = rng.uniform(cx - 6, cx + 6, 1000)
        py = rng.uniform(cy - 6, cy + 6, 1000)
        got = point_in_polygon(px, py, poly)
        want = mpath.Path(np.vstack([ring, ring[:1]])).contains_points(
            np.column_stack([px, py]))
        diff = got != want
        if diff.any():
            # disagreements must sit on the boundary (FP edge cases)
            near = points_on_rings(px[diff], py[diff], [poly.shell],
                                   eps=1e-9)
            assert int(diff.sum()) - int(near.sum()) <= 3


def test_fuzz_random_filters_vs_row_oracle():
    """Random filter trees: planner+evaluator hit sets equal an
    INDEPENDENT row-wise interpreter (not evaluate_filter), so a shared
    bug in the vectorized path cannot self-certify."""
    import operator
    import re as _re

    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.filters import ast as A

    rng = np.random.default_rng(99)
    n = 1500
    ds = TpuDataStore()
    ds.create_schema("t", "name:String:index=true,v:Int,f:Double,"
                          "dtg:Date,*geom:Point")
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-85, 85, n)
    name = np.asarray([f"n{i % 6}" for i in range(n)], dtype=object)
    v = rng.integers(-100, 100, n)
    fv = rng.uniform(0, 1, n)
    t = rng.integers(MS, MS + 21 * DAY, n)
    ds.write("t", {"name": name, "v": v, "f": fv, "dtg": t, "geom": (x, y)})

    def oracle(f, i):
        if isinstance(f, A._Include):
            return True
        if isinstance(f, A.And):
            return all(oracle(p, i) for p in f.filters)
        if isinstance(f, A.Or):
            return any(oracle(p, i) for p in f.filters)
        if isinstance(f, A.Not):
            return not oracle(f.filter, i)
        if isinstance(f, A.BBox):
            return (f.xmin <= x[i] <= f.xmax) and (f.ymin <= y[i] <= f.ymax)
        if isinstance(f, A.During):
            if f.lo_ms is not None and t[i] < f.lo_ms:
                return False
            return not (f.hi_ms is not None and t[i] > f.hi_ms)
        if isinstance(f, A.PropertyCompare):
            ops = {"=": operator.eq, "<>": operator.ne, "<": operator.lt,
                   "<=": operator.le, ">": operator.gt, ">=": operator.ge}
            col = {"v": v, "f": fv}[f.prop]
            return bool(ops[f.op](col[i], f.value))
        if isinstance(f, A.Between):
            col = {"v": v, "f": fv}[f.prop]
            return f.lo <= col[i] <= f.hi
        if isinstance(f, A.In):
            return name[i] in f.values
        if isinstance(f, A.Like):
            # independent character-walk LIKE matcher (NOT the
            # implementation's regex construction)
            def like(s, p):
                if not p:
                    return not s
                if p[0] == "%":
                    return any(like(s[k:], p[1:]) for k in range(len(s) + 1))
                if p[0] == "_":
                    return bool(s) and like(s[1:], p[1:])
                return bool(s) and s[0] == p[0] and like(s[1:], p[1:])
            return like(str(name[i]), f.pattern)
        raise NotImplementedError(type(f))

    def rand_filter(depth=0):
        k = rng.integers(0, 9 if depth < 2 else 7)
        if k == 0:
            x0, x1 = sorted(rng.uniform(-180, 180, 2))
            y0, y1 = sorted(rng.uniform(-85, 85, 2))
            return A.BBox("geom", float(x0), float(y0), float(x1), float(y1))
        if k == 1:
            lo = int(rng.integers(MS, MS + 20 * DAY))
            hi = lo + int(rng.integers(1, 5 * DAY))
            which = rng.integers(0, 3)
            return A.During("dtg", None if which == 1 else lo,
                            None if which == 2 else hi)
        if k == 2:
            return A.PropertyCompare(
                "v", str(rng.choice(["=", "<>", "<", "<=", ">", ">="])),
                int(rng.integers(-100, 100)))
        if k == 3:
            return A.Between("f", float(rng.uniform(0, 0.5)),
                             float(rng.uniform(0.5, 1)))
        if k == 4:
            # sizes straddle the >4 threshold of the np.isin fast path
            return A.In("name", tuple(rng.choice(
                ["n0", "n1", "n2", "n3", "n4", "n5", "zz", "yy"],
                rng.integers(1, 8), replace=False).tolist()))
        if k == 5:
            return A.Like("name",
                          str(rng.choice(["n%", "%1", "n_", "x%"])), False)
        if k == 6:
            return A.Not(rand_filter(depth + 1))
        if k == 7:
            return A.And(tuple(rand_filter(depth + 1)
                               for _ in range(int(rng.integers(2, 4)))))
        return A.Or(tuple(rand_filter(depth + 1)
                          for _ in range(int(rng.integers(2, 4)))))

    for _ in range(60):
        f = rand_filter()
        got = set(int(i) for i in ds.query_result("t", f).positions)
        want = set(i for i in range(n) if oracle(f, i))
        assert got == want, (repr(f)[:120], len(got), len(want))


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_query_many_vs_single(seed):
    """Batched multi-window scans must equal per-window single queries
    (and the brute-force oracle) for random window batches — guards the
    per-window budget + qid|pos wire coding."""
    rng = np.random.default_rng(100 + seed)
    n = 8000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    span = 30 * DAY
    t = rng.integers(MS, MS + span, n)
    idx = Z3PointIndex.build(x, y, t, period=TimePeriod.WEEK)
    n_q = int(rng.integers(1, 40))
    windows = []
    for _ in range(n_q):
        boxes = []
        for _ in range(int(rng.integers(1, 3))):
            x0, y0 = rng.uniform(-180, 170), rng.uniform(-90, 80)
            boxes.append((x0, y0, x0 + rng.uniform(0.5, 80),
                          y0 + rng.uniform(0.5, 80)))
        tlo = int(rng.integers(MS - DAY, MS + span))
        windows.append((boxes, tlo, tlo + int(rng.integers(DAY, span))))
    batched = idx.query_many(windows)
    assert len(batched) == n_q
    for (boxes, tlo, thi), hits in zip(windows, batched):
        single = idx.query(boxes, tlo, thi)
        np.testing.assert_array_equal(hits, single)
        in_any = np.zeros(n, dtype=bool)
        for b in boxes:
            in_any |= ((x >= b[0]) & (x <= b[2])
                       & (y >= b[1]) & (y <= b[3]))
        want = np.flatnonzero(in_any & (t >= tlo) & (t <= thi))
        np.testing.assert_array_equal(hits, want)


def test_fuzz_z2_query_many_vs_single():
    rng = np.random.default_rng(5150)
    n = 8000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    idx = Z2PointIndex.build(x, y)
    n_q = int(rng.integers(2, 30))
    batches = []
    for _ in range(n_q):
        x0, y0 = rng.uniform(-180, 170), rng.uniform(-90, 80)
        batches.append([(x0, y0, x0 + rng.uniform(0.5, 60),
                         y0 + rng.uniform(0.5, 60))])
    out = idx.query_many(batches)
    for boxes, hits in zip(batches, out):
        b = boxes[0]
        want = np.flatnonzero((x >= b[0]) & (x <= b[2])
                              & (y >= b[1]) & (y <= b[3]))
        np.testing.assert_array_equal(hits, want)
        np.testing.assert_array_equal(hits, idx.query(boxes))
