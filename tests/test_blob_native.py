"""Blob store + native (simplified typed) API tests."""

import numpy as np
import pytest

from geomesa_tpu.blob import GeoIndexedBlobStore, wkt_handler
from geomesa_tpu.geometry.types import Point
from geomesa_tpu.native_api import NativeIndex, NativeQuery

MS_2018 = 1514764800000
DAY = 86_400_000


class TestBlobStore:
    def test_put_get_roundtrip(self):
        bs = GeoIndexedBlobStore()
        bid = bs.put(b"payload-bytes", geometry=Point(10.0, 20.0),
                     dtg=MS_2018, filename="a.bin")
        data, filename = bs.get(bid)
        assert data == b"payload-bytes" and filename == "a.bin"
        assert bs.get("missing") is None

    def test_spatial_query_and_delete(self):
        bs = GeoIndexedBlobStore()
        east = bs.put(b"east", geometry=Point(10, 0), dtg=MS_2018)
        west = bs.put(b"west", geometry=Point(-10, 0), dtg=MS_2018)
        ids = bs.query_ids("BBOX(geom, 5, -5, 15, 5)")
        assert ids == [east]
        bs.delete_blob(east)
        assert bs.get(east) is None
        assert bs.query_ids() == [west]

    def test_wkt_handler(self):
        bs = GeoIndexedBlobStore()
        bid = bs.put(b"x", handler=wkt_handler,
                     params={"wkt": "POINT (3 4)"}, dtg=MS_2018)
        assert bs.query_ids("BBOX(geom, 2, 3, 4, 5)") == [bid]
        with pytest.raises(ValueError):
            bs.put(b"nogeom", handler=wkt_handler, params={})

    def test_file_backed(self, tmp_path):
        bs = GeoIndexedBlobStore(blob_dir=str(tmp_path / "blobs"))
        bid = bs.put(b"on-disk", geometry=Point(0, 0), filename="f.txt")
        data, name = bs.get(bid)
        assert data == b"on-disk" and name == "f.txt"
        bs.delete_blob(bid)
        assert bs.get(bid) is None

    def test_delete_blob_store(self):
        bs = GeoIndexedBlobStore()
        bs.put(b"a", geometry=Point(0, 0))
        bs.put(b"b", geometry=Point(1, 1))
        bs.delete_blob_store()
        assert "blob" not in bs.store.type_names


class TestNativeIndex:
    def test_insert_query_typed_values(self):
        idx = NativeIndex("vals")
        idx.insert({"k": 1}, Point(10, 10), MS_2018)
        idx.insert({"k": 2}, Point(20, 20), MS_2018 + DAY)
        idx.insert([1, 2, 3], Point(-10, -10), MS_2018 + 2 * DAY)
        got = idx.query(NativeQuery().within(5, 5, 25, 25))
        assert sorted(v["k"] for v in got) == [1, 2]
        assert idx.query(NativeQuery.include()) and len(idx.query()) == 3

    def test_temporal_builder(self):
        idx = NativeIndex("times")
        a = idx.insert("early", Point(0, 0), MS_2018)
        idx.insert("late", Point(0, 0), MS_2018 + 10 * DAY)
        got = idx.query(NativeQuery().within(-1, -1, 1, 1)
                        .during(MS_2018 - DAY, MS_2018 + DAY))
        assert got == ["early"]
        got = idx.query(NativeQuery().after(MS_2018 + 5 * DAY))
        assert got == ["late"]
        got = idx.query(NativeQuery().before(MS_2018 + 5 * DAY))
        assert got == ["early"]
        with_ids = idx.query_with_ids(NativeQuery().before(MS_2018 + DAY))
        assert with_ids == [(a, "early")]

    def test_update_delete(self):
        idx = NativeIndex("ud")
        fid = idx.insert("v1", Point(1, 1), MS_2018)
        idx.update(fid, "v2", Point(1, 1), MS_2018)
        assert idx.query() == ["v2"]
        idx.delete(fid)
        assert idx.query() == []

    def test_non_point_geometries(self):
        from geomesa_tpu.geometry.types import Polygon
        idx = NativeIndex("polys", points=False)
        idx.insert("square", Polygon([(0, 0), (4, 0), (4, 4), (0, 4), (0, 0)]))
        idx.insert("far", Point(50, 50))
        got = idx.query(NativeQuery().within(1, 1, 2, 2))
        assert got == ["square"]

    def test_supported_indexes(self):
        assert "z3" in NativeIndex("s").supported_indexes()
