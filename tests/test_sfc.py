"""Z2/Z3 curve semantics: bounds, clamping, roundtrips, range correctness
(reference: curve/Z2SFC.scala, Z3SFC.scala)."""

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.curve import TimePeriod, max_offset, z2_sfc, z3_sfc


def test_z2_extremes():
    sfc = z2_sfc()
    assert int(sfc.index(-180.0, -90.0, xp=np)) == 0
    # all 62 bits set at the max corner
    assert int(sfc.index(180.0, 90.0, xp=np)) == (1 << 62) - 1


def test_z3_extremes():
    sfc = z3_sfc(TimePeriod.WEEK)
    assert int(sfc.index(-180.0, -90.0, 0.0, xp=np)) == 0
    assert int(sfc.index(180.0, 90.0, float(max_offset(TimePeriod.WEEK)), xp=np)) == (1 << 63) - 1


def test_z2_lenient_clamp():
    sfc = z2_sfc()
    assert int(sfc.index(-181.0, -91.0, xp=np)) == int(sfc.index(-180.0, -90.0, xp=np))
    assert int(sfc.index(181.0, 91.0, xp=np)) == int(sfc.index(180.0, 90.0, xp=np))


def test_z2_invert_roundtrip(rng):
    sfc = z2_sfc()
    x = rng.uniform(-180, 180, 500)
    y = rng.uniform(-90, 90, 500)
    z = sfc.index(x, y, xp=np)
    rx, ry = sfc.invert(z)
    assert np.max(np.abs(rx - x)) <= 360.0 / (1 << 31)
    assert np.max(np.abs(ry - y)) <= 180.0 / (1 << 31)


def test_z3_invert_roundtrip(rng):
    sfc = z3_sfc(TimePeriod.WEEK)
    x = rng.uniform(-180, 180, 500)
    y = rng.uniform(-90, 90, 500)
    t = rng.uniform(0, max_offset(TimePeriod.WEEK), 500)
    z = sfc.index(x, y, t, xp=np)
    rx, ry, rt = sfc.invert(z)
    assert np.max(np.abs(rx - x)) <= 360.0 / (1 << 21)
    assert np.max(np.abs(ry - y)) <= 180.0 / (1 << 21)
    assert np.max(np.abs(rt - t)) <= max_offset(TimePeriod.WEEK) / (1 << 21)


def test_device_matches_host(rng):
    sfc = z3_sfc(TimePeriod.WEEK)
    x = rng.uniform(-180, 180, 1000)
    y = rng.uniform(-90, 90, 1000)
    t = rng.uniform(0, max_offset(TimePeriod.WEEK), 1000)
    host = sfc.index(x, y, t, xp=np)
    dev = np.asarray(jax.jit(lambda a, b, c: sfc.index(a, b, c))(x, y, t))
    np.testing.assert_array_equal(host, dev)


def test_z2_ranges_contain_all_points(rng):
    sfc = z2_sfc()
    box = (-10.0, 35.0, 15.0, 52.0)
    x = rng.uniform(box[0], box[2], 300)
    y = rng.uniform(box[1], box[3], 300)
    z = sfc.index(x, y, xp=np).astype(np.int64)
    ranges = sfc.ranges([box])
    in_any = np.zeros(len(z), dtype=bool)
    for lo, hi in ranges:
        in_any |= (z >= lo) & (z <= hi)
    assert in_any.all()


def test_z3_ranges_contain_all_points(rng):
    sfc = z3_sfc(TimePeriod.WEEK)
    box = (-74.2, 40.5, -73.7, 40.9)
    tlo, thi = 86_400, 2 * 86_400
    x = rng.uniform(box[0], box[2], 300)
    y = rng.uniform(box[1], box[3], 300)
    t = rng.uniform(tlo, thi, 300)
    z = sfc.index(x, y, t, xp=np).astype(np.int64)
    ranges = sfc.ranges([box], [(tlo, thi)])
    assert len(ranges) <= 2000
    in_any = np.zeros(len(z), dtype=bool)
    for lo, hi in ranges:
        in_any |= (z >= lo) & (z <= hi)
    assert in_any.all()


def test_z3_whole_period():
    sfc = z3_sfc(TimePeriod.WEEK)
    assert sfc.whole_period == (0, max_offset(TimePeriod.WEEK))


def test_legacy_semi_normalized_curves():
    """Legacy (ceil-binned) curves differ from the current ones exactly at
    bin boundaries — the back-compat property the reference keeps them for
    (LegacyZ3SFC.scala, NormalizedDimension.scala:82-97)."""
    import numpy as np
    from geomesa_tpu.curve import z2_sfc, z3_sfc
    from geomesa_tpu.curve.legacy import legacy_z2_sfc, legacy_z3_sfc

    lz2, z2 = legacy_z2_sfc(), z2_sfc()
    x = np.array([-180.0, -179.99997, 0.0, 179.99999])
    y = np.array([-90.0, 0.0, 45.0, 89.99999])
    lz = np.asarray(lz2.index(x, y, xp=np))
    cz = np.asarray(z2.index(x, y, xp=np))
    assert (lz != cz).any()          # different binning
    # roundtrip stays within one legacy bin width
    rx, ry = lz2.invert(lz, xp=np)
    assert np.abs(rx - x).max() < 360.0 / ((1 << 31) - 1) * 1.5
    # z3 legacy time precision is 2^20-1 (vs 2^21 bins current)
    lz3 = legacy_z3_sfc("week")
    assert lz3.time.max_index == (1 << 20) - 1
    z = np.asarray(lz3.index(np.array([10.0]), np.array([20.0]),
                             np.array([1000.0]), xp=np))
    rx, ry, rt = lz3.invert(z, xp=np)
    assert abs(float(rx[0]) - 10.0) < 1e-3 and abs(float(ry[0]) - 20.0) < 1e-3
