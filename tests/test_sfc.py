"""Z2/Z3 curve semantics: bounds, clamping, roundtrips, range correctness
(reference: curve/Z2SFC.scala, Z3SFC.scala)."""

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.curve import TimePeriod, max_offset, z2_sfc, z3_sfc


def test_z2_extremes():
    sfc = z2_sfc()
    assert int(sfc.index(-180.0, -90.0, xp=np)) == 0
    # all 62 bits set at the max corner
    assert int(sfc.index(180.0, 90.0, xp=np)) == (1 << 62) - 1


def test_z3_extremes():
    sfc = z3_sfc(TimePeriod.WEEK)
    assert int(sfc.index(-180.0, -90.0, 0.0, xp=np)) == 0
    assert int(sfc.index(180.0, 90.0, float(max_offset(TimePeriod.WEEK)), xp=np)) == (1 << 63) - 1


def test_z2_lenient_clamp():
    sfc = z2_sfc()
    assert int(sfc.index(-181.0, -91.0, xp=np)) == int(sfc.index(-180.0, -90.0, xp=np))
    assert int(sfc.index(181.0, 91.0, xp=np)) == int(sfc.index(180.0, 90.0, xp=np))


def test_z2_invert_roundtrip(rng):
    sfc = z2_sfc()
    x = rng.uniform(-180, 180, 500)
    y = rng.uniform(-90, 90, 500)
    z = sfc.index(x, y, xp=np)
    rx, ry = sfc.invert(z)
    assert np.max(np.abs(rx - x)) <= 360.0 / (1 << 31)
    assert np.max(np.abs(ry - y)) <= 180.0 / (1 << 31)


def test_z3_invert_roundtrip(rng):
    sfc = z3_sfc(TimePeriod.WEEK)
    x = rng.uniform(-180, 180, 500)
    y = rng.uniform(-90, 90, 500)
    t = rng.uniform(0, max_offset(TimePeriod.WEEK), 500)
    z = sfc.index(x, y, t, xp=np)
    rx, ry, rt = sfc.invert(z)
    assert np.max(np.abs(rx - x)) <= 360.0 / (1 << 21)
    assert np.max(np.abs(ry - y)) <= 180.0 / (1 << 21)
    assert np.max(np.abs(rt - t)) <= max_offset(TimePeriod.WEEK) / (1 << 21)


def test_device_matches_host(rng):
    sfc = z3_sfc(TimePeriod.WEEK)
    x = rng.uniform(-180, 180, 1000)
    y = rng.uniform(-90, 90, 1000)
    t = rng.uniform(0, max_offset(TimePeriod.WEEK), 1000)
    host = sfc.index(x, y, t, xp=np)
    dev = np.asarray(jax.jit(lambda a, b, c: sfc.index(a, b, c))(x, y, t))
    np.testing.assert_array_equal(host, dev)


def test_z2_ranges_contain_all_points(rng):
    sfc = z2_sfc()
    box = (-10.0, 35.0, 15.0, 52.0)
    x = rng.uniform(box[0], box[2], 300)
    y = rng.uniform(box[1], box[3], 300)
    z = sfc.index(x, y, xp=np).astype(np.int64)
    ranges = sfc.ranges([box])
    in_any = np.zeros(len(z), dtype=bool)
    for lo, hi in ranges:
        in_any |= (z >= lo) & (z <= hi)
    assert in_any.all()


def test_z3_ranges_contain_all_points(rng):
    sfc = z3_sfc(TimePeriod.WEEK)
    box = (-74.2, 40.5, -73.7, 40.9)
    tlo, thi = 86_400, 2 * 86_400
    x = rng.uniform(box[0], box[2], 300)
    y = rng.uniform(box[1], box[3], 300)
    t = rng.uniform(tlo, thi, 300)
    z = sfc.index(x, y, t, xp=np).astype(np.int64)
    ranges = sfc.ranges([box], [(tlo, thi)])
    assert len(ranges) <= 2000
    in_any = np.zeros(len(z), dtype=bool)
    for lo, hi in ranges:
        in_any |= (z >= lo) & (z <= hi)
    assert in_any.all()


def test_z3_whole_period():
    sfc = z3_sfc(TimePeriod.WEEK)
    assert sfc.whole_period == (0, max_offset(TimePeriod.WEEK))
