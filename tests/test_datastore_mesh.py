"""Multi-chip TpuDataStore: the same facade over a device mesh must be
oracle-equal to the single-chip store on every strategy path (VERDICT
round-1 item 1 — the reference's laptop-to-cluster property,
GeoMesaDataStore.scala:48-431)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql
from geomesa_tpu.parallel import device_mesh
from geomesa_tpu.planning.planner import Query

MS_2018 = 1514764800000
DAY = 86_400_000
N = 30_007

SPEC = ("name:String:index=true,score:Double,dtg:Date,*geom:Point;"
        "geomesa.z3.interval=week")


def _data(rng):
    return {
        "name": rng.choice(["alpha", "beta", "gamma", "delta"], N),
        "score": rng.uniform(0, 100, N),
        "dtg": rng.integers(MS_2018, MS_2018 + 21 * DAY, N),
        "geom": (rng.uniform(-75.0, -73.0, N), rng.uniform(40.0, 42.0, N)),
    }



def _slice(data, sl):
    """Slice every column, handling the (x, y) geometry tuple."""
    return {k: (v[0][sl], v[1][sl]) if isinstance(v, tuple) else v[sl]
            for k, v in data.items()}

@pytest.fixture(scope="module")
def stores():
    data = _data(np.random.default_rng(77))
    plain = TpuDataStore()
    plain.create_schema("events", SPEC)
    plain.write("events", data)
    mesh = TpuDataStore(mesh=device_mesh())
    mesh.create_schema("events", SPEC)
    mesh.write("events", data)
    return plain, mesh


QUERIES = [
    # z3 path
    "BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
    "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z",
    # z2 path
    "BBOX(geom, -74.2, 40.8, -73.9, 41.1)",
    # attribute equality (+date tier window)
    "name = 'alpha'",
    "name = 'beta' AND dtg DURING 2018-01-03T00:00:00Z/2018-01-08T00:00:00Z",
    "name = 'beta' AND score > 90",
    "name IN ('alpha', 'gamma')",
    "name LIKE 'de%'",
    # temporal only
    "dtg DURING 2018-01-05T00:00:00Z/2018-01-06T00:00:00Z",
    # OR of boxes
    "BBOX(geom, -74.9, 40.1, -74.6, 40.4) OR "
    "BBOX(geom, -73.4, 41.6, -73.1, 41.9)",
    # full scan
    "score < 1.5",
    # intersects polygon + time (xz path on non-point would apply; points
    # route via z3/z2 but exercise geometry predicates)
    "INTERSECTS(geom, POLYGON ((-74.5 40.5, -74 40.5, -74 41.5, "
    "-74.5 41.5, -74.5 40.5))) AND dtg AFTER 2018-01-10T00:00:00Z",
    # id scan
    "IN ('17', '23', '99999999')",
]


@pytest.mark.parametrize("ecql", QUERIES)
def test_mesh_store_matches_plain(stores, ecql):
    plain, mesh = stores
    a = plain.query_result("events", ecql)
    b = mesh.query_result("events", ecql)
    np.testing.assert_array_equal(np.sort(a.positions), np.sort(b.positions))
    # both must also equal the filter oracle
    st = plain._store("events")
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(b.positions), want)


def test_mesh_store_same_strategies(stores):
    plain, mesh = stores
    for ecql, idx in [
        ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
         "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z", "z3"),
        ("BBOX(geom, -74.2, 40.8, -73.9, 41.1)", "z2"),
        ("name = 'alpha'", "attr:name"),
        ("score < 1.5", "full"),
    ]:
        assert mesh.query_result("events", ecql).strategy.index == idx
        assert plain.query_result("events", ecql).strategy.index == idx


def test_mesh_incremental_write_appends(stores):
    """Second write takes the sharded z3 append path (no dirty rebuild)
    and stays oracle-equal."""
    data = _data(np.random.default_rng(99))
    mesh = TpuDataStore(mesh=device_mesh())
    mesh.create_schema("events", SPEC)
    half = N // 2
    first = _slice(data, slice(None, half))
    second = _slice(data, slice(half, None))
    mesh.write("events", first)
    # force the z3 index to exist so the next write appends incrementally
    ecql = ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
            "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    mesh.query("events", ecql)
    assert "z3" in mesh._store("events")._indexes
    mesh.write("events", second)
    # the sharded index must have been appended to, not discarded
    assert "z3" in mesh._store("events")._indexes
    got = mesh.query_result("events", ecql)
    st = mesh._store("events")
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch))
    np.testing.assert_array_equal(np.sort(got.positions), want)


def test_mesh_query_windows(stores):
    plain, mesh = stores
    windows = [
        ([(-74.5, 40.5, -73.5, 41.5)], MS_2018 + DAY, MS_2018 + 6 * DAY),
        ([(-74.9, 40.1, -74.4, 40.9)], None, None),  # untimed → z2
        ([(-74.2, 40.8, -74.0, 41.0)], MS_2018 + 8 * DAY, MS_2018 + 13 * DAY),
    ]
    a = plain.query_windows("events", windows)
    b = mesh.query_windows("events", windows)
    for pa, pb in zip(a, b):
        np.testing.assert_array_equal(np.sort(pa), np.sort(pb))


def test_mesh_sort_limit_projection(stores):
    _, mesh = stores
    q = Query.of("name = 'alpha'", sort_by="score", sort_desc=True,
                 max_features=10)
    batch = mesh.query("events", q)
    assert len(batch) == 10
    assert np.all(np.diff(batch.column("score")) <= 0)
    q = Query.of("name = 'gamma'", properties=["name", "geom"])
    batch = mesh.query("events", q)
    assert set(batch.columns) == {"name", "geom_x", "geom_y"}


def test_mesh_stats_and_explain(stores):
    plain, mesh = stores
    assert mesh.get_count("events") == plain.get_count("events") == N
    ea, eb = plain.get_bounds("events"), mesh.get_bounds("events")
    assert (ea.xmin, ea.ymax) == (eb.xmin, eb.ymax)
    text = mesh.explain(
        "events", "BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND "
        "dtg DURING 2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    assert "chosen: z3" in text


def test_mesh_nonpoint_schema_xz_paths():
    """Polygon schema routes through the sharded XZ2/XZ3 indexes."""
    from geomesa_tpu.geometry import Polygon
    rng = np.random.default_rng(31)
    n = 500
    plain = TpuDataStore()
    mesh = TpuDataStore(mesh=device_mesh())
    cx = rng.uniform(-74.8, -73.2, n)
    cy = rng.uniform(40.2, 41.8, n)
    w = rng.uniform(0.01, 0.2, n)
    polys = [Polygon([(a - d, b - d), (a + d, b - d),
                      (a + d, b + d), (a - d, b + d)])
             for a, b, d in zip(cx, cy, w)]
    data = {"dtg": rng.integers(MS_2018, MS_2018 + 14 * DAY, n),
            "geom": polys}
    for ds in (plain, mesh):
        ds.create_schema("areas", "dtg:Date,*geom:Polygon")
        ds.write("areas", data)
    queries = [
        "INTERSECTS(geom, POLYGON ((-74.5 40.5, -74 40.5, -74 41.5, "
        "-74.5 41.5, -74.5 40.5)))",
        "INTERSECTS(geom, POLYGON ((-74.5 40.5, -74 40.5, -74 41.5, "
        "-74.5 41.5, -74.5 40.5))) AND dtg DURING "
        "2018-01-02T00:00:00Z/2018-01-09T00:00:00Z",
    ]
    for ecql in queries:
        a = plain.query_result("areas", ecql)
        b = mesh.query_result("areas", ecql)
        np.testing.assert_array_equal(np.sort(a.positions),
                                      np.sort(b.positions))
    assert mesh.query_result("areas", queries[0]).strategy.index == "xz2"
    assert mesh.query_result("areas", queries[1]).strategy.index == "xz3"


def test_mesh_store_visibility_masks():
    """Row-level visibility applies to collective scan results (gids are
    row positions, so auth masks align)."""
    from geomesa_tpu.security import StaticAuthorizationsProvider
    rng = np.random.default_rng(61)
    n = 4_001
    data_open = {
        "name": np.array(["a", "b"], dtype=object)[rng.integers(0, 2, n)],
        "score": rng.uniform(0, 10, n),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
    }
    ds = TpuDataStore(mesh=device_mesh(),
                      auth_provider=StaticAuthorizationsProvider(["user"]))
    ds.create_schema("ev", SPEC)
    ds.write("ev", data_open, visibility="user")
    secret = _slice(data_open, slice(None, 100))
    ds.write("ev", secret, visibility="admin")
    ecql = "BBOX(geom, -74.8, 40.2, -73.2, 41.8)"
    r = ds.query_result("ev", ecql)
    # no admin-visible row may appear (they are rows n..n+100)
    assert (r.positions < n).all()
    st = ds._store("ev")
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), st.batch)[:n])
    np.testing.assert_array_equal(np.sort(r.positions), want)
    assert ds.get_count("ev") == n  # restricted count hides secret rows


def test_mesh_store_knn_and_tube_processes():
    """Config-5 analytics (kNN expanding rings, tube-select) run through
    the mesh store's collective batched windows, oracle-equal to the
    single-chip store."""
    from geomesa_tpu.process import knn_process, tube_select
    rng = np.random.default_rng(67)
    n = 20_003
    data = {
        "name": rng.choice(["a", "b"], n),
        "score": rng.uniform(0, 1, n),
        "dtg": rng.integers(MS_2018, MS_2018 + 5 * DAY, n),
        "geom": (rng.uniform(-75.0, -73.0, n), rng.uniform(40.0, 42.0, n)),
    }
    plain = TpuDataStore()
    mesh = TpuDataStore(mesh=device_mesh())
    for ds in (plain, mesh):
        ds.create_schema("ais", SPEC.replace("N", str(n)))
        ds.write("ais", data)
    pa, da = knn_process(plain, "ais", -74.0, 41.0, 15)
    pb, db = knn_process(mesh, "ais", -74.0, 41.0, 15)
    np.testing.assert_array_equal(pa, pb)
    np.testing.assert_allclose(da, db)
    tk = np.linspace(0, 1, 9)
    track = np.column_stack([-75.0 + 2.0 * tk, 40.2 + 1.6 * tk])
    track_t = (MS_2018 + tk * 4 * DAY).astype(np.int64)
    ta = tube_select(plain, "ais", track, track_t, 20_000.0, 6 * 3_600_000)
    tb = tube_select(mesh, "ais", track, track_t, 20_000.0, 6 * 3_600_000)
    np.testing.assert_array_equal(ta, tb)
    assert len(ta) > 0


def test_mesh_store_age_off_and_delete():
    """TTL on the sharded store: scan-time hiding via the interceptor
    and physical expiry both flow through the collective indexes
    (VERDICT r1 item 3's age-off half)."""
    from geomesa_tpu.age_off import age_off
    rng = np.random.default_rng(71)
    n = 8_001
    now_ms = int(np.datetime64("now").astype("datetime64[ms]").astype(int))
    dtg = now_ms - rng.integers(0, 14 * DAY, n)  # 0-14 days old
    ds = TpuDataStore(mesh=device_mesh())
    ds.create_schema("ev", "name:String,dtg:Date,*geom:Point;"
                           "geomesa.age.off='7 days'")
    ds.write("ev", {
        "name": rng.choice(["a", "b"], n),
        "dtg": dtg,
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
    })
    fresh = int((dtg >= now_ms - 7 * DAY).sum())
    # scan-time hiding: every query sees only the retention window
    got = ds.query_result("ev", "BBOX(geom, -180, -90, 180, 90)")
    assert len(got.positions) == fresh
    # physical expiry rebuilds the sharded indexes without expired rows
    removed = age_off(ds, "ev")
    assert removed == n - fresh
    assert ds.get_count("ev") == fresh
    got2 = ds.query_result("ev", "BBOX(geom, -180, -90, 180, 90)")
    assert len(got2.positions) == fresh
    # the rebuilt sharded z3 index serves exact scans
    st = ds._store("ev")
    assert st.z3_index().total() == fresh


def test_mesh_store_sql_frame_and_rdd():
    """The SQL frame and RDD layers ride the mesh store unchanged."""
    from geomesa_tpu.parallel.rdd import spatial_rdd
    from geomesa_tpu.sql.frame import SpatialFrame
    rng = np.random.default_rng(73)
    n = 6_007
    data = {
        "name": rng.choice(["a", "b", "c"], n),
        "score": rng.uniform(0, 1, n),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n)),
    }
    plain = TpuDataStore()
    mesh = TpuDataStore(mesh=device_mesh())
    for ds in (plain, mesh):
        ds.create_schema("ev", SPEC)
        ds.write("ev", data)
    q = "BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND name = 'a'"
    fa = SpatialFrame(plain, "ev").where(q).collect()
    fb = SpatialFrame(mesh, "ev").where(q).collect()
    assert len(fa) == len(fb)
    np.testing.assert_array_equal(np.sort(fa.column("score")),
                                  np.sort(fb.column("score")))
    rdd = spatial_rdd({"store": mesh}, "ev",
                      "BBOX(geom, -74.5, 40.5, -73.5, 41.5)",
                      num_partitions=4)
    assert sum(len(p) for p in rdd.partitions) == len(
        plain.query("ev", "BBOX(geom, -74.5, 40.5, -73.5, 41.5)"))


def test_mesh_differential_fuzz(stores):
    """Seeded random ECQL sweep: the mesh store must equal the plain
    store (and the filter oracle) on every generated query shape."""
    plain, mesh = stores
    rng = np.random.default_rng(83)
    names = ["alpha", "beta", "gamma", "delta"]

    def rand_query():
        parts = []
        kind = rng.integers(0, 5)
        if kind in (0, 1, 3):
            x0 = rng.uniform(-75, -73.4)
            y0 = rng.uniform(40, 41.4)
            w, h = rng.uniform(0.1, 1.2, 2)
            parts.append(f"BBOX(geom, {x0:.3f}, {y0:.3f}, "
                         f"{x0 + w:.3f}, {y0 + h:.3f})")
        if kind in (1, 2):
            d0 = int(rng.integers(1, 15))
            d1 = d0 + int(rng.integers(1, 6))
            parts.append(
                f"dtg DURING 2018-01-{d0:02d}T00:00:00Z/"
                f"2018-01-{d1:02d}T00:00:00Z")
        if kind in (3, 4):
            parts.append(f"name = '{names[rng.integers(0, 4)]}'")
        if kind == 4:
            parts.append(f"score < {rng.uniform(10, 90):.1f}")
        return " AND ".join(parts)

    for _ in range(25):
        ecql = rand_query()
        a = plain.query_result("events", ecql).positions
        b = mesh.query_result("events", ecql).positions
        np.testing.assert_array_equal(np.sort(a), np.sort(b),
                                      err_msg=f"mesh != plain for {ecql}")
        want = np.flatnonzero(evaluate_filter(
            parse_ecql(ecql), plain._store("events").batch))
        np.testing.assert_array_equal(np.sort(b), want,
                                      err_msg=f"oracle mismatch for {ecql}")


def test_mesh_density_pushdown(stores):
    """Pure bbox+time density on the mesh takes the collective psum path
    (no host candidate materialization) and matches the plain store's
    grid; attribute-filtered queries fall back to the query path."""
    from geomesa_tpu.process import density_process
    plain, mesh = stores
    env = (-74.5, 40.5, -73.5, 41.5)
    q = ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
         "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    ga = density_process(plain, "events", q, env, 64, 32)
    gb = density_process(mesh, "events", q, env, 64, 32)
    np.testing.assert_allclose(ga, gb)
    assert ga.sum() > 0
    # weighted
    ga = density_process(plain, "events", q, env, 32, 32,
                         weight_attr="score")
    gb = density_process(mesh, "events", q, env, 32, 32,
                         weight_attr="score")
    np.testing.assert_allclose(ga, gb, rtol=1e-10)
    # attribute predicate → residual filter required → fallback path
    q2 = q + " AND name = 'alpha'"
    ga = density_process(plain, "events", q2, env, 32, 32)
    gb = density_process(mesh, "events", q2, env, 32, 32)
    np.testing.assert_allclose(ga, gb)


def test_mesh_stats_pushdown(stores):
    """Count/MinMax/Histogram over pure bbox+time filters run as the
    device-collective stats scan and equal the plain store's results;
    sketch kinds (TopK) still fold through the monoid path."""
    from geomesa_tpu.process import stats_process
    plain, mesh = stores
    q = ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND dtg DURING "
         "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z")
    spec = "Count();MinMax(score);Histogram(score,16,0,100)"
    a = stats_process(plain, "events", q, spec)
    b = stats_process(mesh, "events", q, spec)
    ca, ma, ha = a.stats
    cb, mb, hb = b.stats
    assert cb.count == ca.count > 0
    assert mb.min == pytest.approx(ma.min)
    assert mb.max == pytest.approx(ma.max)
    np.testing.assert_array_equal(hb.counts, ha.counts)
    # sketch spec falls back to the materializing path, still correct
    ta = stats_process(plain, "events", q, "TopK(name)")
    tb = stats_process(mesh, "events", q, "TopK(name)")
    assert dict(ta.topk(4)) == dict(tb.topk(4))
    # attribute-filtered query cannot push down; results still agree
    q2 = q + " AND name = 'alpha'"
    a2 = stats_process(plain, "events", q2, "Count()")
    b2 = stats_process(mesh, "events", q2, "Count()")
    assert a2.count == b2.count > 0


def test_mesh_pushdown_anded_bboxes_intersect(stores):
    """Regression: AND of two bboxes must intersect (not union) on the
    push-down paths."""
    from geomesa_tpu.process import density_process, stats_process
    plain, mesh = stores
    env = (-75.0, 40.0, -73.0, 42.0)
    q = ("BBOX(geom, -74.8, 40.2, -73.8, 41.2) AND "
         "BBOX(geom, -74.2, 40.8, -73.2, 41.8) AND dtg DURING "
         "2018-01-02T00:00:00Z/2018-01-12T00:00:00Z")
    ga = density_process(plain, "events", q, env, 32, 32)
    gb = density_process(mesh, "events", q, env, 32, 32)
    np.testing.assert_allclose(ga, gb)
    a = stats_process(plain, "events", q, "Count()")
    b = stats_process(mesh, "events", q, "Count()")
    assert a.count == b.count > 0
    # disjoint AND → zero
    q0 = ("BBOX(geom, -74.8, 40.2, -74.5, 40.4) AND "
          "BBOX(geom, -73.5, 41.5, -73.2, 41.8)")
    assert stats_process(mesh, "events", q0, "Count()").count == 0
    assert density_process(mesh, "events", q0, env, 16, 16).sum() == 0


def test_merged_view_mixes_mesh_and_plain():
    """A merged view unions a mesh-backed store with a single-chip store
    (the reference's MergedDataStoreView over heterogeneous backends)."""
    from geomesa_tpu.views import MergedDataStoreView
    rng = np.random.default_rng(91)
    n = 2_001
    spec = "name:String,dtg:Date,*geom:Point"

    def data(seed):
        r = np.random.default_rng(seed)
        return {
            "name": r.choice(["a", "b"], n),
            "dtg": r.integers(MS_2018, MS_2018 + 7 * DAY, n),
            "geom": (r.uniform(-75, -73, n), r.uniform(40, 42, n)),
        }

    mesh_ds = TpuDataStore(mesh=device_mesh())
    plain_ds = TpuDataStore()
    mesh_ds.create_schema("ev", spec)
    plain_ds.create_schema("ev", spec)
    d1, d2 = data(1), data(2)
    mesh_ds.write("ev", d1)
    plain_ds.write("ev", d2)
    view = MergedDataStoreView([mesh_ds, plain_ds])
    ecql = "BBOX(geom, -74.5, 40.5, -73.5, 41.5)"
    got = view.query("ev", ecql)
    def count(d):
        x, y = d["geom"]
        return int(((x >= -74.5) & (x <= -73.5)
                    & (y >= 40.5) & (y <= 41.5)).sum())
    assert len(got) == count(d1) + count(d2)
