"""Regressions for review findings: converter delimiter/raw fields, batch
id aliasing on rewrite, sparse-batch exports, MultiPoint proximity."""

import numpy as np

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.features import FeatureBatch
from geomesa_tpu.geometry import MultiPoint
from geomesa_tpu.io.converters import converter_from_config
from geomesa_tpu.io.export import to_csv, to_geojson
from geomesa_tpu.process.proximity import proximity_process

MS_2018 = 1514764800000


def _sft_store():
    ds = TpuDataStore()
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    return ds


def test_delimited_custom_delimiter():
    ds = _sft_store()
    conv = converter_from_config(ds.get_schema("t"), {
        "type": "delimited-text",
        "delimiter": "|",
        "fields": [
            {"name": "name", "transform": "$0"},
            {"name": "dtg", "transform": "isoDate('2018-01-01T00:00:00Z')"},
            {"name": "geom", "transform": "point($1, $2)"},
        ],
    })
    batch = conv.convert("a|-75.0|40.0\nb|-74.0|41.0\n")
    assert len(batch) == 2
    assert list(batch.columns["name"]) == ["a", "b"]


def test_json_transformless_field():
    ds = _sft_store()
    conv = converter_from_config(ds.get_schema("t"), {
        "type": "json",
        "fields": [
            {"name": "name"},
            {"name": "dtg", "transform": "isoDate('2018-01-01T00:00:00Z')"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
    })
    batch = conv.convert('{"name": "x", "lon": -75.0, "lat": 40.0}\n')
    assert len(batch) == 1
    assert batch.columns["name"][0] == "x"


def test_rewrite_same_batch_unique_ids():
    ds = _sft_store()
    b = FeatureBatch.from_dict(ds.get_schema("t"), {
        "name": np.array(["a", "b"], dtype=object),
        "dtg": np.array([MS_2018, MS_2018], dtype=np.int64),
        "geom": (np.array([-75.0, -74.0]), np.array([40.0, 41.0])),
    })
    orig_ids = b.ids.copy()
    ds.write("t", b)
    np.testing.assert_array_equal(b.ids, orig_ids)  # caller batch untouched
    ds.write("t", b)
    stored = ds.query("t")
    assert len(stored) == 4
    assert len(set(stored.ids)) == 4


def test_export_sparse_batch():
    ds = _sft_store()
    # write a batch missing the 'name' column entirely
    ds.write("t", {
        "dtg": np.array([MS_2018], dtype=np.int64),
        "geom": (np.array([-75.0]), np.array([40.0])),
    })
    out = ds.query("t")
    # must not have 'name' materialized
    assert "name" not in out.columns
    csv_text = to_csv(out)
    assert "2018-01-01" in csv_text
    gj = to_geojson(out)
    assert '"type": "FeatureCollection"' in gj or "FeatureCollection" in gj


def test_proximity_multipoint():
    ds = _sft_store()
    n = 500
    rng = np.random.default_rng(5)
    ds.write("t", {
        "name": np.array(["p"] * n, dtype=object),
        "dtg": np.full(n, MS_2018, dtype=np.int64),
        "geom": (rng.uniform(-75.5, -74.5, n), rng.uniform(39.5, 40.5, n)),
    })
    mp = MultiPoint(np.array([[-75.0, 40.0], [-74.6, 40.4]]))
    pos = proximity_process(ds, "t", [mp], 20_000.0)
    # oracle: haversine to either point
    x = ds.query("t").columns.get("geom")
    bx, by = ds.query("t").geom_xy()
    from geomesa_tpu.process.knn import haversine_m
    d = np.minimum(haversine_m(-75.0, 40.0, bx, by),
                   haversine_m(-74.6, 40.4, bx, by))
    want = np.sort(np.nonzero(d <= 20_000.0)[0])
    np.testing.assert_array_equal(np.sort(pos), want)
