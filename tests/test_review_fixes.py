"""Regressions for review findings: converter delimiter/raw fields, batch
id aliasing on rewrite, sparse-batch exports, MultiPoint proximity."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.features import FeatureBatch
from geomesa_tpu.features.feature_type import parse_spec
from geomesa_tpu.geometry import MultiPoint
from geomesa_tpu.io.converters import converter_from_config
from geomesa_tpu.io.export import to_csv, to_geojson
from geomesa_tpu.process.proximity import proximity_process

MS_2018 = 1514764800000


def _sft_store():
    ds = TpuDataStore()
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    return ds


def test_delimited_custom_delimiter():
    ds = _sft_store()
    conv = converter_from_config(ds.get_schema("t"), {
        "type": "delimited-text",
        "delimiter": "|",
        "fields": [
            {"name": "name", "transform": "$0"},
            {"name": "dtg", "transform": "isoDate('2018-01-01T00:00:00Z')"},
            {"name": "geom", "transform": "point($1, $2)"},
        ],
    })
    batch = conv.convert("a|-75.0|40.0\nb|-74.0|41.0\n")
    assert len(batch) == 2
    assert list(batch.columns["name"]) == ["a", "b"]


def test_json_transformless_field():
    ds = _sft_store()
    conv = converter_from_config(ds.get_schema("t"), {
        "type": "json",
        "fields": [
            {"name": "name"},
            {"name": "dtg", "transform": "isoDate('2018-01-01T00:00:00Z')"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
    })
    batch = conv.convert('{"name": "x", "lon": -75.0, "lat": 40.0}\n')
    assert len(batch) == 1
    assert batch.columns["name"][0] == "x"


def test_rewrite_same_batch_unique_ids():
    ds = _sft_store()
    b = FeatureBatch.from_dict(ds.get_schema("t"), {
        "name": np.array(["a", "b"], dtype=object),
        "dtg": np.array([MS_2018, MS_2018], dtype=np.int64),
        "geom": (np.array([-75.0, -74.0]), np.array([40.0, 41.0])),
    })
    orig_ids = b.ids.copy()
    ds.write("t", b)
    np.testing.assert_array_equal(b.ids, orig_ids)  # caller batch untouched
    ds.write("t", b)
    stored = ds.query("t")
    assert len(stored) == 4
    assert len(set(stored.ids)) == 4


def test_export_sparse_batch():
    ds = _sft_store()
    # write a batch missing the 'name' column entirely
    ds.write("t", {
        "dtg": np.array([MS_2018], dtype=np.int64),
        "geom": (np.array([-75.0]), np.array([40.0])),
    })
    out = ds.query("t")
    # must not have 'name' materialized
    assert "name" not in out.columns
    csv_text = to_csv(out)
    assert "2018-01-01" in csv_text
    gj = to_geojson(out)
    assert '"type": "FeatureCollection"' in gj or "FeatureCollection" in gj


def test_proximity_multipoint():
    ds = _sft_store()
    n = 500
    rng = np.random.default_rng(5)
    ds.write("t", {
        "name": np.array(["p"] * n, dtype=object),
        "dtg": np.full(n, MS_2018, dtype=np.int64),
        "geom": (rng.uniform(-75.5, -74.5, n), rng.uniform(39.5, 40.5, n)),
    })
    mp = MultiPoint(np.array([[-75.0, 40.0], [-74.6, 40.4]]))
    pos = proximity_process(ds, "t", [mp], 20_000.0)
    # oracle: haversine to either point
    x = ds.query("t").columns.get("geom")
    bx, by = ds.query("t").geom_xy()
    from geomesa_tpu.process.knn import haversine_m
    d = np.minimum(haversine_m(-75.0, 40.0, bx, by),
                   haversine_m(-74.6, 40.4, bx, by))
    want = np.sort(np.nonzero(d <= 20_000.0)[0])
    np.testing.assert_array_equal(np.sort(pos), want)


# -- round-2 review fixes ---------------------------------------------------

def test_wkb_decode_ewkb_srid_and_z():
    """PostGIS EWKB (SRID flag + payload) and ISO WKB Z types decode to the
    correct 2-D coordinates instead of reading the SRID as doubles."""
    import struct
    from geomesa_tpu.geometry.wkb import wkb_decode
    ewkb_pt = (bytes([1]) + struct.pack("<I", 0x20000001)
               + struct.pack("<I", 4326) + struct.pack("<dd", 1.0, 2.0))
    g = wkb_decode(ewkb_pt)
    assert (g.x, g.y) == (1.0, 2.0)
    ewkb_ls = (bytes([1]) + struct.pack("<I", 0x20000002)
               + struct.pack("<I", 4326) + struct.pack("<I", 2)
               + struct.pack("<dddd", 0.0, 0.0, 1.0, 1.0))
    g = wkb_decode(ewkb_ls)
    assert g.coords.shape == (2, 2) and g.coords[1, 1] == 1.0
    iso_pz = (bytes([1]) + struct.pack("<I", 1001)
              + struct.pack("<ddd", 3.0, 4.0, 5.0))
    g = wkb_decode(iso_pz)
    assert (g.x, g.y) == (3.0, 4.0)


def test_twkb_precision_out_of_range_rejected():
    from geomesa_tpu.geometry.types import Point
    from geomesa_tpu.geometry.wkb import twkb_decode, twkb_encode
    with pytest.raises(ValueError):
        twkb_encode(Point(1.5, 2.5), precision=8)
    with pytest.raises(ValueError):
        twkb_encode(Point(1.5, 2.5), precision=-9)
    g = twkb_decode(twkb_encode(Point(1.5, 2.5), precision=7))
    assert (g.x, g.y) == (1.5, 2.5)


def test_avro_polygon_and_secondary_geometry_roundtrip():
    import io as _io
    from geomesa_tpu.geometry.types import Polygon
    from geomesa_tpu.io.avro import from_avro, to_avro

    sft = parse_spec("poly", "name:String,*geom:Polygon")
    poly = Polygon(np.array([[0, 0], [1, 0], [1, 1], [0, 0]], dtype=float))
    b = FeatureBatch.from_dict(sft, {"name": ["a"], "geom": [poly]},
                               ids=["f1"])
    buf = _io.BytesIO()
    to_avro(b, buf)
    buf.seek(0)
    rt = from_avro(buf, sft)
    assert len(rt) == 1 and rt.geoms.geometry(0).geom_type == "Polygon"

    sft2 = parse_spec("t2", "name:String,*geom:Point,geom2:Point")
    b2 = FeatureBatch.from_dict(sft2, {
        "name": ["a"],
        "geom": (np.array([1.0]), np.array([2.0])),
        "geom2": (np.array([3.0]), np.array([4.0])),
    }, ids=["f1"])
    buf = _io.BytesIO()
    to_avro(b2, buf)
    buf.seek(0)
    rt2 = from_avro(buf, sft2)
    x2, y2 = rt2.geom_xy("geom2")
    assert (x2[0], y2[0]) == (3.0, 4.0)


def test_profile_context():
    from geomesa_tpu.utils.profiling import Timings, profile
    t = Timings()
    with profile("phase.a", sink=t):
        sum(range(1000))
    with profile("phase.a", sink=t):
        pass
    assert len(t.times["phase.a"]) == 2
    assert t.total_ms("phase.a") >= 0
    assert "phase.a" in repr(t)


def test_bench_compact_summary_bounded():
    """The driver retains only the last ~2,000 stdout chars; the bench's
    final line must parse and fit regardless of how the full record
    grows (round-4 VERDICT weak #1)."""
    import importlib.util
    import json as _json
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "bench", _os.path.join(_os.path.dirname(__file__), "..",
                               "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    big_scale = {"recorded_1b": {"rows": 10**9, "tiers": {"full": 1},
                                 "query_warm_ms": list(range(100)),
                                 "noise": ["x" * 100] * 50},
                 "store_recorded": {"rows": 10**9,
                                    "bulk": ["y" * 200] * 40},
                 "store_live": {"rows": 8_000_000}}
    full = {"metric": "m", "value": 1, "unit": "u", "vs_baseline": 1.0,
            "extra": {"n_points": 1,
                      "bbox_time_scan_features_per_sec": 1,
                      "scan_points_covered_per_sec": 1, "scan_hits": 1,
                      "batched_windows_per_sec": 1.0,
                      "batched_window_hits": 1,
                      "density_256x128_ms": 1.0,
                      "chunked_append_keys_per_sec": 1,
                      "chunked_total_rows": 1, "z2_or3_ms": 1.0,
                      "z2_or3_hits": 1, "density_world_zprefix_ms": 1.0,
                      "xz2_build_s": 1.0, "xz2_query_ms": 1.0,
                      "xz2_candidates": 1, "knn25_4m_ms": 1.0,
                      "tube40_4m_ms": 1.0,
                      "pallas": {"measured_wins": {"density": 2.0},
                                 "active": True},
                      "scale": big_scale, "device": "TPU v5e"}}
    line = _json.dumps(bench._compact_summary(full),
                       separators=(",", ":"))
    assert len(line) < 1900
    parsed = _json.loads(line)
    assert parsed["metric"] == "m"
    # nested record noise must never ride along
    assert "noise" not in line and "bulk" not in line

    # the hard-trim fallback: force an oversized scalar field
    full["extra"]["device"] = "d" * 5000
    line2 = _json.dumps(bench._compact_summary(full),
                        separators=(",", ":"))
    assert len(line2) < 1900
    assert _json.loads(line2)["extra"]["full_record"] == "BENCH_FULL.json"
