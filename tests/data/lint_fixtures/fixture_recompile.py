"""gm-lint fixture: known-bad recompile-hazard snippets (parsed, never
imported; line numbers asserted exactly)."""
import functools
import time

import jax

_MUTABLE_TABLE = {"cap": 8}


def _tweak():
    _MUTABLE_TABLE.update(cap=16)


@functools.partial(jax.jit, static_argnames=("cap",))
def fold(x, cap=8):
    return x[: _MUTABLE_TABLE["cap"]] + cap        # line 17: capture


@functools.partial(jax.jit, static_argnames=("shape",))
def pad(x, shape=[8, 8]):                          # line 21: default
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def scale(x, k):
    return x * k


def callers(x):
    fold(x, cap=[1, 2])                            # line 31: unhashable
    fold(x, cap=time.time())                       # line 32: varying
    scale(x, [1, 2])                               # line 33: positional
    scale(x, time.time())                          # line 34: positional
    return scale(x, k=2)                           # fine: constant
