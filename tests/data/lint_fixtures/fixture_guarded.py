"""gm-lint fixture: known-bad lock-discipline snippets (parsed, never
imported; line numbers asserted exactly)."""
import threading


class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        #: guarded-by: self._lock
        self._entries = {}

    def good(self, key, value):
        with self._lock:
            self._entries[key] = value

    def bad_read(self, key):
        return self._entries.get(key)              # line 17: unlocked

    # gm-lint: holds: self._lock
    def evict(self):
        self._entries.clear()

    def bad_after_block(self, key, value):
        with self._lock:
            self._entries[key] = value
        return len(self._entries)                  # line 26: unlocked
