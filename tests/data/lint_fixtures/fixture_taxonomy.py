"""gm-lint fixture: known-bad metric/span taxonomy snippets (parsed,
never imported; line numbers asserted exactly)."""
from geomesa_tpu.metrics import registry
from geomesa_tpu.obs import device_span, obs_count, span


def emit(schema):
    registry.counter("lena.compaction.merges").inc()   # line 8: typo
    registry.timer(f"query.{schema}.plan_ms")          # fine
    obs_count("heta.touch")                            # line 10: typo
    with span("query.scan.warp"):                      # line 11: span
        pass
    with device_span("query.scan.device", stage="probe"):
        pass                                           # fine
