"""gm-lint fixture: known-bad config-option snippets (parsed, never
imported; line numbers asserted exactly)."""

OPTION = "geomesa.made.up.option"                  # line 4: undeclared


def read(user_data):
    return user_data.get("geomesa.also.unknown")   # line 8: undeclared


def pragma_ok(user_data):
    return user_data.get("geomesa.sanctioned.name")  # gm-lint: disable=config-option fixture
