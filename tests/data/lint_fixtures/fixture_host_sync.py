"""gm-lint fixture: known-bad host-sync snippets.  PARSED by the
analyzer tests, never imported — line numbers are asserted exactly, so
edits here must update tests/test_zzzz_static_analysis.py."""
import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.obs import device_span


@jax.jit
def _probe(z):
    return z + 1


def builder(n):
    def app(x):
        return x * n
    return jax.jit(app)


def bad_item(values):
    return values.sum().item()                     # line 23: .item()


def bad_block(z):
    jax.block_until_ready(_probe(z))               # line 27: block


def bad_asarray(z):
    return np.asarray(_probe(z))                   # line 31: np.asarray


def bad_builder_dispatch(z):
    return np.asarray(builder(3)(z))               # line 35: builder


def bad_cast(z):
    return int(jnp.sum(z))                         # line 39: int()


def good_sanctioned(z):
    with device_span("query.scan.device", stage="probe"):
        return np.asarray(_probe(z))


def good_pragma(z):
    return np.asarray(_probe(z))  # gm-lint: disable=host-sync fixture-sanctioned sync
