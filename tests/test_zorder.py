"""Bit-interleave kernels vs an independent pure-python oracle.

Golden expectations mirror the reference's Z2Test/Z3Test "split" cases
(geomesa-z3/src/test/.../Z2Test.scala, Z3Test.scala): splitting value v
intersperses (step-1) zero bits between the bits of v.
"""

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.curve import zorder


def py_split(v: int, step: int) -> int:
    out = 0
    for i in range(32):
        if (v >> i) & 1:
            out |= 1 << (i * step)
    return out


def py_interleave(coords, step):
    out = 0
    for d, c in enumerate(coords):
        out |= py_split(c, step) << d
    return out


GOLDEN = [0x00000000FFFFFF, 0x0, 0x1, 0x000000000C0F02, 0x00000000000802]


def test_split2_golden():
    for v in GOLDEN:
        got = int(zorder.split2(np.uint64(v), xp=np))
        assert got == py_split(v, 2), hex(v)


def test_split3_golden():
    for v in GOLDEN:
        v &= 0x1FFFFF
        got = int(zorder.split3(np.uint64(v), xp=np))
        assert got == py_split(v, 3), hex(v)


def test_roundtrip_2d(rng):
    x = rng.integers(0, 1 << 31, size=1000, dtype=np.int64)
    y = rng.integers(0, 1 << 31, size=1000, dtype=np.int64)
    # include extremes
    x[:2], y[:2] = [0, (1 << 31) - 1], [0, (1 << 31) - 1]
    z = zorder.interleave2(x, y, xp=np)
    rx, ry = zorder.deinterleave2(z, xp=np)
    np.testing.assert_array_equal(rx.astype(np.int64), x)
    np.testing.assert_array_equal(ry.astype(np.int64), y)
    # spot-check against the oracle
    for i in range(10):
        assert int(z[i]) == py_interleave((int(x[i]), int(y[i])), 2)


def test_roundtrip_3d(rng):
    x = rng.integers(0, 1 << 21, size=1000, dtype=np.int64)
    y = rng.integers(0, 1 << 21, size=1000, dtype=np.int64)
    t = rng.integers(0, 1 << 21, size=1000, dtype=np.int64)
    x[:2], y[:2], t[:2] = [0, (1 << 21) - 1], [0, (1 << 21) - 1], [0, (1 << 21) - 1]
    z = zorder.interleave3(x, y, t, xp=np)
    rx, ry, rt = zorder.deinterleave3(z, xp=np)
    np.testing.assert_array_equal(rx.astype(np.int64), x)
    np.testing.assert_array_equal(ry.astype(np.int64), y)
    np.testing.assert_array_equal(rt.astype(np.int64), t)
    for i in range(10):
        assert int(z[i]) == py_interleave((int(x[i]), int(y[i]), int(t[i])), 3)


def test_jnp_matches_numpy(rng):
    x = rng.integers(0, 1 << 31, size=256, dtype=np.int64)
    y = rng.integers(0, 1 << 31, size=256, dtype=np.int64)
    z_np = zorder.interleave2(x, y, xp=np)
    z_jnp = np.asarray(zorder.interleave2(jnp.asarray(x), jnp.asarray(y), xp=jnp))
    np.testing.assert_array_equal(z_np, z_jnp)

    x3 = x & 0x1FFFFF
    y3 = y & 0x1FFFFF
    t3 = rng.integers(0, 1 << 21, size=256, dtype=np.int64)
    z_np3 = zorder.interleave3(x3, y3, t3, xp=np)
    z_jnp3 = np.asarray(zorder.interleave3(jnp.asarray(x3), jnp.asarray(y3), jnp.asarray(t3)))
    np.testing.assert_array_equal(z_np3, z_jnp3)


def test_z_order_is_monotonic_per_dim(rng):
    # increasing one dimension with others fixed must increase z
    x = np.arange(100, dtype=np.int64)
    z = zorder.interleave2(x, np.full(100, 7, dtype=np.int64), xp=np)
    assert np.all(np.diff(z.astype(np.int64)) > 0)
