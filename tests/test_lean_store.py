"""Lean profile inside TpuDataStore (round-4 VERDICT #1): the scale
path served through the SAME facade — ECQL with attribute residuals,
implicit-id lookups, tombstone deletes, row visibility, stats, arrow,
batched windows, and the auto-threshold switch.

Every hit set is oracle-checked against a brute-force evaluation over a
materialized FeatureBatch of all rows."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.filters import evaluate_filter, parse_ecql

MS = 1514764800000
DAY = 86_400_000
N = 120_000


def _mkstore(auth_provider=None):
    rng = np.random.default_rng(17)
    ds = TpuDataStore(auth_provider=auth_provider)
    ds.create_schema(
        "evt", "name:String:index=true,score:Double,dtg:Date,"
               "*geom:Point;geomesa.index.profile=lean")
    for s in range(0, N, 50_000):   # chunked writes straddle slices
        m = min(50_000, N - s)
        ds.write("evt", {
            "name": rng.choice(["a", "b", "c"], m).astype(object),
            "score": rng.uniform(0, 100, m),
            "dtg": rng.integers(MS, MS + 14 * DAY, m),
            "geom": (rng.uniform(-75, -73, m), rng.uniform(40, 42, m))})
    return ds


@pytest.fixture(scope="module")
def ds():
    return _mkstore()


def _oracle(ds, ecql):
    st = ds._store("evt")
    fb = st.batch.take(np.arange(len(st.batch)))
    want = np.flatnonzero(evaluate_filter(parse_ecql(ecql), fb))
    if st.tombstone is not None:
        want = want[~st.tombstone[want]]
    return want


def test_lean_profile_active(ds):
    st = ds._store("evt")
    assert st.lean
    from geomesa_tpu.features.lean import LeanBatch
    assert isinstance(st.batch, LeanBatch)
    from geomesa_tpu.index.z3_lean import LeanZ3Index
    assert isinstance(st.index("z3"), LeanZ3Index)
    # one index build across all chunked writes (incremental appends)
    assert st.build_counts.get("z3") == 1


@pytest.mark.parametrize("ecql,strategy", [
    ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
     "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z", "z3"),
    ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND name = 'a' AND score > 50",
     "z3"),          # attribute residual over gid-decoded candidates
    ("BBOX(geom,-74.2,40.8,-73.9,41.1)", "z3"),   # spatial-only -> z3
    # no spatial -> the round-5 lean attribute tier (was a full scan
    # through round 4 — round-4 VERDICT #1)
    ("name = 'b' AND score < 10", "attr:"),
])
def test_ecql_oracle_and_strategy(ds, ecql, strategy):
    got = ds.query_result("evt", ecql)
    assert (got.strategy.index.startswith(strategy)
            if strategy.endswith(":") else got.strategy.index == strategy)
    np.testing.assert_array_equal(np.sort(got.positions),
                                  _oracle(ds, ecql))
    # result batch carries the implicit ids of the hit rows
    assert list(got.batch.ids[:3]) == [str(int(p))
                                       for p in got.positions[:3]]


def test_implicit_id_lookup(ds):
    got = ds.query("evt", "IN ('123','999999999','007','xyz')")
    assert list(got.ids) == ["123"]   # non-canonical/man-made ids miss
    assert ds.get_count("evt", "IN ('5','6')") == 2


def test_sort_limit_projection(ds):
    from geomesa_tpu.planning.planner import Query
    q = Query.of("BBOX(geom,-74.5,40.5,-73.5,41.5)",
                 properties=["name", "score"], sort_by="score",
                 sort_desc=True, max_features=10)
    got = ds.query("evt", q)
    assert len(got) == 10 and set(got.columns) == {"name", "score"}
    scores = got.column("score")
    assert np.all(np.diff(scores) <= 0)
    want = _oracle(ds, "BBOX(geom,-74.5,40.5,-73.5,41.5)")
    st = ds._store("evt")
    all_scores = st.batch.column("score")[want]
    np.testing.assert_allclose(scores, np.sort(all_scores)[::-1][:10])


def test_batched_windows(ds):
    wins = [([(-74.5, 40.5, -73.5, 41.5)], MS + 2 * DAY, MS + 9 * DAY),
            ([(-74.2, 40.1, -73.1, 41.2)], None, None)]
    hits = ds.query_windows("evt", wins)
    st = ds._store("evt")
    x, y = st.batch.geom_xy()
    t = st.batch.column("dtg")
    for h, (bxs, lo, hi) in zip(hits, wins):
        b = bxs[0]
        m = (x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
        if lo is not None:
            m &= t >= lo
        if hi is not None:
            m &= t <= hi
        want = np.flatnonzero(m)
        if st.tombstone is not None:
            want = want[~st.tombstone[want]]
        np.testing.assert_array_equal(np.sort(h), want)


def test_stats_bounds_arrow(ds):
    assert ds.get_count("evt") == N
    env = ds.get_bounds("evt")
    assert env is not None and -75 <= env.xmin <= env.xmax <= -73
    mm = ds.stat("evt", "score_minmax")
    assert 0 <= mm.bounds[0] <= mm.bounds[1] <= 100
    lo, hi = ds.get_attribute_bounds("evt", "score")
    assert (lo, hi) == mm.bounds
    ecql = "name = 'c' AND BBOX(geom,-74.5,40.5,-73.5,41.5)"
    pa = pytest.importorskip("pyarrow")
    tbl = ds.query_arrow("evt", ecql,
                         dictionary_fields=("name",)).to_table()
    assert tbl.num_rows == len(_oracle(ds, ecql))
    assert isinstance(tbl.schema.field("name").type, pa.DictionaryType)


def test_sql_over_lean(ds):
    from geomesa_tpu.sql import sql_query
    out = sql_query(ds, "SELECT count(*) AS n FROM evt WHERE "
                        "st_intersects(geom, st_geomFromWKT('POLYGON(("
                        "-74.5 40.5, -73.5 40.5, -73.5 41.5, -74.5 41.5,"
                        " -74.5 40.5))')) GROUP BY name")
    assert int(np.sum(out["n"])) == len(
        _oracle(ds, "BBOX(geom,-74.5,40.5,-73.5,41.5)"))


def test_processes_over_lean(ds):
    from geomesa_tpu.process import knn_process
    from geomesa_tpu.process.knn import haversine_m
    kpos, kdist = knn_process(ds, "evt", -74.0, 41.0, 15)
    st = ds._store("evt")
    x, y = st.batch.geom_xy()
    d = haversine_m(-74.0, 41.0, x, y)
    if st.tombstone is not None:
        d = d[~st.tombstone]
    np.testing.assert_allclose(np.sort(kdist), np.sort(d)[:15],
                               rtol=1e-12)


def test_explain_shows_lean_strategy(ds):
    text = ds.explain("evt", "BBOX(geom,-74.5,40.5,-73.5,41.5)")
    assert "z3" in text and "full" in text  # options + choice listed


def test_delete_tombstones():
    ds = _mkstore()
    st = ds._store("evt")
    before = ds.query_result(
        "evt", "BBOX(geom,-74.5,40.5,-73.5,41.5)").positions
    n_del = ds.delete("evt", [str(int(p)) for p in before[:100]])
    assert n_del == 100
    assert ds.delete("evt", [str(int(before[0]))]) == 0  # idempotent
    after = ds.query_result(
        "evt", "BBOX(geom,-74.5,40.5,-73.5,41.5)").positions
    np.testing.assert_array_equal(after, before[100:])
    assert ds.get_count("evt") == N - 100
    # stats recomputed over live rows
    assert ds.stat("evt", "count").count == N - 100
    # ids never reused: new writes mint fresh row ids past the deletes
    ds.write("evt", {"name": np.array(["z"], object),
                     "score": np.array([1.0]),
                     "dtg": np.array([MS]),
                     "geom": (np.array([-74.0]), np.array([41.0]))})
    got = ds.query("evt", f"IN ('{N}')")
    assert len(got) == 1 and got.column("name")[0] == "z"


def test_row_visibility():
    class Auth:
        def __init__(self):
            self.auths = frozenset()

        def get_authorizations(self):
            return self.auths

    auth = Auth()
    rng = np.random.default_rng(5)
    ds = TpuDataStore(auth_provider=auth)
    ds.create_schema("sec", "dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    m = 1000
    open_pts = {"dtg": rng.integers(MS, MS + DAY, m),
                "geom": (rng.uniform(-75, -73, m),
                         rng.uniform(40, 42, m))}
    ds.write("sec", open_pts)
    ds.write("sec", {"dtg": rng.integers(MS, MS + DAY, m),
                     "geom": (rng.uniform(-75, -73, m),
                              rng.uniform(40, 42, m))},
             visibility="admin")
    got = ds.query_result("sec", "BBOX(geom,-75,40,-73,42)")
    assert len(got.positions) == m          # admin rows hidden
    assert got.positions.max() < m
    assert ds.get_count("sec") == m
    auth.auths = frozenset(["admin"])
    got = ds.query_result("sec", "BBOX(geom,-75,40,-73,42)")
    assert len(got.positions) == 2 * m


def test_lean_rejections(ds):
    with pytest.raises(ValueError, match="implicit feature ids"):
        ds.write("evt", {"name": np.array(["x"], object),
                         "score": np.array([1.0]),
                         "dtg": np.array([MS]),
                         "geom": (np.array([-74.0]), np.array([41.0]))},
                 ids=["custom"])
    with pytest.raises(ValueError, match="attribute-level visibility"):
        ds.write("evt", {"name": np.array(["x"], object),
                         "score": np.array([1.0]),
                         "dtg": np.array([MS]),
                         "geom": (np.array([-74.0]), np.array([41.0]))},
                 attribute_visibilities={"name": "admin"})
    with pytest.raises(ValueError, match="z3/id only"):
        ds._store("evt").index("z2")
    # round-5: indexed attributes are SERVED (the lean attribute tier);
    # un-indexed attributes still reject
    from geomesa_tpu.index.attr_lean import LeanAttrIndex
    assert isinstance(ds._store("evt").attribute_index("name"),
                      LeanAttrIndex)
    with pytest.raises(ValueError, match="not lean-indexable"):
        ds._store("evt").attribute_index("score")
    with pytest.raises(AttributeError, match="implicit ids"):
        _ = ds._store("evt").batch.ids
    # round-5: non-point lean schemas are SERVED (the lean XZ2 tier);
    # a lean schema with no geometry at all still rejects
    ds.create_schema("poly-ok", "v:Int,*poly:Polygon;"
                                "geomesa.index.profile=lean")
    assert ds._store("poly-ok").lean_kind == "xz2"
    with pytest.raises(ValueError, match="point geometry"):
        ds.create_schema("bad", "v:Int,dtg:Date;"
                                "geomesa.index.profile=lean")


def test_auto_threshold_switch(monkeypatch):
    monkeypatch.setattr(TpuDataStore, "LEAN_AUTO_ROWS", 5_000)
    ds = TpuDataStore()
    ds.create_schema("auto", "dtg:Date,*geom:Point")
    rng = np.random.default_rng(3)
    m = 6_000
    ds.write("auto", {"dtg": rng.integers(MS, MS + DAY, m),
                      "geom": (rng.uniform(-75, -73, m),
                               rng.uniform(40, 42, m))})
    st = ds._store("auto")
    assert st.lean
    assert st.sft.user_data.get("geomesa.index.profile") == "lean"
    got = ds.query_result("auto", "BBOX(geom,-74.5,40.5,-73.5,41.5)")
    x, y = st.batch.geom_xy()
    want = np.flatnonzero((x >= -74.5) & (x <= -73.5)
                          & (y >= 40.5) & (y <= 41.5))
    np.testing.assert_array_equal(np.sort(got.positions), want)
    # a small first write does NOT switch
    ds.create_schema("small", "dtg:Date,*geom:Point")
    ds.write("small", {"dtg": np.full(10, MS),
                       "geom": (np.zeros(10), np.zeros(10))})
    assert not ds._store("small").lean


def test_lean_store_over_mesh():
    """The lean profile composes with a device mesh (round-4 VERDICT
    #4): the ShardedLeanZ3Index serves the same facade, oracle-equal
    with the single-chip lean store."""
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.lean import ShardedLeanZ3Index

    rng = np.random.default_rng(29)
    n = 40_000
    data = {
        "name": rng.choice(["a", "b", "c"], n).astype(object),
        "score": rng.uniform(0, 100, n),
        "dtg": rng.integers(MS, MS + 14 * DAY, n),
        "geom": (rng.uniform(-75, -73, n), rng.uniform(40, 42, n))}
    ds = TpuDataStore(mesh=device_mesh())
    ds.create_schema(
        "evt", "name:String:index=true,score:Double,dtg:Date,"
               "*geom:Point;geomesa.index.profile=lean")
    ds.write("evt", {k: (v if k != "geom" else v) for k, v in
                     data.items()})
    st = ds._store("evt")
    assert isinstance(st.index("z3"), ShardedLeanZ3Index)
    plain = TpuDataStore()
    plain.create_schema(
        "evt", "name:String:index=true,score:Double,dtg:Date,"
               "*geom:Point;geomesa.index.profile=lean")
    plain.write("evt", data)
    for ecql in ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
                 "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z",
                 "BBOX(geom,-74.5,40.5,-73.5,41.5) AND name = 'a'"):
        a = ds.query_result("evt", ecql)
        b = plain.query_result("evt", ecql)
        np.testing.assert_array_equal(np.sort(a.positions),
                                      np.sort(b.positions))
    # batched windows + delete parity
    wins = [([(-74.5, 40.5, -73.5, 41.5)], MS + 2 * DAY, MS + 9 * DAY),
            ([(-74.2, 40.1, -73.1, 41.2)], None, None)]
    for hm, hp in zip(ds.query_windows("evt", wins),
                      plain.query_windows("evt", wins)):
        np.testing.assert_array_equal(np.sort(hm), np.sort(hp))
    assert ds.delete("evt", ["7", "9"]) == 2
    assert plain.delete("evt", ["7", "9"]) == 2
    a = ds.query_result("evt", "BBOX(geom,-75,40,-73,42)")
    b = plain.query_result("evt", "BBOX(geom,-75,40,-73,42)")
    np.testing.assert_array_equal(np.sort(a.positions),
                                  np.sort(b.positions))


def test_lean_snapshot_roundtrip(tmp_path, monkeypatch):
    """flush → reload for a lean schema: chunked parquet parts +
    manifest restore rows, tombstones, visibilities, and the envelope;
    the index rebuilds lazily through the streaming append path and
    queries stay oracle-exact (checkpoint/resume at scale)."""
    import os

    monkeypatch.setattr(TpuDataStore, "LEAN_PART_ROWS", 1 << 12)
    rng = np.random.default_rng(41)
    n = 20_000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(MS, MS + 14 * DAY, n)
    score = rng.uniform(0, 100, n)
    ds = TpuDataStore(str(tmp_path / "cat"))
    ds.create_schema("evt", "score:Double,dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    ds.write("evt", {"score": score, "dtg": t, "geom": (x, y)},
             visibility="user")
    ds.delete("evt", ["7", "19", "4242"])
    ds.flush("evt")
    d = tmp_path / "cat" / "evt.lean"
    parts = [f for f in os.listdir(d) if f.startswith("part-")]
    assert len(parts) >= 4            # chunking actually happened

    class Auth:
        def get_authorizations(self):
            return frozenset({"user"})

    ds2 = TpuDataStore(str(tmp_path / "cat"), auth_provider=Auth())
    st2 = ds2._store("evt")
    assert st2.lean and len(st2.batch) == n
    assert st2.tombstone is not None and int(st2.tombstone.sum()) == 3
    assert st2.visibilities is not None
    assert ds2.stat("evt", "count").count == n - 3   # live rows only
    ecql = ("BBOX(geom, -74.5, 40.5, -73.5, 41.5) AND score > 50 AND "
            f"dtg DURING 2018-01-03T00:00:00Z/2018-01-09T00:00:00Z")
    got = ds2.query("evt", ecql)
    want = _oracle(ds2, ecql)
    np.testing.assert_array_equal(
        np.sort(np.asarray(got.ids).astype(np.int64)), want)
    # the reloaded store keeps ingesting through the same live path
    ds2.write("evt", {"score": np.array([99.0]),
                      "dtg": np.array([MS + DAY]),
                      "geom": (np.array([-74.0]), np.array([41.0]))})
    assert len(st2.batch) == n + 1


def test_lean_stats_persist_without_flush(tmp_path):
    ds = TpuDataStore(str(tmp_path / "cat"))
    ds.create_schema("evt", "dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    ds.write("evt", {"dtg": np.full(10, MS),
                     "geom": (np.zeros(10), np.zeros(10))})
    ds.persist_stats("evt")
    ds2 = TpuDataStore(str(tmp_path / "cat"))
    assert ds2._store("evt").lean      # profile survives the catalog
    assert ds2.stat("evt", "count").count == 10
    # no snapshot was flushed: rows are empty, stats still answer
    assert len(ds2._store("evt").batch) == 0


def test_remove_schema_clears_lean_snapshot(tmp_path):
    """A removed schema's snapshot dir must go with it — a stale one
    would resurrect the old rows into a later schema of the same
    name."""
    import os

    ds = TpuDataStore(str(tmp_path / "cat"))
    ds.create_schema("evt", "dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    ds.write("evt", {"dtg": np.full(10, MS),
                     "geom": (np.zeros(10), np.zeros(10))})
    ds.flush("evt")
    assert os.path.isdir(tmp_path / "cat" / "evt.lean")
    ds.remove_schema("evt")
    assert not os.path.exists(tmp_path / "cat" / "evt.lean")
    ds.create_schema("evt", "dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    ds2 = TpuDataStore(str(tmp_path / "cat"))
    assert len(ds2._store("evt").batch) == 0


def test_lean_reflush_is_crash_safe(tmp_path, monkeypatch):
    """Re-flush writes new-stamp parts, swaps the manifest atomically,
    THEN removes the prior flush's parts — at every intermediate point
    the on-disk manifest references only files that exist."""
    import json
    import os

    monkeypatch.setattr(TpuDataStore, "LEAN_PART_ROWS", 64)
    ds = TpuDataStore(str(tmp_path / "cat"))
    ds.create_schema("evt", "dtg:Date,*geom:Point;"
                            "geomesa.index.profile=lean")
    ds.write("evt", {"dtg": np.full(100, MS),
                     "geom": (np.zeros(100), np.zeros(100))})
    ds.flush("evt")
    d = tmp_path / "cat" / "evt.lean"
    first = {f for f in os.listdir(d) if f.startswith("part-")}
    ds.write("evt", {"dtg": np.full(100, MS + DAY),
                     "geom": (np.ones(100), np.ones(100))})
    ds.flush("evt")
    with open(d / "manifest.json") as f:
        manifest = json.load(f)
    on_disk = {f for f in os.listdir(d) if f.startswith("part-")}
    assert manifest["stamp"] == 1
    assert set(manifest["parts"]) == on_disk     # orphans removed
    assert not (first & on_disk)                 # old stamp retired
    ds2 = TpuDataStore(str(tmp_path / "cat"))
    assert len(ds2._store("evt").batch) == 200


def test_tight_budget_never_allocates_doomed_payload():
    """Under a budget too small for any full-tier generation, rollovers
    create keys-tier generations directly instead of allocating payload
    arrays the rebalance would free moments later."""
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel import lean as plean

    requested = []
    orig = plean._ShardedGen.__init__

    def spy(self, mesh, slots, tier="keys"):
        requested.append(tier)
        orig(self, mesh, slots, tier=tier)

    plean._ShardedGen.__init__ = spy
    try:
        rng = np.random.default_rng(3)
        m = 40_000
        idx = plean.ShardedLeanZ3Index(
            period="week", mesh=device_mesh(),
            generation_slots=1 << 10,
            hbm_budget_bytes=(1 << 10) * 20 * 3)
        idx.append(rng.uniform(-75, -73, m), rng.uniform(40, 42, m),
                   rng.integers(MS, MS + 14 * DAY, m))
    finally:
        plean._ShardedGen.__init__ = orig
    assert len(requested) >= 3
    assert "full" not in requested


def test_projection_pushes_into_take(ds):
    """Query.properties restricts which physical columns the take
    materializes (sum(score) over many hits must not copy geometry
    columns); the result still carries ids and only projected
    columns."""
    st = ds._store("evt")
    sub = st.batch.take(np.arange(50), columns={"score"})
    assert set(sub.columns) == {"score"} and len(sub.ids) == 50
    from geomesa_tpu.planning.planner import Query
    got = ds.query("evt", Query.of(
        "BBOX(geom,-74.5,40.5,-73.5,41.5)", properties=["score"]))
    assert set(got.columns) == {"score"}


def test_mesh_lean_snapshot_roundtrip(tmp_path):
    """Snapshot flush/reload composes with the mesh (single-controller)
    lean store: the reloaded store rebuilds its ShardedLeanZ3Index by
    streaming the restored parts and answers oracle-exact."""
    from geomesa_tpu.parallel import device_mesh
    from geomesa_tpu.parallel.lean import ShardedLeanZ3Index

    saved = ShardedLeanZ3Index.GENERATION_SLOTS
    ShardedLeanZ3Index.GENERATION_SLOTS = 1 << 13   # CI-sized appends
    try:
        rng = np.random.default_rng(31)
        n = 30_000
        x = rng.uniform(-75, -73, n)
        y = rng.uniform(40, 42, n)
        t = rng.integers(MS, MS + 14 * DAY, n)
        ds = TpuDataStore(str(tmp_path / "cat"), mesh=device_mesh())
        ds.create_schema("evt", "score:Double,dtg:Date,*geom:Point;"
                                "geomesa.index.profile=lean")
        ds.write("evt", {"score": rng.uniform(0, 100, n),
                         "dtg": t, "geom": (x, y)})
        ds.delete("evt", ["3"])
        ds.flush("evt")
        # delete a row KNOWN to be inside the query bbox, so the
        # reload assertion has teeth
        inside = int(np.flatnonzero(
            (x >= -74.5) & (x <= -73.5) & (y >= 40.5)
            & (y <= 41.5))[0])
        ds.delete("evt", [str(inside)])
        ds.flush("evt")
        ds2 = TpuDataStore(str(tmp_path / "cat"), mesh=device_mesh())
        st2 = ds2._store("evt")
        assert len(st2.batch) == n
        assert st2.tombstone[3] and st2.tombstone[inside]  # persisted
        got = ds2.query("evt", "BBOX(geom,-74.5,40.5,-73.5,41.5)")
        assert isinstance(st2.index("z3"), ShardedLeanZ3Index)
        want = _oracle(ds2, "BBOX(geom,-74.5,40.5,-73.5,41.5)")
        assert inside not in want
        np.testing.assert_array_equal(
            np.sort(np.asarray(got.ids).astype(np.int64)), want)
    finally:
        ShardedLeanZ3Index.GENERATION_SLOTS = saved
