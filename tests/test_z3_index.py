"""Z3 index hit-set equality vs a brute-force numpy oracle — the analog of
the reference's *IdxStrategyTest pattern (scan hits vs brute-force filter
over inserted fixtures, SURVEY.md §4)."""

import numpy as np
import pytest

from geomesa_tpu.curve import TimePeriod, max_offset
from geomesa_tpu.index import Z3PointIndex
from geomesa_tpu.index.z3 import plan_z3_query

MS_2018 = 1514764800000  # 2018-01-01T00:00:00Z


def oracle(x, y, t, boxes, tlo, thi):
    boxes = np.atleast_2d(boxes)
    m = np.zeros(len(x), dtype=bool)
    for b in boxes:
        m |= (x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])
    m &= (t >= tlo) & (t <= thi)
    return np.flatnonzero(m)


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(99)
    n = 200_000
    x = rng.uniform(-75.0, -73.0, n)
    y = rng.uniform(40.0, 42.0, n)
    t = rng.integers(MS_2018, MS_2018 + 30 * 86_400_000, n)  # ~4.3 weeks
    return x, y, t


@pytest.fixture(scope="module")
def index(dataset):
    x, y, t = dataset
    return Z3PointIndex.build(x, y, t, period=TimePeriod.WEEK)


def test_single_week_bbox(index, dataset):
    x, y, t = dataset
    box = (-74.2, 40.5, -73.7, 41.2)
    tlo, thi = MS_2018 + 86_400_000, MS_2018 + 3 * 86_400_000
    got = index.query([box], tlo, thi)
    np.testing.assert_array_equal(got, oracle(x, y, t, box, tlo, thi))


def test_multi_week_interval(index, dataset):
    x, y, t = dataset
    box = (-74.5, 40.2, -73.5, 41.8)
    tlo, thi = MS_2018 + 3 * 86_400_000, MS_2018 + 17 * 86_400_000
    got = index.query([box], tlo, thi)
    np.testing.assert_array_equal(got, oracle(x, y, t, box, tlo, thi))


def test_exact_boundary_inclusive(index, dataset):
    x, y, t = dataset
    # query bounds exactly at data points: inclusive on all edges
    i = 12345
    box = (x[i], y[i], x[i], y[i])
    got = index.query([box], int(t[i]), int(t[i]))
    assert i in got
    np.testing.assert_array_equal(got, oracle(x, y, t, box, t[i], t[i]))


def test_multiple_boxes(index, dataset):
    x, y, t = dataset
    boxes = [(-74.9, 40.1, -74.5, 40.4), (-73.6, 41.5, -73.1, 41.9)]
    tlo, thi = MS_2018, MS_2018 + 20 * 86_400_000
    got = index.query(boxes, tlo, thi)
    np.testing.assert_array_equal(got, oracle(x, y, t, boxes, tlo, thi))


def test_empty_result(index, dataset):
    got = index.query([(10.0, 10.0, 11.0, 11.0)], MS_2018, MS_2018 + 86_400_000)
    assert len(got) == 0


def test_interval_outside_data(index):
    got = index.query([(-75.0, 40.0, -73.0, 42.0)], 0, MS_2018 - 1)
    assert len(got) == 0


def test_whole_dataset(index, dataset):
    x, y, t = dataset
    box = (-180.0, -90.0, 180.0, 90.0)
    tlo, thi = MS_2018, MS_2018 + 31 * 86_400_000
    got = index.query([box], tlo, thi)
    np.testing.assert_array_equal(got, np.arange(len(x)))


@pytest.mark.parametrize("period", [TimePeriod.DAY, TimePeriod.MONTH, TimePeriod.YEAR])
def test_other_periods(period, dataset):
    x, y, t = dataset
    idx = Z3PointIndex.build(x, y, t, period=period)
    box = (-74.3, 40.4, -73.8, 41.3)
    tlo, thi = MS_2018 + 5 * 86_400_000, MS_2018 + 12 * 86_400_000
    got = idx.query([box], tlo, thi)
    np.testing.assert_array_equal(got, oracle(x, y, t, box, tlo, thi))


def test_small_range_budget_still_exact(index, dataset):
    x, y, t = dataset
    box = (-74.4, 40.3, -73.6, 41.7)
    tlo, thi = MS_2018 + 2 * 86_400_000, MS_2018 + 9 * 86_400_000
    got = index.query([box], tlo, thi, max_ranges=16)
    np.testing.assert_array_equal(got, oracle(x, y, t, box, tlo, thi))


def test_plan_respects_range_budget():
    plan = plan_z3_query([(-74.4, 40.3, -73.6, 41.7)], MS_2018,
                         MS_2018 + 13 * 86_400_000, max_ranges=100)
    # budget is split per bin; merging can only reduce counts
    assert plan.num_ranges <= 100 + 3 * 8  # slack for per-bin rounding
    assert (plan.rzlo <= plan.rzhi).all()


def test_time_window_boundaries():
    from geomesa_tpu.index.z3 import _time_windows_by_bin
    w = _time_windows_by_bin(MS_2018, MS_2018 + 13 * 86_400_000, TimePeriod.WEEK)
    assert len(w) == 3  # 2018-01-01 is exactly a week-bin boundary? bins 2504-2506
    week = max_offset(TimePeriod.WEEK)
    bins = sorted(w)
    # first bin starts mid-bin (2018-01-01 is a Monday; epoch weeks start
    # Thursday), so a partial window
    assert w[bins[0]][1] == week
    assert w[bins[-1]][0] == 0


def test_query_many_matches_query(index, dataset):
    """Batched multi-window scan returns the same hit sets as individual
    queries (and brute force)."""
    x, y, t = dataset
    idx = index
    MS = MS_2018
    windows = [
        ([(-74.5, 40.5, -73.5, 41.5)], MS + 2 * 86_400_000, MS + 7 * 86_400_000),
        ([(-74.2, 40.8, -73.9, 41.1)], MS, MS + 3 * 86_400_000),
        ([(-80.0, 35.0, -79.0, 36.0)], MS, MS + 14 * 86_400_000),  # empty
    ]
    batched = idx.query_many(windows)
    for (boxes, lo, hi), got in zip(windows, batched):
        single = idx.query(boxes, lo, hi)
        assert np.array_equal(got, single)


def test_query_open_time_bounds(index, dataset):
    """None time bounds clamp to the data's extent (not the epoch)."""
    x, y, t = dataset
    got = index.query([(-74.5, 40.5, -73.5, 41.5)], None, None)
    brute = np.flatnonzero((x >= -74.5) & (x <= -73.5)
                           & (y >= 40.5) & (y <= 41.5))
    assert np.array_equal(got, brute)


def test_two_phase_query_exact(monkeypatch):
    """Force the two-phase (device-compact) path and check exactness."""
    import numpy as np
    from geomesa_tpu.index import z3 as z3mod

    monkeypatch.setattr(z3mod, "TWO_PHASE_MIN_CAPACITY", 1)
    rng = np.random.default_rng(31)
    n = 50_000
    ms = 1514764800000
    x = rng.uniform(-75, -73, n)
    y = rng.uniform(40, 42, n)
    t = rng.integers(ms, ms + 14 * 86_400_000, n)
    idx = z3mod.Z3PointIndex.build(x, y, t, period="week")
    idx._capacity = 1 << 15
    box = (-74.5, 40.5, -73.5, 41.5)
    lo, hi = ms + 86_400_000, ms + 9 * 86_400_000
    hits = idx.query([box], lo, hi)
    want = np.flatnonzero(
        (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
        & (t >= lo) & (t <= hi))
    np.testing.assert_array_equal(hits, want)
    # empty result through the compact path
    none = idx.query([(10.0, 10.0, 11.0, 11.0)], lo, hi)
    assert len(none) == 0


def test_append_merge_matches_rebuild():
    """Device gather-merge append == full rebuild, repeatedly."""
    import numpy as np
    from geomesa_tpu.index import Z3PointIndex

    rng = np.random.default_rng(17)
    ms = 1514764800000
    n0 = 30_000
    x = rng.uniform(-180, 180, n0)
    y = rng.uniform(-85, 85, n0)
    t = rng.integers(ms, ms + 21 * 86_400_000, n0)
    idx = Z3PointIndex.build(x, y, t, period="week")
    for m in (1, 500, 7_000):
        nx = rng.uniform(-180, 180, m)
        ny = rng.uniform(-85, 85, m)
        nt = rng.integers(ms - 86_400_000, ms + 30 * 86_400_000, m)
        idx.append(nx, ny, nt)
        x = np.concatenate([x, nx]); y = np.concatenate([y, ny])
        t = np.concatenate([t, nt])
        ref = Z3PointIndex.build(x, y, t, period="week")
        k = len(ref)  # appended arrays are capacity-padded past n_rows
        np.testing.assert_array_equal(
            np.asarray(idx.bins)[:k], np.asarray(ref.bins))
        np.testing.assert_array_equal(
            np.asarray(idx.z)[:k], np.asarray(ref.z))
        # query exactness after append (positions may tie-break
        # differently than rebuild, so compare hit sets vs brute force)
        box = (-40.0, -30.0, 50.0, 45.0)
        lo, hi = ms + 86_400_000, ms + 12 * 86_400_000
        hits = idx.query([box], lo, hi)
        want = np.flatnonzero(
            (x >= box[0]) & (x <= box[2]) & (y >= box[1]) & (y <= box[3])
            & (t >= lo) & (t <= hi))
        np.testing.assert_array_equal(hits, want)
    assert len(idx) == len(x)


def test_append_empty_noop():
    import numpy as np
    from geomesa_tpu.index import Z3PointIndex

    ms = 1514764800000
    idx = Z3PointIndex.build([1.0], [2.0], [ms], period="week")
    idx.append([], [], [])
    assert len(idx) == 1
