"""Metric-naming lint (ISSUE 9 satellite): after the whole suite has
run (this module collects LAST — 'zzz' sorts after every 'zz_'), walk
the full process-global metric registry and assert every key matches
the namespace contract documented in docs/observability.md.  A drive-by
metric typo (``lena.compaction.merges``) lands a key outside the
contract and fails here at tier-1 time instead of silently splitting a
dashboard.
"""

from geomesa_tpu.metrics import (
    METRIC_NAMESPACES, lint_metric_names, registry,
)


def test_registry_keys_match_naming_contract():
    names = registry.names()
    # the suite must have populated the registry — an empty walk would
    # make this test vacuously green
    assert names, "expected the suite to have recorded metrics"
    violations = lint_metric_names(names)
    assert violations == [], (
        f"metric keys outside the documented namespaces "
        f"{METRIC_NAMESPACES}: {violations} — fix the key or extend "
        f"the contract in docs/observability.md AND metrics.py")


def test_lint_catches_bad_keys():
    bad = ["lena.compaction.merges",      # namespace typo
           "query",                       # bare namespace, no leaf
           "lean..double_dot",
           "lean.spaced key",
           "unknown.thing"]
    good = ["query.evt.count", "lean.device.ms", "jax.compile.count",
            "storage.evt.attr:score.device_bytes", "web.200",
            "plan.estimate.ratio", "write.pts.features",
            "pallas.density.fallback", "obs.test.empty_ms"]
    assert lint_metric_names(good) == []
    assert lint_metric_names(good + bad) == sorted(bad)
