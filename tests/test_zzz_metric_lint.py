"""Metric-naming lint (ISSUE 9 satellite): after the whole suite has
run (this module collects LAST — 'zzz' sorts after every 'zz_'), walk
the full process-global metric registry and assert every key matches
the namespace contract documented in docs/observability.md.  A drive-by
metric typo (``lena.compaction.merges``) lands a key outside the
contract and fails here at tier-1 time instead of silently splitting a
dashboard.

ISSUE 12 extends the walk: the suite's registry snapshot only covers
keys some earlier test happened to emit, and the ``heat.*``/``job.*``
gauges exist only after a write/compaction cycle has been OBSERVED and
published — so this module drives one explicitly (write → query →
compaction job → heat + storage gauge publication) before linting,
guaranteeing the write/heat/job namespaces are present in the walk
rather than vacuously absent.
"""

import numpy as np

from geomesa_tpu.metrics import (
    METRIC_NAMESPACES, lint_metric_names, registry,
)

MS = 1514764800000
DAY = 86_400_000


def test_registry_covers_write_and_job_cycle_gauges():
    """Drive a full write → query → compaction-job → publish cycle so
    the gauges that exist ONLY after it (heat.*, job.*, write seal
    counters) are registered for the final lint walk."""
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.jobs import run_compaction

    rng = np.random.default_rng(77)
    ds = TpuDataStore(user="lint-cycle")
    ds.create_schema(
        "lintcyc", "dtg:Date,*geom:Point;"
                   "geomesa.index.profile=lean,"
                   "geomesa.lean.generation.slots=4096,"
                   "geomesa.lean.compaction.factor=0")
    for _ in range(3):
        ds.write("lintcyc", {
            "dtg": rng.integers(MS, MS + 14 * DAY, 4096),
            "geom": (rng.uniform(-75, -73, 4096),
                     rng.uniform(40, 42, 4096))})
    ds.query("lintcyc", "BBOX(geom,-75,40,-73,42)")
    run_compaction(ds, "lintcyc")
    rep = ds.heat_report()         # publishes the heat.* gauges
    assert rep["generations"], "expected tracked generations"
    ds.storage_report()            # publishes the storage.* gauges
    names = registry.names()
    # the cycle-only namespaces are PRESENT, so the lint below is not
    # vacuous over them
    assert any(n.startswith("heat.") for n in names)
    assert any(n.startswith("job.compaction.") for n in names)
    assert "write.seals" in names
    assert "write.lintcyc.features" in names


def test_registry_keys_match_naming_contract():
    names = registry.names()
    # the suite must have populated the registry — an empty walk would
    # make this test vacuously green
    assert names, "expected the suite to have recorded metrics"
    violations = lint_metric_names(names)
    assert violations == [], (
        f"metric keys outside the documented namespaces "
        f"{METRIC_NAMESPACES}: {violations} — fix the key or extend "
        f"the contract in docs/observability.md AND metrics.py")


def test_lint_catches_bad_keys():
    bad = ["lena.compaction.merges",      # namespace typo
           "query",                       # bare namespace, no leaf
           "lean..double_dot",
           "lean.spaced key",
           "heta.evt.z3.temperature",     # heat namespace typo
           "unknown.thing"]
    good = ["query.evt.count", "lean.device.ms", "jax.compile.count",
            "storage.evt.attr:score.device_bytes", "web.200",
            "plan.estimate.ratio", "write.pts.features",
            "pallas.density.fallback", "obs.test.empty_ms",
            "heat.evt.z3.temperature", "heat.total.temperature",
            "job.ingest.runs", "job.compaction.ms",
            "write.seals", "write.spills"]
    assert lint_metric_names(good) == []
    assert lint_metric_names(good + bad) == sorted(bad)
