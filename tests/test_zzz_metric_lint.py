"""Metric-naming lint (ISSUE 9 satellite): the runtime
gauge-PRESENCE half of the metric contract — drive a full write →
query → compaction-job → publish cycle so the gauges that only exist
after it are registered, then walk the registry ('zzz' collects after
every 'zz_' so the walk covers the whole suite).

ISSUE 13 moved the name-CONTRACT half to the static analyzer: every
metric/span name LITERAL in the tree is validated by the ``taxonomy``
check of ``python -m geomesa_tpu.analysis`` (tests/
test_zzzz_static_analysis.py runs it tier-1), independent of which
keys a test cycle happens to emit.  The delegation test below pins
that coverage; the registry walk stays as the backstop for
dynamically-BUILT keys (f-string schema/kind segments) that no static
pass can see.
"""

import numpy as np

from geomesa_tpu.metrics import (
    METRIC_NAMESPACES, lint_metric_names, registry,
)

MS = 1514764800000
DAY = 86_400_000


def test_registry_covers_write_and_job_cycle_gauges():
    """Drive a full write → query → compaction-job → publish cycle so
    the gauges that exist ONLY after it (heat.*, job.*, write seal
    counters) are registered for the final lint walk."""
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.jobs import run_compaction

    rng = np.random.default_rng(77)
    ds = TpuDataStore(user="lint-cycle")
    ds.create_schema(
        "lintcyc", "dtg:Date,*geom:Point;"
                   "geomesa.index.profile=lean,"
                   "geomesa.lean.generation.slots=4096,"
                   "geomesa.lean.compaction.factor=0")
    for _ in range(3):
        ds.write("lintcyc", {
            "dtg": rng.integers(MS, MS + 14 * DAY, 4096),
            "geom": (rng.uniform(-75, -73, 4096),
                     rng.uniform(40, 42, 4096))})
    ds.query("lintcyc", "BBOX(geom,-75,40,-73,42)")
    run_compaction(ds, "lintcyc")
    rep = ds.heat_report()         # publishes the heat.* gauges
    assert rep["generations"], "expected tracked generations"
    ds.storage_report()            # publishes the storage.* gauges
    names = registry.names()
    # the cycle-only namespaces are PRESENT, so the lint below is not
    # vacuous over them
    assert any(n.startswith("heat.") for n in names)
    assert any(n.startswith("job.compaction.") for n in names)
    assert "write.seals" in names
    assert "write.lintcyc.features" in names


def test_name_contract_delegated_to_static_check(gm_lint_tree):
    """The name-contract half is the static ``taxonomy`` check now
    (module doc): zero unbaselined taxonomy findings over the tree —
    cycle-INDEPENDENT, so a typo'd literal fails even when no test
    ever executes it.  Filters the session-shared full pass rather
    than re-parsing the package."""
    from geomesa_tpu.analysis import Baseline
    findings = [f for f in gm_lint_tree[0] if f.check_id == "taxonomy"]
    new, _, _ = Baseline.load().split(findings)
    assert new == [], "\n".join(f.render() for f in new)


def test_registry_keys_match_naming_contract():
    """Backstop for dynamically-built keys (module doc): the runtime
    walk over whatever the suite emitted."""
    names = registry.names()
    # the suite must have populated the registry — an empty walk would
    # make this test vacuously green
    assert names, "expected the suite to have recorded metrics"
    violations = lint_metric_names(names)
    assert violations == [], (
        f"metric keys outside the documented namespaces "
        f"{METRIC_NAMESPACES}: {violations} — fix the key or extend "
        f"the contract in docs/observability.md AND metrics.py")


def test_lint_catches_bad_keys():
    bad = ["lena.compaction.merges",      # namespace typo
           "query",                       # bare namespace, no leaf
           "lean..double_dot",
           "lean.spaced key",
           "heta.evt.z3.temperature",     # heat namespace typo
           "unknown.thing"]
    good = ["query.evt.count", "lean.device.ms", "jax.compile.count",
            "storage.evt.attr:score.device_bytes", "web.200",
            "plan.estimate.ratio", "write.pts.features",
            "pallas.density.fallback", "obs.test.empty_ms",
            "heat.evt.z3.temperature", "heat.total.temperature",
            "job.ingest.runs", "job.compaction.ms",
            "write.seals", "write.spills"]
    assert lint_metric_names(good) == []
    assert lint_metric_names(good + bad) == sorted(bad)
