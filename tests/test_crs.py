"""CRS transforms + query-result reprojection (QueryPlanner.scala:74-81
analog)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.geometry import crs
from geomesa_tpu.planning.planner import Query


def test_known_mercator_values():
    # equator/prime meridian → origin; lon 180 → world half-width
    x, y = crs.transform(np.array([0.0, 180.0]), np.array([0.0, 0.0]),
                         "EPSG:4326", "EPSG:3857")
    np.testing.assert_allclose(x, [0.0, 20037508.342789244], rtol=1e-12)
    np.testing.assert_allclose(y, [0.0, 0.0], atol=1e-9)


def test_round_trip():
    rng = np.random.default_rng(3)
    lon = rng.uniform(-180, 180, 1000)
    lat = rng.uniform(-85, 85, 1000)
    mx, my = crs.transform(lon, lat, "4326", "3857")
    lon2, lat2 = crs.transform(mx, my, "EPSG:3857", "CRS:84")
    np.testing.assert_allclose(lon2, lon, atol=1e-9)
    np.testing.assert_allclose(lat2, lat, atol=1e-9)


def test_lat_clipped_at_cutoff():
    _, my = crs.transform(np.array([0.0]), np.array([90.0]), "4326", "3857")
    assert np.isfinite(my).all()


def test_unknown_crs_raises():
    with pytest.raises(ValueError, match="unknown CRS"):
        crs.transform(np.zeros(1), np.zeros(1), "4326", "EPSG:9999")


def test_register_custom_crs():
    # trivial offset CRS
    crs.register_crs("TEST:1",
                     lambda x, y, xp: (x - 10.0, y),
                     lambda x, y, xp: (x + 10.0, y))
    x, y = crs.transform(np.array([5.0]), np.array([2.0]), "4326", "TEST:1")
    np.testing.assert_allclose(x, [15.0])


def test_query_reprojects_points_and_polygons():
    ds = TpuDataStore()
    ds.create_schema("pts", "name:String,*geom:Point")
    ds.write("pts", {"name": ["a", "b"], "geom": ([0.0, 90.0], [0.0, 45.0])})
    res = ds.query_result("pts", Query.of("INCLUDE", crs="EPSG:3857"))
    x, y = res.batch.geom_xy()
    np.testing.assert_allclose(x[1], 90.0 * 20037508.342789244 / 180.0)
    assert abs(y[1]) > 5_000_000  # mercator meters, not degrees

    from geomesa_tpu.geometry import geometry_from_wkt
    ds.create_schema("polys", "name:String,*geom:Polygon")
    ds.write("polys", {
        "name": ["p"],
        "geom": [geometry_from_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))")],
    })
    res = ds.query_result("polys", Query.of("INCLUDE", crs="3857"))
    g = res.batch.geoms
    assert g.coords[:, 0].max() > 1_000_000  # meters
    assert g.bbox[0, 2] > 1_000_000


def test_reproject_noop_same_crs():
    ds = TpuDataStore()
    ds.create_schema("x", "name:String,*geom:Point")
    ds.write("x", {"name": ["a"], "geom": ([1.0], [2.0])})
    res = ds.query_result("x", Query.of("INCLUDE", crs="EPSG:4326"))
    x, y = res.batch.geom_xy()
    np.testing.assert_allclose([x[0], y[0]], [1.0, 2.0])


def test_merged_view_propagates_crs():
    from geomesa_tpu.datastore import TpuDataStore
    from geomesa_tpu.filters import parse_ecql
    from geomesa_tpu.views import MergedDataStoreView

    ds = TpuDataStore()
    ds.create_schema("pts2", "name:String,*geom:Point")
    ds.write("pts2", {"name": ["a"], "geom": ([90.0], [45.0])})
    view = MergedDataStoreView([ds], filters=[parse_ecql("INCLUDE")])
    out = view.query("pts2", Query.of("INCLUDE", crs="EPSG:3857"))
    x, _ = out.geom_xy()
    assert abs(x[0]) > 1e6  # mercator meters, not degrees


def test_reproject_preserves_ids_explicit_flag():
    from geomesa_tpu.features import FeatureBatch
    from geomesa_tpu.features.feature_type import parse_spec
    import numpy as np

    sft = parse_spec("p", "name:String,*geom:Point")
    batch = FeatureBatch.from_dict(
        sft, {"name": np.array(["a"], object),
              "geom": (np.array([1.0]), np.array([2.0]))})
    assert not batch.ids_explicit
    out = crs.reproject_batch(batch, "EPSG:3857")
    assert out.ids_explicit == batch.ids_explicit
