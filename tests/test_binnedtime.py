"""Time binning vs a python-datetime oracle (reference: BinnedTime.scala)."""

import datetime as dt

import numpy as np
import pytest

from geomesa_tpu.curve import (
    TimePeriod,
    bin_to_ms,
    from_binned_time,
    max_date_ms,
    max_offset,
    to_binned_time,
)

UTC = dt.timezone.utc
EPOCH = dt.datetime(1970, 1, 1, tzinfo=UTC)


def ms_of(*args):
    return int(dt.datetime(*args, tzinfo=UTC).timestamp() * 1000)


def oracle_bin(d: dt.datetime, period: TimePeriod):
    if period is TimePeriod.DAY:
        return (d - EPOCH).days
    if period is TimePeriod.WEEK:
        return (d - EPOCH).days // 7
    if period is TimePeriod.MONTH:
        return (d.year - 1970) * 12 + d.month - 1
    return d.year - 1970


def oracle_offset(ms: int, d: dt.datetime, period: TimePeriod):
    sec = ms // 1000
    if period is TimePeriod.DAY:
        return ms - ms // 86_400_000 * 86_400_000
    if period is TimePeriod.WEEK:
        week_start = (d - EPOCH).days // 7 * 7 * 86_400
        return sec - week_start
    if period is TimePeriod.MONTH:
        month_start = int(dt.datetime(d.year, d.month, 1, tzinfo=UTC).timestamp())
        return sec - month_start
    year_start = int(dt.datetime(d.year, 1, 1, tzinfo=UTC).timestamp())
    return (sec - year_start) // 60


def test_max_offsets():
    # BinnedTime.scala maxOffset: day=ms/day, week=s/week, month=s in 31d,
    # year=minutes in 52 weeks
    assert max_offset(TimePeriod.DAY) == 86_400_000
    assert max_offset(TimePeriod.WEEK) == 604_800
    assert max_offset(TimePeriod.MONTH) == 2_678_400
    assert max_offset(TimePeriod.YEAR) == 524_160


def test_known_date():
    # 2018-01-01T00:00:00Z
    ms = ms_of(2018, 1, 1)
    assert ms == 1514764800000
    for period, expected_bin in [
        (TimePeriod.DAY, 17532),
        (TimePeriod.WEEK, 2504),
        (TimePeriod.MONTH, 576),
        (TimePeriod.YEAR, 48),
    ]:
        bins, offs = to_binned_time(ms, period)
        assert int(bins) == expected_bin, period
        d = dt.datetime.fromtimestamp(ms / 1000, UTC)
        assert int(offs) == oracle_offset(ms, d, period), period


@pytest.mark.parametrize("period", list(TimePeriod))
def test_random_dates_vs_oracle(period, rng):
    ms = rng.integers(0, ms_of(2059, 9, 1), size=300)
    bins, offs = to_binned_time(ms, period)
    for i in range(len(ms)):
        d = dt.datetime.fromtimestamp(int(ms[i]) / 1000, UTC)
        assert int(bins[i]) == oracle_bin(d, period), (period, d)
        assert int(offs[i]) == oracle_offset(int(ms[i]), d, period), (period, d)


@pytest.mark.parametrize("period", list(TimePeriod))
def test_roundtrip(period, rng):
    ms = rng.integers(0, ms_of(2059, 9, 1), size=200)
    bins, offs = to_binned_time(ms, period)
    back = from_binned_time(bins, offs, period)
    # precision: day→ms exact; week/month→seconds; year→minutes
    tol = {TimePeriod.DAY: 0, TimePeriod.WEEK: 999,
           TimePeriod.MONTH: 999, TimePeriod.YEAR: 59_999}[period]
    assert np.all(ms - back >= 0)
    assert np.all(ms - back <= tol)


def test_bounds_validation():
    with pytest.raises(ValueError):
        to_binned_time(-1, TimePeriod.WEEK)
    with pytest.raises(ValueError):
        to_binned_time(max_date_ms(TimePeriod.DAY), TimePeriod.DAY)
    # max date is exclusive: one ms before it must work
    to_binned_time(max_date_ms(TimePeriod.DAY) - 1, TimePeriod.DAY)


def test_max_dates_match_reference_docs():
    # exclusive bound = start of bin 32768; BinnedTime.scala docs quote
    # 2059/09/18 (day, last indexable day) and 2598/01/04 (week, exclusive)
    assert np.datetime64(max_date_ms(TimePeriod.DAY) - 1, "ms").astype("M8[D]") == np.datetime64("2059-09-18")
    assert np.datetime64(max_date_ms(TimePeriod.WEEK), "ms").astype("M8[D]") == np.datetime64("2598-01-04")


def test_bin_to_ms_month_year():
    assert int(bin_to_ms(576, TimePeriod.MONTH)) == ms_of(2018, 1, 1)
    assert int(bin_to_ms(48, TimePeriod.YEAR)) == ms_of(2018, 1, 1)
