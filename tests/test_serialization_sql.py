"""WKB/TWKB codecs, Avro container files, st_* functions, SpatialFrame."""

import io

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.geometry.types import (
    Envelope, LineString, MultiLineString, MultiPoint, MultiPolygon, Point,
    Polygon,
)
from geomesa_tpu.geometry.wkb import (
    twkb_decode, twkb_encode, wkb_decode, wkb_encode,
)
from geomesa_tpu.io.avro import avro_schema, from_avro, to_avro
from geomesa_tpu.sql import SpatialFrame, st

MS_2018 = 1514764800000

GEOMS = [
    Point(-75.1, 40.2),
    LineString([[0, 0], [1, 1], [2, 0.5]]),
    Polygon([[0, 0], [4, 0], [4, 4], [0, 4]], ([[1, 1], [2, 1], [2, 2], [1, 2]],)),
    MultiPoint([[1, 2], [3, 4]]),
    MultiLineString(([[0, 0], [1, 1]], [[2, 2], [3, 3]])),
    MultiPolygon(([[0, 0], [1, 0], [1, 1]], [[5, 5], [6, 5], [6, 6]])),
]


@pytest.mark.parametrize("g", GEOMS, ids=[g.geom_type for g in GEOMS])
def test_wkb_roundtrip(g):
    out = wkb_decode(wkb_encode(g))
    assert out.geom_type == g.geom_type
    assert out.envelope.as_tuple() == pytest.approx(g.envelope.as_tuple())


@pytest.mark.parametrize("g", GEOMS, ids=[g.geom_type for g in GEOMS])
def test_twkb_roundtrip(g):
    raw = twkb_encode(g, precision=7)
    out = twkb_decode(raw)
    assert out.geom_type == g.geom_type
    np.testing.assert_allclose(out.envelope.as_tuple(),
                               g.envelope.as_tuple(), atol=1e-6)


def test_twkb_smaller_than_wkb_for_tracks():
    rng = np.random.default_rng(0)
    track = LineString(np.cumsum(rng.uniform(-0.001, 0.001, (500, 2)),
                                 axis=0) + [-75, 40])
    assert len(twkb_encode(track)) < 0.5 * len(wkb_encode(track))


def test_wkb_known_point_bytes():
    # standard WKB for POINT(1 2), little endian
    raw = wkb_encode(Point(1.0, 2.0))
    assert raw == (b"\x01\x01\x00\x00\x00"
                   b"\x00\x00\x00\x00\x00\x00\xf0?"
                   b"\x00\x00\x00\x00\x00\x00\x00@")


def test_avro_roundtrip_and_schema():
    ds = TpuDataStore()
    sft = ds.create_schema("t", "name:String,score:Double,dtg:Date,*geom:Point")
    rng = np.random.default_rng(1)
    n = 100
    ds.write("t", {
        "name": np.array([f"n{i}" for i in range(n)], dtype=object),
        "score": rng.uniform(0, 10, n),
        "dtg": np.full(n, MS_2018, dtype=np.int64),
        "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n)),
    })
    batch = ds.query("t")
    buf = io.BytesIO()
    to_avro(batch, buf)
    buf.seek(0)
    back = from_avro(buf, sft)
    assert len(back) == n
    assert list(back.ids) == list(batch.ids)
    np.testing.assert_allclose(back.columns["score"], batch.columns["score"])
    np.testing.assert_array_equal(back.columns["dtg"], batch.columns["dtg"])
    bx, by = back.geom_xy()
    ox, oy = batch.geom_xy()
    np.testing.assert_allclose(bx, ox)
    sch = avro_schema(sft)
    assert sch["type"] == "record"
    assert any(f["name"] == "geom" for f in sch["fields"])


def test_st_functions():
    x = np.array([-75.0, -74.5, 0.0])
    y = np.array([40.0, 40.5, 0.0])
    pts = st.st_point(x, y)
    np.testing.assert_array_equal(st.st_x(pts), x)

    poly = st.st_geomFromWKT(["POLYGON((-76 39, -74 39, -74 41, -76 41, -76 39))"])[0]
    mask = st.st_contains(poly, pts)
    np.testing.assert_array_equal(mask, [True, True, False])
    np.testing.assert_array_equal(st.st_within(pts, poly), mask)
    np.testing.assert_array_equal(st.st_disjoint(poly, pts), ~mask)

    bbox = st.st_makeBBOX(-76, 39, -74, 41)[0]
    assert st.st_area([bbox])[0] == pytest.approx(4.0)

    line = LineString([[0, 0], [3, 4]])
    assert st.st_length([line])[0] == pytest.approx(5.0)
    assert st.st_numPoints([line])[0] == 2
    c = st.st_centroid([line])[0]
    assert (c.x, c.y) == pytest.approx((1.5, 2.0))

    d = st.st_distanceSphere(st.st_point([-75.0], [40.0]),
                             st.st_point([-75.0], [41.0]))
    assert d[0] == pytest.approx(111_195, rel=0.01)   # 1 deg lat

    buf = st.st_bufferPoint(st.st_point([-75.0], [40.0]), 10_000.0)[0]
    assert st.st_contains(buf, st.st_point([-75.05], [40.0]))[0]
    assert not st.st_contains(buf, st.st_point([-75.5], [40.0]))[0]

    wkt = st.st_asText([poly])[0]
    assert wkt.startswith("POLYGON")
    wkb = st.st_asBinary([poly])[0]
    assert st.st_geomFromWKB([wkb])[0].geom_type == "Polygon"

    assert st.st_dwithin(Point(-75.0, 40.0), pts, 100_000)[0]
    assert st.st_geometryType([poly])[0] == "Polygon"
    p9 = st.st_pointN([line], 2)[0]
    assert (p9.x, p9.y) == (3.0, 4.0)


def test_spatial_frame_pushdown_and_aggregation():
    ds = TpuDataStore()
    ds.create_schema("ev", "name:String,score:Double,dtg:Date,*geom:Point")
    rng = np.random.default_rng(4)
    n = 2000
    ds.write("ev", {
        "name": np.array([f"n{i % 3}" for i in range(n)], dtype=object),
        "score": rng.uniform(0, 10, n),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * 86_400_000, n),
        "geom": (rng.uniform(-76, -73, n), rng.uniform(39, 42, n)),
    })
    frame = (SpatialFrame(ds, "ev")
             .where("BBOX(geom,-75,40,-74,41)")
             .where("name = 'n1'"))
    out = frame.collect()
    x, y = out.geom_xy()
    assert np.all((x >= -75) & (x <= -74) & (y >= 40) & (y <= 41))
    assert all(v == "n1" for v in out.columns["name"])
    # push-down happened: explain mentions an index, not a full scan
    plan = frame.explain()
    assert "z2" in plan.lower() or "z3" in plan.lower()

    assert frame.limit(5).count() == 5
    sel = frame.select("name", "score").collect()
    assert set(sel.columns) == {"name", "score"}

    groups = SpatialFrame(ds, "ev").group_by(
        "name", {"n": ("name", "count"), "avg": ("score", "mean"),
                 "hi": ("score", "max")})
    assert groups["n"].sum() == n
    assert np.all(groups["hi"] <= 10.0)

    pytest.importorskip("pyarrow")
    tbl = frame.to_arrow()
    assert tbl.num_rows == len(out)


# -- round-2 st_* additions (toward the reference's full UDF set) --------
def test_st_boundary_dimension_and_flags():
    from geomesa_tpu.geometry.types import (
        LineString, MultiLineString, MultiPoint, Point, Polygon,
    )
    from geomesa_tpu.sql import functions as F
    poly = Polygon([(0, 0), (4, 0), (4, 4), (0, 4)])
    line = LineString(np.array([[0.0, 0.0], [1.0, 1.0], [2.0, 0.0]]))
    closed = LineString(np.array([[0, 0], [1, 0], [1, 1], [0, 0]], float))
    bowtie = LineString(np.array([[0, 0], [2, 2], [2, 0], [0, 2]], float))
    col = np.array([poly, line, Point(1, 1)], dtype=object)
    b = F.st_boundary(col)
    assert isinstance(b[0], LineString)
    assert isinstance(b[1], MultiPoint) and len(b[1].coords) == 2
    assert isinstance(b[2], MultiPoint) and len(b[2].coords) == 0
    np.testing.assert_array_equal(F.st_dimension(col), [2, 1, 0])
    np.testing.assert_array_equal(F.st_coordDim(col), [2, 2, 2])
    np.testing.assert_array_equal(
        F.st_isClosed(np.array([line, closed], dtype=object)),
        [False, True])
    np.testing.assert_array_equal(
        F.st_isSimple(np.array([line, bowtie], dtype=object)),
        [True, False])
    np.testing.assert_array_equal(
        F.st_isRing(np.array([closed, bowtie], dtype=object)),
        [True, False])
    assert not F.st_isEmpty(col).any()
    assert F.st_isCollection(np.array(
        [MultiLineString((line,)), poly], dtype=object)).tolist() \
        == [True, False]


def test_st_multi_accessors():
    from geomesa_tpu.geometry.types import MultiPolygon, Point, Polygon
    from geomesa_tpu.sql import functions as F
    a = Polygon([(0, 0), (1, 0), (1, 1), (0, 1)])
    hole = Polygon([(0, 0), (10, 0), (10, 10), (0, 10)],
                   (np.array([(4, 4), (6, 4), (6, 6), (4, 6)], float),))
    mp = MultiPolygon((a, hole))
    col = np.array([mp, a], dtype=object)
    np.testing.assert_array_equal(F.st_numGeometries(col), [2, 1])
    assert F.st_geometryN(col, 2)[0] is hole
    rings = F.st_interiorRingN(np.array([hole, a], dtype=object), 1)
    assert rings[0] is not None and rings[1] is None
    cp = F.st_closestPoint(np.array([a], dtype=object), Point(2.0, 0.5))
    assert cp[0].x == 1.0 and abs(cp[0].y - 0.5) < 1e-9


def test_st_touch_cover_overlap():
    from geomesa_tpu.geometry.types import Point, Polygon
    from geomesa_tpu.sql import functions as F
    poly = Polygon([(0, 0), (4, 0), (4, 4), (0, 4)])
    pts = (np.array([4.0, 2.0, 9.0]), np.array([2.0, 2.0, 9.0]))
    np.testing.assert_array_equal(F.st_touches(poly, pts),
                                  [True, False, False])
    np.testing.assert_array_equal(F.st_covers(poly, pts),
                                  [True, True, False])
    b = Polygon([(2, 2), (6, 2), (6, 6), (2, 6)])
    c = Polygon([(10, 10), (11, 10), (11, 11), (10, 11)])
    inner = Polygon([(1, 1), (2, 1), (2, 2), (1, 2)])
    got = F.st_overlaps(np.array([poly, poly, poly], dtype=object),
                        np.array([b, c, inner], dtype=object))
    np.testing.assert_array_equal(got, [True, False, False])


def test_st_geohash_roundtrip():
    from geomesa_tpu.sql import functions as F
    x = np.array([-74.0060, 2.3522])
    y = np.array([40.7128, 48.8566])
    h = F.st_geoHash((x, y), 9)
    assert h[0].startswith("dr5")  # NYC geohash prefix
    px, py = F.st_pointFromGeoHash(h)
    np.testing.assert_allclose(px, x, atol=1e-3)
    np.testing.assert_allclose(py, y, atol=1e-3)
    cells = F.st_geomFromGeoHash(h)
    inside = F.st_covers(cells[0], (x[:1], y[:1]))
    assert inside[0]


def test_st_output_and_text_constructors():
    import json
    from geomesa_tpu.geometry.types import LineString, Point
    from geomesa_tpu.sql import functions as F
    gj = F.st_asGeoJSON((np.array([-74.0]), np.array([40.7])))
    assert json.loads(gj[0])["type"] == "Point"
    txt = F.st_asLatLonText((np.array([-74.5]), np.array([40.25])))
    assert txt[0].startswith("40°15'") and txt[0].endswith("W")
    pts = F.st_pointFromText(np.array(["POINT (1 2)"], dtype=object))
    assert isinstance(pts[0], Point)
    with pytest.raises(ValueError):
        F.st_lineFromText(np.array(["POINT (1 2)"], dtype=object))
    d = F.st_aggregateDistanceSphere(
        (np.array([0.0, 0.0]), np.array([0.0, 1.0])))
    assert abs(d - 111_195) < 500  # one degree of latitude
    assert F.st_byteArray(np.array(["ab"], dtype=object))[0] == b"ab"


def test_st_antimeridian_safe():
    from geomesa_tpu.geometry.types import MultiPolygon, Polygon
    from geomesa_tpu.sql import functions as F
    crossing = Polygon([(170, 10), (-170, 10), (-170, 20), (170, 20)])
    plain = Polygon([(0, 0), (1, 0), (1, 1), (0, 1)])
    out = F.st_antimeridianSafeGeom(np.array([crossing, plain],
                                             dtype=object))
    assert isinstance(out[0], MultiPolygon)
    for p in out[0].polygons:
        assert -180.0 <= p.shell[:, 0].min() <= p.shell[:, 0].max() <= 180.0
    assert out[1] is plain


def test_st_antimeridian_safe_clips_actual_ring():
    """The split halves are the ACTUAL ring clipped at lon=180, not its
    envelope (ADVICE r2): a triangular crossing polygon must produce
    triangular halves, strictly smaller than the bbox rectangles."""
    from geomesa_tpu.geometry.types import MultiPolygon, Polygon
    from geomesa_tpu.sql import functions as F
    tri = Polygon([(170, 10), (-170, 10), (175, 20), (170, 10)])
    out = F.st_antimeridianSafeGeom(np.array([tri], dtype=object))
    mp = out[0]
    assert isinstance(mp, MultiPolygon) and len(mp.polygons) == 2
    total_area = 0.0
    for p in mp.polygons:
        xs, ys = p.shell[:, 0], p.shell[:, 1]
        assert -180.0 <= xs.min() <= xs.max() <= 180.0
        total_area += 0.5 * abs(np.dot(xs[:-1], ys[1:])
                                - np.dot(ys[:-1], xs[1:]))
    # shifted-space shoelace area of the true triangle: base 20 x h 10 / 2
    assert total_area == pytest.approx(100.0, rel=1e-9)
