"""WKB/TWKB codecs, Avro container files, st_* functions, SpatialFrame."""

import io

import numpy as np
import pytest

from geomesa_tpu.datastore import TpuDataStore
from geomesa_tpu.geometry.types import (
    Envelope, LineString, MultiLineString, MultiPoint, MultiPolygon, Point,
    Polygon,
)
from geomesa_tpu.geometry.wkb import (
    twkb_decode, twkb_encode, wkb_decode, wkb_encode,
)
from geomesa_tpu.io.avro import avro_schema, from_avro, to_avro
from geomesa_tpu.sql import SpatialFrame, st

MS_2018 = 1514764800000

GEOMS = [
    Point(-75.1, 40.2),
    LineString([[0, 0], [1, 1], [2, 0.5]]),
    Polygon([[0, 0], [4, 0], [4, 4], [0, 4]], ([[1, 1], [2, 1], [2, 2], [1, 2]],)),
    MultiPoint([[1, 2], [3, 4]]),
    MultiLineString(([[0, 0], [1, 1]], [[2, 2], [3, 3]])),
    MultiPolygon(([[0, 0], [1, 0], [1, 1]], [[5, 5], [6, 5], [6, 6]])),
]


@pytest.mark.parametrize("g", GEOMS, ids=[g.geom_type for g in GEOMS])
def test_wkb_roundtrip(g):
    out = wkb_decode(wkb_encode(g))
    assert out.geom_type == g.geom_type
    assert out.envelope.as_tuple() == pytest.approx(g.envelope.as_tuple())


@pytest.mark.parametrize("g", GEOMS, ids=[g.geom_type for g in GEOMS])
def test_twkb_roundtrip(g):
    raw = twkb_encode(g, precision=7)
    out = twkb_decode(raw)
    assert out.geom_type == g.geom_type
    np.testing.assert_allclose(out.envelope.as_tuple(),
                               g.envelope.as_tuple(), atol=1e-6)


def test_twkb_smaller_than_wkb_for_tracks():
    rng = np.random.default_rng(0)
    track = LineString(np.cumsum(rng.uniform(-0.001, 0.001, (500, 2)),
                                 axis=0) + [-75, 40])
    assert len(twkb_encode(track)) < 0.5 * len(wkb_encode(track))


def test_wkb_known_point_bytes():
    # standard WKB for POINT(1 2), little endian
    raw = wkb_encode(Point(1.0, 2.0))
    assert raw == (b"\x01\x01\x00\x00\x00"
                   b"\x00\x00\x00\x00\x00\x00\xf0?"
                   b"\x00\x00\x00\x00\x00\x00\x00@")


def test_avro_roundtrip_and_schema():
    ds = TpuDataStore()
    sft = ds.create_schema("t", "name:String,score:Double,dtg:Date,*geom:Point")
    rng = np.random.default_rng(1)
    n = 100
    ds.write("t", {
        "name": np.array([f"n{i}" for i in range(n)], dtype=object),
        "score": rng.uniform(0, 10, n),
        "dtg": np.full(n, MS_2018, dtype=np.int64),
        "geom": (rng.uniform(-75, -74, n), rng.uniform(40, 41, n)),
    })
    batch = ds.query("t")
    buf = io.BytesIO()
    to_avro(batch, buf)
    buf.seek(0)
    back = from_avro(buf, sft)
    assert len(back) == n
    assert list(back.ids) == list(batch.ids)
    np.testing.assert_allclose(back.columns["score"], batch.columns["score"])
    np.testing.assert_array_equal(back.columns["dtg"], batch.columns["dtg"])
    bx, by = back.geom_xy()
    ox, oy = batch.geom_xy()
    np.testing.assert_allclose(bx, ox)
    sch = avro_schema(sft)
    assert sch["type"] == "record"
    assert any(f["name"] == "geom" for f in sch["fields"])


def test_st_functions():
    x = np.array([-75.0, -74.5, 0.0])
    y = np.array([40.0, 40.5, 0.0])
    pts = st.st_point(x, y)
    np.testing.assert_array_equal(st.st_x(pts), x)

    poly = st.st_geomFromWKT(["POLYGON((-76 39, -74 39, -74 41, -76 41, -76 39))"])[0]
    mask = st.st_contains(poly, pts)
    np.testing.assert_array_equal(mask, [True, True, False])
    np.testing.assert_array_equal(st.st_within(pts, poly), mask)
    np.testing.assert_array_equal(st.st_disjoint(poly, pts), ~mask)

    bbox = st.st_makeBBOX(-76, 39, -74, 41)[0]
    assert st.st_area([bbox])[0] == pytest.approx(4.0)

    line = LineString([[0, 0], [3, 4]])
    assert st.st_length([line])[0] == pytest.approx(5.0)
    assert st.st_numPoints([line])[0] == 2
    c = st.st_centroid([line])[0]
    assert (c.x, c.y) == pytest.approx((1.5, 2.0))

    d = st.st_distanceSphere(st.st_point([-75.0], [40.0]),
                             st.st_point([-75.0], [41.0]))
    assert d[0] == pytest.approx(111_195, rel=0.01)   # 1 deg lat

    buf = st.st_bufferPoint(st.st_point([-75.0], [40.0]), 10_000.0)[0]
    assert st.st_contains(buf, st.st_point([-75.05], [40.0]))[0]
    assert not st.st_contains(buf, st.st_point([-75.5], [40.0]))[0]

    wkt = st.st_asText([poly])[0]
    assert wkt.startswith("POLYGON")
    wkb = st.st_asBinary([poly])[0]
    assert st.st_geomFromWKB([wkb])[0].geom_type == "Polygon"

    assert st.st_dwithin(Point(-75.0, 40.0), pts, 100_000)[0]
    assert st.st_geometryType([poly])[0] == "Polygon"
    p9 = st.st_pointN([line], 2)[0]
    assert (p9.x, p9.y) == (3.0, 4.0)


def test_spatial_frame_pushdown_and_aggregation():
    ds = TpuDataStore()
    ds.create_schema("ev", "name:String,score:Double,dtg:Date,*geom:Point")
    rng = np.random.default_rng(4)
    n = 2000
    ds.write("ev", {
        "name": np.array([f"n{i % 3}" for i in range(n)], dtype=object),
        "score": rng.uniform(0, 10, n),
        "dtg": rng.integers(MS_2018, MS_2018 + 7 * 86_400_000, n),
        "geom": (rng.uniform(-76, -73, n), rng.uniform(39, 42, n)),
    })
    frame = (SpatialFrame(ds, "ev")
             .where("BBOX(geom,-75,40,-74,41)")
             .where("name = 'n1'"))
    out = frame.collect()
    x, y = out.geom_xy()
    assert np.all((x >= -75) & (x <= -74) & (y >= 40) & (y <= 41))
    assert all(v == "n1" for v in out.columns["name"])
    # push-down happened: explain mentions an index, not a full scan
    plan = frame.explain()
    assert "z2" in plan.lower() or "z3" in plan.lower()

    assert frame.limit(5).count() == 5
    sel = frame.select("name", "score").collect()
    assert set(sel.columns) == {"name", "score"}

    groups = SpatialFrame(ds, "ev").group_by(
        "name", {"n": ("name", "count"), "avg": ("score", "mean"),
                 "hi": ("score", "max")})
    assert groups["n"].sum() == n
    assert np.all(groups["hi"] <= 10.0)

    tbl = frame.to_arrow()
    assert tbl.num_rows == len(out)
