"""End-to-end walkthrough of geomesa-tpu.

Run: ``python examples/demo.py``  (any JAX backend; TPU when available)

Covers the core workflow a GeoMesa user would recognize: define a
schema, ingest through a converter, query with ECQL, run analytics
(density / kNN / tube-select), inspect the query plan, and export —
plus the live streaming layer.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from geomesa_tpu.datastore import TpuDataStore  # noqa: E402
from geomesa_tpu.io.converters import converter_from_config

MS_2018 = 1514764800000
DAY = 86_400_000


def main():
    rng = np.random.default_rng(42)
    ds = TpuDataStore()

    # 1. schema (spec-string DSL; user data tunes the z3 interval)
    ds.create_schema(
        "gdelt", "actor:String:index=true,score:Double,dtg:Date,"
                 "*geom:Point;geomesa.z3.interval=week")

    # 2. converter ingest (CSV → transform expressions → columns)
    n = 200_000
    csv = "\n".join(
        f"actor{i % 50},{rng.uniform():.3f},{MS_2018 + int(rng.integers(14 * DAY))},"
        f"{rng.uniform(-75, -73):.5f},{rng.uniform(40, 42):.5f}"
        for i in range(n))
    conv = converter_from_config(ds.get_schema("gdelt"), {
        "type": "csv",
        "fields": [
            {"name": "actor", "transform": "$0"},
            {"name": "score", "transform": "toDouble($1)"},
            {"name": "dtg", "transform": "toLong($2)"},
            {"name": "geom", "transform": "point($3,$4)"},
        ],
    })
    ds.write("gdelt", conv.convert(csv))
    print(f"ingested {ds.get_count('gdelt'):,} features")

    # 3. ECQL query (planner picks the z3 index; hit set is exact)
    q = ("BBOX(geom,-74.5,40.5,-73.5,41.5) AND dtg DURING "
         "2018-01-03T00:00:00Z/2018-01-10T00:00:00Z AND score > 0.5")
    t0 = time.perf_counter()
    hits = ds.query("gdelt", q)
    print(f"query: {len(hits):,} hits in "
          f"{(time.perf_counter() - t0) * 1e3:.0f}ms")
    print(ds.explain("gdelt", q))

    # 4. analytics
    from geomesa_tpu.process.density import density_process
    grid = density_process(ds, "gdelt", q, (-75, 40, -73, 42), 256, 256)
    print(f"density grid: {grid.shape}, total weight {grid.sum():.0f}")

    from geomesa_tpu.process.knn import knn_process
    pos, dist = knn_process(ds, "gdelt", -74.0, 41.0, k=5)
    print(f"kNN: nearest 5 within {dist.max():.0f} m")

    from geomesa_tpu.process.tube import tube_select
    track = np.stack([np.linspace(-74.8, -73.2, 9),
                      np.linspace(40.2, 41.8, 9)], axis=1)
    times = MS_2018 + np.linspace(0, 7 * DAY, 9).astype(np.int64)
    sel = tube_select(ds, "gdelt", track, times,
                      buffer_m=5_000, time_buffer_ms=12 * 3_600_000)
    print(f"tube-select: {len(sel):,} features along the track")

    # 5. export (GeoJSON / Arrow)
    from geomesa_tpu.io.export import to_geojson
    fc = to_geojson(ds.query("gdelt", q, ))
    print(f"geojson export: {len(fc):,} bytes")
    table = ds.query_arrow_table("gdelt", q, dictionary_fields=("actor",))
    print(f"arrow export: {table.num_rows:,} rows, "
          f"{len(table.column_names)} columns")

    # 6. streaming layer (Kafka-analog live cache)
    from geomesa_tpu.stream import StreamDataStore
    live = StreamDataStore()
    live.create_schema("ships", "mmsi:String,dtg:Date,*geom:Point")
    for i in range(1_000):
        live.write("ships", f"v{i % 100}", {
            "mmsi": f"v{i % 100}", "dtg": MS_2018 + i,
            "geom": (float(rng.uniform(-74.5, -73.5)),
                     float(rng.uniform(40.5, 41.5)))})
    live.consume("ships")
    print(f"live cache: {len(live.query('ships', 'INCLUDE')):,} current "
          "vessels")

    # 7. multi-chip: the SAME facade over a device mesh — every index
    # builds sharded, scans run as collectives (psum/ppermute over ICI)
    import jax
    from geomesa_tpu.parallel import device_mesh
    if (len(jax.devices()) == 1
            and os.environ.get("JAX_PLATFORMS", "") == "cpu"):
        # the container pins a single-chip TPU plugin that ignores
        # JAX_PLATFORMS; honor the caller's cpu request (see
        # __graft_entry__.dryrun_multichip)
        from jax.extend import backend as _backend
        _backend.clear_backends()
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) > 1:
        dsm = TpuDataStore(mesh=device_mesh())
        dsm.create_schema(
            "gdelt", "actor:String:index=true,score:Double,dtg:Date,"
                     "*geom:Point;geomesa.z3.interval=week")
        dsm.write("gdelt", conv.convert(csv))
        hits_mesh = dsm.query("gdelt", q)
        print(f"mesh store ({len(jax.devices())} devices): "
              f"{len(hits_mesh):,} hits (single-chip store found "
              f"{len(ds.query('gdelt', q)):,})")

        # 8. SQL text front-end (the Spark-SQL user surface): st_* calls
        # rewrite to ECQL push-down predicates, aggregates vectorize
        from geomesa_tpu.sql import sql_query
        agg = sql_query(dsm, "SELECT actor, count(*) AS n, avg(score) "
                             "AS avg_s FROM gdelt GROUP BY actor "
                             "ORDER BY n DESC LIMIT 3")
        print("sql top actors:", list(zip(agg["actor"], agg["n"])))

        # 9. device-resident sketches: count-min Frequency over a
        # bbox+time window (per-shard partials psum-merged)
        from geomesa_tpu.process import stats_process
        f = stats_process(dsm, "gdelt", q, "Frequency(score)")
        print("frequency sketch non-zero cells:",
              int((f.table > 0).sum()))
    else:
        print("mesh store: single device visible — run under "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
              "JAX_PLATFORMS=cpu to demo the collectives")


if __name__ == "__main__":
    main()
